"""Known-answer canaries: low-rate requests with precomputed host-oracle
results, injected through the normal serving front door.

Counters say the fleet is *fast*; only a canary says it is *right*. A
canary request is a fixed, deterministic payload whose expected result
was computed ONCE on the host oracle (the same
``crypto.signature`` / ``ops.kzg_batch`` / watchdog tree-root functions
the degrade ladder falls back to). The scheduler injects one every
``interval_s`` through the regular submit verbs — same admission seam
(exempted), same batcher, same device dispatch, same wire — and
compares the resolved result **bit-exactly** against the oracle. A
mismatch is a ``canary.parity`` page-level event plus an exemplar
bundle, never absorbed, never retried into silence: it means the
serving path returned a wrong answer while every latency metric looked
healthy.

Canary shapes (``ETH_SPECS_CANARY_SHAPES``, default ``bls,htr,agg``):

  * ``bls`` — a 3-of-3 valid aggregate signature (keys derived from
    fixed scalars, signed at build time); expected verdict from
    ``fast_aggregate_verify``.
  * ``htr`` — 64 deterministic SSZ chunks; expected root from the
    watchdog's host tree-root fold.
  * ``agg`` — 3 valid G2 signatures; expected 96-byte aggregate from
    ``crypto.signature.aggregate``.
  * ``kzg`` (opt-in: ``ETH_SPECS_CANARY_SHAPES=all``) — a well-formed
    blob with an infinity commitment/proof; expected verdict from
    ``verify_blob_host``. Opt-in because each probe costs a full
    4096-field-element parse.
  * ``slot`` is deliberately NOT a canary shape: the slot pipeline is
    stateful and single-owner — a canary slot would commit state.
    Slot parity is covered by slot_bench's bit-parity gates and the
    dedup-replay invariant instead.

The ``canary=True`` flag rides the request end to end (front door →
wire → replica → service → batcher): canaries are exempt from
admission shed accounting (a canary must never shed real traffic) and
excluded from ``serve.requests`` / ``serve.wait_ms`` /
``frontdoor.e2e_ms`` — so SLO windows, the autoscaler, and bench
throughput numbers never see them. They live in their own
``canary.*`` metric family instead.
"""

from __future__ import annotations

import os
import time

import numpy as np

from . import flight

DEFAULT_SHAPES = ("bls", "htr", "agg")
ALL_SHAPES = ("bls", "htr", "agg", "kzg")


def shapes_from_env() -> tuple[str, ...]:
    raw = os.environ.get("ETH_SPECS_CANARY_SHAPES", "").strip().lower()
    if not raw:
        return DEFAULT_SHAPES
    if raw == "all":
        return ALL_SHAPES
    return tuple(s.strip() for s in raw.split(",") if s.strip() in ALL_SHAPES)


def bits(v) -> bytes:
    """Canonical byte form for bit-exact comparison across result types
    (bool verdicts, aggregate bytes, root words)."""
    if isinstance(v, bool):
        return b"\x01" if v else b"\x00"
    if isinstance(v, (bytes, bytearray)):
        return bytes(v)
    try:
        return np.asarray(v).tobytes()
    except Exception:
        return repr(v).encode()


def _hex(v, limit: int = 96) -> str:
    h = bits(v).hex()
    return h if len(h) <= limit else h[:limit] + "..."


# ------------------------------------------------------------------ shapes --


def _build_bls() -> tuple[tuple, object]:
    from eth_consensus_specs_tpu.crypto import signature

    message = b"eth-specs-canary/bls/known-answer".ljust(32, b"\x00")[:32]
    sks = (0x1501, 0x1502, 0x1503)
    pks = [signature.sk_to_pk(sk) for sk in sks]
    sig = signature.aggregate([signature.sign(sk, message) for sk in sks])
    expected = signature.fast_aggregate_verify(pks, message, sig)
    return (pks, message, sig), expected


def _build_htr() -> tuple[tuple, object]:
    from eth_consensus_specs_tpu.obs.watchdog import host_tree_root_words
    from eth_consensus_specs_tpu.ops.merkle import _chunks_to_words

    n = 64  # a pow2 subtree: depth 6, one fixed compile bucket
    chunks = (np.arange(n * 32, dtype=np.uint64) * 131 + 17) % 251
    chunks = chunks.astype(np.uint8).reshape(n, 32)
    expected = host_tree_root_words(_chunks_to_words(chunks, n))
    return (chunks,), expected


def _build_agg() -> tuple[tuple, object]:
    from eth_consensus_specs_tpu.crypto import signature

    sigs = [
        signature.sign(sk, b"eth-specs-canary/agg/%d" % i)
        for i, sk in enumerate((0x2501, 0x2502, 0x2503))
    ]
    expected = signature.aggregate(list(sigs))
    return (sigs,), expected


def _build_kzg() -> tuple[tuple, object]:
    from eth_consensus_specs_tpu.ops.kzg_batch import verify_blob_host

    # 4096 field elements, each with a zero top byte so every one is
    # canonical; commitment/proof are the compressed point at infinity —
    # a structurally valid input whose verdict the oracle decides
    fe = bytearray((i * 31 + 7) % 256 for i in range(4096 * 32))
    for i in range(0, len(fe), 32):
        fe[i] = 0
    blob = bytes(fe)
    commitment = b"\xc0" + b"\x00" * 47
    proof = b"\xc0" + b"\x00" * 47
    expected = verify_blob_host(blob, commitment, proof)
    return (blob, commitment, proof), expected


_BUILDERS = {
    "bls": _build_bls,
    "htr": _build_htr,
    "agg": _build_agg,
    "kzg": _build_kzg,
}


def warm_keys(shapes=None) -> list[tuple]:
    """Unsigned compile/bucket keys the canary stream can touch. At most
    one canary is ever in flight, so its flush-group size is always 1 —
    item/batch buckets are fixed at 1 and the lane/depth axes are the
    builders' constants. Benches and fleets add these to their warmup
    keys so injecting canaries never trips a zero-cold-compile gate."""
    from eth_consensus_specs_tpu.serve import buckets

    out: list[tuple] = []
    for kind in (shapes if shapes is not None else shapes_from_env()):
        if kind == "htr":
            out.append(("merkle_many", 1, 6))  # 64 chunks = depth 6
        elif kind == "bls":
            out.append(("bls_msm", 1, buckets.pow2_bucket(3)))
        elif kind == "agg":
            out.append(("g2_agg", 1, buckets.agg_lane_bucket(3)))
        elif kind == "kzg":
            from eth_consensus_specs_tpu.ops.kzg_batch import N_BLOB

            # a 1-blob flush touches BOTH kzg seam kernels: the RLC
            # multi-MSM and the batched inverse FFT
            out.append(("kzg", buckets.kzg_lane_bucket(1)))
            out.append(buckets.fr_fft_key_from_profile(1, N_BLOB))
    return out


# --------------------------------------------------------------- scheduler --


class CanaryScheduler:
    """Tick-driven injector: at most one canary in flight, one sent per
    ``interval_s``, cycling the configured shapes. ``pump()`` is called
    from the front-door supervisor tick (or a bench loop) — it never
    blocks: sends go through the client's async submit verbs and
    completed futures are reaped on a later pump.

    ``client`` is anything with the four submit verbs accepting
    ``canary=True`` — a ``FrontDoorClient`` or an in-process
    ``VerifyService``.
    """

    def __init__(self, client, interval_s: float = 2.0, timeout_s: float = 10.0,
                 shapes=None):
        self.client = client
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self.shapes = list(shapes if shapes is not None else shapes_from_env())
        self.sent = 0
        self.ok = 0
        self.parity_failures = 0
        self.errors = 0
        self._specs: dict = {}
        self._idx = 0
        self._pending = None  # (kind, future, expected, t_sent)
        self._next_t = time.monotonic() + self.interval_s

    # ------------------------------------------------------------- pump --

    def pump(self, now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        self._reap(now)
        if self._pending is None and self.shapes and now >= self._next_t:
            self._send(now)

    def drain(self, timeout_s: float = 10.0) -> None:
        """Bench epilogue: wait for the in-flight canary (if any) so the
        run's pass-rate covers every canary it sent."""
        deadline = time.monotonic() + timeout_s
        while self._pending is not None and time.monotonic() < deadline:
            self._reap(time.monotonic())
            if self._pending is not None:
                time.sleep(0.02)

    # ------------------------------------------------------------- send --

    def _spec(self, kind: str) -> tuple[tuple, object]:
        spec = self._specs.get(kind)
        if spec is None:
            spec = self._specs[kind] = _BUILDERS[kind]()
        return spec

    def _send(self, now: float) -> None:
        from eth_consensus_specs_tpu import obs

        kind = self.shapes[self._idx % len(self.shapes)]
        self._idx += 1
        self._next_t = now + self.interval_s
        try:
            payload, expected = self._spec(kind)
            fut = self._submit(kind, payload)
        except Exception as exc:  # noqa: BLE001 — a shed/closed client is an error, not parity
            self.errors += 1
            obs.count("canary.errors", 1)
            obs.event("canary.error", shape=kind, err=repr(exc)[:160])
            self._gauge()
            return
        self.sent += 1
        obs.count("canary.sent", 1)
        obs.count(f"canary.sent.{kind}", 1)
        self._pending = (kind, fut, expected, now)

    def _submit(self, kind: str, payload: tuple):
        if kind == "bls":
            return self.client.submit_bls_aggregate(*payload, canary=True)
        if kind == "htr":
            return self.client.submit_hash_tree_root(*payload, canary=True)
        if kind == "agg":
            return self.client.submit_aggregate(*payload, canary=True)
        if kind == "kzg":
            return self.client.submit_blob_verify(*payload, canary=True)
        raise ValueError(f"unknown canary shape {kind!r}")

    # ------------------------------------------------------------- reap --

    def _reap(self, now: float) -> None:
        from eth_consensus_specs_tpu import obs

        if self._pending is None:
            return
        kind, fut, expected, t0 = self._pending
        if fut.done():
            self._pending = None
            try:
                result = fut.result()
            except Exception as exc:  # noqa: BLE001 — errored canary: degraded, not wrong
                self.errors += 1
                obs.count("canary.errors", 1)
                obs.event("canary.error", shape=kind, err=repr(exc)[:160])
                self._gauge()
                return
            if bits(result) == bits(expected):
                self.ok += 1
                obs.count("canary.ok", 1)
            else:
                # the page: the serving path returned DIFFERENT BITS than
                # the host oracle for a known-answer request
                self.parity_failures += 1
                obs.count("canary.parity_failures", 1)
                obs.event(
                    "canary.parity", shape=kind, severity="page",
                    expected=_hex(expected), got=_hex(result),
                )
                flight.trigger_dump(
                    "canary.parity",
                    detail=f"canary {kind} bit-mismatch vs host oracle",
                    extra={
                        "kind": kind,
                        "expected": _hex(expected, 256),
                        "got": _hex(result, 256),
                    },
                )
            self._gauge()
        elif now - t0 > self.timeout_s:
            self._pending = None
            self.errors += 1
            obs.count("canary.errors", 1)
            obs.event("canary.timeout", shape=kind, waited_s=round(now - t0, 3))
            self._gauge()

    def _gauge(self) -> None:
        from eth_consensus_specs_tpu import obs

        rate = self.pass_rate()
        if rate is not None:
            obs.gauge("canary.pass_rate", rate)

    # ------------------------------------------------------------ report --

    def pass_rate(self) -> float | None:
        done = self.ok + self.parity_failures + self.errors
        return (self.ok / done) if done else None

    def stats(self) -> dict:
        return {
            "shapes": list(self.shapes),
            "sent": self.sent,
            "ok": self.ok,
            "parity_failures": self.parity_failures,
            "errors": self.errors,
            "pass_rate": self.pass_rate(),
        }
