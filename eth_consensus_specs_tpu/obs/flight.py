"""Flight recorder: an always-on bounded ring of recent structured
events, dumped as a postmortem bundle on trigger.

Counters and the JSONL sink tell you what happened *in aggregate*; the
moment something actually goes wrong — a watchdog divergence, a
whole-batch degrade, an OOM-killed gen worker — the question is "what
were the last N things this process did", and by then it is too late to
turn tracing on. So the ring records continuously:

  * every emitted obs event (span ends with their trace ids, flush
    compositions, admission sheds, fault/degrade breadcrumbs) — the
    registry's ``emit`` feeds the ring unconditionally;
  * counter bumps whose increment clears a floor
    (``ETH_SPECS_OBS_FLIGHT_COUNTER_FLOOR``, default 65536) — the rare
    mega-bumps (a 100MB transfer, a million-hash batch) are flight
    events, the per-call pennies are not;
  * explicit :func:`record` calls from anywhere.

Each entry carries a process-monotonic ``seq``, wall time, thread name,
and — when a trace context is active — trace/span ids, so a dumped ring
stitches into the same trees the JSONL stream does.

**Postmortem bundles.** :func:`dump` writes ring + registry snapshot +
filtered env + platform/device info as one JSON file into
``ETH_SPECS_OBS_POSTMORTEM_DIR`` (unset → dumps are no-ops; nothing in
a default run writes to disk). :func:`trigger_dump` is the rate-limited
form the failure paths call — watchdog mismatch (obs/watchdog.py),
``fault.degrade`` fallback (fault/degrade.py), live SLO breach
(obs/slo.py), a lost gen-pool worker (gen/gen_runner.py, which ships
each worker's ring to the parent incrementally so a SIGKILLed worker
still leaves a black box), and pytest session failure
(test_infra/obs_plugin.py). ``scripts/postmortem.py`` pretty-prints and
diffs bundles; ``make postmortem`` shows the most recent one.

Cost discipline: with ``ETH_SPECS_OBS=0`` the registry never calls the
taps, so the hot record path is an allocation-free no-op; with
``ETH_SPECS_OBS_FLIGHT=0`` the ring itself is disabled (taps return on
an int compare). Recording is one small dict + one deque append under a
lock held for the append only.
"""

from __future__ import annotations

import json
import os
import re
import sys
import threading
import time
from collections import deque

from . import trace

_DEFAULT_CAPACITY = 512
_DEFAULT_COUNTER_FLOOR = 65536
# dump-storm guard: a divergence inside a hot loop must not write
# thousands of near-identical bundles
_MAX_DUMPS_PER_TRIGGER = 8

_LOCK = threading.Lock()
_RING: deque = deque(maxlen=_DEFAULT_CAPACITY)
_SEQ = 0
_DUMP_N = 0  # per-process bundle ordinal (unique filenames within a second)
_DUMPS_BY_TRIGGER: dict[str, int] = {}
# durable-resident-state lineage (serve/resident_owner.py): which
# checkpoint this process restored from / last wrote, and the restore
# verdict — the first question a recovery postmortem asks
_LINEAGE: dict | None = None


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    try:
        return int(raw) if raw else default
    except ValueError:
        return default


_CAPACITY = _env_int("ETH_SPECS_OBS_FLIGHT", _DEFAULT_CAPACITY)
_COUNTER_FLOOR = _env_int("ETH_SPECS_OBS_FLIGHT_COUNTER_FLOOR", _DEFAULT_COUNTER_FLOOR)


def _reinit_lock_after_fork_in_child() -> None:
    # a parent background thread (front-door supervisor, dispatcher)
    # may hold the ring lock at fork time; the child would inherit it
    # held forever — it is single-threaded here, so re-creating is safe
    global _LOCK
    _LOCK = threading.Lock()


os.register_at_fork(after_in_child=_reinit_lock_after_fork_in_child)


def refresh_env() -> None:
    """Re-read the flight env knobs (capacity, counter floor) — resolved
    once at import for the hot paths; tests that flip them call this."""
    global _CAPACITY, _COUNTER_FLOOR, _RING
    _CAPACITY = _env_int("ETH_SPECS_OBS_FLIGHT", _DEFAULT_CAPACITY)
    _COUNTER_FLOOR = _env_int(
        "ETH_SPECS_OBS_FLIGHT_COUNTER_FLOOR", _DEFAULT_COUNTER_FLOOR
    )
    with _LOCK:
        _RING = deque(_RING, maxlen=max(_CAPACITY, 1))


def capacity() -> int:
    return _CAPACITY


def dump_dir() -> str | None:
    return os.environ.get("ETH_SPECS_OBS_POSTMORTEM_DIR") or None


# ------------------------------------------------------------------ record --


def _append(entry: dict) -> None:
    global _SEQ
    with _LOCK:
        _SEQ += 1
        entry["seq"] = _SEQ
        _RING.append(entry)


def note_event(event: dict) -> None:
    """Registry tap: called by ``Registry.emit`` for every event (the
    registry already checked obs_enabled). Copies, never mutates — the
    same dict was just written to the JSONL sink."""
    if _CAPACITY <= 0:
        return
    _append({"t": time.time(), "thread": threading.current_thread().name, **event})


def note_count(name: str, n: int | float) -> None:
    """Registry tap for counter bumps: only increments clearing the
    floor become flight events (obs_enabled already checked)."""
    if _CAPACITY <= 0 or n < _COUNTER_FLOOR:
        return
    entry = {
        "kind": "count",
        "name": name,
        "n": n,
        "t": time.time(),
        "thread": threading.current_thread().name,
    }
    entry.update(trace.event_fields(trace.current()))
    _append(entry)


def record(kind: str, **fields) -> None:
    """Explicit flight entry from anywhere (no registry involvement);
    no-op when obs is disabled or the ring is off."""
    from .registry import obs_enabled

    if not obs_enabled() or _CAPACITY <= 0:
        return
    entry = {"kind": kind, "t": time.time(),
             "thread": threading.current_thread().name, **fields}
    entry.update(trace.event_fields(trace.current()))
    _append(entry)


def ring() -> list[dict]:
    """Point-in-time copy of the ring, oldest first."""
    with _LOCK:
        return list(_RING)


def ship_since(seq: int) -> tuple[int, list[dict]]:
    """Entries newer than ``seq`` plus the new high-water mark — the
    cross-process shipping unit (gen pool workers send this with every
    result so the parent always holds their recent ring)."""
    with _LOCK:
        entries = [e for e in _RING if e.get("seq", 0) > seq]
        return _SEQ, entries


def set_lineage(lineage: dict | None) -> None:
    """Record this process's checkpoint lineage (manifest digest, epoch
    span, restore verdict) for inclusion in every subsequent bundle."""
    global _LINEAGE
    _LINEAGE = dict(lineage) if lineage else None


def get_lineage() -> dict | None:
    return dict(_LINEAGE) if _LINEAGE else None


def reset_for_tests() -> None:
    global _SEQ, _DUMP_N, _LINEAGE
    with _LOCK:
        _RING.clear()
        _SEQ = 0
        _DUMP_N = 0
        _LINEAGE = None
        _DUMPS_BY_TRIGGER.clear()


# -------------------------------------------------------------------- dump --


def _platform_info() -> dict:
    import platform as _pl

    info = {
        "system": _pl.system(),
        "release": _pl.release(),
        "machine": _pl.machine(),
        "python": _pl.python_version(),
    }
    # device identity is the first question a postmortem reader asks;
    # best-effort so a jax-less process still dumps
    try:
        import jax

        info["jax_version"] = jax.__version__
        info["jax_backend"] = jax.default_backend()
        info["devices"] = [str(d) for d in jax.devices()]
    except Exception:
        pass
    return info


def _env_section() -> dict:
    """Only the knobs that shape this repo's runtime — never the whole
    environ (tokens/credentials must not land in an uploaded artifact)."""
    return {
        k: v
        for k, v in sorted(os.environ.items())
        if k.startswith(("ETH_SPECS_", "JAX_", "XLA_", "SPEC_TEST_"))
    }


_SECRET_ARG = re.compile(r"token|secret|password|passwd|api[-_]?key|bearer|credential",
                         re.IGNORECASE)


def _argv_section() -> list[str]:
    """argv with secret-shaped arguments redacted — bundles ride CI
    artifacts, so the same exposure rule as the env section applies: a
    `--token=...` (or the value following `--api-key`) must not leak."""
    out: list[str] = []
    redact_next = False
    for arg in sys.argv:
        if redact_next:
            out.append("<redacted>")
            redact_next = False
            continue
        if _SECRET_ARG.search(arg):
            if "=" in arg:
                out.append(arg.split("=", 1)[0] + "=<redacted>")
            else:
                out.append(arg)
                redact_next = arg.startswith("-")
            continue
        out.append(arg)
    return out


def dump(
    trigger: str,
    detail: str | None = None,
    extra: dict | None = None,
    ring_events: list[dict] | None = None,
    out_dir: str | None = None,
) -> str | None:
    """Write a postmortem bundle; returns the path, or None when no
    destination is configured. Never raises — a failing black box must
    not take the plane down with it."""
    out_dir = out_dir or dump_dir()
    if not out_dir:
        return None
    from .registry import get_registry

    try:
        os.makedirs(out_dir, exist_ok=True)
        bundle = {
            "bundle": "eth-specs-postmortem",
            "version": 1,
            "trigger": trigger,
            "detail": detail,
            "unix_time": time.time(),
            "pid": os.getpid(),
            "argv": _argv_section(),
            "platform": _platform_info(),
            "env": _env_section(),
            "ring": ring_events if ring_events is not None else ring(),
            "registry": get_registry().snapshot(),
        }
        try:
            # the HBM residency books (obs/ledger.py): pure numeric byte
            # accounting per owner — an OOM bundle names who held the
            # memory. Nothing env- or argv-shaped can enter via this
            # section, so the redaction discipline above is untouched.
            from . import ledger

            bundle["hbm"] = ledger.postmortem_section()
        except Exception:
            pass
        if _LINEAGE:
            bundle["checkpoint"] = dict(_LINEAGE)
        if extra:
            bundle["extra"] = extra
        stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
        slug = "".join(c if c.isalnum() else "-" for c in trigger)
        global _DUMP_N
        with _LOCK:
            _DUMP_N += 1
            n = _DUMP_N
        path = os.path.join(out_dir, f"postmortem-{stamp}-{os.getpid()}-{slug}-{n}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(bundle, fh, indent=1, sort_keys=True, default=str)
            fh.write("\n")
        os.replace(tmp, path)
    except Exception:
        return None
    reg = get_registry()
    reg.count("flight.dumps", 1)
    reg.emit({"kind": "flight.dump", "trigger": trigger, "path": path})
    return path


def trigger_dump(
    trigger: str,
    detail: str | None = None,
    extra: dict | None = None,
    ring_events: list[dict] | None = None,
) -> str | None:
    """The failure-path entry: no-op without a configured dump dir, and
    capped per trigger kind so a divergence storm can't fill the disk
    with near-identical bundles."""
    if not dump_dir():
        return None
    with _LOCK:
        n = _DUMPS_BY_TRIGGER.get(trigger, 0)
        if n >= _MAX_DUMPS_PER_TRIGGER:
            return None
        _DUMPS_BY_TRIGGER[trigger] = n + 1
    return dump(trigger, detail=detail, extra=extra, ring_events=ring_events)
