"""Central metric catalog: every counter/gauge/histogram/span name.

Declaring names in one place buys two machine checks the hand-maintained
way kept losing:

  * the ``obs-discipline`` speclint rule (analysis/lint.py) fails the
    build when code emits a metric name absent from this catalog — new
    instrumentation lands HERE first, with a help string, where a
    reviewer and a dashboard can see it;
  * :func:`eth_consensus_specs_tpu.obs.export.validate_text` rejects
    expositions containing families this catalog doesn't know — a
    renamed counter breaks CI instead of silently orphaning every
    recording rule and SLO that referenced the old name.

A ``*`` segment matches one or more name characters (``watchdog.*.checks``
covers ``watchdog.sha256.checks``); patterns exist for the families that
are keyed by kernel/op/site at runtime. The ``t.*`` / ``test.*``
namespaces are sanctioned scratch space for tests — production code may
not emit into them (the lint rule has no such carve-out; only the
exposition validator does).
"""

from __future__ import annotations

import re
from dataclasses import dataclass


@dataclass(frozen=True)
class Metric:
    kind: str  # "counter" | "gauge" | "histogram" | "span"
    name: str  # dotted obs name; '*' segments are runtime-keyed
    help: str


def _c(name: str, help: str) -> Metric:
    return Metric("counter", name, help)


def _g(name: str, help: str) -> Metric:
    return Metric("gauge", name, help)


def _h(name: str, help: str) -> Metric:
    return Metric("histogram", name, help)


def _s(name: str, help: str) -> Metric:
    return Metric("span", name, help)


CATALOG: tuple[Metric, ...] = (
    # ------------------------------------------------------------ kernels --
    _c("sha256.compressions", "sha256 compression function evaluations"),
    _c("sha256.dispatches", "device sha256 kernel dispatches"),
    _c("sha256.messages", "messages hashed through the tiled kernel"),
    _s("sha256.tiled", "tiled device sha256 dispatch"),
    _c("merkle.leaf_chunks", "leaf chunks merkleized"),
    _c("merkle.real_hashes", "non-padding hashes in merkle trees"),
    _c("merkle.trees", "merkle trees computed"),
    _s("merkle.subtree_root", "single-tree device merkleization"),
    _s("merkle.many_subtree_root", "vmapped multi-tree device merkleization"),
    _c("shuffle.decision_hashes", "swap-or-not decision hashes"),
    _c("shuffle.lanes", "shuffle lanes processed"),
    _c("shuffle.permutations", "full committee permutations"),
    _s("shuffle.permutation", "device shuffle permutation"),
    _c("state_root.real_hashes", "hashes in post-epoch state roots"),
    _c("state_root.roots", "post-epoch state roots computed"),
    _c("state_root.traces", "state-root kernel (re)traces"),
    _s("state_root.post_epoch", "device post-epoch state root"),
    _s("state_root.post_epoch_host", "host-oracle post-epoch state root"),
    _c("state_root.inc_roots", "incremental (forest) post-epoch state roots"),
    _c("state_root.inc_real_hashes",
       "dirty-path hashes in incremental state roots (capacity model)"),
    _c("merkle_inc.updates", "incremental forest path-update dispatches"),
    _c("merkle_inc.dirty_leaves", "live dirty leaves through forest updates"),
    _c("merkle_inc.real_hashes",
       "hashes in incremental forest updates (capacity model)"),
    _s("merkle_inc.update", "incremental dirty-subtree forest update"),
    _s("resident.run_epochs", "device-resident chained epoch advance"),
    # ------------------------------------------- durable resident state --
    _c("resident.checkpoints", "durable checkpoints committed"),
    _c("resident.checkpoint_blobs_written", "checkpoint blobs written+verified"),
    _c("resident.checkpoint_blobs_reused",
       "checkpoint blobs reused by content address"),
    _c("resident.torn_writes", "checkpoint writes failing read-back verify"),
    _c("resident.restores", "digest-verified checkpoint restores"),
    _c("resident.reingests", "full deterministic re-ingests (restore/scrub fallback)"),
    _c("resident.scrub.checks", "scrub subtree+upper-region integrity checks"),
    _c("resident.scrub.mismatches", "scrub checks that found corruption"),
    _c("resident.scrub.quarantines", "quarantine-and-rebuild passes after scrub hits"),
    _s("resident.checkpoint", "content-addressed forest checkpoint write"),
    _s("resident.restore", "digest-verified forest restore"),
    _s("resident.scrub", "salted-subtree resident integrity scrub"),
    _c("block_epoch.blocks_ingested", "blocks ingested into the chain kernel"),
    _c("block_epoch.epochs", "epoch transitions in block_epoch chains"),
    _c("block_epoch.ingests", "block_epoch ingest calls"),
    _c("block_epoch.slots", "slots advanced in block_epoch chains"),
    _c("block_epoch.traces", "block_epoch kernel (re)traces"),
    _c("block_epoch.validator_slots", "validator-slots processed"),
    _s("block_epoch.chain", "device block/epoch chain run"),
    _s("block_epoch.chain_host", "host-oracle block/epoch chain run"),
    # ---------------------------------------------------------------- bls --
    _c("bls.batch_items", "items in batched aggregate verifications"),
    _c("bls.batches", "batched aggregate verification calls"),
    _c("bls.fast_aggregate_verifies", "FastAggregateVerify calls"),
    _c("bls.messages_distinct", "distinct messages across a batch"),
    _c("bls.pairing_inputs", "pairing inputs accumulated"),
    _c("bls.pairings", "pairing evaluations"),
    _c("bls.pubkeys_aggregated", "pubkeys aggregated"),
    _c("bls.verify_many_items", "items through verify_many"),
    _s("bls.batch_verify", "batched RLC aggregate verification"),
    _s("bls.fast_aggregate_verify", "single FastAggregateVerify"),
    _s("bls.verify_many", "multi-item verify_many with bisection"),
    # ---------------------------------------------------------------- agg --
    _c("agg.committees", "committee contributions aggregated (tier 0)"),
    _c("agg.signatures", "member signatures through the committee tree"),
    _c("agg.subnet_partials", "per-(subnet, root) partial aggregates (tier 1)"),
    _c("agg.global_aggregates", "per-root global aggregates (tier 2)"),
    _c("agg.isolated_invalid", "invalid subnet partials isolated by bisection"),
    _g("agg.registry_validators", "validators in the live aggregation registry"),
    _h("agg.compile_ms", "G2 aggregation kernel first-dispatch compile wall ms"),
    _s("agg.slot", "one slot's committee-tree aggregation"),
    # ---------------------------------------------------------------- kzg --
    _c("kzg.batches", "RLC-combined blob KZG batch checks (one MSM + pairing each)"),
    _c("kzg.blobs_verified", "blobs through verify_many_blobs / the batch verifier"),
    _c("kzg.fft_rows", "blob polynomials through the batched device inverse FFT"),
    _c("kzg.isolated_invalid", "invalid blobs isolated by RLC bisection"),
    _s("kzg.verify_many", "batched blob KZG verification with bisection"),
    # ---------------------------------------------------------------- das --
    _g("das.blobs", "blobs in the live DAS bench flush"),
    _c("das.flushes", "DAS bench blob-verification flushes"),
    # ------------------------------------------------------------- fault --
    _c("fault.degraded", "device->host degradations"),
    _c("fault.degraded.*", "degradations per site"),
    _c("fault.injected", "injected faults fired"),
    _c("fault.retries", "fault.retrying attempts"),
    # --------------------------------------------------------------- gen --
    _c("gen.bytes_serialized", "vector bytes serialized"),
    _c("gen.cases_*", "case outcomes by status (written/failed/skipped/...)"),
    _c("gen.parts", "vector parts written"),
    _c("gen.result_stream_errors", "malformed worker result frames"),
    _c("gen.torn_writes", "read-back-verification catches"),
    _c("gen.workers_recycled", "pool workers recycled at case cap"),
    _c("gen.workers_replaced", "dead/hung pool workers respawned"),
    _s("gen.case", "one generation case"),
    # --------------------------------------------------------- multihost --
    _c("multihost.init_failures", "jax.distributed init failures"),
    _c("multihost.initializations", "jax.distributed initializations"),
    _c("multihost.meshes_flat", "flat device meshes built"),
    _c("multihost.meshes_hybrid", "hybrid device meshes built"),
    _c("multihost.processes", "processes seen at mesh build"),
    _c("multihost.slice_remainder", "rows beyond an even host_local_slice shard split"),
    _s("multihost.initialize", "jax.distributed initialization"),
    # -------------------------------------------------------------- mesh --
    _c("mesh.dispatches", "mesh-sharded kernel dispatches"),
    _c("mesh.sharded_items", "live items (trees/MSM items/pairs) through sharded kernels"),
    _g("mesh.devices", "devices in the live serve mesh"),
    # ------------------------------------------------------------- serve --
    _c("serve.batch_items", "requests across all flushes"),
    _c("serve.cancelled", "futures cancelled by callers"),
    _c("serve.compiles", "first dispatches of a new bucket shape"),
    _c("serve.compiles_after_warmup", "bucket compiles after the warmup phase"),
    _c("serve.degraded_items", "requests served by host oracles"),
    _c("serve.flushes", "micro-batcher flushes"),
    _c("serve.flush.*", "flushes by reason (size/deadline/pressure/idle/close)"),
    _c("serve.precompiled", "bucket shapes warmed by precompile()"),
    _c("serve.rejected", "admission sheds"),
    _c("serve.rejected.*", "admission sheds by reason (queue/bytes)"),
    _c("serve.requests", "submits admitted"),
    _c("serve.requests.*", "submits by kind (bls/htr/state_root)"),
    _g("serve.in_flight_bytes", "admitted payload bytes in flight"),
    _g("serve.queue_depth", "admitted requests queued + in flight"),
    _h("serve.compile_ms", "first-dispatch compile wall ms"),
    _h("serve.compile_ms.*", "first-dispatch compile wall ms per op"),
    _h("serve.wait_ms", "request wait from submit to flush, ms"),
    _h("serve.stage_ms.*",
       "per-request waterfall stage ms (admit/queue/prep/handoff/dispatch_wait/"
       "device/resolve/other/total, plus the front door's wire residual)"),
    _s("serve.dispatch", "one batched device dispatch"),
    # ------------------------------------------------------------- device --
    _h("device.exec_ms", "measured device execution ms per dispatch (devprof)"),
    _h("device.exec_ms.*", "measured device execution ms per kernel"),
    _c("device.roofline_violations",
       "measured device timings implying impossible bandwidth"),
    _c("device.roofline_violations.*", "measured-roofline violations per kernel"),
    _c("device.devprof.windows", "jax.profiler trace windows captured"),
    _c("device.devprof.unavailable", "profiler trace attempts that degraded"),
    # ---------------------------------------------------------------- hbm --
    _g("hbm.resident_bytes.*", "ledger-registered device bytes per owner"),
    _g("hbm.resident_bytes_total", "ledger-registered device bytes, all owners"),
    _c("hbm.registrations", "HBM ledger buffer registrations"),
    _c("hbm.donations", "HBM ledger buffers closed by jit donation"),
    _c("hbm.deletions", "HBM ledger buffers closed by deletion"),
    # ------------------------------------------------ whole-slot pipeline --
    _c("slot.slots", "whole-slot requests committed by the slot world"),
    _c("slot.attestations", "attestations carried by committed slots"),
    _c("slot.blobs", "blob sidecars carried by committed slots"),
    _c("slot.replays", "committed slots replayed from the dedup window"),
    _c("slot.host_folds", "slots degraded to the sequential host fold"),
    _c("slot.forest_rebuilds",
       "resident forests rebuilt from committed columns after a consumed "
       "donation (mid-dispatch device death recovery)"),
    # --------------------------------------------------------- frontdoor --
    _c("frontdoor.backoffs", "router backoffs honored"),
    _c("frontdoor.cancelled", "front-door futures cancelled"),
    _c("frontdoor.corrupt_frames", "corrupt frames detected at the wire"),
    _c("frontdoor.corrupt_retries", "corrupt-frame resends"),
    _c("frontdoor.degraded_to_host", "requests served by the front-door host oracle"),
    _c("frontdoor.duplicates_suppressed", "hedge duplicates suppressed"),
    _c("frontdoor.failovers", "requests failed over to a sibling"),
    _c("frontdoor.hedge_abandoned", "hedge legs abandoned (primary owns the slot)"),
    _c("frontdoor.hedge_wins", "hedge legs that resolved first"),
    _c("frontdoor.hedges", "hedged re-dispatches launched"),
    _c("frontdoor.planned_restarts", "zero-shed drain rollovers"),
    _c("frontdoor.probe_failures", "supervisor health-probe failures"),
    _c("frontdoor.replicas_grown", "replicas added by the SLO autoscaler"),
    _c("frontdoor.replicas_replaced", "dead replicas respawned"),
    _c("frontdoor.replicas_retired", "idle replicas retired by the SLO autoscaler"),
    _c("frontdoor.replies_dropped", "replica replies to vanished callers"),
    _c("frontdoor.request_errors", "typed application errors returned"),
    _c("frontdoor.requests", "front-door submits"),
    _c("frontdoor.requests.*", "front-door submits by kind"),
    _c("frontdoor.respawn_failures", "replica respawn attempts that failed"),
    _c("frontdoor.route.affinity", "requests routed to their shape-affine replica"),
    _c("frontdoor.route.fallback", "requests routed past their affine replica"),
    _c("frontdoor.route.mesh_affinity",
       "requests routed to the mesh tier matching their width"),
    _c("frontdoor.route.warm",
       "requests routed to a replica already warm for their shape"),
    _c("frontdoor.slo_sheds", "SLO-driven admission shrinks"),
    _g("frontdoor.effective_max_queue", "SLO-adjusted admission cap"),
    _g("frontdoor.replicas", "replicas currently in rotation"),
    _h("frontdoor.e2e_ms", "front-door end-to-end latency, ms"),
    _s("frontdoor.rpc", "one framed RPC at the replica boundary"),
    # --------------------------------------------------------- slo burn --
    _c("slo.windows", "supervision probe windows with wait samples"),
    _c("slo.windows_breached",
       "probe windows whose window-local wait p99 breached the objective"),
    # --------------------------------------------- continuous telemetry --
    _c("tsdb.samples", "telemetry windows folded into the series ring"),
    _c("telemetry.errors", "guarded telemetry-tick failures (never fatal)"),
    _c("anomaly.fires", "anomalies fired (post refractory suppression)"),
    _c("anomaly.fires.*", "anomaly fires per detector"),
    _c("anomaly.suppressed", "anomalies suppressed by the refractory window"),
    _c("anomaly.errors", "detector step exceptions swallowed"),
    _c("canary.sent", "known-answer canary requests injected"),
    _c("canary.sent.*", "canary sends per shape (bls/htr/agg/kzg)"),
    _c("canary.ok", "canaries whose result matched the host oracle bit-exactly"),
    _c("canary.parity_failures",
       "canaries whose result MISMATCHED the host oracle (page-level)"),
    _c("canary.errors", "canaries that errored or timed out (degraded, not wrong)"),
    _c("canary.requests", "canary submits through the service pipeline"),
    _c("canary.host_served", "canaries absorbed by the front-door host oracle"),
    _g("canary.pass_rate", "ok / completed canaries, cumulative"),
    _h("canary.wait_ms", "canary wait from submit to flush, ms"),
    _h("canary.e2e_ms", "canary front-door end-to-end latency, ms"),
    # ---------------------------------------------------------- watchdog --
    _c("watchdog.checks", "device/host divergence probes"),
    _c("watchdog.divergences", "device/host mismatches"),
    _c("watchdog.*.checks", "divergence probes per kernel"),
    _c("watchdog.*.divergences", "mismatches per kernel"),
    # ------------------------------------------------------------- xprof --
    _c("xprof.analysis_unavailable", "XLA analyses missing on this backend"),
    _c("xprof.cost_model_mismatch", "hand work_bytes outside tolerance of XLA"),
    _c("xprof.cost_model_mismatch.*", "cost-model mismatches per kernel"),
    _g("xprof.*.*", "per-kernel XLA cost/memory attribution (flops, bytes_accessed, peak_bytes, ...)"),
    _h("xprof.compile_ms", "AOT compile wall ms"),
    _h("xprof.compile_ms.*", "AOT compile wall ms per kernel"),
    # ------------------------------------------------------------ flight --
    _c("flight.dumps", "postmortem bundles written"),
    # ---------------------------------------------------------- lockwatch --
    _c("lockwatch.inversions", "live lock-order inversions observed"),
    _g("lockwatch.acquisitions", "watched-lock acquisitions (published at epilogue)"),
    _g("lockwatch.edges", "distinct live lock-order edges (published at epilogue)"),
    # ------------------------------------------------------- cross-cutting --
    _c("*.bytes_moved", "device traffic attributed via obs.bytes_moved"),
)

# test scratch namespaces: allowed in EXPOSITIONS (tests write through the
# global registry on purpose), never emitted by package code (the lint
# rule checks package code against CATALOG alone)
_TEST_NAMESPACES = ("t.", "test.")

_BY_KIND: dict[str, list[Metric]] = {}
for _m in CATALOG:
    _BY_KIND.setdefault(_m.kind, []).append(_m)


def _pattern_re(name: str) -> re.Pattern:
    rx = "".join(
        re.escape(c) if c != "*" else r"[a-z0-9_.]+" for c in name
    )
    return re.compile("^" + rx + "$")


_KIND_RES: dict[str, list[re.Pattern]] = {
    kind: [_pattern_re(m.name) for m in ms] for kind, ms in _BY_KIND.items()
}


def declared(kind: str, name: str) -> bool:
    """Is `name` (possibly with '*' placeholders from an f-string emit
    site) covered by a catalog entry of `kind`? A placeholder is matched
    as a representative token, so ``serve.flush.*`` (emit site) matches
    the catalog's ``serve.flush.*`` and ``*.bytes_moved`` matches
    ``*.bytes_moved``."""
    sample = name.replace("*", "x0")
    return any(rx.match(sample) for rx in _KIND_RES.get(kind, ()))


# ------------------------------------------------------- exposition check --


def _prom_family_res() -> list[re.Pattern]:
    out: list[re.Pattern] = []
    for m in CATALOG:
        # prom-space: dots collapse to underscores, so '*' must match
        # underscores too (translate around the placeholder — the plain
        # metric_name() would collapse '*' itself to '_')
        prom = m.name.replace(".", "_")
        base = "".join(
            re.escape(c) if c != "*" else "[a-zA-Z0-9_]+" for c in prom
        )
        suffixes = {
            "counter": ("_total",),
            "gauge": ("", "_max"),
            "histogram": ("",),
            "span": ("_calls_total", "_seconds_total"),
        }[m.kind]
        for suf in suffixes:
            out.append(re.compile("^" + base + re.escape(suf) + "$"))
    for ns in _TEST_NAMESPACES:
        out.append(re.compile("^" + re.escape(ns.replace(".", "_")) + ".*$"))
    return out


_PROM_RES: list[re.Pattern] | None = None


def prom_family_known(family: str) -> bool:
    """Used by export.validate_text: is this Prometheus family name one
    the catalog (or the test scratch namespace) declares?"""
    global _PROM_RES
    if _PROM_RES is None:
        _PROM_RES = _prom_family_res()
    return any(rx.match(family) for rx in _PROM_RES)
