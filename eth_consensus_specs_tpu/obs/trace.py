"""Trace context: request-scoped ids that survive threads and processes.

Spans (obs/registry.py) nest through a thread-local stack, which dies
at every thread hand-off — exactly where the serving pipeline lives
(submit thread → batch thread → dispatch thread) and where the gen pool
lives (parent process → worker process). This module carries a small
explicit context across those seams:

  * ``TraceContext(trace_id, span_id, parent_id)`` — W3C-traceparent-
    shaped ids (128-bit trace, 64-bit span, hex);
  * a thread-local **context stack**: ``activate(ctx)`` installs a
    context for a ``with`` block, ``current()`` reads it;
  * every obs span that runs under an active context becomes a trace
    span automatically: the registry asks this module for a child
    context on span entry, and the span's JSONL event carries
    ``trace_id`` / ``span_id`` / ``parent_span`` — so Perfetto (or any
    JSONL consumer) can stitch one request's spans across threads and
    processes into a single tree;
  * ``to_wire`` / ``from_wire`` — the one-string form that rides in
    queue payloads (serve Request objects, gen-pool task tuples);
  * **flow ids**: a batched dispatch span cannot *belong* to the N
    requests it serves, so it *links* them instead — the flush/dispatch
    events list each member request's wire id under ``flows`` (the
    Perfetto flow-event idiom: one producer slice, many consumer
    slices, connected by id).

Everything here is pure stdlib and allocation-light; with no active
context the per-span overhead is one thread-local read.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass

_local = threading.local()


@dataclass(frozen=True)
class TraceContext:
    trace_id: str  # 32 hex chars (128-bit)
    span_id: str  # 16 hex chars (64-bit)
    parent_id: str | None = None  # the parent span's span_id


def _new_id(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


def _stack() -> list:
    stack = getattr(_local, "stack", None)
    if stack is None:
        stack = _local.stack = []
    return stack


def current() -> TraceContext | None:
    stack = _stack()
    return stack[-1] if stack else None


def new_trace() -> TraceContext:
    """Fresh root context (new trace_id, no parent)."""
    return TraceContext(trace_id=_new_id(16), span_id=_new_id(8))


def child(ctx: TraceContext | None = None) -> TraceContext:
    """Child of ``ctx`` (default: the active context); a fresh root when
    there is nothing to be a child of."""
    if ctx is None:
        ctx = current()
    if ctx is None:
        return new_trace()
    return TraceContext(trace_id=ctx.trace_id, span_id=_new_id(8), parent_id=ctx.span_id)


class activate:
    """``with trace.activate(ctx):`` — install ``ctx`` as the thread's
    current context for the block. Re-entrant and exception-safe (plain
    stack discipline)."""

    __slots__ = ("ctx",)

    def __init__(self, ctx: TraceContext | None):
        self.ctx = ctx

    def __enter__(self) -> TraceContext | None:
        if self.ctx is not None:
            _stack().append(self.ctx)
        return self.ctx

    def __exit__(self, *exc) -> bool:
        if self.ctx is not None:
            stack = _stack()
            if stack and stack[-1] is self.ctx:
                stack.pop()
        return False


# ------------------------------------------------------- span integration --


def enter_span() -> TraceContext | None:
    """Called by the registry on span entry: under an active context the
    span becomes a trace span (child context pushed, returned); with no
    active context it returns None and costs one thread-local read."""
    cur = current()
    if cur is None:
        return None
    ctx = TraceContext(trace_id=cur.trace_id, span_id=_new_id(8), parent_id=cur.span_id)
    _stack().append(ctx)
    return ctx


def exit_span(ctx: TraceContext | None) -> None:
    if ctx is None:
        return
    stack = _stack()
    if stack and stack[-1] is ctx:
        stack.pop()


def event_fields(ctx: TraceContext | None) -> dict:
    """The JSONL event fields for a context (empty dict when None)."""
    if ctx is None:
        return {}
    fields = {"trace_id": ctx.trace_id, "span_id": ctx.span_id}
    if ctx.parent_id:
        fields["parent_span"] = ctx.parent_id
    return fields


# ------------------------------------------------------------------- wire --


def to_wire(ctx: TraceContext | None) -> str | None:
    """``trace_id-span_id`` — the form that rides in queue payloads and
    flow-link lists. The receiving side treats the wire span as the
    PARENT of whatever it runs (from_wire restores it as current)."""
    if ctx is None:
        return None
    return f"{ctx.trace_id}-{ctx.span_id}"


def from_wire(wire: str | None) -> TraceContext | None:
    if not wire:
        return None
    trace_id, _, span_id = wire.partition("-")
    if not trace_id or not span_id:
        return None
    return TraceContext(trace_id=trace_id, span_id=span_id)
