"""HBM residency ledger: who owns the device bytes, right now.

At 1M-validator scale the device memory budget is the scarce resource:
the resident state columns, the incremental merkle forest, the
trusted-setup constants, and the warm jit caches all hold HBM for the
life of the process, and an OOM today leaves nothing but an XLA
allocator backtrace. The ledger is the owner-level account: every
long-lived device buffer registers its bytes at creation, re-registers
on replacement (ingest, epoch rollover), and deregisters on donation
(``donate_argnums`` consumed it) or deletion. The books are exposed
three ways:

  * gauges — ``hbm.resident_bytes.<owner>`` per owner and
    ``hbm.resident_bytes_total`` across owners; the registry's gauge
    ``max`` IS the high-water mark, so the merged fleet snapshot
    carries each replica's peak without extra machinery;
  * counters — ``hbm.registrations`` / ``hbm.donations`` /
    ``hbm.deletions`` for churn;
  * :func:`postmortem_section` — a pure-numeric accounting block that
    obs/flight.py embeds in every postmortem bundle as ``bundle["hbm"]``
    (byte counts and owner names only — nothing env- or argv-shaped, so
    the bundle's secret-redaction discipline is untouched), naming the
    owners so the OOM black box answers "who held the memory".

Owners in the serve stack: ``resident_state`` (parallel/resident.py),
``merkle_forest`` (ops/merkle_inc.py epoch forests — donated buffers
leave the books the moment run_epochs consumes them),
``trusted_setup`` (KZG setup, FFT twiddles, sha round constants), and
``jit_cache`` (serve/buckets.py first-dispatch live-array delta — an
approximation of what a compile pinned, see the call site).

The internal account is always live (cheap dict math) so tests can
assert exact bytes with obs disabled; the gauges follow the usual
``ETH_SPECS_OBS`` gate. Never raises.
"""

from __future__ import annotations

import os
import threading

_LOCK = threading.Lock()
_ENTRIES: dict = {}  # (owner, name) -> nbytes
_HIGH_WATER = 0


def _reinit_lock_after_fork_in_child() -> None:
    global _LOCK
    _LOCK = threading.Lock()


os.register_at_fork(after_in_child=_reinit_lock_after_fork_in_child)


def _publish_locked(owner: str) -> None:
    """Refresh the owner + total gauges; caller holds the lock."""
    global _HIGH_WATER
    total = sum(_ENTRIES.values())
    if total > _HIGH_WATER:
        _HIGH_WATER = total
    owner_total = sum(v for (o, _), v in _ENTRIES.items() if o == owner)
    try:
        from .registry import get_registry, obs_enabled

        if obs_enabled():
            reg = get_registry()
            reg.gauge(f"hbm.resident_bytes.{owner}", owner_total)
            reg.gauge("hbm.resident_bytes_total", total)
    except Exception:  # noqa: BLE001 — bookkeeping must never take down a dispatch
        pass


def register(owner: str, name: str, nbytes: int) -> None:
    """Record ``nbytes`` of device memory held by ``owner``'s buffer
    ``name``. Re-registering the same (owner, name) REPLACES the entry —
    an ingest that rebuilds its columns is an update, not a leak."""
    if nbytes < 0:
        return
    with _LOCK:
        _ENTRIES[(owner, name)] = int(nbytes)
        _publish_locked(owner)
    _count("hbm.registrations")


def donate(owner: str, name: str) -> int:
    """Close the entry because the buffer was DONATED into a jit
    (donate_argnums consumed it); returns the bytes released."""
    return _drop(owner, name, "hbm.donations")


def delete(owner: str, name: str) -> int:
    """Close the entry because the buffer was deleted/dropped."""
    return _drop(owner, name, "hbm.deletions")


def _drop(owner: str, name: str, counter: str) -> int:
    with _LOCK:
        freed = _ENTRIES.pop((owner, name), 0)
        _publish_locked(owner)
    if freed:
        _count(counter)
    return freed


def _count(name: str) -> None:
    try:
        from .registry import get_registry, obs_enabled

        if obs_enabled():
            get_registry().count(name, 1)
    except Exception:  # noqa: BLE001
        pass


# ----------------------------------------------------------------- reading --


def resident_bytes(owner: str | None = None) -> int:
    """Current resident total, for one owner or across the books."""
    with _LOCK:
        if owner is None:
            return sum(_ENTRIES.values())
        return sum(v for (o, _), v in _ENTRIES.items() if o == owner)


def high_water_bytes() -> int:
    with _LOCK:
        return _HIGH_WATER


def owners() -> dict:
    """Per-owner resident bytes, sorted largest first."""
    with _LOCK:
        acc: dict = {}
        for (o, _), v in _ENTRIES.items():
            acc[o] = acc.get(o, 0) + v
    return dict(sorted(acc.items(), key=lambda kv: -kv[1]))


def postmortem_section(top: int = 10) -> dict:
    """The bundle block: resident/high-water totals, per-owner split,
    and the ``top`` largest entries. Pure numeric byte accounting —
    nothing here may ever echo env values or argv."""
    with _LOCK:
        entries = sorted(_ENTRIES.items(), key=lambda kv: -kv[1])
        total = sum(_ENTRIES.values())
        hw = _HIGH_WATER
    acc: dict = {}
    for (o, _), v in entries:
        acc[o] = acc.get(o, 0) + v
    return {
        "resident_total_bytes": total,
        "high_water_bytes": hw,
        "owners": dict(sorted(acc.items(), key=lambda kv: -kv[1])),
        "top_entries": [
            {"owner": o, "name": n, "bytes": v} for (o, n), v in entries[:top]
        ],
    }


def reset_for_tests() -> None:
    global _HIGH_WATER
    with _LOCK:
        _ENTRIES.clear()
        _HIGH_WATER = 0
