"""Device-time capture: measured execution seconds per kernel dispatch,
with roofline verdicts computed from MEASURED time, plus sampled
``jax.profiler`` trace windows.

obs/xprof.py times *compiles* and audits the hand byte model against
what XLA emitted; nothing in the repo times actual device execution.
Every "fast as the hardware allows" roofline verdict so far judged a
host-side wall-clock span — batching slop, Python overhead, and sync
latency all billed to the device. This module closes that gap:

  * :func:`measure` — a context manager the dispatch seams
    (serve/service.py ``_execute``) wrap around one device dispatch
    *including its ``block_until_ready``/host-sync*, recording the
    delta into ``device.exec_ms`` + ``device.exec_ms.<kernel>``
    histograms. When the seam declares ``work_bytes`` (the same hand
    model the spans use), the measured seconds feed
    :func:`..gates.roofline_verdict` — an implied GB/s above the
    accelerator roofline bumps ``device.roofline_violations``
    (+ per-kernel) and emits an event; the CI obs-report discipline
    treats violations as a measurement bug, not a fast kernel.
  * :func:`trace_window` — an env-gated (``ETH_SPECS_OBS_DEVPROF=1``,
    off by default like xprof) sampled ``jax.profiler`` trace: the
    first ``ETH_SPECS_OBS_DEVPROF_WINDOWS`` (default 2) windows per
    process write a profile under ``devprof_traces/`` for offline
    inspection, then the sampler goes quiet. Backends or versions
    without the profiler degrade to a counted no-op
    (``device.devprof.unavailable``).

:func:`measure` itself is NOT gated by ``ETH_SPECS_OBS_DEVPROF`` — it
is a cheap ``perf_counter`` pair, active whenever obs is on, because
the serve_bench waterfall section gates on ``device.exec_ms`` being
populated on every platform including CPU CI. With ``ETH_SPECS_OBS=0``
nothing records. Never raises.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time

from . import gates
from .registry import get_registry, obs_enabled

_DEFAULT_WINDOWS = 2
_DEFAULT_TRACE_DIR = "devprof_traces"

_SEEN_LOCK = threading.Lock()
_SEEN: set[str] = set()
_WINDOWS_TAKEN = 0


def _reinit_lock_after_fork_in_child() -> None:
    # a serving thread can be inside measure() at fork time; the child
    # must get a fresh, unheld lock (same idiom as xprof/flight)
    global _SEEN_LOCK
    _SEEN_LOCK = threading.Lock()


os.register_at_fork(after_in_child=_reinit_lock_after_fork_in_child)


def profiler_enabled() -> bool:
    """Trace-window gate (the histogram capture only needs obs)."""
    return obs_enabled() and os.environ.get("ETH_SPECS_OBS_DEVPROF", "0") not in (
        "0", "false", "",
    )


def _max_windows() -> int:
    raw = os.environ.get("ETH_SPECS_OBS_DEVPROF_WINDOWS", "")
    try:
        return int(raw) if raw else _DEFAULT_WINDOWS
    except ValueError:
        return _DEFAULT_WINDOWS


def reset_for_tests() -> None:
    global _WINDOWS_TAKEN
    with _SEEN_LOCK:
        _SEEN.clear()
        _WINDOWS_TAKEN = 0


# ----------------------------------------------------------------- measure --


def record(kernel: str, seconds: float, work_bytes: float | None = None) -> dict | None:
    """Record one measured device execution. Returns the roofline
    verdict dict when ``work_bytes`` was declared, else None."""
    if not obs_enabled() or seconds < 0:
        return None
    reg = get_registry()
    ms = seconds * 1e3
    reg.observe("device.exec_ms", ms)
    reg.observe(f"device.exec_ms.{kernel}", ms)
    verdict = None
    if work_bytes:
        verdict = gates.roofline_verdict(work_bytes, max(seconds, 1e-9))
        if not verdict["roofline_ok"]:
            # measured time says the kernel beat the memory system's
            # physics: the byte model (or the sync point) is lying
            reg.count("device.roofline_violations", 1)
            reg.count(f"device.roofline_violations.{kernel}", 1)
            reg.emit({
                "kind": "device.roofline_violation",
                "kernel": kernel,
                "s": round(seconds, 9),
                "work_bytes": float(work_bytes),
                "implied_gbps": verdict["implied_gbps"],
            })
    with _SEEN_LOCK:
        first = kernel not in _SEEN
        if first:
            _SEEN.add(kernel)
    if first:
        event = {"kind": "device.exec", "kernel": kernel, "s": round(seconds, 9)}
        if verdict:
            event["implied_gbps"] = verdict["implied_gbps"]
            event["roofline_ok"] = verdict["roofline_ok"]
        reg.emit(event)
    return verdict


class _Measure:
    __slots__ = ("kernel", "work_bytes", "verdict", "_t0")

    def __init__(self, kernel: str, work_bytes: float | None):
        self.kernel = kernel
        self.work_bytes = work_bytes
        self.verdict = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            try:
                self.verdict = record(
                    self.kernel, time.perf_counter() - self._t0, self.work_bytes
                )
            except Exception:  # noqa: BLE001 — measurement must not kill a dispatch
                pass
        return False


def measure(kernel: str, work_bytes: float | None = None) -> _Measure:
    """Time one device dispatch (the ``with`` body MUST include the
    sync — ``block_until_ready`` or a host transfer — or the measured
    delta is launch latency, not execution). A body that raises records
    nothing: a degraded dispatch's timing would poison the histogram."""
    return _Measure(kernel, work_bytes)


# ------------------------------------------------------------ trace window --


@contextlib.contextmanager
def trace_window(kernel: str):
    """Sampled ``jax.profiler`` window around one dispatch; yields True
    when a profile is actually being captured. Off by default; bounded
    per process; degrades to a counted no-op without the profiler."""
    global _WINDOWS_TAKEN
    if not profiler_enabled():
        yield False
        return
    with _SEEN_LOCK:
        if _WINDOWS_TAKEN >= _max_windows():
            yield False
            return
        _WINDOWS_TAKEN += 1
        n = _WINDOWS_TAKEN
    out_dir = os.environ.get("ETH_SPECS_OBS_DEVPROF_DIR") or _DEFAULT_TRACE_DIR
    reg = get_registry()
    try:
        import jax.profiler as profiler

        os.makedirs(out_dir, exist_ok=True)
        profiler.start_trace(out_dir)
    except Exception:  # noqa: BLE001 — profiler missing/broken: degrade, keep serving
        reg.count("device.devprof.unavailable", 1)
        yield False
        return
    try:
        yield True
    finally:
        try:
            profiler.stop_trace()
            reg.count("device.devprof.windows", 1)
            reg.emit({
                "kind": "device.devprof.window",
                "kernel": kernel,
                "n": n,
                "dir": out_dir,
            })
        except Exception:  # noqa: BLE001
            reg.count("device.devprof.unavailable", 1)
