"""Mergeable fixed-log-bucket histogram — the latency primitive.

A reservoir (serve/service.py's old 4096-sample deque) answers "p99 of
the last 4096 waits", silently truncates history under load, and has to
sort under a lock to answer anything. A fixed-layout log-bucket
histogram answers "p99 of the whole run" in O(buckets), records in
O(1), and — because every histogram with the same ``(lo, growth)``
layout has bit-identical bucket edges — two of them **merge** by adding
counts. That last property is what lets a gen-pool worker ship its wait
distribution to the parent as a delta (gen/gen_runner.py) and lets a
run-level report aggregate per-process histograms without ever seeing a
raw sample.

Layout: bucket ``i`` covers ``(lo * growth**(i-1), lo * growth**i]``;
values ``<= lo`` land in bucket 0, values past the last edge in the
overflow bucket (whose upper edge is +Inf). The default layout —
``lo=1e-3, hi=1e7, growth=2**(1/4)`` — spans sub-microsecond to ~3 h
when recording milliseconds, in 134 buckets, with quantile relative
error bounded by ``sqrt(growth)-1`` ≈ 9 % (quantiles report the
geometric midpoint of the winning bucket, clamped to the observed
min/max so small samples stay exact-ish).

Thread safety: one lock per histogram, held for an O(1) list increment
— no sorting, no allocation, no global registry lock on the record
path.

Serialization: :meth:`snapshot` is a plain JSON-able dict;
:meth:`from_snapshot` reconstructs (derived convenience fields are
ignored), so a snapshot that crossed a process boundary or a JSON file
still answers quantile queries (obs/slo.py evaluates SLOs from exactly
such snapshots).
"""

from __future__ import annotations

import math
import threading

# default layout: shared by every histogram the registry auto-creates,
# so any two registries' same-named histograms are always mergeable
DEFAULT_LO = 1e-3
DEFAULT_HI = 1e7
DEFAULT_GROWTH = 2.0 ** 0.25


class Histogram:
    __slots__ = ("lo", "growth", "counts", "count", "sum", "min", "max",
                 "_log_growth", "_lock")

    def __init__(self, lo: float = DEFAULT_LO, hi: float = DEFAULT_HI,
                 growth: float = DEFAULT_GROWTH):
        if not (lo > 0 and hi > lo and growth > 1):
            raise ValueError("need lo > 0, hi > lo, growth > 1")
        self.lo = float(lo)
        self.growth = float(growth)
        self._log_growth = math.log(self.growth)
        n = int(math.ceil(math.log(hi / lo) / self._log_growth))
        # counts[0] covers (-inf, lo]; counts[n+1] is the overflow bucket
        self.counts: list[int] = [0] * (n + 2)
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self._lock = threading.Lock()

    # ------------------------------------------------------------ record --

    def _index(self, value: float) -> int:
        if value <= self.lo:
            return 0
        i = int(math.ceil(math.log(value / self.lo) / self._log_growth))
        # float fuzz at an exact edge can land one bucket high/low; both
        # stay within the layout's error bound, so only clamp the range
        return min(max(i, 0), len(self.counts) - 1)

    def record(self, value: float) -> None:
        value = float(value)
        i = self._index(value)
        with self._lock:
            self.counts[i] += 1
            self.count += 1
            self.sum += value
            if value < self.min:
                self.min = value
            if value > self.max:
                self.max = value

    # ------------------------------------------------------------- query --

    def upper_edges(self) -> list[float]:
        """Inclusive upper bucket edges, last one +Inf — the Prometheus
        ``le`` sequence."""
        n = len(self.counts) - 1
        return [self.lo * self.growth ** i for i in range(n)] + [math.inf]

    def quantile(self, q: float) -> float | None:
        """q in [0, 1]; None when empty. Returns the geometric midpoint
        of the bucket holding the q-th sample, clamped to the observed
        [min, max] (so p0/p100 are exact and tiny samples don't report
        an edge nobody hit)."""
        if not 0.0 <= q <= 1.0:
            raise ValueError("quantile must be in [0, 1]")
        with self._lock:
            total = self.count
            counts = list(self.counts)
            lo_seen, hi_seen = self.min, self.max
        if total == 0:
            return None
        if q == 0.0:
            return float(lo_seen)
        if q == 1.0:
            return float(hi_seen)
        rank = max(q * total, 1.0)  # 1-based rank of the target sample
        acc = 0
        idx = len(counts) - 1
        for i, c in enumerate(counts):
            acc += c
            if acc >= rank:
                idx = i
                break
        if idx == 0:
            mid = self.lo
        elif idx == len(counts) - 1:
            mid = hi_seen  # overflow bucket: the observed max is the bound
        else:
            upper = self.lo * self.growth ** idx
            mid = upper / math.sqrt(self.growth)  # geometric bucket midpoint
        return float(min(max(mid, lo_seen), hi_seen))

    def mean(self) -> float | None:
        with self._lock:
            return (self.sum / self.count) if self.count else None

    # ------------------------------------------------------------- merge --

    def _layout(self) -> tuple:
        return (self.lo, self.growth, len(self.counts))

    def merge(self, other: "Histogram | dict") -> None:
        """Add another histogram's (or snapshot's) counts into this one.
        Layouts must match exactly — same lo, growth, bucket count —
        which every registry-default histogram satisfies."""
        if isinstance(other, Histogram):
            other = other.snapshot()  # takes other's lock: a consistent view
        layout = (float(other["lo"]), float(other["growth"]), len(other["counts"]))
        if layout != self._layout():
            raise ValueError(f"histogram layout mismatch: {layout} != {self._layout()}")
        with self._lock:
            for i, c in enumerate(other["counts"]):
                self.counts[i] += c
            self.count += other["count"]
            self.sum += other["sum"]
            if other["count"]:
                self.min = min(self.min, other["min"])
                self.max = max(self.max, other["max"])

    # --------------------------------------------------------- serialize --

    def snapshot(self) -> dict:
        """JSON-able full state + derived p50/p99/mean convenience fields
        (ignored by from_snapshot/merge)."""
        with self._lock:
            snap = {
                "lo": self.lo,
                "growth": self.growth,
                "counts": list(self.counts),
                "count": self.count,
                "sum": self.sum,
                "min": self.min if self.count else None,
                "max": self.max if self.count else None,
            }
        if snap["count"]:
            snap["mean"] = round(snap["sum"] / snap["count"], 9)
            p50, p99 = self.quantile(0.5), self.quantile(0.99)
            snap["p50"] = round(p50, 9) if p50 is not None else None
            snap["p99"] = round(p99, 9) if p99 is not None else None
        return snap

    def delta_since(self, base: dict | None) -> dict | None:
        """Snapshot of everything recorded since ``base`` (an earlier
        snapshot of THIS histogram), or None when nothing changed — the
        worker→parent shipping unit. min/max are shipped as current
        values: they only tighten monotonically, so merging them
        repeatedly with min/max is idempotent."""
        snap = self.snapshot()
        if base is None:
            return snap if snap["count"] else None
        if snap["count"] == base["count"]:
            return None
        snap["counts"] = [c - b for c, b in zip(snap["counts"], base["counts"])]
        snap["count"] -= base["count"]
        snap["sum"] -= base["sum"]
        return snap

    @classmethod
    def from_snapshot(cls, snap: dict) -> "Histogram":
        h = cls.__new__(cls)
        h.lo = float(snap["lo"])
        h.growth = float(snap["growth"])
        h._log_growth = math.log(h.growth)
        h.counts = [int(c) for c in snap["counts"]]
        h.count = int(snap["count"])
        h.sum = float(snap["sum"])
        h.min = snap["min"] if snap.get("min") is not None else math.inf
        h.max = snap["max"] if snap.get("max") is not None else -math.inf
        h._lock = threading.Lock()
        return h
