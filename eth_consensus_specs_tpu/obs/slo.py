"""Declarative SLOs evaluated from a registry snapshot.

An SLO here is a small named predicate over ``obs.snapshot()`` (or an
``obs_report.json`` loaded from disk — the shapes match), so the same
objectives gate a live service (scripts/serve_bench.py), a CI run (the
obs-report job), and ad-hoc inspection. Three kinds:

  * ``quantile_max`` — a mergeable-histogram quantile must not exceed
    a bound (serve wait p99);
  * ``counter_max`` — a counter must not exceed a bound (watchdog
    divergences == 0, compiles-after-warmup == 0 are ``bound 0``);
  * ``ratio_max`` — numerator/denominator counters must not exceed a
    bound (``serve.degraded_items`` per served request — the per-ITEM
    degradation counter, not per-event ``fault.degraded``: one dead
    flush degrades every member request, and the ratio must say so).

Evaluation is vacuous-pass on missing data *except* for ratio
numerators: a nonzero numerator with a zero denominator is a violation
(degradations happened with no traffic to amortize them), and an absent
counter reads as 0 (monotonic counters start there).

The default objective set — the north-star telemetry contract — and its
env knobs:

    ETH_SPECS_SLO_WAIT_P99_MS    serve wait p99 bound, ms   (default 250)
    ETH_SPECS_SLO_DEGRADED_RATE  serve.degraded_items per serve request
                                 (default 0.01)

plus fixed ``watchdog.divergences == 0`` and
``serve.compiles_after_warmup == 0`` (recorded by serve_bench after its
warmup phase; absent in runs without a warmup notion → passes).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from dataclasses import dataclass

from .histogram import Histogram

# timestamped window verdicts behind the burn-rate advisory: the
# counters alone can't support a time cap (one ancient breached window
# would dominate forever), so note_window() keeps a bounded in-process
# record of (monotonic t, breached) per evaluated window
_WINDOWS_CAP = 4096
_WINDOWS_LOCK = threading.Lock()
_WINDOWS: deque = deque(maxlen=_WINDOWS_CAP)


def _reinit_lock_after_fork_in_child() -> None:
    # same idiom as obs/flight.py: a supervisor thread may hold the
    # record lock at fork time; the child is single-threaded here
    global _WINDOWS_LOCK
    _WINDOWS_LOCK = threading.Lock()


os.register_at_fork(after_in_child=_reinit_lock_after_fork_in_child)


@dataclass(frozen=True)
class SLO:
    name: str
    kind: str  # "quantile_max" | "counter_max" | "ratio_max"
    bound: float
    # quantile_max
    histogram: str | None = None
    q: float = 0.99
    # counter_max / ratio_max
    counter: str | None = None
    denominator: str | None = None


@dataclass
class SLOResult:
    name: str
    ok: bool
    observed: float | None
    bound: float
    detail: str = ""

    def as_dict(self) -> dict:
        return {
            "name": self.name,
            "ok": self.ok,
            "observed": self.observed,
            "bound": self.bound,
            "detail": self.detail,
        }


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    try:
        return float(raw) if raw else default
    except ValueError:
        return default


def default_slos() -> list[SLO]:
    return [
        SLO(
            name="serve_wait_p99",
            kind="quantile_max",
            histogram="serve.wait_ms",
            q=0.99,
            bound=_env_float("ETH_SPECS_SLO_WAIT_P99_MS", 250.0),
        ),
        SLO(
            name="degraded_rate",
            kind="ratio_max",
            counter="serve.degraded_items",
            denominator="serve.requests",
            bound=_env_float("ETH_SPECS_SLO_DEGRADED_RATE", 0.01),
        ),
        SLO(name="watchdog_divergences", kind="counter_max",
            counter="watchdog.divergences", bound=0),
        SLO(name="compiles_after_warmup", kind="counter_max",
            counter="serve.compiles_after_warmup", bound=0),
    ]


def _eval_one(slo: SLO, snap: dict) -> SLOResult:
    counters = snap.get("counters", {})
    if slo.kind == "quantile_max":
        hsnap = snap.get("histograms", {}).get(slo.histogram)
        if not hsnap or not hsnap.get("count"):
            return SLOResult(slo.name, True, None, slo.bound,
                             f"no samples in {slo.histogram} (vacuous pass)")
        observed = Histogram.from_snapshot(hsnap).quantile(slo.q)
        return SLOResult(
            slo.name, observed <= slo.bound, round(observed, 3), slo.bound,
            f"p{int(slo.q * 100)}({slo.histogram}) over {hsnap['count']} samples",
        )
    if slo.kind == "counter_max":
        observed = counters.get(slo.counter, 0)
        return SLOResult(slo.name, observed <= slo.bound, observed, slo.bound,
                         slo.counter)
    if slo.kind == "ratio_max":
        num = counters.get(slo.counter, 0)
        den = counters.get(slo.denominator, 0)
        if den == 0:
            # no traffic: clean iff nothing degraded either
            return SLOResult(slo.name, num == 0, float(num), slo.bound,
                             f"{slo.counter}={num} with {slo.denominator}=0")
        observed = num / den
        return SLOResult(slo.name, observed <= slo.bound, round(observed, 6),
                         slo.bound, f"{slo.counter}/{slo.denominator}")
    return SLOResult(slo.name, False, None, slo.bound, f"unknown SLO kind {slo.kind!r}")


def evaluate(snap: dict | None = None, slos: list[SLO] | None = None) -> list[SLOResult]:
    """Evaluate ``slos`` (default: :func:`default_slos`) against ``snap``
    (default: the live registry snapshot). A breach observed against the
    LIVE registry is a flight-recorder trigger — the process just failed
    its objectives, so it leaves a postmortem bundle; evaluating a loaded
    report (snap passed in) is inspection, not an incident, and never
    dumps."""
    live = snap is None
    if snap is None:
        from .registry import get_registry

        snap = get_registry().snapshot()
    results = [_eval_one(s, snap) for s in (slos if slos is not None else default_slos())]
    if live and not passed(results):
        from . import flight

        flight.trigger_dump(
            "slo.breach",
            detail=",".join(r.name for r in results if not r.ok),
            extra={"slo": report(results)},
        )
    return results


def passed(results: list[SLOResult]) -> bool:
    return all(r.ok for r in results)


def report(results: list[SLOResult]) -> dict:
    """JSON-able summary: {ok, violations: [names], results: [...]}."""
    return {
        "ok": passed(results),
        "violations": [r.name for r in results if not r.ok],
        "results": [r.as_dict() for r in results],
    }


def note_window(breached: bool, t: float | None = None) -> None:
    """Record one evaluated supervision window's verdict: bumps the
    ``slo.windows[_breached]`` counters AND appends a timestamped record
    so :func:`burn_rate` can answer time-capped queries. The front door
    supervisor calls this once per probe window with traffic
    (frontdoor._burn_step)."""
    from .registry import get_registry

    reg = get_registry()
    reg.count("slo.windows", 1)
    if breached:
        reg.count("slo.windows_breached", 1)
    with _WINDOWS_LOCK:
        _WINDOWS.append((time.monotonic() if t is None else t, bool(breached)))


def reset_windows_for_tests() -> None:
    with _WINDOWS_LOCK:
        _WINDOWS.clear()


def burn_rate(snap: dict | None = None, window_s: float | None = None) -> dict | None:
    """Windowed burn-rate advisory: the fraction of supervision probe
    windows (with traffic) whose window-local wait p99 breached the
    objective (recorded via :func:`note_window`).

    With ``window_s=None`` this reads the cumulative ``slo.windows`` /
    ``slo.windows_breached`` counters from ``snap`` (default: live
    registry) — the whole-run advisory. With ``window_s`` set, only
    windows recorded within the last ``window_s`` seconds count, so one
    ancient breached window can't dominate the advisory forever; this
    uses the live in-process records and therefore ignores ``snap``
    (a loaded report has no timestamps to cap by).

    Returns ``{"windows", "breached", "burn_rate"}`` (plus
    ``"window_s"`` when capped) or None when no window qualifies. A p99
    SLO that only breaches at the end of a long run looks fine in the
    run-wide histogram; the burn rate says how much of the RUN was
    spent out of budget. Advisory, never gating — perf_track ingests
    it as a secondary (lower is better)."""
    if window_s is not None:
        cutoff = time.monotonic() - float(window_s)
        with _WINDOWS_LOCK:
            records = [b for (t, b) in _WINDOWS if t >= cutoff]
        if not records:
            return None
        breached = sum(1 for b in records if b)
        return {
            "windows": len(records),
            "breached": breached,
            "burn_rate": round(breached / len(records), 6),
            "window_s": float(window_s),
        }
    if snap is None:
        from .registry import get_registry

        snap = get_registry().snapshot()
    counters = snap.get("counters", {})
    windows = counters.get("slo.windows", 0)
    if not windows:
        return None
    breached = counters.get("slo.windows_breached", 0)
    return {
        "windows": int(windows),
        "breached": int(breached),
        "burn_rate": round(breached / windows, 6),
    }
