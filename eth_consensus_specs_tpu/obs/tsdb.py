"""In-process metric time-series: a bounded ring of timestamped windows.

Everything the registry holds is cumulative — counters only grow,
histograms only accumulate — which answers "what happened since boot"
but not "what is happening NOW". The continuous-telemetry plane
(docs/observability.md#continuous-telemetry) needs the latter: anomaly
detectors (obs/anomaly.py) reason about the last few minutes, and the
fleet scoreboard (scripts/obs_top.py) draws sparklines from per-window
deltas.

This module turns the existing :class:`~.delta.DeltaShipper` machinery
into a time series. Each supervisor probe tick the sampler takes one
delta — exactly the shipping unit replicas already produce — and folds
it into a :class:`Sample`:

  * ``counters`` — raw per-window counter increments, and ``rates``
    (increments / window seconds);
  * ``gauges`` — the ``{last, max}`` levels that changed this window;
  * ``hists`` — histogram **bucket deltas** for the window, so a
    window-local p99 comes from :meth:`Histogram.from_snapshot
    <..obs.histogram.Histogram.from_snapshot>` over just this window's
    samples (no cumulative smearing);
  * ``events`` — the flight-ring entries shipped in the window (the
    anomaly engine reads replica-death breadcrumbs and nearby trace ids
    straight from here).

The ring is bounded (``ETH_SPECS_OBS_TSDB_RING`` samples, default 600 —
two minutes at the default 200 ms probe interval) and entirely
in-process: nothing is written to disk, nothing leaves the process
except via an exemplar bundle when a detector fires.

The sampler must own its OWN shipper (the ``_slo_shipper`` /
``_burn_shipper`` precedent in serve/frontdoor.py): shippers are
per-consumer cursors, and sharing one would steal windows from the SLO
evaluator.
"""

from __future__ import annotations

import os
import time
from collections import deque

from .delta import DeltaShipper
from .histogram import Histogram

_DEFAULT_RING = 600


def ring_capacity_from_env() -> int:
    raw = os.environ.get("ETH_SPECS_OBS_TSDB_RING", "")
    try:
        n = int(raw) if raw else _DEFAULT_RING
    except ValueError:
        n = _DEFAULT_RING
    return max(n, 2)


def enabled_from_env() -> bool:
    return os.environ.get("ETH_SPECS_OBS_TSDB", "1") not in ("0", "false", "")


class Sample:
    """One timestamped telemetry window (all fields plain JSON-ables)."""

    __slots__ = ("t", "dt", "counters", "rates", "gauges", "hists", "events")

    def __init__(self, t, dt, counters=None, rates=None, gauges=None,
                 hists=None, events=None):
        self.t = float(t)
        self.dt = float(dt)
        self.counters = counters or {}
        self.rates = rates or {}
        self.gauges = gauges or {}
        self.hists = hists or {}
        self.events = events or []

    def hist_count(self, name: str) -> int:
        h = self.hists.get(name)
        return int(h.get("count", 0)) if h else 0

    def quantile(self, name: str, q: float) -> float | None:
        """Window-local quantile from this window's bucket deltas."""
        h = self.hists.get(name)
        if not h or not h.get("count"):
            return None
        return Histogram.from_snapshot(h).quantile(q)

    def summary(self) -> dict:
        """Compact JSON view for exemplar bundles: everything except the
        raw bucket arrays (replaced by count/p99 per histogram)."""
        hists = {}
        for name, h in self.hists.items():
            if not h.get("count"):
                continue
            hh = Histogram.from_snapshot(h)
            hists[name] = {
                "count": h["count"],
                "sum": round(h.get("sum", 0.0), 3),
                "p99": hh.quantile(0.99),
            }
        return {
            "t": self.t,
            "dt": round(self.dt, 6),
            "counters": dict(self.counters),
            "rates": {k: round(v, 3) for k, v in self.rates.items()},
            "gauges": self.gauges,
            "hists": hists,
        }


class SeriesRing:
    """Bounded ring of :class:`Sample` windows, oldest first."""

    def __init__(self, capacity: int | None = None):
        self._ring: deque[Sample] = deque(maxlen=capacity or ring_capacity_from_env())

    def __len__(self) -> int:
        return len(self._ring)

    @property
    def capacity(self) -> int:
        return self._ring.maxlen or 0

    def append(self, sample: Sample) -> Sample:
        self._ring.append(sample)
        return sample

    def samples(self) -> list[Sample]:
        return list(self._ring)

    def last(self, n: int) -> list[Sample]:
        if n <= 0:
            return []
        return list(self._ring)[-n:]

    def span_s(self) -> float:
        """Wall seconds the ring currently covers."""
        if len(self._ring) < 2:
            return 0.0
        return self._ring[-1].t - self._ring[0].t

    # ---------------------------------------------------------- series --

    def rate_series(self, name: str) -> list[tuple[float, float]]:
        return [(s.t, s.rates.get(name, 0.0)) for s in self._ring]

    def counter_series(self, name: str) -> list[tuple[float, float]]:
        return [(s.t, s.counters.get(name, 0)) for s in self._ring]

    def gauge_series(self, name: str) -> list[tuple[float, float]]:
        """Gauge ``last`` levels, carried forward across windows where
        the gauge did not change (deltas only ship changes)."""
        out: list[tuple[float, float]] = []
        level: float | None = None
        for s in self._ring:
            g = s.gauges.get(name)
            if g is not None:
                level = g.get("last") if isinstance(g, dict) else g
            if level is not None:
                out.append((s.t, float(level)))
        return out

    def quantile_series(self, name: str, q: float) -> list[tuple[float, float]]:
        """Window-local quantiles for one histogram; windows with no
        samples are skipped (a quiet window has no latency, not zero)."""
        out: list[tuple[float, float]] = []
        for s in self._ring:
            v = s.quantile(name, q)
            if v is not None:
                out.append((s.t, v))
        return out

    def tail_summary(self, n: int = 32) -> list[dict]:
        """The last ``n`` windows as compact dicts — the 'triggering
        series window' section of an anomaly exemplar bundle."""
        return [s.summary() for s in self.last(n)]


def sample_from_delta(delta: dict, t: float, dt: float) -> Sample:
    """Fold one DeltaShipper delta into a timestamped window sample."""
    dt = max(float(dt), 1e-9)
    counters = dict(delta.get("counters", {}))
    return Sample(
        t=t,
        dt=dt,
        counters=counters,
        rates={k: v / dt for k, v in counters.items()},
        gauges=dict(delta.get("gauges", {})),
        hists=dict(delta.get("histograms", {})),
        events=list(delta.get("flight", ())),
    )


class Sampler:
    """Owns a delta cursor + ring; one :meth:`sample` per probe tick.

    ``swallow_initial`` (the shipper default) applies: the first sample
    covers construction → first tick only, so boot churn from before the
    telemetry plane existed never lands in the series.
    """

    def __init__(self, capacity: int | None = None, shipper: DeltaShipper | None = None):
        self.ring = SeriesRing(capacity)
        self._shipper = shipper or DeltaShipper()
        self._last_t = time.monotonic()

    def sample(self, t: float | None = None) -> Sample:
        from eth_consensus_specs_tpu import obs

        t = time.monotonic() if t is None else t
        dt = max(t - self._last_t, 1e-9)
        self._last_t = t
        s = self.ring.append(sample_from_delta(self._shipper.delta(), t, dt))
        obs.count("tsdb.samples", 1)
        return s
