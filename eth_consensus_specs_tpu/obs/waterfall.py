"""Request waterfall: per-stage latency attribution for the serve stack.

``serve.wait_ms`` says how long a request took; it cannot say *where*
the time went — admission, batching, host prep, dispatch-queue
backpressure, device execution, or future resolution are one
undifferentiated number. Tail latency under bursty mixes is a per-stage
phenomenon: you cannot tune admission, batching, or routing against a
single p99. So every :class:`~..serve.batcher.Request` carries a
**stamp vector** — a dict of monotonic marks written as the request
crosses each pipeline boundary:

    t_submit (anchor) → admitted → queued → flush_assembled → prepped
    → dispatch_queued → device_start → device_done → resolved

The marks partition wall clock into CONTIGUOUS named stages (see
:data:`STAGES`); at resolve time each interval lands in a
``serve.stage_ms.<stage>`` histogram. Because the stages tile the
request's lifetime, the named sums cover the end-to-end wall by
construction — anything they miss (a dropped stamp on an error path, a
scheduler gap) is a first-class ``other`` stage, never silent. The
``total`` stage is the request's own e2e and the denominator for the
coverage gate in scripts/serve_bench.py.

**Cross-process merge.** Monotonic clocks do not compare across
processes, so a replica never ships absolute stamps: the serving
process stashes each request's *durations* here keyed by trace id
(:func:`stash`), the RPC layer pops them (:func:`pop`) and attaches
them to the submit reply, and the front door records only the residual
``serve.stage_ms.wire`` = client e2e − replica-reported total. The
replica's own stage histograms reach the parent via the obs delta
(obs/delta.py) like every other metric — re-observing the shipped
durations client-side would double count.

Everything here is allocation-light and never raises; with
``ETH_SPECS_OBS=0`` the histogram writes are no-ops (marks still cost
one ``time.monotonic`` — the serve layer is not jit-reachable).
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict

# marks in pipeline order, AFTER the t_submit anchor; "admitted" is
# written by the admission controller, the rest by batcher/service
MARKS = (
    "admitted",
    "queued",
    "flush_assembled",
    "prepped",
    "dispatch_queued",
    "device_start",
    "device_done",
    "resolved",
)

# contiguous named stages: (stage, start mark, end mark); "t0" is the
# request's t_submit. The admit stage absorbs Request construction and
# the batcher enqueue on purpose — sub-microsecond slivers between
# "admitted" and "queued" belong to admission's bill, not to "other".
STAGES = (
    ("admit", "t0", "queued"),
    ("queue", "queued", "flush_assembled"),
    ("prep", "flush_assembled", "prepped"),
    ("handoff", "prepped", "dispatch_queued"),
    ("dispatch_wait", "dispatch_queued", "device_start"),
    ("device", "device_start", "device_done"),
    ("resolve", "device_done", "resolved"),
)

STAGE_NAMES = tuple(s for s, _, _ in STAGES)

# cross-process duration stash: trace_id -> durations dict, bounded so
# a direct-service caller that never pops (serve_bench default mode)
# cannot grow it without limit
_STASH_CAP = 4096
_STASH_LOCK = threading.Lock()
_STASH: OrderedDict[str, dict] = OrderedDict()


def _reinit_lock_after_fork_in_child() -> None:
    # same idiom as obs/flight.py: a parent thread may hold the stash
    # lock at fork time; the child is single-threaded here
    global _STASH_LOCK
    _STASH_LOCK = threading.Lock()


os.register_at_fork(after_in_child=_reinit_lock_after_fork_in_child)


# ------------------------------------------------------------------- marks --


def mark(stamps: dict | None, name: str, t: float | None = None) -> None:
    """Write one monotonic mark into a request's stamp vector. First
    write wins — a hedged or retried path can never rewind a stamp, so
    the vector stays monotone even when two threads race a boundary."""
    if stamps is None:
        return
    if name not in stamps:
        stamps[name] = time.monotonic() if t is None else t


def mark_all(reqs, name: str) -> None:
    """Stamp a shared boundary (flush assembly, device start/done) onto
    every request of a flush with ONE clock read — the flush executes as
    a unit, so its members share the boundary by definition."""
    t = time.monotonic()
    for r in reqs:
        mark(getattr(r, "stamps", None), name, t)


def stage_durations_ms(t0: float, stamps: dict | None) -> dict:
    """Fold a stamp vector into named-stage durations (milliseconds).

    Returns ``{}`` until the request is resolved. A stage whose marks
    are missing (error path resolved before dispatch) is simply absent;
    its time shows up in ``other`` = total − sum(named), clamped at 0.
    """
    if not stamps:
        return {}
    resolved = stamps.get("resolved")
    if resolved is None:
        return {}
    marks = dict(stamps)
    marks["t0"] = t0
    total = max((resolved - t0) * 1e3, 0.0)
    out: dict = {}
    named = 0.0
    for stage, start, end in STAGES:
        a = marks.get(start)
        b = marks.get(end)
        if a is None or b is None:
            continue
        d = max((b - a) * 1e3, 0.0)
        out[stage] = d
        named += d
    out["other"] = max(total - named, 0.0)
    out["total"] = total
    return out


def observe(durations: dict) -> None:
    """Record one request's stage durations into the
    ``serve.stage_ms.<stage>`` histograms. No-op when obs is disabled
    or the request never produced durations."""
    if not durations:
        return
    from .registry import get_registry, obs_enabled

    if not obs_enabled():
        return
    reg = get_registry()
    for stage, ms in durations.items():
        reg.observe(f"serve.stage_ms.{stage}", ms)


# ------------------------------------------------------------------- stash --


def stash(trace_id: str | None, durations: dict) -> None:
    """Park a resolved request's durations for the RPC layer to attach
    to its reply (keyed by trace id — ``trace.child`` preserves it, so
    the service-side request and the wire frame share the key)."""
    if not trace_id or not durations:
        return
    with _STASH_LOCK:
        _STASH[trace_id] = durations
        _STASH.move_to_end(trace_id)
        while len(_STASH) > _STASH_CAP:
            _STASH.popitem(last=False)


def pop(trace_id: str | None) -> dict | None:
    """Claim (and remove) the stashed durations for one trace id."""
    if not trace_id:
        return None
    with _STASH_LOCK:
        return _STASH.pop(trace_id, None)


def stash_size() -> int:
    with _STASH_LOCK:
        return len(_STASH)


def reset_for_tests() -> None:
    with _STASH_LOCK:
        _STASH.clear()


# ------------------------------------------------------------------ report --


def report(snapshot: dict) -> dict:
    """Waterfall summary from a registry snapshot: per-stage
    count/p50/p99/sum plus the two gateable aggregates —

    * ``coverage``: sum of named-stage milliseconds over the ``total``
      stage's milliseconds (the ≥0.95 serve_bench gate);
    * ``other_share_p50``: the ``other`` stage's p50 as a fraction of
      the ``total`` p50 (the <0.20 gate).

    Works on any snapshot with the stage histograms — a live registry,
    a merged front-door view, or a postmortem bundle's ``registry``.
    """
    hists = snapshot.get("histograms", {})
    prefix = "serve.stage_ms."
    stages: dict = {}
    for name, h in hists.items():
        if name.startswith(prefix):
            stages[name[len(prefix):]] = {
                "count": h.get("count", 0),
                "p50_ms": h.get("p50", 0.0),
                "p99_ms": h.get("p99", 0.0),
                "sum_ms": h.get("sum", 0.0),
            }
    total = stages.get("total")
    named_sum = sum(
        s["sum_ms"] for name, s in stages.items() if name in STAGE_NAMES
    )
    coverage = None
    other_share_p50 = None
    if total and total["sum_ms"] > 0:
        coverage = named_sum / total["sum_ms"]
        if total["p50_ms"] > 0:
            other = stages.get("other", {"p50_ms": 0.0})
            other_share_p50 = other["p50_ms"] / total["p50_ms"]
    return {
        "stages": stages,
        "coverage": coverage,
        "other_share_p50": other_share_p50,
        "e2e_p50_ms": total["p50_ms"] if total else None,
        "e2e_p99_ms": total["p99_ms"] if total else None,
    }
