"""In-process observability registry: spans, counters, event stream.

One process-wide thread-safe `Registry` holds

  * **counters** — monotonically increasing named totals
    (``sha256.compressions``, ``merkle.real_hashes``, ``watchdog.checks``);
  * **span aggregates** — per-name call count / total / min / max wall
    seconds with `block_until_ready` semantics (the span blocks on its
    ``result`` before stopping the clock, so async dispatch can't report
    a kernel as free), plus a roofline verdict via obs/gates.py whenever
    the span declared its ``work_bytes``;
  * **events** — a bounded in-memory ring of structured records, mirrored
    to a JSONL sink when ``ETH_SPECS_OBS_JSONL`` names a file.

Spans nest through a thread-local stack: each record carries its parent
span name and depth, so ``epoch.justification`` inside
``epoch.accounting`` is attributable in both the registry and the
Perfetto trace (the span also enters a ``jax.profiler.TraceAnnotation``
via utils/profiling.annotate, so the same names appear in
TensorBoard/Perfetto when a `utils.profiling.trace` region is live).

Everything degrades to near-zero cost: ``ETH_SPECS_OBS=0`` turns every
entry point into a no-op, and all jax interaction is lazy + best-effort
so the registry works in processes that never import jax.
"""

from __future__ import annotations

import json
import os
import threading
import time

from . import flight, gates, trace
from .histogram import Histogram

_MAX_EVENTS = 10_000

# cached once per process (refreshed in the at-fork hook): emit() stamps
# every event with its origin pid so the fleet timeline assembler
# (obs/timeline.py) can group one JSONL stream's events per process
_PID = os.getpid()


def refresh_enabled() -> bool:
    """Re-read ETH_SPECS_OBS into the cached module flag. The flag is
    resolved once at import so the hot paths don't pay an environ lookup
    per span/counter call; processes that flip the env var mid-run
    (tests) call this to apply it."""
    global _ENABLED
    _ENABLED = os.environ.get("ETH_SPECS_OBS", "1") not in ("0", "false", "")
    return _ENABLED


_ENABLED = True
refresh_enabled()


def obs_enabled() -> bool:
    return _ENABLED


class _SpanHandle:
    """Live span: assign ``.result`` to the device value the span produced
    so the exit path can block on it (dispatch-acknowledged-but-not-
    executed work then shows up as time, not as a suspiciously free op)."""

    __slots__ = ("name", "attrs", "t0", "parent", "depth", "result", "_annotation",
                 "_registry", "_trace")

    def __init__(self, registry: "Registry", name: str, attrs: dict):
        self.name = name
        self.attrs = attrs
        self.result = None
        self._registry = registry
        self._annotation = None

    def __enter__(self):
        stack = self._registry._span_stack()
        self.parent = stack[-1] if stack else None
        self.depth = len(stack)
        stack.append(self.name)
        # under an active trace context (obs/trace.py) the span becomes a
        # trace span: its event carries trace_id/span_id/parent_span so
        # it stitches across thread and process boundaries
        self._trace = trace.enter_span()
        self._annotation = _enter_annotation(self.name)
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        if self.result is not None:
            _block_until_ready(self.result)
        seconds = time.perf_counter() - self.t0
        if self._annotation is not None:
            try:
                self._annotation.__exit__(exc_type, exc, tb)
            except Exception:
                pass
        trace.exit_span(self._trace)
        stack = self._registry._span_stack()
        if stack and stack[-1] == self.name:
            stack.pop()
        if exc_type is None:
            self._registry.record_span(
                self.name, seconds, self.attrs, parent=self.parent, depth=self.depth,
                trace_ctx=self._trace,
            )
        return False


class _NullSpan:
    """Disabled-mode span: context manager with a writable ``result``.
    One instance per call — a shared singleton would pin the last
    assigned ``result`` (possibly a large device array) for the process
    lifetime and race across threads."""

    __slots__ = ("result",)

    def __init__(self):
        self.result = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.result = None
        return False


def _enter_annotation(name: str):
    """Layer the span onto the jax profiler (utils/profiling.annotate) so
    the same names show up in Perfetto/TensorBoard. Best-effort: no jax,
    no annotation — the registry side still records."""
    try:
        from eth_consensus_specs_tpu.utils.profiling import annotate

        ann = annotate(name)
        ann.__enter__()
        return ann
    except Exception:
        return None


def _block_until_ready(x):
    try:
        import jax

        jax.block_until_ready(x)
    except Exception:
        pass


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._local = threading.local()
        self.counters: dict[str, float] = {}
        self.gauges: dict[str, dict] = {}
        self.histograms: dict[str, Histogram] = {}
        self.spans: dict[str, dict] = {}
        self.events: list[dict] = []
        self._jsonl_path: str | None = os.environ.get("ETH_SPECS_OBS_JSONL") or None
        self._jsonl_fh = None

    # ------------------------------------------------------------- spans --

    def _span_stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def span(self, name: str, **attrs) -> _SpanHandle | _NullSpan:
        if not obs_enabled():
            return _NullSpan()
        return _SpanHandle(self, name, attrs)

    def record_span(
        self, name: str, seconds: float, attrs: dict | None = None,
        parent: str | None = None, depth: int = 0,
        trace_ctx=None,
    ) -> None:
        attrs = attrs or {}
        verdict = None
        work_bytes = attrs.get("work_bytes")
        if work_bytes and seconds > 0:
            # every device timing carries its roofline verdict (the
            # bench-grade gate, one implementation: obs/gates.py)
            verdict = gates.roofline_verdict(work_bytes, seconds)
        with self._lock:
            agg = self.spans.get(name)
            if agg is None:
                agg = self.spans[name] = {
                    "count": 0,
                    "total_s": 0.0,
                    "min_s": float("inf"),
                    "max_s": 0.0,
                    "work_bytes": 0,
                    "roofline_violations": 0,
                    "parent": parent,
                    "depth": depth,
                }
            agg["count"] += 1
            agg["total_s"] += seconds
            agg["min_s"] = min(agg["min_s"], seconds)
            agg["max_s"] = max(agg["max_s"], seconds)
            if work_bytes:
                agg["work_bytes"] += int(work_bytes)
            if verdict is not None:
                agg["implied_gbps"] = verdict["implied_gbps"]  # last call's rate
                if not verdict["roofline_ok"]:
                    agg["roofline_violations"] += 1
                # the aggregate verdict is the ALL-calls conjunction — one
                # impossible timing taints the span, whatever came after
                agg["roofline_ok"] = agg["roofline_violations"] == 0
        event = {"kind": "span", "name": name, "s": round(seconds, 9), "depth": depth}
        if parent:
            event["parent"] = parent
        event.update(trace.event_fields(trace_ctx))
        for k, v in attrs.items():
            # reserved event fields can't be shadowed by span attributes
            if k not in event and isinstance(v, (int, float, str, bool)):
                event[k] = v
        if verdict is not None:
            event.update(verdict)
        self.emit(event)

    # ---------------------------------------------------------- counters --

    def count(self, name: str, n: int | float = 1) -> None:
        if not obs_enabled():
            return
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + n
        # flight-recorder tap (obs/flight.py): mega-bumps above the floor
        # land in the postmortem ring; ETH_SPECS_OBS=0 never reaches here
        flight.note_count(name, n)

    def bytes_moved(self, name: str, nbytes: int) -> None:
        self.count(f"{name}.bytes_moved", int(nbytes))

    def gauge(self, name: str, value: int | float) -> None:
        """Record a point-in-time level (queue depth, in-flight bytes):
        unlike a counter it can go down — the snapshot keeps the last and
        the max, which is what capacity questions ("did the queue ever
        hit the cap?") actually need."""
        if not obs_enabled():
            return
        with self._lock:
            g = self.gauges.get(name)
            if g is None:
                g = self.gauges[name] = {"last": 0.0, "max": 0.0}
            g["last"] = value
            g["max"] = max(g["max"], value)

    # -------------------------------------------------------- histograms --

    def observe(self, name: str, value: float) -> None:
        """Record a sample into the named mergeable log-bucket histogram
        (auto-created with the shared default layout, so same-named
        histograms from any process always merge). The record path takes
        only the histogram's own O(1) lock — never the registry lock."""
        if not obs_enabled():
            return
        h = self.histograms.get(name)
        if h is None:
            with self._lock:
                h = self.histograms.setdefault(name, Histogram())
        h.record(value)

    def histogram(self, name: str) -> Histogram | None:
        return self.histograms.get(name)

    def merge_histogram(self, name: str, snap: dict) -> None:
        """Fold a serialized histogram delta (Histogram.delta_since) from
        another process into this registry's same-named histogram."""
        if not obs_enabled():
            return
        h = self.histograms.get(name)
        if h is None:
            with self._lock:
                h = self.histograms.setdefault(
                    name, Histogram(lo=snap["lo"], growth=snap["growth"])
                )
        h.merge(snap)

    def merge_gauge(self, name: str, g: dict) -> None:
        """Fold another process's gauge state in: ``last`` is latest-wins
        (the shipper is the fresher observation), ``max`` is monotonic."""
        if not obs_enabled():
            return
        with self._lock:
            cur = self.gauges.setdefault(name, {"last": 0.0, "max": 0.0})
            cur["last"] = g.get("last", cur["last"])
            cur["max"] = max(cur["max"], g.get("max", 0.0))

    def merge_span(self, name: str, agg: dict) -> None:
        """Fold another process's span-aggregate DELTA in (obs/delta.py
        ships count/total_s/work_bytes/roofline_violations as
        differences; min_s/max_s as current values — they only tighten,
        so repeated merging is idempotent). The merged roofline verdict
        stays the all-calls conjunction: one replica's impossible timing
        taints the fleet-wide span."""
        if not obs_enabled():
            return
        with self._lock:
            cur = self.spans.get(name)
            if cur is None:
                cur = self.spans[name] = {
                    "count": 0,
                    "total_s": 0.0,
                    "min_s": float("inf"),
                    "max_s": 0.0,
                    "work_bytes": 0,
                    "roofline_violations": 0,
                    "parent": agg.get("parent"),
                    "depth": agg.get("depth", 0),
                }
            cur["count"] += agg.get("count", 0)
            cur["total_s"] += agg.get("total_s", 0.0)
            cur["min_s"] = min(cur["min_s"], agg.get("min_s", float("inf")))
            cur["max_s"] = max(cur["max_s"], agg.get("max_s", 0.0))
            cur["work_bytes"] += int(agg.get("work_bytes", 0))
            cur["roofline_violations"] += agg.get("roofline_violations", 0)
            if "implied_gbps" in agg:
                cur["implied_gbps"] = agg["implied_gbps"]  # shipper's last rate
            if "roofline_ok" in agg or "roofline_ok" in cur:
                cur["roofline_ok"] = cur["roofline_violations"] == 0

    # ------------------------------------------------------------ events --

    def emit(self, event: dict) -> None:
        if not obs_enabled():
            return
        # paired clock stamps + process/thread identity on every event:
        # the fleet timeline assembler (obs/timeline.py) estimates
        # per-process clock offsets from the wall/monotonic PAIR and
        # needs pid/tid for truthful process/thread tracks. Four scalar
        # stores — the no-context fast path stays allocation-light.
        if "t_mono" not in event:
            event["t_mono"] = time.perf_counter()
            event["t_wall"] = time.time()
            event["pid"] = _PID
            event["tid"] = threading.get_ident()
        # every emitted event is also a flight-recorder entry: the ring
        # holds the last N of these when a postmortem trigger fires
        flight.note_event(event)
        with self._lock:
            self.events.append(event)
            if len(self.events) > _MAX_EVENTS:
                del self.events[: len(self.events) // 2]
            fh = self._jsonl_handle()
            # write under the lock: lines never interleave, and a
            # concurrent configure_jsonl close can't yank the handle
            # mid-write (a closed file raises ValueError, not OSError)
            if fh is not None:
                try:
                    fh.write(json.dumps(event, sort_keys=True) + "\n")
                    fh.flush()
                except (OSError, ValueError):
                    pass

    def _jsonl_handle(self):
        if self._jsonl_path is None:
            return None
        if self._jsonl_fh is None:
            try:
                self._jsonl_fh = open(self._jsonl_path, "a")
            except OSError:
                self._jsonl_path = None
        return self._jsonl_fh

    def configure_jsonl(self, path: str | None) -> None:
        with self._lock:
            if self._jsonl_fh is not None:
                try:
                    self._jsonl_fh.close()
                except OSError:
                    pass
            self._jsonl_fh = None
            self._jsonl_path = path

    # ----------------------------------------------------------- reports --

    def snapshot(self) -> dict:
        """Point-in-time copy: {counters, spans, watchdog} — the watchdog
        section is derived from its counters so one code path feeds the
        pytest report, bench, and ad-hoc inspection."""
        with self._lock:
            counters = dict(self.counters)
            gauges = {name: dict(g) for name, g in self.gauges.items()}
            hist_refs = dict(self.histograms)
            spans = {
                name: {k: (round(v, 9) if isinstance(v, float) else v) for k, v in agg.items()}
                for name, agg in self.spans.items()
            }
        kernels: dict[str, dict] = {}
        for key, val in counters.items():
            if not key.startswith("watchdog."):
                continue
            parts = key.split(".")
            if len(parts) == 3:  # watchdog.<kernel>.<checks|divergences>
                kernels.setdefault(parts[1], {})[parts[2]] = val
        return {
            "counters": counters,
            "gauges": gauges,
            # each histogram serializes under its own lock (post-snapshot
            # records may slip in — a snapshot is a point-in-time-ish view)
            "histograms": {name: h.snapshot() for name, h in hist_refs.items()},
            "spans": spans,
            "watchdog": {
                "checks": counters.get("watchdog.checks", 0),
                "divergences": counters.get("watchdog.divergences", 0),
                "kernels": kernels,
            },
        }

    def reset(self) -> None:
        with self._lock:
            self.counters.clear()
            self.gauges.clear()
            self.histograms.clear()
            self.spans.clear()
            self.events.clear()


_REGISTRY = Registry()


def get_registry() -> Registry:
    return _REGISTRY


def _reinit_locks_after_fork_in_child() -> None:
    """Fork-safety: the parent may fork (gen pool workers) while one of
    its BACKGROUND threads — the front-door supervisor merging replica
    deltas, a dispatcher bumping counters — holds an obs-layer lock.
    The child inherits that lock HELD by a thread that doesn't exist
    there, and its first obs call deadlocks forever. The child is
    single-threaded at this moment, so unconditionally re-creating
    every lock is safe; torn metric values are bounded (single-key dict
    writes) and the worker's delta baseline swallows them at init. The
    inherited JSONL handle is dropped too — its buffer may hold half a
    line another thread was writing; the child reopens lazily in append
    mode."""
    global _PID
    reg = _REGISTRY
    reg._lock = threading.Lock()
    reg._local = threading.local()
    reg._jsonl_fh = None
    _PID = os.getpid()  # the child's events must carry ITS pid
    for h in list(reg.histograms.values()):
        h._lock = threading.Lock()


os.register_at_fork(after_in_child=_reinit_locks_after_fork_in_child)
