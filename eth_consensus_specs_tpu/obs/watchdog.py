"""Always-on device/host divergence watchdog.

Round 4's failure mode — an accelerator platform acknowledging work
before executing it — is only caught by *continuously* coupling device
results to host recomputes, not just inside bench.py. This module
samples the kernel hot paths at an env-tunable rate and recomputes a
(salted, where an extra dispatch is involved) slice of each device
result on the host with an engine that shares nothing with XLA
(hashlib / the pure spec loop / the host pairing). Match/mismatch lands
in first-class metrics:

    watchdog.checks / watchdog.divergences            (global)
    watchdog.<kernel>.checks / .divergences           (per kernel)

plus a structured event per divergence with enough context to reproduce.

Tuning: ``ETH_SPECS_OBS_WATCHDOG`` is the sampling rate in [0, 1] —
``0`` disables, ``1`` checks every call (CI smoke), default ``0.05``
(every ~20th call per kernel; the FIRST call is always checked so every
process gets at least one verdict per touched kernel).
"""

from __future__ import annotations

import hashlib
import os
import threading

import numpy as np

from . import flight, gates
from .registry import get_registry, obs_enabled

_DEFAULT_RATE = 0.05

_lock = threading.Lock()
_calls: dict[str, int] = {}


def _reinit_lock_after_fork_in_child() -> None:
    # fork-safety: a serving thread can be inside should_check when the
    # gen pool forks; the child's first sampled kernel call must not
    # block on a lock held by a thread that does not exist there
    global _lock
    _lock = threading.Lock()


os.register_at_fork(after_in_child=_reinit_lock_after_fork_in_child)


def sampling_rate() -> float:
    raw = os.environ.get("ETH_SPECS_OBS_WATCHDOG", "")
    if not raw:
        return _DEFAULT_RATE
    try:
        return min(max(float(raw), 0.0), 1.0)
    except ValueError:
        return _DEFAULT_RATE


def should_check(kernel: str) -> bool:
    """Deterministic interval sampling per kernel: call k is checked when
    k % round(1/rate) == 1, so the first call always is — a short test
    process still produces a verdict for every kernel it touched."""
    if not obs_enabled():
        return False
    rate = sampling_rate()
    if rate <= 0.0:
        return False
    with _lock:
        _calls[kernel] = n = _calls.get(kernel, 0) + 1
    interval = max(1, round(1.0 / rate))
    return n % interval == 1 or interval == 1


def call_salt(kernel: str) -> int:
    """Deterministic per-call salt (Weyl sequence over the call counter):
    varies every sampled call, so a platform-side (program, input) result
    cache can never replay a previous probe's answer."""
    with _lock:
        n = _calls.get(kernel, 0)
    return (n * 0x9E3779B1 + 0x7F4A7C15) & 0xFFFFFFFF


def record(kernel: str, ok: bool, detail: dict | None = None) -> None:
    reg = get_registry()
    reg.count("watchdog.checks")
    reg.count(f"watchdog.{kernel}.checks")
    if not ok:
        reg.count("watchdog.divergences")
        reg.count(f"watchdog.{kernel}.divergences")
        event = {"kind": "watchdog.divergence", "kernel": kernel}
        if detail:
            event.update(detail)
        reg.emit(event)
        # a divergence is THE postmortem moment: dump the flight ring +
        # registry so the black box holds what led up to the wrong answer
        flight.trigger_dump("watchdog.divergence", detail=kernel, extra={"event": event})


# ------------------------------------------------------------ kernel checks --


def _be_words_to_bytes(row: np.ndarray) -> bytes:
    return row.astype(">u4", order="C").view(np.uint8).tobytes()


def _sample_rows(m: int, k: int = 3) -> list[int]:
    return sorted({0, m // 2, m - 1} if m >= k else set(range(m)))


def check_sha256_slice(words, digests, kernel: str = "sha256") -> bool:
    """Sampled rows of the batched 64-byte hash: device digest vs hashlib
    on the SAME input words. No extra device work — the output is already
    in hand at the call site; only the sampled rows (96 B each) cross to
    the host."""
    ok = True
    rows = _sample_rows(int(words.shape[0]))
    for i in rows:
        msg = _be_words_to_bytes(np.asarray(words[i]))
        expect = hashlib.sha256(msg).digest()
        got = _be_words_to_bytes(np.asarray(digests[i]))
        if got != expect:
            ok = False
            record(
                kernel,
                False,
                {"row": i, "expected": expect.hex()[:32], "got": got.hex()[:32]},
            )
            break
    if ok:
        record(kernel, True)
    return ok


def host_tree_root_words(words: np.ndarray) -> bytes:
    """Pairwise hashlib reduction of uint32[2**d, 8] big-endian leaf words
    to the 32-byte root — the zero-XLA host oracle for tree slices."""
    level = [
        _be_words_to_bytes(words[i]) for i in range(words.shape[0])
    ]
    while len(level) > 1:
        level = [
            hashlib.sha256(level[i] + level[i + 1]).digest()
            for i in range(0, len(level), 2)
        ]
    return level[0]


_SLICE_DEPTH = 6  # 64-leaf salted probe for trees too big to replay fully
_FULL_REPLAY_MAX_DEPTH = 12  # <= 4095 hashlib hashes: cheap to replay whole


def check_merkle_root(words: np.ndarray, depth: int, root: bytes) -> bool:
    """Device tree root vs host. Small trees are replayed whole through
    hashlib. Large trees get a salted-slice probe: 2**6 sampled leaves
    XOR a per-call salt run through the SAME device kernel and recomputed
    on host — an extra (tiny) dispatch whose answer the platform cannot
    have cached, checking the hash engine is actually executing."""
    if depth <= _FULL_REPLAY_MAX_DEPTH:
        ok = host_tree_root_words(words) == root
        record("merkle", ok, None if ok else {"depth": depth, "mode": "full-replay"})
        return ok
    from jax import numpy as jnp

    from eth_consensus_specs_tpu.ops.merkle import _tree_root_fused

    salt = np.uint32(call_salt("merkle"))
    step = max(words.shape[0] // (1 << _SLICE_DEPTH), 1)
    sampled = np.ascontiguousarray(words[::step][: 1 << _SLICE_DEPTH]) ^ salt
    dev = np.asarray(_tree_root_fused(jnp.asarray(sampled), _SLICE_DEPTH))
    ok = _be_words_to_bytes(dev) == host_tree_root_words(sampled)
    record(
        "merkle",
        ok,
        None if ok else {"depth": depth, "mode": "salted-slice", "salt": int(salt)},
    )
    return ok


def _spec_shuffled_index(index: int, n: int, seed: bytes, rounds: int) -> int:
    """The per-index swap-or-not loop, straight off the spec text
    (specs/phase0/beacon-chain.md:816-836) — shares nothing with the
    whole-permutation device kernel it cross-checks."""
    sha = hashlib.sha256
    for r in range(rounds):
        pivot = int.from_bytes(sha(seed + bytes([r])).digest()[:8], "little") % n
        flip = (pivot - index) % n
        pos = max(index, flip)
        src = sha(seed + bytes([r]) + (pos // 256).to_bytes(4, "little")).digest()
        if (src[(pos % 256) // 8] >> (pos % 8)) & 1:
            index = flip
    return index


def check_shuffle_slice(perm, n: int, seed: bytes, rounds: int) -> bool:
    """Sampled lanes of the device permutation vs the per-index spec loop
    (only the sampled lanes cross to the host)."""
    ok = True
    for i in _sample_rows(n, k=2):
        expect = _spec_shuffled_index(i, n, seed, rounds)
        got = int(np.asarray(perm[i]))
        if got != expect:
            ok = False
            record(
                "shuffle",
                False,
                {"lane": i, "expected": expect, "got": got, "n": n},
            )
            break
    if ok:
        record("shuffle", True)
    return ok


def check_bls_item(points, msg: bytes, sig, batch_verdict: bool) -> bool:
    """One sampled (pubkeys, message, aggregate) re-verified through the
    plain host pairing — no device MSM, no routed pairing, no h2g2 cache.
    A True batch verdict must reproduce for every member item."""
    from eth_consensus_specs_tpu.crypto.curve import g1_generator, g1_infinity
    from eth_consensus_specs_tpu.crypto.hash_to_curve import hash_to_g2
    from eth_consensus_specs_tpu.crypto.pairing import pairing_check

    aggpk = g1_infinity()
    for p in points:
        aggpk = aggpk + p
    host_ok = pairing_check(
        [(aggpk, hash_to_g2(bytes(msg))), (-g1_generator(), sig)]
    )
    ok = bool(host_ok) == bool(batch_verdict)
    record(
        "bls_batch",
        ok,
        None if ok else {"batch": batch_verdict, "host": bool(host_ok), "digest": gates.digest(bytes(msg))},
    )
    return ok


def reset_for_tests() -> None:
    with _lock:
        _calls.clear()
