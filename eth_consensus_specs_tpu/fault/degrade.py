"""Graceful degradation: device path -> host oracle.

`degrade(site, device_fn, host_fn)` runs the device path; when it dies
of a DEVICE-side failure (XLA compile/runtime error, OOM, or an injected
fault) it retries once through `retrying` — transient allocator pressure
and nth-shot injections recover here — then falls back to the host
oracle so the run completes slower rather than not at all. Logic errors
(anything that doesn't classify as a device failure) propagate: masking
a real bug behind the oracle would un-couple the two legs the bench
correctness story depends on.
"""

from __future__ import annotations

import re

from eth_consensus_specs_tpu import obs

from .retry import retrying
from .spec import FaultInjected

# substrings of RuntimeError messages that identify device-side death.
# Deliberately NARROW (allocator/compiler failure vocabulary only): a
# marker like "device" would also match shape/transfer logic errors
# ("incompatible shapes when transferring to device") and silently mask
# real kernel bugs behind the host oracle.
_DEVICE_ERROR_MARKERS = (
    "resource_exhausted",
    "resource exhausted",
    "out of memory",
    "failed to compile",
    "compilation failure",
    "failed to allocate",
)
# "oom" needs a word boundary: plain containment would also match
# "room"/"bloom" in unrelated error messages
_OOM_RE = re.compile(r"\boom\b")


def is_device_failure(exc: BaseException) -> bool:
    """True for failures of the accelerator runtime (safe to degrade),
    False for logic errors (must propagate)."""
    if isinstance(exc, (FaultInjected, MemoryError)):
        return True
    if getattr(exc, "degradable", False):
        # an exception type may declare itself environmental damage
        # rather than a logic error (ops/snapshot.py's torn/corrupt
        # checkpoint refusals): degrading to the host path re-derives
        # the state instead of serving a wrong answer
        return True
    msg = str(exc).lower()
    if "xla" in type(exc).__name__.lower():
        # jaxlib.xla_extension.XlaRuntimeError et al. — but XLA also routes
        # argument/shape LOGIC errors through the same type; those must
        # still propagate
        return "invalid_argument" not in msg and "invalid argument" not in msg
    if isinstance(exc, RuntimeError):
        return bool(_OOM_RE.search(msg)) or any(
            marker in msg for marker in _DEVICE_ERROR_MARKERS
        )
    return False


def degrade(site: str, device_fn, host_fn, *, attempts: int = 2):
    """Run ``device_fn()`` with `attempts` tries (retrying on device-side
    failures only), then fall back to ``host_fn()`` with a
    ``fault.degraded`` counter + event breadcrumb."""
    try:
        return retrying(
            device_fn,
            name=site,
            attempts=attempts,
            retry_on=is_device_failure,
            base_delay=0.02,
            max_delay=0.5,
        )
    except BaseException as exc:
        if not is_device_failure(exc):
            raise
        obs.count("fault.degraded", 1)
        obs.count(f"fault.degraded.{site}", 1)
        obs.event("fault.degraded", site=site, error=repr(exc)[:200])
        # black-box the moment of device death: what the process was
        # doing when the accelerator gave out (obs/flight.py; no-op
        # without ETH_SPECS_OBS_POSTMORTEM_DIR)
        obs.flight.trigger_dump(
            "fault.degrade", detail=site, extra={"error": repr(exc)[:500]}
        )
        return host_fn()
