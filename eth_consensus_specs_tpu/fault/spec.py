"""Deterministic fault-injection engine behind ``ETH_SPECS_FAULT``.

Grammar::

    spec  := rule (";" rule)*
    rule  := site ":" mode (":" key "=" value)*
    mode  := raise | kill | stall | corrupt
    keys  := nth    1-based hit index that first fires (default 1)
             times  consecutive hits that fire (default 1; "inf" = every
                    hit from `nth` on)
             delay  stall duration in seconds (default 30)
             latch  file path: the rule fires only while the file can be
                    created O_CREAT|O_EXCL — first process wins, so a
                    fleet of pool workers injects exactly one fault

A `site` is a dotted name the instrumented code passes to `check()`
(``gen.case``, ``state_root.device``, ``serve.dispatch``, and the
replica socket boundary ``frontdoor.rpc`` — there `stall` makes a
replica miss the hedge deadline, `kill` SIGKILLs it mid-batch, and
`corrupt` flips a byte of a framed payload AFTER its digest is
computed, so the receiver must detect, count, and retry it — see
serve/wire.py); a trailing ``*`` makes the rule a prefix match. Rules are parsed once from the environment at
import (`refresh()` re-reads; `install()` sets programmatically;
`injected()` is the scoped test helper). Hit counters are per-process —
forked pool workers inherit the parent's rules and count their own
executions, which is exactly the "SIGKILL a worker on ITS Nth case"
semantics the chaos tests want.

Every fire records ``fault.injected`` (counter + event) through the obs
registry BEFORE acting, so even a self-SIGKILL leaves a breadcrumb in a
configured JSONL sink.
"""

from __future__ import annotations

import os
import signal
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass

from eth_consensus_specs_tpu import obs
from eth_consensus_specs_tpu.analysis import lockwatch

_MODES = ("raise", "kill", "stall", "corrupt")


class FaultInjected(RuntimeError):
    """Raised by a `raise`-mode rule (and treated as a device-side
    failure by fault.degrade)."""

    def __init__(self, site: str, hit: int = 0):
        super().__init__(f"injected fault at {site} (hit {hit})")
        self.site = site
        self.hit = hit


@dataclass
class FaultRule:
    site: str
    mode: str
    nth: int = 1
    times: float = 1
    delay: float = 30.0
    latch: str | None = None
    hits: int = 0

    def matches(self, site: str) -> bool:
        if self.site.endswith("*"):
            return site.startswith(self.site[:-1])
        return site == self.site

    def in_window(self) -> bool:
        return self.nth <= self.hits < self.nth + self.times


_LOCK = lockwatch.wrap(threading.Lock(), "fault.spec._LOCK")
_RULES: list[FaultRule] = []


def _reinit_lock_after_fork_in_child() -> None:
    # hit counters are checked under this lock from any thread (the
    # front-door dispatcher among them); a fork mid-check must not hand
    # the child a lock held by a thread that doesn't exist there
    global _LOCK
    _LOCK = lockwatch.wrap(threading.Lock(), "fault.spec._LOCK")


os.register_at_fork(after_in_child=_reinit_lock_after_fork_in_child)


def parse(spec_str: str) -> list[FaultRule]:
    """Parse a fault spec string into rules (raises ValueError on a
    malformed spec — a typo'd chaos run must not silently run clean)."""
    out: list[FaultRule] = []
    for chunk in spec_str.split(";"):
        chunk = chunk.strip()
        if not chunk:
            continue
        parts = chunk.split(":")
        if len(parts) < 2:
            raise ValueError(f"fault rule needs at least site:mode — got {chunk!r}")
        site, mode = parts[0].strip(), parts[1].strip()
        if not site:
            raise ValueError(f"empty site in fault rule {chunk!r}")
        if mode not in _MODES:
            raise ValueError(f"unknown fault mode {mode!r} in {chunk!r} (want {_MODES})")
        rule = FaultRule(site=site, mode=mode)
        for kv in parts[2:]:
            key, sep, value = kv.partition("=")
            key, value = key.strip(), value.strip()
            if not sep:
                raise ValueError(f"fault key {kv!r} in {chunk!r} is not key=value")
            if key == "nth":
                rule.nth = int(value)
            elif key == "times":
                rule.times = float("inf") if value in ("inf", "forever") else int(value)
            elif key == "delay":
                rule.delay = float(value)
            elif key == "latch":
                rule.latch = value
            else:
                raise ValueError(f"unknown fault key {key!r} in {chunk!r}")
        out.append(rule)
    return out


def install(spec_str: str | None) -> list[FaultRule]:
    """Install rules programmatically (None/empty clears). Resets hit
    counters — an install is the start of a new deterministic scenario."""
    global _RULES
    with _LOCK:
        _RULES = parse(spec_str) if spec_str else []
        return list(_RULES)


def refresh() -> list[FaultRule]:
    """(Re-)read ``ETH_SPECS_FAULT`` from the environment."""
    return install(os.environ.get("ETH_SPECS_FAULT") or None)


refresh()


def active() -> bool:
    return bool(_RULES)


def rules() -> list[FaultRule]:
    return list(_RULES)


@contextmanager
def injected(spec_str: str):
    """Scoped install for tests; restores the env-derived rules on exit."""
    install(spec_str)
    try:
        yield
    finally:
        refresh()


def _acquire_latch(path: str) -> bool:
    try:
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
    except OSError:
        return False
    os.close(fd)
    return True


def _count_hit(rule: FaultRule) -> bool:
    """Bump the rule's hit counter and decide whether this hit fires
    (window + latch)."""
    with _LOCK:
        rule.hits += 1
        if not rule.in_window():
            return False
    # latch probe outside the lock: O_EXCL is itself the atomic arbiter
    if rule.latch is not None and not _acquire_latch(rule.latch):
        return False
    return True


def check(site: str, tag: str | None = None) -> None:
    """Injection point for raise/kill/stall rules. A no-op (one list
    check) when no rules are installed, so hot paths can call it
    unconditionally."""
    if not _RULES:
        return
    for rule in _RULES:
        if rule.mode == "corrupt" or not rule.matches(site):
            continue
        if not _count_hit(rule):
            continue
        # breadcrumb FIRST: a kill-mode fire must still reach the JSONL sink
        obs.count("fault.injected", 1)
        obs.event("fault.injected", site=site, mode=rule.mode, hit=rule.hits, tag=tag or "")
        if rule.mode == "raise":
            raise FaultInjected(site, rule.hits)
        if rule.mode == "stall":
            time.sleep(rule.delay)
        elif rule.mode == "kill":
            os.kill(os.getpid(), signal.SIGKILL)


def corrupt(site: str, data: bytes) -> bytes:
    """Injection point for corrupt-mode rules: returns `data` with one
    byte flipped when a matching rule fires, `data` unchanged otherwise."""
    if not _RULES:
        return data
    for rule in _RULES:
        if rule.mode != "corrupt" or not rule.matches(site):
            continue
        if not _count_hit(rule):
            continue
        obs.count("fault.injected", 1)
        obs.event("fault.injected", site=site, mode="corrupt", hit=rule.hits, nbytes=len(data))
        if not data:
            return b"\xff"
        i = len(data) // 2
        return data[:i] + bytes([data[i] ^ 0xFF]) + data[i + 1 :]
    return data
