"""fault — deterministic fault injection, retry, and graceful degradation.

The generation pipeline and kernel runtime are only trustworthy under
failure if failure is REHEARSABLE: a worker OOM-kill, a hung device
compile, or a mid-write SIGKILL must be reproducible in a test, and
every recovery action must leave an observable trace (PR-1 obs
registry). This package provides the three legs:

  * **injection** (`fault.check(site)` / `fault.corrupt(site, data)`) —
    an env/config-driven harness (``ETH_SPECS_FAULT=<spec>``, grammar in
    fault/spec.py and docs/robustness.md) that can raise at a named
    site, SIGKILL the current process on the Nth hit, stall a case past
    its deadline, or flip a byte of serialized output. Deterministic:
    per-rule hit counters, no RNG; an optional ``latch=<path>`` key
    coordinates "exactly once across processes" through an O_EXCL file.
  * **retry** (`fault.retrying(fn, ...)`) — capped exponential backoff
    with deterministic jitter, the single helper every recovery path in
    the repo goes through (pool re-dispatch, dumper write-verify,
    manifest append, worker respawn, degrade's device re-try).
  * **degradation** (`fault.degrade(site, device_fn, host_fn)`) — run
    the device path; on a device-side failure (compile, OOM, injected)
    retry once, then fall back to the host oracle with a
    ``fault.degraded`` counter + event, so a run completes slower
    rather than not at all.

Counters: ``fault.injected``, ``fault.retries``, ``fault.degraded`` (+
``fault.degraded.<site>``). Events: ``fault.injected``, ``fault.retry``,
``fault.degraded``.
"""

from .degrade import degrade, is_device_failure  # noqa: F401
from .retry import backoff_delays, retrying  # noqa: F401
from .spec import (  # noqa: F401
    FaultInjected,
    FaultRule,
    active,
    check,
    corrupt,
    injected,
    install,
    parse,
    refresh,
    rules,
)
