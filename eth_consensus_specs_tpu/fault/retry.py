"""Capped exponential backoff with deterministic jitter.

One retry helper for every recovery path (pool re-dispatch, dumper
write-verify, manifest append, worker respawn, degrade's device
re-try). Jitter is derived from a hash of ``(name, attempt)`` instead of
an RNG: two retriers with different names de-sync (no thundering herd),
and the same name replays the exact same schedule — the property the
deterministic fault harness needs to keep chaos tests reproducible.
"""

from __future__ import annotations

import hashlib
import time
from typing import Callable

from eth_consensus_specs_tpu import obs


def backoff_delays(
    name: str,
    attempts: int,
    base_delay: float = 0.05,
    max_delay: float = 2.0,
    jitter: float = 0.5,
) -> list[float]:
    """The full sleep schedule between `attempts` tries: base * 2**i
    capped at `max_delay`, stretched by up to ``jitter`` of itself by the
    hash-derived fraction."""
    out = []
    for i in range(max(attempts - 1, 0)):
        frac = int.from_bytes(hashlib.sha256(f"{name}:{i}".encode()).digest()[:4], "big") / 2**32
        out.append(min(base_delay * (2**i), max_delay) * (1.0 + jitter * frac))
    return out


def retrying(
    fn: Callable,
    *,
    name: str = "retry",
    attempts: int = 3,
    retry_on=(Exception,),
    base_delay: float = 0.05,
    max_delay: float = 2.0,
    jitter: float = 0.5,
    sleep: Callable[[float], None] = time.sleep,
    on_retry: Callable | None = None,
):
    """Call ``fn()`` up to `attempts` times, sleeping the backoff_delays
    schedule between failures; re-raises the last error when the budget
    is exhausted. ``retry_on`` is a tuple of exception types or a
    predicate ``exc -> bool`` (non-matching errors propagate
    immediately). Each retry records ``fault.retries`` + a
    ``fault.retry`` event."""
    if attempts < 1:
        raise ValueError(f"retrying needs attempts >= 1, got {attempts}")
    if isinstance(retry_on, type):
        retry_on = (retry_on,)
    predicate = retry_on if not isinstance(retry_on, tuple) else None
    delays = backoff_delays(name, attempts, base_delay, max_delay, jitter)
    for i in range(attempts):
        try:
            return fn()
        except BaseException as exc:
            retriable = predicate(exc) if predicate is not None else isinstance(exc, retry_on)
            if not retriable or i + 1 >= attempts:
                raise
            obs.count("fault.retries", 1)
            obs.event(
                "fault.retry",
                name=name,
                attempt=i + 1,
                error=type(exc).__name__,
                delay_s=round(delays[i], 4),
            )
            if on_retry is not None:
                on_retry(exc, i + 1)
            sleep(delays[i])
