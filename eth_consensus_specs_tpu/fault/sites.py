"""fault/sites — the registry of every fault-injection site.

A fault site is a contract: "this seam can fail, and something proves
the system survives it." Before this registry the site strings lived
only at their ``fault.check(...)`` call sites and in the docs failure
matrix, with nothing keeping the three views consistent. Now:

  * every literal passed to ``fault.check`` / ``fault.corrupt`` (and
    every ``site=`` keyword at the wire layer) must be declared here —
    the ``fault-site-registry`` speclint rule fails on undeclared
    sites;
  * every declared site must be *referenced* by a chaos test or the
    docs failure matrix (the rule's project-level completeness check) —
    an injection point nothing exercises is a dead invariant;
  * docs/robustness.md's instrumented-sites list links here.

``exercised_by`` is the human pointer to the chaos coverage; the lint
rule independently verifies the site string appears under tests/ or
docs/.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class FaultSite:
    name: str
    description: str
    modes: tuple[str, ...]  # modes that are meaningful at this seam
    exercised_by: str  # chaos test / docs failure-matrix pointer


_S = FaultSite

SITES: dict[str, FaultSite] = {
    s.name: s
    for s in (
        _S(
            "gen.case",
            "before each generation case executes on a pool worker",
            ("raise", "kill", "stall"),
            "tests/test_gen_faults.py, scripts/chaos_smoke.py",
        ),
        _S(
            "gen.dump_bytes",
            "the compressed frame of each .ssz_snappy write (read-back "
            "verification must catch the flip)",
            ("corrupt",),
            "tests/test_gen_faults.py",
        ),
        _S(
            "state_root.device",
            "the device state-root kernel's eager entry point (raise "
            "triggers bit-exact host degradation)",
            ("raise", "stall"),
            "tests/test_fault.py",
        ),
        _S(
            "block_epoch.device",
            "the device block/epoch chain kernel's eager entry point",
            ("raise", "stall"),
            "tests/test_fault.py",
        ),
        _S(
            "serve.dispatch",
            "the verification service's batched device dispatch (raise "
            "degrades the whole in-flight batch to host oracles)",
            ("raise", "stall"),
            "tests/test_serve.py",
        ),
        _S(
            "frontdoor.rpc",
            "the replica socket boundary: stall misses the hedge deadline, "
            "kill SIGKILLs the replica mid-batch, corrupt flips a framed "
            "payload byte after its digest (must be detected, never accepted)",
            ("raise", "kill", "stall", "corrupt"),
            "tests/test_frontdoor.py, scripts/serve_bench.py --chaos",
        ),
        _S(
            "frontdoor.rpc.admin",
            "replica admin replies (health/drain/shutdown) — a separate "
            "site so chaos on the request path cannot corrupt supervision",
            ("corrupt",),
            "docs/robustness.md failure matrix",
        ),
        _S(
            "resident.checkpoint",
            "the durable checkpoint write path: corrupt flips a blob byte "
            "between serialize and fsync (read-back verify must refuse the "
            "torn write), kill dies mid-commit (the previous LATEST must "
            "survive intact)",
            ("raise", "kill", "stall", "corrupt"),
            "tests/test_snapshot.py, scripts/recovery_smoke.py",
        ),
        _S(
            "resident.restore",
            "the digest-verified restore at replica boot: corrupt damages "
            "a blob in flight (restore must REFUSE and degrade to full "
            "host re-ingest, never serve a wrong root)",
            ("raise", "stall", "corrupt"),
            "tests/test_snapshot.py",
        ),
        _S(
            "slot.verify",
            "the slot pipeline's device verification leg (BLS + KZG), "
            "BEFORE any state mutation: raise degrades the WHOLE slot to "
            "the sequential host fold, bit-identically — never a "
            "half-applied slot",
            ("raise", "stall"),
            "tests/test_slot.py, scripts/slot_bench.py --chaos",
        ),
        _S(
            "slot.reroot",
            "the donated apply-and-re-root dispatch, after verdicts but "
            "before the forest is consumed: raise retries once on device "
            "then falls back to the host fold from the committed pre-slot "
            "columns (the donation-consumed flag forces a forest rebuild)",
            ("raise", "stall"),
            "tests/test_slot.py, scripts/slot_bench.py --chaos",
        ),
        _S(
            "resident.scrub",
            "the salted-subtree integrity scrub: corrupt flips the observed "
            "root so the expect-root cross-check fires (mismatch counters + "
            "postmortem + quarantine-and-rebuild)",
            ("raise", "corrupt"),
            "tests/test_snapshot.py",
        ),
    )
}


def declared(name: str) -> bool:
    return name in SITES


def names() -> set[str]:
    return set(SITES)
