/* Native runtime: SHA-256 compression + incremental-Merkle deposit tree.
 *
 * The reference's one production artifact is the Solidity incremental
 * Merkle deposit contract (solidity_deposit_contract/deposit_contract.sol);
 * its native-crypto runtime (milagro/hashlib C cores) sits behind Python
 * bindings. This file is the equivalent native layer here: a standalone
 * SHA-256 with batch pair hashing (host-side merkleization fallback) and
 * the branch/zero-hash incremental insert + root algorithms
 * (deposit_contract.sol:69-96), loaded through ctypes (no pybind11).
 */

#include <stdint.h>
#include <string.h>

#define ROTR(x, n) (((x) >> (n)) | ((x) << (32 - (n))))

static const uint32_t K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

static const uint32_t H0[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                               0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};

static void compress(uint32_t state[8], const uint8_t block[64]) {
    uint32_t w[64];
    for (int i = 0; i < 16; i++)
        w[i] = ((uint32_t)block[4 * i] << 24) | ((uint32_t)block[4 * i + 1] << 16) |
               ((uint32_t)block[4 * i + 2] << 8) | (uint32_t)block[4 * i + 3];
    for (int i = 16; i < 64; i++) {
        uint32_t s0 = ROTR(w[i - 15], 7) ^ ROTR(w[i - 15], 18) ^ (w[i - 15] >> 3);
        uint32_t s1 = ROTR(w[i - 2], 17) ^ ROTR(w[i - 2], 19) ^ (w[i - 2] >> 10);
        w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = state[0], b = state[1], c = state[2], d = state[3];
    uint32_t e = state[4], f = state[5], g = state[6], h = state[7];
    for (int i = 0; i < 64; i++) {
        uint32_t S1 = ROTR(e, 6) ^ ROTR(e, 11) ^ ROTR(e, 25);
        uint32_t ch = (e & f) ^ (~e & g);
        uint32_t t1 = h + S1 + ch + K[i] + w[i];
        uint32_t S0 = ROTR(a, 2) ^ ROTR(a, 13) ^ ROTR(a, 22);
        uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
        uint32_t t2 = S0 + maj;
        h = g; g = f; f = e; e = d + t1;
        d = c; c = b; b = a; a = t1 + t2;
    }
    state[0] += a; state[1] += b; state[2] += c; state[3] += d;
    state[4] += e; state[5] += f; state[6] += g; state[7] += h;
}

/* SHA-256 of exactly 64 bytes (one Merkle pair): the padding block is
 * constant, so hash = compress(compress(H0, msg), PAD64). */
void sha256_pair(const uint8_t *in64, uint8_t *out32) {
    uint32_t st[8];
    memcpy(st, H0, sizeof st);
    compress(st, in64);
    uint8_t pad[64] = {0};
    pad[0] = 0x80;
    pad[62] = 0x02; /* bit length 512 = 0x0200, big-endian in last 8 bytes */
    compress(st, pad);
    for (int i = 0; i < 8; i++) {
        out32[4 * i] = (uint8_t)(st[i] >> 24);
        out32[4 * i + 1] = (uint8_t)(st[i] >> 16);
        out32[4 * i + 2] = (uint8_t)(st[i] >> 8);
        out32[4 * i + 3] = (uint8_t)st[i];
    }
}

/* n independent 64-byte messages -> n 32-byte digests. */
void sha256_pairs(const uint8_t *in, uint8_t *out, uint64_t n) {
    for (uint64_t i = 0; i < n; i++)
        sha256_pair(in + 64 * i, out + 32 * i);
}

/* One level of a Merkle tree: 2n chunks in, n parents out (in-place safe
 * when out == in). */
void merkle_level(const uint8_t *chunks, uint8_t *out, uint64_t n_pairs) {
    for (uint64_t i = 0; i < n_pairs; i++)
        sha256_pair(chunks + 64 * i, out + 32 * i);
}

/* Incremental deposit-tree insert (deposit_contract.sol:101-140): update
 * `branch` (depth x 32 bytes) in place for leaf number `index` (0-based
 * BEFORE increment, i.e. deposit_count prior to this deposit). */
void deposit_tree_insert(uint8_t *branch, uint64_t index, const uint8_t *leaf,
                         uint32_t depth) {
    uint8_t node[32];
    uint8_t buf[64];
    memcpy(node, leaf, 32);
    uint64_t size = index + 1;
    for (uint32_t h = 0; h < depth; h++) {
        if (size & 1) {
            memcpy(branch + 32 * h, node, 32);
            return;
        }
        memcpy(buf, branch + 32 * h, 32);
        memcpy(buf + 32, node, 32);
        sha256_pair(buf, node);
        size >>= 1;
    }
}

/* Deposit root with length mix-in (deposit_contract.sol:80-96). The
 * zero-hash table (zh[h] = H(zh[h-1] || zh[h-1]), zh[0] = 0) is passed in
 * so callers control it. */
void deposit_tree_root(const uint8_t *branch, const uint8_t *zerohashes,
                       uint64_t deposit_count, uint32_t depth, uint8_t *out32) {
    uint8_t node[32] = {0};
    uint8_t buf[64];
    uint64_t size = deposit_count;
    for (uint32_t h = 0; h < depth; h++) {
        if (size & 1) {
            memcpy(buf, branch + 32 * h, 32);
            memcpy(buf + 32, node, 32);
        } else {
            memcpy(buf, node, 32);
            memcpy(buf + 32, zerohashes + 32 * h, 32);
        }
        sha256_pair(buf, node);
        size >>= 1;
    }
    /* mix in the count: H(root || uint64-LE count padded to 32 bytes) */
    memcpy(buf, node, 32);
    memset(buf + 32, 0, 32);
    for (int i = 0; i < 8; i++)
        buf[32 + i] = (uint8_t)(deposit_count >> (8 * i));
    sha256_pair(buf, out32);
}
