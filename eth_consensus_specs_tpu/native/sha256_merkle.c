/* Native runtime: SHA-256 compression + incremental-Merkle deposit tree.
 *
 * The reference's one production artifact is the Solidity incremental
 * Merkle deposit contract (solidity_deposit_contract/deposit_contract.sol);
 * its native-crypto runtime (milagro/hashlib C cores) sits behind Python
 * bindings. This file is the equivalent native layer here: a standalone
 * SHA-256 with batch pair hashing (host-side merkleization fallback) and
 * the branch/zero-hash incremental insert + root algorithms
 * (deposit_contract.sol:69-96), loaded through ctypes (no pybind11).
 */

#include <stdint.h>
#include <string.h>

#if defined(__x86_64__)
#include <immintrin.h>
#define HAVE_SHA_NI_PATH 1
#endif

#define ROTR(x, n) (((x) >> (n)) | ((x) << (32 - (n))))

static const uint32_t K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

static const uint32_t H0[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                               0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};

static void compress(uint32_t state[8], const uint8_t block[64]) {
    uint32_t w[64];
    for (int i = 0; i < 16; i++)
        w[i] = ((uint32_t)block[4 * i] << 24) | ((uint32_t)block[4 * i + 1] << 16) |
               ((uint32_t)block[4 * i + 2] << 8) | (uint32_t)block[4 * i + 3];
    for (int i = 16; i < 64; i++) {
        uint32_t s0 = ROTR(w[i - 15], 7) ^ ROTR(w[i - 15], 18) ^ (w[i - 15] >> 3);
        uint32_t s1 = ROTR(w[i - 2], 17) ^ ROTR(w[i - 2], 19) ^ (w[i - 2] >> 10);
        w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = state[0], b = state[1], c = state[2], d = state[3];
    uint32_t e = state[4], f = state[5], g = state[6], h = state[7];
    for (int i = 0; i < 64; i++) {
        uint32_t S1 = ROTR(e, 6) ^ ROTR(e, 11) ^ ROTR(e, 25);
        uint32_t ch = (e & f) ^ (~e & g);
        uint32_t t1 = h + S1 + ch + K[i] + w[i];
        uint32_t S0 = ROTR(a, 2) ^ ROTR(a, 13) ^ ROTR(a, 22);
        uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
        uint32_t t2 = S0 + maj;
        h = g; g = f; f = e; e = d + t1;
        d = c; c = b; b = a; a = t1 + t2;
    }
    state[0] += a; state[1] += b; state[2] += c; state[3] += d;
    state[4] += e; state[5] += f; state[6] += g; state[7] += h;
}

static void sha256_pair_scalar(const uint8_t *in64, uint8_t *out32) {
    uint32_t st[8];
    memcpy(st, H0, sizeof st);
    compress(st, in64);
    uint8_t pad[64] = {0};
    pad[0] = 0x80;
    pad[62] = 0x02; /* bit length 512 = 0x0200, big-endian in last 8 bytes */
    compress(st, pad);
    for (int i = 0; i < 8; i++) {
        out32[4 * i] = (uint8_t)(st[i] >> 24);
        out32[4 * i + 1] = (uint8_t)(st[i] >> 16);
        out32[4 * i + 2] = (uint8_t)(st[i] >> 8);
        out32[4 * i + 3] = (uint8_t)st[i];
    }
}

#ifdef HAVE_SHA_NI_PATH
/* SHA-NI fast path.  The 64-byte-message padding block is a CONSTANT, so
 * its 64-round message schedule (plus the K constants) collapses to a
 * precomputed W+K table: the second compression runs 32 sha256rnds2 with
 * no msg1/msg2 schedule work at all. */

static uint32_t WK_PAD[64]; /* w[i] + K[i] for the constant pad block */
static int wk_pad_ready = 0;

static void init_wk_pad(void) {
    uint8_t pad[64] = {0};
    pad[0] = 0x80;
    pad[62] = 0x02;
    uint32_t w[64];
    for (int i = 0; i < 16; i++)
        w[i] = ((uint32_t)pad[4 * i] << 24) | ((uint32_t)pad[4 * i + 1] << 16) |
               ((uint32_t)pad[4 * i + 2] << 8) | (uint32_t)pad[4 * i + 3];
    for (int i = 16; i < 64; i++) {
        uint32_t s0 = ROTR(w[i - 15], 7) ^ ROTR(w[i - 15], 18) ^ (w[i - 15] >> 3);
        uint32_t s1 = ROTR(w[i - 2], 17) ^ ROTR(w[i - 2], 19) ^ (w[i - 2] >> 10);
        w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    for (int i = 0; i < 64; i++) WK_PAD[i] = w[i] + K[i];
    wk_pad_ready = 1;
}

/* Two sha256 rounds x2 halves for one 4-round group with schedule values
 * already K-added in `wk`; the canonical ABEF/CDGH register split. */
#define RNDS4(S0, S1, WKV)                                   \
    do {                                                     \
        __m128i _wk = (WKV);                                 \
        (S1) = _mm_sha256rnds2_epu32((S1), (S0), _wk);       \
        _wk = _mm_shuffle_epi32(_wk, 0x0E);                  \
        (S0) = _mm_sha256rnds2_epu32((S0), (S1), _wk);       \
    } while (0)

__attribute__((target("sha,ssse3,sse4.1"))) static void
sha_ni_pair(const uint8_t *in64, uint8_t *out32) {
    const __m128i MASK =
        _mm_set_epi64x(0x0c0d0e0f08090a0bULL, 0x0405060700010203ULL);
    /* pack H0 into ABEF / CDGH */
    __m128i abcd = _mm_loadu_si128((const __m128i *)&H0[0]);
    __m128i efgh = _mm_loadu_si128((const __m128i *)&H0[4]);
    __m128i tmp = _mm_shuffle_epi32(abcd, 0xB1); /* CDAB */
    efgh = _mm_shuffle_epi32(efgh, 0x1B);        /* HGFE -> EFGH rev */
    __m128i st0 = _mm_alignr_epi8(tmp, efgh, 8); /* ABEF */
    __m128i st1 = _mm_blend_epi16(efgh, tmp, 0xF0); /* CDGH */
    const __m128i abef_h0 = st0, cdgh_h0 = st1;

    /* compression 1: the message block, rolling 4-word schedule */
    __m128i msgs[4];
    for (int i = 0; i < 4; i++)
        msgs[i] = _mm_shuffle_epi8(
            _mm_loadu_si128((const __m128i *)(in64 + 16 * i)), MASK);
    for (int g = 0; g < 16; g++) {
        __m128i wk = _mm_add_epi32(msgs[g & 3],
                                   _mm_loadu_si128((const __m128i *)&K[4 * g]));
        RNDS4(st0, st1, wk);
        if (g < 12) {
            /* msgs[g&3] <- W[4g+16 .. 4g+19] */
            __m128i x = _mm_sha256msg1_epu32(msgs[g & 3], msgs[(g + 1) & 3]);
            x = _mm_add_epi32(
                x, _mm_alignr_epi8(msgs[(g + 3) & 3], msgs[(g + 2) & 3], 4));
            msgs[g & 3] = _mm_sha256msg2_epu32(x, msgs[(g + 3) & 3]);
        }
    }
    st0 = _mm_add_epi32(st0, abef_h0);
    st1 = _mm_add_epi32(st1, cdgh_h0);

    /* compression 2: constant pad block, precomputed W+K */
    const __m128i abef_s = st0, cdgh_s = st1;
    for (int g = 0; g < 16; g++)
        RNDS4(st0, st1, _mm_loadu_si128((const __m128i *)&WK_PAD[4 * g]));
    st0 = _mm_add_epi32(st0, abef_s);
    st1 = _mm_add_epi32(st1, cdgh_s);

    /* unpack ABEF/CDGH -> big-endian digest bytes */
    tmp = _mm_shuffle_epi32(st0, 0x1B);            /* FEBA */
    st1 = _mm_shuffle_epi32(st1, 0xB1);            /* DCHG */
    __m128i dcba = _mm_blend_epi16(tmp, st1, 0xF0); /* ABCD (le lanes) */
    __m128i hgfe = _mm_alignr_epi8(st1, tmp, 8);    /* EFGH (le lanes) */
    _mm_storeu_si128((__m128i *)out32, _mm_shuffle_epi8(dcba, MASK));
    _mm_storeu_si128((__m128i *)(out32 + 16), _mm_shuffle_epi8(hgfe, MASK));
}

/* Two independent messages interleaved to hide sha256rnds2 latency (the
 * two dependency chains share no registers). */
__attribute__((target("sha,ssse3,sse4.1"))) static void
sha_ni_pair2(const uint8_t *a64, const uint8_t *b64, uint8_t *aout,
             uint8_t *bout) {
    const __m128i MASK =
        _mm_set_epi64x(0x0c0d0e0f08090a0bULL, 0x0405060700010203ULL);
    __m128i abcd = _mm_loadu_si128((const __m128i *)&H0[0]);
    __m128i efgh = _mm_loadu_si128((const __m128i *)&H0[4]);
    __m128i tmp = _mm_shuffle_epi32(abcd, 0xB1);
    efgh = _mm_shuffle_epi32(efgh, 0x1B);
    const __m128i abef_h0 = _mm_alignr_epi8(tmp, efgh, 8);
    const __m128i cdgh_h0 = _mm_blend_epi16(efgh, tmp, 0xF0);

    __m128i a0 = abef_h0, a1 = cdgh_h0, b0 = abef_h0, b1 = cdgh_h0;
    __m128i ma[4], mb[4];
    for (int i = 0; i < 4; i++) {
        ma[i] = _mm_shuffle_epi8(
            _mm_loadu_si128((const __m128i *)(a64 + 16 * i)), MASK);
        mb[i] = _mm_shuffle_epi8(
            _mm_loadu_si128((const __m128i *)(b64 + 16 * i)), MASK);
    }
    for (int g = 0; g < 16; g++) {
        __m128i kv = _mm_loadu_si128((const __m128i *)&K[4 * g]);
        __m128i wka = _mm_add_epi32(ma[g & 3], kv);
        __m128i wkb = _mm_add_epi32(mb[g & 3], kv);
        a1 = _mm_sha256rnds2_epu32(a1, a0, wka);
        b1 = _mm_sha256rnds2_epu32(b1, b0, wkb);
        wka = _mm_shuffle_epi32(wka, 0x0E);
        wkb = _mm_shuffle_epi32(wkb, 0x0E);
        a0 = _mm_sha256rnds2_epu32(a0, a1, wka);
        b0 = _mm_sha256rnds2_epu32(b0, b1, wkb);
        if (g < 12) {
            __m128i xa = _mm_sha256msg1_epu32(ma[g & 3], ma[(g + 1) & 3]);
            __m128i xb = _mm_sha256msg1_epu32(mb[g & 3], mb[(g + 1) & 3]);
            xa = _mm_add_epi32(
                xa, _mm_alignr_epi8(ma[(g + 3) & 3], ma[(g + 2) & 3], 4));
            xb = _mm_add_epi32(
                xb, _mm_alignr_epi8(mb[(g + 3) & 3], mb[(g + 2) & 3], 4));
            ma[g & 3] = _mm_sha256msg2_epu32(xa, ma[(g + 3) & 3]);
            mb[g & 3] = _mm_sha256msg2_epu32(xb, mb[(g + 3) & 3]);
        }
    }
    a0 = _mm_add_epi32(a0, abef_h0);
    a1 = _mm_add_epi32(a1, cdgh_h0);
    b0 = _mm_add_epi32(b0, abef_h0);
    b1 = _mm_add_epi32(b1, cdgh_h0);

    const __m128i as0 = a0, as1 = a1, bs0 = b0, bs1 = b1;
    for (int g = 0; g < 16; g++) {
        __m128i wk = _mm_loadu_si128((const __m128i *)&WK_PAD[4 * g]);
        a1 = _mm_sha256rnds2_epu32(a1, a0, wk);
        b1 = _mm_sha256rnds2_epu32(b1, b0, wk);
        wk = _mm_shuffle_epi32(wk, 0x0E);
        a0 = _mm_sha256rnds2_epu32(a0, a1, wk);
        b0 = _mm_sha256rnds2_epu32(b0, b1, wk);
    }
    a0 = _mm_add_epi32(a0, as0);
    a1 = _mm_add_epi32(a1, as1);
    b0 = _mm_add_epi32(b0, bs0);
    b1 = _mm_add_epi32(b1, bs1);

    tmp = _mm_shuffle_epi32(a0, 0x1B);
    a1 = _mm_shuffle_epi32(a1, 0xB1);
    _mm_storeu_si128((__m128i *)aout,
                     _mm_shuffle_epi8(_mm_blend_epi16(tmp, a1, 0xF0), MASK));
    _mm_storeu_si128((__m128i *)(aout + 16),
                     _mm_shuffle_epi8(_mm_alignr_epi8(a1, tmp, 8), MASK));
    tmp = _mm_shuffle_epi32(b0, 0x1B);
    b1 = _mm_shuffle_epi32(b1, 0xB1);
    _mm_storeu_si128((__m128i *)bout,
                     _mm_shuffle_epi8(_mm_blend_epi16(tmp, b1, 0xF0), MASK));
    _mm_storeu_si128((__m128i *)(bout + 16),
                     _mm_shuffle_epi8(_mm_alignr_epi8(b1, tmp, 8), MASK));
}

static int have_sha_ni(void) {
    /* v is published only AFTER WK_PAD is fully initialized (ctypes
     * releases the GIL, so first use can race): a second thread either
     * sees v < 0 and redoes the idempotent init, or sees v >= 0 with the
     * table already filled (x86-TSO orders the table stores first). */
    static volatile int v = -1;
    if (v < 0) {
        int have = __builtin_cpu_supports("sha") ? 1 : 0;
        if (have && !wk_pad_ready) init_wk_pad();
        v = have;
    }
    return v;
}
#else
static int have_sha_ni(void) { return 0; }
#endif

/* SHA-256 of exactly 64 bytes (one Merkle pair): the padding block is
 * constant, so hash = compress(compress(H0, msg), PAD64). */
void sha256_pair(const uint8_t *in64, uint8_t *out32) {
#ifdef HAVE_SHA_NI_PATH
    if (have_sha_ni()) {
        sha_ni_pair(in64, out32);
        return;
    }
#endif
    sha256_pair_scalar(in64, out32);
}

/* n independent 64-byte messages -> n 32-byte digests. */
void sha256_pairs(const uint8_t *in, uint8_t *out, uint64_t n) {
#ifdef HAVE_SHA_NI_PATH
    if (have_sha_ni()) {
        uint64_t i = 0;
        for (; i + 2 <= n; i += 2)
            sha_ni_pair2(in + 64 * i, in + 64 * (i + 1), out + 32 * i,
                         out + 32 * (i + 1));
        if (i < n) sha_ni_pair(in + 64 * i, out + 32 * i);
        return;
    }
#endif
    for (uint64_t i = 0; i < n; i++)
        sha256_pair_scalar(in + 64 * i, out + 32 * i);
}

/* One level of a Merkle tree: 2n chunks in, n parents out (in-place safe
 * when out == in). */
void merkle_level(const uint8_t *chunks, uint8_t *out, uint64_t n_pairs) {
    for (uint64_t i = 0; i < n_pairs; i++)
        sha256_pair(chunks + 64 * i, out + 32 * i);
}

/* Incremental deposit-tree insert (deposit_contract.sol:101-140): update
 * `branch` (depth x 32 bytes) in place for leaf number `index` (0-based
 * BEFORE increment, i.e. deposit_count prior to this deposit). */
void deposit_tree_insert(uint8_t *branch, uint64_t index, const uint8_t *leaf,
                         uint32_t depth) {
    uint8_t node[32];
    uint8_t buf[64];
    memcpy(node, leaf, 32);
    uint64_t size = index + 1;
    for (uint32_t h = 0; h < depth; h++) {
        if (size & 1) {
            memcpy(branch + 32 * h, node, 32);
            return;
        }
        memcpy(buf, branch + 32 * h, 32);
        memcpy(buf + 32, node, 32);
        sha256_pair(buf, node);
        size >>= 1;
    }
}

/* Deposit root with length mix-in (deposit_contract.sol:80-96). The
 * zero-hash table (zh[h] = H(zh[h-1] || zh[h-1]), zh[0] = 0) is passed in
 * so callers control it. */
void deposit_tree_root(const uint8_t *branch, const uint8_t *zerohashes,
                       uint64_t deposit_count, uint32_t depth, uint8_t *out32) {
    uint8_t node[32] = {0};
    uint8_t buf[64];
    uint64_t size = deposit_count;
    for (uint32_t h = 0; h < depth; h++) {
        if (size & 1) {
            memcpy(buf, branch + 32 * h, 32);
            memcpy(buf + 32, node, 32);
        } else {
            memcpy(buf, node, 32);
            memcpy(buf + 32, zerohashes + 32 * h, 32);
        }
        sha256_pair(buf, node);
        size >>= 1;
    }
    /* mix in the count: H(root || uint64-LE count padded to 32 bytes) */
    memcpy(buf, node, 32);
    memset(buf + 32, 0, 32);
    for (int i = 0; i < 8; i++)
        buf[32 + i] = (uint8_t)(deposit_count >> (8 * i));
    sha256_pair(buf, out32);
}
