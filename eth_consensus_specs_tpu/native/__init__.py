"""ctypes loader for the native runtime (sha256_merkle.c).

Compiles the shared object on first use with the system C compiler into
the package directory (a one-time ~1s cost), mirroring how the reference
leans on prebuilt C cores (hashlib/milagro) behind Python bindings. Set
``ETH_SPECS_TPU_NO_NATIVE=1`` to force the pure-Python fallbacks; all
callers degrade gracefully when no compiler is available."""

from __future__ import annotations

import ctypes
import os
import subprocess
import sysconfig

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "sha256_merkle.c")
_LIB = os.path.join(_DIR, "_sha256_merkle.so")

_lib: ctypes.CDLL | None = None
_tried = False


def _compile() -> bool:
    cc = os.environ.get("CC") or sysconfig.get_config_var("CC") or "cc"
    # link to a per-process temp name, then atomically rename: concurrent
    # first-use compilations (pytest-xdist, parallel imports) must never
    # let a reader dlopen a partially written object
    tmp = f"{_LIB}.{os.getpid()}.tmp"
    cmd = cc.split() + ["-O2", "-fPIC", "-shared", "-o", tmp, _SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _LIB)
        return True
    except (OSError, subprocess.SubprocessError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def get_lib() -> ctypes.CDLL | None:
    """The loaded native library, or None when unavailable/disabled."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if os.environ.get("ETH_SPECS_TPU_NO_NATIVE"):
        return None
    if not os.path.exists(_LIB) or os.path.getmtime(_LIB) < os.path.getmtime(_SRC):
        if not _compile():
            return None
    try:
        lib = ctypes.CDLL(_LIB)
    except OSError:
        return None
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.sha256_pair.argtypes = [u8p, u8p]
    lib.sha256_pairs.argtypes = [u8p, u8p, ctypes.c_uint64]
    lib.merkle_level.argtypes = [u8p, u8p, ctypes.c_uint64]
    lib.deposit_tree_insert.argtypes = [u8p, ctypes.c_uint64, u8p, ctypes.c_uint32]
    lib.deposit_tree_root.argtypes = [u8p, u8p, ctypes.c_uint64, ctypes.c_uint32, u8p]
    _lib = lib
    return _lib


def available() -> bool:
    return get_lib() is not None


def _buf(data: bytes):
    return (ctypes.c_uint8 * len(data)).from_buffer_copy(data)


def sha256_pair(data64: bytes) -> bytes:
    lib = get_lib()
    assert lib is not None and len(data64) == 64
    out = (ctypes.c_uint8 * 32)()
    lib.sha256_pair(_buf(data64), out)
    return bytes(out)


def sha256_pairs(data: bytes) -> bytes:
    """Concatenated 64-byte messages -> concatenated 32-byte digests."""
    lib = get_lib()
    assert lib is not None and len(data) % 64 == 0
    n = len(data) // 64
    out = (ctypes.c_uint8 * (32 * n))()
    lib.sha256_pairs(_buf(data), out, n)
    return bytes(out)
