"""ctypes loader for the native runtime (sha256_merkle.c).

Compiles the shared object on first use with the system C compiler into
the package directory (a one-time ~1s cost), mirroring how the reference
leans on prebuilt C cores (hashlib/milagro) behind Python bindings. Set
``ETH_SPECS_TPU_NO_NATIVE=1`` to force the pure-Python fallbacks; all
callers degrade gracefully when no compiler is available."""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import sys as _sys
import sysconfig

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "sha256_merkle.c")
_LIB = os.path.join(_DIR, "_sha256_merkle.so")

_lib: ctypes.CDLL | None = None
_tried = False


def _src_digest(*srcs: str) -> str:
    h = hashlib.sha256()
    for s in srcs:
        with open(s, "rb") as f:
            h.update(f.read())
    return h.hexdigest()


def _cpu_isa_token() -> str:
    """Coarse CPU-capability fingerprint for the build stamp (x86 ISA
    extensions the optimized builds may use; empty off-x86/Linux)."""
    try:
        with open("/proc/cpuinfo") as f:
            flags = ""
            for line in f:
                if line.startswith("flags"):
                    flags = line
                    break
        return "+".join(t for t in ("bmi2", "adx") if f" {t}" in flags)
    except OSError:
        return "unknown"


def _probe_ok(lib_path: str, symbol: str) -> bool:
    """Run ``symbol()`` from the candidate library in a THROWAWAY child
    process and require exit 0.  An ISA-extension build on a CPU without
    those opcodes dies with SIGILL — isolating the first call keeps the
    crash out of the importing process and lets the flag ladder fall back
    to the portable build."""
    code = (
        "import ctypes,sys;"
        f"sys.exit(0 if ctypes.CDLL({lib_path!r}).{symbol}() == 0 else 1)"
    )
    try:
        res = subprocess.run(
            [_sys.executable, "-c", code], capture_output=True, timeout=60
        )
        return res.returncode == 0
    except (OSError, subprocess.SubprocessError):
        return False


def _ensure_shared(
    out: str,
    srcs: tuple[str, ...],
    opt: str,
    timeout: int,
    probe_symbol: str | None = None,
) -> bool:
    """Compile ``srcs[0]`` into ``out`` unless an object built from exactly
    these sources already exists. Freshness is a content-hash stamp file
    (``out + '.sha256'``), not mtimes: git does not preserve mtimes, so a
    stale or foreign binary must never silently win over the audited source
    for consensus-critical code. Links to a per-process temp name, then
    atomically renames: concurrent first-use compilations (pytest-xdist,
    parallel imports) must never let a reader dlopen a partial object."""
    # The stamp encodes source content AND the build variant AND the CPU
    # capability the variant relies on: a checkout (or baked image) moved
    # to a CPU without BMI2/ADX must MISS the stamp, re-enter the flag
    # ladder, and let the crash-isolated probe reject the ISA build —
    # never dlopen a mulx/adcx object into the importing process blind.
    want = f"{_src_digest(*srcs)}:{opt}:{_cpu_isa_token()}"
    stamp = out + ".sha256"
    try:
        with open(stamp) as f:
            if f.read().strip() == want and os.path.exists(out):
                return True
    except OSError:
        pass
    cc = os.environ.get("CC") or sysconfig.get_config_var("CC") or "cc"
    tmp = f"{out}.{os.getpid()}.tmp"
    built = False
    candidates = [opt.split(), [opt.split()[0]]]
    if candidates[1] == candidates[0]:
        candidates.pop()  # single-flag opt: no distinct fallback to try
    for flags in candidates:
        # first choice may carry ISA-extension flags (BMI2/ADX measurably
        # speed the Montgomery carry chains); retry with the bare -O level
        # for compilers that reject them or CPUs that trap on the opcodes
        # (the probe below catches the latter in a crash-isolated child)
        cmd = cc.split() + flags + ["-fPIC", "-shared", "-o", tmp, srcs[0]]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=timeout)
        except (OSError, subprocess.SubprocessError):
            try:
                os.unlink(tmp)
            except OSError:
                pass
            continue
        if probe_symbol is not None and not _probe_ok(tmp, probe_symbol):
            try:
                os.unlink(tmp)
            except OSError:
                pass
            continue
        os.replace(tmp, out)
        built = True
        break
    if not built:
        return False
    # Stamp failure must not discard a successfully installed library —
    # worst case the next process recompiles once more.
    try:
        stamp_tmp = f"{stamp}.{os.getpid()}.tmp"
        with open(stamp_tmp, "w") as f:
            f.write(want)
        os.replace(stamp_tmp, stamp)
    except OSError:
        pass
    return True


def _compile() -> bool:
    return _ensure_shared(_LIB, (_SRC,), "-O2", 120)


def get_lib() -> ctypes.CDLL | None:
    """The loaded native library, or None when unavailable/disabled."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if os.environ.get("ETH_SPECS_TPU_NO_NATIVE"):
        return None
    if not _compile():
        return None
    try:
        lib = ctypes.CDLL(_LIB)
    except OSError:
        return None
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.sha256_pair.argtypes = [u8p, u8p]
    lib.sha256_pairs.argtypes = [u8p, u8p, ctypes.c_uint64]
    lib.merkle_level.argtypes = [u8p, u8p, ctypes.c_uint64]
    lib.deposit_tree_insert.argtypes = [u8p, ctypes.c_uint64, u8p, ctypes.c_uint32]
    lib.deposit_tree_root.argtypes = [u8p, u8p, ctypes.c_uint64, ctypes.c_uint32, u8p]
    _lib = lib
    return _lib


def available() -> bool:
    return get_lib() is not None


def _buf(data: bytes):
    return (ctypes.c_uint8 * len(data)).from_buffer_copy(data)


def sha256_pair(data64: bytes) -> bytes:
    lib = get_lib()
    assert lib is not None and len(data64) == 64
    out = (ctypes.c_uint8 * 32)()
    lib.sha256_pair(_buf(data64), out)
    return bytes(out)


def sha256_pairs(data: bytes) -> bytes:
    """Concatenated 64-byte messages -> concatenated 32-byte digests."""
    lib = get_lib()
    assert lib is not None and len(data) % 64 == 0
    n = len(data) // 64
    out = (ctypes.c_uint8 * (32 * n))()
    lib.sha256_pairs(_buf(data), out, n)
    return bytes(out)


# --- BLS12-381 native core (bls12_381.c) -----------------------------------

_BLS_SRC = os.path.join(_DIR, "bls12_381.c")
_BLS_LIB_PATH = os.path.join(_DIR, "_bls12_381.so")

_bls_lib: ctypes.CDLL | None = None
_bls_tried = False


def _compile_bls() -> bool:
    hdr = os.path.join(_DIR, "bls12_381_consts.h")
    return _ensure_shared(
        _BLS_LIB_PATH,
        (_BLS_SRC, hdr),
        "-O3 -mbmi2 -madx -mtune=skylake-avx512",
        300,
        probe_symbol="bls_selftest",
    )


def get_bls_lib() -> ctypes.CDLL | None:
    """The native BLS12-381 library, or None when unavailable/disabled."""
    global _bls_lib, _bls_tried
    if _bls_lib is not None or _bls_tried:
        return _bls_lib
    _bls_tried = True
    if os.environ.get("ETH_SPECS_TPU_NO_NATIVE"):
        return None
    if not _compile_bls():
        return None
    try:
        lib = ctypes.CDLL(_BLS_LIB_PATH)
    except OSError:
        return None
    u8p = ctypes.POINTER(ctypes.c_uint8)
    c = ctypes
    lib.bls_selftest.restype = c.c_int
    lib.bls_g1_mul.argtypes = [u8p, c.c_uint8, u8p, u8p, u8p]
    lib.bls_g2_mul.argtypes = [u8p, c.c_uint8, u8p, u8p, u8p]
    lib.bls_g1_mul_wide.argtypes = [u8p, c.c_uint8, u8p, c.c_uint64, u8p, u8p]
    lib.bls_g2_mul_wide.argtypes = [u8p, c.c_uint8, u8p, c.c_uint64, u8p, u8p]
    lib.bls_g1_aggregate.argtypes = [c.c_uint64, u8p, u8p, u8p, u8p]
    lib.bls_g2_aggregate.argtypes = [c.c_uint64, u8p, u8p, u8p, u8p]
    lib.bls_g1_msm.argtypes = [c.c_uint64, u8p, u8p, u8p, u8p, u8p]
    lib.bls_g2_msm.argtypes = [c.c_uint64, u8p, u8p, u8p, u8p, u8p]
    lib.bls_g1_in_subgroup.argtypes = [u8p]
    lib.bls_g1_in_subgroup.restype = c.c_int
    lib.bls_g2_in_subgroup.argtypes = [u8p]
    lib.bls_g2_in_subgroup.restype = c.c_int
    lib.bls_g2_clear_cofactor.argtypes = [u8p, u8p, u8p]
    lib.bls_g2_decompress.argtypes = [u8p, u8p, u8p]
    lib.bls_g2_decompress.restype = c.c_int
    lib.bls_g2_map_set_params.argtypes = [u8p]
    lib.bls_g2_map_from_fields.argtypes = [u8p, u8p, u8p]
    lib.bls_g2_map_from_fields.restype = c.c_int
    lib.bls_g1_on_curve.argtypes = [u8p]
    lib.bls_g1_on_curve.restype = c.c_int
    lib.bls_g2_on_curve.argtypes = [u8p]
    lib.bls_g2_on_curve.restype = c.c_int
    lib.bls_pairing_check.argtypes = [c.c_uint64, u8p, u8p, u8p]
    lib.bls_pairing_check.restype = c.c_int
    lib.bls_g2_prepare_many.argtypes = [c.c_uint64, u8p, c.POINTER(c.c_uint64)]
    lib.bls_g2_prepare_many.restype = c.c_uint64
    lib.bls_pairing.argtypes = [u8p, u8p, u8p]
    lib.bls_fp_sqrt.argtypes = [u8p, u8p]
    lib.bls_fp_sqrt.restype = c.c_int
    lib.bls_fp2_sqrt.argtypes = [u8p, u8p]
    lib.bls_fp2_sqrt.restype = c.c_int
    lib.bls_fp_inv.argtypes = [u8p, u8p]
    lib.bls_fp_inv.restype = c.c_int
    lib.bls_fp2_inv.argtypes = [u8p, u8p]
    lib.bls_fp2_inv.restype = c.c_int
    if lib.bls_selftest() != 0:
        return None
    _bls_lib = lib
    return _bls_lib
