/* BLS12-381 native runtime: Montgomery Fp, the Fp2/Fp6/Fp12 tower, G1/G2
 * Jacobian arithmetic, Pippenger MSM, and the optimal ate pairing.
 *
 * This is the framework's host-native crypto core — the slot the reference
 * fills with the milagro/arkworks C/Rust extensions behind its backend
 * switch (reference: tests/core/pyspec/eth2spec/utils/bls.py:224-296).
 * The tower layout and the pairing structure mirror the first-party Python
 * oracle (crypto/fields.py, crypto/pairing.py): u^2 = -1, v^3 = 1+u,
 * w^2 = v, generic affine line functions over the untwisted Fp12 image,
 * negative-x conjugation, naive hard-part exponentiation. The Python side
 * stays the oracle; tests cross-check every exported function against it.
 *
 * All byte interfaces are big-endian 48-byte field elements (matching the
 * SSZ/IETF compressed-point serialization the Python layer handles);
 * scalars are 32-byte big-endian. Infinity travels as a separate flag.
 *
 * Build: cc -O2 -fPIC -shared -o _bls12_381.so bls12_381.c
 */

#include <stdint.h>
#include <stdlib.h>
#include <string.h>

#include "bls12_381_consts.h"

typedef unsigned __int128 u128;

/* ---------------------------------------------------------------- Fp --- */

typedef struct { uint64_t l[6]; } fp;

static const fp FP_ZERO = {{0, 0, 0, 0, 0, 0}};

static int fp_is_zero(const fp *a) {
    uint64_t r = 0;
    for (int i = 0; i < 6; i++) r |= a->l[i];
    return r == 0;
}

static int fp_eq(const fp *a, const fp *b) {
    uint64_t r = 0;
    for (int i = 0; i < 6; i++) r |= a->l[i] ^ b->l[i];
    return r == 0;
}

/* -1 if a < b, 0 if equal, 1 if a > b (plain limb compare) */
static int limbs_cmp(const uint64_t *a, const uint64_t *b, int n) {
    for (int i = n - 1; i >= 0; i--) {
        if (a[i] < b[i]) return -1;
        if (a[i] > b[i]) return 1;
    }
    return 0;
}

static void fp_add(fp *r, const fp *a, const fp *b) {
    uint64_t t[6];
    u128 c = 0;
    for (int i = 0; i < 6; i++) {
        c += (u128)a->l[i] + b->l[i];
        t[i] = (uint64_t)c;
        c >>= 64;
    }
    if (c || limbs_cmp(t, FP_P, 6) >= 0) {
        u128 br = 0;
        for (int i = 0; i < 6; i++) {
            u128 d = (u128)t[i] - FP_P[i] - br;
            r->l[i] = (uint64_t)d;
            br = (d >> 64) & 1;
        }
    } else {
        memcpy(r->l, t, sizeof t);
    }
}

static void fp_sub(fp *r, const fp *a, const fp *b) {
    u128 br = 0;
    uint64_t t[6];
    for (int i = 0; i < 6; i++) {
        u128 d = (u128)a->l[i] - b->l[i] - br;
        t[i] = (uint64_t)d;
        br = (d >> 64) & 1;
    }
    if (br) {
        u128 c = 0;
        for (int i = 0; i < 6; i++) {
            c += (u128)t[i] + FP_P[i];
            r->l[i] = (uint64_t)c;
            c >>= 64;
        }
    } else {
        memcpy(r->l, t, sizeof t);
    }
}

static void fp_neg(fp *r, const fp *a) {
    if (fp_is_zero(a)) { *r = *a; return; }
    u128 br = 0;
    for (int i = 0; i < 6; i++) {
        u128 d = (u128)FP_P[i] - a->l[i] - br;
        r->l[i] = (uint64_t)d;
        br = (d >> 64) & 1;
    }
}

/* CIOS Montgomery multiplication: r = a*b*2^-384 mod p. */
#if defined(__x86_64__) && defined(__BMI2__) && defined(__ADX__)
/* CIOS Montgomery multiplication on mulx/adcx/adox dual carry chains —
 * ~1.5x the portable u128 version on the same core (the whole pairing /
 * hash-to-curve / decompression stack is fp_mul-bound, so this is a
 * framework-wide host-crypto speedup).  Bounds: inputs < p, so every
 * ai*b[5] high word is < 2^62 (p's top limb is 0x1a01...) and the t6
 * accumulator never overflows; at each row boundary t < 2p, so the
 * final carry out of the shifted add chain is provably zero.  The
 * loader proves CPU support at runtime (crash-isolated selftest probe,
 * native/__init__.py) before this build is accepted. */
#include <immintrin.h>
typedef unsigned long long ull_;
static void fp_mul(fp *r, const fp *a, const fp *b) {
    ull_ t0 = 0, t1 = 0, t2 = 0, t3 = 0, t4 = 0, t5 = 0, t6 = 0;
    const uint64_t *bl = b->l, *pl = FP_P;
    for (int i = 0; i < 6; i++) {
        ull_ ai = a->l[i], lo0, lo1, lo2, lo3, lo4, lo5, h0, h1, h2, h3, h4, h5;
        unsigned char c;
        lo0 = _mulx_u64(ai, bl[0], &h0); lo1 = _mulx_u64(ai, bl[1], &h1);
        lo2 = _mulx_u64(ai, bl[2], &h2); lo3 = _mulx_u64(ai, bl[3], &h3);
        lo4 = _mulx_u64(ai, bl[4], &h4); lo5 = _mulx_u64(ai, bl[5], &h5);
        c = _addcarryx_u64(0, t0, lo0, &t0); c = _addcarryx_u64(c, t1, lo1, &t1);
        c = _addcarryx_u64(c, t2, lo2, &t2); c = _addcarryx_u64(c, t3, lo3, &t3);
        c = _addcarryx_u64(c, t4, lo4, &t4); c = _addcarryx_u64(c, t5, lo5, &t5);
        t6 = (ull_)c;
        c = _addcarryx_u64(0, t1, h0, &t1); c = _addcarryx_u64(c, t2, h1, &t2);
        c = _addcarryx_u64(c, t3, h2, &t3); c = _addcarryx_u64(c, t4, h3, &t4);
        c = _addcarryx_u64(c, t5, h4, &t5); t6 += (ull_)c + h5;
        ull_ m = t0 * FP_N0;
        lo0 = _mulx_u64(m, pl[0], &h0); lo1 = _mulx_u64(m, pl[1], &h1);
        lo2 = _mulx_u64(m, pl[2], &h2); lo3 = _mulx_u64(m, pl[3], &h3);
        lo4 = _mulx_u64(m, pl[4], &h4); lo5 = _mulx_u64(m, pl[5], &h5);
        c = _addcarryx_u64(0, t0, lo0, &t0); c = _addcarryx_u64(c, t1, lo1, &t1);
        c = _addcarryx_u64(c, t2, lo2, &t2); c = _addcarryx_u64(c, t3, lo3, &t3);
        c = _addcarryx_u64(c, t4, lo4, &t4); c = _addcarryx_u64(c, t5, lo5, &t5);
        ull_ d1 = (ull_)c; /* carry into position 6 */
        c = _addcarryx_u64(0, t1, h0, &t0); c = _addcarryx_u64(c, t2, h1, &t1);
        c = _addcarryx_u64(c, t3, h2, &t2); c = _addcarryx_u64(c, t4, h3, &t3);
        c = _addcarryx_u64(c, t5, h4, &t4); c = _addcarryx_u64(c, t6, h5 + d1, &t5);
        t6 = 0; /* c provably 0: row boundary value < 2p */
    }
    ull_ o0, o1, o2, o3, o4, o5;
    unsigned char br;
    br = _subborrow_u64(0, t0, pl[0], &o0); br = _subborrow_u64(br, t1, pl[1], &o1);
    br = _subborrow_u64(br, t2, pl[2], &o2); br = _subborrow_u64(br, t3, pl[3], &o3);
    br = _subborrow_u64(br, t4, pl[4], &o4); br = _subborrow_u64(br, t5, pl[5], &o5);
    if (!br) { t0 = o0; t1 = o1; t2 = o2; t3 = o3; t4 = o4; t5 = o5; }
    r->l[0] = t0; r->l[1] = t1; r->l[2] = t2;
    r->l[3] = t3; r->l[4] = t4; r->l[5] = t5;
}
#else
static void fp_mul(fp *r, const fp *a, const fp *b) {
    uint64_t t[8];
    memset(t, 0, sizeof t);
    for (int i = 0; i < 6; i++) {
        u128 c = 0;
        for (int j = 0; j < 6; j++) {
            c += (u128)a->l[i] * b->l[j] + t[j];
            t[j] = (uint64_t)c;
            c >>= 64;
        }
        c += t[6];
        t[6] = (uint64_t)c;
        t[7] = (uint64_t)(c >> 64);

        uint64_t m = t[0] * FP_N0;
        c = (u128)m * FP_P[0] + t[0];
        c >>= 64;
        for (int j = 1; j < 6; j++) {
            c += (u128)m * FP_P[j] + t[j];
            t[j - 1] = (uint64_t)c;
            c >>= 64;
        }
        c += t[6];
        t[5] = (uint64_t)c;
        t[6] = t[7] + (uint64_t)(c >> 64);
        t[7] = 0;
    }
    if (t[6] || limbs_cmp(t, FP_P, 6) >= 0) {
        u128 br = 0;
        for (int i = 0; i < 6; i++) {
            u128 d = (u128)t[i] - FP_P[i] - br;
            r->l[i] = (uint64_t)d;
            br = (d >> 64) & 1;
        }
    } else {
        memcpy(r->l, t, 6 * sizeof(uint64_t));
    }
}
#endif /* BMI2+ADX vs portable fp_mul */

static void fp_sqr(fp *r, const fp *a) { fp_mul(r, a, a); }

static void fp_one(fp *r) { memcpy(r->l, FP_R1, sizeof r->l); }

static void fp_from_plain(fp *r, const uint64_t plain[6]) {
    fp tmp, r2;
    memcpy(tmp.l, plain, sizeof tmp.l);
    memcpy(r2.l, FP_R2, sizeof r2.l);
    fp_mul(r, &tmp, &r2);
}

static void fp_to_plain(uint64_t out[6], const fp *a) {
    fp one_plain = {{1, 0, 0, 0, 0, 0}};
    fp t;
    fp_mul(&t, a, &one_plain);
    memcpy(out, t.l, sizeof t.l);
}

static void fp_from_be(fp *r, const uint8_t in[48]) {
    uint64_t plain[6];
    for (int i = 0; i < 6; i++) {
        uint64_t v = 0;
        const uint8_t *p = in + (5 - i) * 8;
        for (int j = 0; j < 8; j++) v = (v << 8) | p[j];
        plain[i] = v;
    }
    fp_from_plain(r, plain);
}

static void fp_to_be(uint8_t out[48], const fp *a) {
    uint64_t plain[6];
    fp_to_plain(plain, a);
    for (int i = 0; i < 6; i++) {
        uint64_t v = plain[i];
        uint8_t *p = out + (5 - i) * 8;
        for (int j = 7; j >= 0; j--) { p[j] = (uint8_t)v; v >>= 8; }
    }
}

/* MSB-first square-and-multiply over a little-endian limb exponent. */
static void fp_pow_limbs(fp *r, const fp *base, const uint64_t *exp, int nlimbs) {
    int top = -1;
    for (int i = nlimbs - 1; i >= 0 && top < 0; i--)
        if (exp[i]) {
            for (int b = 63; b >= 0; b--)
                if ((exp[i] >> b) & 1) { top = i * 64 + b; break; }
        }
    fp acc;
    fp_one(&acc);
    if (top < 0) { *r = acc; return; }
    for (int bit = top; bit >= 0; bit--) {
        fp_sqr(&acc, &acc);
        if ((exp[bit / 64] >> (bit % 64)) & 1) fp_mul(&acc, &acc, base);
    }
    *r = acc;
}

/* plain-limb helpers for the binary extended GCD */

static int limbs_is_even(const uint64_t a[6]) { return (a[0] & 1) == 0; }

static int limbs_is_one(const uint64_t a[6]) {
    return a[0] == 1 && !(a[1] | a[2] | a[3] | a[4] | a[5]);
}

static int limbs_is_zero6(const uint64_t a[6]) {
    return !(a[0] | a[1] | a[2] | a[3] | a[4] | a[5]);
}

static void limbs_sub6(uint64_t r[6], const uint64_t a[6], const uint64_t b[6]) {
    u128 br = 0;
    for (int i = 0; i < 6; i++) {
        u128 d = (u128)a[i] - b[i] - br;
        r[i] = (uint64_t)d;
        br = (d >> 64) & 1;
    }
}

/* r = a >> 1, with an incoming top carry bit */
static void limbs_shr1(uint64_t r[6], const uint64_t a[6], uint64_t carry) {
    for (int i = 0; i < 6; i++) {
        uint64_t next = (i < 5) ? a[i + 1] : carry;
        r[i] = (a[i] >> 1) | (next << 63);
    }
}

/* halve x modulo p: x even -> x>>1, else (x+p)>>1 (needs the carry bit) */
static void limbs_half_mod_p(uint64_t x[6]) {
    if (limbs_is_even(x)) {
        limbs_shr1(x, x, 0);
    } else {
        u128 c = 0;
        uint64_t t[6];
        for (int i = 0; i < 6; i++) {
            c += (u128)x[i] + FP_P[i];
            t[i] = (uint64_t)c;
            c >>= 64;
        }
        limbs_shr1(x, t, (uint64_t)c);
    }
}

static void limbs_submod_p(uint64_t r[6], const uint64_t a[6], const uint64_t b[6]) {
    u128 br = 0;
    uint64_t t[6];
    for (int i = 0; i < 6; i++) {
        u128 d = (u128)a[i] - b[i] - br;
        t[i] = (uint64_t)d;
        br = (d >> 64) & 1;
    }
    if (br) {
        u128 c = 0;
        for (int i = 0; i < 6; i++) {
            c += (u128)t[i] + FP_P[i];
            r[i] = (uint64_t)c;
            c >>= 64;
        }
    } else {
        memcpy(r, t, 6 * sizeof(uint64_t));
    }
}

/* Binary extended GCD inversion (odd modulus): ~100x faster than the
 * Fermat pow and the reason the Miller loop's affine formulation is viable
 * on the host.  Falls back to pow for zero input (returns zero like pow). */
static void fp_inv(fp *r, const fp *a) {
    uint64_t u[6], v[6], x1[6], x2[6];
    fp_to_plain(u, a);
    if (limbs_is_zero6(u)) { *r = FP_ZERO; return; }
    memcpy(v, FP_P, sizeof v);
    memset(x1, 0, sizeof x1);
    x1[0] = 1;
    memset(x2, 0, sizeof x2);
    while (!limbs_is_one(u) && !limbs_is_one(v)) {
        while (limbs_is_even(u)) {
            limbs_shr1(u, u, 0);
            limbs_half_mod_p(x1);
        }
        while (limbs_is_even(v)) {
            limbs_shr1(v, v, 0);
            limbs_half_mod_p(x2);
        }
        if (limbs_cmp(u, v, 6) >= 0) {
            limbs_sub6(u, u, v);
            limbs_submod_p(x1, x1, x2);
        } else {
            limbs_sub6(v, v, u);
            limbs_submod_p(x2, x2, x1);
        }
    }
    fp_from_plain(r, limbs_is_one(u) ? x1 : x2);
}

/* sqrt for p = 3 mod 4; returns 1 on success. */
static int fp_sqrt(fp *r, const fp *a) {
    fp c, c2;
    fp_pow_limbs(&c, a, FP_SQRT_EXP, 6);
    fp_sqr(&c2, &c);
    if (!fp_eq(&c2, a)) return 0;
    *r = c;
    return 1;
}

/* --------------------------------------------------------------- Fp2 --- */

typedef struct { fp c0, c1; } fp2;

static void fp2_zero(fp2 *r) { r->c0 = FP_ZERO; r->c1 = FP_ZERO; }
static void fp2_one(fp2 *r) { fp_one(&r->c0); r->c1 = FP_ZERO; }

static int fp2_is_zero(const fp2 *a) { return fp_is_zero(&a->c0) && fp_is_zero(&a->c1); }
static int fp2_eq(const fp2 *a, const fp2 *b) { return fp_eq(&a->c0, &b->c0) && fp_eq(&a->c1, &b->c1); }

static void fp2_add(fp2 *r, const fp2 *a, const fp2 *b) {
    fp_add(&r->c0, &a->c0, &b->c0);
    fp_add(&r->c1, &a->c1, &b->c1);
}

static void fp2_sub(fp2 *r, const fp2 *a, const fp2 *b) {
    fp_sub(&r->c0, &a->c0, &b->c0);
    fp_sub(&r->c1, &a->c1, &b->c1);
}

static void fp2_neg(fp2 *r, const fp2 *a) {
    fp_neg(&r->c0, &a->c0);
    fp_neg(&r->c1, &a->c1);
}

static void fp2_conj(fp2 *r, const fp2 *a) {
    r->c0 = a->c0;
    fp_neg(&r->c1, &a->c1);
}

static void fp2_mul(fp2 *r, const fp2 *a, const fp2 *b) {
    fp t0, t1, s0, s1, cross;
    fp_mul(&t0, &a->c0, &b->c0);
    fp_mul(&t1, &a->c1, &b->c1);
    fp_add(&s0, &a->c0, &a->c1);
    fp_add(&s1, &b->c0, &b->c1);
    fp_mul(&cross, &s0, &s1);
    fp_sub(&cross, &cross, &t0);
    fp_sub(&cross, &cross, &t1);
    fp_sub(&r->c0, &t0, &t1);
    r->c1 = cross;
}

static void fp2_sqr(fp2 *r, const fp2 *a) {
    fp s, d, m;
    fp_add(&s, &a->c0, &a->c1);
    fp_sub(&d, &a->c0, &a->c1);
    fp_mul(&m, &a->c0, &a->c1);
    fp_mul(&r->c0, &s, &d);
    fp_add(&r->c1, &m, &m);
}

static void fp2_mul_fp(fp2 *r, const fp2 *a, const fp *k) {
    fp_mul(&r->c0, &a->c0, k);
    fp_mul(&r->c1, &a->c1, k);
}

/* multiply by xi = 1 + u: (c0 - c1) + (c0 + c1) u */
static void fp2_mul_xi(fp2 *r, const fp2 *a) {
    fp t0, t1;
    fp_sub(&t0, &a->c0, &a->c1);
    fp_add(&t1, &a->c0, &a->c1);
    r->c0 = t0;
    r->c1 = t1;
}

static void fp2_inv(fp2 *r, const fp2 *a) {
    fp n, t, ninv;
    fp_sqr(&n, &a->c0);
    fp_sqr(&t, &a->c1);
    fp_add(&n, &n, &t);
    fp_inv(&ninv, &n);
    fp_mul(&r->c0, &a->c0, &ninv);
    fp_mul(&t, &a->c1, &ninv);
    fp_neg(&r->c1, &t);
}

/* sqrt in Fp2 by the norm method (mirrors crypto/fields.py Fq2.sqrt). */
static int fp2_sqrt(fp2 *r, const fp2 *a) {
    if (fp2_is_zero(a)) { fp2_zero(r); return 1; }
    if (fp_is_zero(&a->c1)) {
        fp s;
        if (fp_sqrt(&s, &a->c0)) { r->c0 = s; r->c1 = FP_ZERO; return 1; }
        fp na;
        fp_neg(&na, &a->c0);
        if (!fp_sqrt(&s, &na)) return 0;
        r->c0 = FP_ZERO;
        r->c1 = s;
        return 1;
    }
    fp norm, t, sn;
    fp_sqr(&norm, &a->c0);
    fp_sqr(&t, &a->c1);
    fp_add(&norm, &norm, &t);
    if (!fp_sqrt(&sn, &norm)) return 0;
    fp two, inv2;
    fp_one(&two);
    fp_add(&two, &two, &two);
    fp_inv(&inv2, &two);
    for (int attempt = 0; attempt < 2; attempt++) {
        fp half, x;
        if (attempt == 0) fp_add(&half, &a->c0, &sn);
        else fp_sub(&half, &a->c0, &sn);
        fp_mul(&half, &half, &inv2);
        if (!fp_sqrt(&x, &half) || fp_is_zero(&x)) continue;
        fp twox, txinv, y;
        fp_add(&twox, &x, &x);
        fp_inv(&txinv, &twox);
        fp_mul(&y, &a->c1, &txinv);
        fp2 cand = { x, y }, sq;
        fp2_sqr(&sq, &cand);
        if (fp2_eq(&sq, a)) { *r = cand; return 1; }
    }
    return 0;
}

/* --------------------------------------------------------------- Fp6 --- */

typedef struct { fp2 c0, c1, c2; } fp6;

static void fp6_zero(fp6 *r) { fp2_zero(&r->c0); fp2_zero(&r->c1); fp2_zero(&r->c2); }
static void fp6_one(fp6 *r) { fp2_one(&r->c0); fp2_zero(&r->c1); fp2_zero(&r->c2); }

static int fp6_is_zero(const fp6 *a) {
    return fp2_is_zero(&a->c0) && fp2_is_zero(&a->c1) && fp2_is_zero(&a->c2);
}

static int fp6_eq(const fp6 *a, const fp6 *b) {
    return fp2_eq(&a->c0, &b->c0) && fp2_eq(&a->c1, &b->c1) && fp2_eq(&a->c2, &b->c2);
}

static void fp6_add(fp6 *r, const fp6 *a, const fp6 *b) {
    fp2_add(&r->c0, &a->c0, &b->c0);
    fp2_add(&r->c1, &a->c1, &b->c1);
    fp2_add(&r->c2, &a->c2, &b->c2);
}

static void fp6_sub(fp6 *r, const fp6 *a, const fp6 *b) {
    fp2_sub(&r->c0, &a->c0, &b->c0);
    fp2_sub(&r->c1, &a->c1, &b->c1);
    fp2_sub(&r->c2, &a->c2, &b->c2);
}

static void fp6_neg(fp6 *r, const fp6 *a) {
    fp2_neg(&r->c0, &a->c0);
    fp2_neg(&r->c1, &a->c1);
    fp2_neg(&r->c2, &a->c2);
}

static void fp6_mul(fp6 *r, const fp6 *a, const fp6 *b) {
    fp2 t0, t1, t2, s, u, v;
    fp2_mul(&t0, &a->c0, &b->c0);
    fp2_mul(&t1, &a->c1, &b->c1);
    fp2_mul(&t2, &a->c2, &b->c2);

    /* c0 = t0 + xi*((a1+a2)(b1+b2) - t1 - t2) */
    fp2_add(&s, &a->c1, &a->c2);
    fp2_add(&u, &b->c1, &b->c2);
    fp2_mul(&v, &s, &u);
    fp2_sub(&v, &v, &t1);
    fp2_sub(&v, &v, &t2);
    fp2_mul_xi(&v, &v);
    fp2 c0, c1, c2;
    fp2_add(&c0, &t0, &v);

    /* c1 = (a0+a1)(b0+b1) - t0 - t1 + xi*t2 */
    fp2_add(&s, &a->c0, &a->c1);
    fp2_add(&u, &b->c0, &b->c1);
    fp2_mul(&v, &s, &u);
    fp2_sub(&v, &v, &t0);
    fp2_sub(&v, &v, &t1);
    fp2 xt2;
    fp2_mul_xi(&xt2, &t2);
    fp2_add(&c1, &v, &xt2);

    /* c2 = (a0+a2)(b0+b2) - t0 - t2 + t1 */
    fp2_add(&s, &a->c0, &a->c2);
    fp2_add(&u, &b->c0, &b->c2);
    fp2_mul(&v, &s, &u);
    fp2_sub(&v, &v, &t0);
    fp2_sub(&v, &v, &t2);
    fp2_add(&c2, &v, &t1);

    r->c0 = c0; r->c1 = c1; r->c2 = c2;
}

/* CH-SQR2 squaring: 5 fp2 multiplications instead of 6. */
static void fp6_sqr(fp6 *r, const fp6 *a) {
    fp2 s0, s1, s2, s3, s4, t;
    fp2_sqr(&s0, &a->c0);
    fp2_mul(&s1, &a->c0, &a->c1);
    fp2_add(&s1, &s1, &s1);
    fp2_sub(&t, &a->c0, &a->c1);
    fp2_add(&t, &t, &a->c2);
    fp2_sqr(&s2, &t);
    fp2_mul(&s3, &a->c1, &a->c2);
    fp2_add(&s3, &s3, &s3);
    fp2_sqr(&s4, &a->c2);
    fp2 c0, c1, c2;
    fp2_mul_xi(&t, &s3);
    fp2_add(&c0, &s0, &t);
    fp2_mul_xi(&t, &s4);
    fp2_add(&c1, &s1, &t);
    fp2_add(&c2, &s1, &s2);
    fp2_add(&c2, &c2, &s3);
    fp2_sub(&c2, &c2, &s0);
    fp2_sub(&c2, &c2, &s4);
    r->c0 = c0; r->c1 = c1; r->c2 = c2;
}

/* multiply by v: (c0,c1,c2) -> (xi*c2, c0, c1) */
static void fp6_mul_v(fp6 *r, const fp6 *a) {
    fp2 t;
    fp2_mul_xi(&t, &a->c2);
    fp2 c1 = a->c0, c2 = a->c1;
    r->c0 = t;
    r->c1 = c1;
    r->c2 = c2;
}

static void fp6_inv(fp6 *r, const fp6 *a) {
    fp2 t0, t1, t2, s, v, denom;
    /* t0 = a0^2 - xi*a1*a2 */
    fp2_sqr(&t0, &a->c0);
    fp2_mul(&s, &a->c1, &a->c2);
    fp2_mul_xi(&s, &s);
    fp2_sub(&t0, &t0, &s);
    /* t1 = xi*a2^2 - a0*a1 */
    fp2_sqr(&t1, &a->c2);
    fp2_mul_xi(&t1, &t1);
    fp2_mul(&s, &a->c0, &a->c1);
    fp2_sub(&t1, &t1, &s);
    /* t2 = a1^2 - a0*a2 */
    fp2_sqr(&t2, &a->c1);
    fp2_mul(&s, &a->c0, &a->c2);
    fp2_sub(&t2, &t2, &s);
    /* denom = a0*t0 + xi*(a2*t1 + a1*t2) */
    fp2_mul(&s, &a->c2, &t1);
    fp2_mul(&v, &a->c1, &t2);
    fp2_add(&s, &s, &v);
    fp2_mul_xi(&s, &s);
    fp2_mul(&v, &a->c0, &t0);
    fp2_add(&s, &s, &v);
    fp2_inv(&denom, &s);
    fp2_mul(&r->c0, &t0, &denom);
    fp2_mul(&r->c1, &t1, &denom);
    fp2_mul(&r->c2, &t2, &denom);
}

/* -------------------------------------------------------------- Fp12 --- */

typedef struct { fp6 c0, c1; } fp12;

static void fp12_one(fp12 *r) { fp6_one(&r->c0); fp6_zero(&r->c1); }

static int fp12_eq(const fp12 *a, const fp12 *b) {
    return fp6_eq(&a->c0, &b->c0) && fp6_eq(&a->c1, &b->c1);
}

static int fp12_is_one(const fp12 *a) {
    fp12 one;
    fp12_one(&one);
    return fp12_eq(a, &one);
}

static void fp12_add(fp12 *r, const fp12 *a, const fp12 *b) {
    fp6_add(&r->c0, &a->c0, &b->c0);
    fp6_add(&r->c1, &a->c1, &b->c1);
}

static void fp12_sub(fp12 *r, const fp12 *a, const fp12 *b) {
    fp6_sub(&r->c0, &a->c0, &b->c0);
    fp6_sub(&r->c1, &a->c1, &b->c1);
}

static void fp12_mul(fp12 *r, const fp12 *a, const fp12 *b) {
    fp6 t0, t1, s0, s1, cross, shifted;
    fp6_mul(&t0, &a->c0, &b->c0);
    fp6_mul(&t1, &a->c1, &b->c1);
    fp6_add(&s0, &a->c0, &a->c1);
    fp6_add(&s1, &b->c0, &b->c1);
    fp6_mul(&cross, &s0, &s1);
    fp6_sub(&cross, &cross, &t0);
    fp6_sub(&cross, &cross, &t1);
    fp6_mul_v(&shifted, &t1);
    fp6_add(&r->c0, &t0, &shifted);
    r->c1 = cross;
}

/* (c0 + c1 w)^2 = (c0^2 + v c1^2) + 2 c0 c1 w, via Karatsuba:
 * c0' = (c0+c1)(c0+v*c1) - t - v*t,  c1' = 2t,  t = c0*c1. */
static void fp12_sqr(fp12 *r, const fp12 *a) {
    fp6 t, s0, s1, vt, c0;
    fp6_mul(&t, &a->c0, &a->c1);
    fp6_add(&s0, &a->c0, &a->c1);
    fp6_mul_v(&vt, &a->c1);
    fp6_add(&s1, &a->c0, &vt);
    fp6_mul(&c0, &s0, &s1);
    fp6_sub(&c0, &c0, &t);
    fp6_mul_v(&vt, &t);
    fp6_sub(&c0, &c0, &vt);
    r->c0 = c0;
    fp6_add(&r->c1, &t, &t);
}

static void fp12_conj(fp12 *r, const fp12 *a) {
    r->c0 = a->c0;
    fp6_neg(&r->c1, &a->c1);
}

static void fp12_inv(fp12 *r, const fp12 *a) {
    fp6 t0, t1, t;
    fp6_sqr(&t0, &a->c0);
    fp6_sqr(&t1, &a->c1);
    fp6_mul_v(&t1, &t1);
    fp6_sub(&t0, &t0, &t1);
    fp6_inv(&t, &t0);
    fp6_mul(&r->c0, &a->c0, &t);
    fp6_mul(&t1, &a->c1, &t);
    fp6_neg(&r->c1, &t1);
}

static void fp12_neg(fp12 *r, const fp12 *a) {
    fp6_neg(&r->c0, &a->c0);
    fp6_neg(&r->c1, &a->c1);
}

/* frobenius^2 via gamma powers on the flattened w^i coefficients
 * (coeff order: c0.c0, c1.c0, c0.c1, c1.c1, c0.c2, c1.c2). */
static fp FROB2_POWS[6]; /* gamma^i in Montgomery form, set in init */

static void fp12_frob2(fp12 *r, const fp12 *a) {
    fp2 *rc[6] = { &r->c0.c0, &r->c1.c0, &r->c0.c1, &r->c1.c1, &r->c0.c2, &r->c1.c2 };
    const fp2 *ac[6] = { &a->c0.c0, &a->c1.c0, &a->c0.c1, &a->c1.c1, &a->c0.c2, &a->c1.c2 };
    for (int i = 0; i < 6; i++) fp2_mul_fp(rc[i], ac[i], &FROB2_POWS[i]);
}

static void fp12_pow_limbs(fp12 *r, const fp12 *base, const uint64_t *exp, int nlimbs, int nbits) {
    fp12 acc;
    fp12_one(&acc);
    for (int bit = nbits - 1; bit >= 0; bit--) {
        fp12_sqr(&acc, &acc);
        if ((exp[bit / 64] >> (bit % 64)) & 1) fp12_mul(&acc, &acc, base);
    }
    *r = acc;
}

/* ------------------------------------------------------------- curves --- */

/* Jacobian points; Z == 0 encodes infinity. One implementation per
 * coordinate field (formulas identical to crypto/curve.py _jac_*). */

typedef struct { fp X, Y, Z; } g1p;
typedef struct { fp2 X, Y, Z; } g2p;

static void g1_set_inf(g1p *r) { r->X = FP_ZERO; fp_one(&r->Y); r->Z = FP_ZERO; }
static int g1_is_inf(const g1p *p) { return fp_is_zero(&p->Z); }
static void g2_set_inf(g2p *r) { fp2_zero(&r->X); fp2_one(&r->Y); fp2_zero(&r->Z); }
static int g2_is_inf(const g2p *p) { return fp2_is_zero(&p->Z); }

static void g1_dbl(g1p *r, const g1p *p) {
    if (g1_is_inf(p) || fp_is_zero(&p->Y)) { g1_set_inf(r); return; }
    fp A, B, C, D, E, F, t, X3, Y3, Z3;
    fp_sqr(&A, &p->X);
    fp_sqr(&B, &p->Y);
    fp_sqr(&C, &B);
    fp_add(&t, &p->X, &B);
    fp_sqr(&t, &t);
    fp_sub(&t, &t, &A);
    fp_sub(&D, &t, &C);
    fp_add(&D, &D, &D);
    fp_add(&E, &A, &A);
    fp_add(&E, &E, &A);
    fp_sqr(&F, &E);
    fp_sub(&X3, &F, &D);
    fp_sub(&X3, &X3, &D);
    fp eight_c;
    fp_add(&eight_c, &C, &C);
    fp_add(&eight_c, &eight_c, &eight_c);
    fp_add(&eight_c, &eight_c, &eight_c);
    fp_sub(&t, &D, &X3);
    fp_mul(&Y3, &E, &t);
    fp_sub(&Y3, &Y3, &eight_c);
    fp_mul(&Z3, &p->Y, &p->Z);
    fp_add(&Z3, &Z3, &Z3);
    r->X = X3; r->Y = Y3; r->Z = Z3;
}

static void g1_add(g1p *r, const g1p *p, const g1p *q) {
    if (g1_is_inf(p)) { *r = *q; return; }
    if (g1_is_inf(q)) { *r = *p; return; }
    fp Z1Z1, Z2Z2, U1, U2, S1, S2, t;
    fp_sqr(&Z1Z1, &p->Z);
    fp_sqr(&Z2Z2, &q->Z);
    fp_mul(&U1, &p->X, &Z2Z2);
    fp_mul(&U2, &q->X, &Z1Z1);
    fp_mul(&t, &p->Y, &q->Z);
    fp_mul(&S1, &t, &Z2Z2);
    fp_mul(&t, &q->Y, &p->Z);
    fp_mul(&S2, &t, &Z1Z1);
    if (fp_eq(&U1, &U2)) {
        if (fp_eq(&S1, &S2)) { g1_dbl(r, p); return; }
        g1_set_inf(r);
        return;
    }
    fp H, I, J, rr, V, X3, Y3, Z3;
    fp_sub(&H, &U2, &U1);
    fp_add(&I, &H, &H);
    fp_sqr(&I, &I);
    fp_mul(&J, &H, &I);
    fp_sub(&rr, &S2, &S1);
    fp_add(&rr, &rr, &rr);
    fp_mul(&V, &U1, &I);
    fp_sqr(&X3, &rr);
    fp_sub(&X3, &X3, &J);
    fp_sub(&X3, &X3, &V);
    fp_sub(&X3, &X3, &V);
    fp_sub(&t, &V, &X3);
    fp_mul(&Y3, &rr, &t);
    fp s1j;
    fp_mul(&s1j, &S1, &J);
    fp_add(&s1j, &s1j, &s1j);
    fp_sub(&Y3, &Y3, &s1j);
    fp_add(&Z3, &p->Z, &q->Z);
    fp_sqr(&Z3, &Z3);
    fp_sub(&Z3, &Z3, &Z1Z1);
    fp_sub(&Z3, &Z3, &Z2Z2);
    fp_mul(&Z3, &Z3, &H);
    r->X = X3; r->Y = Y3; r->Z = Z3;
}

static void g2_dbl(g2p *r, const g2p *p) {
    if (g2_is_inf(p) || fp2_is_zero(&p->Y)) { g2_set_inf(r); return; }
    fp2 A, B, C, D, E, F, t, X3, Y3, Z3;
    fp2_sqr(&A, &p->X);
    fp2_sqr(&B, &p->Y);
    fp2_sqr(&C, &B);
    fp2_add(&t, &p->X, &B);
    fp2_sqr(&t, &t);
    fp2_sub(&t, &t, &A);
    fp2_sub(&D, &t, &C);
    fp2_add(&D, &D, &D);
    fp2_add(&E, &A, &A);
    fp2_add(&E, &E, &A);
    fp2_sqr(&F, &E);
    fp2_sub(&X3, &F, &D);
    fp2_sub(&X3, &X3, &D);
    fp2 eight_c;
    fp2_add(&eight_c, &C, &C);
    fp2_add(&eight_c, &eight_c, &eight_c);
    fp2_add(&eight_c, &eight_c, &eight_c);
    fp2_sub(&t, &D, &X3);
    fp2_mul(&Y3, &E, &t);
    fp2_sub(&Y3, &Y3, &eight_c);
    fp2_mul(&Z3, &p->Y, &p->Z);
    fp2_add(&Z3, &Z3, &Z3);
    r->X = X3; r->Y = Y3; r->Z = Z3;
}

static void g2_add(g2p *r, const g2p *p, const g2p *q) {
    if (g2_is_inf(p)) { *r = *q; return; }
    if (g2_is_inf(q)) { *r = *p; return; }
    fp2 Z1Z1, Z2Z2, U1, U2, S1, S2, t;
    fp2_sqr(&Z1Z1, &p->Z);
    fp2_sqr(&Z2Z2, &q->Z);
    fp2_mul(&U1, &p->X, &Z2Z2);
    fp2_mul(&U2, &q->X, &Z1Z1);
    fp2_mul(&t, &p->Y, &q->Z);
    fp2_mul(&S1, &t, &Z2Z2);
    fp2_mul(&t, &q->Y, &p->Z);
    fp2_mul(&S2, &t, &Z1Z1);
    if (fp2_eq(&U1, &U2)) {
        if (fp2_eq(&S1, &S2)) { g2_dbl(r, p); return; }
        g2_set_inf(r);
        return;
    }
    fp2 H, I, J, rr, V, X3, Y3, Z3;
    fp2_sub(&H, &U2, &U1);
    fp2_add(&I, &H, &H);
    fp2_sqr(&I, &I);
    fp2_mul(&J, &H, &I);
    fp2_sub(&rr, &S2, &S1);
    fp2_add(&rr, &rr, &rr);
    fp2_mul(&V, &U1, &I);
    fp2_sqr(&X3, &rr);
    fp2_sub(&X3, &X3, &J);
    fp2_sub(&X3, &X3, &V);
    fp2_sub(&X3, &X3, &V);
    fp2_sub(&t, &V, &X3);
    fp2_mul(&Y3, &rr, &t);
    fp2 s1j;
    fp2_mul(&s1j, &S1, &J);
    fp2_add(&s1j, &s1j, &s1j);
    fp2_sub(&Y3, &Y3, &s1j);
    fp2_add(&Z3, &p->Z, &q->Z);
    fp2_sqr(&Z3, &Z3);
    fp2_sub(&Z3, &Z3, &Z1Z1);
    fp2_sub(&Z3, &Z3, &Z2Z2);
    fp2_mul(&Z3, &Z3, &H);
    r->X = X3; r->Y = Y3; r->Z = Z3;
}

static void g1_from_affine(g1p *r, const fp *x, const fp *y) {
    r->X = *x;
    r->Y = *y;
    fp_one(&r->Z);
}

static void g2_from_affine(g2p *r, const fp2 *x, const fp2 *y) {
    r->X = *x;
    r->Y = *y;
    fp2_one(&r->Z);
}

static void g1_to_affine(fp *x, fp *y, int *inf, const g1p *p) {
    if (g1_is_inf(p)) { *inf = 1; *x = FP_ZERO; *y = FP_ZERO; return; }
    *inf = 0;
    fp zi, zi2, zi3;
    fp_inv(&zi, &p->Z);
    fp_sqr(&zi2, &zi);
    fp_mul(&zi3, &zi2, &zi);
    fp_mul(x, &p->X, &zi2);
    fp_mul(y, &p->Y, &zi3);
}

static void g2_to_affine(fp2 *x, fp2 *y, int *inf, const g2p *p) {
    if (g2_is_inf(p)) { *inf = 1; fp2_zero(x); fp2_zero(y); return; }
    *inf = 0;
    fp2 zi, zi2, zi3;
    fp2_inv(&zi, &p->Z);
    fp2_sqr(&zi2, &zi);
    fp2_mul(&zi3, &zi2, &zi);
    fp2_mul(x, &p->X, &zi2);
    fp2_mul(y, &p->Y, &zi3);
}

/* 4-bit fixed-window scalar multiplication; scalar is 4 LE limbs (256 bit). */

static void g1_mul_scalar(g1p *r, const g1p *p, const uint64_t sc[4]) {
    g1p table[16];
    g1_set_inf(&table[0]);
    table[1] = *p;
    for (int i = 2; i < 16; i++) g1_add(&table[i], &table[i - 1], p);
    g1p acc;
    g1_set_inf(&acc);
    for (int nib = 63; nib >= 0; nib--) {
        for (int k = 0; k < 4; k++) g1_dbl(&acc, &acc);
        unsigned idx = (unsigned)((sc[nib / 16] >> ((nib % 16) * 4)) & 0xF);
        if (idx) g1_add(&acc, &acc, &table[idx]);
    }
    *r = acc;
}

static void g2_mul_scalar(g2p *r, const g2p *p, const uint64_t sc[4]) {
    g2p table[16];
    g2_set_inf(&table[0]);
    table[1] = *p;
    for (int i = 2; i < 16; i++) g2_add(&table[i], &table[i - 1], p);
    g2p acc;
    g2_set_inf(&acc);
    for (int nib = 63; nib >= 0; nib--) {
        for (int k = 0; k < 4; k++) g2_dbl(&acc, &acc);
        unsigned idx = (unsigned)((sc[nib / 16] >> ((nib % 16) * 4)) & 0xF);
        if (idx) g2_add(&acc, &acc, &table[idx]);
    }
    *r = acc;
}

/* arbitrary-length big-endian scalar multiplication (nibble windows) —
 * covers the 636-bit h_eff cofactor clearing of hash-to-G2. */
static void g1_mul_be(g1p *r, const g1p *p, const uint8_t *be, uint64_t len) {
    g1p table[16];
    g1_set_inf(&table[0]);
    table[1] = *p;
    for (int i = 2; i < 16; i++) g1_add(&table[i], &table[i - 1], p);
    g1p acc;
    g1_set_inf(&acc);
    for (uint64_t i = 0; i < len; i++) {
        for (int half = 1; half >= 0; half--) {
            unsigned nib = half ? (be[i] >> 4) : (be[i] & 0xF);
            for (int k = 0; k < 4; k++) g1_dbl(&acc, &acc);
            if (nib) g1_add(&acc, &acc, &table[nib]);
        }
    }
    *r = acc;
}

static void g2_mul_be(g2p *r, const g2p *p, const uint8_t *be, uint64_t len) {
    g2p table[16];
    g2_set_inf(&table[0]);
    table[1] = *p;
    for (int i = 2; i < 16; i++) g2_add(&table[i], &table[i - 1], p);
    g2p acc;
    g2_set_inf(&acc);
    for (uint64_t i = 0; i < len; i++) {
        for (int half = 1; half >= 0; half--) {
            unsigned nib = half ? (be[i] >> 4) : (be[i] & 0xF);
            for (int k = 0; k < 4; k++) g2_dbl(&acc, &acc);
            if (nib) g2_add(&acc, &acc, &table[nib]);
        }
    }
    *r = acc;
}

/* ------------------------------------------------------------ pairing --- */

/* The Miller loop runs with the G2 point kept in affine coordinates on the
 * twisted curve E'(Fp2).  For the untwist (x, y) -> (x w^-2, y w^-3) the
 * tangent/chord slope of the untwisted point is lambda' * w^-1 with
 * lambda' the slope on E', so the line through the untwisted T evaluated
 * at an embedded G1 point (px, py) is (using w^-k = w^(6-k) * xi^-1):
 *
 *     l = py + (lambda'*tx - ty) xi^-1 w^3 - lambda' px xi^-1 w^5
 *
 * — a sparse Fp12 element with coefficients only at w^0 (Fp), w^3, w^5.
 * This is algebraically identical to the Python oracle's generic-Fp12
 * line (crypto/pairing.py), so the Miller value matches bit-for-bit. */

static fp2 XI_INV; /* (1+u)^-1 — set in init */
static fp2 FROB1_G[6]; /* gamma1_i = xi^(i(p-1)/6) — set in init */
static fp2 PSI_X, PSI_Y; /* untwist-frobenius-twist constants — set in init */

/* f *= l where l = py + a3 w^3 + a5 w^5 (py in Fp; a3, a5 in Fp2).
 * Coefficient slots: w^0 -> c0.c0, w^3 -> c1.c1, w^5 -> c1.c2, so
 * l.c0 = (py, 0, 0) and l.c1 = (0, a3, a5). */
static void fp12_mul_line(fp12 *f, const fp *py, const fp2 *a3, const fp2 *a5) {
    fp6 l1_f0, l1_f1, t;
    /* l.c1 * f->c0 and l.c1 * f->c1 with l.c1 = (0, a3, a5):
     * (a0,a1,a2)*(0,b1,b2) = (xi(a1 b2 + a2 b1), a0 b1 + xi a2 b2, a0 b2 + a1 b1) */
    fp2 u, v;
#define SPARSE6(dst, src) \
    do { \
        fp2_mul(&u, &(src)->c1, a5); \
        fp2_mul(&v, &(src)->c2, a3); \
        fp2_add(&u, &u, &v); \
        fp2_mul_xi(&(dst).c0, &u); \
        fp2_mul(&u, &(src)->c0, a3); \
        fp2_mul(&v, &(src)->c2, a5); \
        fp2_mul_xi(&v, &v); \
        fp2_add(&(dst).c1, &u, &v); \
        fp2_mul(&u, &(src)->c0, a5); \
        fp2_mul(&v, &(src)->c1, a3); \
        fp2_add(&(dst).c2, &u, &v); \
    } while (0)
    SPARSE6(l1_f0, &f->c0);
    SPARSE6(l1_f1, &f->c1);
#undef SPARSE6
    /* r.c0 = py*f.c0 + v*(f.c1 * l.c1);  r.c1 = py*f.c1 + f.c0 * l.c1 */
    fp6 c0, c1;
    fp2_mul_fp(&c0.c0, &f->c0.c0, py);
    fp2_mul_fp(&c0.c1, &f->c0.c1, py);
    fp2_mul_fp(&c0.c2, &f->c0.c2, py);
    fp6_mul_v(&t, &l1_f1);
    fp6_add(&c0, &c0, &t);
    fp2_mul_fp(&c1.c0, &f->c1.c0, py);
    fp2_mul_fp(&c1.c1, &f->c1.c1, py);
    fp2_mul_fp(&c1.c2, &f->c1.c2, py);
    fp6_add(&c1, &c1, &l1_f0);
    f->c0 = c0;
    f->c1 = c1;
}

/* f *= l for a vertical line l = px - tx w^4 xi^-1 (w^4 -> c0.c2 slot). */
static void fp12_mul_vline(fp12 *f, const fp *px, const fp2 *a4) {
    /* l.c0 = (px, 0, a4), l.c1 = 0:
     * (a0,a1,a2)*(b0,0,b2) = (a0 b0 + xi(a1 b2), a1 b0 + xi a2 b2, a2 b0 + a0 b2) */
    fp6 c0, c1;
    fp2 u, v;
#define VSPARSE6(dst, src) \
    do { \
        fp2_mul_fp(&u, &(src)->c0, px); \
        fp2_mul(&v, &(src)->c1, a4); \
        fp2_mul_xi(&v, &v); \
        fp2_add(&(dst).c0, &u, &v); \
        fp2_mul_fp(&u, &(src)->c1, px); \
        fp2_mul(&v, &(src)->c2, a4); \
        fp2_mul_xi(&v, &v); \
        fp2_add(&(dst).c1, &u, &v); \
        fp2_mul_fp(&u, &(src)->c2, px); \
        fp2_mul(&v, &(src)->c0, a4); \
        fp2_add(&(dst).c2, &u, &v); \
    } while (0)
    VSPARSE6(c0, &f->c0);
    VSPARSE6(c1, &f->c1);
#undef VSPARSE6
    f->c0 = c0;
    f->c1 = c1;
}

/* T on E'(Fp2), affine with infinity flag. */
typedef struct { fp2 x, y; int inf; } e2a;

/* shared tail of a Miller step once lambda' is known: multiply the line
 * into f and move T to (lam^2 - tx - ox, lam(tx - x3) - ty). */
static void miller_apply(fp12 *f, e2a *t, const fp2 *lam, const fp2 *other_x,
                         const fp *px, const fp *py) {
    fp2 a3, a5, tmp, x3, y3;
    /* a3 = (lam*tx - ty) * xi^-1;  a5 = -lam*px * xi^-1 */
    fp2_mul(&a3, lam, &t->x);
    fp2_sub(&a3, &a3, &t->y);
    fp2_mul(&a3, &a3, &XI_INV);
    fp2_mul_fp(&a5, lam, px);
    fp2_neg(&a5, &a5);
    fp2_mul(&a5, &a5, &XI_INV);
    fp12_mul_line(f, py, &a3, &a5);
    fp2_sqr(&x3, lam);
    fp2_sub(&x3, &x3, &t->x);
    fp2_sub(&x3, &x3, other_x);
    fp2_sub(&tmp, &t->x, &x3);
    fp2_mul(&y3, lam, &tmp);
    fp2_sub(&y3, &y3, &t->y);
    t->x = x3;
    t->y = y3;
}

static void tangent_lambda(fp2 *lam, const e2a *t) {
    fp2 num, den;
    fp2_sqr(&num, &t->x);
    fp2_add(&den, &num, &num);
    fp2_add(&num, &den, &num); /* 3 x^2 */
    fp2_add(&den, &t->y, &t->y);
    fp2_inv(&den, &den);
    fp2_mul(lam, &num, &den);
}

static void miller_step_dbl(fp12 *f, e2a *t, const fp *px, const fp *py) {
    fp12_sqr(f, f);
    if (t->inf) return;
    fp2 lam;
    tangent_lambda(&lam, t);
    fp2 tx = t->x;
    miller_apply(f, t, &lam, &tx, px, py);
}

static void miller_step_add(fp12 *f, e2a *t, const e2a *q,
                            const fp *px, const fp *py) {
    if (t->inf) { *t = *q; return; }
    if (q->inf) return;
    fp2 lam;
    if (fp2_eq(&t->x, &q->x)) {
        if (!fp2_eq(&t->y, &q->y)) {
            /* vertical: l = px - tx w^4 xi^-1, then t + q = O */
            fp2 a4;
            fp2_mul(&a4, &t->x, &XI_INV);
            fp2_neg(&a4, &a4);
            fp12_mul_vline(f, px, &a4);
            t->inf = 1;
            return;
        }
        tangent_lambda(&lam, t);
    } else {
        fp2 dy, dx;
        fp2_sub(&dy, &q->y, &t->y);
        fp2_sub(&dx, &q->x, &t->x);
        fp2_inv(&dx, &dx);
        fp2_mul(&lam, &dy, &dx);
    }
    miller_apply(f, t, &lam, &q->x, px, py);
}

/* Miller loop f_{|x|,Q}(P), conjugated for x < 0.  P affine in G1,
 * Q affine in G2 (coords in Fp2 on the twist).  Step ordering mirrors
 * crypto/pairing.py (tangent at pre-doubling t; addition chord through
 * (t_new, q)), so the Fp12 value matches the Python oracle exactly. */
static void miller_loop(fp12 *f, const fp *p1x, const fp *p1y, int p1_inf,
                        const fp2 *q2x, const fp2 *q2y, int q2_inf) {
    fp12_one(f);
    if (p1_inf || q2_inf) return;
    e2a q = { *q2x, *q2y, 0 }, t = q;
    for (int bit = 62; bit >= 0; bit--) {
        miller_step_dbl(f, &t, p1x, p1y);
        if ((BLS_X_ABS >> bit) & 1) miller_step_add(f, &t, &q, p1x, p1y);
    }
    fp12 c;
    fp12_conj(&c, f);
    *f = c;
}

static void fp12_frob1(fp12 *r, const fp12 *a) {
    fp2 *rc[6] = { &r->c0.c0, &r->c1.c0, &r->c0.c1, &r->c1.c1, &r->c0.c2, &r->c1.c2 };
    const fp2 *ac[6] = { &a->c0.c0, &a->c1.c0, &a->c0.c1, &a->c1.c1, &a->c0.c2, &a->c1.c2 };
    for (int i = 0; i < 6; i++) {
        fp2 c;
        fp2_conj(&c, ac[i]);
        fp2_mul(rc[i], &c, &FROB1_G[i]);
    }
}

/* f^x for the (negative) BLS parameter; valid in the cyclotomic subgroup
 * where inversion is conjugation. */
static void fp12_powx(fp12 *r, const fp12 *f) {
    fp12 acc = *f;
    for (int bit = 62; bit >= 0; bit--) {
        fp12_sqr(&acc, &acc);
        if ((BLS_X_ABS >> bit) & 1) fp12_mul(&acc, &acc, f);
    }
    fp12_conj(r, &acc);
}

/* shared easy part: f^((p^6-1)(p^2+1)) */
static void final_exp_easy(fp12 *r, const fp12 *f) {
    fp12 c, i, t, u;
    fp12_conj(&c, f);
    fp12_inv(&i, f);
    fp12_mul(&t, &c, &i);
    fp12_frob2(&u, &t);
    fp12_mul(r, &u, &t);
}

/* exact final exponentiation (naive hard part) — used where the GT value
 * itself is exported and must equal the Python oracle. */
static void final_exponentiation(fp12 *r, const fp12 *f) {
    fp12 t;
    final_exp_easy(&t, f);
    fp12_pow_limbs(r, &t, HARD_EXP, HARD_EXP_LIMBS, HARD_EXP_BITS);
}

/* fast membership check: computes m^(3*hard) via
 * 3H = (x-1)^2 (x+p)(x^2+p^2-1) + 3 (verified in gen_bls_consts.py);
 * since gcd(3, r) = 1 this is 1 iff m^H is 1. */
static int final_exp_is_one_fast(const fp12 *f) {
    fp12 m, a, b, c, d, e, g, t;
    final_exp_easy(&m, f);
    fp12_powx(&a, &m);
    fp12_conj(&t, &m);
    fp12_mul(&a, &a, &t); /* m^(x-1) */
    fp12_powx(&b, &a);
    fp12_conj(&t, &a);
    fp12_mul(&b, &b, &t); /* m^((x-1)^2) */
    fp12_powx(&c, &b);
    fp12_frob1(&t, &b);
    fp12_mul(&c, &c, &t); /* b^(x+p) */
    fp12_powx(&d, &c);
    fp12_powx(&d, &d); /* c^(x^2) */
    fp12_frob2(&e, &c); /* c^(p^2) */
    fp12_mul(&g, &d, &e);
    fp12_conj(&t, &c);
    fp12_mul(&g, &g, &t); /* c^(x^2+p^2-1) */
    /* times m^3 */
    fp12_sqr(&t, &m);
    fp12_mul(&t, &t, &m);
    fp12_mul(&g, &g, &t);
    return fp12_is_one(&g);
}

/* --------------------------------------------------------------- init --- */

static int g_initialized = 0;
static fp C390; /* raw residue 2^390 mod p — set in init */

/* Runs at dlopen time (single-threaded, before ctypes returns the handle),
 * so no caller can ever observe partially-built Frobenius/psi tables even
 * though ctypes releases the GIL around calls. ensure_init() stays as a
 * belt-and-braces guard for non-dlopen embeddings. */
__attribute__((constructor)) static void bls_init_ctor(void);

static void ensure_init(void) {
    if (g_initialized) return;
    /* gamma powers for frobenius^2 */
    fp gamma;
    fp_from_plain(&gamma, FROB2_GAMMA);
    fp_one(&FROB2_POWS[0]);
    for (int i = 1; i < 6; i++) fp_mul(&FROB2_POWS[i], &FROB2_POWS[i - 1], &gamma);
    fp_from_plain(&XI_INV.c0, XI_INV_C0);
    fp_from_plain(&XI_INV.c1, XI_INV_C1);
    const uint64_t *g1c[6][2] = {
        {FROB1_G0_C0, FROB1_G0_C1}, {FROB1_G1_C0, FROB1_G1_C1},
        {FROB1_G2_C0, FROB1_G2_C1}, {FROB1_G3_C0, FROB1_G3_C1},
        {FROB1_G4_C0, FROB1_G4_C1}, {FROB1_G5_C0, FROB1_G5_C1},
    };
    for (int i = 0; i < 6; i++) {
        fp_from_plain(&FROB1_G[i].c0, g1c[i][0]);
        fp_from_plain(&FROB1_G[i].c1, g1c[i][1]);
    }
    fp_from_plain(&PSI_X.c0, PSI_X_C0);
    fp_from_plain(&PSI_X.c1, PSI_X_C1);
    fp_from_plain(&PSI_Y.c0, PSI_Y_C0);
    fp_from_plain(&PSI_Y.c1, PSI_Y_C1);
    {
        /* C390 holds the RAW value 2^390 mod p: fp_from_plain(64) computes
         * 64*2^384 mod p and stores it without a final from-Montgomery
         * step, which is exactly the plain residue 2^390 mod p.  Used to
         * emit values in the device kernel's 2^390-Montgomery encoding
         * (ops/lazy_limbs.py R = 2^390) with a single fp_mul. */
        uint64_t sixty_four[6] = {64, 0, 0, 0, 0, 0};
        fp_from_plain(&C390, sixty_four);
    }
    g_initialized = 1;
}

__attribute__((constructor)) static void bls_init_ctor(void) { ensure_init(); }

/* ------------------------------------------------------- byte helpers --- */

static void scalar_from_be32(uint64_t out[4], const uint8_t in[32]) {
    for (int i = 0; i < 4; i++) {
        uint64_t v = 0;
        const uint8_t *p = in + (3 - i) * 8;
        for (int j = 0; j < 8; j++) v = (v << 8) | p[j];
        out[i] = v;
    }
}

static void g1_load(fp *x, fp *y, const uint8_t in[96]) {
    fp_from_be(x, in);
    fp_from_be(y, in + 48);
}

static void g1_store(uint8_t out[96], const fp *x, const fp *y) {
    fp_to_be(out, x);
    fp_to_be(out + 48, y);
}

static void g2_load(fp2 *x, fp2 *y, const uint8_t in[192]) {
    fp_from_be(&x->c0, in);
    fp_from_be(&x->c1, in + 48);
    fp_from_be(&y->c0, in + 96);
    fp_from_be(&y->c1, in + 144);
}

static void g2_store(uint8_t out[192], const fp2 *x, const fp2 *y) {
    fp_to_be(out, &x->c0);
    fp_to_be(out + 48, &x->c1);
    fp_to_be(out + 96, &y->c0);
    fp_to_be(out + 144, &y->c1);
}

/* ------------------------------------------------------------ exports --- */

void bls_g1_mul(const uint8_t in[96], uint8_t in_inf, const uint8_t scalar[32],
                uint8_t out[96], uint8_t *out_inf) {
    ensure_init();
    uint64_t sc[4];
    scalar_from_be32(sc, scalar);
    if (in_inf) { memset(out, 0, 96); *out_inf = 1; return; }
    fp x, y;
    g1_load(&x, &y, in);
    g1p p, r;
    g1_from_affine(&p, &x, &y);
    g1_mul_scalar(&r, &p, sc);
    int inf;
    g1_to_affine(&x, &y, &inf, &r);
    *out_inf = (uint8_t)inf;
    g1_store(out, &x, &y);
}

void bls_g2_mul(const uint8_t in[192], uint8_t in_inf, const uint8_t scalar[32],
                uint8_t out[192], uint8_t *out_inf) {
    ensure_init();
    uint64_t sc[4];
    scalar_from_be32(sc, scalar);
    if (in_inf) { memset(out, 0, 192); *out_inf = 1; return; }
    fp2 x, y;
    g2_load(&x, &y, in);
    g2p p, r;
    g2_from_affine(&p, &x, &y);
    g2_mul_scalar(&r, &p, sc);
    int inf;
    g2_to_affine(&x, &y, &inf, &r);
    *out_inf = (uint8_t)inf;
    g2_store(out, &x, &y);
}

void bls_g1_aggregate(uint64_t n, const uint8_t *pts, const uint8_t *infs,
                      uint8_t out[96], uint8_t *out_inf) {
    ensure_init();
    g1p acc;
    g1_set_inf(&acc);
    for (uint64_t i = 0; i < n; i++) {
        if (infs[i]) continue;
        fp x, y;
        g1_load(&x, &y, pts + 96 * i);
        g1p p;
        g1_from_affine(&p, &x, &y);
        g1_add(&acc, &acc, &p);
    }
    fp x, y;
    int inf;
    g1_to_affine(&x, &y, &inf, &acc);
    *out_inf = (uint8_t)inf;
    g1_store(out, &x, &y);
}

void bls_g2_aggregate(uint64_t n, const uint8_t *pts, const uint8_t *infs,
                      uint8_t out[192], uint8_t *out_inf) {
    ensure_init();
    g2p acc;
    g2_set_inf(&acc);
    for (uint64_t i = 0; i < n; i++) {
        if (infs[i]) continue;
        fp2 x, y;
        g2_load(&x, &y, pts + 192 * i);
        g2p p;
        g2_from_affine(&p, &x, &y);
        g2_add(&acc, &acc, &p);
    }
    fp2 x, y;
    int inf;
    g2_to_affine(&x, &y, &inf, &acc);
    *out_inf = (uint8_t)inf;
    g2_store(out, &x, &y);
}

static unsigned msm_window(uint64_t n) {
    if (n < 4) return 2;
    if (n < 16) return 4;
    if (n < 128) return 6;
    if (n < 1024) return 9;
    return 12;
}

void bls_g1_msm(uint64_t n, const uint8_t *pts, const uint8_t *infs,
                const uint8_t *scalars, uint8_t out[96], uint8_t *out_inf) {
    ensure_init();
    unsigned c = msm_window(n);
    unsigned nbuckets = (1u << c) - 1;
    g1p *points = malloc(n * sizeof(g1p));
    uint64_t (*scs)[4] = malloc(n * sizeof(*scs));
    g1p *buckets = malloc(nbuckets * sizeof(g1p));
    for (uint64_t i = 0; i < n; i++) {
        if (infs[i]) { g1_set_inf(&points[i]); memset(scs[i], 0, 32); continue; }
        fp x, y;
        g1_load(&x, &y, pts + 96 * i);
        g1_from_affine(&points[i], &x, &y);
        scalar_from_be32(scs[i], scalars + 32 * i);
    }
    g1p result;
    g1_set_inf(&result);
    int nwin = (256 + c - 1) / c;
    for (int win = nwin - 1; win >= 0; win--) {
        for (unsigned k = 0; k < c; k++) g1_dbl(&result, &result);
        for (unsigned b = 0; b < nbuckets; b++) g1_set_inf(&buckets[b]);
        unsigned lo = win * c;
        for (uint64_t i = 0; i < n; i++) {
            if (g1_is_inf(&points[i])) continue;
            unsigned idx = 0;
            for (unsigned b = 0; b < c; b++) {
                unsigned bit = lo + b;
                if (bit < 256 && ((scs[i][bit / 64] >> (bit % 64)) & 1)) idx |= 1u << b;
            }
            if (idx) g1_add(&buckets[idx - 1], &buckets[idx - 1], &points[i]);
        }
        g1p running, acc;
        g1_set_inf(&running);
        g1_set_inf(&acc);
        for (int b = (int)nbuckets - 1; b >= 0; b--) {
            g1_add(&running, &running, &buckets[b]);
            g1_add(&acc, &acc, &running);
        }
        g1_add(&result, &result, &acc);
    }
    free(points);
    free(scs);
    free(buckets);
    fp x, y;
    int inf;
    g1_to_affine(&x, &y, &inf, &result);
    *out_inf = (uint8_t)inf;
    g1_store(out, &x, &y);
}

void bls_g2_msm(uint64_t n, const uint8_t *pts, const uint8_t *infs,
                const uint8_t *scalars, uint8_t out[192], uint8_t *out_inf) {
    ensure_init();
    unsigned c = msm_window(n);
    unsigned nbuckets = (1u << c) - 1;
    g2p *points = malloc(n * sizeof(g2p));
    uint64_t (*scs)[4] = malloc(n * sizeof(*scs));
    g2p *buckets = malloc(nbuckets * sizeof(g2p));
    for (uint64_t i = 0; i < n; i++) {
        if (infs[i]) { g2_set_inf(&points[i]); memset(scs[i], 0, 32); continue; }
        fp2 x, y;
        g2_load(&x, &y, pts + 192 * i);
        g2_from_affine(&points[i], &x, &y);
        scalar_from_be32(scs[i], scalars + 32 * i);
    }
    g2p result;
    g2_set_inf(&result);
    int nwin = (256 + c - 1) / c;
    for (int win = nwin - 1; win >= 0; win--) {
        for (unsigned k = 0; k < c; k++) g2_dbl(&result, &result);
        for (unsigned b = 0; b < nbuckets; b++) g2_set_inf(&buckets[b]);
        unsigned lo = win * c;
        for (uint64_t i = 0; i < n; i++) {
            if (g2_is_inf(&points[i])) continue;
            unsigned idx = 0;
            for (unsigned b = 0; b < c; b++) {
                unsigned bit = lo + b;
                if (bit < 256 && ((scs[i][bit / 64] >> (bit % 64)) & 1)) idx |= 1u << b;
            }
            if (idx) g2_add(&buckets[idx - 1], &buckets[idx - 1], &points[i]);
        }
        g2p running, acc;
        g2_set_inf(&running);
        g2_set_inf(&acc);
        for (int b = (int)nbuckets - 1; b >= 0; b--) {
            g2_add(&running, &running, &buckets[b]);
            g2_add(&acc, &acc, &running);
        }
        g2_add(&result, &result, &acc);
    }
    free(points);
    free(scs);
    free(buckets);
    fp2 x, y;
    int inf;
    g2_to_affine(&x, &y, &inf, &result);
    *out_inf = (uint8_t)inf;
    g2_store(out, &x, &y);
}

int bls_g1_in_subgroup(const uint8_t in[96]) {
    ensure_init();
    fp x, y;
    g1_load(&x, &y, in);
    g1p p, r;
    g1_from_affine(&p, &x, &y);
    uint64_t order[4];
    memcpy(order, CURVE_ORDER_R, sizeof order);
    g1_mul_scalar(&r, &p, order);
    return g1_is_inf(&r);
}

/* psi(x, y) = (conj(x) * PSI_X, conj(y) * PSI_Y) on E'(Fp2). */
static void g2_psi(fp2 *rx, fp2 *ry, const fp2 *x, const fp2 *y) {
    fp2 cx, cy;
    fp2_conj(&cx, x);
    fp2_conj(&cy, y);
    fp2_mul(rx, &cx, &PSI_X);
    fp2_mul(ry, &cy, &PSI_Y);
}

/* psi on Jacobian coordinates: X/Z^2, Y/Z^3 transform coordinate-wise
 * under conj (a field automorphism), so (conj(X)*PSI_X, conj(Y)*PSI_Y,
 * conj(Z)) represents psi of the affine point — no inversion needed. */
static void g2_psi_jac(g2p *r, const g2p *p) {
    if (g2_is_inf(p)) { g2_set_inf(r); return; }
    fp2 cx, cy, cz;
    fp2_conj(&cx, &p->X);
    fp2_conj(&cy, &p->Y);
    fp2_conj(&cz, &p->Z);
    fp2_mul(&r->X, &cx, &PSI_X);
    fp2_mul(&r->Y, &cy, &PSI_Y);
    r->Z = cz;
}

/* [|x|]P by plain double-and-add: the BLS parameter has Hamming weight 6
 * (bits 63,62,60,57,48,16), so 63 doublings + 5 additions with no window
 * table — ~40% fewer point ops than the generic nibble-window path. */
static void g2_mul_z(g2p *r, const g2p *p) {
    g2p acc = *p;
    for (int bit = 62; bit >= 0; bit--) {
        g2_dbl(&acc, &acc);
        if ((BLS_X_ABS >> bit) & 1) g2_add(&acc, &acc, p);
    }
    *r = acc;
}

/* Bowe's criterion: Q in G2 iff psi(Q) == [x]Q (x the negative BLS
 * parameter), i.e. psi(Q) == -[|x|]Q.  ~4x cheaper than mul-by-r. */
int bls_g2_in_subgroup(const uint8_t in[192]) {
    ensure_init();
    fp2 x, y, px, py;
    g2_load(&x, &y, in);
    g2_psi(&px, &py, &x, &y);
    g2p p, r;
    g2_from_affine(&p, &x, &y);
    g2_mul_z(&r, &p);
    fp2 rx, ry;
    int inf;
    g2_to_affine(&rx, &ry, &inf, &r);
    if (inf) return 0; /* [|x|]Q = O can't equal psi(Q) of a finite Q */
    fp2_neg(&ry, &ry); /* -[|x|]Q */
    return fp2_eq(&rx, &px) && fp2_eq(&ry, &py);
}

/* Budroni-Pintore cofactor clearing, exactly equal to [h_eff]Q on E2:
 * [x^2-x-1]Q + [x-1]psi(Q) + psi^2([2]Q), x < 0, so with z = |x|:
 * [z^2+z-1]Q + [z+1](-psi(Q)) + psi^2([2]Q). */
void bls_g2_clear_cofactor(const uint8_t in[192], uint8_t out[192], uint8_t *out_inf) {
    ensure_init();
    fp2 x, y;
    g2_load(&x, &y, in);
    g2p q, acc;
    g2_from_affine(&q, &x, &y);
    /* Shared-ladder decomposition of the same group element:
     *   [z^2+z-1]Q = [z][z]Q + [z]Q - Q,  [z+1](-psi(Q)) = -psi([z+1]Q)
     * (psi is an endomorphism), so two plain [z]-ladders (HW(z)=6) plus
     * a handful of adds replace the previous 128-bit + 64-bit windowed
     * scalar muls — ~45% fewer point operations for the identical result. */
    g2p a, b, apq, t;
    g2_mul_z(&a, &q);  /* [z]Q */
    g2_mul_z(&b, &a);  /* [z^2]Q */
    g2_add(&apq, &a, &q); /* [z+1]Q */
    g2_psi_jac(&t, &apq); /* psi([z+1]Q) */
    /* acc = b + a - q - t */
    g2p nq = q, nt = t;
    fp2_neg(&nq.Y, &q.Y);
    fp2_neg(&nt.Y, &t.Y);
    g2_add(&acc, &b, &a);
    g2_add(&acc, &acc, &nq);
    g2_add(&acc, &acc, &nt);
    /* + psi^2([2]Q) */
    g2p dq, p2;
    g2_dbl(&dq, &q);
    g2_psi_jac(&p2, &dq);
    g2_psi_jac(&p2, &p2);
    g2_add(&acc, &acc, &p2);
    fp2 ox, oy;
    int inf;
    g2_to_affine(&ox, &oy, &inf, &acc);
    *out_inf = (uint8_t)inf;
    g2_store(out, &ox, &oy);
}

/* G2 decompression: x from the 96-byte IETF compressed form, y via
 * fp2_sqrt + the lexicographic-largest flag, then the psi-based subgroup
 * check. Returns 1 ok / 0 malformed; out is the 192-byte affine point,
 * out_inf set for the canonical infinity encoding. */
int bls_g2_decompress(const uint8_t in[96], uint8_t out[192], uint8_t *out_inf) {
    ensure_init();
    int flags = in[0];
    if (!(flags & 0x80)) return 0;
    if (flags & 0x40) {
        if (flags & 0x3F) return 0;
        for (int i = 1; i < 96; i++)
            if (in[i]) return 0;
        memset(out, 0, 192);
        *out_inf = 1;
        return 1;
    }
    uint8_t xb[96];
    memcpy(xb, in, 96);
    xb[0] &= 0x1F;
    /* canonical-range check BEFORE the Montgomery conversion */
    {
        /* compare both 48-byte limbs against p big-endian */
        uint8_t pbe[48];
        for (int i = 0; i < 6; i++)
            for (int j = 0; j < 8; j++)
                pbe[48 - 1 - (8 * i + j)] = (uint8_t)(FP_P[i] >> (8 * j));
        if (memcmp(xb, pbe, 48) >= 0) return 0;      /* x.c1 (imaginary first) */
        if (memcmp(in + 48, pbe, 48) >= 0) return 0; /* x.c0 */
    }
    fp2 x, y2, y;
    /* serialization order: c1 (imaginary) first, then c0 */
    fp_from_be(&x.c1, xb);
    fp_from_be(&x.c0, in + 48);
    /* y^2 = x^3 + B2 with B2 = 4 + 4u (Montgomery 4 built from one) */
    fp2 t, b2;
    fp2_sqr(&t, &x);
    fp2_mul(&y2, &t, &x);
    {
        fp four;
        fp_one(&four);
        fp_add(&four, &four, &four);
        fp_add(&four, &four, &four);
        b2.c0 = four;
        b2.c1 = four;
    }
    fp2_add(&y2, &y2, &b2);
    if (!fp2_sqrt(&y, &y2)) return 0;
    /* lexicographic-largest flag: compare c1 first (imaginary most
     * significant), then c0, against (p-1)/2 — in canonical form */
    {
        uint8_t yb[96];
        fp_to_be(yb, &y.c1);
        fp_to_be(yb + 48, &y.c0);
        /* (p-1)/2 = p >> 1 (p odd) */
        uint64_t half[6];
        for (int i = 0; i < 6; i++) {
            half[i] = FP_P[i] >> 1;
            if (i < 5) half[i] |= FP_P[i + 1] << 63;
        }
        uint8_t halfbe[48];
        for (int i = 0; i < 6; i++)
            for (int j = 0; j < 8; j++)
                halfbe[48 - 1 - (8 * i + j)] = (uint8_t)(half[i] >> (8 * j));
        int is_zero_c1 = 1;
        for (int i = 0; i < 48; i++)
            if (yb[i]) { is_zero_c1 = 0; break; }
        int largest;
        if (!is_zero_c1)
            largest = memcmp(yb, halfbe, 48) > 0;
        else
            largest = memcmp(yb + 48, halfbe, 48) > 0;
        int want = (flags & 0x20) ? 1 : 0;
        if (largest != want) fp2_neg(&y, &y);
    }
    /* subgroup membership (psi check) */
    {
        uint8_t tmp[192];
        g2_store(tmp, &x, &y);
        if (!bls_g2_in_subgroup(tmp)) return 0;
    }
    g2_store(out, &x, &y);
    *out_inf = 0;
    return 1;
}

/* --------------------- RFC 9380 G2 map stage (SSWU + 3-isogeny) ---------
 * The hash-to-field half (expand_message_xmd) stays in Python (hashlib's
 * C SHA-256 is already fast); this entry performs everything after it:
 * SSWU on E2' for both field elements, addition on E2', the 3-isogeny to
 * E2, and Budroni-Pintore cofactor clearing. Ciphersuite parameters are
 * marshaled once from the Python side, whose copies are structurally
 * validated at import (crypto/hash_to_curve.py _validate_ciphersuite);
 * cross-check tests keep the two paths bit-identical. */

static fp2 MAP_A, MAP_B, MAP_Z;
static fp2 MAP_K[15]; /* K1[0..3], K2[0..2], K3[0..3], K4[0..3] */
static int map_params_set = 0;

void bls_g2_map_set_params(const uint8_t *in /* 18 * 96 bytes */) {
    ensure_init();
    fp2 *dst3[3] = {&MAP_A, &MAP_B, &MAP_Z};
    const uint8_t *p = in;
    for (int i = 0; i < 3; i++, p += 96) {
        fp_from_be(&dst3[i]->c0, p);
        fp_from_be(&dst3[i]->c1, p + 48);
    }
    for (int i = 0; i < 15; i++, p += 96) {
        fp_from_be(&MAP_K[i].c0, p);
        fp_from_be(&MAP_K[i].c1, p + 48);
    }
    map_params_set = 1;
}

/* RFC 9380 section 4.1 sgn0 for m=2: parity of the first nonzero limb
 * (parity read from the canonical, non-Montgomery representation). */
static int fp2_sgn0(const fp2 *a) {
    uint8_t b0[48], b1[48];
    fp_to_be(b0, &a->c0);
    fp_to_be(b1, &a->c1);
    int zero0 = 1;
    for (int i = 0; i < 48; i++)
        if (b0[i]) { zero0 = 0; break; }
    int s0 = b0[47] & 1;
    int s1 = b1[47] & 1;
    return s0 | (zero0 & s1);
}

/* Simplified SWU on E2' (RFC 9380 section 6.6.2), affine output. */
static void g2_sswu(fp2 *xo, fp2 *yo, const fp2 *u) {
    fp2 one, u2, tv1, tv2, t, x1, gx1, y;
    fp2_one(&one);
    fp2_sqr(&u2, u);
    fp2_mul(&tv1, &MAP_Z, &u2);
    fp2_sqr(&t, &tv1);
    fp2_add(&tv2, &t, &tv1);
    if (fp2_is_zero(&tv2)) {
        fp2 za, zai;
        fp2_mul(&za, &MAP_Z, &MAP_A);
        fp2_inv(&zai, &za);
        fp2_mul(&x1, &MAP_B, &zai);
    } else {
        fp2 tv2i, s, nb, ai;
        fp2_inv(&tv2i, &tv2);
        fp2_add(&s, &one, &tv2i);
        fp2_neg(&nb, &MAP_B);
        fp2_inv(&ai, &MAP_A);
        fp2_mul(&t, &nb, &ai);
        fp2_mul(&x1, &t, &s);
    }
    fp2_sqr(&t, &x1);
    fp2_add(&t, &t, &MAP_A);
    fp2_mul(&gx1, &t, &x1);
    fp2_add(&gx1, &gx1, &MAP_B);
    if (fp2_sqrt(&y, &gx1)) {
        *xo = x1;
    } else {
        fp2 x2, gx2;
        fp2_mul(&x2, &tv1, &x1);
        fp2_sqr(&t, &x2);
        fp2_add(&t, &t, &MAP_A);
        fp2_mul(&gx2, &t, &x2);
        fp2_add(&gx2, &gx2, &MAP_B);
        fp2_sqrt(&y, &gx2); /* gx1 non-square implies gx2 square */
        *xo = x2;
    }
    if (fp2_sgn0(u) != fp2_sgn0(&y)) fp2_neg(&y, &y);
    *yo = y;
}

/* Generic affine addition on E2' (a = MAP_A). Returns 0 when the sum is
 * the point at infinity. */
static int eprime_add(fp2 *rx, fp2 *ry, const fp2 *ax, const fp2 *ay,
                      const fp2 *bx, const fp2 *by) {
    fp2 lam, num, den, t;
    if (fp2_eq(ax, bx)) {
        fp2 nby;
        fp2_neg(&nby, by);
        if (fp2_eq(ay, &nby)) return 0;
        /* doubling: lam = (3 x^2 + A) / (2 y) */
        fp2_sqr(&t, ax);
        fp2_add(&num, &t, &t);
        fp2_add(&num, &num, &t);
        fp2_add(&num, &num, &MAP_A);
        fp2_add(&den, ay, ay);
    } else {
        fp2_sub(&num, by, ay);
        fp2_sub(&den, bx, ax);
    }
    fp2 deni;
    fp2_inv(&deni, &den);
    fp2_mul(&lam, &num, &deni);
    fp2 x3, y3;
    fp2_sqr(&x3, &lam);
    fp2_sub(&x3, &x3, ax);
    fp2_sub(&x3, &x3, bx);
    fp2_sub(&t, ax, &x3);
    fp2_mul(&y3, &lam, &t);
    fp2_sub(&y3, &y3, ay);
    *rx = x3;
    *ry = y3;
    return 1;
}

static void fp2_horner(fp2 *r, const fp2 *k, int n, const fp2 *x) {
    *r = k[n - 1];
    for (int i = n - 2; i >= 0; i--) {
        fp2 t;
        fp2_mul(&t, r, x);
        fp2_add(r, &t, &k[i]);
    }
}

/* u_in: u0.c0 | u0.c1 | u1.c0 | u1.c1, 48-byte big-endian canonical each.
 * out: affine E2 point (192 bytes) after cofactor clearing. Returns -1 if
 * parameters were never set, 0 otherwise. */
int bls_g2_map_from_fields(const uint8_t u_in[192], uint8_t out[192],
                           uint8_t *out_inf) {
    ensure_init();
    if (!map_params_set) return -1;
    fp2 u0, u1, x0, y0, x1, y1, rx, ry;
    fp_from_be(&u0.c0, u_in);
    fp_from_be(&u0.c1, u_in + 48);
    fp_from_be(&u1.c0, u_in + 96);
    fp_from_be(&u1.c1, u_in + 144);
    g2_sswu(&x0, &y0, &u0);
    g2_sswu(&x1, &y1, &u1);
    if (!eprime_add(&rx, &ry, &x0, &y0, &x1, &y1)) {
        memset(out, 0, 192);
        *out_inf = 1;
        return 0;
    }
    /* 3-isogeny E2' -> E2 (a homomorphism, so adding before the map equals
     * the per-u mapping followed by addition on E2) */
    fp2 xn, xd, yn, yd;
    fp2_horner(&xn, &MAP_K[0], 4, &rx);
    fp2_horner(&xd, &MAP_K[4], 3, &rx);
    fp2_horner(&yn, &MAP_K[7], 4, &rx);
    fp2_horner(&yd, &MAP_K[11], 4, &rx);
    if (fp2_is_zero(&xd) || fp2_is_zero(&yd)) {
        /* isogeny pole = kernel point: maps to O */
        memset(out, 0, 192);
        *out_inf = 1;
        return 0;
    }
    fp2 xdi, ydi, ex, ey, t;
    fp2_inv(&xdi, &xd);
    fp2_mul(&ex, &xn, &xdi);
    fp2_inv(&ydi, &yd);
    fp2_mul(&t, &ry, &yn);
    fp2_mul(&ey, &t, &ydi);
    uint8_t tmp[192];
    g2_store(tmp, &ex, &ey);
    bls_g2_clear_cofactor(tmp, out, out_inf);
    return 0;
}

int bls_g1_on_curve(const uint8_t in[96]) {
    ensure_init();
    fp x, y, lhs, rhs, b;
    g1_load(&x, &y, in);
    fp_sqr(&lhs, &y);
    fp_sqr(&rhs, &x);
    fp_mul(&rhs, &rhs, &x);
    uint64_t four[6] = {4, 0, 0, 0, 0, 0};
    fp_from_plain(&b, four);
    fp_add(&rhs, &rhs, &b);
    return fp_eq(&lhs, &rhs);
}

int bls_g2_on_curve(const uint8_t in[192]) {
    ensure_init();
    fp2 x, y, lhs, rhs, b;
    g2_load(&x, &y, in);
    fp2_sqr(&lhs, &y);
    fp2_sqr(&rhs, &x);
    fp2_mul(&rhs, &rhs, &x);
    uint64_t four[6] = {4, 0, 0, 0, 0, 0};
    fp_from_plain(&b.c0, four);
    b.c1 = b.c0;
    fp2_add(&rhs, &rhs, &b);
    return fp2_eq(&lhs, &rhs);
}

/* inf_flags[i]: bit0 = G1 point i at infinity, bit1 = G2 point i. */
/* Multi-pairing: one SHARED Miller accumulator for all pairs, so the
 * fp12 squaring per loop iteration is paid once instead of once per pair
 * (the loop bits are identical for every pair; the accumulated product
 * equals the product of per-pair Miller values, and the x<0 conjugation
 * distributes over the product). The affine tangent denominators (2y,
 * never zero in odd-order G2) of all pairs are inverted together with the
 * Montgomery batch trick — 1 inversion + 3(m-1) muls per iteration
 * instead of m inversions. Addition steps keep per-pair inversion: the
 * BLS x parameter has Hamming weight 6, so they are rare. */
typedef struct { fp px, py; e2a q, t; } mpair;

/* In-place batch inversion of m nonzero values (Montgomery trick). */
static void fp2_batch_inv(fp2 *vals, fp2 *scratch, uint64_t m) {
    if (m == 0) return;
    scratch[0] = vals[0];
    for (uint64_t i = 1; i < m; i++) fp2_mul(&scratch[i], &scratch[i - 1], &vals[i]);
    fp2 inv;
    fp2_inv(&inv, &scratch[m - 1]);
    for (uint64_t i = m - 1; i > 0; i--) {
        fp2 t;
        fp2_mul(&t, &inv, &scratch[i - 1]); /* vals[i]^-1 */
        fp2_mul(&inv, &inv, &vals[i]);      /* running inv of prefix */
        vals[i] = t;
    }
    vals[0] = inv;
}

int bls_pairing_check(uint64_t n, const uint8_t *g1s, const uint8_t *g2s,
                      const uint8_t *inf_flags) {
    ensure_init();
    mpair stack_pairs[16];
    fp2 stack_den[2 * 16];
    uint64_t stack_idx[16];
    mpair *pairs = n <= 16 ? stack_pairs : malloc(n * sizeof(mpair));
    fp2 *den = n <= 16 ? stack_den : malloc(2 * n * sizeof(fp2));
    uint64_t *idx = n <= 16 ? stack_idx : malloc(n * sizeof(uint64_t));
    if (pairs == NULL || den == NULL || idx == NULL) {
        /* fail CLOSED: a check that cannot run must never report valid */
        if (pairs != stack_pairs) free(pairs);
        if (den != stack_den) free(den);
        if (idx != stack_idx) free(idx);
        return 0;
    }
    fp2 *scratch = den + n;
    uint64_t live = 0;
    for (uint64_t i = 0; i < n; i++) {
        int g1_inf = inf_flags[i] & 1;
        int g2_inf = (inf_flags[i] >> 1) & 1;
        if (g1_inf || g2_inf) continue;
        mpair *m = &pairs[live++];
        g1_load(&m->px, &m->py, g1s + 96 * i);
        g2_load(&m->q.x, &m->q.y, g2s + 192 * i);
        m->q.inf = 0;
        m->t = m->q;
    }
    fp12 f;
    fp12_one(&f);
    int degenerate = 0;
    for (int bit = 62; bit >= 0; bit--) {
        fp12_sqr(&f, &f);
        /* gather 2y denominators of the still-finite accumulators; a
         * y==0 accumulator (order-2 point, unreachable for subgroup
         * inputs) would poison the whole batch inversion — fail CLOSED */
        uint64_t m = 0;
        for (uint64_t i = 0; i < live; i++) {
            if (pairs[i].t.inf) continue;
            fp2_add(&den[m], &pairs[i].t.y, &pairs[i].t.y);
            if (fp2_is_zero(&den[m])) { degenerate = 1; break; }
            idx[m++] = i;
        }
        if (degenerate) break;
        fp2_batch_inv(den, scratch, m);
        for (uint64_t j = 0; j < m; j++) {
            mpair *p = &pairs[idx[j]];
            fp2 num, t3, lam, tx;
            fp2_sqr(&num, &p->t.x);
            fp2_add(&t3, &num, &num);
            fp2_add(&num, &t3, &num); /* 3 x^2 */
            fp2_mul(&lam, &num, &den[j]);
            tx = p->t.x;
            miller_apply(&f, &p->t, &lam, &tx, &p->px, &p->py);
        }
        if ((BLS_X_ABS >> bit) & 1) {
            for (uint64_t i = 0; i < live; i++) {
                mpair *p = &pairs[i];
                miller_step_add(&f, &p->t, &p->q, &p->px, &p->py);
            }
        }
    }
    if (pairs != stack_pairs) free(pairs);
    if (den != stack_den) free(den);
    if (idx != stack_idx) free(idx);
    if (degenerate) return 0;
    fp12 c;
    fp12_conj(&c, &f);
    return final_exp_is_one_fast(&c);
}

/* Emit a mont-form fp as the device pairing kernel's limb encoding:
 * 15 x 26-bit limbs (little-endian limb order, one u64 per limb) of the
 * plain residue v * 2^390 mod p (lazy_limbs R = 2^390).  One fp_mul by
 * the raw constant 2^390 mod p converts v*2^384 -> plain v*2^390. */
static void fp_to_dev_limbs(uint64_t out[15], const fp *a) {
    fp t;
    fp_mul(&t, a, &C390);
    for (int i = 0; i < 15; i++) {
        int bit = 26 * i, w = bit >> 6, off = bit & 63;
        uint64_t lo = t.l[w] >> off;
        if (off > 38 && w < 5) lo |= t.l[w + 1] << (64 - off);
        out[i] = lo & 0x3FFFFFFULL;
    }
}

static void fp2_to_dev_limbs(uint64_t out[30], const fp2 *a) {
    fp_to_dev_limbs(out, &a->c0);
    fp_to_dev_limbs(out + 15, &a->c1);
}

/* Lockstep affine ate walks for n subgroup G2 points, emitting the
 * per-step line coefficients the device Miller kernel consumes
 * (ops/pairing_device.prepare_g2 computes the same rows one point at a
 * time in Python; this is the batched native producer).  Output layout:
 * out[pair][step][coeff][fq2 c0|c1][15 limbs] with coeff 0 = a3 =
 * (lam*tx - ty)*xi^-1 and coeff 1 = lam*xi^-1, all in the device's
 * 2^390-Montgomery 26-bit limb encoding.  Tangent denominators are
 * inverted with one Montgomery batch inversion per step across all n
 * walks; the (rare) addition steps batch their chord denominators the
 * same way.  Returns the number of steps written per pair, or 0 on a
 * degenerate step (T at infinity / vertical chord — impossible for
 * subgroup inputs; callers fall back to the host oracle). */
uint64_t bls_g2_prepare_many(uint64_t n, const uint8_t *g2s, uint64_t *out) {
    ensure_init();
    if (n == 0) return 0;
    e2a *t = malloc(n * sizeof(e2a));
    e2a *q = malloc(n * sizeof(e2a));
    fp2 *den = malloc(2 * n * sizeof(fp2));
    if (t == NULL || q == NULL || den == NULL) {
        free(t); free(q); free(den);
        return 0;
    }
    fp2 *scratch = den + n;
    for (uint64_t i = 0; i < n; i++) {
        g2_load(&q[i].x, &q[i].y, g2s + 192 * i);
        q[i].inf = 0;
        t[i] = q[i];
    }
    const uint64_t stride = 2 * 2 * 15; /* u64s per (pair, step) */
    uint64_t total_steps = 0; /* 63 doublings + one add per set low bit */
    for (int bit = 62; bit >= 0; bit--)
        total_steps += 1 + ((BLS_X_ABS >> bit) & 1);
    uint64_t n_steps = 0;
    int ok = 1;
    uint64_t step = 0;
    for (int bit = 62; bit >= 0 && ok; bit--) {
        /* doubling: tangent at pre-doubling T.  A y==0 point (order 2)
         * would feed a zero denominator into the batch inversion and emit
         * garbage lines — honor the degenerate-step contract instead. */
        for (uint64_t i = 0; i < n; i++) {
            fp2_add(&den[i], &t[i].y, &t[i].y);
            if (fp2_is_zero(&den[i])) { ok = 0; break; }
        }
        if (!ok) break;
        fp2_batch_inv(den, scratch, n);
        for (uint64_t i = 0; i < n; i++) {
            fp2 num, t3, lam, a3, tmp, x3, y3;
            fp2_sqr(&num, &t[i].x);
            fp2_add(&t3, &num, &num);
            fp2_add(&num, &t3, &num); /* 3 tx^2 */
            fp2_mul(&lam, &num, &den[i]);
            fp2_mul(&a3, &lam, &t[i].x);
            fp2_sub(&a3, &a3, &t[i].y);
            fp2_mul(&a3, &a3, &XI_INV);
            fp2 lam_xi;
            fp2_mul(&lam_xi, &lam, &XI_INV);
            fp2_to_dev_limbs(out + (i * total_steps + step) * stride, &a3);
            fp2_to_dev_limbs(out + (i * total_steps + step) * stride + 30, &lam_xi);
            fp2_sqr(&x3, &lam);
            fp2_sub(&x3, &x3, &t[i].x);
            fp2_sub(&x3, &x3, &t[i].x);
            fp2_sub(&tmp, &t[i].x, &x3);
            fp2_mul(&y3, &lam, &tmp);
            fp2_sub(&y3, &y3, &t[i].y);
            t[i].x = x3;
            t[i].y = y3;
        }
        step++;
        if ((BLS_X_ABS >> bit) & 1) {
            /* addition: chord through post-doubling T and Q */
            for (uint64_t i = 0; i < n; i++) {
                if (fp2_eq(&t[i].x, &q[i].x)) { ok = 0; break; }
                fp2_sub(&den[i], &q[i].x, &t[i].x);
            }
            if (!ok) break;
            fp2_batch_inv(den, scratch, n);
            for (uint64_t i = 0; i < n; i++) {
                fp2 dy, lam, a3, lam_xi, tmp, x3, y3;
                fp2_sub(&dy, &q[i].y, &t[i].y);
                fp2_mul(&lam, &dy, &den[i]);
                fp2_mul(&a3, &lam, &t[i].x);
                fp2_sub(&a3, &a3, &t[i].y);
                fp2_mul(&a3, &a3, &XI_INV);
                fp2_mul(&lam_xi, &lam, &XI_INV);
                fp2_to_dev_limbs(out + (i * total_steps + step) * stride, &a3);
                fp2_to_dev_limbs(out + (i * total_steps + step) * stride + 30, &lam_xi);
                fp2_sqr(&x3, &lam);
                fp2_sub(&x3, &x3, &t[i].x);
                fp2_sub(&x3, &x3, &q[i].x);
                fp2_sub(&tmp, &t[i].x, &x3);
                fp2_mul(&y3, &lam, &tmp);
                fp2_sub(&y3, &y3, &t[i].y);
                t[i].x = x3;
                t[i].y = y3;
            }
            step++;
        }
    }
    n_steps = ok ? step : 0;
    free(t); free(q); free(den);
    return n_steps;
}

/* Single full pairing, result written as 12 * 48 bytes (flattened w^i
 * coefficient order: for i in 0..5 emit coeff_i.c0 then coeff_i.c1). */
void bls_pairing(const uint8_t g1[96], const uint8_t g2[192], uint8_t out[576]) {
    ensure_init();
    fp px, py;
    fp2 qx, qy;
    g1_load(&px, &py, g1);
    g2_load(&qx, &qy, g2);
    fp12 m, r;
    miller_loop(&m, &px, &py, 0, &qx, &qy, 0);
    final_exponentiation(&r, &m);
    const fp2 *coeffs[6] = { &r.c0.c0, &r.c1.c0, &r.c0.c1, &r.c1.c1, &r.c0.c2, &r.c1.c2 };
    for (int i = 0; i < 6; i++) {
        fp_to_be(out + 96 * i, &coeffs[i]->c0);
        fp_to_be(out + 96 * i + 48, &coeffs[i]->c1);
    }
}

void bls_g1_mul_wide(const uint8_t in[96], uint8_t in_inf, const uint8_t *scalar_be,
                     uint64_t sc_len, uint8_t out[96], uint8_t *out_inf) {
    ensure_init();
    if (in_inf) { memset(out, 0, 96); *out_inf = 1; return; }
    fp x, y;
    g1_load(&x, &y, in);
    g1p p, r;
    g1_from_affine(&p, &x, &y);
    g1_mul_be(&r, &p, scalar_be, sc_len);
    int inf;
    g1_to_affine(&x, &y, &inf, &r);
    *out_inf = (uint8_t)inf;
    g1_store(out, &x, &y);
}

void bls_g2_mul_wide(const uint8_t in[192], uint8_t in_inf, const uint8_t *scalar_be,
                     uint64_t sc_len, uint8_t out[192], uint8_t *out_inf) {
    ensure_init();
    if (in_inf) { memset(out, 0, 192); *out_inf = 1; return; }
    fp2 x, y;
    g2_load(&x, &y, in);
    g2p p, r;
    g2_from_affine(&p, &x, &y);
    g2_mul_be(&r, &p, scalar_be, sc_len);
    int inf;
    g2_to_affine(&x, &y, &inf, &r);
    *out_inf = (uint8_t)inf;
    g2_store(out, &x, &y);
}

int bls_fp_inv(const uint8_t in[48], uint8_t out[48]) {
    ensure_init();
    fp a, r;
    fp_from_be(&a, in);
    if (fp_is_zero(&a)) return 0;
    fp_inv(&r, &a);
    fp_to_be(out, &r);
    return 1;
}

int bls_fp2_inv(const uint8_t in[96], uint8_t out[96]) {
    ensure_init();
    fp2 a, r;
    fp_from_be(&a.c0, in);
    fp_from_be(&a.c1, in + 48);
    if (fp2_is_zero(&a)) return 0;
    fp2_inv(&r, &a);
    fp_to_be(out, &r.c0);
    fp_to_be(out + 48, &r.c1);
    return 1;
}

int bls_fp_sqrt(const uint8_t in[48], uint8_t out[48]) {
    ensure_init();
    fp a, r;
    fp_from_be(&a, in);
    if (!fp_sqrt(&r, &a)) return 0;
    fp_to_be(out, &r);
    return 1;
}

int bls_fp2_sqrt(const uint8_t in[96], uint8_t out[96]) {
    ensure_init();
    fp2 a, r;
    fp_from_be(&a.c0, in);
    fp_from_be(&a.c1, in + 48);
    if (!fp2_sqrt(&r, &a)) return 0;
    fp_to_be(out, &r.c0);
    fp_to_be(out + 48, &r.c1);
    return 1;
}

/* Montgomery round-trip and small algebraic identities; 0 = pass. */
int bls_selftest(void) {
    ensure_init();
    uint64_t plain[6] = {0x123456789abcdef0ULL, 0xfedcba9876543210ULL, 7, 0, 42, 0x10ULL};
    fp a, b, c, d;
    fp_from_plain(&a, plain);
    uint64_t back[6];
    fp_to_plain(back, &a);
    if (memcmp(back, plain, sizeof plain) != 0) return 1;
    /* (a+a)*a == a*a + a*a */
    fp_add(&b, &a, &a);
    fp_mul(&b, &b, &a);
    fp_sqr(&c, &a);
    fp_add(&c, &c, &c);
    if (!fp_eq(&b, &c)) return 2;
    /* a * a^-1 == 1 */
    fp_inv(&d, &a);
    fp_mul(&d, &d, &a);
    fp one;
    fp_one(&one);
    if (!fp_eq(&d, &one)) return 3;
    /* fp2 inversion */
    fp2 e = { a, c }, f, g;
    fp2_inv(&f, &e);
    fp2_mul(&g, &f, &e);
    fp2 o2;
    fp2_one(&o2);
    if (!fp2_eq(&g, &o2)) return 4;
    /* fp12 inversion */
    fp12 h, hi, hh, o12;
    fp6_zero(&h.c0);
    fp6_zero(&h.c1);
    h.c0.c0 = e;
    h.c1.c1 = e;
    h.c0.c2.c0 = a;
    fp12_inv(&hi, &h);
    fp12_mul(&hh, &hi, &h);
    fp12_one(&o12);
    if (!fp12_eq(&hh, &o12)) return 5;
    return 0;
}
