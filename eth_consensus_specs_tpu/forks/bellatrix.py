"""bellatrix: the Merge — execution payloads, the ExecutionEngine protocol
seam, merge-transition predicates, and final penalty parameters.

Behavioral parity targets (reference, by section):
  * state machine:  specs/bellatrix/beacon-chain.md (ExecutionPayload :152,
    process_execution_payload :382, predicates :203-222, engine protocol
    :291-360, final penalty values :64)
  * fork choice:    specs/bellatrix/fork-choice.md (PowBlock,
    validate_merge_block)
  * fork upgrade:   specs/bellatrix/fork.md (upgrade_to_bellatrix)

The execution layer itself is a protocol boundary: consensus only ever
calls `verify_and_notify_new_payload`. The default NoopExecutionEngine
accepts everything (as the reference's injected engine does,
reference: pysetup/spec_builders/bellatrix.py), and tests monkeypatch it
to exercise invalid-payload paths.
"""

from dataclasses import dataclass

from eth_consensus_specs_tpu.ssz import (
    Bitvector,
    ByteList,
    ByteVector,
    Bytes20,
    Bytes32,
    Container,
    List,
    Vector,
    hash_tree_root,
    uint64,
    uint256,
)

from .altair import AltairSpec, ParticipationFlags
from .phase0 import (
    BLSSignature,
    Gwei,
    Root,
    Slot,
    ValidatorIndex,
    Version,
)

Hash32 = Bytes32
ExecutionAddress = Bytes20


class NoopExecutionEngine:
    """Stand-in engine: accepts every payload (reference analogue: the
    NoopExecutionEngine injected into generated specs). Tests monkeypatch
    the bound spec attribute to simulate engine verdicts."""

    def notify_new_payload(self, execution_payload) -> bool:
        return True

    def is_valid_block_hash(self, execution_payload) -> bool:
        return True

    def verify_and_notify_new_payload(self, new_payload_request) -> bool:
        """Accepts everything the (noop) notifier accepts, matching the
        engine the reference injects for tests (reference:
        pysetup/spec_builders/bellatrix.py:60-62) — so zero-length-
        transaction payloads are valid in vectors. The normative composite
        (which rejects b'' transactions; specs/bellatrix/beacon-chain.md:
        344-360) is `spec_composite_verify`, for engines implementing the
        real protocol flow. Delegating to notify_new_payload keeps
        engine-verdict test doubles (which override notify) effective."""
        return self.notify_new_payload(new_payload_request.execution_payload)

    def spec_composite_verify(self, new_payload_request) -> bool:
        execution_payload = new_payload_request.execution_payload
        if b"" in [bytes(tx) for tx in execution_payload.transactions]:
            return False
        if not self.is_valid_block_hash(execution_payload):
            return False
        if not self.notify_new_payload(execution_payload):
            return False
        return True


class BellatrixSpec(AltairSpec):
    fork_name = "bellatrix"

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.EXECUTION_ENGINE = NoopExecutionEngine()

    # == type system ======================================================

    def _build_types(self) -> None:
        super()._build_types()
        P = self
        Transaction = ByteList[P.MAX_BYTES_PER_TRANSACTION]
        self.Transaction = Transaction

        class ExecutionPayload(Container):
            parent_hash: Hash32
            fee_recipient: ExecutionAddress
            state_root: Bytes32
            receipts_root: Bytes32
            logs_bloom: ByteVector[P.BYTES_PER_LOGS_BLOOM]
            prev_randao: Bytes32
            block_number: uint64
            gas_limit: uint64
            gas_used: uint64
            timestamp: uint64
            extra_data: ByteList[P.MAX_EXTRA_DATA_BYTES]
            base_fee_per_gas: uint256
            block_hash: Hash32
            transactions: List[Transaction, P.MAX_TRANSACTIONS_PER_PAYLOAD]

        class ExecutionPayloadHeader(Container):
            parent_hash: Hash32
            fee_recipient: ExecutionAddress
            state_root: Bytes32
            receipts_root: Bytes32
            logs_bloom: ByteVector[P.BYTES_PER_LOGS_BLOOM]
            prev_randao: Bytes32
            block_number: uint64
            gas_limit: uint64
            gas_used: uint64
            timestamp: uint64
            extra_data: ByteList[P.MAX_EXTRA_DATA_BYTES]
            base_fee_per_gas: uint256
            block_hash: Hash32
            transactions_root: Root

        class BeaconBlockBody(Container):
            randao_reveal: BLSSignature
            eth1_data: P.Eth1Data
            graffiti: Bytes32
            proposer_slashings: List[P.ProposerSlashing, P.MAX_PROPOSER_SLASHINGS]
            attester_slashings: List[P.AttesterSlashing, P.MAX_ATTESTER_SLASHINGS]
            attestations: List[P.Attestation, P.MAX_ATTESTATIONS]
            deposits: List[P.Deposit, P.MAX_DEPOSITS]
            voluntary_exits: List[P.SignedVoluntaryExit, P.MAX_VOLUNTARY_EXITS]
            sync_aggregate: P.SyncAggregate
            execution_payload: ExecutionPayload  # [New in Bellatrix]

        class BeaconBlock(Container):
            slot: Slot
            proposer_index: ValidatorIndex
            parent_root: Root
            state_root: Root
            body: BeaconBlockBody

        class SignedBeaconBlock(Container):
            message: BeaconBlock
            signature: BLSSignature

        class BeaconState(Container):
            genesis_time: uint64
            genesis_validators_root: Root
            slot: Slot
            fork: P.Fork
            latest_block_header: P.BeaconBlockHeader
            block_roots: Vector[Root, P.SLOTS_PER_HISTORICAL_ROOT]
            state_roots: Vector[Root, P.SLOTS_PER_HISTORICAL_ROOT]
            historical_roots: List[Root, P.HISTORICAL_ROOTS_LIMIT]
            eth1_data: P.Eth1Data
            eth1_data_votes: List[P.Eth1Data, P.EPOCHS_PER_ETH1_VOTING_PERIOD * P.SLOTS_PER_EPOCH]
            eth1_deposit_index: uint64
            validators: List[P.Validator, P.VALIDATOR_REGISTRY_LIMIT]
            balances: List[Gwei, P.VALIDATOR_REGISTRY_LIMIT]
            randao_mixes: Vector[Bytes32, P.EPOCHS_PER_HISTORICAL_VECTOR]
            slashings: Vector[Gwei, P.EPOCHS_PER_SLASHINGS_VECTOR]
            previous_epoch_participation: List[ParticipationFlags, P.VALIDATOR_REGISTRY_LIMIT]
            current_epoch_participation: List[ParticipationFlags, P.VALIDATOR_REGISTRY_LIMIT]
            justification_bits: Bitvector[self.JUSTIFICATION_BITS_LENGTH]
            previous_justified_checkpoint: P.Checkpoint
            current_justified_checkpoint: P.Checkpoint
            finalized_checkpoint: P.Checkpoint
            inactivity_scores: List[uint64, P.VALIDATOR_REGISTRY_LIMIT]
            current_sync_committee: P.SyncCommittee
            next_sync_committee: P.SyncCommittee
            latest_execution_payload_header: ExecutionPayloadHeader  # [New in Bellatrix]

        # fork-choice PoW anchor (specs/bellatrix/fork-choice.md)
        class PowBlock(Container):
            block_hash: Hash32
            parent_hash: Hash32
            total_difficulty: uint256

        for name, typ in list(locals().items()):
            if isinstance(typ, type) and issubclass(typ, Container):
                typ.__name__ = name
                setattr(self, name, typ)

    # == request dataclasses ==============================================

    @dataclass
    class NewPayloadRequest:
        execution_payload: object

    # == predicates ========================================================

    def is_merge_transition_complete(self, state) -> bool:
        return state.latest_execution_payload_header != self.ExecutionPayloadHeader()

    def is_merge_transition_block(self, state, body) -> bool:
        return not self.is_merge_transition_complete(state) and (
            body.execution_payload != self.ExecutionPayload()
        )

    def is_execution_enabled(self, state, body) -> bool:
        return self.is_merge_transition_block(state, body) or self.is_merge_transition_complete(
            state
        )

    # == misc ==============================================================

    def compute_timestamp_at_slot(self, state, slot: int) -> int:
        slots_since_genesis = int(slot) - self.GENESIS_SLOT
        return int(state.genesis_time) + slots_since_genesis * self.config.SECONDS_PER_SLOT

    # == penalty knobs (final values) ======================================

    def inactivity_penalty_quotient(self) -> int:
        return self.INACTIVITY_PENALTY_QUOTIENT_BELLATRIX

    def min_slashing_penalty_quotient(self) -> int:
        return self.MIN_SLASHING_PENALTY_QUOTIENT_BELLATRIX

    def proportional_slashing_multiplier(self) -> int:
        return self.PROPORTIONAL_SLASHING_MULTIPLIER_BELLATRIX

    # == block processing ==================================================

    def process_block(self, state, block) -> None:
        self.process_block_header(state, block)
        if self.is_execution_enabled(state, block.body):
            self.process_execution_payload(state, block.body, self.EXECUTION_ENGINE)
        self.process_randao(state, block.body)
        self.process_eth1_data(state, block.body)
        self.process_operations(state, block.body)
        self.process_sync_aggregate(state, block.body.sync_aggregate)

    def process_execution_payload(self, state, body, execution_engine) -> None:
        payload = body.execution_payload
        if self.is_merge_transition_complete(state):
            assert (
                payload.parent_hash == state.latest_execution_payload_header.block_hash
            ), "payload parent mismatch"
        assert payload.prev_randao == self.get_randao_mix(
            state, self.get_current_epoch(state)
        ), "wrong prev_randao"
        assert payload.timestamp == self.compute_timestamp_at_slot(
            state, state.slot
        ), "wrong payload timestamp"
        assert execution_engine.verify_and_notify_new_payload(
            self.NewPayloadRequest(execution_payload=payload)
        ), "execution engine rejected payload"
        state.latest_execution_payload_header = self.execution_payload_to_header(payload)

    def execution_payload_to_header(self, payload):
        return self.ExecutionPayloadHeader(
            parent_hash=payload.parent_hash,
            fee_recipient=payload.fee_recipient,
            state_root=payload.state_root,
            receipts_root=payload.receipts_root,
            logs_bloom=payload.logs_bloom,
            prev_randao=payload.prev_randao,
            block_number=payload.block_number,
            gas_limit=payload.gas_limit,
            gas_used=payload.gas_used,
            timestamp=payload.timestamp,
            extra_data=payload.extra_data,
            base_fee_per_gas=payload.base_fee_per_gas,
            block_hash=payload.block_hash,
            transactions_root=hash_tree_root(payload.transactions),
        )

    # == fork choice: merge block validation ===============================

    def get_pow_block(self, block_hash):
        """Implementation-dependent PoW chain accessor; tests monkeypatch.
        (reference: specs/bellatrix/fork-choice.md get_pow_block)"""
        raise NotImplementedError("requires an execution-layer client")

    def is_valid_terminal_pow_block(self, block, parent) -> bool:
        is_total_difficulty_reached = (
            int(block.total_difficulty) >= self.config.TERMINAL_TOTAL_DIFFICULTY
        )
        is_parent_total_difficulty_valid = (
            int(parent.total_difficulty) < self.config.TERMINAL_TOTAL_DIFFICULTY
        )
        return is_total_difficulty_reached and is_parent_total_difficulty_valid

    def validate_merge_block(self, block) -> None:
        if bytes(self.config.TERMINAL_BLOCK_HASH) != b"\x00" * 32:
            # terminal-hash override path
            assert (
                self.compute_epoch_at_slot(int(block.slot))
                >= self.config.TERMINAL_BLOCK_HASH_ACTIVATION_EPOCH
            ), "terminal block hash override not yet active"
            assert block.body.execution_payload.parent_hash == Bytes32(
                self.config.TERMINAL_BLOCK_HASH
            ), "payload parent is not the terminal block"
            return
        pow_block = self.get_pow_block(block.body.execution_payload.parent_hash)
        pow_parent = self.get_pow_block(pow_block.parent_hash)
        assert self.is_valid_terminal_pow_block(pow_block, pow_parent), "invalid terminal block"

    def _merge_block_gate(self, store, block) -> None:
        """[New in Bellatrix] fork-choice on_block runs validate_merge_block
        for the transition block, judged against the PARENT (pre) state —
        the post-state is always merge-complete once the block carries a
        payload (specs/bellatrix/fork-choice.md on_block:303-304)."""
        pre_state = store.block_states[block.parent_root]
        if self.is_merge_transition_block(pre_state, block.body):
            self.validate_merge_block(block)

    # == proposer re-org fcU suppression (specs/bellatrix/fork-choice.md:98-175)

    def validator_is_connected(self, validator_index: int) -> bool:
        """Whether the local node manages `validator_index` (reference
        injects a constant-True stub into the generated spec; tests may
        monkeypatch)."""
        return True

    def should_override_forkchoice_update(self, store, head_root) -> bool:
        """Suppress notify_forkchoice_updated when the next proposal we
        control is expected to re-org a late, weak head
        (specs/bellatrix/fork-choice.md:117-175)."""
        head_block = store.blocks[head_root]
        parent_root = head_block.parent_root
        parent_block = store.blocks[parent_root]
        current_slot = self.get_current_slot(store)
        proposal_slot = int(head_block.slot) + 1

        head_late = self.is_head_late(store, head_root)
        shuffling_stable = self.is_shuffling_stable(proposal_slot)
        ffg_competitive = self.is_ffg_competitive(store, head_root, parent_root)
        finalization_ok = self.is_finalization_ok(store, proposal_slot)

        # only suppress when we expect to propose the next slot ourselves
        parent_state_advanced = store.block_states[parent_root].copy()
        self.process_slots(parent_state_advanced, proposal_slot)
        proposer_index = self.get_beacon_proposer_index(parent_state_advanced)
        proposing_reorg_slot = self.validator_is_connected(proposer_index)

        parent_slot_ok = int(parent_block.slot) + 1 == int(head_block.slot)
        proposing_on_time = self.is_proposing_on_time(store)
        # unlike get_proposer_head, the head's own slot also qualifies
        current_time_ok = int(head_block.slot) == current_slot or (
            proposal_slot == current_slot and proposing_on_time
        )
        single_slot_reorg = parent_slot_ok and current_time_ok

        # weigh the head only once its slot's attestations are in the store
        if current_slot > int(head_block.slot):
            head_weak = self.is_head_weak(store, head_root)
            parent_strong = self.is_parent_strong(store, parent_root)
        else:
            head_weak = True
            parent_strong = True

        return all(
            [
                head_late,
                shuffling_stable,
                ffg_competitive,
                finalization_ok,
                proposing_reorg_slot,
                single_slot_reorg,
                head_weak,
                parent_strong,
            ]
        )

    # == genesis (reference: bellatrix beacon-chain.md Testing section) ====

    def initialize_beacon_state_from_eth1(
        self, eth1_block_hash, eth1_timestamp, deposits, execution_payload_header=None
    ):
        state = super().initialize_beacon_state_from_eth1(
            eth1_block_hash, eth1_timestamp, deposits
        )
        state.fork = self.Fork(
            previous_version=Version(self.config[f"{self.fork_name.upper()}_FORK_VERSION"]),
            current_version=Version(self.config[f"{self.fork_name.upper()}_FORK_VERSION"]),
            epoch=self.GENESIS_EPOCH,
        )
        if execution_payload_header is not None:
            # pre-merge genesis keeps the empty default header
            state.latest_execution_payload_header = execution_payload_header
        return state

    # == fork upgrade (specs/bellatrix/fork.md) ============================

    def upgrade_from_parent(self, pre):
        epoch = self.compute_epoch_at_slot(int(pre.slot))
        return self.BeaconState(
            genesis_time=pre.genesis_time,
            genesis_validators_root=pre.genesis_validators_root,
            slot=pre.slot,
            fork=self.Fork(
                previous_version=pre.fork.current_version,
                current_version=Version(self.config.BELLATRIX_FORK_VERSION),
                epoch=epoch,
            ),
            latest_block_header=pre.latest_block_header,
            block_roots=list(pre.block_roots),
            state_roots=list(pre.state_roots),
            historical_roots=list(pre.historical_roots),
            eth1_data=pre.eth1_data,
            eth1_data_votes=list(pre.eth1_data_votes),
            eth1_deposit_index=pre.eth1_deposit_index,
            validators=list(pre.validators),
            balances=list(pre.balances),
            randao_mixes=list(pre.randao_mixes),
            slashings=list(pre.slashings),
            previous_epoch_participation=list(pre.previous_epoch_participation),
            current_epoch_participation=list(pre.current_epoch_participation),
            justification_bits=list(pre.justification_bits),
            previous_justified_checkpoint=pre.previous_justified_checkpoint,
            current_justified_checkpoint=pre.current_justified_checkpoint,
            finalized_checkpoint=pre.finalized_checkpoint,
            inactivity_scores=list(pre.inactivity_scores),
            current_sync_committee=pre.current_sync_committee,
            next_sync_committee=pre.next_sync_committee,
            latest_execution_payload_header=self.ExecutionPayloadHeader(),
        )
