"""EIP-6914: reuse fully-withdrawn validator indices for new deposits.

Behavioral parity target: specs/_features/eip6914/beacon-chain.md
(is_reusable_validator :45-50, get_index_for_new_validator :57-62) and
fork-choice.md (on_reused_index :36-38)."""

from eth_consensus_specs_tpu.forks.capella import CapellaSpec


class EIP6914Spec(CapellaSpec):
    fork_name = "eip6914"

    # preset (specs/_features/eip6914/beacon-chain.md:31-34)
    SAFE_EPOCHS_TO_REUSE_INDEX = 2**16

    def is_reusable_validator(self, validator, balance: int, epoch: int) -> bool:
        """Index can be re-assigned once long-withdrawn and drained."""
        return (
            int(epoch) > int(validator.withdrawable_epoch) + self.SAFE_EPOCHS_TO_REUSE_INDEX
            and int(balance) == 0
        )

    def get_index_for_new_validator(self, state) -> int:
        """[Modified in EIP6914] scan for a reusable slot before growing."""
        for index, validator in enumerate(state.validators):
            if self.is_reusable_validator(
                validator, state.balances[index], self.get_current_epoch(state)
            ):
                return index
        return len(state.validators)

    def on_reused_index(self, store, index: int) -> None:
        """Fork choice: a reused index sheds its equivocation record
        (specs/_features/eip6914/fork-choice.md:36-38)."""
        store.equivocating_indices.discard(int(index))
