"""EIP-6800 (Verkle): execution witnesses on the beacon chain.

Behavioral parity target: specs/_features/eip6800/beacon-chain.md — the
Banderwagon/stem custom types (:34-41), verkle proof containers
(:108-158), the witness-carrying payload/header (:57-105), and the
modified process_execution_payload committing the witness root
(:166-216). Built on deneb, like the reference.

Naming note: the reference document's header retains the stale
`excess_data_gas` name while its payload uses `excess_blob_gas`; the
deneb-era `excess_blob_gas` is kept for both here (same field, same
position)."""

from eth_consensus_specs_tpu.forks.bellatrix import ExecutionAddress, Hash32
from eth_consensus_specs_tpu.forks.deneb import DenebSpec
from eth_consensus_specs_tpu.forks.phase0 import Root
from eth_consensus_specs_tpu.ssz import (
    ByteList,
    ByteVector,
    Bytes31,
    Bytes32,
    Container,
    List,
    Union,
    Vector,
    hash_tree_root,
    uint64,
    uint256,
)

BanderwagonGroupElement = Bytes32
BanderwagonFieldElement = Bytes32
Stem = Bytes31
Bytes1 = ByteVector[1]


class EIP6800Spec(DenebSpec):
    fork_name = "eip6800"

    # preset (specs/_features/eip6800/beacon-chain.md:45-52)
    MAX_STEMS = 2**16
    MAX_COMMITMENTS_PER_STEM = 33
    VERKLE_WIDTH = 2**8
    IPA_PROOF_DEPTH = 2**3

    def _build_types(self) -> None:
        super()._build_types()
        P = self

        # new containers (:108-158); Optional[T] is SSZ Union[None, T]
        class SuffixStateDiff(Container):
            suffix: Bytes1
            current_value: Union[None, Bytes32]
            new_value: Union[None, Bytes32]

        class StemStateDiff(Container):
            stem: Stem
            suffix_diffs: List[SuffixStateDiff, P.VERKLE_WIDTH]

        class IPAProof(Container):
            cl: Vector[BanderwagonGroupElement, P.IPA_PROOF_DEPTH]
            cr: Vector[BanderwagonGroupElement, P.IPA_PROOF_DEPTH]
            final_evaluation: BanderwagonFieldElement

        class VerkleProof(Container):
            other_stems: List[Bytes31, P.MAX_STEMS]
            depth_extension_present: ByteList[P.MAX_STEMS]
            commitments_by_path: List[
                BanderwagonGroupElement, P.MAX_STEMS * P.MAX_COMMITMENTS_PER_STEM
            ]
            d: BanderwagonGroupElement
            ipa_proof: IPAProof

        class ExecutionWitness(Container):
            state_diff: List[StemStateDiff, P.MAX_STEMS]
            verkle_proof: VerkleProof

        # modified payload/header (:57-105)
        class ExecutionPayload(Container):
            parent_hash: Hash32
            fee_recipient: ExecutionAddress
            state_root: Bytes32
            receipts_root: Bytes32
            logs_bloom: ByteVector[P.BYTES_PER_LOGS_BLOOM]
            prev_randao: Bytes32
            block_number: uint64
            gas_limit: uint64
            gas_used: uint64
            timestamp: uint64
            extra_data: ByteList[P.MAX_EXTRA_DATA_BYTES]
            base_fee_per_gas: uint256
            block_hash: Hash32
            transactions: List[P.Transaction, P.MAX_TRANSACTIONS_PER_PAYLOAD]
            withdrawals: List[P.Withdrawal, P.MAX_WITHDRAWALS_PER_PAYLOAD]
            blob_gas_used: uint64
            excess_blob_gas: uint64
            execution_witness: ExecutionWitness  # [New in EIP6800]

        class ExecutionPayloadHeader(Container):
            parent_hash: Hash32
            fee_recipient: ExecutionAddress
            state_root: Bytes32
            receipts_root: Bytes32
            logs_bloom: ByteVector[P.BYTES_PER_LOGS_BLOOM]
            prev_randao: Bytes32
            block_number: uint64
            gas_limit: uint64
            gas_used: uint64
            timestamp: uint64
            extra_data: ByteList[P.MAX_EXTRA_DATA_BYTES]
            base_fee_per_gas: uint256
            block_hash: Hash32
            transactions_root: Root
            withdrawals_root: Root
            blob_gas_used: uint64
            excess_blob_gas: uint64
            execution_witness_root: Root  # [New in EIP6800]

        class BeaconBlockBody(Container):
            randao_reveal: P.BeaconBlockBody.fields()["randao_reveal"]
            eth1_data: P.Eth1Data
            graffiti: Bytes32
            proposer_slashings: P.BeaconBlockBody.fields()["proposer_slashings"]
            attester_slashings: P.BeaconBlockBody.fields()["attester_slashings"]
            attestations: P.BeaconBlockBody.fields()["attestations"]
            deposits: P.BeaconBlockBody.fields()["deposits"]
            voluntary_exits: P.BeaconBlockBody.fields()["voluntary_exits"]
            sync_aggregate: P.SyncAggregate
            execution_payload: ExecutionPayload
            bls_to_execution_changes: P.BeaconBlockBody.fields()["bls_to_execution_changes"]
            blob_kzg_commitments: P.BeaconBlockBody.fields()["blob_kzg_commitments"]

        class BeaconBlock(Container):
            slot: P.BeaconBlock.fields()["slot"]
            proposer_index: P.BeaconBlock.fields()["proposer_index"]
            parent_root: Root
            state_root: Root
            body: BeaconBlockBody

        class SignedBeaconBlock(Container):
            message: BeaconBlock
            signature: P.SignedBeaconBlock.fields()["signature"]

        fields = dict(P.BeaconState.fields())
        fields["latest_execution_payload_header"] = ExecutionPayloadHeader
        BeaconState = type("BeaconState", (Container,), {"__annotations__": fields})

        for name, typ in list(locals().items()):
            if isinstance(typ, type) and issubclass(typ, Container) and typ.fields():
                typ.__name__ = name
                setattr(self, name, typ)
        self.BeaconState = BeaconState

    def execution_payload_to_header(self, payload):
        """[Modified in EIP6800] commit to the execution witness
        (specs/_features/eip6800/beacon-chain.md:192-216)."""
        header = super().execution_payload_to_header(payload)
        return self.ExecutionPayloadHeader(
            **{
                name: getattr(header, name)
                for name in header.fields()
                if name != "execution_witness_root"
            },
            execution_witness_root=hash_tree_root(payload.execution_witness),
        )

    def upgrade_from_parent(self, pre):
        """deneb -> eip6800 (specs/_features/eip6800/fork.md): the stored
        header grows the zero witness root; everything else carries."""
        from eth_consensus_specs_tpu.forks.features import carry_state_fields

        fields = carry_state_fields(pre)
        pre_header = pre.latest_execution_payload_header
        fields["latest_execution_payload_header"] = self.ExecutionPayloadHeader(
            **{name: getattr(pre_header, name) for name in pre_header.fields()}
        )
        fields["fork"] = self.Fork(
            previous_version=pre.fork.current_version,
            current_version=self.config.EIP6800_FORK_VERSION,
            epoch=self.get_current_epoch(pre),
        )
        return self.BeaconState(**fields)
