"""EIP-7441 (Whisk): single secret leader election via shuffled trackers.

Behavioral parity target: specs/_features/eip7441/beacon-chain.md — the
whisk state fields and tracker selection (:136-237), opening-proof block
header (:244-279), shuffle processing (:283-346), registration
(:348-372), deposit-time tracker creation (:389-434), and the
header-derived proposer index (:436-446).

Proof backends — first-party, pluggable (the REFERENCE itself delegates
both proofs to the external `curdleproofs` package, which is not part of
its tree; pysetup/spec_builders/eip7441.py:12):

* Tracker/opening proofs are REAL Chaum-Pedersen discrete-log-equality
  proofs (Fiat-Shamir): prove knowledge of k with k_r_G == k * r_G and
  k_commitment == k * G. Sound and complete; 128-byte serialization.

* Shuffle proofs default to the ZERO-KNOWLEDGE backend
  (crypto/curdleproofs.py): a first-party curdleproofs-class
  same-permutation + same-scalar argument — permutation committed before
  any challenge, grand-product argument binding the committed
  permutation to Fiat-Shamir weights, generalized-Schnorr linkage to the
  tracker equations.  The proof reveals nothing beyond validity; the
  secret-leader-election property Whisk exists for survives the proof.

* A TRANSPARENT backend (proof == serialized permutation + per-element
  scalars) remains as a TEST-ONLY mode: generation falls back to it only
  for the legacy per-element-scalar call shape, and verification accepts
  it only when `ALLOW_TRANSPARENT_SHUFFLE_PROOFS` is set on the spec
  (tests exercising the legacy byte format flip it explicitly).
"""

from eth_consensus_specs_tpu.crypto.curve import (
    Point,
    g1_from_bytes,
    g1_generator,
    g1_to_bytes,
)
from eth_consensus_specs_tpu.crypto.fields import R as BLS_MODULUS
from eth_consensus_specs_tpu.forks.capella import CapellaSpec
from eth_consensus_specs_tpu.forks.phase0 import BLSSignature, Bytes32 as _B32, Root
from eth_consensus_specs_tpu.ssz import (
    ByteList,
    Bytes32,
    Bytes48,
    Container,
    List,
    Vector,
    hash_tree_root,
)

BLSG1Point = Bytes48


class EIP7441Spec(CapellaSpec):
    fork_name = "eip7441"

    # Domain types (specs/_features/eip7441/beacon-chain.md:37-43)
    DOMAIN_CANDIDATE_SELECTION = b"\x07\x00\x00\x00"
    DOMAIN_SHUFFLE = b"\x07\x10\x00\x00"
    DOMAIN_PROPOSER_SELECTION = b"\x07\x20\x00\x00"

    BLS_MODULUS = BLS_MODULUS

    @property
    def BLS_G1_GENERATOR(self) -> bytes:
        return g1_to_bytes(g1_generator())

    # == type system ======================================================

    def _build_types(self) -> None:
        super()._build_types()
        P = self

        WhiskShuffleProof = ByteList[P.MAX_SHUFFLE_PROOF_SIZE]
        WhiskTrackerProof = ByteList[P.MAX_OPENING_PROOF_SIZE]
        self.WhiskShuffleProof = WhiskShuffleProof
        self.WhiskTrackerProof = WhiskTrackerProof

        class WhiskTracker(Container):
            r_G: BLSG1Point
            k_r_G: BLSG1Point

        class BeaconBlockBody(Container):
            randao_reveal: BLSSignature
            eth1_data: P.Eth1Data
            graffiti: Bytes32
            proposer_slashings: P.BeaconBlockBody.fields()["proposer_slashings"]
            attester_slashings: P.BeaconBlockBody.fields()["attester_slashings"]
            attestations: P.BeaconBlockBody.fields()["attestations"]
            deposits: P.BeaconBlockBody.fields()["deposits"]
            voluntary_exits: P.BeaconBlockBody.fields()["voluntary_exits"]
            sync_aggregate: P.SyncAggregate
            execution_payload: P.ExecutionPayload
            bls_to_execution_changes: P.BeaconBlockBody.fields()["bls_to_execution_changes"]
            # [New in EIP7441]
            whisk_opening_proof: WhiskTrackerProof
            whisk_post_shuffle_trackers: Vector[WhiskTracker, P.VALIDATORS_PER_SHUFFLE]
            whisk_shuffle_proof: WhiskShuffleProof
            whisk_registration_proof: WhiskTrackerProof
            whisk_tracker: WhiskTracker
            whisk_k_commitment: BLSG1Point

        class BeaconBlock(Container):
            slot: P.BeaconBlock.fields()["slot"]
            proposer_index: P.BeaconBlock.fields()["proposer_index"]
            parent_root: Root
            state_root: Root
            body: BeaconBlockBody

        class SignedBeaconBlock(Container):
            message: BeaconBlock
            signature: BLSSignature

        fields = dict(P.BeaconState.fields())
        fields["whisk_candidate_trackers"] = Vector[WhiskTracker, P.CANDIDATE_TRACKERS_COUNT]
        fields["whisk_proposer_trackers"] = Vector[WhiskTracker, P.PROPOSER_TRACKERS_COUNT]
        fields["whisk_trackers"] = List[WhiskTracker, P.VALIDATOR_REGISTRY_LIMIT]
        fields["whisk_k_commitments"] = List[BLSG1Point, P.VALIDATOR_REGISTRY_LIMIT]
        BeaconState = type("BeaconState", (Container,), {"__annotations__": fields})

        for name, typ in list(locals().items()):
            if isinstance(typ, type) and issubclass(typ, Container) and typ.fields():
                typ.__name__ = name
                setattr(self, name, typ)
        self.BeaconState = BeaconState

    # == proof backend ====================================================

    def _fiat_shamir(self, *parts: bytes) -> int:
        data = b"WHISKDLEQ" + b"".join(bytes(p) for p in parts)
        return int.from_bytes(self.hash(data), "big") % BLS_MODULUS

    def whisk_generate_opening_proof(self, k: int, tracker) -> bytes:
        """Prover half of the Chaum-Pedersen DLEQ (test/validator side)."""
        r_G = g1_from_bytes(bytes(tracker.r_G))
        g = g1_generator()
        # deterministic nonce from (k, tracker): no RNG in tests
        t = self._fiat_shamir(
            int(k).to_bytes(32, "big"), bytes(tracker.r_G), bytes(tracker.k_r_G), b"nonce"
        )
        a1 = r_G.mul(t)
        a2 = g.mul(t)
        c = self._fiat_shamir(
            bytes(tracker.r_G), bytes(tracker.k_r_G), g1_to_bytes(a1), g1_to_bytes(a2)
        )
        s = (t + c * int(k)) % BLS_MODULUS
        return g1_to_bytes(a1) + g1_to_bytes(a2) + s.to_bytes(32, "big")

    def IsValidWhiskOpeningProof(self, tracker, k_commitment, tracker_proof) -> bool:
        """Verify knowledge of k with tracker.k_r_G == k * tracker.r_G and
        k_commitment == k * G (beacon-chain.md:124-132)."""
        proof = bytes(tracker_proof)
        if len(proof) != 128:
            return False
        try:
            a1 = g1_from_bytes(proof[0:48])
            a2 = g1_from_bytes(proof[48:96])
            r_G = g1_from_bytes(bytes(tracker.r_G))
            k_r_G = g1_from_bytes(bytes(tracker.k_r_G))
            k_C = g1_from_bytes(bytes(k_commitment))
        except (ValueError, AssertionError):
            return False
        s = int.from_bytes(proof[96:128], "big")
        c = self._fiat_shamir(bytes(tracker.r_G), bytes(tracker.k_r_G), proof[0:48], proof[48:96])
        return r_G.mul(s) == a1 + k_r_G.mul(c) and g1_generator().mul(s) == a2 + k_C.mul(c)

    # Verification of the legacy transparent byte format is TEST-ONLY
    # (see module doc); the ZK backend needs no opt-in.
    ALLOW_TRANSPARENT_SHUFFLE_PROOFS = False

    def _tracker_pairs(self, trackers):
        return [
            (g1_from_bytes(bytes(t.r_G)), g1_from_bytes(bytes(t.k_r_G)))
            for t in trackers
        ]

    def whisk_generate_shuffle_proof(self, pre_shuffle_trackers, permutation, scalars):
        """post[i] = scalars[i] * pre[permutation[i]].  With a uniform
        scalar (the Whisk relation: one secret k per shuffle) the proof is
        the ZERO-KNOWLEDGE curdleproofs-class argument; distinct
        per-element scalars fall back to the transparent test-only
        format."""
        assert len(permutation) == len(scalars) == len(pre_shuffle_trackers)
        if len(set(int(s) for s in scalars)) == 1:
            from eth_consensus_specs_tpu.crypto import curdleproofs

            post_pairs, proof = curdleproofs.prove_shuffle(
                self._tracker_pairs(pre_shuffle_trackers),
                [int(p) for p in permutation],
                int(scalars[0]),
            )
            post = [
                self.WhiskTracker(r_G=g1_to_bytes(r), k_r_G=g1_to_bytes(krg))
                for r, krg in post_pairs
            ]
            return post, proof
        # the transparent format is gated at BOTH ends: generating a proof
        # the default verifier rejects would be a silent footgun
        assert self.ALLOW_TRANSPARENT_SHUFFLE_PROOFS, (
            "per-element scalars produce the transparent TEST-ONLY proof "
            "format; set ALLOW_TRANSPARENT_SHUFFLE_PROOFS to use it"
        )
        post = []
        proof = b""
        for i, (p, s) in enumerate(zip(permutation, scalars)):
            src = pre_shuffle_trackers[int(p)]
            post.append(
                self.WhiskTracker(
                    r_G=g1_to_bytes(g1_from_bytes(bytes(src.r_G)).mul(int(s))),
                    k_r_G=g1_to_bytes(g1_from_bytes(bytes(src.k_r_G)).mul(int(s))),
                )
            )
            proof += int(p).to_bytes(8, "little") + int(s).to_bytes(32, "big")
        return post, proof

    def IsValidWhiskShuffleProof(
        self, pre_shuffle_trackers, post_shuffle_trackers, shuffle_proof
    ) -> bool:
        """Verify post is a rerandomized permutation of pre
        (beacon-chain.md:106-121).  ZK proofs (crypto/curdleproofs.py)
        are the production path; the transparent format verifies only
        under ALLOW_TRANSPARENT_SHUFFLE_PROOFS."""
        from eth_consensus_specs_tpu.crypto import curdleproofs

        proof = bytes(shuffle_proof)
        n = len(pre_shuffle_trackers)
        if proof[: len(curdleproofs.MAGIC)] == curdleproofs.MAGIC:
            if len(post_shuffle_trackers) != n:
                return False
            try:
                pre_pairs = self._tracker_pairs(pre_shuffle_trackers)
                post_pairs = self._tracker_pairs(post_shuffle_trackers)
            except (ValueError, AssertionError):
                return False
            return curdleproofs.verify_shuffle(pre_pairs, post_pairs, proof)
        if not self.ALLOW_TRANSPARENT_SHUFFLE_PROOFS:
            return False
        if len(proof) != n * 40 or len(post_shuffle_trackers) != n:
            return False
        seen = set()
        for i in range(n):
            p = int.from_bytes(proof[i * 40 : i * 40 + 8], "little")
            s = int.from_bytes(proof[i * 40 + 8 : i * 40 + 40], "big")
            if p >= n or p in seen or s % BLS_MODULUS == 0:
                return False
            seen.add(p)
            try:
                src_r = g1_from_bytes(bytes(pre_shuffle_trackers[p].r_G))
                src_krg = g1_from_bytes(bytes(pre_shuffle_trackers[p].k_r_G))
            except (ValueError, AssertionError):
                return False
            post = post_shuffle_trackers[i]
            if bytes(post.r_G) != g1_to_bytes(src_r.mul(s)):
                return False
            if bytes(post.k_r_G) != g1_to_bytes(src_krg.mul(s)):
                return False
        return True

    # == tracker selection (beacon-chain.md:186-237) =======================

    def select_whisk_proposer_trackers(self, state, epoch: int) -> None:
        proposer_seed = self.get_seed(
            state,
            max(int(epoch) - self.config.PROPOSER_SELECTION_GAP, 0),
            self.DOMAIN_PROPOSER_SELECTION,
        )
        perm = self._shuffle_permutation(
            len(state.whisk_candidate_trackers), proposer_seed
        )
        for i in range(self.PROPOSER_TRACKERS_COUNT):
            state.whisk_proposer_trackers[i] = state.whisk_candidate_trackers[
                int(perm[i])
            ]

    def select_whisk_candidate_trackers(self, state, epoch: int) -> None:
        active_validator_indices = self.get_active_validator_indices(state, int(epoch))
        for i in range(self.CANDIDATE_TRACKERS_COUNT):
            seed = self.hash(
                self.get_seed(state, int(epoch), self.DOMAIN_CANDIDATE_SELECTION)
                + self.uint_to_bytes(i, 8)
            )
            candidate_index = self.compute_proposer_index(
                state, active_validator_indices, seed
            )  # sample by effective balance
            state.whisk_candidate_trackers[i] = state.whisk_trackers[candidate_index]

    def process_whisk_updates(self, state) -> None:
        next_epoch = self.get_current_epoch(state) + 1
        if next_epoch % self.config.EPOCHS_PER_SHUFFLING_PHASE == 0:
            self.select_whisk_proposer_trackers(state, next_epoch)
            self.select_whisk_candidate_trackers(state, next_epoch)

    def process_epoch(self, state) -> None:
        super().process_epoch(state)
        # [New in EIP7441]
        self.process_whisk_updates(state)

    # == block processing (beacon-chain.md:244-387) ========================

    def process_whisk_opening_proof(self, state, block) -> None:
        tracker = state.whisk_proposer_trackers[
            int(state.slot) % self.PROPOSER_TRACKERS_COUNT
        ]
        k_commitment = state.whisk_k_commitments[int(block.proposer_index)]
        assert self.IsValidWhiskOpeningProof(
            tracker, k_commitment, block.body.whisk_opening_proof
        ), "invalid whisk opening proof"

    def process_block_header(self, state, block) -> None:
        """[Modified in EIP7441] no proposer-index equality check; the
        opening proof authorizes the proposer (beacon-chain.md:254-279)."""
        assert block.slot == state.slot, "block/state slot mismatch"
        assert block.slot > state.latest_block_header.slot, "block not newer than header"
        assert bytes(block.parent_root) == bytes(
            hash_tree_root(state.latest_block_header)
        ), "parent root mismatch"
        state.latest_block_header = self.BeaconBlockHeader(
            slot=block.slot,
            proposer_index=block.proposer_index,
            parent_root=block.parent_root,
            state_root=_B32(),
            body_root=hash_tree_root(block.body),
        )
        proposer = state.validators[int(block.proposer_index)]
        assert not proposer.slashed, "proposer is slashed"
        # [New in EIP7441]
        self.process_whisk_opening_proof(state, block)

    def get_shuffle_indices(self, randao_reveal) -> list[int]:
        indices = []
        for i in range(self.VALIDATORS_PER_SHUFFLE):
            pre_image = bytes(randao_reveal) + self.uint_to_bytes(i, 8)
            indices.append(
                self.bytes_to_uint64(self.hash(pre_image)[0:8])
                % self.CANDIDATE_TRACKERS_COUNT
            )
        return indices

    def process_shuffled_trackers(self, state, body) -> None:
        shuffle_epoch = self.get_current_epoch(state) % self.config.EPOCHS_PER_SHUFFLING_PHASE
        if (
            shuffle_epoch + self.config.PROPOSER_SELECTION_GAP + 1
            >= self.config.EPOCHS_PER_SHUFFLING_PHASE
        ):
            # cooldown: trackers must be zeroed
            assert body.whisk_post_shuffle_trackers == type(
                body.whisk_post_shuffle_trackers
            )(), "cooldown requires zero trackers"
            assert bytes(body.whisk_shuffle_proof) == b"", "cooldown requires empty proof"
        else:
            shuffle_indices = self.get_shuffle_indices(body.randao_reveal)
            pre_shuffle_trackers = [
                state.whisk_candidate_trackers[i] for i in shuffle_indices
            ]
            assert self.IsValidWhiskShuffleProof(
                pre_shuffle_trackers,
                list(body.whisk_post_shuffle_trackers),
                body.whisk_shuffle_proof,
            ), "invalid shuffle proof"
            for i, shuffle_index in enumerate(shuffle_indices):
                state.whisk_candidate_trackers[shuffle_index] = (
                    body.whisk_post_shuffle_trackers[i]
                )

    def is_k_commitment_unique(self, state, k_commitment) -> bool:
        return all(
            bytes(c) != bytes(k_commitment) for c in state.whisk_k_commitments
        )

    def process_whisk_registration(self, state, body) -> None:
        proposer_index = self.get_beacon_proposer_index(state)
        if bytes(state.whisk_trackers[proposer_index].r_G) == self.BLS_G1_GENERATOR:
            # first Whisk proposal
            assert bytes(body.whisk_tracker.r_G) != self.BLS_G1_GENERATOR, (
                "registration tracker must be fresh"
            )
            assert self.is_k_commitment_unique(state, body.whisk_k_commitment), (
                "k commitment not unique"
            )
            assert self.IsValidWhiskOpeningProof(
                body.whisk_tracker, body.whisk_k_commitment, body.whisk_registration_proof
            ), "invalid registration proof"
            state.whisk_trackers[proposer_index] = body.whisk_tracker
            state.whisk_k_commitments[proposer_index] = body.whisk_k_commitment
        else:
            assert bytes(body.whisk_registration_proof) == b"", "unexpected proof"
            assert body.whisk_tracker == self.WhiskTracker(), "unexpected tracker"
            assert bytes(body.whisk_k_commitment) == bytes(BLSG1Point()), (
                "unexpected commitment"
            )

    def process_block(self, state, block) -> None:
        self.process_block_header(state, block)
        self.process_withdrawals(state, block.body.execution_payload)
        self.process_execution_payload(state, block.body, self.EXECUTION_ENGINE)
        self.process_randao(state, block.body)
        self.process_eth1_data(state, block.body)
        self.process_operations(state, block.body)
        self.process_sync_aggregate(state, block.body.sync_aggregate)
        # [New in EIP7441]
        self.process_shuffled_trackers(state, block.body)
        self.process_whisk_registration(state, block.body)

    # == deposits (beacon-chain.md:392-434) ================================

    def get_initial_whisk_k(self, validator_index: int, counter: int) -> int:
        return (
            int.from_bytes(
                self.hash(
                    self.uint_to_bytes(int(validator_index), 8)
                    + self.uint_to_bytes(int(counter), 8)
                ),
                "little",
            )
            % BLS_MODULUS
        )

    def get_unique_whisk_k(self, state, validator_index: int) -> int:
        counter = 0
        while True:
            k = self.get_initial_whisk_k(validator_index, counter)
            if self.is_k_commitment_unique(state, self.get_k_commitment(k)):
                return k
            counter += 1

    def get_k_commitment(self, k: int) -> bytes:
        return g1_to_bytes(g1_generator().mul(int(k)))

    def get_initial_tracker(self, k: int) -> "Container":
        return self.WhiskTracker(
            r_G=self.BLS_G1_GENERATOR, k_r_G=g1_to_bytes(g1_generator().mul(int(k)))
        )

    def add_validator_to_registry(self, state, pubkey, withdrawal_credentials, amount) -> None:
        super().add_validator_to_registry(state, pubkey, withdrawal_credentials, amount)
        # [New in EIP7441]
        k = self.get_unique_whisk_k(state, len(state.validators) - 1)
        state.whisk_trackers.append(self.get_initial_tracker(k))
        state.whisk_k_commitments.append(self.get_k_commitment(k))

    # == proposer index (beacon-chain.md:439-446) ==========================

    def get_beacon_proposer_index(self, state) -> int:
        assert int(state.latest_block_header.slot) == int(state.slot), (
            "proposer index only known during block processing"
        )
        return int(state.latest_block_header.proposer_index)

    # == fork upgrade (specs/_features/eip7441/fork.md:55-119) =============

    def upgrade_from_parent(self, pre):
        """capella -> whisk. Initial k's use counter 0 directly as fork.md
        does (collisions are negligible); the reference document's stale
        `validators=[]` is corrected to carry the registry."""
        ks = [
            self.get_initial_whisk_k(validator_index, 0)
            for validator_index in range(len(pre.validators))
        ]
        whisk_k_commitments = [self.get_k_commitment(k) for k in ks]
        whisk_trackers = [self.get_initial_tracker(k) for k in ks]

        from eth_consensus_specs_tpu.forks.features import carry_state_fields

        epoch = self.get_current_epoch(pre)
        fields = carry_state_fields(pre)
        fields["fork"] = self.Fork(
            previous_version=pre.fork.current_version,
            current_version=self.config.EIP7441_FORK_VERSION,
            epoch=epoch,
        )
        post = self.BeaconState(
            **fields,
            whisk_proposer_trackers=[
                self.WhiskTracker() for _ in range(self.PROPOSER_TRACKERS_COUNT)
            ],
            whisk_candidate_trackers=[
                self.WhiskTracker() for _ in range(self.CANDIDATE_TRACKERS_COUNT)
            ],
            whisk_trackers=whisk_trackers,
            whisk_k_commitments=whisk_k_commitments,
        )
        # candidate selection with an older epoch, then proposers, then a
        # final candidate round for the upcoming shuffling phase
        self.select_whisk_candidate_trackers(
            post, max(epoch - (self.config.PROPOSER_SELECTION_GAP + 1), 0)
        )
        self.select_whisk_proposer_trackers(post, epoch)
        self.select_whisk_candidate_trackers(post, epoch)
        return post

    # == test/genesis bootstrap ===========================================

    def initialize_feature_state(self, state) -> None:
        """Fill the whisk fields on a fresh genesis state: every validator
        gets a deterministic k and initial tracker, candidates/proposers
        selected for phase 0 (mirrors fork.md's upgrade semantics)."""
        for index in range(len(state.validators)):
            k = self.get_unique_whisk_k(state, index)
            state.whisk_trackers.append(self.get_initial_tracker(k))
            state.whisk_k_commitments.append(self.get_k_commitment(k))
        self.select_whisk_candidate_trackers(state, self.get_current_epoch(state))
        self.select_whisk_proposer_trackers(state, self.get_current_epoch(state))
