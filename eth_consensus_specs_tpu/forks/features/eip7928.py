"""EIP-7928: block-level access lists in the execution payload.

Behavioral parity target: specs/_features/eip7928/beacon-chain.md — the
BlockAccessList payload field (:25-56), header root (:58-81), modified
process_execution_payload hashing the access list into the header
(:144-198), and fork.md's upgrade."""

from eth_consensus_specs_tpu.forks.fulu import FuluSpec
from eth_consensus_specs_tpu.ssz import ByteList, Bytes32, ByteVector, Container, List, hash_tree_root, uint64, uint256
from eth_consensus_specs_tpu.forks.bellatrix import ExecutionAddress, Hash32
from eth_consensus_specs_tpu.forks.phase0 import Root


class EIP7928Spec(FuluSpec):
    fork_name = "eip7928"

    def _build_types(self) -> None:
        super()._build_types()
        P = self

        # RLP-encoded block access list (specs/_features/eip7928/beacon-chain.md:25-29)
        BlockAccessList = ByteList[P.MAX_BYTES_PER_TRANSACTION]
        self.BlockAccessList = BlockAccessList

        class ExecutionPayload(Container):
            parent_hash: Hash32
            fee_recipient: ExecutionAddress
            state_root: Bytes32
            receipts_root: Bytes32
            logs_bloom: ByteVector[P.BYTES_PER_LOGS_BLOOM]
            prev_randao: Bytes32
            block_number: uint64
            gas_limit: uint64
            gas_used: uint64
            timestamp: uint64
            extra_data: ByteList[P.MAX_EXTRA_DATA_BYTES]
            base_fee_per_gas: uint256
            block_hash: Hash32
            transactions: List[P.Transaction, P.MAX_TRANSACTIONS_PER_PAYLOAD]
            withdrawals: List[P.Withdrawal, P.MAX_WITHDRAWALS_PER_PAYLOAD]
            blob_gas_used: uint64
            excess_blob_gas: uint64
            block_access_list: BlockAccessList  # [New in EIP7928]

        class ExecutionPayloadHeader(Container):
            parent_hash: Hash32
            fee_recipient: ExecutionAddress
            state_root: Bytes32
            receipts_root: Bytes32
            logs_bloom: ByteVector[P.BYTES_PER_LOGS_BLOOM]
            prev_randao: Bytes32
            block_number: uint64
            gas_limit: uint64
            gas_used: uint64
            timestamp: uint64
            extra_data: ByteList[P.MAX_EXTRA_DATA_BYTES]
            base_fee_per_gas: uint256
            block_hash: Hash32
            transactions_root: Root
            withdrawals_root: Root
            blob_gas_used: uint64
            excess_blob_gas: uint64
            block_access_list_root: Root  # [New in EIP7928]

        class BeaconBlockBody(Container):
            randao_reveal: P.BeaconBlockBody.fields()["randao_reveal"]
            eth1_data: P.Eth1Data
            graffiti: Bytes32
            proposer_slashings: P.BeaconBlockBody.fields()["proposer_slashings"]
            attester_slashings: P.BeaconBlockBody.fields()["attester_slashings"]
            attestations: P.BeaconBlockBody.fields()["attestations"]
            deposits: P.BeaconBlockBody.fields()["deposits"]
            voluntary_exits: P.BeaconBlockBody.fields()["voluntary_exits"]
            sync_aggregate: P.SyncAggregate
            execution_payload: ExecutionPayload  # [Modified in EIP7928]
            bls_to_execution_changes: P.BeaconBlockBody.fields()["bls_to_execution_changes"]
            blob_kzg_commitments: P.BeaconBlockBody.fields()["blob_kzg_commitments"]
            execution_requests: P.ExecutionRequests

        class BeaconBlock(Container):
            slot: P.BeaconBlock.fields()["slot"]
            proposer_index: P.BeaconBlock.fields()["proposer_index"]
            parent_root: Root
            state_root: Root
            body: BeaconBlockBody

        class SignedBeaconBlock(Container):
            message: BeaconBlock
            signature: P.SignedBeaconBlock.fields()["signature"]

        # rebuild the state with the modified header type, field-for-field
        fields = dict(P.BeaconState.fields())
        fields["latest_execution_payload_header"] = ExecutionPayloadHeader
        BeaconState = type("BeaconState", (Container,), {"__annotations__": fields})

        for name, typ in list(locals().items()):
            if isinstance(typ, type) and issubclass(typ, Container) and typ.fields():
                typ.__name__ = name
                setattr(self, name, typ)
        self.BeaconState = BeaconState

    def execution_payload_to_header(self, payload):
        """[Modified in EIP7928] commit to the access list
        (specs/_features/eip7928/beacon-chain.md:180-198)."""
        header = super().execution_payload_to_header(payload)
        return self.ExecutionPayloadHeader(
            **{name: getattr(header, name) for name in header.fields() if name != "block_access_list_root"},
            block_access_list_root=hash_tree_root(payload.block_access_list),
        )

    def upgrade_from_parent(self, pre):
        """fulu -> eip7928 (specs/_features/eip7928/fork.md): the stored
        header grows the zero access-list root; everything else carries."""
        from eth_consensus_specs_tpu.forks.features import carry_state_fields

        fields = carry_state_fields(pre)
        pre_header = pre.latest_execution_payload_header
        fields["latest_execution_payload_header"] = self.ExecutionPayloadHeader(
            **{name: getattr(pre_header, name) for name in pre_header.fields()}
        )
        fields["fork"] = self.Fork(
            previous_version=pre.fork.current_version,
            current_version=self.config.EIP7928_FORK_VERSION,
            epoch=self.get_current_epoch(pre),
        )
        return self.BeaconState(**fields)
