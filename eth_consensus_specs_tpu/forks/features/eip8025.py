"""EIP-8025: stateless validation with zkEVM execution proofs.

Behavioral parity targets:
  * beacon chain: specs/_features/eip8025/beacon-chain.md — proof
    containers (:67-82), verify_execution_proof(s) (:93-147), and the
    stateless_validation branch of process_execution_payload (:151-216)
  * proof system: specs/_features/eip8025/zkevm.md — the MOCK proof
    system the reference itself specifies, kept byte-identical here:
    verification binds the proof's public_inputs to the claimed
    parent/block hashes, while verify_execution_proof_impl is the
    reference's intentional size-check-only placeholder (proof_data is
    NOT cryptographically verified — true of the upstream spec too; a
    real proof system slots in behind the same interface). Built on fulu.
"""

from eth_consensus_specs_tpu.forks.bellatrix import Hash32
from eth_consensus_specs_tpu.forks.fulu import FuluSpec
from eth_consensus_specs_tpu.forks.phase0 import BLSSignature, Root, ValidatorIndex
from eth_consensus_specs_tpu.ssz import ByteList, Container, hash_tree_root, uint8
from eth_consensus_specs_tpu.utils import bls


class EIP8025Spec(FuluSpec):
    fork_name = "eip8025"

    # constants (beacon-chain.md:42-56, zkevm.md:44-50)
    MAX_EXECUTION_PROOFS_PER_PAYLOAD = 4
    DOMAIN_EXECUTION_PROOF = b"\x0b\x00\x00\x00"
    MAX_PROOF_SIZE = 307200
    MAX_PROVING_KEY_SIZE = 2**28
    MAX_VERIFICATION_KEY_SIZE = 2**20
    MAX_WITNESS_SIZE = 314572800

    @property
    def PROGRAM(self) -> bytes:
        return b"DEFAULT__PROGRAM"

    # configuration (beacon-chain.md:58-62)
    MIN_REQUIRED_EXECUTION_PROOFS = 1

    def _build_types(self) -> None:
        super()._build_types()
        P = self

        ProgramBytecode = ByteList[16]
        ProofID = uint8
        self.ProgramBytecode = ProgramBytecode
        self.ProofID = ProofID

        class PublicInput(Container):
            block_hash: Hash32
            parent_hash: Hash32

        class ZKEVMProof(Container):
            proof_data: ByteList[P.MAX_PROOF_SIZE]
            proof_type: ProofID
            public_inputs: PublicInput

        class ExecutionProof(Container):
            beacon_root: Root
            zk_proof: ZKEVMProof
            validator_index: ValidatorIndex

        class SignedExecutionProof(Container):
            message: ExecutionProof
            signature: BLSSignature

        for name, typ in list(locals().items()):
            if isinstance(typ, type) and issubclass(typ, Container):
                typ.__name__ = name
                setattr(self, name, typ)

    # == zkEVM mock proof system (zkevm.md) ================================

    def generate_verification_key(self, program_bytecode: bytes, proof_id: int) -> bytes:
        return bytes(program_bytecode) + int(proof_id).to_bytes(1, "little")

    def generate_proving_key(self, program_bytecode: bytes, proof_id: int) -> bytes:
        return bytes(program_bytecode) + int(proof_id).to_bytes(1, "little")

    def generate_keys(self, program_bytecode: bytes, proof_id: int):
        return (
            self.generate_proving_key(program_bytecode, proof_id),
            self.generate_verification_key(program_bytecode, proof_id),
        )

    def verify_execution_proof_impl(self, proof, verification_key: bytes) -> bool:
        if len(proof.proof_data) > self.MAX_PROOF_SIZE:
            return False
        return True

    def generate_zkevm_proof(self, block_hash: bytes, parent_hash: bytes, proof_id: int):
        """generate_execution_proof_impl folded into the public entry
        (zkevm.md:150-170): proof_data = H(block || parent || id)."""
        public_inputs = self.PublicInput(block_hash=block_hash, parent_hash=parent_hash)
        proof_data = self.hash(
            bytes(block_hash) + bytes(parent_hash) + int(proof_id).to_bytes(1, "little")
        )
        return self.ZKEVMProof(
            proof_data=proof_data, proof_type=proof_id, public_inputs=public_inputs
        )

    def verify_zkevm_proof(
        self, zk_proof, parent_hash: bytes, block_hash: bytes, program_bytecode: bytes
    ) -> bool:
        if bytes(zk_proof.public_inputs.block_hash) != bytes(block_hash):
            return False
        if bytes(zk_proof.public_inputs.parent_hash) != bytes(parent_hash):
            return False
        _, verification_key = self.generate_keys(program_bytecode, int(zk_proof.proof_type))
        return self.verify_execution_proof_impl(zk_proof, verification_key)

    # == execution proof functions (beacon-chain.md:93-147) ================

    def verify_execution_proof(
        self, signed_proof, parent_hash, block_hash, state, el_program: bytes
    ) -> bool:
        proof_message = signed_proof.message
        validator = state.validators[int(proof_message.validator_index)]
        signing_root = self.compute_signing_root(
            proof_message, self.get_domain(state, self.DOMAIN_EXECUTION_PROOF)
        )
        if not bls.Verify(validator.pubkey, signing_root, signed_proof.signature):
            return False
        program_bytecode = bytes(el_program) + int(
            proof_message.zk_proof.proof_type
        ).to_bytes(1, "little")
        return self.verify_zkevm_proof(
            proof_message.zk_proof, parent_hash, block_hash, program_bytecode
        )

    def retrieve_execution_proofs(self, block_hash):
        """Implementation/context dependent; tests override."""
        return []

    def verify_execution_proofs(self, parent_hash, block_hash, state) -> bool:
        signed_execution_proofs = self.retrieve_execution_proofs(block_hash)
        if len(signed_execution_proofs) < self.MIN_REQUIRED_EXECUTION_PROOFS:
            return False
        for signed_proof in signed_execution_proofs:
            if not self.verify_execution_proof(
                signed_proof, parent_hash, block_hash, state, self.PROGRAM
            ):
                return False
        return True

    # == payload processing (beacon-chain.md:151-216) ======================

    def process_execution_payload(
        self, state, body, execution_engine, stateless_validation: bool = False
    ) -> None:
        """[Modified in EIP8025] optional stateless validation path."""
        if not stateless_validation:
            return super().process_execution_payload(state, body, execution_engine)
        payload = body.execution_payload
        assert (
            payload.parent_hash == state.latest_execution_payload_header.block_hash
        ), "payload parent mismatch"
        assert payload.prev_randao == self.get_randao_mix(
            state, self.get_current_epoch(state)
        ), "wrong prev_randao"
        assert payload.timestamp == self.compute_timestamp_at_slot(
            state, state.slot
        ), "wrong payload timestamp"
        assert (
            len(body.blob_kzg_commitments)
            <= self.get_blob_parameters(self.get_current_epoch(state)).max_blobs_per_block
        ), "too many blobs"
        # [New in EIP8025] execution proofs replace the engine call
        assert self.verify_execution_proofs(
            payload.parent_hash, payload.block_hash, state
        ), "insufficient or invalid execution proofs"
        state.latest_execution_payload_header = self.execution_payload_to_header(payload)