"""In-development feature forks (reference: specs/_features/).

Each feature is an executable spec subclassing its base fork, exactly like
the mainline forks — `get_feature_spec("eip6914", "minimal")` gives the
familiar `spec.process_...` surface. Features are NOT part of FORK_ORDER
(they fork off specific mainline forks, not each other), mirroring how the
reference keeps them outside the sequential upgrade DAG
(pysetup/md_doc_paths.py:18-31)."""

from __future__ import annotations

from functools import lru_cache

from eth_consensus_specs_tpu.config import load_config, load_preset

FEATURE_BASE_FORK = {
    "eip6800": "deneb",
    "eip6914": "capella",
    "eip7441": "capella",
    "eip7805": "fulu",
    "eip7928": "fulu",
    "eip8025": "fulu",
}


def _feature_class(name: str):
    if name == "eip6800":
        from .eip6800 import EIP6800Spec

        return EIP6800Spec
    if name == "eip8025":
        from .eip8025 import EIP8025Spec

        return EIP8025Spec
    if name == "eip6914":
        from .eip6914 import EIP6914Spec

        return EIP6914Spec
    if name == "eip7441":
        from .eip7441 import EIP7441Spec

        return EIP7441Spec
    if name == "eip7805":
        from .eip7805 import EIP7805Spec

        return EIP7805Spec
    if name == "eip7928":
        from .eip7928 import EIP7928Spec

        return EIP7928Spec
    raise ValueError(f"unknown feature {name!r}")


@lru_cache(maxsize=None)
def get_feature_spec(name: str, preset_name: str = "mainnet"):
    import os

    from eth_consensus_specs_tpu.config import _DATA_DIR, _load_yaml

    cls = _feature_class(name)
    preset = load_preset(preset_name, FEATURE_BASE_FORK[name])
    feature_file = os.path.join(
        _DATA_DIR, "presets", preset_name, "features", f"{name}.yaml"
    )
    if os.path.exists(feature_file):
        preset = preset.replace(**_load_yaml(feature_file))
    config = load_config(preset_name)
    return cls(preset, config, preset_name=preset_name)


def available_features() -> list[str]:
    return sorted(FEATURE_BASE_FORK)


def carry_state_fields(pre) -> dict:
    """Field dict of a state for cross-type reconstruction in feature
    upgrades: sequence views become plain lists so the target fork's
    (differently parametrized) sequence types re-coerce element-wise."""
    from eth_consensus_specs_tpu.ssz import Bitlist, Bitvector, List, Vector

    return {
        name: list(getattr(pre, name))
        if issubclass(t, (List, Vector, Bitlist, Bitvector))
        else getattr(pre, name)
        for name, t in pre.fields().items()
    }
