"""EIP-7805 (FOCIL): fork-choice enforced, committee-based inclusion
lists.

Behavioral parity targets:
  * beacon chain: specs/_features/eip7805/beacon-chain.md (containers
    :54-71, signature predicate :78-92, committee accessor :96-111)
  * inclusion-list store: specs/_features/eip7805/inclusion-list.md
    (store :27-37, process_inclusion_list :56-79, transaction collection
    :88-104)
  * fork choice (subset): specs/_features/eip7805/fork-choice.md
    (on_inclusion_list validation + equivocator tracking)
"""

from dataclasses import dataclass, field
from typing import Dict, Set, Tuple

from eth_consensus_specs_tpu.forks.fulu import FuluSpec
from eth_consensus_specs_tpu.forks.phase0 import BLSSignature, Root, Slot, ValidatorIndex
from eth_consensus_specs_tpu.ssz import Container, List, hash_tree_root
from eth_consensus_specs_tpu.utils import bls


class EIP7805Spec(FuluSpec):
    fork_name = "eip7805"

    # specs/_features/eip7805/beacon-chain.md:37-40
    DOMAIN_INCLUSION_LIST_COMMITTEE = b"\x0c\x00\x00\x00"

    def _build_types(self) -> None:
        super()._build_types()
        P = self

        class InclusionList(Container):
            slot: Slot
            validator_index: ValidatorIndex
            inclusion_list_committee_root: Root
            transactions: List[P.Transaction, P.MAX_TRANSACTIONS_PER_PAYLOAD]

        class SignedInclusionList(Container):
            message: InclusionList
            signature: BLSSignature

        for name, typ in list(locals().items()):
            if isinstance(typ, type) and issubclass(typ, Container):
                typ.__name__ = name
                setattr(self, name, typ)

    # == predicates/accessors (beacon-chain.md:78-111) =====================

    def is_valid_inclusion_list_signature(self, state, signed_inclusion_list) -> bool:
        message = signed_inclusion_list.message
        index = int(message.validator_index)
        pubkey = state.validators[index].pubkey
        domain = self.get_domain(
            state,
            self.DOMAIN_INCLUSION_LIST_COMMITTEE,
            self.compute_epoch_at_slot(int(message.slot)),
        )
        signing_root = self.compute_signing_root(message, domain)
        return bls.Verify(pubkey, signing_root, signed_inclusion_list.signature)

    def get_inclusion_list_committee(self, state, slot: int):
        epoch = self.compute_epoch_at_slot(int(slot))
        seed = self.get_seed(state, epoch, self.DOMAIN_INCLUSION_LIST_COMMITTEE)
        indices = self.get_active_validator_indices(state, epoch)
        start = (int(slot) % self.SLOTS_PER_EPOCH) * self.INCLUSION_LIST_COMMITTEE_SIZE
        end = start + self.INCLUSION_LIST_COMMITTEE_SIZE
        perm = self._shuffle_permutation(len(indices), seed)
        return [int(indices[int(perm[i % len(indices)])]) for i in range(start, end)]

    # == inclusion-list store (inclusion-list.md) ==========================

    @dataclass
    class InclusionListStore:
        inclusion_lists: Dict[Tuple[int, bytes], set] = field(default_factory=dict)
        equivocators: Dict[Tuple[int, bytes], Set[int]] = field(default_factory=dict)

    def get_inclusion_list_store(self) -> "EIP7805Spec.InclusionListStore":
        return self.InclusionListStore()

    def process_inclusion_list(
        self, store, inclusion_list, is_before_view_freeze_deadline: bool
    ) -> None:
        """Equivocation-aware ingest (inclusion-list.md:56-79)."""
        key = (int(inclusion_list.slot), bytes(inclusion_list.inclusion_list_committee_root))
        equivocators = store.equivocators.setdefault(key, set())
        stored = store.inclusion_lists.setdefault(key, set())

        if int(inclusion_list.validator_index) in equivocators:
            return

        for stored_inclusion_list in stored:
            if int(stored_inclusion_list.validator_index) != int(
                inclusion_list.validator_index
            ):
                continue
            if stored_inclusion_list != inclusion_list:
                equivocators.add(int(inclusion_list.validator_index))
                stored.remove(stored_inclusion_list)
            return

        if is_before_view_freeze_deadline:
            stored.add(inclusion_list)

    def get_inclusion_list_transactions(self, store, state, slot: int):
        """Deduplicated transactions from timely, non-equivocating lists
        (inclusion-list.md:88-104)."""
        committee = self.get_inclusion_list_committee(state, int(slot))
        committee_root = bytes(
            hash_tree_root(
                self._committee_vector_type()(committee)
            )
        )
        key = (int(slot), committee_root)
        txs = [
            bytes(transaction)
            for inclusion_list in store.inclusion_lists.get(key, set())
            for transaction in inclusion_list.transactions
        ]
        return list(set(txs))

    def _committee_vector_type(self):
        from eth_consensus_specs_tpu.ssz import Vector

        return Vector[ValidatorIndex, self.INCLUSION_LIST_COMMITTEE_SIZE]

    # == fork-choice hook (fork-choice.md subset) ==========================

    def on_inclusion_list(
        self, store, inclusion_store, state, signed_inclusion_list,
        is_before_view_freeze_deadline: bool,
    ) -> None:
        """Validate and ingest a gossiped inclusion list: committee
        membership + root match + signature, then store-level
        equivocation processing."""
        message = signed_inclusion_list.message
        committee = self.get_inclusion_list_committee(state, int(message.slot))
        assert int(message.validator_index) in committee, "not in committee"
        committee_root = bytes(hash_tree_root(self._committee_vector_type()(committee)))
        assert bytes(message.inclusion_list_committee_root) == committee_root, (
            "committee root mismatch"
        )
        assert self.is_valid_inclusion_list_signature(state, signed_inclusion_list), (
            "bad signature"
        )
        self.process_inclusion_list(
            inclusion_store, message, is_before_view_freeze_deadline
        )
