"""phase0: the core beacon-chain state machine, fork choice, genesis and
honest-validator duties.

Behavioral parity targets (reference, by section):
  * state machine:  specs/phase0/beacon-chain.md (state_transition :1346,
    process_epoch :1395+, process_block :1852, operations :1980+)
  * fork choice:    specs/phase0/fork-choice.md (Store :162, get_head :403,
    on_block :761) — the modern version with unrealized justification
  * validator:      specs/phase0/validator.md (duties, aggregation)
  * weak subj.:     specs/phase0/weak-subjectivity.md

Architecture notes (why this is not a transliteration):
  * One CLASS per fork; `self.` resolves constants, types and functions so a
    later fork overrides by subclassing (see forks/__init__.py).
  * The committee pipeline runs on the whole-permutation form of the
    swap-or-not shuffle (ops/shuffle.py): one vectorized pass produces the
    full epoch permutation, cached by (seed, n) — the reference instead
    LRU-caches the per-index O(rounds) loop (pysetup/spec_builders/
    phase0.py:48-105). Identity of the two forms is tested.
  * Epoch accounting (rewards/penalties) also has a columnar fast path
    (ops/state_columns.py) used when the validator set is large; the
    object-path here is the semantics oracle.
"""

from dataclasses import dataclass, field

from eth_consensus_specs_tpu.config import FrozenNamespace
from eth_consensus_specs_tpu.ssz import (
    Bitlist,
    Bitvector,
    ByteList,
    ByteVector,
    Bytes4,
    Bytes32,
    Bytes48,
    Bytes96,
    Container,
    List,
    Vector,
    boolean,
    hash_tree_root,
    uint8,
    uint32,
    uint64,
)
from eth_consensus_specs_tpu.ssz.hashing import hash_bytes
from eth_consensus_specs_tpu.ssz.merkle import is_valid_merkle_branch
from eth_consensus_specs_tpu.utils import bls

# -- aliases (custom types; reference: specs/phase0/beacon-chain.md types table)
Slot = uint64
Epoch = uint64
CommitteeIndex = uint64
ValidatorIndex = uint64
Gwei = uint64
Root = Bytes32
Version = Bytes4
DomainType = Bytes4
ForkDigest = Bytes4
Domain = Bytes32
BLSPubkey = Bytes48
BLSSignature = Bytes96


class Phase0Spec:
    fork_name = "phase0"

    # -- constants (non-preset; beacon-chain.md constants table) -----------
    GENESIS_SLOT = 0
    GENESIS_EPOCH = 0
    FAR_FUTURE_EPOCH = 2**64 - 1
    BASE_REWARDS_PER_EPOCH = 4
    DEPOSIT_CONTRACT_TREE_DEPTH = 32
    JUSTIFICATION_BITS_LENGTH = 4
    ENDIANNESS = "little"
    BLS_WITHDRAWAL_PREFIX = b"\x00"
    ETH1_ADDRESS_WITHDRAWAL_PREFIX = b"\x01"

    DOMAIN_BEACON_PROPOSER = DomainType(b"\x00\x00\x00\x00")
    DOMAIN_BEACON_ATTESTER = DomainType(b"\x01\x00\x00\x00")
    DOMAIN_RANDAO = DomainType(b"\x02\x00\x00\x00")
    DOMAIN_DEPOSIT = DomainType(b"\x03\x00\x00\x00")
    DOMAIN_VOLUNTARY_EXIT = DomainType(b"\x04\x00\x00\x00")
    DOMAIN_SELECTION_PROOF = DomainType(b"\x05\x00\x00\x00")
    DOMAIN_AGGREGATE_AND_PROOF = DomainType(b"\x06\x00\x00\x00")
    DOMAIN_APPLICATION_MASK = DomainType(b"\x00\x00\x00\x01")

    TARGET_AGGREGATORS_PER_COMMITTEE = 16
    ATTESTATION_SUBNET_COUNT = 64

    # safe-block / ws defaults
    SAFETY_DECAY = 10

    def __init__(self, preset: FrozenNamespace, config: FrozenNamespace, preset_name: str = "mainnet"):
        self.preset = preset
        self.config = config
        self.preset_name = preset_name
        # expose preset constants as attributes (compile-time tier)
        for k, v in preset.items():
            setattr(self, k, v)
        self._shuffle_cache: dict[tuple[bytes, int], object] = {}
        self._build_types()

    # == type system ======================================================

    def _build_types(self) -> None:
        """Construct per-preset SSZ container types (static shapes)."""
        P = self  # preset-sized

        class Fork(Container):
            previous_version: Version
            current_version: Version
            epoch: Epoch

        class ForkData(Container):
            current_version: Version
            genesis_validators_root: Root

        class Checkpoint(Container):
            epoch: Epoch
            root: Root

        class Validator(Container):
            pubkey: BLSPubkey
            withdrawal_credentials: Bytes32
            effective_balance: Gwei
            slashed: boolean
            activation_eligibility_epoch: Epoch
            activation_epoch: Epoch
            exit_epoch: Epoch
            withdrawable_epoch: Epoch

        class AttestationData(Container):
            slot: Slot
            index: CommitteeIndex
            beacon_block_root: Root
            source: Checkpoint
            target: Checkpoint

        class IndexedAttestation(Container):
            attesting_indices: List[ValidatorIndex, P.MAX_VALIDATORS_PER_COMMITTEE]
            data: AttestationData
            signature: BLSSignature

        class PendingAttestation(Container):
            aggregation_bits: Bitlist[P.MAX_VALIDATORS_PER_COMMITTEE]
            data: AttestationData
            inclusion_delay: Slot
            proposer_index: ValidatorIndex

        class Eth1Data(Container):
            deposit_root: Root
            deposit_count: uint64
            block_hash: Bytes32

        class HistoricalBatch(Container):
            block_roots: Vector[Root, P.SLOTS_PER_HISTORICAL_ROOT]
            state_roots: Vector[Root, P.SLOTS_PER_HISTORICAL_ROOT]

        class DepositMessage(Container):
            pubkey: BLSPubkey
            withdrawal_credentials: Bytes32
            amount: Gwei

        class DepositData(Container):
            pubkey: BLSPubkey
            withdrawal_credentials: Bytes32
            amount: Gwei
            signature: BLSSignature

        class BeaconBlockHeader(Container):
            slot: Slot
            proposer_index: ValidatorIndex
            parent_root: Root
            state_root: Root
            body_root: Root

        class SigningData(Container):
            object_root: Root
            domain: Domain

        class SignedBeaconBlockHeader(Container):
            message: BeaconBlockHeader
            signature: BLSSignature

        class ProposerSlashing(Container):
            signed_header_1: SignedBeaconBlockHeader
            signed_header_2: SignedBeaconBlockHeader

        class AttesterSlashing(Container):
            attestation_1: IndexedAttestation
            attestation_2: IndexedAttestation

        class Attestation(Container):
            aggregation_bits: Bitlist[P.MAX_VALIDATORS_PER_COMMITTEE]
            data: AttestationData
            signature: BLSSignature

        class Deposit(Container):
            proof: Vector[Bytes32, self.DEPOSIT_CONTRACT_TREE_DEPTH + 1]
            data: DepositData

        class VoluntaryExit(Container):
            epoch: Epoch
            validator_index: ValidatorIndex

        class SignedVoluntaryExit(Container):
            message: VoluntaryExit
            signature: BLSSignature

        class BeaconBlockBody(Container):
            randao_reveal: BLSSignature
            eth1_data: Eth1Data
            graffiti: Bytes32
            proposer_slashings: List[ProposerSlashing, P.MAX_PROPOSER_SLASHINGS]
            attester_slashings: List[AttesterSlashing, P.MAX_ATTESTER_SLASHINGS]
            attestations: List[Attestation, P.MAX_ATTESTATIONS]
            deposits: List[Deposit, P.MAX_DEPOSITS]
            voluntary_exits: List[SignedVoluntaryExit, P.MAX_VOLUNTARY_EXITS]

        class BeaconBlock(Container):
            slot: Slot
            proposer_index: ValidatorIndex
            parent_root: Root
            state_root: Root
            body: BeaconBlockBody

        class SignedBeaconBlock(Container):
            message: BeaconBlock
            signature: BLSSignature

        class BeaconState(Container):
            genesis_time: uint64
            genesis_validators_root: Root
            slot: Slot
            fork: Fork
            latest_block_header: BeaconBlockHeader
            block_roots: Vector[Root, P.SLOTS_PER_HISTORICAL_ROOT]
            state_roots: Vector[Root, P.SLOTS_PER_HISTORICAL_ROOT]
            historical_roots: List[Root, P.HISTORICAL_ROOTS_LIMIT]
            eth1_data: Eth1Data
            eth1_data_votes: List[Eth1Data, P.EPOCHS_PER_ETH1_VOTING_PERIOD * P.SLOTS_PER_EPOCH]
            eth1_deposit_index: uint64
            validators: List[Validator, P.VALIDATOR_REGISTRY_LIMIT]
            balances: List[Gwei, P.VALIDATOR_REGISTRY_LIMIT]
            randao_mixes: Vector[Bytes32, P.EPOCHS_PER_HISTORICAL_VECTOR]
            slashings: Vector[Gwei, P.EPOCHS_PER_SLASHINGS_VECTOR]
            previous_epoch_attestations: List[PendingAttestation, P.MAX_ATTESTATIONS * P.SLOTS_PER_EPOCH]
            current_epoch_attestations: List[PendingAttestation, P.MAX_ATTESTATIONS * P.SLOTS_PER_EPOCH]
            justification_bits: Bitvector[self.JUSTIFICATION_BITS_LENGTH]
            previous_justified_checkpoint: Checkpoint
            current_justified_checkpoint: Checkpoint
            finalized_checkpoint: Checkpoint

        class Eth1Block(Container):
            # honest-validator abstraction of an eth1 block
            # (reference: specs/phase0/validator.md:121-126)
            timestamp: uint64
            deposit_root: Root
            deposit_count: uint64

        class AggregateAndProof(Container):
            aggregator_index: ValidatorIndex
            aggregate: Attestation
            selection_proof: BLSSignature

        class SignedAggregateAndProof(Container):
            message: AggregateAndProof
            signature: BLSSignature

        for name, typ in list(locals().items()):
            if isinstance(typ, type) and issubclass(typ, Container):
                typ.__name__ = name
                setattr(self, name, typ)

        # custom-type aliases on the spec surface, as in the generated
        # reference modules (spec.Root, spec.Slot, ...)
        self.Slot = Slot
        self.Epoch = Epoch
        self.CommitteeIndex = CommitteeIndex
        self.ValidatorIndex = ValidatorIndex
        self.Gwei = Gwei
        self.Root = Root
        self.Version = Version
        self.DomainType = DomainType
        self.ForkDigest = ForkDigest
        self.Domain = Domain
        self.BLSPubkey = BLSPubkey
        self.BLSSignature = BLSSignature

    # == math / serialization helpers =====================================

    @staticmethod
    def integer_squareroot(n: int) -> int:
        import math

        if n < 0 or n >= 2**64:
            raise ValueError("integer_squareroot: input out of uint64 range")
        return math.isqrt(n)

    @staticmethod
    def xor(a: bytes, b: bytes) -> Bytes32:
        return Bytes32(bytes(x ^ y for x, y in zip(a, b)))

    @staticmethod
    def uint_to_bytes(n, length: int = None) -> bytes:  # type: ignore[assignment]
        if isinstance(n, uint64) and length is None:
            return int(n).to_bytes(8, "little")
        if length is None:
            length = 8
        return int(n).to_bytes(length, "little")

    @staticmethod
    def bytes_to_uint64(data: bytes) -> int:
        return int.from_bytes(data, "little")

    @staticmethod
    def hash(data: bytes) -> Bytes32:
        return Bytes32(hash_bytes(bytes(data)))

    @staticmethod
    def hash_tree_root(obj) -> Root:
        return hash_tree_root(obj)

    # == predicates =======================================================

    def is_active_validator(self, validator, epoch: int) -> bool:
        return validator.activation_epoch <= epoch < validator.exit_epoch

    def is_eligible_for_activation_queue(self, validator) -> bool:
        return (
            validator.activation_eligibility_epoch == self.FAR_FUTURE_EPOCH
            and validator.effective_balance == self.MAX_EFFECTIVE_BALANCE
        )

    def is_eligible_for_activation(self, state, validator) -> bool:
        return (
            validator.activation_eligibility_epoch <= state.finalized_checkpoint.epoch
            and validator.activation_epoch == self.FAR_FUTURE_EPOCH
        )

    def is_slashable_validator(self, validator, epoch: int) -> bool:
        return (not validator.slashed) and (
            validator.activation_epoch <= epoch < validator.withdrawable_epoch
        )

    def is_slashable_attestation_data(self, data_1, data_2) -> bool:
        # double vote or surround vote (reference: beacon-chain.md:759-771)
        return (data_1 != data_2 and data_1.target.epoch == data_2.target.epoch) or (
            data_1.source.epoch < data_2.source.epoch and data_2.target.epoch < data_1.target.epoch
        )

    def _indexed_attestation_signature_inputs(self, state, indexed_attestation):
        """(pubkeys, signing_root) for an indexed attestation's aggregate
        signature — the ONE place the verification triple is assembled, so
        the per-attestation check and the block-level batch can never
        diverge on what they prove."""
        pubkeys = [
            state.validators[i].pubkey for i in indexed_attestation.attesting_indices
        ]
        domain = self.get_domain(
            state, self.DOMAIN_BEACON_ATTESTER, indexed_attestation.data.target.epoch
        )
        signing_root = self.compute_signing_root(indexed_attestation.data, domain)
        return pubkeys, signing_root

    def is_valid_indexed_attestation(self, state, indexed_attestation) -> bool:
        indices = list(indexed_attestation.attesting_indices)
        if len(indices) == 0 or not indices == sorted(set(indices)):
            return False
        if self._attestation_sigs_preverified:
            # signatures already proven by the block-level RLC batch
            # (one pairing per block, _batch_verify_attestations)
            return True
        pubkeys, signing_root = self._indexed_attestation_signature_inputs(
            state, indexed_attestation
        )
        return bls.FastAggregateVerify(pubkeys, signing_root, indexed_attestation.signature)

    _attestation_sigs_preverified = False

    def _batch_verify_attestations(self, state, attestations) -> bool:
        """One RLC pairing for all block attestations (the live batch seam,
        SURVEY §2.3 DP axis #1). False means 'not proven here' — the caller
        falls back to per-attestation verification, so an invalid signature
        still fails at the exact spec assertion. Sound because nothing a
        block's earlier operations mutate (registry keys, committees,
        domains) feeds these signatures."""
        if not bls.bls_active or len(attestations) < 2:
            return False
        from eth_consensus_specs_tpu.ops import bls_batch

        items = []
        for attestation in attestations:
            try:
                indexed = self.get_indexed_attestation(state, attestation)
                indices = list(indexed.attesting_indices)
                if len(indices) == 0 or indices != sorted(set(indices)):
                    return False
                pubkeys, signing_root = self._indexed_attestation_signature_inputs(
                    state, indexed
                )
            except (AssertionError, IndexError, KeyError, ValueError):
                # malformed attestation (bad committee index, oversized
                # bitlist, ...): not proven here — the sequential path
                # rejects it at the exact spec assertion
                return False
            items.append(
                ([bytes(pk) for pk in pubkeys], bytes(signing_root), bytes(indexed.signature))
            )
        return bls_batch.batch_verify_aggregates(items)

    def _process_attestations(self, state, attestations) -> None:
        """Attestation loop with the batch-verification flag scoped around
        it — shared by every fork's process_operations override."""
        self._attestation_sigs_preverified = self._batch_verify_attestations(
            state, attestations
        )
        try:
            for operation in attestations:
                self.process_attestation(state, operation)
        finally:
            self._attestation_sigs_preverified = False

    def is_valid_merkle_branch(self, leaf, branch, depth: int, index: int, root) -> bool:
        return is_valid_merkle_branch(bytes(leaf), [bytes(b) for b in branch], depth, int(index), bytes(root))

    # == misc computations ================================================

    def compute_shuffled_index(self, index: int, index_count: int, seed: bytes) -> int:
        """Single-index swap-or-not (spec form; whole-permutation kernel in
        ops/shuffle.py is the production path; identity is tested)."""
        assert index < index_count
        for current_round in range(self.SHUFFLE_ROUND_COUNT):
            pivot = self.bytes_to_uint64(
                self.hash(seed + bytes([current_round]))[:8]
            ) % index_count
            flip = (pivot + index_count - index) % index_count
            position = max(index, flip)
            source = self.hash(
                seed + bytes([current_round]) + self.uint_to_bytes(uint32(position // 256), 4)
            )
            byte_val = source[(position % 256) // 8]
            bit = (byte_val >> (position % 8)) % 2
            index = flip if bit else index
        return index

    def _shuffle_permutation(self, index_count: int, seed: bytes):
        """Whole permutation, cached by (seed, n). perm[i] ==
        compute_shuffled_index(i, n, seed). On an accelerator backend large
        registries go through the device kernel (ops/shuffle.py
        shuffle_permutation_device, bit-equal by test); small sets and CPU
        runs keep the numpy host form."""
        key = (bytes(seed), index_count)
        if key not in self._shuffle_cache:
            perm = None
            if index_count >= (1 << 12):
                try:
                    import jax

                    if jax.default_backend() != "cpu":
                        import numpy as _np

                        from eth_consensus_specs_tpu.ops.shuffle import (
                            shuffle_permutation_device,
                        )

                        perm = _np.asarray(
                            shuffle_permutation_device(
                                index_count, bytes(seed), self.SHUFFLE_ROUND_COUNT
                            )
                        ).astype(_np.int64)
                except Exception:
                    perm = None
            if perm is None:
                from eth_consensus_specs_tpu.ops.shuffle import shuffle_permutation

                perm = shuffle_permutation(
                    index_count, bytes(seed), self.SHUFFLE_ROUND_COUNT
                )
            self._shuffle_cache[key] = perm
            if len(self._shuffle_cache) > 64:
                self._shuffle_cache.pop(next(iter(self._shuffle_cache)))
        return self._shuffle_cache[key]

    def compute_proposer_index(self, state, indices, seed: bytes) -> int:
        assert len(indices) > 0
        MAX_RANDOM_BYTE = 2**8 - 1
        total = len(indices)
        perm = self._shuffle_permutation(total, seed)
        i = 0
        while True:
            candidate_index = indices[int(perm[i % total])]
            random_byte = self.hash(seed + self.uint_to_bytes(uint64(i // 32)))[i % 32]
            effective_balance = state.validators[candidate_index].effective_balance
            if effective_balance * MAX_RANDOM_BYTE >= self.MAX_EFFECTIVE_BALANCE * random_byte:
                return int(candidate_index)
            i += 1

    def compute_committee(self, indices, seed: bytes, index: int, count: int):
        n = len(indices)
        start = n * index // count
        end = n * (index + 1) // count
        perm = self._shuffle_permutation(n, seed)
        return [indices[int(perm[i])] for i in range(start, end)]

    def compute_epoch_at_slot(self, slot: int) -> int:
        return int(slot) // self.SLOTS_PER_EPOCH

    def compute_start_slot_at_epoch(self, epoch: int) -> int:
        return int(epoch) * self.SLOTS_PER_EPOCH

    def compute_activation_exit_epoch(self, epoch: int) -> int:
        return int(epoch) + 1 + self.MAX_SEED_LOOKAHEAD

    def compute_fork_data_root(self, current_version, genesis_validators_root) -> Root:
        return hash_tree_root(
            self.ForkData(
                current_version=current_version,
                genesis_validators_root=genesis_validators_root,
            )
        )

    # == networking helpers (p2p gossip topic selection) ===================

    def compute_subnet_for_attestation(
        self, committees_per_slot: int, slot: int, committee_index: int
    ) -> int:
        """Gossip subnet for an unaggregated attestation (reference:
        specs/phase0/validator.md:703-714)."""
        slots_since_epoch_start = int(slot) % self.SLOTS_PER_EPOCH
        committees_since_epoch_start = int(committees_per_slot) * slots_since_epoch_start
        return (committees_since_epoch_start + int(committee_index)) % int(
            self.config.ATTESTATION_SUBNET_COUNT
        )

    def compute_subscribed_subnet(self, node_id: int, epoch: int, index: int) -> int:
        """Deterministic long-lived subnet for a node (reference:
        specs/phase0/p2p-interface.md:1344-1355): the node-id prefix walks
        a shuffled 2^prefix ring re-seeded each subscription period."""
        cfg = self.config
        node_id_bits = 256
        prefix_bits = int(cfg.ATTESTATION_SUBNET_PREFIX_BITS)
        node_id_prefix = int(node_id) >> (node_id_bits - prefix_bits)
        node_offset = int(node_id) % int(cfg.EPOCHS_PER_SUBNET_SUBSCRIPTION)
        permutation_seed = self.hash(
            self.uint_to_bytes(
                uint64(
                    (int(epoch) + node_offset) // int(cfg.EPOCHS_PER_SUBNET_SUBSCRIPTION)
                )
            )
        )
        permutated_prefix = self.compute_shuffled_index(
            node_id_prefix, 1 << prefix_bits, permutation_seed
        )
        return (int(permutated_prefix) + int(index)) % int(cfg.ATTESTATION_SUBNET_COUNT)

    def compute_subscribed_subnets(self, node_id: int, epoch: int) -> list[int]:
        """reference: specs/phase0/p2p-interface.md:1359-1361."""
        return [
            self.compute_subscribed_subnet(node_id, epoch, index)
            for index in range(int(self.config.SUBNETS_PER_NODE))
        ]

    def compute_fork_digest(self, current_version, genesis_validators_root) -> ForkDigest:
        return ForkDigest(
            bytes(self.compute_fork_data_root(current_version, genesis_validators_root))[:4]
        )

    def compute_domain(self, domain_type, fork_version=None, genesis_validators_root=None) -> Domain:
        if fork_version is None:
            fork_version = self.config.GENESIS_FORK_VERSION
        if genesis_validators_root is None:
            genesis_validators_root = Root()
        fork_data_root = self.compute_fork_data_root(Version(fork_version), genesis_validators_root)
        return Domain(bytes(domain_type) + bytes(fork_data_root)[:28])

    def compute_signing_root(self, ssz_object, domain) -> Root:
        return hash_tree_root(
            self.SigningData(object_root=hash_tree_root(ssz_object), domain=Domain(domain))
        )

    # == accessors ========================================================

    def get_current_epoch(self, state) -> int:
        return self.compute_epoch_at_slot(state.slot)

    def get_previous_epoch(self, state) -> int:
        current = self.get_current_epoch(state)
        return self.GENESIS_EPOCH if current == self.GENESIS_EPOCH else current - 1

    def get_block_root(self, state, epoch: int) -> Root:
        return self.get_block_root_at_slot(state, self.compute_start_slot_at_epoch(epoch))

    def get_block_root_at_slot(self, state, slot: int) -> Root:
        assert slot < state.slot <= slot + self.SLOTS_PER_HISTORICAL_ROOT
        return state.block_roots[int(slot) % self.SLOTS_PER_HISTORICAL_ROOT]

    def get_randao_mix(self, state, epoch: int) -> Bytes32:
        return state.randao_mixes[int(epoch) % self.EPOCHS_PER_HISTORICAL_VECTOR]

    def get_active_validator_indices(self, state, epoch: int):
        return [
            i for i, v in enumerate(state.validators) if self.is_active_validator(v, epoch)
        ]

    def get_validator_churn_limit(self, state) -> int:
        active = self.get_active_validator_indices(state, self.get_current_epoch(state))
        return max(
            self.config.MIN_PER_EPOCH_CHURN_LIMIT, len(active) // self.config.CHURN_LIMIT_QUOTIENT
        )

    def get_seed(self, state, epoch: int, domain_type) -> Bytes32:
        mix = self.get_randao_mix(
            state, int(epoch) + self.EPOCHS_PER_HISTORICAL_VECTOR - self.MIN_SEED_LOOKAHEAD - 1
        )
        return self.hash(bytes(domain_type) + self.uint_to_bytes(uint64(epoch)) + bytes(mix))

    def get_committee_count_per_slot(self, state, epoch: int) -> int:
        active = len(self.get_active_validator_indices(state, epoch))
        return max(
            1,
            min(
                self.MAX_COMMITTEES_PER_SLOT,
                active // self.SLOTS_PER_EPOCH // self.TARGET_COMMITTEE_SIZE,
            ),
        )

    def get_beacon_committee(self, state, slot: int, index: int):
        epoch = self.compute_epoch_at_slot(slot)
        committees_per_slot = self.get_committee_count_per_slot(state, epoch)
        return self.compute_committee(
            indices=self.get_active_validator_indices(state, epoch),
            seed=self.get_seed(state, epoch, self.DOMAIN_BEACON_ATTESTER),
            index=(int(slot) % self.SLOTS_PER_EPOCH) * committees_per_slot + int(index),
            count=committees_per_slot * self.SLOTS_PER_EPOCH,
        )

    def get_beacon_proposer_index(self, state) -> int:
        epoch = self.get_current_epoch(state)
        seed = self.hash(
            bytes(self.get_seed(state, epoch, self.DOMAIN_BEACON_PROPOSER))
            + self.uint_to_bytes(uint64(state.slot))
        )
        indices = self.get_active_validator_indices(state, epoch)
        return self.compute_proposer_index(state, indices, seed)

    def get_total_balance(self, state, indices) -> int:
        return max(
            self.EFFECTIVE_BALANCE_INCREMENT,
            sum(int(state.validators[i].effective_balance) for i in set(indices)),
        )

    def get_total_active_balance(self, state) -> int:
        return self.get_total_balance(
            state, set(self.get_active_validator_indices(state, self.get_current_epoch(state)))
        )

    def get_domain(self, state, domain_type, epoch=None) -> Domain:
        epoch = self.get_current_epoch(state) if epoch is None else int(epoch)
        fork_version = (
            state.fork.previous_version if epoch < state.fork.epoch else state.fork.current_version
        )
        return self.compute_domain(domain_type, fork_version, state.genesis_validators_root)

    def get_indexed_attestation(self, state, attestation):
        attesting_indices = self.get_attesting_indices(state, attestation)
        return self.IndexedAttestation(
            attesting_indices=sorted(attesting_indices),
            data=attestation.data,
            signature=attestation.signature,
        )

    def get_attesting_indices(self, state, attestation):
        committee = self.get_beacon_committee(state, attestation.data.slot, attestation.data.index)
        return {
            int(committee[i]) for i, bit in enumerate(attestation.aggregation_bits) if bit
        }

    # == mutators =========================================================

    def increase_balance(self, state, index: int, delta: int) -> None:
        state.balances[int(index)] = int(state.balances[int(index)]) + int(delta)

    def decrease_balance(self, state, index: int, delta: int) -> None:
        bal = int(state.balances[int(index)])
        state.balances[int(index)] = 0 if int(delta) > bal else bal - int(delta)

    def initiate_validator_exit(self, state, index: int) -> None:
        validator = state.validators[int(index)]
        if validator.exit_epoch != self.FAR_FUTURE_EPOCH:
            return
        exit_epochs = [
            int(v.exit_epoch) for v in state.validators if v.exit_epoch != self.FAR_FUTURE_EPOCH
        ]
        exit_queue_epoch = max(
            exit_epochs + [self.compute_activation_exit_epoch(self.get_current_epoch(state))]
        )
        exit_queue_churn = len(
            [v for v in state.validators if v.exit_epoch == exit_queue_epoch]
        )
        if exit_queue_churn >= self.get_validator_churn_limit(state):
            exit_queue_epoch += 1
        validator.exit_epoch = exit_queue_epoch
        validator.withdrawable_epoch = (
            int(validator.exit_epoch) + self.config.MIN_VALIDATOR_WITHDRAWABILITY_DELAY
        )

    # fork-tunable slashing knobs — later forks re-point these constants
    # (e.g. *_ALTAIR, *_BELLATRIX) without re-stating the slashing logic
    def min_slashing_penalty_quotient(self) -> int:
        return self.MIN_SLASHING_PENALTY_QUOTIENT

    def proportional_slashing_multiplier(self) -> int:
        return self.PROPORTIONAL_SLASHING_MULTIPLIER

    def whistleblower_proposer_reward(self, whistleblower_reward: int) -> int:
        return whistleblower_reward // self.PROPOSER_REWARD_QUOTIENT

    def whistleblower_reward_quotient(self) -> int:
        return self.WHISTLEBLOWER_REWARD_QUOTIENT

    def slash_validator(self, state, slashed_index: int, whistleblower_index=None) -> None:
        epoch = self.get_current_epoch(state)
        self.initiate_validator_exit(state, slashed_index)
        validator = state.validators[int(slashed_index)]
        validator.slashed = True
        validator.withdrawable_epoch = max(
            int(validator.withdrawable_epoch), epoch + self.EPOCHS_PER_SLASHINGS_VECTOR
        )
        state.slashings[epoch % self.EPOCHS_PER_SLASHINGS_VECTOR] = (
            int(state.slashings[epoch % self.EPOCHS_PER_SLASHINGS_VECTOR])
            + int(validator.effective_balance)
        )
        self.decrease_balance(
            state,
            slashed_index,
            int(validator.effective_balance) // self.min_slashing_penalty_quotient(),
        )
        # proposer + whistleblower rewards
        proposer_index = self.get_beacon_proposer_index(state)
        if whistleblower_index is None:
            whistleblower_index = proposer_index
        whistleblower_reward = int(validator.effective_balance) // self.whistleblower_reward_quotient()
        proposer_reward = self.whistleblower_proposer_reward(whistleblower_reward)
        self.increase_balance(state, proposer_index, proposer_reward)
        self.increase_balance(state, whistleblower_index, whistleblower_reward - proposer_reward)

    # == genesis ==========================================================

    def initialize_beacon_state_from_eth1(self, eth1_block_hash, eth1_timestamp, deposits):
        fork = self.Fork(
            previous_version=Version(self.config.GENESIS_FORK_VERSION),
            current_version=Version(self.config.GENESIS_FORK_VERSION),
            epoch=self.GENESIS_EPOCH,
        )
        state = self.BeaconState(
            genesis_time=int(eth1_timestamp) + self.config.GENESIS_DELAY,
            fork=fork,
            eth1_data=self.Eth1Data(
                deposit_count=len(deposits), block_hash=Bytes32(eth1_block_hash)
            ),
            latest_block_header=self.BeaconBlockHeader(
                body_root=hash_tree_root(self.BeaconBlockBody())
            ),
            randao_mixes=self.BeaconState.fields()["randao_mixes"](
                [Bytes32(eth1_block_hash)] * self.EPOCHS_PER_HISTORICAL_VECTOR
            ),
        )
        # apply deposits with an incrementally-updated deposit root
        leaves = [d.data for d in deposits]
        DepositDataList = List[self.DepositData, 2**self.DEPOSIT_CONTRACT_TREE_DEPTH]
        for index, deposit in enumerate(deposits):
            state.eth1_data.deposit_root = hash_tree_root(DepositDataList(leaves[: index + 1]))
            self.process_deposit(state, deposit)
        # finalize activations
        for index, validator in enumerate(state.validators):
            balance = int(state.balances[index])
            validator.effective_balance = min(
                balance - balance % self.EFFECTIVE_BALANCE_INCREMENT, self.MAX_EFFECTIVE_BALANCE
            )
            if validator.effective_balance == self.MAX_EFFECTIVE_BALANCE:
                validator.activation_eligibility_epoch = self.GENESIS_EPOCH
                validator.activation_epoch = self.GENESIS_EPOCH
        state.genesis_validators_root = hash_tree_root(state.validators)
        return state

    def is_valid_genesis_state(self, state) -> bool:
        if state.genesis_time < self.config.MIN_GENESIS_TIME:
            return False
        return (
            len(self.get_active_validator_indices(state, self.GENESIS_EPOCH))
            >= self.config.MIN_GENESIS_ACTIVE_VALIDATOR_COUNT
        )

    # == state transition =================================================

    def state_transition(self, state, signed_block, validate_result: bool = True):
        block = signed_block.message
        self.process_slots(state, block.slot)
        if validate_result:
            assert self.verify_block_signature(state, signed_block)
        self.process_block(state, block)
        if validate_result:
            assert block.state_root == hash_tree_root(state), "invalid post-state root"

    def verify_block_signature(self, state, signed_block) -> bool:
        proposer = state.validators[int(signed_block.message.proposer_index)]
        signing_root = self.compute_signing_root(
            signed_block.message, self.get_domain(state, self.DOMAIN_BEACON_PROPOSER)
        )
        return bls.Verify(proposer.pubkey, signing_root, signed_block.signature)

    def process_slots(self, state, slot: int) -> None:
        assert state.slot < slot
        while state.slot < slot:
            self.process_slot(state)
            if (int(state.slot) + 1) % self.SLOTS_PER_EPOCH == 0:
                self.process_epoch(state)
            state.slot = int(state.slot) + 1

    def process_slot(self, state) -> None:
        previous_state_root = hash_tree_root(state)
        state.state_roots[int(state.slot) % self.SLOTS_PER_HISTORICAL_ROOT] = previous_state_root
        if state.latest_block_header.state_root == Bytes32():
            state.latest_block_header.state_root = previous_state_root
        previous_block_root = hash_tree_root(state.latest_block_header)
        state.block_roots[int(state.slot) % self.SLOTS_PER_HISTORICAL_ROOT] = previous_block_root

    # -- epoch processing --------------------------------------------------

    def process_epoch_object(self, state) -> None:
        """phase0's process_epoch IS the object path (the pending-
        attestation columnar wrapper stays opt-in); altair+ override both
        and flip the default to columnar."""
        self.process_epoch(state)

    def process_epoch(self, state) -> None:
        self.process_justification_and_finalization(state)
        self.process_rewards_and_penalties(state)
        self.process_registry_updates(state)
        self.process_slashings(state)
        self.process_eth1_data_reset(state)
        self.process_effective_balance_updates(state)
        self._process_epoch_resets(state)

    def _process_epoch_resets(self, state) -> None:
        """Tail resets shared by the object and columnar epoch paths."""
        self.process_slashings_reset(state)
        self.process_randao_mixes_reset(state)
        self.process_historical_roots_update(state)
        self.process_participation_record_updates(state)

    # -- columnar (device) epoch processing --------------------------------

    def _registry_columns(self, state):
        """Per-validator registry arrays shared by every fork's columnar
        extractor: (eff, bal, slashed, activation, exit, withdrawable)."""
        import numpy as np

        n = len(state.validators)
        eff = np.empty(n, np.uint64)
        bal = np.empty(n, np.uint64)
        slashed = np.empty(n, bool)
        act = np.empty(n, np.uint64)
        exitep = np.empty(n, np.uint64)
        wd = np.empty(n, np.uint64)
        for i, v in enumerate(state.validators):
            eff[i] = int(v.effective_balance)
            slashed[i] = bool(v.slashed)
            act[i] = int(v.activation_epoch)
            exitep[i] = int(v.exit_epoch)
            wd[i] = int(v.withdrawable_epoch)
        for i, b in enumerate(state.balances):
            bal[i] = int(b)
        return eff, bal, slashed, act, exitep, wd

    def _justification_state(self, state):
        """Scalar JustificationState snapshot (fork-independent)."""
        import numpy as np

        from eth_consensus_specs_tpu.ops.state_columns import JustificationState

        prev_epoch = self.get_previous_epoch(state)
        cur_epoch = self.get_current_epoch(state)
        return JustificationState(
            current_epoch=np.uint64(cur_epoch),
            justification_bits=np.array(list(state.justification_bits), bool),
            prev_justified_epoch=np.uint64(int(state.previous_justified_checkpoint.epoch)),
            prev_justified_root=np.frombuffer(
                bytes(state.previous_justified_checkpoint.root), np.uint8
            ),
            cur_justified_epoch=np.uint64(int(state.current_justified_checkpoint.epoch)),
            cur_justified_root=np.frombuffer(
                bytes(state.current_justified_checkpoint.root), np.uint8
            ),
            finalized_epoch=np.uint64(int(state.finalized_checkpoint.epoch)),
            finalized_root=np.frombuffer(bytes(state.finalized_checkpoint.root), np.uint8),
            block_root_prev=np.frombuffer(
                bytes(self.get_block_root(state, prev_epoch)), np.uint8
            ),
            block_root_cur=np.frombuffer(
                bytes(self.get_block_root(state, cur_epoch)), np.uint8
            ),
            slashings_sum=np.uint64(sum(int(s) for s in state.slashings)),
        )

    def _writeback_extra(self, state, res) -> None:
        """Fork hook: write back kernel outputs beyond balances/effective
        balances (altair+ adds inactivity scores)."""

    def _writeback_justification(self, state, res) -> None:
        state.previous_justified_checkpoint = self.Checkpoint(
            epoch=int(res.prev_justified_epoch), root=Bytes32(res.prev_justified_root.tobytes())
        )
        state.current_justified_checkpoint = self.Checkpoint(
            epoch=int(res.cur_justified_epoch), root=Bytes32(res.cur_justified_root.tobytes())
        )
        state.finalized_checkpoint = self.Checkpoint(
            epoch=int(res.finalized_epoch), root=Bytes32(res.finalized_root.tobytes())
        )
        state.justification_bits = self.BeaconState.fields()["justification_bits"](
            [bool(b) for b in res.justification_bits]
        )

    def _writeback_balances(self, state, res, include_eff: bool = True) -> None:
        new_bal = [int(x) for x in res.balance]
        for i in range(len(new_bal)):
            state.balances[i] = new_bal[i]
        if include_eff:
            new_eff = res.effective_balance
            for i, v in enumerate(state.validators):
                ne = int(new_eff[i])
                if int(v.effective_balance) != ne:
                    v.effective_balance = ne

    def _writeback_accounting(self, state, res) -> None:
        """Apply a columnar EpochResult back onto the object state in spec
        order: justification scalars, registry updates (which must see the
        PRE-update effective balances and POST-justification checkpoint),
        balance/effective-balance columns, fork extras, then the resets."""
        self._writeback_justification(state, res)
        self.process_registry_updates(state)
        self._writeback_balances(state, res)
        self._writeback_extra(state, res)
        self.process_eth1_data_reset(state)
        self._process_epoch_resets(state)

    def _shuffled_active_array(self, state, epoch, act_col=None, exit_col=None):
        """Active validator indices in shuffled order as an int64 array —
        committees are contiguous slices of this (compute_committee
        semantics as one gather). With registry columns provided, the
        active set comes from one vectorized compare instead of the
        per-validator Python predicate."""
        import numpy as np

        if act_col is not None:
            e = np.uint64(int(epoch))
            active = np.nonzero((act_col <= e) & (e < exit_col))[0].astype(np.int64)
        else:
            active = np.asarray(
                [int(i) for i in self.get_active_validator_indices(state, epoch)],
                dtype=np.int64,
            )
        seed = self.get_seed(state, epoch, self.DOMAIN_BEACON_ATTESTER)
        perm = np.asarray(self._shuffle_permutation(len(active), bytes(seed)))
        return active[perm]

    def extract_epoch_columns(self, state):
        """Flatten the object-view state into the columnar arrays consumed by
        ops/state_columns.epoch_accounting. Participation is pre-reduced to
        per-component masks here (committee resolution reuses the cached
        whole-permutation shuffle), so the device kernel sees only dense
        vectors. Returns (EpochColumns, JustificationState)."""
        import numpy as np

        from eth_consensus_specs_tpu.ops.state_columns import EpochColumns

        eff, bal, slashed, act, exitep, wd = self._registry_columns(state)
        n = len(state.validators)

        prev_epoch = self.get_previous_epoch(state)
        cur_epoch = self.get_current_epoch(state)
        src = np.zeros(n, bool)
        tgt = np.zeros(n, bool)
        head = np.zeros(n, bool)
        cur_tgt = np.zeros(n, bool)
        proposer = np.zeros(n, np.int64)
        # min inclusion delay per attester; kernel clamps the non-attester max
        best = np.full(n, np.iinfo(np.uint64).max, np.uint64)

        # Vectorized attester resolution: one cached whole-permutation
        # shuffle per epoch, committees as array SLICES of the shuffled
        # active set, membership bits as dense bool arrays — no per-member
        # Python loop (round-2 verdict weak #4; reference per-index path:
        # specs/phase0/beacon-chain.md:816-836 + compute_committee :863-876).
        shuffled_by_epoch: dict = {}

        def committee_arr(slot, index):
            epoch_a = self.compute_epoch_at_slot(slot)
            if epoch_a not in shuffled_by_epoch:
                shuffled_by_epoch[epoch_a] = self._shuffled_active_array(
                    state, epoch_a, act_col=act, exit_col=exitep
                )
            shuffled = shuffled_by_epoch[epoch_a]
            cps = self.get_committee_count_per_slot(state, epoch_a)
            total = cps * self.SLOTS_PER_EPOCH
            gi = (int(slot) % self.SLOTS_PER_EPOCH) * cps + int(index)
            m = len(shuffled)
            return shuffled[m * gi // total : m * (gi + 1) // total]

        prev_target_root = self.get_block_root(state, prev_epoch)
        for a in state.previous_epoch_attestations:
            committee = committee_arr(a.data.slot, a.data.index)
            bits = a.aggregation_bits.to_numpy()
            attesters = committee[bits[: len(committee)]]
            d = int(a.inclusion_delay)
            p = int(a.proposer_index)
            is_tgt = a.data.target.root == prev_target_root
            is_head = is_tgt and a.data.beacon_block_root == self.get_block_root_at_slot(
                state, a.data.slot
            )
            src[attesters] = True
            if is_tgt:
                tgt[attesters] = True
            if is_head:
                head[attesters] = True
            better = d < best[attesters]  # strict: first-listed wins ties, like min()
            improved = attesters[better]
            best[improved] = d
            proposer[improved] = p
        cur_target_root = self.get_block_root(state, cur_epoch)
        for a in state.current_epoch_attestations:
            if a.data.target.root != cur_target_root:
                continue
            committee = committee_arr(a.data.slot, a.data.index)
            bits = a.aggregation_bits.to_numpy()
            cur_tgt[committee[bits[: len(committee)]]] = True

        cols = EpochColumns(
            effective_balance=eff,
            balance=bal,
            slashed=slashed,
            activation_epoch=act,
            exit_epoch=exitep,
            withdrawable_epoch=wd,
            src_att=src,
            tgt_att=tgt,
            head_att=head,
            cur_tgt_att=cur_tgt,
            incl_delay=np.minimum(best, np.uint64(1) << np.uint64(32)),
            incl_proposer=proposer,
        )
        return cols, self._justification_state(state)

    def process_epoch_columnar(self, state) -> None:
        """Bit-exact process_epoch with the accounting epoch fused on device
        (ops/state_columns.py; hoisting proof in that module's docstring).
        Registry updates + the cheap resets stay host-side."""
        import jax
        import numpy as np

        from eth_consensus_specs_tpu.ops.state_columns import EpochParams, epoch_accounting

        cols, just = self.extract_epoch_columns(state)
        res = epoch_accounting(EpochParams.from_spec(self), cols, just)
        res = jax.tree_util.tree_map(np.asarray, res)  # one device->host sync
        self._writeback_accounting(state, res)

    def get_matching_source_attestations(self, state, epoch: int):
        assert epoch in (self.get_previous_epoch(state), self.get_current_epoch(state))
        return (
            state.current_epoch_attestations
            if epoch == self.get_current_epoch(state)
            else state.previous_epoch_attestations
        )

    def get_matching_target_attestations(self, state, epoch: int):
        return [
            a
            for a in self.get_matching_source_attestations(state, epoch)
            if a.data.target.root == self.get_block_root(state, epoch)
        ]

    def get_matching_head_attestations(self, state, epoch: int):
        return [
            a
            for a in self.get_matching_target_attestations(state, epoch)
            if a.data.beacon_block_root == self.get_block_root_at_slot(state, a.data.slot)
        ]

    def get_unslashed_attesting_indices(self, state, attestations):
        output = set()
        for a in attestations:
            output |= self.get_attesting_indices_from_data(state, a.data, a.aggregation_bits)
        return {i for i in output if not state.validators[i].slashed}

    def get_attesting_indices_from_data(self, state, data, bits):
        committee = self.get_beacon_committee(state, data.slot, data.index)
        return {int(committee[i]) for i, bit in enumerate(bits) if bit}

    def get_attesting_balance(self, state, attestations) -> int:
        return self.get_total_balance(state, self.get_unslashed_attesting_indices(state, attestations))

    def process_justification_and_finalization(self, state) -> None:
        # skip the first two epochs (no complete previous epoch to account)
        if self.get_current_epoch(state) <= self.GENESIS_EPOCH + 1:
            return
        previous_attestations = self.get_matching_target_attestations(
            state, self.get_previous_epoch(state)
        )
        current_attestations = self.get_matching_target_attestations(
            state, self.get_current_epoch(state)
        )
        total_active_balance = self.get_total_active_balance(state)
        previous_target_balance = self.get_attesting_balance(state, previous_attestations)
        current_target_balance = self.get_attesting_balance(state, current_attestations)
        self.weigh_justification_and_finalization(
            state, total_active_balance, previous_target_balance, current_target_balance
        )

    def weigh_justification_and_finalization(
        self, state, total_active_balance, previous_epoch_target_balance, current_epoch_target_balance
    ) -> None:
        previous_epoch = self.get_previous_epoch(state)
        current_epoch = self.get_current_epoch(state)
        old_previous_justified = state.previous_justified_checkpoint
        old_current_justified = state.current_justified_checkpoint

        state.previous_justified_checkpoint = state.current_justified_checkpoint
        bits = list(state.justification_bits)
        bits = [False] + bits[: self.JUSTIFICATION_BITS_LENGTH - 1]
        if previous_epoch_target_balance * 3 >= total_active_balance * 2:
            state.current_justified_checkpoint = self.Checkpoint(
                epoch=previous_epoch, root=self.get_block_root(state, previous_epoch)
            )
            bits[1] = True
        if current_epoch_target_balance * 3 >= total_active_balance * 2:
            state.current_justified_checkpoint = self.Checkpoint(
                epoch=current_epoch, root=self.get_block_root(state, current_epoch)
            )
            bits[0] = True
        state.justification_bits = self.BeaconState.fields()["justification_bits"](bits)

        # finalization: 2nd/3rd/4th-most-recent epochs justified chains
        if all(bits[1:4]) and int(old_previous_justified.epoch) + 3 == current_epoch:
            state.finalized_checkpoint = old_previous_justified
        if all(bits[1:3]) and int(old_previous_justified.epoch) + 2 == current_epoch:
            state.finalized_checkpoint = old_previous_justified
        if all(bits[0:3]) and int(old_current_justified.epoch) + 2 == current_epoch:
            state.finalized_checkpoint = old_current_justified
        if all(bits[0:2]) and int(old_current_justified.epoch) + 1 == current_epoch:
            state.finalized_checkpoint = old_current_justified

    def get_base_reward(self, state, index: int) -> int:
        total_balance = self.get_total_active_balance(state)
        effective_balance = int(state.validators[int(index)].effective_balance)
        return (
            effective_balance
            * self.BASE_REWARD_FACTOR
            // self.integer_squareroot(total_balance)
            // self.BASE_REWARDS_PER_EPOCH
        )

    def get_proposer_reward(self, state, attesting_index: int) -> int:
        return self.get_base_reward(state, attesting_index) // self.PROPOSER_REWARD_QUOTIENT

    def get_finality_delay(self, state) -> int:
        return self.get_previous_epoch(state) - int(state.finalized_checkpoint.epoch)

    def is_in_inactivity_leak(self, state) -> bool:
        return self.get_finality_delay(state) > self.MIN_EPOCHS_TO_INACTIVITY_PENALTY

    def get_eligible_validator_indices(self, state):
        previous_epoch = self.get_previous_epoch(state)
        return [
            i
            for i, v in enumerate(state.validators)
            if self.is_active_validator(v, previous_epoch)
            or (v.slashed and previous_epoch + 1 < v.withdrawable_epoch)
        ]

    def get_attestation_component_deltas(self, state, attestations):
        rewards = [0] * len(state.validators)
        penalties = [0] * len(state.validators)
        total_balance = self.get_total_active_balance(state)
        unslashed_attesting_indices = self.get_unslashed_attesting_indices(state, attestations)
        attesting_balance = self.get_total_balance(state, unslashed_attesting_indices)
        for index in self.get_eligible_validator_indices(state):
            if index in unslashed_attesting_indices:
                increment = self.EFFECTIVE_BALANCE_INCREMENT
                if self.is_in_inactivity_leak(state):
                    # optimal-participation credit during leaks
                    rewards[index] += self.get_base_reward(state, index)
                else:
                    reward_numerator = self.get_base_reward(state, index) * (
                        attesting_balance // increment
                    )
                    rewards[index] += reward_numerator // (total_balance // increment)
            else:
                penalties[index] += self.get_base_reward(state, index)
        return rewards, penalties

    def get_source_deltas(self, state):
        return self.get_attestation_component_deltas(
            state, self.get_matching_source_attestations(state, self.get_previous_epoch(state))
        )

    def get_target_deltas(self, state):
        return self.get_attestation_component_deltas(
            state, self.get_matching_target_attestations(state, self.get_previous_epoch(state))
        )

    def get_head_deltas(self, state):
        return self.get_attestation_component_deltas(
            state, self.get_matching_head_attestations(state, self.get_previous_epoch(state))
        )

    def get_inclusion_delay_deltas(self, state):
        rewards = [0] * len(state.validators)
        matching_source = self.get_matching_source_attestations(
            state, self.get_previous_epoch(state)
        )
        for index in self.get_unslashed_attesting_indices(state, matching_source):
            attestation = min(
                (
                    a
                    for a in matching_source
                    if index in self.get_attesting_indices_from_data(state, a.data, a.aggregation_bits)
                ),
                key=lambda a: int(a.inclusion_delay),
            )
            rewards[int(attestation.proposer_index)] += self.get_proposer_reward(state, index)
            max_attester_reward = self.get_base_reward(state, index) - self.get_proposer_reward(
                state, index
            )
            rewards[index] += max_attester_reward // int(attestation.inclusion_delay)
        return rewards, [0] * len(state.validators)

    def get_inactivity_penalty_deltas(self, state):
        penalties = [0] * len(state.validators)
        if self.is_in_inactivity_leak(state):
            matching_target_attesting_indices = self.get_unslashed_attesting_indices(
                state, self.get_matching_target_attestations(state, self.get_previous_epoch(state))
            )
            for index in self.get_eligible_validator_indices(state):
                base_reward = self.get_base_reward(state, index)
                penalties[index] += (
                    self.BASE_REWARDS_PER_EPOCH * base_reward
                    - self.get_proposer_reward(state, index)
                )
                if index not in matching_target_attesting_indices:
                    effective_balance = int(state.validators[index].effective_balance)
                    penalties[index] += (
                        effective_balance
                        * self.get_finality_delay(state)
                        // self.INACTIVITY_PENALTY_QUOTIENT
                    )
        return [0] * len(state.validators), penalties

    def get_attestation_deltas(self, state):
        source_rewards, source_penalties = self.get_source_deltas(state)
        target_rewards, target_penalties = self.get_target_deltas(state)
        head_rewards, head_penalties = self.get_head_deltas(state)
        inclusion_rewards, _ = self.get_inclusion_delay_deltas(state)
        _, inactivity_penalties = self.get_inactivity_penalty_deltas(state)
        rewards = [
            source_rewards[i] + target_rewards[i] + head_rewards[i] + inclusion_rewards[i]
            for i in range(len(state.validators))
        ]
        penalties = [
            source_penalties[i] + target_penalties[i] + head_penalties[i] + inactivity_penalties[i]
            for i in range(len(state.validators))
        ]
        return rewards, penalties

    def process_rewards_and_penalties(self, state) -> None:
        if self.get_current_epoch(state) == self.GENESIS_EPOCH:
            return
        rewards, penalties = self.get_attestation_deltas(state)
        for index in range(len(state.validators)):
            self.increase_balance(state, index, rewards[index])
            self.decrease_balance(state, index, penalties[index])

    def process_registry_updates(self, state) -> None:
        current_epoch = self.get_current_epoch(state)
        for index, validator in enumerate(state.validators):
            if self.is_eligible_for_activation_queue(validator):
                validator.activation_eligibility_epoch = current_epoch + 1
            if (
                self.is_active_validator(validator, current_epoch)
                and validator.effective_balance <= self.config.EJECTION_BALANCE
            ):
                self.initiate_validator_exit(state, index)
        activation_queue = sorted(
            [
                index
                for index, validator in enumerate(state.validators)
                if self.is_eligible_for_activation(state, validator)
            ],
            key=lambda index: (int(state.validators[index].activation_eligibility_epoch), index),
        )
        for index in activation_queue[: self.get_validator_churn_limit(state)]:
            state.validators[index].activation_epoch = self.compute_activation_exit_epoch(
                current_epoch
            )

    def process_slashings(self, state) -> None:
        epoch = self.get_current_epoch(state)
        total_balance = self.get_total_active_balance(state)
        adjusted_total_slashing_balance = min(
            sum(int(s) for s in state.slashings) * self.proportional_slashing_multiplier(),
            total_balance,
        )
        for index, validator in enumerate(state.validators):
            if (
                validator.slashed
                and epoch + self.EPOCHS_PER_SLASHINGS_VECTOR // 2 == validator.withdrawable_epoch
            ):
                increment = self.EFFECTIVE_BALANCE_INCREMENT
                penalty_numerator = (
                    int(validator.effective_balance) // increment * adjusted_total_slashing_balance
                )
                penalty = penalty_numerator // total_balance * increment
                self.decrease_balance(state, index, penalty)

    def process_eth1_data_reset(self, state) -> None:
        next_epoch = self.get_current_epoch(state) + 1
        if next_epoch % self.EPOCHS_PER_ETH1_VOTING_PERIOD == 0:
            state.eth1_data_votes = self.BeaconState.fields()["eth1_data_votes"]()

    def process_effective_balance_updates(self, state) -> None:
        hysteresis_increment = self.EFFECTIVE_BALANCE_INCREMENT // self.HYSTERESIS_QUOTIENT
        downward_threshold = hysteresis_increment * self.HYSTERESIS_DOWNWARD_MULTIPLIER
        upward_threshold = hysteresis_increment * self.HYSTERESIS_UPWARD_MULTIPLIER
        for index, validator in enumerate(state.validators):
            balance = int(state.balances[index])
            if (
                balance + downward_threshold < validator.effective_balance
                or int(validator.effective_balance) + upward_threshold < balance
            ):
                validator.effective_balance = min(
                    balance - balance % self.EFFECTIVE_BALANCE_INCREMENT,
                    self.MAX_EFFECTIVE_BALANCE,
                )

    def process_slashings_reset(self, state) -> None:
        next_epoch = self.get_current_epoch(state) + 1
        state.slashings[next_epoch % self.EPOCHS_PER_SLASHINGS_VECTOR] = 0

    def process_randao_mixes_reset(self, state) -> None:
        current_epoch = self.get_current_epoch(state)
        next_epoch = current_epoch + 1
        state.randao_mixes[next_epoch % self.EPOCHS_PER_HISTORICAL_VECTOR] = self.get_randao_mix(
            state, current_epoch
        )

    def process_historical_roots_update(self, state) -> None:
        next_epoch = self.get_current_epoch(state) + 1
        if next_epoch % (self.SLOTS_PER_HISTORICAL_ROOT // self.SLOTS_PER_EPOCH) == 0:
            historical_batch = self.HistoricalBatch(
                block_roots=state.block_roots, state_roots=state.state_roots
            )
            state.historical_roots.append(hash_tree_root(historical_batch))

    def process_participation_record_updates(self, state) -> None:
        state.previous_epoch_attestations = state.current_epoch_attestations
        state.current_epoch_attestations = self.BeaconState.fields()["current_epoch_attestations"]()

    # -- block processing --------------------------------------------------

    def process_block(self, state, block) -> None:
        self.process_block_header(state, block)
        self.process_randao(state, block.body)
        self.process_eth1_data(state, block.body)
        self.process_operations(state, block.body)

    def process_block_header(self, state, block) -> None:
        assert block.slot == state.slot, "block slot must match state slot"
        assert block.slot > state.latest_block_header.slot, "block must be newer than latest header"
        assert block.proposer_index == self.get_beacon_proposer_index(state), "wrong proposer"
        assert block.parent_root == hash_tree_root(state.latest_block_header), "parent mismatch"
        state.latest_block_header = self.BeaconBlockHeader(
            slot=block.slot,
            proposer_index=block.proposer_index,
            parent_root=block.parent_root,
            state_root=Bytes32(),
            body_root=hash_tree_root(block.body),
        )
        proposer = state.validators[int(block.proposer_index)]
        assert not proposer.slashed, "proposer is slashed"

    def process_randao(self, state, body) -> None:
        epoch = self.get_current_epoch(state)
        proposer = state.validators[self.get_beacon_proposer_index(state)]
        signing_root = self.compute_signing_root(
            uint64(epoch), self.get_domain(state, self.DOMAIN_RANDAO)
        )
        assert bls.Verify(proposer.pubkey, signing_root, body.randao_reveal), "bad randao reveal"
        mix = self.xor(self.get_randao_mix(state, epoch), self.hash(body.randao_reveal))
        state.randao_mixes[epoch % self.EPOCHS_PER_HISTORICAL_VECTOR] = mix

    def process_eth1_data(self, state, body) -> None:
        state.eth1_data_votes.append(body.eth1_data)
        votes = [v for v in state.eth1_data_votes if v == body.eth1_data]
        if len(votes) * 2 > self.EPOCHS_PER_ETH1_VOTING_PERIOD * self.SLOTS_PER_EPOCH:
            state.eth1_data = body.eth1_data

    def process_operations(self, state, body) -> None:
        assert len(body.deposits) == min(
            self.MAX_DEPOSITS,
            int(state.eth1_data.deposit_count) - int(state.eth1_deposit_index),
        ), "wrong deposit count in block"
        for operation in body.proposer_slashings:
            self.process_proposer_slashing(state, operation)
        for operation in body.attester_slashings:
            self.process_attester_slashing(state, operation)
        self._process_attestations(state, body.attestations)
        for operation in body.deposits:
            self.process_deposit(state, operation)
        for operation in body.voluntary_exits:
            self.process_voluntary_exit(state, operation)

    def process_proposer_slashing(self, state, proposer_slashing) -> None:
        header_1 = proposer_slashing.signed_header_1.message
        header_2 = proposer_slashing.signed_header_2.message
        assert header_1.slot == header_2.slot, "headers not for same slot"
        assert header_1.proposer_index == header_2.proposer_index, "headers not by same proposer"
        assert header_1 != header_2, "headers are identical"
        proposer = state.validators[int(header_1.proposer_index)]
        assert self.is_slashable_validator(proposer, self.get_current_epoch(state))
        for signed_header in (proposer_slashing.signed_header_1, proposer_slashing.signed_header_2):
            domain = self.get_domain(
                state,
                self.DOMAIN_BEACON_PROPOSER,
                self.compute_epoch_at_slot(signed_header.message.slot),
            )
            signing_root = self.compute_signing_root(signed_header.message, domain)
            assert bls.Verify(proposer.pubkey, signing_root, signed_header.signature), "bad header sig"
        self.slash_validator(state, header_1.proposer_index)

    def process_attester_slashing(self, state, attester_slashing) -> None:
        attestation_1 = attester_slashing.attestation_1
        attestation_2 = attester_slashing.attestation_2
        assert self.is_slashable_attestation_data(attestation_1.data, attestation_2.data)
        assert self.is_valid_indexed_attestation(state, attestation_1), "attestation_1 invalid"
        assert self.is_valid_indexed_attestation(state, attestation_2), "attestation_2 invalid"
        slashed_any = False
        indices = set(int(i) for i in attestation_1.attesting_indices) & set(
            int(i) for i in attestation_2.attesting_indices
        )
        for index in sorted(indices):
            if self.is_slashable_validator(
                state.validators[index], self.get_current_epoch(state)
            ):
                self.slash_validator(state, index)
                slashed_any = True
        assert slashed_any, "no validator slashed"

    def process_attestation(self, state, attestation) -> None:
        data = attestation.data
        assert data.target.epoch in (
            self.get_previous_epoch(state),
            self.get_current_epoch(state),
        ), "target epoch out of range"
        assert data.target.epoch == self.compute_epoch_at_slot(data.slot), "target/slot mismatch"
        assert (
            int(data.slot) + self.MIN_ATTESTATION_INCLUSION_DELAY
            <= state.slot
            <= int(data.slot) + self.SLOTS_PER_EPOCH
        ), "attestation outside inclusion window"
        assert data.index < self.get_committee_count_per_slot(state, data.target.epoch)

        committee = self.get_beacon_committee(state, data.slot, data.index)
        assert len(attestation.aggregation_bits) == len(committee), "bitlist/committee length mismatch"

        pending_attestation = self.PendingAttestation(
            data=data,
            aggregation_bits=attestation.aggregation_bits,
            inclusion_delay=int(state.slot) - int(data.slot),
            proposer_index=self.get_beacon_proposer_index(state),
        )
        if data.target.epoch == self.get_current_epoch(state):
            assert data.source == state.current_justified_checkpoint, "wrong source checkpoint"
            state.current_epoch_attestations.append(pending_attestation)
        else:
            assert data.source == state.previous_justified_checkpoint, "wrong source checkpoint"
            state.previous_epoch_attestations.append(pending_attestation)

        assert self.is_valid_indexed_attestation(
            state, self.get_indexed_attestation(state, attestation)
        ), "invalid aggregate signature"

    def get_validator_from_deposit(self, pubkey, withdrawal_credentials, amount):
        effective_balance = min(
            int(amount) - int(amount) % self.EFFECTIVE_BALANCE_INCREMENT, self.MAX_EFFECTIVE_BALANCE
        )
        return self.Validator(
            pubkey=pubkey,
            withdrawal_credentials=withdrawal_credentials,
            activation_eligibility_epoch=self.FAR_FUTURE_EPOCH,
            activation_epoch=self.FAR_FUTURE_EPOCH,
            exit_epoch=self.FAR_FUTURE_EPOCH,
            withdrawable_epoch=self.FAR_FUTURE_EPOCH,
            effective_balance=effective_balance,
        )

    def add_validator_to_registry(self, state, pubkey, withdrawal_credentials, amount) -> None:
        state.validators.append(
            self.get_validator_from_deposit(pubkey, withdrawal_credentials, amount)
        )
        state.balances.append(amount)

    def apply_deposit(self, state, pubkey, withdrawal_credentials, amount, signature) -> None:
        validator_pubkeys = [v.pubkey for v in state.validators]
        if pubkey not in validator_pubkeys:
            # new validator: the deposit signature (proof of possession) must
            # verify under the deposit domain (no fork/state dependence)
            deposit_message = self.DepositMessage(
                pubkey=pubkey, withdrawal_credentials=withdrawal_credentials, amount=amount
            )
            domain = self.compute_domain(self.DOMAIN_DEPOSIT)
            signing_root = self.compute_signing_root(deposit_message, domain)
            if not bls.Verify(pubkey, signing_root, signature):
                return  # invalid proof-of-possession: deposit is ignored
            self.add_validator_to_registry(state, pubkey, withdrawal_credentials, amount)
        else:
            index = validator_pubkeys.index(pubkey)
            self.increase_balance(state, index, amount)

    def process_deposit(self, state, deposit) -> None:
        assert self.is_valid_merkle_branch(
            leaf=hash_tree_root(deposit.data),
            branch=deposit.proof,
            depth=self.DEPOSIT_CONTRACT_TREE_DEPTH + 1,  # +1 for the mixed-in list length
            index=int(state.eth1_deposit_index),
            root=state.eth1_data.deposit_root,
        ), "invalid deposit proof"
        state.eth1_deposit_index = int(state.eth1_deposit_index) + 1
        self.apply_deposit(
            state,
            pubkey=deposit.data.pubkey,
            withdrawal_credentials=deposit.data.withdrawal_credentials,
            amount=deposit.data.amount,
            signature=deposit.data.signature,
        )

    def process_voluntary_exit(self, state, signed_voluntary_exit) -> None:
        voluntary_exit = signed_voluntary_exit.message
        validator = state.validators[int(voluntary_exit.validator_index)]
        assert self.is_active_validator(validator, self.get_current_epoch(state)), "not active"
        assert validator.exit_epoch == self.FAR_FUTURE_EPOCH, "already exiting"
        assert self.get_current_epoch(state) >= voluntary_exit.epoch, "exit not yet valid"
        assert (
            self.get_current_epoch(state)
            >= int(validator.activation_epoch) + self.config.SHARD_COMMITTEE_PERIOD
        ), "validator too young to exit"
        domain = self.get_domain(state, self.DOMAIN_VOLUNTARY_EXIT, voluntary_exit.epoch)
        signing_root = self.compute_signing_root(voluntary_exit, domain)
        assert bls.Verify(validator.pubkey, signing_root, signed_voluntary_exit.signature)
        self.initiate_validator_exit(state, voluntary_exit.validator_index)

    # == fork choice (specs/phase0/fork-choice.md) =========================

    @dataclass
    class LatestMessage:
        epoch: int
        root: Bytes32

    @dataclass
    class Store:
        time: int
        genesis_time: int
        justified_checkpoint: object
        finalized_checkpoint: object
        unrealized_justified_checkpoint: object
        unrealized_finalized_checkpoint: object
        proposer_boost_root: Bytes32
        equivocating_indices: set = field(default_factory=set)
        blocks: dict = field(default_factory=dict)
        block_states: dict = field(default_factory=dict)
        block_timeliness: dict = field(default_factory=dict)
        checkpoint_states: dict = field(default_factory=dict)
        latest_messages: dict = field(default_factory=dict)
        unrealized_justifications: dict = field(default_factory=dict)

    PROPOSER_SCORE_BOOST = 40

    def get_forkchoice_store(self, anchor_state, anchor_block):
        assert anchor_block.state_root == hash_tree_root(anchor_state)
        anchor_root = hash_tree_root(anchor_block)
        anchor_epoch = self.get_current_epoch(anchor_state)
        justified_checkpoint = self.Checkpoint(epoch=anchor_epoch, root=anchor_root)
        finalized_checkpoint = self.Checkpoint(epoch=anchor_epoch, root=anchor_root)
        return self.Store(
            time=int(anchor_state.genesis_time)
            + self.config.SECONDS_PER_SLOT * int(anchor_state.slot),
            genesis_time=int(anchor_state.genesis_time),
            justified_checkpoint=justified_checkpoint,
            finalized_checkpoint=finalized_checkpoint,
            unrealized_justified_checkpoint=justified_checkpoint,
            unrealized_finalized_checkpoint=finalized_checkpoint,
            proposer_boost_root=Root(),
            blocks={anchor_root: anchor_block.copy()},
            block_states={anchor_root: anchor_state.copy()},
            checkpoint_states={justified_checkpoint: anchor_state.copy()},
            unrealized_justifications={anchor_root: justified_checkpoint},
        )

    def get_slots_since_genesis(self, store) -> int:
        return (store.time - store.genesis_time) // self.config.SECONDS_PER_SLOT

    def get_current_slot(self, store) -> int:
        return self.GENESIS_SLOT + self.get_slots_since_genesis(store)

    def get_current_store_epoch(self, store) -> int:
        return self.compute_epoch_at_slot(self.get_current_slot(store))

    def compute_slots_since_epoch_start(self, slot: int) -> int:
        return int(slot) - self.compute_start_slot_at_epoch(self.compute_epoch_at_slot(slot))

    def get_ancestor(self, store, root, slot: int):
        block = store.blocks[root]
        if block.slot > slot:
            return self.get_ancestor(store, block.parent_root, slot)
        return root

    def get_checkpoint_block(self, store, root, epoch: int):
        return self.get_ancestor(store, root, self.compute_start_slot_at_epoch(epoch))

    def get_weight(self, store, root) -> int:
        state = store.checkpoint_states[store.justified_checkpoint]
        # active set at the justified state's own epoch (reference:
        # specs/phase0/fork-choice.md:283-288 uses get_current_epoch(state))
        unslashed_and_active_indices = [
            i
            for i in self.get_active_validator_indices(state, self.get_current_epoch(state))
            if not state.validators[i].slashed
        ]
        attestation_score = sum(
            int(state.validators[i].effective_balance)
            for i in unslashed_and_active_indices
            if (
                i in store.latest_messages
                and i not in store.equivocating_indices
                and self.get_ancestor(
                    store, store.latest_messages[i].root, store.blocks[root].slot
                )
                == root
            )
        )
        if store.proposer_boost_root == Root():
            return attestation_score
        proposer_score = 0
        if self.get_ancestor(store, store.proposer_boost_root, store.blocks[root].slot) == root:
            committee_weight = self.get_total_active_balance(state) // self.SLOTS_PER_EPOCH
            proposer_score = (committee_weight * self.config.PROPOSER_SCORE_BOOST) // 100
        return attestation_score + proposer_score

    def get_voting_source(self, store, block_root):
        block = store.blocks[block_root]
        current_epoch = self.get_current_store_epoch(store)
        block_epoch = self.compute_epoch_at_slot(block.slot)
        if current_epoch > block_epoch:
            return store.unrealized_justifications[block_root]
        head_state = store.block_states[block_root]
        return head_state.current_justified_checkpoint

    def filter_block_tree(self, store, block_root, blocks: dict) -> bool:
        block = store.blocks[block_root]
        children = [root for root in store.blocks if store.blocks[root].parent_root == block_root]
        if any(children):
            filter_results = [self.filter_block_tree(store, child, blocks) for child in children]
            if any(filter_results):
                blocks[block_root] = block
                return True
            return False
        current_epoch = self.get_current_store_epoch(store)
        voting_source = self.get_voting_source(store, block_root)
        correct_justified = (
            store.justified_checkpoint.epoch == self.GENESIS_EPOCH
            or voting_source.epoch == store.justified_checkpoint.epoch
            or int(voting_source.epoch) + 2 >= current_epoch
        )
        finalized_checkpoint_block = self.get_checkpoint_block(
            store, block_root, store.finalized_checkpoint.epoch
        )
        correct_finalized = (
            store.finalized_checkpoint.epoch == self.GENESIS_EPOCH
            or store.finalized_checkpoint.root == finalized_checkpoint_block
        )
        if correct_justified and correct_finalized:
            blocks[block_root] = block
            return True
        return False

    def get_filtered_block_tree(self, store) -> dict:
        base = store.justified_checkpoint.root
        blocks: dict = {}
        self.filter_block_tree(store, base, blocks)
        return blocks

    def get_head(self, store):
        blocks = self.get_filtered_block_tree(store)
        head = store.justified_checkpoint.root
        while True:
            children = [root for root in blocks if blocks[root].parent_root == head]
            if len(children) == 0:
                return head
            head = max(children, key=lambda root: (self.get_weight(store, root), bytes(root)))

    def get_head_root(self, store) -> bytes:
        """Fork-agnostic head accessor: pre-gloas the head IS the root;
        gloas overrides to unwrap its (root, payload_status) node."""
        return bytes(self.get_head(store))

    def update_checkpoints(self, store, justified_checkpoint, finalized_checkpoint) -> None:
        if justified_checkpoint.epoch > store.justified_checkpoint.epoch:
            store.justified_checkpoint = justified_checkpoint
        if finalized_checkpoint.epoch > store.finalized_checkpoint.epoch:
            store.finalized_checkpoint = finalized_checkpoint

    def update_unrealized_checkpoints(
        self, store, unrealized_justified_checkpoint, unrealized_finalized_checkpoint
    ) -> None:
        if unrealized_justified_checkpoint.epoch > store.unrealized_justified_checkpoint.epoch:
            store.unrealized_justified_checkpoint = unrealized_justified_checkpoint
        if unrealized_finalized_checkpoint.epoch > store.unrealized_finalized_checkpoint.epoch:
            store.unrealized_finalized_checkpoint = unrealized_finalized_checkpoint

    def compute_pulled_up_tip(self, store, block_root) -> None:
        state = store.block_states[block_root].copy()
        self.process_justification_and_finalization(state)
        store.unrealized_justifications[block_root] = state.current_justified_checkpoint
        self.update_unrealized_checkpoints(
            store, state.current_justified_checkpoint, state.finalized_checkpoint
        )
        block_epoch = self.compute_epoch_at_slot(store.blocks[block_root].slot)
        current_epoch = self.get_current_store_epoch(store)
        if block_epoch < current_epoch:
            # blocks from prior epochs count as fully realized immediately
            self.update_checkpoints(
                store, state.current_justified_checkpoint, state.finalized_checkpoint
            )

    def on_tick(self, store, time: int) -> None:
        while (
            store.time < time
            and self.get_slots_since_genesis(store)
            < (time - store.genesis_time) // self.config.SECONDS_PER_SLOT
        ):
            previous_time = (
                store.genesis_time
                + (self.get_slots_since_genesis(store) + 1) * self.config.SECONDS_PER_SLOT
            )
            self.on_tick_per_slot(store, previous_time)
        self.on_tick_per_slot(store, time)

    def on_tick_per_slot(self, store, time: int) -> None:
        previous_slot = self.get_current_slot(store)
        store.time = time
        current_slot = self.get_current_slot(store)
        if current_slot > previous_slot:
            store.proposer_boost_root = Root()
            if self.compute_slots_since_epoch_start(current_slot) == 0:
                self.update_checkpoints(
                    store,
                    store.unrealized_justified_checkpoint,
                    store.unrealized_finalized_checkpoint,
                )

    # -- millisecond slot components (specs/phase0/fork-choice.md:457-492) --

    BASIS_POINTS = 10_000
    UINT64_MAX = 2**64 - 1

    def seconds_to_milliseconds(self, seconds: int) -> int:
        """Overflow-safe s→ms (specs/phase0/fork-choice.md:457-466)."""
        if int(seconds) > self.UINT64_MAX // 1000:
            return self.UINT64_MAX
        return int(seconds) * 1000

    def get_slot_component_duration_ms(self, basis_points: int) -> int:
        return int(basis_points) * self.config.SLOT_DURATION_MS // self.BASIS_POINTS

    def get_attestation_due_ms(self, epoch: int) -> int:
        return self.get_slot_component_duration_ms(self.config.ATTESTATION_DUE_BPS)

    def get_proposer_reorg_cutoff_ms(self, epoch: int) -> int:
        return self.get_slot_component_duration_ms(self.config.PROPOSER_REORG_CUTOFF_BPS)

    def get_aggregate_due_ms(self, epoch: int) -> int:
        return self.get_slot_component_duration_ms(self.config.AGGREGATE_DUE_BPS)

    def _time_into_slot_ms(self, store) -> int:
        seconds_since_genesis = int(store.time) - int(store.genesis_time)
        return (
            self.seconds_to_milliseconds(seconds_since_genesis)
            % self.config.SLOT_DURATION_MS
        )

    def is_before_attesting_interval(self, store) -> bool:
        epoch = self.get_current_store_epoch(store)
        return self._time_into_slot_ms(store) < self.get_attestation_due_ms(epoch)

    # -- proposer head / re-org helpers (specs/phase0/fork-choice.md:500-612,
    # optional for clients, normative shape) --------------------------------

    def calculate_committee_fraction(self, state, committee_percent: int) -> int:
        committee_weight = self.get_total_active_balance(state) // self.SLOTS_PER_EPOCH
        return (committee_weight * int(committee_percent)) // 100

    def is_head_late(self, store, head_root) -> bool:
        return not store.block_timeliness[head_root]

    def is_shuffling_stable(self, slot: int) -> bool:
        return int(slot) % self.SLOTS_PER_EPOCH != 0

    def is_ffg_competitive(self, store, head_root, parent_root) -> bool:
        return (
            store.unrealized_justifications[head_root]
            == store.unrealized_justifications[parent_root]
        )

    def is_finalization_ok(self, store, slot: int) -> bool:
        epochs_since_finalization = (
            self.compute_epoch_at_slot(slot) - store.finalized_checkpoint.epoch
        )
        return (
            epochs_since_finalization
            <= self.config.REORG_MAX_EPOCHS_SINCE_FINALIZATION
        )

    def is_proposing_on_time(self, store) -> bool:
        epoch = self.get_current_store_epoch(store)
        return self._time_into_slot_ms(store) <= self.get_proposer_reorg_cutoff_ms(epoch)

    def is_head_weak(self, store, head_root) -> bool:
        justified_state = store.checkpoint_states[store.justified_checkpoint]
        reorg_threshold = self.calculate_committee_fraction(
            justified_state, self.config.REORG_HEAD_WEIGHT_THRESHOLD
        )
        return self.get_weight(store, head_root) < reorg_threshold

    def is_parent_strong(self, store, parent_root) -> bool:
        justified_state = store.checkpoint_states[store.justified_checkpoint]
        parent_threshold = self.calculate_committee_fraction(
            justified_state, self.config.REORG_PARENT_WEIGHT_THRESHOLD
        )
        return self.get_weight(store, parent_root) > parent_threshold

    def get_proposer_head(self, store, head_root, slot: int):
        """The root a proposer should build on: the head's parent when the
        head arrived late and is weak enough for a single-slot re-org
        (specs/phase0/fork-choice.md:565-612)."""
        head_block = store.blocks[head_root]
        parent_root = head_block.parent_root
        parent_block = store.blocks[parent_root]

        head_late = self.is_head_late(store, head_root)
        shuffling_stable = self.is_shuffling_stable(slot)
        ffg_competitive = self.is_ffg_competitive(store, head_root, parent_root)
        finalization_ok = self.is_finalization_ok(store, slot)
        proposing_on_time = self.is_proposing_on_time(store)

        # single-slot re-org only
        parent_slot_ok = int(parent_block.slot) + 1 == int(head_block.slot)
        current_time_ok = int(head_block.slot) + 1 == int(slot)
        single_slot_reorg = parent_slot_ok and current_time_ok

        # proposer boost must have worn off before weighing the head
        assert store.proposer_boost_root != head_root
        head_weak = self.is_head_weak(store, head_root)
        parent_strong = self.is_parent_strong(store, parent_root)

        if all(
            [
                head_late,
                shuffling_stable,
                ffg_competitive,
                finalization_ok,
                proposing_on_time,
                single_slot_reorg,
                head_weak,
                parent_strong,
            ]
        ):
            return parent_root
        return head_root

    def on_block(self, store, signed_block) -> None:
        block = signed_block.message
        assert block.parent_root in store.block_states, "unknown parent"
        state = store.block_states[block.parent_root].copy()
        assert self.get_current_slot(store) >= block.slot, "block from the future"

        finalized_slot = self.compute_start_slot_at_epoch(store.finalized_checkpoint.epoch)
        assert block.slot > finalized_slot, "block not after finalized slot"
        assert (
            self.get_checkpoint_block(store, block.parent_root, store.finalized_checkpoint.epoch)
            == store.finalized_checkpoint.root
        ), "block does not descend from finalized root"

        # data-availability gate: no-op pre-deneb; blob proofs in deneb+
        # (specs/deneb/fork-choice.md:54-63), column sampling in fulu+
        # (specs/fulu/fork-choice.md:38)
        self._data_availability_check(block)

        self.state_transition(state, signed_block, True)

        # merge-transition gate: no-op pre-bellatrix (overridden to run
        # validate_merge_block against the PRE-state, specs/bellatrix/
        # fork-choice.md on_block "[New in Bellatrix]")
        self._merge_block_gate(store, block)

        block_root = hash_tree_root(block)
        store.blocks[block_root] = block.copy()
        store.block_states[block_root] = state

        # proposer boost for timely first-seen blocks (ms-based threshold,
        # specs/phase0/fork-choice.md:790-796)
        is_timely = self.get_current_slot(
            store
        ) == block.slot and self.is_before_attesting_interval(store)
        store.block_timeliness[block_root] = is_timely
        is_first_block = store.proposer_boost_root == Root()
        if is_timely and is_first_block:
            store.proposer_boost_root = block_root

        self.update_checkpoints(
            store, state.current_justified_checkpoint, state.finalized_checkpoint
        )
        self.compute_pulled_up_tip(store, block_root)

    def _data_availability_check(self, block) -> None:
        """Fork-choice data-availability gate; phase0 has no blob data."""

    def _merge_block_gate(self, store, block) -> None:
        """Terminal-PoW-block gate for merge-transition blocks; phase0 has
        no execution payloads."""

    def validate_target_epoch_against_current_time(self, store, attestation) -> None:
        target = attestation.data.target
        current_epoch = self.get_current_store_epoch(store)
        previous_epoch = max(current_epoch - 1, self.GENESIS_EPOCH)
        assert target.epoch in (current_epoch, previous_epoch), "target epoch not current/previous"

    def validate_on_attestation(self, store, attestation, is_from_block: bool) -> None:
        target = attestation.data.target
        if not is_from_block:
            self.validate_target_epoch_against_current_time(store, attestation)
        assert target.epoch == self.compute_epoch_at_slot(attestation.data.slot)
        assert target.root in store.blocks, "unknown target root"
        assert attestation.data.beacon_block_root in store.blocks, "unknown head root"
        assert (
            store.blocks[attestation.data.beacon_block_root].slot <= attestation.data.slot
        ), "attestation head newer than attestation slot"
        assert (
            target.root
            == self.get_checkpoint_block(store, attestation.data.beacon_block_root, target.epoch)
        ), "target does not match head chain"
        assert self.get_current_slot(store) >= int(attestation.data.slot) + 1, "attestation too new"

    def store_target_checkpoint_state(self, store, target) -> None:
        if target not in store.checkpoint_states:
            base_state = store.block_states[target.root].copy()
            target_slot = self.compute_start_slot_at_epoch(target.epoch)
            if base_state.slot < target_slot:
                self.process_slots(base_state, target_slot)
            store.checkpoint_states[target] = base_state

    def update_latest_messages(self, store, attesting_indices, attestation) -> None:
        target = attestation.data.target
        beacon_block_root = attestation.data.beacon_block_root
        non_equivocating = [i for i in attesting_indices if i not in store.equivocating_indices]
        for i in non_equivocating:
            if (
                i not in store.latest_messages
                or target.epoch > store.latest_messages[i].epoch
            ):
                store.latest_messages[i] = self.LatestMessage(
                    epoch=int(target.epoch), root=beacon_block_root
                )

    def on_attestation(self, store, attestation, is_from_block: bool = False) -> None:
        self.validate_on_attestation(store, attestation, is_from_block)
        self.store_target_checkpoint_state(store, attestation.data.target)
        target_state = store.checkpoint_states[attestation.data.target]
        indexed_attestation = self.get_indexed_attestation(target_state, attestation)
        assert self.is_valid_indexed_attestation(target_state, indexed_attestation)
        self.update_latest_messages(store, indexed_attestation.attesting_indices, attestation)

    def on_attester_slashing(self, store, attester_slashing) -> None:
        attestation_1 = attester_slashing.attestation_1
        attestation_2 = attester_slashing.attestation_2
        assert self.is_slashable_attestation_data(attestation_1.data, attestation_2.data)
        state = store.block_states[store.justified_checkpoint.root]
        assert self.is_valid_indexed_attestation(state, attestation_1)
        assert self.is_valid_indexed_attestation(state, attestation_2)
        indices = set(int(i) for i in attestation_1.attesting_indices) & set(
            int(i) for i in attestation_2.attesting_indices
        )
        store.equivocating_indices.update(indices)

    # == honest validator (specs/phase0/validator.md) ======================

    def check_if_validator_active(self, state, validator_index: int) -> bool:
        """specs/phase0/validator.md `check_if_validator_active`."""
        validator = state.validators[validator_index]
        return self.is_active_validator(validator, self.get_current_epoch(state))

    def get_committee_assignment(self, state, epoch: int, validator_index: int):
        next_epoch = self.get_current_epoch(state) + 1
        assert epoch <= next_epoch
        start_slot = self.compute_start_slot_at_epoch(epoch)
        committee_count_per_slot = self.get_committee_count_per_slot(state, epoch)
        for slot in range(start_slot, start_slot + self.SLOTS_PER_EPOCH):
            for index in range(committee_count_per_slot):
                committee = self.get_beacon_committee(state, slot, index)
                if int(validator_index) in [int(c) for c in committee]:
                    return committee, index, slot
        return None

    def is_proposer(self, state, validator_index: int) -> bool:
        return self.get_beacon_proposer_index(state) == int(validator_index)

    def get_epoch_signature(self, state, block, privkey: int) -> BLSSignature:
        domain = self.get_domain(
            state, self.DOMAIN_RANDAO, self.compute_epoch_at_slot(block.slot)
        )
        signing_root = self.compute_signing_root(
            uint64(self.compute_epoch_at_slot(block.slot)), domain
        )
        return BLSSignature(bls.Sign(privkey, signing_root))

    def compute_new_state_root(self, state, block) -> Root:
        temp_state = state.copy()
        signed_block = self.SignedBeaconBlock(message=block)
        self.state_transition(temp_state, signed_block, validate_result=False)
        return hash_tree_root(temp_state)

    def get_block_signature(self, state, block, privkey: int) -> BLSSignature:
        domain = self.get_domain(
            state, self.DOMAIN_BEACON_PROPOSER, self.compute_epoch_at_slot(block.slot)
        )
        return BLSSignature(bls.Sign(privkey, self.compute_signing_root(block, domain)))

    def get_attestation_signature(self, state, attestation_data, privkey: int) -> BLSSignature:
        domain = self.get_domain(
            state, self.DOMAIN_BEACON_ATTESTER, attestation_data.target.epoch
        )
        return BLSSignature(bls.Sign(privkey, self.compute_signing_root(attestation_data, domain)))

    def get_slot_signature(self, state, slot: int, privkey: int) -> BLSSignature:
        domain = self.get_domain(
            state, self.DOMAIN_SELECTION_PROOF, self.compute_epoch_at_slot(slot)
        )
        return BLSSignature(bls.Sign(privkey, self.compute_signing_root(uint64(slot), domain)))

    def is_aggregator(self, state, slot: int, index: int, slot_signature) -> bool:
        committee = self.get_beacon_committee(state, slot, index)
        modulo = max(1, len(committee) // self.TARGET_AGGREGATORS_PER_COMMITTEE)
        return self.bytes_to_uint64(self.hash(slot_signature)[:8]) % modulo == 0

    def get_aggregate_signature(self, attestations) -> BLSSignature:
        return BLSSignature(bls.Aggregate([a.signature for a in attestations]))

    def get_aggregate_and_proof(self, state, aggregator_index, aggregate, privkey: int):
        return self.AggregateAndProof(
            aggregator_index=aggregator_index,
            aggregate=aggregate,
            selection_proof=self.get_slot_signature(state, aggregate.data.slot, privkey),
        )

    def get_aggregate_and_proof_signature(self, state, aggregate_and_proof, privkey: int):
        aggregate = aggregate_and_proof.aggregate
        domain = self.get_domain(
            state,
            self.DOMAIN_AGGREGATE_AND_PROOF,
            self.compute_epoch_at_slot(aggregate.data.slot),
        )
        return BLSSignature(
            bls.Sign(privkey, self.compute_signing_root(aggregate_and_proof, domain))
        )

    def compute_time_at_slot(self, state, slot: int) -> int:
        return int(state.genesis_time) + int(slot) * self.config.SECONDS_PER_SLOT

    def voting_period_start_time(self, state) -> int:
        eth1_voting_period_start_slot = int(state.slot) - int(state.slot) % (
            self.EPOCHS_PER_ETH1_VOTING_PERIOD * self.SLOTS_PER_EPOCH
        )
        return self.compute_time_at_slot(state, eth1_voting_period_start_slot)

    def is_candidate_block(self, block, period_start: int) -> bool:
        follow_time = self.config.SECONDS_PER_ETH1_BLOCK * self.config.ETH1_FOLLOW_DISTANCE
        return (
            int(block.timestamp) + follow_time <= period_start
            and int(block.timestamp) + follow_time * 2 >= period_start
        )

    def get_eth1_data(self, block):
        return self.Eth1Data(
            deposit_root=block.deposit_root,
            deposit_count=block.deposit_count,
            block_hash=hash_tree_root(block),
        )

    def get_eth1_vote(self, state, eth1_chain):
        """Majority vote over the voting-period candidate window
        (reference: specs/phase0/validator.md:479-510)."""
        period_start = self.voting_period_start_time(state)
        votes_to_consider = [
            self.get_eth1_data(block)
            for block in eth1_chain
            if (
                self.is_candidate_block(block, period_start)
                # never move back to an earlier deposit contract state
                and int(self.get_eth1_data(block).deposit_count)
                >= int(state.eth1_data.deposit_count)
            )
        ]
        valid_votes = [vote for vote in state.eth1_data_votes if vote in votes_to_consider]
        default_vote = votes_to_consider[-1] if any(votes_to_consider) else state.eth1_data
        return max(
            valid_votes,
            # tiebreak by earliest vote among equal counts
            key=lambda v: (valid_votes.count(v), -valid_votes.index(v)),
            default=default_vote,
        )

    def get_randao_reveal(self, state, slot: int, privkey: int) -> BLSSignature:
        temp_state = state.copy()
        if temp_state.slot < slot:
            self.process_slots(temp_state, slot)
        return self.get_epoch_signature(
            temp_state, self.BeaconBlock(slot=slot), privkey
        )

    # == weak subjectivity (specs/phase0/weak-subjectivity.md) =============

    def compute_weak_subjectivity_period(self, state) -> int:
        ws_period = self.config.MIN_VALIDATOR_WITHDRAWABILITY_DELAY
        N = len(self.get_active_validator_indices(state, self.get_current_epoch(state)))
        t = self.get_total_active_balance(state) // N // self.ETH_TO_GWEI
        T = self.MAX_EFFECTIVE_BALANCE // self.ETH_TO_GWEI
        delta = self.get_validator_churn_limit(state)
        Delta = self.MAX_DEPOSITS * self.SLOTS_PER_EPOCH
        D = self.SAFETY_DECAY
        if T * (200 + 3 * D) < t * (200 + 12 * D):
            epochs_for_validator_set_churn = N * (t * (200 + 12 * D) - T * (200 + 3 * D)) // (
                600 * delta * (2 * t + T)
            )
            epochs_for_balance_top_ups = N * (200 + 3 * D) // (600 * Delta)
            ws_period += max(epochs_for_validator_set_churn, epochs_for_balance_top_ups)
        else:
            ws_period += 3 * N * D * t // (200 * Delta * (T - t))
        return ws_period

    ETH_TO_GWEI = 10**9

    def is_within_weak_subjectivity_period(self, store, ws_state, ws_checkpoint) -> bool:
        assert ws_state.latest_block_header.state_root == ws_checkpoint.root
        assert self.compute_epoch_at_slot(ws_state.slot) == ws_checkpoint.epoch
        ws_period = self.compute_weak_subjectivity_period(ws_state)
        ws_state_epoch = self.compute_epoch_at_slot(ws_state.slot)
        current_epoch = self.compute_epoch_at_slot(self.get_current_slot(store))
        return current_epoch <= ws_state_epoch + ws_period
