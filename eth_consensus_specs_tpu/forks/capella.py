"""capella: withdrawals, BLS-to-execution credential changes, historical
summaries.

Behavioral parity targets (reference, by section):
  * state machine:  specs/capella/beacon-chain.md (Withdrawal :96,
    get_expected_withdrawals :339, process_withdrawals :377,
    process_bls_to_execution_change :475, historical summaries :307)
  * fork upgrade:   specs/capella/fork.md (upgrade_to_capella)

Architecture note: the withdrawals sweep is a bounded circular scan over
the registry — on the columnar path this is a masked window reduction
(future ops/withdrawals kernel); the object path here is the semantics
oracle.
"""

from eth_consensus_specs_tpu.ssz import (
    Bitvector,
    ByteList,
    ByteVector,
    Bytes32,
    Container,
    List,
    Vector,
    hash_tree_root,
    uint64,
    uint256,
)
from eth_consensus_specs_tpu.utils import bls

from .altair import ParticipationFlags
from .bellatrix import BellatrixSpec, ExecutionAddress, Hash32
from .phase0 import (
    BLSPubkey,
    BLSSignature,
    DomainType,
    Gwei,
    Root,
    Slot,
    ValidatorIndex,
    Version,
)

WithdrawalIndex = uint64


class CapellaSpec(BellatrixSpec):
    fork_name = "capella"

    DOMAIN_BLS_TO_EXECUTION_CHANGE = DomainType(b"\x0a\x00\x00\x00")
    # light-client headers carry the execution header + proof from capella on
    # (specs/capella/light-client/sync-protocol.md:51-57)
    _light_client_has_execution = True

    # == type system ======================================================

    def _build_types(self) -> None:
        super()._build_types()
        P = self

        class Withdrawal(Container):
            index: WithdrawalIndex
            validator_index: ValidatorIndex
            address: ExecutionAddress
            amount: Gwei

        class BLSToExecutionChange(Container):
            validator_index: ValidatorIndex
            from_bls_pubkey: BLSPubkey
            to_execution_address: ExecutionAddress

        class SignedBLSToExecutionChange(Container):
            message: BLSToExecutionChange
            signature: BLSSignature

        class HistoricalSummary(Container):
            # hash_tree_root-compatible with phase0 HistoricalBatch
            block_summary_root: Root
            state_summary_root: Root

        class ExecutionPayload(Container):
            parent_hash: Hash32
            fee_recipient: ExecutionAddress
            state_root: Bytes32
            receipts_root: Bytes32
            logs_bloom: ByteVector[P.BYTES_PER_LOGS_BLOOM]
            prev_randao: Bytes32
            block_number: uint64
            gas_limit: uint64
            gas_used: uint64
            timestamp: uint64
            extra_data: ByteList[P.MAX_EXTRA_DATA_BYTES]
            base_fee_per_gas: uint256
            block_hash: Hash32
            transactions: List[P.Transaction, P.MAX_TRANSACTIONS_PER_PAYLOAD]
            withdrawals: List[Withdrawal, P.MAX_WITHDRAWALS_PER_PAYLOAD]  # [New in Capella]

        class ExecutionPayloadHeader(Container):
            parent_hash: Hash32
            fee_recipient: ExecutionAddress
            state_root: Bytes32
            receipts_root: Bytes32
            logs_bloom: ByteVector[P.BYTES_PER_LOGS_BLOOM]
            prev_randao: Bytes32
            block_number: uint64
            gas_limit: uint64
            gas_used: uint64
            timestamp: uint64
            extra_data: ByteList[P.MAX_EXTRA_DATA_BYTES]
            base_fee_per_gas: uint256
            block_hash: Hash32
            transactions_root: Root
            withdrawals_root: Root  # [New in Capella]

        class BeaconBlockBody(Container):
            randao_reveal: BLSSignature
            eth1_data: P.Eth1Data
            graffiti: Bytes32
            proposer_slashings: List[P.ProposerSlashing, P.MAX_PROPOSER_SLASHINGS]
            attester_slashings: List[P.AttesterSlashing, P.MAX_ATTESTER_SLASHINGS]
            attestations: List[P.Attestation, P.MAX_ATTESTATIONS]
            deposits: List[P.Deposit, P.MAX_DEPOSITS]
            voluntary_exits: List[P.SignedVoluntaryExit, P.MAX_VOLUNTARY_EXITS]
            sync_aggregate: P.SyncAggregate
            execution_payload: ExecutionPayload
            bls_to_execution_changes: List[
                SignedBLSToExecutionChange, P.MAX_BLS_TO_EXECUTION_CHANGES
            ]  # [New in Capella]

        class BeaconBlock(Container):
            slot: Slot
            proposer_index: ValidatorIndex
            parent_root: Root
            state_root: Root
            body: BeaconBlockBody

        class SignedBeaconBlock(Container):
            message: BeaconBlock
            signature: BLSSignature

        class BeaconState(Container):
            genesis_time: uint64
            genesis_validators_root: Root
            slot: Slot
            fork: P.Fork
            latest_block_header: P.BeaconBlockHeader
            block_roots: Vector[Root, P.SLOTS_PER_HISTORICAL_ROOT]
            state_roots: Vector[Root, P.SLOTS_PER_HISTORICAL_ROOT]
            historical_roots: List[Root, P.HISTORICAL_ROOTS_LIMIT]
            eth1_data: P.Eth1Data
            eth1_data_votes: List[P.Eth1Data, P.EPOCHS_PER_ETH1_VOTING_PERIOD * P.SLOTS_PER_EPOCH]
            eth1_deposit_index: uint64
            validators: List[P.Validator, P.VALIDATOR_REGISTRY_LIMIT]
            balances: List[Gwei, P.VALIDATOR_REGISTRY_LIMIT]
            randao_mixes: Vector[Bytes32, P.EPOCHS_PER_HISTORICAL_VECTOR]
            slashings: Vector[Gwei, P.EPOCHS_PER_SLASHINGS_VECTOR]
            previous_epoch_participation: List[ParticipationFlags, P.VALIDATOR_REGISTRY_LIMIT]
            current_epoch_participation: List[ParticipationFlags, P.VALIDATOR_REGISTRY_LIMIT]
            justification_bits: Bitvector[self.JUSTIFICATION_BITS_LENGTH]
            previous_justified_checkpoint: P.Checkpoint
            current_justified_checkpoint: P.Checkpoint
            finalized_checkpoint: P.Checkpoint
            inactivity_scores: List[uint64, P.VALIDATOR_REGISTRY_LIMIT]
            current_sync_committee: P.SyncCommittee
            next_sync_committee: P.SyncCommittee
            latest_execution_payload_header: ExecutionPayloadHeader
            next_withdrawal_index: WithdrawalIndex  # [New in Capella]
            next_withdrawal_validator_index: ValidatorIndex  # [New in Capella]
            historical_summaries: List[
                HistoricalSummary, P.HISTORICAL_ROOTS_LIMIT
            ]  # [New in Capella]

        for name, typ in list(locals().items()):
            if isinstance(typ, type) and issubclass(typ, Container):
                typ.__name__ = name
                setattr(self, name, typ)

    # == predicates ========================================================

    def has_eth1_withdrawal_credential(self, validator) -> bool:
        return bytes(validator.withdrawal_credentials)[:1] == self.ETH1_ADDRESS_WITHDRAWAL_PREFIX

    def is_fully_withdrawable_validator(self, validator, balance: int, epoch: int) -> bool:
        return (
            self.has_eth1_withdrawal_credential(validator)
            and int(validator.withdrawable_epoch) <= epoch
            and int(balance) > 0
        )

    def is_partially_withdrawable_validator(self, validator, balance: int) -> bool:
        return (
            self.has_eth1_withdrawal_credential(validator)
            and int(validator.effective_balance) == self.MAX_EFFECTIVE_BALANCE
            and int(balance) > self.MAX_EFFECTIVE_BALANCE
        )

    # == epoch processing ==================================================

    def process_historical_summaries_update(self, state) -> None:
        next_epoch = self.get_current_epoch(state) + 1
        if next_epoch % (self.SLOTS_PER_HISTORICAL_ROOT // self.SLOTS_PER_EPOCH) == 0:
            state.historical_summaries.append(
                self.HistoricalSummary(
                    block_summary_root=hash_tree_root(state.block_roots),
                    state_summary_root=hash_tree_root(state.state_roots),
                )
            )

    # capella swaps historical ROOTS accumulation for summaries
    def process_historical_roots_update(self, state) -> None:
        self.process_historical_summaries_update(state)

    # == block processing ==================================================

    def process_block(self, state, block) -> None:
        self.process_block_header(state, block)
        self.process_withdrawals(state, block.body.execution_payload)  # [New in Capella]
        self.process_execution_payload(state, block.body, self.EXECUTION_ENGINE)
        self.process_randao(state, block.body)
        self.process_eth1_data(state, block.body)
        self.process_operations(state, block.body)
        self.process_sync_aggregate(state, block.body.sync_aggregate)

    def get_expected_withdrawals(self, state):
        """Bounded circular sweep over the registry collecting full and
        partial (excess-balance) withdrawals."""
        epoch = self.get_current_epoch(state)
        withdrawal_index = int(state.next_withdrawal_index)
        validator_index = int(state.next_withdrawal_validator_index)
        withdrawals = []
        bound = min(len(state.validators), self.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP)
        for _ in range(bound):
            validator = state.validators[validator_index]
            balance = int(state.balances[validator_index])
            address = bytes(validator.withdrawal_credentials)[12:]
            if self.is_fully_withdrawable_validator(validator, balance, epoch):
                withdrawals.append(
                    self.Withdrawal(
                        index=withdrawal_index,
                        validator_index=validator_index,
                        address=address,
                        amount=balance,
                    )
                )
                withdrawal_index += 1
            elif self.is_partially_withdrawable_validator(validator, balance):
                withdrawals.append(
                    self.Withdrawal(
                        index=withdrawal_index,
                        validator_index=validator_index,
                        address=address,
                        amount=balance - self.MAX_EFFECTIVE_BALANCE,
                    )
                )
                withdrawal_index += 1
            if len(withdrawals) == self.MAX_WITHDRAWALS_PER_PAYLOAD:
                break
            validator_index = (validator_index + 1) % len(state.validators)
        return withdrawals

    def process_withdrawals(self, state, payload) -> None:
        expected_withdrawals = self.get_expected_withdrawals(state)
        assert list(payload.withdrawals) == expected_withdrawals, "withdrawals mismatch"

        for withdrawal in expected_withdrawals:
            self.decrease_balance(state, withdrawal.validator_index, withdrawal.amount)

        if len(expected_withdrawals) != 0:
            state.next_withdrawal_index = int(expected_withdrawals[-1].index) + 1

        if len(expected_withdrawals) == self.MAX_WITHDRAWALS_PER_PAYLOAD:
            # full payload: next sweep resumes right after the last paid index
            state.next_withdrawal_validator_index = (
                int(expected_withdrawals[-1].validator_index) + 1
            ) % len(state.validators)
        else:
            # partial payload: jump the whole sweep window
            state.next_withdrawal_validator_index = (
                int(state.next_withdrawal_validator_index)
                + self.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP
            ) % len(state.validators)

    def process_execution_payload(self, state, body, execution_engine) -> None:
        payload = body.execution_payload
        # capella removes the merge-transition branch: parent always checked
        assert (
            payload.parent_hash == state.latest_execution_payload_header.block_hash
        ), "payload parent mismatch"
        assert payload.prev_randao == self.get_randao_mix(
            state, self.get_current_epoch(state)
        ), "wrong prev_randao"
        assert payload.timestamp == self.compute_timestamp_at_slot(
            state, state.slot
        ), "wrong payload timestamp"
        assert execution_engine.verify_and_notify_new_payload(
            self.NewPayloadRequest(execution_payload=payload)
        ), "execution engine rejected payload"
        state.latest_execution_payload_header = self.execution_payload_to_header(payload)

    def execution_payload_to_header(self, payload):
        return self.ExecutionPayloadHeader(
            parent_hash=payload.parent_hash,
            fee_recipient=payload.fee_recipient,
            state_root=payload.state_root,
            receipts_root=payload.receipts_root,
            logs_bloom=payload.logs_bloom,
            prev_randao=payload.prev_randao,
            block_number=payload.block_number,
            gas_limit=payload.gas_limit,
            gas_used=payload.gas_used,
            timestamp=payload.timestamp,
            extra_data=payload.extra_data,
            base_fee_per_gas=payload.base_fee_per_gas,
            block_hash=payload.block_hash,
            transactions_root=hash_tree_root(payload.transactions),
            withdrawals_root=hash_tree_root(payload.withdrawals),
        )

    def process_operations(self, state, body) -> None:
        super().process_operations(state, body)
        for operation in body.bls_to_execution_changes:
            self.process_bls_to_execution_change(state, operation)

    def process_bls_to_execution_change(self, state, signed_address_change) -> None:
        address_change = signed_address_change.message
        assert address_change.validator_index < len(state.validators), "unknown validator"
        validator = state.validators[int(address_change.validator_index)]
        creds = bytes(validator.withdrawal_credentials)
        assert creds[:1] == self.BLS_WITHDRAWAL_PREFIX, "not a BLS credential"
        assert creds[1:] == self.hash(address_change.from_bls_pubkey)[1:], "pubkey mismatch"
        # fork-agnostic domain: address changes stay valid across forks
        domain = self.compute_domain(
            self.DOMAIN_BLS_TO_EXECUTION_CHANGE,
            genesis_validators_root=state.genesis_validators_root,
        )
        signing_root = self.compute_signing_root(address_change, domain)
        assert bls.Verify(
            address_change.from_bls_pubkey, signing_root, signed_address_change.signature
        ), "bad credential-change signature"
        validator.withdrawal_credentials = Bytes32(
            bytes(self.ETH1_ADDRESS_WITHDRAWAL_PREFIX)
            + b"\x00" * 11
            + bytes(address_change.to_execution_address)
        )

    # == fork upgrade (specs/capella/fork.md) ==============================

    def upgrade_from_parent(self, pre):
        epoch = self.compute_epoch_at_slot(int(pre.slot))
        pre_header = pre.latest_execution_payload_header
        header = self.ExecutionPayloadHeader(
            parent_hash=pre_header.parent_hash,
            fee_recipient=pre_header.fee_recipient,
            state_root=pre_header.state_root,
            receipts_root=pre_header.receipts_root,
            logs_bloom=pre_header.logs_bloom,
            prev_randao=pre_header.prev_randao,
            block_number=pre_header.block_number,
            gas_limit=pre_header.gas_limit,
            gas_used=pre_header.gas_used,
            timestamp=pre_header.timestamp,
            extra_data=pre_header.extra_data,
            base_fee_per_gas=pre_header.base_fee_per_gas,
            block_hash=pre_header.block_hash,
            transactions_root=pre_header.transactions_root,
            # withdrawals_root defaults to zero
        )
        return self.BeaconState(
            genesis_time=pre.genesis_time,
            genesis_validators_root=pre.genesis_validators_root,
            slot=pre.slot,
            fork=self.Fork(
                previous_version=pre.fork.current_version,
                current_version=Version(self.config.CAPELLA_FORK_VERSION),
                epoch=epoch,
            ),
            latest_block_header=pre.latest_block_header,
            block_roots=list(pre.block_roots),
            state_roots=list(pre.state_roots),
            historical_roots=list(pre.historical_roots),
            eth1_data=pre.eth1_data,
            eth1_data_votes=list(pre.eth1_data_votes),
            eth1_deposit_index=pre.eth1_deposit_index,
            validators=list(pre.validators),
            balances=list(pre.balances),
            randao_mixes=list(pre.randao_mixes),
            slashings=list(pre.slashings),
            previous_epoch_participation=list(pre.previous_epoch_participation),
            current_epoch_participation=list(pre.current_epoch_participation),
            justification_bits=list(pre.justification_bits),
            previous_justified_checkpoint=pre.previous_justified_checkpoint,
            current_justified_checkpoint=pre.current_justified_checkpoint,
            finalized_checkpoint=pre.finalized_checkpoint,
            inactivity_scores=list(pre.inactivity_scores),
            current_sync_committee=pre.current_sync_committee,
            next_sync_committee=pre.next_sync_committee,
            latest_execution_payload_header=header,
            next_withdrawal_index=0,
            next_withdrawal_validator_index=0,
            historical_summaries=[],
        )
