"""Altair light-client sync protocol.

Behavioral parity targets (reference, by section):
  * sync protocol:  specs/altair/light-client/sync-protocol.md
      - containers :87-171, validation :372-456, application :458-548,
        force update :480-499, finality/optimistic wrappers :550-595
  * full node:      specs/altair/light-client/full-node.md
      - bootstrap :62-78, update :109-168, derived updates :189-220

The hardcoded gindices (105 / 54 / 55) are the altair+ BeaconState
positions of finalized_checkpoint.root and the two sync committees
(reference inlines the same constants, pysetup/spec_builders/altair.py:
40-45); proofs are produced by the generic gindex walker in
ssz/merkle.py:compute_merkle_proof, so full-node and light-client sides
are two independent code paths meeting at the branch bytes.

Mixed into AltairSpec — every later fork inherits the protocol surface.
"""

from dataclasses import dataclass
from typing import Optional

from eth_consensus_specs_tpu.ssz import Bytes32, Container, Vector, hash_tree_root
from eth_consensus_specs_tpu.ssz.merkle import compute_merkle_proof
from eth_consensus_specs_tpu.utils import bls

from .phase0 import Slot


def floorlog2(x: int) -> int:
    assert x > 0
    return int(x).bit_length() - 1


class LightClientMixin:
    # Constants (sync-protocol.md:68-74). MIN_SYNC_COMMITTEE_PARTICIPANTS
    # and UPDATE_TIMEOUT arrive from the altair preset files.
    FINALIZED_ROOT_GINDEX = 105
    CURRENT_SYNC_COMMITTEE_GINDEX = 54
    NEXT_SYNC_COMMITTEE_GINDEX = 55
    # capella+ (specs/capella/light-client/sync-protocol.md:44)
    EXECUTION_PAYLOAD_GINDEX = 25
    # capella adds execution data to the header (fork classes flip this)
    _light_client_has_execution = False

    def __init__(self, *args, **kwargs):
        # LC containers reference the FINAL fork types (ExecutionPayloadHeader
        # changes per fork), so they build after the whole _build_types chain
        super().__init__(*args, **kwargs)
        self._build_light_client_types()

    def _lc_max_gindices(self) -> tuple:
        """(finalized_root, current_sc, next_sc) gindices sizing the branch
        vectors — electra's deeper state overrides these."""
        return (
            self.FINALIZED_ROOT_GINDEX,
            self.CURRENT_SYNC_COMMITTEE_GINDEX,
            self.NEXT_SYNC_COMMITTEE_GINDEX,
        )

    # == type system =======================================================

    def _build_light_client_types(self) -> None:
        P = self
        fin_g, cur_g, next_g = self._lc_max_gindices()
        FinalityBranch = Vector[Bytes32, floorlog2(fin_g)]
        CurrentSyncCommitteeBranch = Vector[Bytes32, floorlog2(cur_g)]
        NextSyncCommitteeBranch = Vector[Bytes32, floorlog2(next_g)]
        ExecutionBranch = Vector[Bytes32, floorlog2(self.EXECUTION_PAYLOAD_GINDEX)]
        self.FinalityBranch = FinalityBranch
        self.CurrentSyncCommitteeBranch = CurrentSyncCommitteeBranch
        self.NextSyncCommitteeBranch = NextSyncCommitteeBranch
        self.ExecutionBranch = ExecutionBranch

        if self._light_client_has_execution:

            class LightClientHeader(Container):
                beacon: P.BeaconBlockHeader
                execution: P.ExecutionPayloadHeader  # [New in Capella]
                execution_branch: ExecutionBranch  # [New in Capella]

        else:

            class LightClientHeader(Container):
                beacon: P.BeaconBlockHeader

        class LightClientBootstrap(Container):
            header: LightClientHeader
            current_sync_committee: P.SyncCommittee
            current_sync_committee_branch: CurrentSyncCommitteeBranch

        class LightClientUpdate(Container):
            attested_header: LightClientHeader
            next_sync_committee: P.SyncCommittee
            next_sync_committee_branch: NextSyncCommitteeBranch
            finalized_header: LightClientHeader
            finality_branch: FinalityBranch
            sync_aggregate: P.SyncAggregate
            signature_slot: Slot

        class LightClientFinalityUpdate(Container):
            attested_header: LightClientHeader
            finalized_header: LightClientHeader
            finality_branch: FinalityBranch
            sync_aggregate: P.SyncAggregate
            signature_slot: Slot

        class LightClientOptimisticUpdate(Container):
            attested_header: LightClientHeader
            sync_aggregate: P.SyncAggregate
            signature_slot: Slot

        for name, typ in list(locals().items()):
            if isinstance(typ, type) and issubclass(typ, Container):
                typ.__name__ = name
                setattr(self, name, typ)

    @dataclass
    class LightClientStore:
        finalized_header: object
        current_sync_committee: object
        next_sync_committee: object
        best_valid_update: Optional[object]
        optimistic_header: object
        previous_max_active_participants: int
        current_max_active_participants: int

    # == helpers (sync-protocol.md:173-320) ================================

    def compute_sync_committee_period(self, epoch: int) -> int:
        return int(epoch) // self.EPOCHS_PER_SYNC_COMMITTEE_PERIOD

    def compute_sync_committee_period_at_slot(self, slot: int) -> int:
        return self.compute_sync_committee_period(self.compute_epoch_at_slot(slot))

    def compute_fork_version(self, epoch: int):
        """Fork version active at `epoch` per the config's fork schedule."""
        from eth_consensus_specs_tpu.config import FORK_ORDER

        version = self.config.GENESIS_FORK_VERSION
        for fork in FORK_ORDER[1:]:
            fork_epoch = getattr(self.config, f"{fork.upper()}_FORK_EPOCH", None)
            if fork_epoch is None:
                break
            if epoch >= fork_epoch:
                version = getattr(self.config, f"{fork.upper()}_FORK_VERSION")
        return version

    def finalized_root_gindex_at_slot(self, _slot: int) -> int:
        return self.FINALIZED_ROOT_GINDEX

    def current_sync_committee_gindex_at_slot(self, _slot: int) -> int:
        return self.CURRENT_SYNC_COMMITTEE_GINDEX

    def next_sync_committee_gindex_at_slot(self, _slot: int) -> int:
        return self.NEXT_SYNC_COMMITTEE_GINDEX

    @staticmethod
    def normalize_merkle_branch(branch, gindex: int) -> list:
        """Zero-extend a branch to the depth of `gindex` (electra LC spec
        normalize_merkle_branch; consumed by the electra upgrade_lc_*
        helpers when pre-electra objects re-home to the deeper state)."""
        depth = floorlog2(gindex)
        num_extra = depth - len(branch)
        return [Bytes32()] * num_extra + [bytes(b) for b in branch]

    def get_lc_execution_root(self, header):
        """capella+ (specs/capella/light-client/sync-protocol.md:129-135)."""
        epoch = self.compute_epoch_at_slot(header.beacon.slot)
        if epoch >= self.config.CAPELLA_FORK_EPOCH:
            return hash_tree_root(header.execution)
        return Bytes32()

    def is_valid_light_client_header(self, header) -> bool:
        if not self._light_client_has_execution:
            return True  # altair/bellatrix: nothing beyond the beacon header
        # capella+ (specs/capella/light-client/sync-protocol.md:141-156)
        epoch = self.compute_epoch_at_slot(header.beacon.slot)
        if epoch < self.config.CAPELLA_FORK_EPOCH:
            return (
                header.execution == self.ExecutionPayloadHeader()
                and header.execution_branch == self.ExecutionBranch()
            )
        return self.is_valid_merkle_branch(
            leaf=self.get_lc_execution_root(header),
            branch=header.execution_branch,
            depth=floorlog2(self.EXECUTION_PAYLOAD_GINDEX),
            index=self.get_subtree_index(self.EXECUTION_PAYLOAD_GINDEX),
            root=header.beacon.body_root,
        )

    def is_sync_committee_update(self, update) -> bool:
        return update.next_sync_committee_branch != self.NextSyncCommitteeBranch()

    def is_finality_update(self, update) -> bool:
        return update.finality_branch != self.FinalityBranch()

    def is_better_update(self, new_update, old_update) -> bool:
        """Update preference order (sync-protocol.md:217-271)."""
        max_active_participants = len(new_update.sync_aggregate.sync_committee_bits)
        new_num_active = sum(map(bool, new_update.sync_aggregate.sync_committee_bits))
        old_num_active = sum(map(bool, old_update.sync_aggregate.sync_committee_bits))
        new_has_supermajority = new_num_active * 3 >= max_active_participants * 2
        old_has_supermajority = old_num_active * 3 >= max_active_participants * 2
        if new_has_supermajority != old_has_supermajority:
            return new_has_supermajority
        if not new_has_supermajority and new_num_active != old_num_active:
            return new_num_active > old_num_active

        new_has_relevant_sync_committee = self.is_sync_committee_update(new_update) and (
            self.compute_sync_committee_period_at_slot(new_update.attested_header.beacon.slot)
            == self.compute_sync_committee_period_at_slot(new_update.signature_slot)
        )
        old_has_relevant_sync_committee = self.is_sync_committee_update(old_update) and (
            self.compute_sync_committee_period_at_slot(old_update.attested_header.beacon.slot)
            == self.compute_sync_committee_period_at_slot(old_update.signature_slot)
        )
        if new_has_relevant_sync_committee != old_has_relevant_sync_committee:
            return new_has_relevant_sync_committee

        new_has_finality = self.is_finality_update(new_update)
        old_has_finality = self.is_finality_update(old_update)
        if new_has_finality != old_has_finality:
            return new_has_finality

        if new_has_finality:
            new_sc_finality = self.compute_sync_committee_period_at_slot(
                new_update.finalized_header.beacon.slot
            ) == self.compute_sync_committee_period_at_slot(
                new_update.attested_header.beacon.slot
            )
            old_sc_finality = self.compute_sync_committee_period_at_slot(
                old_update.finalized_header.beacon.slot
            ) == self.compute_sync_committee_period_at_slot(
                old_update.attested_header.beacon.slot
            )
            if new_sc_finality != old_sc_finality:
                return new_sc_finality

        if new_num_active != old_num_active:
            return new_num_active > old_num_active
        if new_update.attested_header.beacon.slot != old_update.attested_header.beacon.slot:
            return (
                new_update.attested_header.beacon.slot
                < old_update.attested_header.beacon.slot
            )
        return new_update.signature_slot < old_update.signature_slot

    def is_next_sync_committee_known(self, store) -> bool:
        return store.next_sync_committee != self.SyncCommittee()

    def get_safety_threshold(self, store) -> int:
        return (
            max(
                store.previous_max_active_participants,
                store.current_max_active_participants,
            )
            // 2
        )

    @staticmethod
    def get_subtree_index(generalized_index: int) -> int:
        return generalized_index % 2 ** floorlog2(generalized_index)

    def is_valid_normalized_merkle_branch(self, leaf, branch, gindex: int, root) -> bool:
        depth = floorlog2(gindex)
        index = self.get_subtree_index(gindex)
        num_extra = len(branch) - depth
        for i in range(num_extra):
            if bytes(branch[i]) != bytes(Bytes32()):
                return False
        return self.is_valid_merkle_branch(leaf, branch[num_extra:], depth, index, root)

    # == initialization (sync-protocol.md:329-354) =========================

    def initialize_light_client_store(self, trusted_block_root, bootstrap):
        assert self.is_valid_light_client_header(bootstrap.header)
        assert hash_tree_root(bootstrap.header.beacon) == trusted_block_root

        assert self.is_valid_normalized_merkle_branch(
            leaf=hash_tree_root(bootstrap.current_sync_committee),
            branch=bootstrap.current_sync_committee_branch,
            gindex=self.current_sync_committee_gindex_at_slot(bootstrap.header.beacon.slot),
            root=bootstrap.header.beacon.state_root,
        ), "invalid current sync committee branch"

        return self.LightClientStore(
            finalized_header=bootstrap.header,
            current_sync_committee=bootstrap.current_sync_committee,
            next_sync_committee=self.SyncCommittee(),
            best_valid_update=None,
            optimistic_header=bootstrap.header,
            previous_max_active_participants=0,
            current_max_active_participants=0,
        )

    # == update validation / application (sync-protocol.md:372-548) ========

    def validate_light_client_update(
        self, store, update, current_slot: int, genesis_validators_root
    ) -> None:
        sync_aggregate = update.sync_aggregate
        num_active = sum(map(bool, sync_aggregate.sync_committee_bits))
        assert num_active >= self.MIN_SYNC_COMMITTEE_PARTICIPANTS, "too few participants"

        assert self.is_valid_light_client_header(update.attested_header)
        update_attested_slot = int(update.attested_header.beacon.slot)
        update_finalized_slot = int(update.finalized_header.beacon.slot)
        assert (
            current_slot >= int(update.signature_slot) > update_attested_slot >= update_finalized_slot
        ), "slots out of order"
        store_period = self.compute_sync_committee_period_at_slot(
            store.finalized_header.beacon.slot
        )
        update_signature_period = self.compute_sync_committee_period_at_slot(
            update.signature_slot
        )
        if self.is_next_sync_committee_known(store):
            assert update_signature_period in (
                store_period,
                store_period + 1,
            ), "update skips a sync committee period"
        else:
            assert update_signature_period == store_period, "next committee unknown"

        update_attested_period = self.compute_sync_committee_period_at_slot(
            update_attested_slot
        )
        update_has_next_sync_committee = not self.is_next_sync_committee_known(store) and (
            self.is_sync_committee_update(update) and update_attested_period == store_period
        )
        assert (
            update_attested_slot > int(store.finalized_header.beacon.slot)
            or update_has_next_sync_committee
        ), "update not relevant"

        if not self.is_finality_update(update):
            assert update.finalized_header == self.LightClientHeader()
        else:
            if update_finalized_slot == self.GENESIS_SLOT:
                assert update.finalized_header == self.LightClientHeader()
                finalized_root = Bytes32()
            else:
                assert self.is_valid_light_client_header(update.finalized_header)
                finalized_root = hash_tree_root(update.finalized_header.beacon)
            assert self.is_valid_normalized_merkle_branch(
                leaf=finalized_root,
                branch=update.finality_branch,
                gindex=self.finalized_root_gindex_at_slot(update_attested_slot),
                root=update.attested_header.beacon.state_root,
            ), "invalid finality branch"

        if not self.is_sync_committee_update(update):
            assert update.next_sync_committee == self.SyncCommittee()
        else:
            if update_attested_period == store_period and self.is_next_sync_committee_known(
                store
            ):
                assert update.next_sync_committee == store.next_sync_committee
            assert self.is_valid_normalized_merkle_branch(
                leaf=hash_tree_root(update.next_sync_committee),
                branch=update.next_sync_committee_branch,
                gindex=self.next_sync_committee_gindex_at_slot(update_attested_slot),
                root=update.attested_header.beacon.state_root,
            ), "invalid next sync committee branch"

        if update_signature_period == store_period:
            sync_committee = store.current_sync_committee
        else:
            sync_committee = store.next_sync_committee
        participant_pubkeys = [
            pubkey
            for (bit, pubkey) in zip(
                sync_aggregate.sync_committee_bits, sync_committee.pubkeys
            )
            if bit
        ]
        fork_version_slot = max(int(update.signature_slot), 1) - 1
        fork_version = self.compute_fork_version(
            self.compute_epoch_at_slot(fork_version_slot)
        )
        domain = self.compute_domain(
            self.DOMAIN_SYNC_COMMITTEE, fork_version, genesis_validators_root
        )
        signing_root = self.compute_signing_root(update.attested_header.beacon, domain)
        assert bls.FastAggregateVerify(
            participant_pubkeys, signing_root, sync_aggregate.sync_committee_signature
        ), "invalid sync aggregate signature"

    def apply_light_client_update(self, store, update) -> None:
        store_period = self.compute_sync_committee_period_at_slot(
            store.finalized_header.beacon.slot
        )
        update_finalized_period = self.compute_sync_committee_period_at_slot(
            update.finalized_header.beacon.slot
        )
        if not self.is_next_sync_committee_known(store):
            assert update_finalized_period == store_period
            store.next_sync_committee = update.next_sync_committee
        elif update_finalized_period == store_period + 1:
            store.current_sync_committee = store.next_sync_committee
            store.next_sync_committee = update.next_sync_committee
            store.previous_max_active_participants = store.current_max_active_participants
            store.current_max_active_participants = 0
        if int(update.finalized_header.beacon.slot) > int(store.finalized_header.beacon.slot):
            store.finalized_header = update.finalized_header
            if int(store.finalized_header.beacon.slot) > int(
                store.optimistic_header.beacon.slot
            ):
                store.optimistic_header = store.finalized_header

    def process_light_client_store_force_update(self, store, current_slot: int) -> None:
        if (
            current_slot > int(store.finalized_header.beacon.slot) + self.UPDATE_TIMEOUT
            and store.best_valid_update is not None
        ):
            # during long non-finality the attested header stands in for the
            # finalized one so period progression cannot stall
            if int(store.best_valid_update.finalized_header.beacon.slot) <= int(
                store.finalized_header.beacon.slot
            ):
                store.best_valid_update.finalized_header = (
                    store.best_valid_update.attested_header
                )
            self.apply_light_client_update(store, store.best_valid_update)
            store.best_valid_update = None

    def process_light_client_update(
        self, store, update, current_slot: int, genesis_validators_root
    ) -> None:
        self.validate_light_client_update(
            store, update, current_slot, genesis_validators_root
        )
        sync_committee_bits = update.sync_aggregate.sync_committee_bits
        num_active = sum(map(bool, sync_committee_bits))

        if store.best_valid_update is None or self.is_better_update(
            update, store.best_valid_update
        ):
            store.best_valid_update = update.copy()

        store.current_max_active_participants = max(
            store.current_max_active_participants, num_active
        )

        if num_active > self.get_safety_threshold(store) and int(
            update.attested_header.beacon.slot
        ) > int(store.optimistic_header.beacon.slot):
            store.optimistic_header = update.attested_header

        update_has_finalized_next_sync_committee = (
            not self.is_next_sync_committee_known(store)
            and self.is_sync_committee_update(update)
            and self.is_finality_update(update)
            and (
                self.compute_sync_committee_period_at_slot(
                    update.finalized_header.beacon.slot
                )
                == self.compute_sync_committee_period_at_slot(
                    update.attested_header.beacon.slot
                )
            )
        )
        if num_active * 3 >= len(sync_committee_bits) * 2 and (
            int(update.finalized_header.beacon.slot) > int(store.finalized_header.beacon.slot)
            or update_has_finalized_next_sync_committee
        ):
            self.apply_light_client_update(store, update)
            store.best_valid_update = None

    def process_light_client_finality_update(
        self, store, finality_update, current_slot: int, genesis_validators_root
    ) -> None:
        update = self.LightClientUpdate(
            attested_header=finality_update.attested_header,
            next_sync_committee=self.SyncCommittee(),
            next_sync_committee_branch=self.NextSyncCommitteeBranch(),
            finalized_header=finality_update.finalized_header,
            finality_branch=finality_update.finality_branch,
            sync_aggregate=finality_update.sync_aggregate,
            signature_slot=finality_update.signature_slot,
        )
        self.process_light_client_update(
            store, update, current_slot, genesis_validators_root
        )

    def process_light_client_optimistic_update(
        self, store, optimistic_update, current_slot: int, genesis_validators_root
    ) -> None:
        update = self.LightClientUpdate(
            attested_header=optimistic_update.attested_header,
            next_sync_committee=self.SyncCommittee(),
            next_sync_committee_branch=self.NextSyncCommitteeBranch(),
            finalized_header=self.LightClientHeader(),
            finality_branch=self.FinalityBranch(),
            sync_aggregate=optimistic_update.sync_aggregate,
            signature_slot=optimistic_update.signature_slot,
        )
        self.process_light_client_update(
            store, update, current_slot, genesis_validators_root
        )

    # == full-node side (full-node.md) =====================================

    def block_to_light_client_header(self, block):
        beacon = self.BeaconBlockHeader(
            slot=block.message.slot,
            proposer_index=block.message.proposer_index,
            parent_root=block.message.parent_root,
            state_root=block.message.state_root,
            body_root=hash_tree_root(block.message.body),
        )
        if not self._light_client_has_execution:
            return self.LightClientHeader(beacon=beacon)
        # capella+ (specs/capella/light-client/full-node.md:21-60): attach
        # the execution header + its proof within the block body
        epoch = self.compute_epoch_at_slot(block.message.slot)
        if epoch >= self.config.CAPELLA_FORK_EPOCH:
            execution = self.execution_payload_to_header(block.message.body.execution_payload)
            execution_branch = compute_merkle_proof(
                block.message.body, self.EXECUTION_PAYLOAD_GINDEX
            )
            return self.LightClientHeader(
                beacon=beacon, execution=execution, execution_branch=execution_branch
            )
        return self.LightClientHeader(beacon=beacon)

    def create_light_client_bootstrap(self, state, block):
        assert (
            self.compute_epoch_at_slot(state.slot) >= self.config.ALTAIR_FORK_EPOCH
        ), "pre-altair state"
        assert state.slot == state.latest_block_header.slot
        header = state.latest_block_header.copy()
        header.state_root = hash_tree_root(state)
        assert hash_tree_root(header) == hash_tree_root(block.message)

        return self.LightClientBootstrap(
            header=self.block_to_light_client_header(block),
            current_sync_committee=state.current_sync_committee,
            current_sync_committee_branch=compute_merkle_proof(
                state, self.current_sync_committee_gindex_at_slot(state.slot)
            ),
        )

    def create_light_client_update(
        self, state, block, attested_state, attested_block, finalized_block
    ):
        assert (
            self.compute_epoch_at_slot(attested_state.slot) >= self.config.ALTAIR_FORK_EPOCH
        )
        sync_aggregate = block.message.body.sync_aggregate
        assert (
            sum(map(bool, sync_aggregate.sync_committee_bits))
            >= self.MIN_SYNC_COMMITTEE_PARTICIPANTS
        )

        assert state.slot == state.latest_block_header.slot
        header = state.latest_block_header.copy()
        header.state_root = hash_tree_root(state)
        assert hash_tree_root(header) == hash_tree_root(block.message)
        update_signature_period = self.compute_sync_committee_period_at_slot(
            block.message.slot
        )

        assert attested_state.slot == attested_state.latest_block_header.slot
        attested_header = attested_state.latest_block_header.copy()
        attested_header.state_root = hash_tree_root(attested_state)
        assert (
            hash_tree_root(attested_header)
            == hash_tree_root(attested_block.message)
            == block.message.parent_root
        )
        update_attested_period = self.compute_sync_committee_period_at_slot(
            attested_block.message.slot
        )

        update = self.LightClientUpdate()
        update.attested_header = self.block_to_light_client_header(attested_block)

        # next committee is only useful when signed by the current committee
        if update_attested_period == update_signature_period:
            update.next_sync_committee = attested_state.next_sync_committee
            update.next_sync_committee_branch = self.NextSyncCommitteeBranch(
                compute_merkle_proof(
                    attested_state,
                    self.next_sync_committee_gindex_at_slot(attested_state.slot),
                )
            )

        if finalized_block is not None:
            if finalized_block.message.slot != self.GENESIS_SLOT:
                update.finalized_header = self.block_to_light_client_header(finalized_block)
                assert (
                    hash_tree_root(update.finalized_header.beacon)
                    == attested_state.finalized_checkpoint.root
                )
            else:
                assert attested_state.finalized_checkpoint.root == Bytes32()
            update.finality_branch = self.FinalityBranch(
                compute_merkle_proof(
                    attested_state,
                    self.finalized_root_gindex_at_slot(attested_state.slot),
                )
            )

        update.sync_aggregate = sync_aggregate
        update.signature_slot = block.message.slot
        return update

    def create_light_client_finality_update(self, update):
        return self.LightClientFinalityUpdate(
            attested_header=update.attested_header,
            finalized_header=update.finalized_header,
            finality_branch=update.finality_branch,
            sync_aggregate=update.sync_aggregate,
            signature_slot=update.signature_slot,
        )

    def create_light_client_optimistic_update(self, update):
        return self.LightClientOptimisticUpdate(
            attested_header=update.attested_header,
            sync_aggregate=update.sync_aggregate,
            signature_slot=update.signature_slot,
        )
