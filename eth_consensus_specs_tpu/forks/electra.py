"""electra: MaxEB (EIP-7251), execution-layer deposits (EIP-6110),
execution-layer withdrawals (EIP-7002), committee-bit attestations
(EIP-7549), blob-count bump (EIP-7691).

Behavioral parity targets (reference, by section):
  * state machine:  specs/electra/beacon-chain.md
      - balance-denominated churn :572-600, exit/consolidation queues
        :734-792, pending deposits :943-1020, consolidations :1022-1047
      - committee-bit attestations :613-637, :1435-1488
      - execution requests pipeline :1307-1325, :1389-1426
      - withdrawals with pending partials :1186-1303
  * fork upgrade:   specs/electra/fork.md (upgrade_to_electra :42-144)

Architecture note: Electra replaces phase0's count-denominated churn with
*balance*-denominated queues (exit/consolidation balance accumulators).
These are scalar state machines — tiny, inherently serial — so they stay
host-side; the big per-validator scans they gate (registry updates,
effective-balance updates) remain columnar-kernel targets keyed off the
same EpochColumns as earlier forks.
"""

from eth_consensus_specs_tpu.ssz import (
    Bitlist,
    Bitvector,
    Bytes32,
    Container,
    List,
    Vector,
    hash_tree_root,
    deserialize,
    serialize,
    uint64,
)
from eth_consensus_specs_tpu.utils import bls

from .altair import ParticipationFlags
from .bellatrix import ExecutionAddress, Hash32
from .capella import WithdrawalIndex
from .deneb import DenebExecutionEngine, DenebSpec, KZGCommitment
from .phase0 import (
    BLSPubkey,
    BLSSignature,
    Epoch,
    Gwei,
    Root,
    Slot,
    ValidatorIndex,
    Version,
    uint64 as _u64,
)


class ElectraExecutionEngine(DenebExecutionEngine):
    """Adds the EIP-7685 execution-requests list to the payload handshake
    (reference: specs/electra/beacon-chain.md:1092-1166)."""

    def __init__(self, spec):
        self._spec = spec

    def is_valid_block_hash(
        self, execution_payload, parent_beacon_block_root, execution_requests_list
    ) -> bool:
        return True

    def notify_new_payload(
        self, execution_payload, parent_beacon_block_root, execution_requests_list
    ) -> bool:
        return True

    def verify_and_notify_new_payload(self, new_payload_request) -> bool:
        execution_payload = new_payload_request.execution_payload
        parent_beacon_block_root = new_payload_request.parent_beacon_block_root
        execution_requests_list = self._spec.get_execution_requests_list(
            new_payload_request.execution_requests
        )
        if b"" in [bytes(tx) for tx in execution_payload.transactions]:
            return False
        if not self.is_valid_block_hash(
            execution_payload, parent_beacon_block_root, execution_requests_list
        ):
            return False
        if not self.is_valid_versioned_hashes(new_payload_request):
            return False
        if not self.notify_new_payload(
            execution_payload, parent_beacon_block_root, execution_requests_list
        ):
            return False
        return True


class ElectraSpec(DenebSpec):
    fork_name = "electra"

    # Light client: the electra BeaconState grows past 32 fields, deepening
    # every state-rooted gindex (specs/electra/light-client/sync-protocol.md:56-58)
    FINALIZED_ROOT_GINDEX_ELECTRA = 169
    CURRENT_SYNC_COMMITTEE_GINDEX_ELECTRA = 86
    NEXT_SYNC_COMMITTEE_GINDEX_ELECTRA = 87

    # Constants (specs/electra/beacon-chain.md:125-149)
    UNSET_DEPOSIT_REQUESTS_START_INDEX = 2**64 - 1
    FULL_EXIT_REQUEST_AMOUNT = 0
    COMPOUNDING_WITHDRAWAL_PREFIX = b"\x02"
    DEPOSIT_REQUEST_TYPE = b"\x00"
    WITHDRAWAL_REQUEST_TYPE = b"\x01"
    CONSOLIDATION_REQUEST_TYPE = b"\x02"

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.EXECUTION_ENGINE = ElectraExecutionEngine(self)

    # == type system ======================================================

    def _build_types(self) -> None:
        super()._build_types()
        P = self

        # New containers (specs/electra/beacon-chain.md:219-310)
        class PendingDeposit(Container):
            pubkey: BLSPubkey
            withdrawal_credentials: Bytes32
            amount: Gwei
            signature: BLSSignature
            slot: Slot

        class PendingPartialWithdrawal(Container):
            validator_index: ValidatorIndex
            amount: Gwei
            withdrawable_epoch: Epoch

        class PendingConsolidation(Container):
            source_index: ValidatorIndex
            target_index: ValidatorIndex

        class DepositRequest(Container):
            pubkey: BLSPubkey
            withdrawal_credentials: Bytes32
            amount: Gwei
            signature: BLSSignature
            index: uint64

        class WithdrawalRequest(Container):
            source_address: ExecutionAddress
            validator_pubkey: BLSPubkey
            amount: Gwei

        class ConsolidationRequest(Container):
            source_address: ExecutionAddress
            source_pubkey: BLSPubkey
            target_pubkey: BLSPubkey

        class ExecutionRequests(Container):
            deposits: List[DepositRequest, P.MAX_DEPOSIT_REQUESTS_PER_PAYLOAD]
            withdrawals: List[WithdrawalRequest, P.MAX_WITHDRAWAL_REQUESTS_PER_PAYLOAD]
            consolidations: List[ConsolidationRequest, P.MAX_CONSOLIDATION_REQUESTS_PER_PAYLOAD]

        class SingleAttestation(Container):
            committee_index: uint64
            attester_index: ValidatorIndex
            data: P.AttestationData
            signature: BLSSignature

        # Modified containers (EIP-7549: committee bits move out of data.index)
        class Attestation(Container):
            aggregation_bits: Bitlist[
                P.MAX_VALIDATORS_PER_COMMITTEE * P.MAX_COMMITTEES_PER_SLOT
            ]  # [Modified in Electra:EIP7549]
            data: P.AttestationData
            signature: BLSSignature
            committee_bits: Bitvector[P.MAX_COMMITTEES_PER_SLOT]  # [New in Electra:EIP7549]

        class IndexedAttestation(Container):
            attesting_indices: List[
                ValidatorIndex, P.MAX_VALIDATORS_PER_COMMITTEE * P.MAX_COMMITTEES_PER_SLOT
            ]  # [Modified in Electra:EIP7549]
            data: P.AttestationData
            signature: BLSSignature

        class AttesterSlashing(Container):
            attestation_1: IndexedAttestation
            attestation_2: IndexedAttestation

        # [Modified in Electra:EIP7549] aggregate carries the new Attestation
        # (specs/electra/validator.md AggregateAndProof)
        class AggregateAndProof(Container):
            aggregator_index: ValidatorIndex
            aggregate: Attestation
            selection_proof: BLSSignature

        class SignedAggregateAndProof(Container):
            message: AggregateAndProof
            signature: BLSSignature

        class BeaconBlockBody(Container):
            randao_reveal: BLSSignature
            eth1_data: P.Eth1Data
            graffiti: Bytes32
            proposer_slashings: List[P.ProposerSlashing, P.MAX_PROPOSER_SLASHINGS]
            attester_slashings: List[
                AttesterSlashing, P.MAX_ATTESTER_SLASHINGS_ELECTRA
            ]  # [Modified in Electra:EIP7549]
            attestations: List[
                Attestation, P.MAX_ATTESTATIONS_ELECTRA
            ]  # [Modified in Electra:EIP7549]
            deposits: List[P.Deposit, P.MAX_DEPOSITS]
            voluntary_exits: List[P.SignedVoluntaryExit, P.MAX_VOLUNTARY_EXITS]
            sync_aggregate: P.SyncAggregate
            execution_payload: P.ExecutionPayload
            bls_to_execution_changes: List[
                P.SignedBLSToExecutionChange, P.MAX_BLS_TO_EXECUTION_CHANGES
            ]
            blob_kzg_commitments: List[KZGCommitment, P.MAX_BLOB_COMMITMENTS_PER_BLOCK]
            execution_requests: ExecutionRequests  # [New in Electra]

        class BeaconBlock(Container):
            slot: Slot
            proposer_index: ValidatorIndex
            parent_root: Root
            state_root: Root
            body: BeaconBlockBody

        class SignedBeaconBlock(Container):
            message: BeaconBlock
            signature: BLSSignature

        class BeaconState(Container):
            genesis_time: uint64
            genesis_validators_root: Root
            slot: Slot
            fork: P.Fork
            latest_block_header: P.BeaconBlockHeader
            block_roots: Vector[Root, P.SLOTS_PER_HISTORICAL_ROOT]
            state_roots: Vector[Root, P.SLOTS_PER_HISTORICAL_ROOT]
            historical_roots: List[Root, P.HISTORICAL_ROOTS_LIMIT]
            eth1_data: P.Eth1Data
            eth1_data_votes: List[P.Eth1Data, P.EPOCHS_PER_ETH1_VOTING_PERIOD * P.SLOTS_PER_EPOCH]
            eth1_deposit_index: uint64
            validators: List[P.Validator, P.VALIDATOR_REGISTRY_LIMIT]
            balances: List[Gwei, P.VALIDATOR_REGISTRY_LIMIT]
            randao_mixes: Vector[Bytes32, P.EPOCHS_PER_HISTORICAL_VECTOR]
            slashings: Vector[Gwei, P.EPOCHS_PER_SLASHINGS_VECTOR]
            previous_epoch_participation: List[ParticipationFlags, P.VALIDATOR_REGISTRY_LIMIT]
            current_epoch_participation: List[ParticipationFlags, P.VALIDATOR_REGISTRY_LIMIT]
            justification_bits: Bitvector[self.JUSTIFICATION_BITS_LENGTH]
            previous_justified_checkpoint: P.Checkpoint
            current_justified_checkpoint: P.Checkpoint
            finalized_checkpoint: P.Checkpoint
            inactivity_scores: List[uint64, P.VALIDATOR_REGISTRY_LIMIT]
            current_sync_committee: P.SyncCommittee
            next_sync_committee: P.SyncCommittee
            latest_execution_payload_header: P.ExecutionPayloadHeader
            next_withdrawal_index: WithdrawalIndex
            next_withdrawal_validator_index: ValidatorIndex
            historical_summaries: List[P.HistoricalSummary, P.HISTORICAL_ROOTS_LIMIT]
            deposit_requests_start_index: uint64  # [New in Electra:EIP6110]
            deposit_balance_to_consume: Gwei  # [New in Electra:EIP7251]
            exit_balance_to_consume: Gwei  # [New in Electra:EIP7251]
            earliest_exit_epoch: Epoch  # [New in Electra:EIP7251]
            consolidation_balance_to_consume: Gwei  # [New in Electra:EIP7251]
            earliest_consolidation_epoch: Epoch  # [New in Electra:EIP7251]
            pending_deposits: List[
                PendingDeposit, P.PENDING_DEPOSITS_LIMIT
            ]  # [New in Electra:EIP7251]
            pending_partial_withdrawals: List[
                PendingPartialWithdrawal, P.PENDING_PARTIAL_WITHDRAWALS_LIMIT
            ]  # [New in Electra:EIP7251]
            pending_consolidations: List[
                PendingConsolidation, P.PENDING_CONSOLIDATIONS_LIMIT
            ]  # [New in Electra:EIP7251]

        for name, typ in list(locals().items()):
            if isinstance(typ, type) and issubclass(typ, Container):
                typ.__name__ = name
                setattr(self, name, typ)

    # == request dataclasses ==============================================

    class NewPayloadRequest:
        def __init__(
            self,
            execution_payload,
            versioned_hashes=(),
            parent_beacon_block_root=b"",
            execution_requests=None,
        ):
            self.execution_payload = execution_payload
            self.versioned_hashes = versioned_hashes
            self.parent_beacon_block_root = parent_beacon_block_root
            self.execution_requests = execution_requests

    # == predicates (specs/electra/beacon-chain.md:424-546) ================

    def is_eligible_for_activation_queue(self, validator) -> bool:
        return (
            validator.activation_eligibility_epoch == self.FAR_FUTURE_EPOCH
            # [Modified in Electra:EIP7251]
            and int(validator.effective_balance) >= self.MIN_ACTIVATION_BALANCE
        )

    def is_compounding_withdrawal_credential(self, withdrawal_credentials) -> bool:
        return bytes(withdrawal_credentials)[:1] == self.COMPOUNDING_WITHDRAWAL_PREFIX

    def has_compounding_withdrawal_credential(self, validator) -> bool:
        return self.is_compounding_withdrawal_credential(validator.withdrawal_credentials)

    def has_execution_withdrawal_credential(self, validator) -> bool:
        return self.has_eth1_withdrawal_credential(
            validator
        ) or self.has_compounding_withdrawal_credential(validator)

    def is_fully_withdrawable_validator(self, validator, balance: int, epoch: int) -> bool:
        return (
            # [Modified in Electra:EIP7251]
            self.has_execution_withdrawal_credential(validator)
            and int(validator.withdrawable_epoch) <= epoch
            and int(balance) > 0
        )

    def is_partially_withdrawable_validator(self, validator, balance: int) -> bool:
        max_effective_balance = self.get_max_effective_balance(validator)
        return (
            # [Modified in Electra:EIP7251]
            self.has_execution_withdrawal_credential(validator)
            and int(validator.effective_balance) == max_effective_balance
            and int(balance) > max_effective_balance
        )

    # == misc ==============================================================

    def get_committee_indices(self, committee_bits):
        return [index for index, bit in enumerate(committee_bits) if bit]

    def get_max_effective_balance(self, validator) -> int:
        if self.has_compounding_withdrawal_credential(validator):
            return self.MAX_EFFECTIVE_BALANCE_ELECTRA
        return self.MIN_ACTIVATION_BALANCE

    def compute_proposer_index(self, state, indices, seed: bytes) -> int:
        """16-bit random-value effective-balance filter against MaxEB
        (reference: specs/electra/beacon-chain.md:426-455)."""
        assert len(indices) > 0
        MAX_RANDOM_VALUE = 2**16 - 1
        total = len(indices)
        perm = self._shuffle_permutation(total, seed)
        i = 0
        while True:
            candidate_index = indices[int(perm[i % total])]
            random_bytes = self.hash(seed + self.uint_to_bytes(_u64(i // 16)))
            offset = i % 16 * 2
            random_value = self.bytes_to_uint64(random_bytes[offset : offset + 2])
            effective_balance = int(state.validators[candidate_index].effective_balance)
            if (
                effective_balance * MAX_RANDOM_VALUE
                >= self.MAX_EFFECTIVE_BALANCE_ELECTRA * random_value
            ):
                return int(candidate_index)
            i += 1

    # == accessors =========================================================

    def get_balance_churn_limit(self, state) -> int:
        """Balance-denominated churn (reference: beacon-chain.md:572-583)."""
        churn = max(
            self.config.MIN_PER_EPOCH_CHURN_LIMIT_ELECTRA,
            self.get_total_active_balance(state) // self.config.CHURN_LIMIT_QUOTIENT,
        )
        return churn - churn % self.EFFECTIVE_BALANCE_INCREMENT

    def get_activation_exit_churn_limit(self, state) -> int:
        return min(
            self.config.MAX_PER_EPOCH_ACTIVATION_EXIT_CHURN_LIMIT,
            self.get_balance_churn_limit(state),
        )

    def get_consolidation_churn_limit(self, state) -> int:
        return self.get_balance_churn_limit(state) - self.get_activation_exit_churn_limit(state)

    def get_pending_balance_to_withdraw(self, state, validator_index: int) -> int:
        return sum(
            int(withdrawal.amount)
            for withdrawal in state.pending_partial_withdrawals
            if withdrawal.validator_index == validator_index
        )

    def get_attesting_indices(self, state, attestation):
        """EIP-7549: union over the committees named by committee_bits
        (reference: beacon-chain.md:613-637)."""
        output = set()
        committee_indices = self.get_committee_indices(attestation.committee_bits)
        committee_offset = 0
        for committee_index in committee_indices:
            committee = self.get_beacon_committee(state, attestation.data.slot, committee_index)
            committee_attesters = {
                int(attester_index)
                for i, attester_index in enumerate(committee)
                if attestation.aggregation_bits[committee_offset + i]
            }
            output = output.union(committee_attesters)
            committee_offset += len(committee)
        return output

    def get_next_sync_committee_indices(self, state):
        """16-bit acceptance test against MaxEB (reference:
        beacon-chain.md:639-674)."""
        epoch = self.get_current_epoch(state) + 1
        MAX_RANDOM_VALUE = 2**16 - 1
        active = self.get_active_validator_indices(state, epoch)
        n = len(active)
        seed = self.get_seed(state, epoch, self.DOMAIN_SYNC_COMMITTEE)
        perm = self._shuffle_permutation(n, seed)
        out = []
        i = 0
        while len(out) < self.SYNC_COMMITTEE_SIZE:
            candidate = active[int(perm[i % n])]
            random_bytes = self.hash(seed + self.uint_to_bytes(_u64(i // 16)))
            offset = i % 16 * 2
            random_value = self.bytes_to_uint64(random_bytes[offset : offset + 2])
            effective_balance = int(state.validators[candidate].effective_balance)
            if (
                effective_balance * MAX_RANDOM_VALUE
                >= self.MAX_EFFECTIVE_BALANCE_ELECTRA * random_value
            ):
                out.append(candidate)
            i += 1
        return out

    # == mutators (specs/electra/beacon-chain.md:676-830) ==================

    def initiate_validator_exit(self, state, index: int) -> None:
        validator = state.validators[int(index)]
        if validator.exit_epoch != self.FAR_FUTURE_EPOCH:
            return
        # [Modified in Electra:EIP7251] balance-denominated exit queue
        exit_queue_epoch = self.compute_exit_epoch_and_update_churn(
            state, int(validator.effective_balance)
        )
        validator.exit_epoch = exit_queue_epoch
        validator.withdrawable_epoch = (
            int(validator.exit_epoch) + self.config.MIN_VALIDATOR_WITHDRAWABILITY_DELAY
        )

    def switch_to_compounding_validator(self, state, index: int) -> None:
        validator = state.validators[int(index)]
        validator.withdrawal_credentials = Bytes32(
            self.COMPOUNDING_WITHDRAWAL_PREFIX + bytes(validator.withdrawal_credentials)[1:]
        )
        self.queue_excess_active_balance(state, index)

    def queue_excess_active_balance(self, state, index: int) -> None:
        balance = int(state.balances[int(index)])
        if balance > self.MIN_ACTIVATION_BALANCE:
            excess_balance = balance - self.MIN_ACTIVATION_BALANCE
            state.balances[int(index)] = self.MIN_ACTIVATION_BALANCE
            validator = state.validators[int(index)]
            # G2 infinity signature + GENESIS_SLOT mark an internal transfer,
            # distinguishing it from a pending deposit request
            state.pending_deposits.append(
                self.PendingDeposit(
                    pubkey=validator.pubkey,
                    withdrawal_credentials=validator.withdrawal_credentials,
                    amount=excess_balance,
                    signature=bls.G2_POINT_AT_INFINITY,
                    slot=self.GENESIS_SLOT,
                )
            )

    def compute_exit_epoch_and_update_churn(self, state, exit_balance: int) -> int:
        earliest_exit_epoch = max(
            int(state.earliest_exit_epoch),
            self.compute_activation_exit_epoch(self.get_current_epoch(state)),
        )
        per_epoch_churn = self.get_activation_exit_churn_limit(state)
        if int(state.earliest_exit_epoch) < earliest_exit_epoch:
            exit_balance_to_consume = per_epoch_churn
        else:
            exit_balance_to_consume = int(state.exit_balance_to_consume)

        if exit_balance > exit_balance_to_consume:
            balance_to_process = exit_balance - exit_balance_to_consume
            additional_epochs = (balance_to_process - 1) // per_epoch_churn + 1
            earliest_exit_epoch += additional_epochs
            exit_balance_to_consume += additional_epochs * per_epoch_churn

        state.exit_balance_to_consume = exit_balance_to_consume - exit_balance
        state.earliest_exit_epoch = earliest_exit_epoch
        return int(state.earliest_exit_epoch)

    def compute_consolidation_epoch_and_update_churn(
        self, state, consolidation_balance: int
    ) -> int:
        earliest_consolidation_epoch = max(
            int(state.earliest_consolidation_epoch),
            self.compute_activation_exit_epoch(self.get_current_epoch(state)),
        )
        per_epoch_consolidation_churn = self.get_consolidation_churn_limit(state)
        if int(state.earliest_consolidation_epoch) < earliest_consolidation_epoch:
            consolidation_balance_to_consume = per_epoch_consolidation_churn
        else:
            consolidation_balance_to_consume = int(state.consolidation_balance_to_consume)

        if consolidation_balance > consolidation_balance_to_consume:
            balance_to_process = consolidation_balance - consolidation_balance_to_consume
            additional_epochs = (balance_to_process - 1) // per_epoch_consolidation_churn + 1
            earliest_consolidation_epoch += additional_epochs
            consolidation_balance_to_consume += (
                additional_epochs * per_epoch_consolidation_churn
            )

        state.consolidation_balance_to_consume = (
            consolidation_balance_to_consume - consolidation_balance
        )
        state.earliest_consolidation_epoch = earliest_consolidation_epoch
        return int(state.earliest_consolidation_epoch)

    def compute_subnet_for_blob_sidecar(self, blob_index: int) -> int:
        """[Modified in Electra:EIP7691] reference:
        specs/electra/validator.md:321-323."""
        return int(blob_index) % int(self.config.BLOB_SIDECAR_SUBNET_COUNT_ELECTRA)

    # electra re-points both slashing quotients (beacon-chain.md:794-830)
    def min_slashing_penalty_quotient(self) -> int:
        return self.MIN_SLASHING_PENALTY_QUOTIENT_ELECTRA

    def whistleblower_reward_quotient(self) -> int:
        return self.WHISTLEBLOWER_REWARD_QUOTIENT_ELECTRA

    # == epoch processing (specs/electra/beacon-chain.md:834-1072) =========

    def process_epoch_columnar(self, state) -> None:
        """TWO-PHASE electra fusion (replaces round-2's object-path
        fallback): phase A runs justification + inactivity + rewards +
        the slashings sweep fused on device with the effective-balance
        hysteresis EXCLUDED; the pending deposit/consolidation queues —
        which the spec interleaves between slashings and the
        effective-balance update (specs/electra/beacon-chain.md:943,1022)
        and which touch O(queue) entries, not O(N) — run host-side in
        exact spec order; the hysteresis then runs over the post-queue
        balances.  Bit-exact vs process_epoch_object by the columnar
        oracle tests."""
        import jax
        import numpy as np

        from eth_consensus_specs_tpu.ops.altair_epoch import (
            AltairEpochParams,
            altair_epoch_accounting_phase_a,
        )

        cols, just = self.extract_epoch_columns(state)
        res = altair_epoch_accounting_phase_a(
            AltairEpochParams.from_spec(self), cols, just, include_effective_balance=False
        )
        res = jax.tree_util.tree_map(np.asarray, res)  # one device->host sync
        self._writeback_justification(state, res)
        self.process_registry_updates(state)  # [Modified in Electra:EIP7251]
        self._writeback_balances(state, res, include_eff=False)
        self._writeback_extra(state, res)  # inactivity scores
        self.process_eth1_data_reset(state)
        self._process_pending_queues(state)
        self.process_effective_balance_updates(state)  # [Modified in Electra:EIP7251]
        self._process_epoch_resets(state)

    def _process_pending_queues(self, state) -> None:
        """The O(queue) host-side sub-transitions the spec interleaves
        between the slashings sweep and the effective-balance hysteresis
        (specs/electra/beacon-chain.md:943,1022). A hook so later forks
        (gloas builder payments) extend the interleave in BOTH the
        columnar and the object epoch identically."""
        self.process_pending_deposits(state)  # [New in Electra:EIP7251]
        self.process_pending_consolidations(state)  # [New in Electra:EIP7251]

    def process_epoch_object(self, state) -> None:
        self.process_justification_and_finalization(state)
        self.process_inactivity_updates(state)
        self.process_rewards_and_penalties(state)
        self.process_registry_updates(state)  # [Modified in Electra:EIP7251]
        self.process_slashings(state)  # [Modified in Electra:EIP7251]
        self.process_eth1_data_reset(state)
        self._process_pending_queues(state)
        self.process_effective_balance_updates(state)  # [Modified in Electra:EIP7251]
        self._process_epoch_resets(state)

    def process_registry_updates(self, state) -> None:
        """Single-pass eligibility/ejection/activation loop (reference:
        beacon-chain.md:865-891) — activations no longer queue-sorted."""
        current_epoch = self.get_current_epoch(state)
        activation_epoch = self.compute_activation_exit_epoch(current_epoch)
        for index, validator in enumerate(state.validators):
            if self.is_eligible_for_activation_queue(validator):
                validator.activation_eligibility_epoch = current_epoch + 1
            elif (
                self.is_active_validator(validator, current_epoch)
                and int(validator.effective_balance) <= self.config.EJECTION_BALANCE
            ):
                self.initiate_validator_exit(state, index)
            elif self.is_eligible_for_activation(state, validator):
                validator.activation_epoch = activation_epoch

    def process_slashings(self, state) -> None:
        """Per-increment penalty quantum (reference: beacon-chain.md:893-920)."""
        epoch = self.get_current_epoch(state)
        total_balance = self.get_total_active_balance(state)
        adjusted_total_slashing_balance = min(
            sum(int(s) for s in state.slashings) * self.proportional_slashing_multiplier(),
            total_balance,
        )
        increment = self.EFFECTIVE_BALANCE_INCREMENT
        penalty_per_effective_balance_increment = adjusted_total_slashing_balance // (
            total_balance // increment
        )
        for index, validator in enumerate(state.validators):
            if (
                validator.slashed
                and epoch + self.EPOCHS_PER_SLASHINGS_VECTOR // 2 == validator.withdrawable_epoch
            ):
                effective_balance_increments = int(validator.effective_balance) // increment
                # [Modified in Electra:EIP7251]
                penalty = penalty_per_effective_balance_increment * effective_balance_increments
                self.decrease_balance(state, index, penalty)

    def apply_pending_deposit(self, state, deposit) -> None:
        validator_pubkeys = [v.pubkey for v in state.validators]
        if deposit.pubkey not in validator_pubkeys:
            # proof of possession — the deposit contract does not check it
            if self.is_valid_deposit_signature(
                deposit.pubkey, deposit.withdrawal_credentials, deposit.amount, deposit.signature
            ):
                self.add_validator_to_registry(
                    state, deposit.pubkey, deposit.withdrawal_credentials, deposit.amount
                )
        else:
            validator_index = validator_pubkeys.index(deposit.pubkey)
            self.increase_balance(state, validator_index, deposit.amount)

    def process_pending_deposits(self, state) -> None:
        """Drain the deposit queue under finality + churn gates (reference:
        beacon-chain.md:943-1020)."""
        next_epoch = self.get_current_epoch(state) + 1
        available_for_processing = int(
            state.deposit_balance_to_consume
        ) + self.get_activation_exit_churn_limit(state)
        processed_amount = 0
        next_deposit_index = 0
        deposits_to_postpone = []
        is_churn_limit_reached = False
        finalized_slot = self.compute_start_slot_at_epoch(
            int(state.finalized_checkpoint.epoch)
        )

        for deposit in state.pending_deposits:
            # deposit requests wait until all Eth1-bridge deposits are applied
            if (
                int(deposit.slot) > self.GENESIS_SLOT
                and int(state.eth1_deposit_index) < int(state.deposit_requests_start_index)
            ):
                break
            if int(deposit.slot) > finalized_slot:
                break
            if next_deposit_index >= self.MAX_PENDING_DEPOSITS_PER_EPOCH:
                break

            is_validator_exited = False
            is_validator_withdrawn = False
            validator_pubkeys = [v.pubkey for v in state.validators]
            if deposit.pubkey in validator_pubkeys:
                validator = state.validators[validator_pubkeys.index(deposit.pubkey)]
                is_validator_exited = int(validator.exit_epoch) < self.FAR_FUTURE_EPOCH
                is_validator_withdrawn = int(validator.withdrawable_epoch) < next_epoch

            if is_validator_withdrawn:
                # balance can never become active again; skip the churn
                self.apply_pending_deposit(state, deposit)
            elif is_validator_exited:
                deposits_to_postpone.append(deposit)
            else:
                is_churn_limit_reached = (
                    processed_amount + int(deposit.amount) > available_for_processing
                )
                if is_churn_limit_reached:
                    break
                processed_amount += int(deposit.amount)
                self.apply_pending_deposit(state, deposit)

            next_deposit_index += 1

        state.pending_deposits = (
            list(state.pending_deposits)[next_deposit_index:] + deposits_to_postpone
        )
        if is_churn_limit_reached:
            state.deposit_balance_to_consume = available_for_processing - processed_amount
        else:
            state.deposit_balance_to_consume = 0

    def process_pending_consolidations(self, state) -> None:
        next_epoch = self.get_current_epoch(state) + 1
        next_pending_consolidation = 0
        for pending_consolidation in state.pending_consolidations:
            source_validator = state.validators[int(pending_consolidation.source_index)]
            if source_validator.slashed:
                next_pending_consolidation += 1
                continue
            if int(source_validator.withdrawable_epoch) > next_epoch:
                break
            # move min(balance, effective) — the excess stays withdrawable
            source_effective_balance = min(
                int(state.balances[int(pending_consolidation.source_index)]),
                int(source_validator.effective_balance),
            )
            self.decrease_balance(
                state, pending_consolidation.source_index, source_effective_balance
            )
            self.increase_balance(
                state, pending_consolidation.target_index, source_effective_balance
            )
            next_pending_consolidation += 1

        state.pending_consolidations = list(state.pending_consolidations)[
            next_pending_consolidation:
        ]

    def process_effective_balance_updates(self, state) -> None:
        hysteresis_increment = self.EFFECTIVE_BALANCE_INCREMENT // self.HYSTERESIS_QUOTIENT
        downward_threshold = hysteresis_increment * self.HYSTERESIS_DOWNWARD_MULTIPLIER
        upward_threshold = hysteresis_increment * self.HYSTERESIS_UPWARD_MULTIPLIER
        for index, validator in enumerate(state.validators):
            balance = int(state.balances[index])
            # [Modified in Electra:EIP7251] per-validator cap
            max_effective_balance = self.get_max_effective_balance(validator)
            if (
                balance + downward_threshold < validator.effective_balance
                or int(validator.effective_balance) + upward_threshold < balance
            ):
                validator.effective_balance = min(
                    balance - balance % self.EFFECTIVE_BALANCE_INCREMENT, max_effective_balance
                )

    # == block processing (specs/electra/beacon-chain.md:1168-1864) ========

    def max_blobs_per_block(self) -> int:
        return self.config.MAX_BLOBS_PER_BLOCK_ELECTRA  # [Modified in Electra:EIP7691]

    def get_execution_requests_list(self, execution_requests):
        """EIP-7685 typed flat encoding (reference: beacon-chain.md:1307-1325)."""
        requests = [
            (self.DEPOSIT_REQUEST_TYPE, execution_requests.deposits),
            (self.WITHDRAWAL_REQUEST_TYPE, execution_requests.withdrawals),
            (self.CONSOLIDATION_REQUEST_TYPE, execution_requests.consolidations),
        ]
        return [
            request_type + serialize(request_data)
            for request_type, request_data in requests
            if len(request_data) != 0
        ]

    def get_execution_requests(self, execution_requests_list):
        """Inverse of the flat encoding: typed EL request bytes →
        ExecutionRequests, enforcing strictly-ascending unique types and
        non-empty payloads (specs/electra/validator.md:270-305)."""
        deposits = []
        withdrawals = []
        consolidations = []
        request_types = [
            self.DEPOSIT_REQUEST_TYPE,
            self.WITHDRAWAL_REQUEST_TYPE,
            self.CONSOLIDATION_REQUEST_TYPE,
        ]
        prev_request_type = None
        for request in execution_requests_list:
            request_type, request_data = bytes(request[0:1]), bytes(request[1:])
            assert request_type in request_types, "unknown request type"
            assert len(request_data) != 0, "empty request data"
            assert prev_request_type is None or prev_request_type < request_type, (
                "request types must be strictly ascending"
            )
            prev_request_type = request_type
            if request_type == self.DEPOSIT_REQUEST_TYPE:
                deposits = deserialize(
                    List[self.DepositRequest, self.MAX_DEPOSIT_REQUESTS_PER_PAYLOAD],
                    request_data,
                )
            elif request_type == self.WITHDRAWAL_REQUEST_TYPE:
                withdrawals = deserialize(
                    List[
                        self.WithdrawalRequest,
                        self.MAX_WITHDRAWAL_REQUESTS_PER_PAYLOAD,
                    ],
                    request_data,
                )
            else:
                consolidations = deserialize(
                    List[
                        self.ConsolidationRequest,
                        self.MAX_CONSOLIDATION_REQUESTS_PER_PAYLOAD,
                    ],
                    request_data,
                )
        return self.ExecutionRequests(
            deposits=deposits,
            withdrawals=withdrawals,
            consolidations=consolidations,
        )

    def get_eth1_vote(self, state, eth1_chain):
        """[Modified in Electra:EIP6110] once the bridge is fully drained
        the vote freezes at the current eth1_data — clients can then drop
        the polling mechanism (specs/electra/validator.md:173-177)."""
        if int(state.eth1_deposit_index) == int(state.deposit_requests_start_index):
            return state.eth1_data
        return super().get_eth1_vote(state, eth1_chain)

    def get_eth1_pending_deposit_count(self, state) -> int:
        """How many legacy bridge deposits the next block must carry
        (specs/electra/validator.md:157-165)."""
        eth1_deposit_index_limit = min(
            int(state.eth1_data.deposit_count),
            int(state.deposit_requests_start_index),
        )
        if int(state.eth1_deposit_index) < eth1_deposit_index_limit:
            return min(
                int(self.MAX_DEPOSITS),
                eth1_deposit_index_limit - int(state.eth1_deposit_index),
            )
        return 0

    def process_execution_payload(self, state, body, execution_engine) -> None:
        payload = body.execution_payload
        assert (
            payload.parent_hash == state.latest_execution_payload_header.block_hash
        ), "payload parent mismatch"
        assert payload.prev_randao == self.get_randao_mix(
            state, self.get_current_epoch(state)
        ), "wrong prev_randao"
        assert payload.timestamp == self.compute_timestamp_at_slot(
            state, state.slot
        ), "wrong payload timestamp"
        assert len(body.blob_kzg_commitments) <= self.max_blobs_per_block(), "too many blobs"
        versioned_hashes = [
            self.kzg_commitment_to_versioned_hash(commitment)
            for commitment in body.blob_kzg_commitments
        ]
        assert execution_engine.verify_and_notify_new_payload(
            self.NewPayloadRequest(
                execution_payload=payload,
                versioned_hashes=versioned_hashes,
                parent_beacon_block_root=state.latest_block_header.parent_root,
                execution_requests=body.execution_requests,  # [New in Electra]
            )
        ), "execution engine rejected payload"
        state.latest_execution_payload_header = self.execution_payload_to_header(payload)

    def process_operations(self, state, body) -> None:
        """Deposit-cap switchover + the three execution-request op types
        (reference: beacon-chain.md:1389-1426)."""
        # [Modified in Electra:EIP6110] former deposit mechanism winds down
        eth1_deposit_index_limit = min(
            int(state.eth1_data.deposit_count), int(state.deposit_requests_start_index)
        )
        if int(state.eth1_deposit_index) < eth1_deposit_index_limit:
            assert len(body.deposits) == min(
                self.MAX_DEPOSITS, eth1_deposit_index_limit - int(state.eth1_deposit_index)
            ), "wrong deposit count"
        else:
            assert len(body.deposits) == 0, "deposits no longer allowed"

        for operation in body.proposer_slashings:
            self.process_proposer_slashing(state, operation)
        for operation in body.attester_slashings:
            self.process_attester_slashing(state, operation)
        self._process_attestations(state, body.attestations)
        for operation in body.deposits:
            self.process_deposit(state, operation)
        for operation in body.voluntary_exits:
            self.process_voluntary_exit(state, operation)
        for operation in body.bls_to_execution_changes:
            self.process_bls_to_execution_change(state, operation)
        for operation in body.execution_requests.deposits:  # [New in Electra:EIP6110]
            self.process_deposit_request(state, operation)
        for operation in body.execution_requests.withdrawals:  # [New in Electra:EIP7002]
            self.process_withdrawal_request(state, operation)
        for operation in body.execution_requests.consolidations:  # [New in Electra:EIP7251]
            self.process_consolidation_request(state, operation)

    def process_attestation(self, state, attestation) -> None:
        """EIP-7549 committee-bit validation (reference:
        beacon-chain.md:1435-1488)."""
        data = attestation.data
        assert data.target.epoch in (
            self.get_previous_epoch(state),
            self.get_current_epoch(state),
        ), "target epoch out of range"
        assert data.target.epoch == self.compute_epoch_at_slot(data.slot), "target/slot mismatch"
        assert (
            int(data.slot) + self.MIN_ATTESTATION_INCLUSION_DELAY <= state.slot
        ), "attestation too recent"

        # [Modified in Electra:EIP7549]
        assert data.index == 0, "data.index must be zero post-electra"
        committee_indices = self.get_committee_indices(attestation.committee_bits)
        committee_offset = 0
        for committee_index in committee_indices:
            assert committee_index < self.get_committee_count_per_slot(
                state, data.target.epoch
            ), "committee index out of range"
            committee = self.get_beacon_committee(state, data.slot, committee_index)
            committee_attesters = {
                int(attester_index)
                for i, attester_index in enumerate(committee)
                if attestation.aggregation_bits[committee_offset + i]
            }
            assert len(committee_attesters) > 0, "empty committee participation"
            committee_offset += len(committee)
        assert len(attestation.aggregation_bits) == committee_offset, "bitlist length mismatch"

        participation_flag_indices = self.get_attestation_participation_flag_indices(
            state, data, int(state.slot) - int(data.slot)
        )
        assert self.is_valid_indexed_attestation(
            state, self.get_indexed_attestation(state, attestation)
        ), "invalid aggregate signature"

        if data.target.epoch == self.get_current_epoch(state):
            epoch_participation = state.current_epoch_participation
        else:
            epoch_participation = state.previous_epoch_participation

        proposer_reward_numerator = 0
        for index in self.get_attesting_indices(state, attestation):
            for flag_index, weight in enumerate(self.PARTICIPATION_FLAG_WEIGHTS):
                if flag_index in participation_flag_indices and not self.has_flag(
                    epoch_participation[index], flag_index
                ):
                    epoch_participation[index] = self.add_flag(
                        epoch_participation[index], flag_index
                    )
                    proposer_reward_numerator += self.get_base_reward(state, index) * weight

        proposer_reward_denominator = (
            (self.WEIGHT_DENOMINATOR - self.PROPOSER_WEIGHT)
            * self.WEIGHT_DENOMINATOR
            // self.PROPOSER_WEIGHT
        )
        proposer_reward = proposer_reward_numerator // proposer_reward_denominator
        self.increase_balance(state, self.get_beacon_proposer_index(state), proposer_reward)

    def get_validator_from_deposit(self, pubkey, withdrawal_credentials, amount):
        """New validators start at effective balance 0 until their pending
        deposit lands (reference: beacon-chain.md:1498-1518)."""
        validator = self.Validator(
            pubkey=pubkey,
            withdrawal_credentials=withdrawal_credentials,
            effective_balance=0,
            slashed=False,
            activation_eligibility_epoch=self.FAR_FUTURE_EPOCH,
            activation_epoch=self.FAR_FUTURE_EPOCH,
            exit_epoch=self.FAR_FUTURE_EPOCH,
            withdrawable_epoch=self.FAR_FUTURE_EPOCH,
        )
        # [Modified in Electra:EIP7251]
        max_effective_balance = self.get_max_effective_balance(validator)
        validator.effective_balance = min(
            int(amount) - int(amount) % self.EFFECTIVE_BALANCE_INCREMENT, max_effective_balance
        )
        return validator

    def is_valid_deposit_signature(
        self, pubkey, withdrawal_credentials, amount, signature
    ) -> bool:
        deposit_message = self.DepositMessage(
            pubkey=pubkey, withdrawal_credentials=withdrawal_credentials, amount=amount
        )
        domain = self.compute_domain(self.DOMAIN_DEPOSIT)  # deposits valid across forks
        signing_root = self.compute_signing_root(deposit_message, domain)
        return bls.Verify(pubkey, signing_root, signature)

    def apply_deposit(self, state, pubkey, withdrawal_credentials, amount, signature) -> None:
        validator_pubkeys = [v.pubkey for v in state.validators]
        if pubkey not in validator_pubkeys:
            if self.is_valid_deposit_signature(pubkey, withdrawal_credentials, amount, signature):
                # [Modified in Electra:EIP7251] registry entry with 0 balance
                self.add_validator_to_registry(state, pubkey, withdrawal_credentials, 0)
            else:
                return
        # [Modified in Electra:EIP7251] balance flows through the queue
        state.pending_deposits.append(
            self.PendingDeposit(
                pubkey=pubkey,
                withdrawal_credentials=withdrawal_credentials,
                amount=amount,
                signature=signature,
                slot=self.GENESIS_SLOT,  # distinguishes from a deposit request
            )
        )

    def process_voluntary_exit(self, state, signed_voluntary_exit) -> None:
        voluntary_exit = signed_voluntary_exit.message
        validator = state.validators[int(voluntary_exit.validator_index)]
        assert self.is_active_validator(validator, self.get_current_epoch(state)), "not active"
        assert validator.exit_epoch == self.FAR_FUTURE_EPOCH, "already exiting"
        assert self.get_current_epoch(state) >= voluntary_exit.epoch, "exit not yet valid"
        assert (
            self.get_current_epoch(state)
            >= int(validator.activation_epoch) + self.config.SHARD_COMMITTEE_PERIOD
        ), "validator too young to exit"
        # [New in Electra:EIP7251] no exit while partial withdrawals pend
        assert (
            self.get_pending_balance_to_withdraw(state, int(voluntary_exit.validator_index)) == 0
        ), "pending withdrawals in queue"
        domain = self.compute_domain(
            self.DOMAIN_VOLUNTARY_EXIT,
            self.config.CAPELLA_FORK_VERSION,
            state.genesis_validators_root,
        )
        signing_root = self.compute_signing_root(voluntary_exit, domain)
        assert bls.Verify(validator.pubkey, signing_root, signed_voluntary_exit.signature)
        self.initiate_validator_exit(state, voluntary_exit.validator_index)

    # == withdrawals (specs/electra/beacon-chain.md:1186-1303) =============

    def get_expected_withdrawals(self, state):
        """Pending-partial queue drain, then the capella-style sweep.
        Returns (withdrawals, processed_partial_withdrawals_count)."""
        epoch = self.get_current_epoch(state)
        withdrawal_index = int(state.next_withdrawal_index)
        validator_index = int(state.next_withdrawal_validator_index)
        withdrawals = []
        processed_partial_withdrawals_count = 0

        # [New in Electra:EIP7251] consume pending partial withdrawals
        for withdrawal in state.pending_partial_withdrawals:
            if (
                int(withdrawal.withdrawable_epoch) > epoch
                or len(withdrawals) == self.MAX_PENDING_PARTIALS_PER_WITHDRAWALS_SWEEP
            ):
                break
            validator = state.validators[int(withdrawal.validator_index)]
            has_sufficient_effective_balance = (
                int(validator.effective_balance) >= self.MIN_ACTIVATION_BALANCE
            )
            total_withdrawn = sum(
                int(w.amount)
                for w in withdrawals
                if w.validator_index == withdrawal.validator_index
            )
            balance = int(state.balances[int(withdrawal.validator_index)]) - total_withdrawn
            has_excess_balance = balance > self.MIN_ACTIVATION_BALANCE
            if (
                validator.exit_epoch == self.FAR_FUTURE_EPOCH
                and has_sufficient_effective_balance
                and has_excess_balance
            ):
                withdrawable_balance = min(
                    balance - self.MIN_ACTIVATION_BALANCE, int(withdrawal.amount)
                )
                withdrawals.append(
                    self.Withdrawal(
                        index=withdrawal_index,
                        validator_index=withdrawal.validator_index,
                        address=bytes(validator.withdrawal_credentials)[12:],
                        amount=withdrawable_balance,
                    )
                )
                withdrawal_index += 1
            processed_partial_withdrawals_count += 1

        # sweep for the remaining (full + excess-balance) withdrawals
        bound = min(len(state.validators), self.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP)
        for _ in range(bound):
            validator = state.validators[validator_index]
            # [Modified in Electra:EIP7251] account amounts already queued
            total_withdrawn = sum(
                int(w.amount) for w in withdrawals if w.validator_index == validator_index
            )
            balance = int(state.balances[validator_index]) - total_withdrawn
            address = bytes(validator.withdrawal_credentials)[12:]
            if self.is_fully_withdrawable_validator(validator, balance, epoch):
                withdrawals.append(
                    self.Withdrawal(
                        index=withdrawal_index,
                        validator_index=validator_index,
                        address=address,
                        amount=balance,
                    )
                )
                withdrawal_index += 1
            elif self.is_partially_withdrawable_validator(validator, balance):
                withdrawals.append(
                    self.Withdrawal(
                        index=withdrawal_index,
                        validator_index=validator_index,
                        address=address,
                        amount=balance - self.get_max_effective_balance(validator),
                    )
                )
                withdrawal_index += 1
            if len(withdrawals) == self.MAX_WITHDRAWALS_PER_PAYLOAD:
                break
            validator_index = (validator_index + 1) % len(state.validators)
        return withdrawals, processed_partial_withdrawals_count

    def process_withdrawals(self, state, payload) -> None:
        # [Modified in Electra:EIP7251]
        expected_withdrawals, processed_partial_withdrawals_count = (
            self.get_expected_withdrawals(state)
        )
        assert list(payload.withdrawals) == expected_withdrawals, "withdrawals mismatch"

        for withdrawal in expected_withdrawals:
            self.decrease_balance(state, withdrawal.validator_index, withdrawal.amount)

        # [New in Electra:EIP7251]
        state.pending_partial_withdrawals = list(state.pending_partial_withdrawals)[
            processed_partial_withdrawals_count:
        ]

        if len(expected_withdrawals) != 0:
            state.next_withdrawal_index = int(expected_withdrawals[-1].index) + 1

        if len(expected_withdrawals) == self.MAX_WITHDRAWALS_PER_PAYLOAD:
            state.next_withdrawal_validator_index = (
                int(expected_withdrawals[-1].validator_index) + 1
            ) % len(state.validators)
        else:
            state.next_withdrawal_validator_index = (
                int(state.next_withdrawal_validator_index)
                + self.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP
            ) % len(state.validators)

    # == execution-layer requests (beacon-chain.md:1655-1864) ==============

    def process_withdrawal_request(self, state, withdrawal_request) -> None:
        amount = int(withdrawal_request.amount)
        is_full_exit_request = amount == self.FULL_EXIT_REQUEST_AMOUNT

        if (
            len(state.pending_partial_withdrawals) == self.PENDING_PARTIAL_WITHDRAWALS_LIMIT
            and not is_full_exit_request
        ):
            return

        validator_pubkeys = [v.pubkey for v in state.validators]
        request_pubkey = withdrawal_request.validator_pubkey
        if request_pubkey not in validator_pubkeys:
            return
        index = validator_pubkeys.index(request_pubkey)
        validator = state.validators[index]

        has_correct_credential = self.has_execution_withdrawal_credential(validator)
        is_correct_source_address = (
            bytes(validator.withdrawal_credentials)[12:]
            == bytes(withdrawal_request.source_address)
        )
        if not (has_correct_credential and is_correct_source_address):
            return
        if not self.is_active_validator(validator, self.get_current_epoch(state)):
            return
        if validator.exit_epoch != self.FAR_FUTURE_EPOCH:
            return
        if (
            self.get_current_epoch(state)
            < int(validator.activation_epoch) + self.config.SHARD_COMMITTEE_PERIOD
        ):
            return

        pending_balance_to_withdraw = self.get_pending_balance_to_withdraw(state, index)

        if is_full_exit_request:
            if pending_balance_to_withdraw == 0:
                self.initiate_validator_exit(state, index)
            return

        has_sufficient_effective_balance = (
            int(validator.effective_balance) >= self.MIN_ACTIVATION_BALANCE
        )
        has_excess_balance = (
            int(state.balances[index])
            > self.MIN_ACTIVATION_BALANCE + pending_balance_to_withdraw
        )
        # partial withdrawals only for compounding credentials
        if (
            self.has_compounding_withdrawal_credential(validator)
            and has_sufficient_effective_balance
            and has_excess_balance
        ):
            to_withdraw = min(
                int(state.balances[index])
                - self.MIN_ACTIVATION_BALANCE
                - pending_balance_to_withdraw,
                amount,
            )
            exit_queue_epoch = self.compute_exit_epoch_and_update_churn(state, to_withdraw)
            withdrawable_epoch = (
                exit_queue_epoch + self.config.MIN_VALIDATOR_WITHDRAWABILITY_DELAY
            )
            state.pending_partial_withdrawals.append(
                self.PendingPartialWithdrawal(
                    validator_index=index,
                    amount=to_withdraw,
                    withdrawable_epoch=withdrawable_epoch,
                )
            )

    def process_deposit_request(self, state, deposit_request) -> None:
        if int(state.deposit_requests_start_index) == self.UNSET_DEPOSIT_REQUESTS_START_INDEX:
            state.deposit_requests_start_index = deposit_request.index
        state.pending_deposits.append(
            self.PendingDeposit(
                pubkey=deposit_request.pubkey,
                withdrawal_credentials=deposit_request.withdrawal_credentials,
                amount=deposit_request.amount,
                signature=deposit_request.signature,
                slot=state.slot,
            )
        )

    def is_valid_switch_to_compounding_request(self, state, consolidation_request) -> bool:
        if consolidation_request.source_pubkey != consolidation_request.target_pubkey:
            return False
        source_pubkey = consolidation_request.source_pubkey
        validator_pubkeys = [v.pubkey for v in state.validators]
        if source_pubkey not in validator_pubkeys:
            return False
        source_validator = state.validators[validator_pubkeys.index(source_pubkey)]
        if bytes(source_validator.withdrawal_credentials)[12:] != bytes(
            consolidation_request.source_address
        ):
            return False
        if not self.has_eth1_withdrawal_credential(source_validator):
            return False
        if not self.is_active_validator(source_validator, self.get_current_epoch(state)):
            return False
        if source_validator.exit_epoch != self.FAR_FUTURE_EPOCH:
            return False
        return True

    def process_consolidation_request(self, state, consolidation_request) -> None:
        if self.is_valid_switch_to_compounding_request(state, consolidation_request):
            validator_pubkeys = [v.pubkey for v in state.validators]
            source_index = validator_pubkeys.index(consolidation_request.source_pubkey)
            self.switch_to_compounding_validator(state, source_index)
            return

        # source == target would be a disguised exit
        if consolidation_request.source_pubkey == consolidation_request.target_pubkey:
            return
        if len(state.pending_consolidations) == self.PENDING_CONSOLIDATIONS_LIMIT:
            return
        if self.get_consolidation_churn_limit(state) <= self.MIN_ACTIVATION_BALANCE:
            return

        validator_pubkeys = [v.pubkey for v in state.validators]
        if consolidation_request.source_pubkey not in validator_pubkeys:
            return
        if consolidation_request.target_pubkey not in validator_pubkeys:
            return
        source_index = validator_pubkeys.index(consolidation_request.source_pubkey)
        target_index = validator_pubkeys.index(consolidation_request.target_pubkey)
        source_validator = state.validators[source_index]
        target_validator = state.validators[target_index]

        has_correct_credential = self.has_execution_withdrawal_credential(source_validator)
        is_correct_source_address = (
            bytes(source_validator.withdrawal_credentials)[12:]
            == bytes(consolidation_request.source_address)
        )
        if not (has_correct_credential and is_correct_source_address):
            return
        if not self.has_compounding_withdrawal_credential(target_validator):
            return
        current_epoch = self.get_current_epoch(state)
        if not self.is_active_validator(source_validator, current_epoch):
            return
        if not self.is_active_validator(target_validator, current_epoch):
            return
        if source_validator.exit_epoch != self.FAR_FUTURE_EPOCH:
            return
        if target_validator.exit_epoch != self.FAR_FUTURE_EPOCH:
            return
        if current_epoch < int(source_validator.activation_epoch) + self.config.SHARD_COMMITTEE_PERIOD:
            return
        if self.get_pending_balance_to_withdraw(state, source_index) > 0:
            return

        source_validator.exit_epoch = self.compute_consolidation_epoch_and_update_churn(
            state, int(source_validator.effective_balance)
        )
        source_validator.withdrawable_epoch = (
            int(source_validator.exit_epoch) + self.config.MIN_VALIDATOR_WITHDRAWABILITY_DELAY
        )
        state.pending_consolidations.append(
            self.PendingConsolidation(source_index=source_index, target_index=target_index)
        )

    # == honest validator (specs/electra/validator.md:125-147) =============

    def compute_on_chain_aggregate(self, network_aggregates):
        """Merge same-data single-committee aggregates into one on-chain
        attestation (EIP-7549)."""
        aggregates = sorted(
            network_aggregates,
            key=lambda a: self.get_committee_indices(a.committee_bits)[0],
        )
        data = aggregates[0].data
        bits_type = self.Attestation.fields()["aggregation_bits"]
        aggregation_bits = bits_type(
            [bool(b) for a in aggregates for b in a.aggregation_bits]
        )
        signature = bls.Aggregate([a.signature for a in aggregates])
        committee_indices = [
            self.get_committee_indices(a.committee_bits)[0] for a in aggregates
        ]
        committee_bits = self.Attestation.fields()["committee_bits"](
            [index in committee_indices for index in range(self.MAX_COMMITTEES_PER_SLOT)]
        )
        return self.Attestation(
            aggregation_bits=aggregation_bits,
            data=data,
            committee_bits=committee_bits,
            signature=signature,
        )

    # == light client (specs/electra/light-client/sync-protocol.md) ========

    def _lc_max_gindices(self) -> tuple:
        return (
            self.FINALIZED_ROOT_GINDEX_ELECTRA,
            self.CURRENT_SYNC_COMMITTEE_GINDEX_ELECTRA,
            self.NEXT_SYNC_COMMITTEE_GINDEX_ELECTRA,
        )

    def finalized_root_gindex_at_slot(self, slot: int) -> int:
        epoch = self.compute_epoch_at_slot(slot)
        if epoch >= self.config.ELECTRA_FORK_EPOCH:  # [Modified in Electra]
            return self.FINALIZED_ROOT_GINDEX_ELECTRA
        return self.FINALIZED_ROOT_GINDEX

    def current_sync_committee_gindex_at_slot(self, slot: int) -> int:
        epoch = self.compute_epoch_at_slot(slot)
        if epoch >= self.config.ELECTRA_FORK_EPOCH:  # [Modified in Electra]
            return self.CURRENT_SYNC_COMMITTEE_GINDEX_ELECTRA
        return self.CURRENT_SYNC_COMMITTEE_GINDEX

    def next_sync_committee_gindex_at_slot(self, slot: int) -> int:
        epoch = self.compute_epoch_at_slot(slot)
        if epoch >= self.config.ELECTRA_FORK_EPOCH:  # [Modified in Electra]
            return self.NEXT_SYNC_COMMITTEE_GINDEX_ELECTRA
        return self.NEXT_SYNC_COMMITTEE_GINDEX

    # light-client object upgrades (specs/electra/light-client/fork.md:41-119):
    # pre-electra branches zero-extend to the deeper electra gindices

    def upgrade_lc_header_to_electra(self, pre):
        return self.LightClientHeader(
            beacon=pre.beacon,
            execution=pre.execution,
            execution_branch=pre.execution_branch,
        )

    def upgrade_lc_bootstrap_to_electra(self, pre):
        return self.LightClientBootstrap(
            header=self.upgrade_lc_header_to_electra(pre.header),
            current_sync_committee=pre.current_sync_committee,
            current_sync_committee_branch=self.normalize_merkle_branch(
                pre.current_sync_committee_branch,
                self.CURRENT_SYNC_COMMITTEE_GINDEX_ELECTRA,
            ),
        )

    def upgrade_lc_update_to_electra(self, pre):
        return self.LightClientUpdate(
            attested_header=self.upgrade_lc_header_to_electra(pre.attested_header),
            next_sync_committee=pre.next_sync_committee,
            next_sync_committee_branch=self.normalize_merkle_branch(
                pre.next_sync_committee_branch, self.NEXT_SYNC_COMMITTEE_GINDEX_ELECTRA
            ),
            finalized_header=self.upgrade_lc_header_to_electra(pre.finalized_header),
            finality_branch=self.normalize_merkle_branch(
                pre.finality_branch, self.FINALIZED_ROOT_GINDEX_ELECTRA
            ),
            sync_aggregate=pre.sync_aggregate,
            signature_slot=pre.signature_slot,
        )

    def upgrade_lc_finality_update_to_electra(self, pre):
        return self.LightClientFinalityUpdate(
            attested_header=self.upgrade_lc_header_to_electra(pre.attested_header),
            finalized_header=self.upgrade_lc_header_to_electra(pre.finalized_header),
            finality_branch=self.normalize_merkle_branch(
                pre.finality_branch, self.FINALIZED_ROOT_GINDEX_ELECTRA
            ),
            sync_aggregate=pre.sync_aggregate,
            signature_slot=pre.signature_slot,
        )

    def upgrade_lc_optimistic_update_to_electra(self, pre):
        return self.LightClientOptimisticUpdate(
            attested_header=self.upgrade_lc_header_to_electra(pre.attested_header),
            sync_aggregate=pre.sync_aggregate,
            signature_slot=pre.signature_slot,
        )

    def upgrade_lc_store_to_electra(self, pre):
        if pre.best_valid_update is None:
            best_valid_update = None
        else:
            best_valid_update = self.upgrade_lc_update_to_electra(pre.best_valid_update)
        return self.LightClientStore(
            finalized_header=self.upgrade_lc_header_to_electra(pre.finalized_header),
            current_sync_committee=pre.current_sync_committee,
            next_sync_committee=pre.next_sync_committee,
            best_valid_update=best_valid_update,
            optimistic_header=self.upgrade_lc_header_to_electra(pre.optimistic_header),
            previous_max_active_participants=pre.previous_max_active_participants,
            current_max_active_participants=pre.current_max_active_participants,
        )

    # == fork upgrade (specs/electra/fork.md:42-144) =======================

    def upgrade_from_parent(self, pre):
        epoch = self.compute_epoch_at_slot(int(pre.slot))

        earliest_exit_epoch = self.compute_activation_exit_epoch(epoch)
        for validator in pre.validators:
            if validator.exit_epoch != self.FAR_FUTURE_EPOCH:
                if int(validator.exit_epoch) > earliest_exit_epoch:
                    earliest_exit_epoch = int(validator.exit_epoch)
        earliest_exit_epoch += 1

        post = self.BeaconState(
            genesis_time=pre.genesis_time,
            genesis_validators_root=pre.genesis_validators_root,
            slot=pre.slot,
            fork=self.Fork(
                previous_version=pre.fork.current_version,
                current_version=Version(self.config.ELECTRA_FORK_VERSION),
                epoch=epoch,
            ),
            latest_block_header=pre.latest_block_header,
            block_roots=list(pre.block_roots),
            state_roots=list(pre.state_roots),
            historical_roots=list(pre.historical_roots),
            eth1_data=pre.eth1_data,
            eth1_data_votes=list(pre.eth1_data_votes),
            eth1_deposit_index=pre.eth1_deposit_index,
            validators=list(pre.validators),
            balances=list(pre.balances),
            randao_mixes=list(pre.randao_mixes),
            slashings=list(pre.slashings),
            previous_epoch_participation=list(pre.previous_epoch_participation),
            current_epoch_participation=list(pre.current_epoch_participation),
            justification_bits=list(pre.justification_bits),
            previous_justified_checkpoint=pre.previous_justified_checkpoint,
            current_justified_checkpoint=pre.current_justified_checkpoint,
            finalized_checkpoint=pre.finalized_checkpoint,
            inactivity_scores=list(pre.inactivity_scores),
            current_sync_committee=pre.current_sync_committee,
            next_sync_committee=pre.next_sync_committee,
            latest_execution_payload_header=pre.latest_execution_payload_header,
            next_withdrawal_index=pre.next_withdrawal_index,
            next_withdrawal_validator_index=pre.next_withdrawal_validator_index,
            historical_summaries=list(pre.historical_summaries),
            deposit_requests_start_index=self.UNSET_DEPOSIT_REQUESTS_START_INDEX,
            deposit_balance_to_consume=0,
            exit_balance_to_consume=0,
            earliest_exit_epoch=earliest_exit_epoch,
            consolidation_balance_to_consume=0,
            earliest_consolidation_epoch=self.compute_activation_exit_epoch(epoch),
            pending_deposits=[],
            pending_partial_withdrawals=[],
            pending_consolidations=[],
        )
        post.exit_balance_to_consume = self.get_activation_exit_churn_limit(post)
        post.consolidation_balance_to_consume = self.get_consolidation_churn_limit(post)

        # not-yet-active validators re-enter through the deposit queue
        pre_activation = sorted(
            [
                index
                for index, validator in enumerate(post.validators)
                if validator.activation_epoch == self.FAR_FUTURE_EPOCH
            ],
            key=lambda index: (
                int(post.validators[index].activation_eligibility_epoch),
                index,
            ),
        )
        for index in pre_activation:
            balance = int(post.balances[index])
            post.balances[index] = 0
            validator = post.validators[index]
            validator.effective_balance = 0
            validator.activation_eligibility_epoch = self.FAR_FUTURE_EPOCH
            post.pending_deposits.append(
                self.PendingDeposit(
                    pubkey=validator.pubkey,
                    withdrawal_credentials=validator.withdrawal_credentials,
                    amount=balance,
                    signature=bls.G2_POINT_AT_INFINITY,
                    slot=self.GENESIS_SLOT,
                )
            )

        # early compounding adopters go through the activation churn
        for index, validator in enumerate(post.validators):
            if self.has_compounding_withdrawal_credential(validator):
                self.queue_excess_active_balance(post, index)

        return post
