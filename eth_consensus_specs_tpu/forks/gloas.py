"""gloas: ePBS — enshrined proposer-builder separation (EIP-7732).

The state transition splits in two: importing a signed beacon block (which
commits to a builder's *bid*) and separately importing the builder's
signed execution payload *envelope*. A payload-timeliness committee (PTC)
attests whether the payload actually appeared; builder payments settle
through a two-epoch pending-payment window weighted by same-slot
attestations.

Behavioral parity targets (reference, by section):
  * containers:     specs/gloas/beacon-chain.md:128-319
  * predicates:     :321-408 (builder credentials, same-slot attestation,
    indexed payload attestation, parent-block-full)
  * selection:      :440-530 (balance-weighted selection/acceptance,
    proposer indices, sync committee)
  * accessors:      :532-634 (participation flags with payload matching,
    get_ptc, payment quorum)
  * transition:     :636-735 (split transition, process_slot availability
    reset, builder pending payments, bid processing, state-only
    withdrawals, payload-attestation op, envelope processing :1221-1318)
  * fork upgrade:   specs/gloas/fork.md:34-110

TPU-first notes: balance-weighted selection is the same 16-bit
acceptance-sampling kernel electra introduced for proposers, reused for
three committees — one vectorizable primitive instead of three loops. The
per-slot payment weights live in a fixed 2*SLOTS_PER_EPOCH vector, i.e. a
static-shape accumulator a fused attestation kernel can scatter-add into.
"""

from dataclasses import dataclass, field

from eth_consensus_specs_tpu.ssz import (
    Bitvector,
    Bytes32,
    Container,
    List,
    Vector,
    boolean,
    hash_tree_root,
    uint64,
)
from eth_consensus_specs_tpu.utils import bls

from .bellatrix import ExecutionAddress, Hash32
from .capella import WithdrawalIndex
from .deneb import KZGCommitment
from .fulu import FuluSpec
from .phase0 import (
    BLSSignature,
    Epoch,
    Gwei,
    Root,
    Slot,
    ValidatorIndex,
    Version,
)


class GloasSpec(FuluSpec):
    fork_name = "gloas"

    # Domain types (specs/gloas/beacon-chain.md:88-93)
    DOMAIN_BEACON_BUILDER = b"\x1b\x00\x00\x00"
    DOMAIN_PTC_ATTESTER = b"\x0c\x00\x00\x00"

    # Misc (:95-100)
    BUILDER_PAYMENT_THRESHOLD_NUMERATOR = 6
    BUILDER_PAYMENT_THRESHOLD_DENOMINATOR = 10

    # Withdrawal prefixes (:102-106)
    BUILDER_WITHDRAWAL_PREFIX = b"\x03"

    # == type system ======================================================

    def _build_types(self) -> None:
        super()._build_types()
        P = self

        # New containers (:130-231)
        class BuilderPendingWithdrawal(Container):
            fee_recipient: ExecutionAddress
            amount: Gwei
            builder_index: ValidatorIndex
            withdrawable_epoch: Epoch

        class BuilderPendingPayment(Container):
            weight: Gwei
            withdrawal: BuilderPendingWithdrawal

        class PayloadAttestationData(Container):
            beacon_block_root: Root
            slot: Slot
            payload_present: boolean
            blob_data_available: boolean

        class PayloadAttestation(Container):
            aggregation_bits: Bitvector[P.PTC_SIZE]
            data: PayloadAttestationData
            signature: BLSSignature

        class PayloadAttestationMessage(Container):
            validator_index: ValidatorIndex
            data: PayloadAttestationData
            signature: BLSSignature

        class IndexedPayloadAttestation(Container):
            attesting_indices: List[ValidatorIndex, P.PTC_SIZE]
            data: PayloadAttestationData
            signature: BLSSignature

        class ExecutionPayloadBid(Container):
            parent_block_hash: Hash32
            parent_block_root: Root
            block_hash: Hash32
            prev_randao: Bytes32
            fee_recipient: ExecutionAddress
            gas_limit: uint64
            builder_index: ValidatorIndex
            slot: Slot
            value: Gwei
            execution_payment: Gwei
            blob_kzg_commitments_root: Root

        class SignedExecutionPayloadBid(Container):
            message: ExecutionPayloadBid
            signature: BLSSignature

        class ExecutionPayloadEnvelope(Container):
            payload: P.ExecutionPayload
            execution_requests: P.ExecutionRequests
            builder_index: ValidatorIndex
            beacon_block_root: Root
            slot: Slot
            blob_kzg_commitments: List[KZGCommitment, P.MAX_BLOB_COMMITMENTS_PER_BLOCK]
            state_root: Root

        class SignedExecutionPayloadEnvelope(Container):
            message: ExecutionPayloadEnvelope
            signature: BLSSignature

        # Modified containers (:233-319)
        class BeaconBlockBody(Container):
            randao_reveal: BLSSignature
            eth1_data: P.Eth1Data
            graffiti: Bytes32
            proposer_slashings: List[P.ProposerSlashing, P.MAX_PROPOSER_SLASHINGS]
            attester_slashings: List[P.AttesterSlashing, P.MAX_ATTESTER_SLASHINGS_ELECTRA]
            attestations: List[P.Attestation, P.MAX_ATTESTATIONS_ELECTRA]
            deposits: List[P.Deposit, P.MAX_DEPOSITS]
            voluntary_exits: List[P.SignedVoluntaryExit, P.MAX_VOLUNTARY_EXITS]
            sync_aggregate: P.SyncAggregate
            bls_to_execution_changes: List[
                P.SignedBLSToExecutionChange, P.MAX_BLS_TO_EXECUTION_CHANGES
            ]
            # [New in Gloas:EIP7732] (payload/commitments/requests removed)
            signed_execution_payload_bid: SignedExecutionPayloadBid
            payload_attestations: List[PayloadAttestation, P.MAX_PAYLOAD_ATTESTATIONS]

        class BeaconBlock(Container):
            slot: Slot
            proposer_index: ValidatorIndex
            parent_root: Root
            state_root: Root
            body: BeaconBlockBody

        class SignedBeaconBlock(Container):
            message: BeaconBlock
            signature: BLSSignature

        class BeaconState(Container):
            genesis_time: uint64
            genesis_validators_root: Root
            slot: Slot
            fork: P.Fork
            latest_block_header: P.BeaconBlockHeader
            block_roots: P.BeaconState.fields()["block_roots"]
            state_roots: P.BeaconState.fields()["state_roots"]
            historical_roots: P.BeaconState.fields()["historical_roots"]
            eth1_data: P.Eth1Data
            eth1_data_votes: P.BeaconState.fields()["eth1_data_votes"]
            eth1_deposit_index: uint64
            validators: P.BeaconState.fields()["validators"]
            balances: P.BeaconState.fields()["balances"]
            randao_mixes: P.BeaconState.fields()["randao_mixes"]
            slashings: P.BeaconState.fields()["slashings"]
            previous_epoch_participation: P.BeaconState.fields()[
                "previous_epoch_participation"
            ]
            current_epoch_participation: P.BeaconState.fields()[
                "current_epoch_participation"
            ]
            justification_bits: P.BeaconState.fields()["justification_bits"]
            previous_justified_checkpoint: P.Checkpoint
            current_justified_checkpoint: P.Checkpoint
            finalized_checkpoint: P.Checkpoint
            inactivity_scores: P.BeaconState.fields()["inactivity_scores"]
            current_sync_committee: P.SyncCommittee
            next_sync_committee: P.SyncCommittee
            # [New in Gloas:EIP7732] (latest_execution_payload_header removed)
            latest_execution_payload_bid: ExecutionPayloadBid
            next_withdrawal_index: WithdrawalIndex
            next_withdrawal_validator_index: ValidatorIndex
            historical_summaries: P.BeaconState.fields()["historical_summaries"]
            deposit_requests_start_index: uint64
            deposit_balance_to_consume: Gwei
            exit_balance_to_consume: Gwei
            earliest_exit_epoch: Epoch
            consolidation_balance_to_consume: Gwei
            earliest_consolidation_epoch: Epoch
            pending_deposits: P.BeaconState.fields()["pending_deposits"]
            pending_partial_withdrawals: P.BeaconState.fields()[
                "pending_partial_withdrawals"
            ]
            pending_consolidations: P.BeaconState.fields()["pending_consolidations"]
            proposer_lookahead: P.BeaconState.fields()["proposer_lookahead"]
            # [New in Gloas:EIP7732]
            execution_payload_availability: Bitvector[P.SLOTS_PER_HISTORICAL_ROOT]
            builder_pending_payments: Vector[BuilderPendingPayment, 2 * P.SLOTS_PER_EPOCH]
            builder_pending_withdrawals: List[
                BuilderPendingWithdrawal, P.BUILDER_PENDING_WITHDRAWALS_LIMIT
            ]
            latest_block_hash: Hash32
            latest_withdrawals_root: Root

        for name, typ in list(locals().items()):
            if isinstance(typ, type) and issubclass(typ, Container):
                typ.__name__ = name
                setattr(self, name, typ)

    # gloas re-keys fork-choice weights by (root, payload_status) nodes; the
    # optional proposer re-org helper family is specified only through fulu
    # (specs/gloas/fork-choice.md modifies get_weight but not these), so the
    # inherited root-keyed versions would crash — fail loudly instead.
    _REORG_HELPERS_UNSPECIFIED = (
        "the proposer re-org helpers are not specified for gloas "
        "(get_weight is keyed by ForkChoiceNode, not Root)"
    )

    def is_head_weak(self, store, head_root) -> bool:
        raise NotImplementedError(self._REORG_HELPERS_UNSPECIFIED)

    def is_parent_strong(self, store, parent_root) -> bool:
        raise NotImplementedError(self._REORG_HELPERS_UNSPECIFIED)

    def get_proposer_head(self, store, head_root, slot: int):
        raise NotImplementedError(self._REORG_HELPERS_UNSPECIFIED)

    def should_override_forkchoice_update(self, store, head_root) -> bool:
        raise NotImplementedError(self._REORG_HELPERS_UNSPECIFIED)

    # == slot-component timing (specs/gloas/fork-choice.md:437-485) ========

    def _fork_due_ms(self, epoch: int, pre_bps: int, post_bps: int) -> int:
        """Epoch-gated slot component: gloas tightens every deadline."""
        bps = post_bps if int(epoch) >= self.config.GLOAS_FORK_EPOCH else pre_bps
        return self.get_slot_component_duration_ms(bps)

    def get_attestation_due_ms(self, epoch: int) -> int:
        return self._fork_due_ms(
            epoch,
            self.config.ATTESTATION_DUE_BPS,
            self.config.ATTESTATION_DUE_BPS_GLOAS,
        )

    def get_aggregate_due_ms(self, epoch: int) -> int:
        return self._fork_due_ms(
            epoch, self.config.AGGREGATE_DUE_BPS, self.config.AGGREGATE_DUE_BPS_GLOAS
        )

    def get_sync_message_due_ms(self, epoch: int) -> int:
        return self._fork_due_ms(
            epoch,
            self.config.SYNC_MESSAGE_DUE_BPS,
            self.config.SYNC_MESSAGE_DUE_BPS_GLOAS,
        )

    def get_contribution_due_ms(self, epoch: int) -> int:
        return self._fork_due_ms(
            epoch,
            self.config.CONTRIBUTION_DUE_BPS,
            self.config.CONTRIBUTION_DUE_BPS_GLOAS,
        )

    def get_payload_attestation_due_ms(self, epoch: int) -> int:
        return self.get_slot_component_duration_ms(
            self.config.PAYLOAD_ATTESTATION_DUE_BPS
        )

    # == predicates (:323-408) =============================================

    def is_builder_withdrawal_credential(self, withdrawal_credentials) -> bool:
        return bytes(withdrawal_credentials)[:1] == self.BUILDER_WITHDRAWAL_PREFIX

    def has_builder_withdrawal_credential(self, validator) -> bool:
        return self.is_builder_withdrawal_credential(validator.withdrawal_credentials)

    def has_compounding_withdrawal_credential(self, validator) -> bool:
        """[Modified in Gloas] builders compound too."""
        if self.is_compounding_withdrawal_credential(validator.withdrawal_credentials):
            return True
        return self.is_builder_withdrawal_credential(validator.withdrawal_credentials)

    def is_attestation_same_slot(self, state, data) -> bool:
        """Attestation votes for the block proposed at its own slot (:362-374)."""
        if int(data.slot) == 0:
            return True
        blockroot = bytes(data.beacon_block_root)
        slot_blockroot = bytes(self.get_block_root_at_slot(state, int(data.slot)))
        prev_blockroot = bytes(self.get_block_root_at_slot(state, int(data.slot) - 1))
        return blockroot == slot_blockroot and blockroot != prev_blockroot

    def is_valid_indexed_payload_attestation(self, state, indexed_payload_attestation) -> bool:
        """(:379-396)"""
        indices = [int(i) for i in indexed_payload_attestation.attesting_indices]
        if len(indices) == 0 or indices != sorted(indices):
            return False
        pubkeys = [state.validators[i].pubkey for i in indices]
        domain = self.get_domain(state, self.DOMAIN_PTC_ATTESTER, None)
        signing_root = self.compute_signing_root(indexed_payload_attestation.data, domain)
        return bls.FastAggregateVerify(
            pubkeys, signing_root, indexed_payload_attestation.signature
        )

    def is_parent_block_full(self, state) -> bool:
        """(:406-408)"""
        return bytes(state.latest_execution_payload_bid.block_hash) == bytes(
            state.latest_block_hash
        )

    # == misc (:410-509) ===================================================

    def get_pending_balance_to_withdraw(self, state, validator_index: int) -> int:
        """[Modified in Gloas] include builder payments/withdrawals (:418-437)."""
        validator_index = int(validator_index)
        return (
            sum(
                int(w.amount)
                for w in state.pending_partial_withdrawals
                if int(w.validator_index) == validator_index
            )
            + sum(
                int(w.amount)
                for w in state.builder_pending_withdrawals
                if int(w.builder_index) == validator_index
            )
            + sum(
                int(p.withdrawal.amount)
                for p in state.builder_pending_payments
                if int(p.withdrawal.builder_index) == validator_index
            )
        )

    def compute_balance_weighted_acceptance(self, state, index: int, seed: bytes, i: int) -> bool:
        """16-bit effective-balance acceptance sampling (:474-487)."""
        MAX_RANDOM_VALUE = 2**16 - 1
        random_bytes = self.hash(seed + self.uint_to_bytes(i // 16, 8))
        offset = i % 16 * 2
        random_value = self.bytes_to_uint64(random_bytes[offset : offset + 2])
        effective_balance = int(state.validators[int(index)].effective_balance)
        return (
            effective_balance * MAX_RANDOM_VALUE
            >= self.MAX_EFFECTIVE_BALANCE_ELECTRA * random_value
        )

    def compute_balance_weighted_selection(
        self, state, indices, seed: bytes, size: int, shuffle_indices: bool
    ):
        """(:443-468); the swap-or-not walk uses the cached whole
        permutation (ops/shuffle) instead of per-index hashing."""
        total = len(indices)
        assert total > 0
        perm = self._shuffle_permutation(total, seed) if shuffle_indices else None
        selected = []
        i = 0
        while len(selected) < size:
            next_index = i % total
            if shuffle_indices:
                next_index = int(perm[next_index])
            candidate_index = int(indices[next_index])
            if self.compute_balance_weighted_acceptance(state, candidate_index, seed, i):
                selected.append(candidate_index)
            i += 1
        return selected

    def compute_proposer_indices(self, state, epoch: int, seed: bytes, indices):
        """[Modified in Gloas] via balance-weighted selection (:496-508)."""
        start_slot = self.compute_start_slot_at_epoch(int(epoch))
        seeds = [
            self.hash(seed + self.uint_to_bytes(int(start_slot + i), 8))
            for i in range(self.SLOTS_PER_EPOCH)
        ]
        return [
            self.compute_balance_weighted_selection(
                state, indices, s, size=1, shuffle_indices=True
            )[0]
            for s in seeds
        ]

    # == accessors (:511-634) ==============================================

    def get_next_sync_committee_indices(self, state):
        """[Modified in Gloas] balance-weighted selection (:520-529)."""
        epoch = self.get_current_epoch(state) + 1
        seed = self.get_seed(state, epoch, self.DOMAIN_SYNC_COMMITTEE)
        indices = self.get_active_validator_indices(state, epoch)
        return self.compute_balance_weighted_selection(
            state, indices, seed, size=self.SYNC_COMMITTEE_SIZE, shuffle_indices=True
        )

    def get_attestation_participation_flag_indices(self, state, data, inclusion_delay: int):
        """[Modified in Gloas] head requires payload matching (:538-581)."""
        if data.target.epoch == self.get_current_epoch(state):
            justified_checkpoint = state.current_justified_checkpoint
        else:
            justified_checkpoint = state.previous_justified_checkpoint
        is_matching_source = data.source == justified_checkpoint

        target_root = self.get_block_root(state, data.target.epoch)
        is_matching_target = is_matching_source and bytes(data.target.root) == bytes(target_root)

        # [New in Gloas:EIP7732]
        if self.is_attestation_same_slot(state, data):
            assert data.index == 0, "same-slot attestation index must be 0"
            payload_matches = True
        else:
            slot_index = int(data.slot) % self.SLOTS_PER_HISTORICAL_ROOT
            payload_index = int(state.execution_payload_availability[slot_index])
            payload_matches = int(data.index) == payload_index

        head_root = self.get_block_root_at_slot(state, data.slot)
        head_root_matches = bytes(data.beacon_block_root) == bytes(head_root)
        is_matching_head = is_matching_target and head_root_matches and payload_matches

        assert is_matching_source, "attestation source does not match justified checkpoint"

        participation_flag_indices = []
        if is_matching_source and inclusion_delay <= self.integer_squareroot(
            self.SLOTS_PER_EPOCH
        ):
            participation_flag_indices.append(self.TIMELY_SOURCE_FLAG_INDEX)
        if is_matching_target:
            participation_flag_indices.append(self.TIMELY_TARGET_FLAG_INDEX)
        if is_matching_head and inclusion_delay == self.MIN_ATTESTATION_INCLUSION_DELAY:
            participation_flag_indices.append(self.TIMELY_HEAD_FLAG_INDEX)
        return participation_flag_indices

    def get_ptc_assignment(self, state, epoch: int, validator_index: int):
        """The slot in `epoch` where the validator sits on the PTC, or
        None (specs/gloas/validator.md:57-73; assignments are computable
        one epoch ahead)."""
        next_epoch = self.get_current_epoch(state) + 1
        assert epoch <= next_epoch
        start_slot = self.compute_start_slot_at_epoch(epoch)
        for slot in range(start_slot, start_slot + self.SLOTS_PER_EPOCH):
            if int(validator_index) in self.get_ptc(state, slot):
                return slot
        return None

    def get_payload_attestation_message_signature(
        self, state, attestation, privkey: int
    ):
        """specs/gloas/validator.md:213-219.

        NOTE upstream asymmetry, mirrored faithfully: this helper derives
        the domain from the ATTESTATION SLOT's epoch, while the on-chain
        verifier is_valid_indexed_payload_attestation uses
        get_domain(..., None) = the state's CURRENT epoch
        (specs/gloas/beacon-chain.md:393). PTC attestations are same-slot
        messages, so the two agree except across an epoch boundary."""
        domain = self.get_domain(
            state,
            self.DOMAIN_PTC_ATTESTER,
            self.compute_epoch_at_slot(attestation.data.slot),
        )
        signing_root = self.compute_signing_root(attestation.data, domain)
        return bls.Sign(privkey, signing_root)

    def get_ptc(self, state, slot: int):
        """Payload-timeliness committee (:587-602)."""
        epoch = self.compute_epoch_at_slot(int(slot))
        seed = self.hash(
            self.get_seed(state, epoch, self.DOMAIN_PTC_ATTESTER)
            + self.uint_to_bytes(int(slot), 8)
        )
        indices = []
        committees_per_slot = self.get_committee_count_per_slot(state, epoch)
        for i in range(committees_per_slot):
            committee = self.get_beacon_committee(state, int(slot), i)
            indices.extend(int(v) for v in committee)
        return self.compute_balance_weighted_selection(
            state, indices, seed, size=self.PTC_SIZE, shuffle_indices=False
        )

    def get_indexed_payload_attestation(self, state, slot: int, payload_attestation):
        """(:607-622)"""
        ptc = self.get_ptc(state, int(slot))
        bits = payload_attestation.aggregation_bits
        attesting_indices = [index for i, index in enumerate(ptc) if bits[i]]
        return self.IndexedPayloadAttestation(
            attesting_indices=sorted(attesting_indices),
            data=payload_attestation.data,
            signature=payload_attestation.signature,
        )

    def get_builder_payment_quorum_threshold(self, state) -> int:
        """(:627-634)"""
        per_slot_balance = self.get_total_active_balance(state) // self.SLOTS_PER_EPOCH
        quorum = per_slot_balance * self.BUILDER_PAYMENT_THRESHOLD_NUMERATOR
        return quorum // self.BUILDER_PAYMENT_THRESHOLD_DENOMINATOR

    # == slot processing (:655-671) ========================================

    def process_slot(self, state) -> None:
        super().process_slot(state)
        # [New in Gloas:EIP7732] unset the next payload availability
        availability = list(state.execution_payload_availability)
        availability[(int(state.slot) + 1) % self.SLOTS_PER_HISTORICAL_ROOT] = 0
        state.execution_payload_availability = availability

    # == epoch processing (:675-717) =======================================

    # process_epoch is INHERITED (fulu's columnar-by-default dispatch +
    # lookahead shift); the gloas delta — builder payment settlement
    # between the consolidation queue and the effective-balance
    # hysteresis (:675-717) — rides the electra queue-interleave hook so
    # the fused device epoch IS the default for the newest fork too.

    def _process_pending_queues(self, state) -> None:
        super()._process_pending_queues(state)
        # [New in Gloas:EIP7732]
        self.process_builder_pending_payments(state)

    def process_builder_pending_payments(self, state) -> None:
        """Settle above-quorum payments from the previous epoch (:701-717)."""
        quorum = self.get_builder_payment_quorum_threshold(state)
        payments = list(state.builder_pending_payments)
        for payment in payments[: self.SLOTS_PER_EPOCH]:
            if int(payment.weight) > quorum:
                amount = int(payment.withdrawal.amount)
                exit_queue_epoch = self.compute_exit_epoch_and_update_churn(state, amount)
                withdrawable_epoch = (
                    int(exit_queue_epoch) + self.config.MIN_VALIDATOR_WITHDRAWABILITY_DELAY
                )
                withdrawal = payment.withdrawal.copy()
                withdrawal.withdrawable_epoch = withdrawable_epoch
                state.builder_pending_withdrawals.append(withdrawal)
        state.builder_pending_payments = payments[self.SLOTS_PER_EPOCH :] + [
            self.BuilderPendingPayment() for _ in range(self.SLOTS_PER_EPOCH)
        ]

    # == block processing (:719-735) =======================================

    def process_block(self, state, block) -> None:
        self.process_block_header(state, block)
        # [Modified in Gloas:EIP7732] withdrawals are state-deterministic
        self.process_withdrawals(state)
        # [New in Gloas:EIP7732]
        self.process_execution_payload_bid(state, block)
        self.process_randao(state, block.body)
        self.process_eth1_data(state, block.body)
        self.process_operations(state, block.body)
        self.process_sync_aggregate(state, block.body.sync_aggregate)

    # == withdrawals (:739-927) ============================================

    def is_builder_payment_withdrawable(self, state, withdrawal) -> bool:
        """(:742-750)"""
        builder = state.validators[int(withdrawal.builder_index)]
        current_epoch = self.compute_epoch_at_slot(int(state.slot))
        return int(builder.withdrawable_epoch) >= current_epoch or not builder.slashed

    def get_expected_withdrawals(self, state):
        """[Modified in Gloas] builder sweep first; returns
        (withdrawals, builder_count, partials_count) (:756-864)."""
        epoch = self.get_current_epoch(state)
        withdrawal_index = int(state.next_withdrawal_index)
        validator_index = int(state.next_withdrawal_validator_index)
        withdrawals = []
        processed_partial_withdrawals_count = 0
        processed_builder_withdrawals_count = 0

        # [New in Gloas:EIP7732] sweep for builder payments
        for withdrawal in state.builder_pending_withdrawals:
            if (
                int(withdrawal.withdrawable_epoch) > epoch
                or len(withdrawals) + 1 == self.MAX_WITHDRAWALS_PER_PAYLOAD
            ):
                break
            if self.is_builder_payment_withdrawable(state, withdrawal):
                builder_index = int(withdrawal.builder_index)
                total_withdrawn = sum(
                    int(w.amount) for w in withdrawals if int(w.validator_index) == builder_index
                )
                balance = int(state.balances[builder_index]) - total_withdrawn
                builder = state.validators[builder_index]
                if builder.slashed:
                    withdrawable_balance = min(balance, int(withdrawal.amount))
                elif balance > self.MIN_ACTIVATION_BALANCE:
                    withdrawable_balance = min(
                        balance - self.MIN_ACTIVATION_BALANCE, int(withdrawal.amount)
                    )
                else:
                    withdrawable_balance = 0
                if withdrawable_balance > 0:
                    withdrawals.append(
                        self.Withdrawal(
                            index=withdrawal_index,
                            validator_index=builder_index,
                            address=withdrawal.fee_recipient,
                            amount=withdrawable_balance,
                        )
                    )
                    withdrawal_index += 1
            processed_builder_withdrawals_count += 1

        # sweep for pending partial withdrawals
        bound = min(
            len(withdrawals) + self.MAX_PENDING_PARTIALS_PER_WITHDRAWALS_SWEEP,
            self.MAX_WITHDRAWALS_PER_PAYLOAD - 1,
        )
        for withdrawal in state.pending_partial_withdrawals:
            if int(withdrawal.withdrawable_epoch) > epoch or len(withdrawals) == bound:
                break
            validator = state.validators[int(withdrawal.validator_index)]
            has_sufficient_effective_balance = (
                int(validator.effective_balance) >= self.MIN_ACTIVATION_BALANCE
            )
            total_withdrawn = sum(
                int(w.amount)
                for w in withdrawals
                if int(w.validator_index) == int(withdrawal.validator_index)
            )
            balance = int(state.balances[int(withdrawal.validator_index)]) - total_withdrawn
            has_excess_balance = balance > self.MIN_ACTIVATION_BALANCE
            if (
                int(validator.exit_epoch) == self.FAR_FUTURE_EPOCH
                and has_sufficient_effective_balance
                and has_excess_balance
            ):
                withdrawable_balance = min(
                    balance - self.MIN_ACTIVATION_BALANCE, int(withdrawal.amount)
                )
                withdrawals.append(
                    self.Withdrawal(
                        index=withdrawal_index,
                        validator_index=withdrawal.validator_index,
                        address=ExecutionAddress(bytes(validator.withdrawal_credentials)[12:]),
                        amount=withdrawable_balance,
                    )
                )
                withdrawal_index += 1
            processed_partial_withdrawals_count += 1

        # sweep for remaining
        bound = min(len(state.validators), self.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP)
        for _ in range(bound):
            validator = state.validators[validator_index]
            total_withdrawn = sum(
                int(w.amount) for w in withdrawals if int(w.validator_index) == validator_index
            )
            balance = int(state.balances[validator_index]) - total_withdrawn
            if self.is_fully_withdrawable_validator(validator, balance, epoch):
                withdrawals.append(
                    self.Withdrawal(
                        index=withdrawal_index,
                        validator_index=validator_index,
                        address=ExecutionAddress(bytes(validator.withdrawal_credentials)[12:]),
                        amount=balance,
                    )
                )
                withdrawal_index += 1
            elif self.is_partially_withdrawable_validator(validator, balance):
                withdrawals.append(
                    self.Withdrawal(
                        index=withdrawal_index,
                        validator_index=validator_index,
                        address=ExecutionAddress(bytes(validator.withdrawal_credentials)[12:]),
                        amount=balance - self.get_max_effective_balance(validator),
                    )
                )
                withdrawal_index += 1
            if len(withdrawals) == self.MAX_WITHDRAWALS_PER_PAYLOAD:
                break
            validator_index = (validator_index + 1) % len(state.validators)

        return (
            withdrawals,
            processed_builder_withdrawals_count,
            processed_partial_withdrawals_count,
        )

    def process_withdrawals(self, state, payload=None) -> None:
        """[Modified in Gloas] state-only; payload honors
        latest_withdrawals_root later (:877-926)."""
        # [New in Gloas:EIP7732] no-op when the parent block was empty
        if not self.is_parent_block_full(state):
            return

        (
            withdrawals,
            processed_builder_withdrawals_count,
            processed_partial_withdrawals_count,
        ) = self.get_expected_withdrawals(state)
        withdrawals_list = List[self.Withdrawal, self.MAX_WITHDRAWALS_PER_PAYLOAD](withdrawals)
        state.latest_withdrawals_root = hash_tree_root(withdrawals_list)
        for withdrawal in withdrawals:
            self.decrease_balance(state, int(withdrawal.validator_index), int(withdrawal.amount))

        # update the pending builder withdrawals
        remaining = [
            w
            for w in list(state.builder_pending_withdrawals)[
                :processed_builder_withdrawals_count
            ]
            if not self.is_builder_payment_withdrawable(state, w)
        ]
        state.builder_pending_withdrawals = remaining + list(
            state.builder_pending_withdrawals
        )[processed_builder_withdrawals_count:]

        state.pending_partial_withdrawals = list(state.pending_partial_withdrawals)[
            processed_partial_withdrawals_count:
        ]

        if len(withdrawals) != 0:
            state.next_withdrawal_index = int(withdrawals[-1].index) + 1

        if len(withdrawals) == self.MAX_WITHDRAWALS_PER_PAYLOAD:
            state.next_withdrawal_validator_index = (
                int(withdrawals[-1].validator_index) + 1
            ) % len(state.validators)
        else:
            next_index = (
                int(state.next_withdrawal_validator_index)
                + self.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP
            )
            state.next_withdrawal_validator_index = next_index % len(state.validators)

    # == execution payload bid (:931-1007) =================================

    def verify_execution_payload_bid_signature(self, state, signed_bid) -> bool:
        builder = state.validators[int(signed_bid.message.builder_index)]
        signing_root = self.compute_signing_root(
            signed_bid.message, self.get_domain(state, self.DOMAIN_BEACON_BUILDER)
        )
        return bls.Verify(builder.pubkey, signing_root, signed_bid.signature)

    def process_execution_payload_bid(self, state, block) -> None:
        signed_bid = block.body.signed_execution_payload_bid
        bid = signed_bid.message
        builder_index = int(bid.builder_index)
        builder = state.validators[builder_index]

        amount = int(bid.value)
        # self-builds bid zero and carry the infinity signature
        if builder_index == int(block.proposer_index):
            assert amount == 0, "self-build bid must be zero"
            assert bytes(signed_bid.signature) == bls.G2_POINT_AT_INFINITY, (
                "self-build must use infinity signature"
            )
        else:
            assert self.has_builder_withdrawal_credential(builder), "not a builder credential"
            assert self.verify_execution_payload_bid_signature(state, signed_bid), (
                "invalid bid signature"
            )

        assert self.is_active_validator(builder, self.get_current_epoch(state)), (
            "builder not active"
        )
        assert not builder.slashed, "builder slashed"

        pending_payments = sum(
            int(p.withdrawal.amount)
            for p in state.builder_pending_payments
            if int(p.withdrawal.builder_index) == builder_index
        )
        pending_withdrawals = sum(
            int(w.amount)
            for w in state.builder_pending_withdrawals
            if int(w.builder_index) == builder_index
        )
        assert (
            amount == 0
            or int(state.balances[builder_index])
            >= amount + pending_payments + pending_withdrawals + self.MIN_ACTIVATION_BALANCE
        ), "builder cannot cover bid"

        assert int(bid.slot) == int(block.slot), "bid for wrong slot"
        assert bytes(bid.parent_block_hash) == bytes(state.latest_block_hash), (
            "bid parent hash mismatch"
        )
        assert bytes(bid.parent_block_root) == bytes(block.parent_root), (
            "bid parent root mismatch"
        )
        assert bytes(bid.prev_randao) == bytes(
            self.get_randao_mix(state, self.get_current_epoch(state))
        ), "bid randao mismatch"

        if amount > 0:
            pending_payment = self.BuilderPendingPayment(
                weight=0,
                withdrawal=self.BuilderPendingWithdrawal(
                    fee_recipient=bid.fee_recipient,
                    amount=amount,
                    builder_index=builder_index,
                    withdrawable_epoch=self.FAR_FUTURE_EPOCH,
                ),
            )
            state.builder_pending_payments[
                self.SLOTS_PER_EPOCH + int(bid.slot) % self.SLOTS_PER_EPOCH
            ] = pending_payment

        state.latest_execution_payload_bid = bid

    # == operations (:1011-1204) ===========================================

    def process_operations(self, state, body) -> None:
        """[Modified in Gloas] PTC attestations in; request ops move to the
        envelope (:1018-1050)."""
        eth1_deposit_index_limit = min(
            int(state.eth1_data.deposit_count), int(state.deposit_requests_start_index)
        )
        if int(state.eth1_deposit_index) < eth1_deposit_index_limit:
            assert len(body.deposits) == min(
                self.MAX_DEPOSITS, eth1_deposit_index_limit - int(state.eth1_deposit_index)
            ), "wrong deposit count"
        else:
            assert len(body.deposits) == 0, "deposits no longer allowed"

        for operation in body.proposer_slashings:
            self.process_proposer_slashing(state, operation)
        for operation in body.attester_slashings:
            self.process_attester_slashing(state, operation)
        # batch-verification seam: one RLC pairing per block (phase0.py)
        self._process_attestations(state, body.attestations)
        for operation in body.deposits:
            self.process_deposit(state, operation)
        for operation in body.voluntary_exits:
            self.process_voluntary_exit(state, operation)
        for operation in body.bls_to_execution_changes:
            self.process_bls_to_execution_change(state, operation)
        # [New in Gloas:EIP7732]
        for operation in body.payload_attestations:
            self.process_payload_attestation(state, operation)

    def process_attestation(self, state, attestation) -> None:
        """[Modified in Gloas] index signals payload availability; same-slot
        attesters add weight to the slot's builder payment (:1061-1142)."""
        data = attestation.data
        assert data.target.epoch in (
            self.get_previous_epoch(state),
            self.get_current_epoch(state),
        ), "target epoch out of range"
        assert data.target.epoch == self.compute_epoch_at_slot(data.slot), "target/slot mismatch"
        assert (
            int(data.slot) + self.MIN_ATTESTATION_INCLUSION_DELAY <= state.slot
        ), "attestation too recent"

        # [Modified in Gloas:EIP7732]
        assert int(data.index) < 2, "index must encode payload availability (0/1)"
        committee_indices = self.get_committee_indices(attestation.committee_bits)
        committee_offset = 0
        for committee_index in committee_indices:
            assert committee_index < self.get_committee_count_per_slot(
                state, data.target.epoch
            ), "committee index out of range"
            committee = self.get_beacon_committee(state, data.slot, committee_index)
            committee_attesters = {
                int(attester_index)
                for i, attester_index in enumerate(committee)
                if attestation.aggregation_bits[committee_offset + i]
            }
            assert len(committee_attesters) > 0, "empty committee participation"
            committee_offset += len(committee)
        assert len(attestation.aggregation_bits) == committee_offset, "bitlist length mismatch"

        participation_flag_indices = self.get_attestation_participation_flag_indices(
            state, data, int(state.slot) - int(data.slot)
        )
        assert self.is_valid_indexed_attestation(
            state, self.get_indexed_attestation(state, attestation)
        ), "invalid aggregate signature"

        # [Modified in Gloas:EIP7732]
        if data.target.epoch == self.get_current_epoch(state):
            current_epoch_target = True
            epoch_participation = state.current_epoch_participation
            payment_index = self.SLOTS_PER_EPOCH + int(data.slot) % self.SLOTS_PER_EPOCH
        else:
            current_epoch_target = False
            epoch_participation = state.previous_epoch_participation
            payment_index = int(data.slot) % self.SLOTS_PER_EPOCH
        payment = state.builder_pending_payments[payment_index].copy()

        proposer_reward_numerator = 0
        for index in self.get_attesting_indices(state, attestation):
            will_set_new_flag = False
            for flag_index, weight in enumerate(self.PARTICIPATION_FLAG_WEIGHTS):
                if flag_index in participation_flag_indices and not self.has_flag(
                    epoch_participation[index], flag_index
                ):
                    epoch_participation[index] = self.add_flag(
                        epoch_participation[index], flag_index
                    )
                    proposer_reward_numerator += self.get_base_reward(state, index) * weight
                    will_set_new_flag = True

            # [New in Gloas:EIP7732] same-slot attesters weight the payment
            if (
                will_set_new_flag
                and self.is_attestation_same_slot(state, data)
                and int(payment.withdrawal.amount) > 0
            ):
                payment.weight = int(payment.weight) + int(
                    state.validators[index].effective_balance
                )

        proposer_reward_denominator = (
            (self.WEIGHT_DENOMINATOR - self.PROPOSER_WEIGHT)
            * self.WEIGHT_DENOMINATOR
            // self.PROPOSER_WEIGHT
        )
        proposer_reward = proposer_reward_numerator // proposer_reward_denominator
        self.increase_balance(state, self.get_beacon_proposer_index(state), proposer_reward)

        # [New in Gloas:EIP7732]
        state.builder_pending_payments[payment_index] = payment

    def process_payload_attestation(self, state, payload_attestation) -> None:
        """(:1149-1163)"""
        data = payload_attestation.data
        assert bytes(data.beacon_block_root) == bytes(state.latest_block_header.parent_root), (
            "payload attestation not for parent block"
        )
        assert int(data.slot) + 1 == int(state.slot), "payload attestation not for previous slot"
        indexed_payload_attestation = self.get_indexed_payload_attestation(
            state, int(data.slot), payload_attestation
        )
        assert self.is_valid_indexed_payload_attestation(
            state, indexed_payload_attestation
        ), "invalid payload attestation signature"

    def process_proposer_slashing(self, state, proposer_slashing) -> None:
        """[Modified in Gloas] voids the slot's pending builder payment
        (:1170-1203)."""
        super().process_proposer_slashing(state, proposer_slashing)
        slot = int(proposer_slashing.signed_header_1.message.slot)
        proposal_epoch = self.compute_epoch_at_slot(slot)
        if proposal_epoch == self.get_current_epoch(state):
            payment_index = self.SLOTS_PER_EPOCH + slot % self.SLOTS_PER_EPOCH
            state.builder_pending_payments[payment_index] = self.BuilderPendingPayment()
        elif proposal_epoch == self.get_previous_epoch(state):
            payment_index = slot % self.SLOTS_PER_EPOCH
            state.builder_pending_payments[payment_index] = self.BuilderPendingPayment()

    # == execution payload (envelope) processing (:1208-1318) ==============

    def verify_execution_payload_envelope_signature(self, state, signed_envelope) -> bool:
        builder = state.validators[int(signed_envelope.message.builder_index)]
        signing_root = self.compute_signing_root(
            signed_envelope.message, self.get_domain(state, self.DOMAIN_BEACON_BUILDER)
        )
        return bls.Verify(builder.pubkey, signing_root, signed_envelope.signature)

    def process_execution_payload(self, state, signed_envelope, execution_engine, verify=True):
        """[Modified in Gloas] independent transition step importing the
        builder's payload envelope (:1228-1318)."""
        envelope = signed_envelope.message
        payload = envelope.payload

        if verify:
            assert self.verify_execution_payload_envelope_signature(
                state, signed_envelope
            ), "invalid envelope signature"

        previous_state_root = hash_tree_root(state)
        if bytes(state.latest_block_header.state_root) == b"\x00" * 32:
            state.latest_block_header.state_root = previous_state_root

        assert bytes(envelope.beacon_block_root) == bytes(
            hash_tree_root(state.latest_block_header)
        ), "envelope not for latest block"
        assert int(envelope.slot) == int(state.slot), "envelope for wrong slot"

        committed_bid = state.latest_execution_payload_bid
        assert int(envelope.builder_index) == int(committed_bid.builder_index), (
            "wrong builder"
        )
        assert bytes(committed_bid.blob_kzg_commitments_root) == bytes(
            hash_tree_root(envelope.blob_kzg_commitments)
        ), "commitments root mismatch"
        assert bytes(committed_bid.prev_randao) == bytes(payload.prev_randao), (
            "randao mismatch"
        )
        assert bytes(hash_tree_root(payload.withdrawals)) == bytes(
            state.latest_withdrawals_root
        ), "withdrawals root mismatch"
        assert int(committed_bid.gas_limit) == int(payload.gas_limit), "gas limit mismatch"
        assert bytes(committed_bid.block_hash) == bytes(payload.block_hash), (
            "block hash mismatch"
        )
        assert bytes(payload.parent_hash) == bytes(state.latest_block_hash), (
            "payload parent mismatch"
        )
        assert payload.timestamp == self.compute_timestamp_at_slot(state, state.slot), (
            "wrong payload timestamp"
        )
        assert (
            len(envelope.blob_kzg_commitments)
            <= self.get_blob_parameters(self.get_current_epoch(state)).max_blobs_per_block
        ), "too many blobs"
        versioned_hashes = [
            self.kzg_commitment_to_versioned_hash(commitment)
            for commitment in envelope.blob_kzg_commitments
        ]
        requests = envelope.execution_requests
        assert execution_engine.verify_and_notify_new_payload(
            self.NewPayloadRequest(
                execution_payload=payload,
                versioned_hashes=versioned_hashes,
                parent_beacon_block_root=state.latest_block_header.parent_root,
                execution_requests=requests,
            )
        ), "execution engine rejected payload"

        for operation in requests.deposits:
            self.process_deposit_request(state, operation)
        for operation in requests.withdrawals:
            self.process_withdrawal_request(state, operation)
        for operation in requests.consolidations:
            self.process_consolidation_request(state, operation)

        # queue the builder payment
        payment_index = self.SLOTS_PER_EPOCH + int(state.slot) % self.SLOTS_PER_EPOCH
        payment = state.builder_pending_payments[payment_index].copy()
        amount = int(payment.withdrawal.amount)
        if amount > 0:
            exit_queue_epoch = self.compute_exit_epoch_and_update_churn(state, amount)
            withdrawal = payment.withdrawal.copy()
            withdrawal.withdrawable_epoch = (
                int(exit_queue_epoch) + self.config.MIN_VALIDATOR_WITHDRAWABILITY_DELAY
            )
            state.builder_pending_withdrawals.append(withdrawal)
        state.builder_pending_payments[payment_index] = self.BuilderPendingPayment()

        # cache the execution payload hash + availability
        availability = list(state.execution_payload_availability)
        availability[int(state.slot) % self.SLOTS_PER_HISTORICAL_ROOT] = 1
        state.execution_payload_availability = availability
        state.latest_block_hash = payload.block_hash

        if verify:
            assert bytes(envelope.state_root) == bytes(hash_tree_root(state)), (
                "envelope state root mismatch"
            )

    # == fork choice (specs/gloas/fork-choice.md) ==========================
    #
    # The block tree becomes a DAG over (root, payload_status) nodes: each
    # beacon block can be extended on its EMPTY branch (payload never
    # revealed) or its FULL branch (envelope imported), and LMD votes carry
    # the attested payload availability in data.index.

    PAYLOAD_STATUS_PENDING = 0
    PAYLOAD_STATUS_EMPTY = 1
    PAYLOAD_STATUS_FULL = 2

    @property
    def PAYLOAD_TIMELY_THRESHOLD(self) -> int:
        return self.PTC_SIZE // 2

    @dataclass(frozen=True)
    class ForkChoiceNode:
        root: bytes
        payload_status: int

    @dataclass(frozen=True)
    class LatestMessage:
        """[Modified in Gloas] slot-granular vote with payload flag
        (fork-choice.md:74-84)."""

        slot: int
        root: bytes
        payload_present: bool

    @dataclass
    class Store(FuluSpec.Store):
        """[Modified in Gloas] adds execution_payload_states + ptc_vote
        (fork-choice.md:117-137); populated by get_forkchoice_store."""

        execution_payload_states: dict = field(default_factory=dict)
        ptc_vote: dict = field(default_factory=dict)

    def get_forkchoice_store(self, anchor_state, anchor_block):
        store = super().get_forkchoice_store(anchor_state, anchor_block)
        anchor_root = bytes(hash_tree_root(anchor_block))
        # [New in Gloas:EIP7732] (fork-choice.md:163-165)
        store.execution_payload_states = {anchor_root: anchor_state.copy()}
        store.ptc_vote = {anchor_root: [False] * self.PTC_SIZE}
        return store

    def update_latest_messages(self, store, attesting_indices, attestation) -> None:
        """[Modified in Gloas] slot-keyed messages (fork-choice.md:95-108)."""
        slot = int(attestation.data.slot)
        beacon_block_root = bytes(attestation.data.beacon_block_root)
        payload_present = int(attestation.data.index) == 1
        non_equivocating = [i for i in attesting_indices if i not in store.equivocating_indices]
        for i in non_equivocating:
            if i not in store.latest_messages or slot > store.latest_messages[i].slot:
                store.latest_messages[i] = self.LatestMessage(
                    slot=slot, root=beacon_block_root, payload_present=payload_present
                )

    def notify_ptc_messages(self, store, state, payload_attestations) -> None:
        """Ingest block-carried PTC attestations (fork-choice.md:172-194)."""
        if int(state.slot) == 0:
            return
        for payload_attestation in payload_attestations:
            indexed = self.get_indexed_payload_attestation(
                state, int(state.slot) - 1, payload_attestation
            )
            for idx in indexed.attesting_indices:
                self.on_payload_attestation_message(
                    store,
                    self.PayloadAttestationMessage(
                        validator_index=idx, data=payload_attestation.data
                    ),
                    is_from_block=True,
                )

    def is_payload_timely(self, store, root) -> bool:
        """(fork-choice.md:200-213)"""
        root = bytes(root)
        assert root in store.ptc_vote, "unknown block for PTC vote"
        if root not in store.execution_payload_states:
            return False
        return sum(store.ptc_vote[root]) > self.PAYLOAD_TIMELY_THRESHOLD

    def get_parent_payload_status(self, store, block) -> int:
        """(fork-choice.md:219-223)"""
        parent = store.blocks[bytes(block.parent_root)]
        parent_block_hash = bytes(block.body.signed_execution_payload_bid.message.parent_block_hash)
        message_block_hash = bytes(parent.body.signed_execution_payload_bid.message.block_hash)
        return (
            self.PAYLOAD_STATUS_FULL
            if parent_block_hash == message_block_hash
            else self.PAYLOAD_STATUS_EMPTY
        )

    def is_parent_node_full(self, store, block) -> bool:
        return self.get_parent_payload_status(store, block) == self.PAYLOAD_STATUS_FULL

    def get_ancestor(self, store, root, slot: int):
        """[Modified in Gloas] returns a ForkChoiceNode carrying whether
        the chain passes through the ancestor's EMPTY or FULL branch
        (fork-choice.md:239-256)."""
        root = bytes(root)
        block = store.blocks[root]
        if int(block.slot) <= int(slot):
            return self.ForkChoiceNode(root=root, payload_status=self.PAYLOAD_STATUS_PENDING)
        parent = store.blocks[bytes(block.parent_root)]
        if int(parent.slot) > int(slot):
            return self.get_ancestor(store, block.parent_root, slot)
        return self.ForkChoiceNode(
            root=bytes(block.parent_root),
            payload_status=self.get_parent_payload_status(store, block),
        )

    def get_checkpoint_block(self, store, root, epoch: int):
        """[Modified in Gloas] unwraps the node (fork-choice.md:264-269)."""
        epoch_first_slot = self.compute_start_slot_at_epoch(int(epoch))
        return self.get_ancestor(store, root, epoch_first_slot).root

    def is_supporting_vote(self, store, node, message) -> bool:
        """(fork-choice.md:275-296)"""
        block = store.blocks[bytes(node.root)]
        if bytes(node.root) == bytes(message.root):
            if node.payload_status == self.PAYLOAD_STATUS_PENDING:
                return True
            if int(message.slot) <= int(block.slot):
                return False
            if message.payload_present:
                return node.payload_status == self.PAYLOAD_STATUS_FULL
            return node.payload_status == self.PAYLOAD_STATUS_EMPTY
        ancestor = self.get_ancestor(store, message.root, int(block.slot))
        return bytes(node.root) == bytes(ancestor.root) and (
            node.payload_status == self.PAYLOAD_STATUS_PENDING
            or node.payload_status == ancestor.payload_status
        )

    def should_extend_payload(self, store, root) -> bool:
        """(fork-choice.md:308-315)"""
        proposer_root = bytes(store.proposer_boost_root)
        return (
            self.is_payload_timely(store, root)
            or proposer_root == b"\x00" * 32
            or bytes(store.blocks[proposer_root].parent_root) != bytes(root)
            or self.is_parent_node_full(store, store.blocks[proposer_root])
        )

    def get_payload_status_tiebreaker(self, store, node) -> int:
        """(fork-choice.md:321-332)"""
        if (
            node.payload_status == self.PAYLOAD_STATUS_PENDING
            or int(store.blocks[bytes(node.root)].slot) + 1 != self.get_current_slot(store)
        ):
            return node.payload_status
        if node.payload_status == self.PAYLOAD_STATUS_EMPTY:
            return 1
        return 2 if self.should_extend_payload(store, node.root) else 0

    def get_proposer_score(self, store) -> int:
        state = store.checkpoint_states[store.justified_checkpoint]
        committee_weight = self.get_total_active_balance(state) // self.SLOTS_PER_EPOCH
        return (committee_weight * self.config.PROPOSER_SCORE_BOOST) // 100

    def get_weight(self, store, node) -> int:
        """[Modified in Gloas] weight of a (root, payload_status) node
        (fork-choice.md:338-380)."""
        if (
            node.payload_status == self.PAYLOAD_STATUS_PENDING
            or int(store.blocks[bytes(node.root)].slot) + 1 != self.get_current_slot(store)
        ):
            state = store.checkpoint_states[store.justified_checkpoint]
            unslashed_and_active_indices = [
                i
                for i in self.get_active_validator_indices(
                    state, self.get_current_epoch(state)
                )
                if not state.validators[i].slashed
            ]
            attestation_score = sum(
                int(state.validators[i].effective_balance)
                for i in unslashed_and_active_indices
                if (
                    i in store.latest_messages
                    and i not in store.equivocating_indices
                    and self.is_supporting_vote(store, node, store.latest_messages[i])
                )
            )
            if bytes(store.proposer_boost_root) == b"\x00" * 32:
                return attestation_score
            proposer_score = 0
            message = self.LatestMessage(
                slot=self.get_current_slot(store),
                root=bytes(store.proposer_boost_root),
                payload_present=False,
            )
            if self.is_supporting_vote(store, node, message):
                proposer_score = self.get_proposer_score(store)
            return attestation_score + proposer_score
        return 0

    def get_node_children(self, store, blocks, node):
        """(fork-choice.md:386-402)"""
        if node.payload_status == self.PAYLOAD_STATUS_PENDING:
            children = [
                self.ForkChoiceNode(
                    root=bytes(node.root), payload_status=self.PAYLOAD_STATUS_EMPTY
                )
            ]
            if bytes(node.root) in store.execution_payload_states:
                children.append(
                    self.ForkChoiceNode(
                        root=bytes(node.root), payload_status=self.PAYLOAD_STATUS_FULL
                    )
                )
            return children
        return [
            self.ForkChoiceNode(root=bytes(root), payload_status=self.PAYLOAD_STATUS_PENDING)
            for root in blocks.keys()
            if (
                bytes(blocks[root].parent_root) == bytes(node.root)
                and node.payload_status == self.get_parent_payload_status(store, blocks[root])
            )
        ]

    def get_head(self, store):
        """[Modified in Gloas] LMD-GHOST over (root, payload_status) nodes;
        returns the head ForkChoiceNode (fork-choice.md:411-433)."""
        blocks = self.get_filtered_block_tree(store)
        head = self.ForkChoiceNode(
            root=bytes(store.justified_checkpoint.root),
            payload_status=self.PAYLOAD_STATUS_PENDING,
        )
        while True:
            children = self.get_node_children(store, blocks, head)
            if len(children) == 0:
                return head
            head = max(
                children,
                key=lambda child: (
                    self.get_weight(store, child),
                    bytes(child.root),
                    self.get_payload_status_tiebreaker(store, child),
                ),
            )

    def get_head_root(self, store) -> bytes:
        return bytes(self.get_head(store).root)

    def validate_on_attestation(self, store, attestation, is_from_block: bool) -> None:
        """[Modified in Gloas] index encodes payload availability
        (fork-choice.md:634-672)."""
        target = attestation.data.target
        if not is_from_block:
            self.validate_target_epoch_against_current_time(store, attestation)
        assert target.epoch == self.compute_epoch_at_slot(attestation.data.slot)
        assert bytes(target.root) in store.blocks, "unknown target root"
        assert bytes(attestation.data.beacon_block_root) in store.blocks, "unknown head root"
        block_slot = int(store.blocks[bytes(attestation.data.beacon_block_root)].slot)
        assert block_slot <= int(attestation.data.slot), "attestation older than its block"
        # [New in Gloas:EIP7732]
        assert int(attestation.data.index) in (0, 1), "index must encode availability"
        if block_slot == int(attestation.data.slot):
            assert int(attestation.data.index) == 0, "same-slot attestation index must be 0"
        assert bytes(target.root) == bytes(
            self.get_checkpoint_block(store, attestation.data.beacon_block_root, target.epoch)
        ), "target does not match head chain"
        assert self.get_current_slot(store) >= int(attestation.data.slot) + 1, (
            "attestation too new"
        )

    def on_block(self, store, signed_block) -> None:
        """[Modified in Gloas] pre-state selection follows the parent's
        payload status; DA checking moves to the envelope
        (fork-choice.md:496-563)."""
        block = signed_block.message
        assert bytes(block.parent_root) in store.block_states, "unknown parent"

        parent_block = store.blocks[bytes(block.parent_root)]
        bid = block.body.signed_execution_payload_bid.message
        parent_bid = parent_block.body.signed_execution_payload_bid.message
        if self.is_parent_node_full(store, block):
            assert bytes(block.parent_root) in store.execution_payload_states, (
                "parent payload state missing"
            )
            state = store.execution_payload_states[bytes(block.parent_root)].copy()
        else:
            assert bytes(bid.parent_block_hash) == bytes(parent_bid.parent_block_hash), (
                "empty-parent bid must chain the grandparent hash"
            )
            state = store.block_states[bytes(block.parent_root)].copy()

        assert self.get_current_slot(store) >= block.slot, "block from the future"
        finalized_slot = self.compute_start_slot_at_epoch(store.finalized_checkpoint.epoch)
        assert block.slot > finalized_slot, "block not after finalized slot"
        assert bytes(
            self.get_checkpoint_block(store, block.parent_root, store.finalized_checkpoint.epoch)
        ) == bytes(store.finalized_checkpoint.root), "block does not descend from finalized root"

        block_root = bytes(hash_tree_root(block))
        self.state_transition(state, signed_block, True)

        store.blocks[block_root] = block.copy()
        store.block_states[block_root] = state
        # [New in Gloas:EIP7732]
        store.ptc_vote[block_root] = [False] * self.PTC_SIZE
        self.notify_ptc_messages(store, state, block.body.payload_attestations)

        is_timely = self.get_current_slot(
            store
        ) == block.slot and self.is_before_attesting_interval(store)
        store.block_timeliness[block_root] = is_timely
        if is_timely and bytes(store.proposer_boost_root) == b"\x00" * 32:
            store.proposer_boost_root = block_root

        self.update_checkpoints(
            store, state.current_justified_checkpoint, state.finalized_checkpoint
        )
        self.compute_pulled_up_tip(store, block_root)

    def on_execution_payload(self, store, signed_envelope) -> None:
        """Import a builder envelope into the store (fork-choice.md:567-592)."""
        envelope = signed_envelope.message
        root = bytes(envelope.beacon_block_root)
        assert root in store.block_states, "unknown beacon block"
        # [Modified in Fulu:EIP7594] column-sampled availability
        assert self.is_data_available(root), "column data not available"
        state = store.block_states[root].copy()
        self.process_execution_payload(state, signed_envelope, self.EXECUTION_ENGINE)
        store.execution_payload_states[root] = state

    def on_payload_attestation_message(
        self, store, ptc_message, is_from_block: bool = False
    ) -> None:
        """(fork-choice.md:595-631)"""
        data = ptc_message.data
        state = store.block_states[bytes(data.beacon_block_root)]
        ptc = self.get_ptc(state, int(data.slot))
        if int(data.slot) != int(state.slot):
            return
        assert int(ptc_message.validator_index) in ptc, "attester not in PTC"
        if not is_from_block:
            assert int(data.slot) == self.get_current_slot(store), "PTC message not current"
            assert self.is_valid_indexed_payload_attestation(
                state,
                self.IndexedPayloadAttestation(
                    attesting_indices=[ptc_message.validator_index],
                    data=data,
                    signature=ptc_message.signature,
                ),
            ), "invalid PTC message signature"
        ptc_index = ptc.index(int(ptc_message.validator_index))
        store.ptc_vote[bytes(data.beacon_block_root)][ptc_index] = bool(data.payload_present)

    # == fork upgrade (specs/gloas/fork.md:34-110) =========================

    def upgrade_from_parent(self, pre):
        epoch = self.compute_epoch_at_slot(int(pre.slot))
        post = self.BeaconState(
            genesis_time=pre.genesis_time,
            genesis_validators_root=pre.genesis_validators_root,
            slot=pre.slot,
            fork=self.Fork(
                previous_version=pre.fork.current_version,
                current_version=Version(self.config.GLOAS_FORK_VERSION),
                epoch=epoch,
            ),
            latest_block_header=pre.latest_block_header,
            block_roots=list(pre.block_roots),
            state_roots=list(pre.state_roots),
            historical_roots=list(pre.historical_roots),
            eth1_data=pre.eth1_data,
            eth1_data_votes=list(pre.eth1_data_votes),
            eth1_deposit_index=pre.eth1_deposit_index,
            validators=list(pre.validators),
            balances=list(pre.balances),
            randao_mixes=list(pre.randao_mixes),
            slashings=list(pre.slashings),
            previous_epoch_participation=list(pre.previous_epoch_participation),
            current_epoch_participation=list(pre.current_epoch_participation),
            justification_bits=list(pre.justification_bits),
            previous_justified_checkpoint=pre.previous_justified_checkpoint,
            current_justified_checkpoint=pre.current_justified_checkpoint,
            finalized_checkpoint=pre.finalized_checkpoint,
            inactivity_scores=list(pre.inactivity_scores),
            current_sync_committee=pre.current_sync_committee,
            next_sync_committee=pre.next_sync_committee,
            # [New in Gloas:EIP7732]
            latest_execution_payload_bid=self.ExecutionPayloadBid(
                block_hash=pre.latest_execution_payload_header.block_hash,
            ),
            next_withdrawal_index=pre.next_withdrawal_index,
            next_withdrawal_validator_index=pre.next_withdrawal_validator_index,
            historical_summaries=list(pre.historical_summaries),
            deposit_requests_start_index=pre.deposit_requests_start_index,
            deposit_balance_to_consume=pre.deposit_balance_to_consume,
            exit_balance_to_consume=pre.exit_balance_to_consume,
            earliest_exit_epoch=pre.earliest_exit_epoch,
            consolidation_balance_to_consume=pre.consolidation_balance_to_consume,
            earliest_consolidation_epoch=pre.earliest_consolidation_epoch,
            pending_deposits=list(pre.pending_deposits),
            pending_partial_withdrawals=list(pre.pending_partial_withdrawals),
            pending_consolidations=list(pre.pending_consolidations),
            proposer_lookahead=list(pre.proposer_lookahead),
            # [New in Gloas:EIP7732]
            execution_payload_availability=[1] * self.SLOTS_PER_HISTORICAL_ROOT,
            builder_pending_payments=[
                self.BuilderPendingPayment() for _ in range(2 * self.SLOTS_PER_EPOCH)
            ],
            builder_pending_withdrawals=[],
            latest_block_hash=pre.latest_execution_payload_header.block_hash,
            latest_withdrawals_root=Root(),
        )
        return post
