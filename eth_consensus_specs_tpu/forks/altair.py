"""altair: sync committees, participation-flag incentive accounting,
inactivity scores, and the first hard-fork upgrade path.

Behavioral parity targets (reference, by section):
  * state machine:  specs/altair/beacon-chain.md (process_sync_aggregate
    :575, modified process_attestation :509, flag deltas :398,
    inactivity updates :687, sync committee updates :771)
  * BLS extensions: specs/altair/bls.md (eth_aggregate_pubkeys :36,
    eth_fast_aggregate_verify :58)
  * fork upgrade:   specs/altair/fork.md (upgrade_to_altair,
    translate_participation)

Architecture notes:
  * Participation is a columnar uint8 flag vector per epoch — ALREADY the
    TPU layout: the altair epoch kernel (flag deltas, inactivity) consumes
    it directly with no committee re-resolution, unlike phase0 where
    pending attestations must be re-reduced to masks each epoch.
  * The sync-aggregate fast path keeps the spec's subtract-non-participants
    trick (majority case: one aggregate key minus the absentees) — the
    G1-sum shape that ops/bls_batch batches.
"""

from eth_consensus_specs_tpu.ssz import (
    Bitvector,
    Bytes32,
    Container,
    List,
    Vector,
    hash_tree_root,
    uint8,
    uint64,
)
from eth_consensus_specs_tpu.utils import bls

from .phase0 import (
    BLSPubkey,
    BLSSignature,
    Domain,
    DomainType,
    Epoch,
    Gwei,
    Phase0Spec,
    Root,
    Slot,
    ValidatorIndex,
    Version,
)

ParticipationFlags = uint8


from .light_client import LightClientMixin


class AltairSpec(LightClientMixin, Phase0Spec):
    fork_name = "altair"

    # -- participation flag indices (beacon-chain.md constants) ------------
    TIMELY_SOURCE_FLAG_INDEX = 0
    TIMELY_TARGET_FLAG_INDEX = 1
    TIMELY_HEAD_FLAG_INDEX = 2

    # -- incentivization weights -------------------------------------------
    TIMELY_SOURCE_WEIGHT = 14
    TIMELY_TARGET_WEIGHT = 26
    TIMELY_HEAD_WEIGHT = 14
    SYNC_REWARD_WEIGHT = 2
    PROPOSER_WEIGHT = 8
    WEIGHT_DENOMINATOR = 64

    DOMAIN_SYNC_COMMITTEE = DomainType(b"\x07\x00\x00\x00")
    DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF = DomainType(b"\x08\x00\x00\x00")
    DOMAIN_CONTRIBUTION_AND_PROOF = DomainType(b"\x09\x00\x00\x00")

    G2_POINT_AT_INFINITY = bls.G2_POINT_AT_INFINITY

    # honest-validator constants (specs/altair/validator.md)
    TARGET_AGGREGATORS_PER_SYNC_SUBCOMMITTEE = 16
    SYNC_COMMITTEE_SUBNET_COUNT = 4

    @property
    def PARTICIPATION_FLAG_WEIGHTS(self):
        return [self.TIMELY_SOURCE_WEIGHT, self.TIMELY_TARGET_WEIGHT, self.TIMELY_HEAD_WEIGHT]

    # == networking helpers ================================================

    def compute_sync_committee_period(self, epoch: int) -> int:
        return int(epoch) // self.EPOCHS_PER_SYNC_COMMITTEE_PERIOD

    def compute_subnets_for_sync_committee(self, state, validator_index: int) -> set:
        """Sync-committee gossip subnets for a validator (reference:
        specs/altair/validator.md:378-397)."""
        next_slot_epoch = self.compute_epoch_at_slot(int(state.slot) + 1)
        if self.compute_sync_committee_period(
            self.get_current_epoch(state)
        ) == self.compute_sync_committee_period(next_slot_epoch):
            sync_committee = state.current_sync_committee
        else:
            sync_committee = state.next_sync_committee
        target_pubkey = state.validators[validator_index].pubkey
        sync_committee_indices = [
            index
            for index, pubkey in enumerate(sync_committee.pubkeys)
            if pubkey == target_pubkey
        ]
        return {
            index // (self.SYNC_COMMITTEE_SIZE // self.SYNC_COMMITTEE_SUBNET_COUNT)
            for index in sync_committee_indices
        }

    # == sync-committee duties (specs/altair/validator.md:347-560) =========

    def get_sync_committee_message(
        self, state, block_root, validator_index: int, privkey: int
    ):
        """specs/altair/validator.md:347-361."""
        epoch = self.get_current_epoch(state)
        domain = self.get_domain(state, self.DOMAIN_SYNC_COMMITTEE, epoch)
        signing_root = self.compute_signing_root(Root(block_root), domain)
        return self.SyncCommitteeMessage(
            slot=state.slot,
            beacon_block_root=block_root,
            validator_index=validator_index,
            signature=bls.Sign(privkey, signing_root),
        )

    def get_sync_committee_selection_proof(
        self, state, slot: int, subcommittee_index: int, privkey: int
    ):
        """specs/altair/validator.md:425-435."""
        domain = self.get_domain(
            state,
            self.DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF,
            self.compute_epoch_at_slot(slot),
        )
        signing_data = self.SyncAggregatorSelectionData(
            slot=slot, subcommittee_index=subcommittee_index
        )
        return bls.Sign(privkey, self.compute_signing_root(signing_data, domain))

    def is_sync_committee_aggregator(self, signature) -> bool:
        """specs/altair/validator.md:438-446."""
        modulo = max(
            1,
            self.SYNC_COMMITTEE_SIZE
            // self.SYNC_COMMITTEE_SUBNET_COUNT
            // self.TARGET_AGGREGATORS_PER_SYNC_SUBCOMMITTEE,
        )
        return self.bytes_to_uint64(self.hash(bytes(signature))[0:8]) % modulo == 0

    def get_contribution_and_proof(
        self, state, aggregator_index: int, contribution, privkey: int
    ):
        """specs/altair/validator.md:528-545."""
        selection_proof = self.get_sync_committee_selection_proof(
            state,
            contribution.slot,
            contribution.subcommittee_index,
            privkey,
        )
        return self.ContributionAndProof(
            aggregator_index=aggregator_index,
            contribution=contribution,
            selection_proof=selection_proof,
        )

    def get_contribution_and_proof_signature(
        self, state, contribution_and_proof, privkey: int
    ):
        """specs/altair/validator.md:551-560."""
        contribution = contribution_and_proof.contribution
        domain = self.get_domain(
            state,
            self.DOMAIN_CONTRIBUTION_AND_PROOF,
            self.compute_epoch_at_slot(contribution.slot),
        )
        return bls.Sign(
            privkey, self.compute_signing_root(contribution_and_proof, domain)
        )

    def process_sync_committee_contributions(self, block, contributions) -> None:
        """Fold per-subnet contributions into the block's SyncAggregate
        (specs/altair/validator.md:271-289)."""
        sync_aggregate = self.SyncAggregate()
        signatures = []
        sync_subcommittee_size = (
            self.SYNC_COMMITTEE_SIZE // self.SYNC_COMMITTEE_SUBNET_COUNT
        )
        for contribution in contributions:
            subcommittee_index = int(contribution.subcommittee_index)
            for index, participated in enumerate(contribution.aggregation_bits):
                if participated:
                    participant_index = (
                        sync_subcommittee_size * subcommittee_index + index
                    )
                    sync_aggregate.sync_committee_bits[participant_index] = True
            signatures.append(contribution.signature)
        sync_aggregate.sync_committee_signature = bls.Aggregate(signatures)
        block.body.sync_aggregate = sync_aggregate

    # == type system ======================================================

    def _build_types(self) -> None:
        super()._build_types()
        P = self

        class SyncAggregate(Container):
            sync_committee_bits: Bitvector[P.SYNC_COMMITTEE_SIZE]
            sync_committee_signature: BLSSignature

        class SyncCommittee(Container):
            pubkeys: Vector[BLSPubkey, P.SYNC_COMMITTEE_SIZE]
            aggregate_pubkey: BLSPubkey

        class BeaconBlockBody(Container):
            randao_reveal: BLSSignature
            eth1_data: P.Eth1Data
            graffiti: Bytes32
            proposer_slashings: List[P.ProposerSlashing, P.MAX_PROPOSER_SLASHINGS]
            attester_slashings: List[P.AttesterSlashing, P.MAX_ATTESTER_SLASHINGS]
            attestations: List[P.Attestation, P.MAX_ATTESTATIONS]
            deposits: List[P.Deposit, P.MAX_DEPOSITS]
            voluntary_exits: List[P.SignedVoluntaryExit, P.MAX_VOLUNTARY_EXITS]
            sync_aggregate: SyncAggregate  # [New in Altair]

        class BeaconBlock(Container):
            slot: Slot
            proposer_index: ValidatorIndex
            parent_root: Root
            state_root: Root
            body: BeaconBlockBody

        class SignedBeaconBlock(Container):
            message: BeaconBlock
            signature: BLSSignature

        class BeaconState(Container):
            genesis_time: uint64
            genesis_validators_root: Root
            slot: Slot
            fork: P.Fork
            latest_block_header: P.BeaconBlockHeader
            block_roots: Vector[Root, P.SLOTS_PER_HISTORICAL_ROOT]
            state_roots: Vector[Root, P.SLOTS_PER_HISTORICAL_ROOT]
            historical_roots: List[Root, P.HISTORICAL_ROOTS_LIMIT]
            eth1_data: P.Eth1Data
            eth1_data_votes: List[P.Eth1Data, P.EPOCHS_PER_ETH1_VOTING_PERIOD * P.SLOTS_PER_EPOCH]
            eth1_deposit_index: uint64
            validators: List[P.Validator, P.VALIDATOR_REGISTRY_LIMIT]
            balances: List[Gwei, P.VALIDATOR_REGISTRY_LIMIT]
            randao_mixes: Vector[Bytes32, P.EPOCHS_PER_HISTORICAL_VECTOR]
            slashings: Vector[Gwei, P.EPOCHS_PER_SLASHINGS_VECTOR]
            previous_epoch_participation: List[ParticipationFlags, P.VALIDATOR_REGISTRY_LIMIT]
            current_epoch_participation: List[ParticipationFlags, P.VALIDATOR_REGISTRY_LIMIT]
            justification_bits: Bitvector[self.JUSTIFICATION_BITS_LENGTH]
            previous_justified_checkpoint: P.Checkpoint
            current_justified_checkpoint: P.Checkpoint
            finalized_checkpoint: P.Checkpoint
            inactivity_scores: List[uint64, P.VALIDATOR_REGISTRY_LIMIT]
            current_sync_committee: SyncCommittee
            next_sync_committee: SyncCommittee

        # honest-validator containers (specs/altair/validator.md)
        class SyncCommitteeMessage(Container):
            slot: Slot
            beacon_block_root: Root
            validator_index: ValidatorIndex
            signature: BLSSignature

        class SyncCommitteeContribution(Container):
            slot: Slot
            beacon_block_root: Root
            subcommittee_index: uint64
            aggregation_bits: Bitvector[P.SYNC_COMMITTEE_SIZE // 4]
            signature: BLSSignature

        class ContributionAndProof(Container):
            aggregator_index: ValidatorIndex
            contribution: SyncCommitteeContribution
            selection_proof: BLSSignature

        class SignedContributionAndProof(Container):
            message: ContributionAndProof
            signature: BLSSignature

        class SyncAggregatorSelectionData(Container):
            slot: Slot
            subcommittee_index: uint64

        for name, typ in list(locals().items()):
            if isinstance(typ, type) and issubclass(typ, Container):
                typ.__name__ = name
                setattr(self, name, typ)

    # == BLS extensions (specs/altair/bls.md) ==============================

    def eth_aggregate_pubkeys(self, pubkeys) -> bytes:
        """Elliptic-curve sum of pubkeys — ALWAYS real group math, on both
        sides of the parity seam.  The aggregate lands in state as
        SyncCommittee.aggregate_pubkey, and upstream's PUBLISHED vectors
        (generated with bls on) carry the real sum, so state bytes must
        not depend on the bls_active test switch; the specc preamble
        unconditionally binds the compiled reference's AggregatePKs to
        the same ungated sum (the round-5 conformance byte-diff caught
        the two sides disagreeing on an 8-epoch electra chain)."""
        assert len(pubkeys) > 0
        from eth_consensus_specs_tpu.crypto.curve import g1_from_bytes, g1_to_bytes

        acc = None
        for pk in pubkeys:
            p = g1_from_bytes(bytes(pk))  # raises on invalid encodings
            if p.is_infinity():
                raise AssertionError("identity pubkey is not a valid key")
            acc = p if acc is None else acc + p
        return BLSPubkey(g1_to_bytes(acc))

    def eth_fast_aggregate_verify(self, pubkeys, message, signature) -> bool:
        if len(pubkeys) == 0 and bytes(signature) == self.G2_POINT_AT_INFINITY:
            return True
        return bls.FastAggregateVerify(pubkeys, message, signature)

    # == misc helpers ======================================================

    # -- validator timing (specs/altair/fork-choice.md:21-32) --------------

    def get_sync_message_due_ms(self, epoch: int) -> int:
        return self.get_slot_component_duration_ms(self.config.SYNC_MESSAGE_DUE_BPS)

    def get_contribution_due_ms(self, epoch: int) -> int:
        return self.get_slot_component_duration_ms(self.config.CONTRIBUTION_DUE_BPS)

    @staticmethod
    def add_flag(flags: int, flag_index: int) -> int:
        return int(flags) | (1 << flag_index)

    @staticmethod
    def has_flag(flags: int, flag_index: int) -> bool:
        flag = 1 << flag_index
        return int(flags) & flag == flag

    def get_index_for_new_validator(self, state) -> int:
        return len(state.validators)

    @staticmethod
    def set_or_append_list(lst, index: int, value) -> None:
        if index == len(lst):
            lst.append(value)
        else:
            lst[index] = value

    def add_validator_to_registry(self, state, pubkey, withdrawal_credentials, amount) -> None:
        index = self.get_index_for_new_validator(state)
        validator = self.get_validator_from_deposit(pubkey, withdrawal_credentials, amount)
        self.set_or_append_list(state.validators, index, validator)
        self.set_or_append_list(state.balances, index, amount)
        self.set_or_append_list(state.previous_epoch_participation, index, 0)
        self.set_or_append_list(state.current_epoch_participation, index, 0)
        self.set_or_append_list(state.inactivity_scores, index, 0)

    # == sync committee accessors ==========================================

    def get_next_sync_committee_indices(self, state):
        """Sync committee sampling (with duplicates): shuffled candidate
        stream filtered by the effective-balance acceptance test
        (reference: specs/altair/beacon-chain.md:265-291)."""
        epoch = self.get_current_epoch(state) + 1
        MAX_RANDOM_BYTE = 2**8 - 1
        active = self.get_active_validator_indices(state, epoch)
        n = len(active)
        seed = self.get_seed(state, epoch, self.DOMAIN_SYNC_COMMITTEE)
        perm = self._shuffle_permutation(n, seed)
        out = []
        i = 0
        while len(out) < self.SYNC_COMMITTEE_SIZE:
            candidate = active[int(perm[i % n])]
            random_byte = self.hash(seed + self.uint_to_bytes(uint64(i // 32)))[i % 32]
            effective_balance = int(state.validators[candidate].effective_balance)
            if effective_balance * MAX_RANDOM_BYTE >= self.MAX_EFFECTIVE_BALANCE * random_byte:
                out.append(candidate)
            i += 1
        return out

    def get_next_sync_committee(self, state):
        indices = self.get_next_sync_committee_indices(state)
        pubkeys = [state.validators[index].pubkey for index in indices]
        aggregate_pubkey = self.eth_aggregate_pubkeys(pubkeys)
        return self.SyncCommittee(pubkeys=pubkeys, aggregate_pubkey=aggregate_pubkey)

    # == incentive accounting ==============================================

    def get_base_reward_per_increment(self, state) -> int:
        return (
            self.EFFECTIVE_BALANCE_INCREMENT
            * self.BASE_REWARD_FACTOR
            // self.integer_squareroot(self.get_total_active_balance(state))
        )

    def get_base_reward(self, state, index: int) -> int:
        increments = (
            int(state.validators[int(index)].effective_balance)
            // self.EFFECTIVE_BALANCE_INCREMENT
        )
        return increments * self.get_base_reward_per_increment(state)

    def get_unslashed_participating_indices(self, state, flag_index: int, epoch: int):
        assert epoch in (self.get_previous_epoch(state), self.get_current_epoch(state))
        if epoch == self.get_current_epoch(state):
            epoch_participation = state.current_epoch_participation
        else:
            epoch_participation = state.previous_epoch_participation
        return {
            i
            for i in self.get_active_validator_indices(state, epoch)
            if self.has_flag(epoch_participation[i], flag_index)
            and not state.validators[i].slashed
        }

    def get_attestation_participation_flag_indices(self, state, data, inclusion_delay: int):
        if data.target.epoch == self.get_current_epoch(state):
            justified_checkpoint = state.current_justified_checkpoint
        else:
            justified_checkpoint = state.previous_justified_checkpoint
        is_matching_source = data.source == justified_checkpoint
        is_matching_target = (
            is_matching_source and data.target.root == self.get_block_root(state, data.target.epoch)
        )
        is_matching_head = (
            is_matching_target
            and data.beacon_block_root == self.get_block_root_at_slot(state, data.slot)
        )
        assert is_matching_source, "attestation source does not match justified checkpoint"

        participation_flag_indices = []
        if is_matching_source and inclusion_delay <= self.integer_squareroot(self.SLOTS_PER_EPOCH):
            participation_flag_indices.append(self.TIMELY_SOURCE_FLAG_INDEX)
        if is_matching_target and inclusion_delay <= self.SLOTS_PER_EPOCH:
            participation_flag_indices.append(self.TIMELY_TARGET_FLAG_INDEX)
        if is_matching_head and inclusion_delay == self.MIN_ATTESTATION_INCLUSION_DELAY:
            participation_flag_indices.append(self.TIMELY_HEAD_FLAG_INDEX)
        return participation_flag_indices

    def get_flag_index_deltas(self, state, flag_index: int):
        rewards = [0] * len(state.validators)
        penalties = [0] * len(state.validators)
        previous_epoch = self.get_previous_epoch(state)
        unslashed_participating_indices = self.get_unslashed_participating_indices(
            state, flag_index, previous_epoch
        )
        weight = self.PARTICIPATION_FLAG_WEIGHTS[flag_index]
        unslashed_participating_balance = self.get_total_balance(
            state, unslashed_participating_indices
        )
        unslashed_participating_increments = (
            unslashed_participating_balance // self.EFFECTIVE_BALANCE_INCREMENT
        )
        active_increments = (
            self.get_total_active_balance(state) // self.EFFECTIVE_BALANCE_INCREMENT
        )
        for index in self.get_eligible_validator_indices(state):
            base_reward = self.get_base_reward(state, index)
            if index in unslashed_participating_indices:
                if not self.is_in_inactivity_leak(state):
                    reward_numerator = base_reward * weight * unslashed_participating_increments
                    rewards[index] += reward_numerator // (
                        active_increments * self.WEIGHT_DENOMINATOR
                    )
            elif flag_index != self.TIMELY_HEAD_FLAG_INDEX:
                penalties[index] += base_reward * weight // self.WEIGHT_DENOMINATOR
        return rewards, penalties

    def get_inactivity_penalty_deltas(self, state):
        rewards = [0] * len(state.validators)
        penalties = [0] * len(state.validators)
        previous_epoch = self.get_previous_epoch(state)
        matching_target_indices = self.get_unslashed_participating_indices(
            state, self.TIMELY_TARGET_FLAG_INDEX, previous_epoch
        )
        for index in self.get_eligible_validator_indices(state):
            if index not in matching_target_indices:
                penalty_numerator = int(state.validators[index].effective_balance) * int(
                    state.inactivity_scores[index]
                )
                penalty_denominator = (
                    self.config.INACTIVITY_SCORE_BIAS * self.inactivity_penalty_quotient()
                )
                penalties[index] += penalty_numerator // penalty_denominator
        return rewards, penalties

    # == mutators ==========================================================
    # slash_validator itself is inherited; altair only re-points its knobs
    # (reference: specs/altair/beacon-chain.md:455-488)

    def inactivity_penalty_quotient(self) -> int:
        return self.INACTIVITY_PENALTY_QUOTIENT_ALTAIR

    def min_slashing_penalty_quotient(self) -> int:
        return self.MIN_SLASHING_PENALTY_QUOTIENT_ALTAIR

    def proportional_slashing_multiplier(self) -> int:
        return self.PROPORTIONAL_SLASHING_MULTIPLIER_ALTAIR

    def whistleblower_proposer_reward(self, whistleblower_reward: int) -> int:
        return whistleblower_reward * self.PROPOSER_WEIGHT // self.WEIGHT_DENOMINATOR

    # == block processing ==================================================

    def process_block(self, state, block) -> None:
        self.process_block_header(state, block)
        self.process_randao(state, block.body)
        self.process_eth1_data(state, block.body)
        self.process_operations(state, block.body)
        self.process_sync_aggregate(state, block.body.sync_aggregate)

    def process_attestation(self, state, attestation) -> None:
        data = attestation.data
        assert data.target.epoch in (
            self.get_previous_epoch(state),
            self.get_current_epoch(state),
        ), "target epoch out of range"
        assert data.target.epoch == self.compute_epoch_at_slot(data.slot), "target/slot mismatch"
        assert (
            int(data.slot) + self.MIN_ATTESTATION_INCLUSION_DELAY
            <= state.slot
            <= int(data.slot) + self.SLOTS_PER_EPOCH
        ), "attestation outside inclusion window"
        assert data.index < self.get_committee_count_per_slot(state, data.target.epoch)

        committee = self.get_beacon_committee(state, data.slot, data.index)
        assert len(attestation.aggregation_bits) == len(committee), "bitlist length mismatch"

        participation_flag_indices = self.get_attestation_participation_flag_indices(
            state, data, int(state.slot) - int(data.slot)
        )

        assert self.is_valid_indexed_attestation(
            state, self.get_indexed_attestation(state, attestation)
        ), "invalid aggregate signature"

        if data.target.epoch == self.get_current_epoch(state):
            epoch_participation = state.current_epoch_participation
        else:
            epoch_participation = state.previous_epoch_participation

        proposer_reward_numerator = 0
        for index in self.get_attesting_indices(state, attestation):
            for flag_index, weight in enumerate(self.PARTICIPATION_FLAG_WEIGHTS):
                if flag_index in participation_flag_indices and not self.has_flag(
                    epoch_participation[index], flag_index
                ):
                    epoch_participation[index] = self.add_flag(
                        epoch_participation[index], flag_index
                    )
                    proposer_reward_numerator += self.get_base_reward(state, index) * weight

        proposer_reward_denominator = (
            (self.WEIGHT_DENOMINATOR - self.PROPOSER_WEIGHT)
            * self.WEIGHT_DENOMINATOR
            // self.PROPOSER_WEIGHT
        )
        proposer_reward = proposer_reward_numerator // proposer_reward_denominator
        self.increase_balance(state, self.get_beacon_proposer_index(state), proposer_reward)

    def process_sync_aggregate(self, state, sync_aggregate) -> None:
        """Verify + reward the per-slot sync committee vote (reference:
        specs/altair/beacon-chain.md:575-650). The majority fast path keeps
        one G1 subtraction instead of up to SYNC_COMMITTEE_SIZE additions."""
        committee_pubkeys = state.current_sync_committee.pubkeys
        committee_bits = list(sync_aggregate.sync_committee_bits)
        # participant collection + signing-root derivation always execute
        # (reference structure: only the signature check sits behind the
        # bls switch); the EC work lives inside the gated verify below
        participant_pubkeys = [
            pk for pk, bit in zip(committee_pubkeys, committee_bits) if bit
        ]
        previous_slot = max(int(state.slot), 1) - 1
        domain = self.get_domain(
            state, self.DOMAIN_SYNC_COMMITTEE, self.compute_epoch_at_slot(previous_slot)
        )
        signing_root = self.compute_signing_root(
            Root(self.get_block_root_at_slot(state, previous_slot)), domain
        )
        if bls.bls_active:
            participating = len(participant_pubkeys)
            if participating == self.SYNC_COMMITTEE_SIZE:
                verify_keys = [state.current_sync_committee.aggregate_pubkey]
            elif participating > self.SYNC_COMMITTEE_SIZE // 2:
                # majority fast path: one G1 subtraction instead of up to
                # SYNC_COMMITTEE_SIZE additions
                non_participant_pubkeys = [
                    pk for pk, bit in zip(committee_pubkeys, committee_bits) if not bit
                ]
                non_participant_aggregate = self.eth_aggregate_pubkeys(non_participant_pubkeys)
                participant_point = bls.add(
                    bls.pubkey_to_G1(state.current_sync_committee.aggregate_pubkey),
                    bls.neg(bls.pubkey_to_G1(non_participant_aggregate)),
                )
                verify_keys = [BLSPubkey(bls.G1_to_pubkey(participant_point))]
            else:
                verify_keys = participant_pubkeys
            assert self.eth_fast_aggregate_verify(
                verify_keys, signing_root, sync_aggregate.sync_committee_signature
            ), "invalid sync committee signature"

        total_active_increments = (
            self.get_total_active_balance(state) // self.EFFECTIVE_BALANCE_INCREMENT
        )
        total_base_rewards = self.get_base_reward_per_increment(state) * total_active_increments
        max_participant_rewards = (
            total_base_rewards * self.SYNC_REWARD_WEIGHT
            // self.WEIGHT_DENOMINATOR
            // self.SLOTS_PER_EPOCH
        )
        participant_reward = max_participant_rewards // self.SYNC_COMMITTEE_SIZE
        proposer_reward = (
            participant_reward * self.PROPOSER_WEIGHT
            // (self.WEIGHT_DENOMINATOR - self.PROPOSER_WEIGHT)
        )

        all_pubkeys = [v.pubkey for v in state.validators]
        committee_indices = [
            all_pubkeys.index(pubkey) for pubkey in state.current_sync_committee.pubkeys
        ]
        proposer_index = self.get_beacon_proposer_index(state)
        for participant_index, participation_bit in zip(committee_indices, committee_bits):
            if participation_bit:
                self.increase_balance(state, participant_index, participant_reward)
                self.increase_balance(state, proposer_index, proposer_reward)
            else:
                self.decrease_balance(state, participant_index, participant_reward)

    # == epoch processing ==================================================

    def process_epoch(self, state) -> None:
        """DEFAULT spec path: the fused columnar epoch (device when an
        accelerator is attached).  The per-validator object pipeline stays
        available as process_epoch_object — it is the oracle the columnar
        tests compare against — and takes over when
        ETH_SPECS_TPU_OBJECT_EPOCH=1."""
        import os

        if os.environ.get("ETH_SPECS_TPU_OBJECT_EPOCH") == "1":
            self.process_epoch_object(state)
        else:
            self.process_epoch_columnar(state)

    def process_epoch_object(self, state) -> None:
        self.process_justification_and_finalization(state)
        self.process_inactivity_updates(state)
        self.process_rewards_and_penalties(state)
        self.process_registry_updates(state)
        self.process_slashings(state)
        self.process_eth1_data_reset(state)
        self.process_effective_balance_updates(state)
        self._process_epoch_resets(state)

    def extract_epoch_columns(self, state):
        """Flatten the object state into the flag-based columnar arrays for
        ops/altair_epoch. Participation is already columnar in altair+
        (uint8 flag lists), so no committee resolution is needed — the
        extraction is a plain O(N) copy. Returns
        (AltairEpochColumns, JustificationState)."""
        import numpy as np

        from eth_consensus_specs_tpu.ops.altair_epoch import AltairEpochColumns

        eff, bal, slashed, act, exitep, wd = self._registry_columns(state)
        n = len(state.validators)
        prev_flags = np.fromiter(
            (int(f) for f in state.previous_epoch_participation), np.uint8, n
        )
        cur_flags = np.fromiter(
            (int(f) for f in state.current_epoch_participation), np.uint8, n
        )
        cur_tgt = ((cur_flags >> self.TIMELY_TARGET_FLAG_INDEX) & 1).astype(bool)
        scores = np.fromiter((int(s) for s in state.inactivity_scores), np.uint64, n)

        cols = AltairEpochColumns(
            effective_balance=eff,
            balance=bal,
            slashed=slashed,
            activation_epoch=act,
            exit_epoch=exitep,
            withdrawable_epoch=wd,
            prev_flags=prev_flags,
            cur_tgt_att=cur_tgt,
            inactivity_scores=scores,
        )
        return cols, self._justification_state(state)

    def _writeback_extra(self, state, res) -> None:
        new_scores = res.inactivity_scores
        for i in range(len(new_scores)):
            ns = int(new_scores[i])
            if int(state.inactivity_scores[i]) != ns:
                state.inactivity_scores[i] = ns

    def process_epoch_columnar(self, state) -> None:
        """Bit-exact process_epoch with the flag-based accounting epoch
        fused on device (ops/altair_epoch.py). Registry updates + resets
        stay host-side; the hoisting argument is in the kernel docstring.
        Sync-committee resampling inside the resets reads the POST-update
        effective balances — the shared writeback keeps that ordering."""
        import jax
        import numpy as np

        from eth_consensus_specs_tpu.ops.altair_epoch import (
            AltairEpochParams,
            altair_epoch_accounting,
        )

        cols, just = self.extract_epoch_columns(state)
        res = altair_epoch_accounting(AltairEpochParams.from_spec(self), cols, just)
        res = jax.tree_util.tree_map(np.asarray, res)  # one device->host sync
        self._writeback_accounting(state, res)

    def process_justification_and_finalization(self, state) -> None:
        if self.get_current_epoch(state) <= self.GENESIS_EPOCH + 1:
            return
        previous_indices = self.get_unslashed_participating_indices(
            state, self.TIMELY_TARGET_FLAG_INDEX, self.get_previous_epoch(state)
        )
        current_indices = self.get_unslashed_participating_indices(
            state, self.TIMELY_TARGET_FLAG_INDEX, self.get_current_epoch(state)
        )
        total_active_balance = self.get_total_active_balance(state)
        previous_target_balance = self.get_total_balance(state, previous_indices)
        current_target_balance = self.get_total_balance(state, current_indices)
        self.weigh_justification_and_finalization(
            state, total_active_balance, previous_target_balance, current_target_balance
        )

    def process_inactivity_updates(self, state) -> None:
        if self.get_current_epoch(state) == self.GENESIS_EPOCH:
            return
        participating = self.get_unslashed_participating_indices(
            state, self.TIMELY_TARGET_FLAG_INDEX, self.get_previous_epoch(state)
        )
        leak_free = not self.is_in_inactivity_leak(state)
        for index in self.get_eligible_validator_indices(state):
            score = int(state.inactivity_scores[index])
            if index in participating:
                score -= min(1, score)
            else:
                score += self.config.INACTIVITY_SCORE_BIAS
            if leak_free:
                score -= min(self.config.INACTIVITY_SCORE_RECOVERY_RATE, score)
            state.inactivity_scores[index] = score

    def process_rewards_and_penalties(self, state) -> None:
        if self.get_current_epoch(state) == self.GENESIS_EPOCH:
            return
        flag_deltas = [
            self.get_flag_index_deltas(state, flag_index)
            for flag_index in range(len(self.PARTICIPATION_FLAG_WEIGHTS))
        ]
        deltas = flag_deltas + [self.get_inactivity_penalty_deltas(state)]
        for rewards, penalties in deltas:
            for index in range(len(state.validators)):
                self.increase_balance(state, index, rewards[index])
                self.decrease_balance(state, index, penalties[index])

    # process_slashings is inherited: the proportional_slashing_multiplier()
    # knob above is altair's entire modification

    def process_participation_flag_updates(self, state) -> None:
        state.previous_epoch_participation = state.current_epoch_participation
        state.current_epoch_participation = self.BeaconState.fields()[
            "current_epoch_participation"
        ]([0] * len(state.validators))

    def process_sync_committee_updates(self, state) -> None:
        next_epoch = self.get_current_epoch(state) + 1
        if next_epoch % self.EPOCHS_PER_SYNC_COMMITTEE_PERIOD == 0:
            state.current_sync_committee = state.next_sync_committee
            state.next_sync_committee = self.get_next_sync_committee(state)

    # phase0's pending-attestation resets do not exist here
    def process_participation_record_updates(self, state) -> None:  # pragma: no cover
        raise NotImplementedError("phase0-only; altair uses participation flags")

    def _process_epoch_resets(self, state) -> None:
        # altair re-sequences the tail (participation flags + sync committee
        # replace phase0's pending-attestation reset); keep the shared-name
        # hook coherent for anything driving the pipeline generically
        self.process_slashings_reset(state)
        self.process_randao_mixes_reset(state)
        self.process_historical_roots_update(state)
        self.process_participation_flag_updates(state)
        self.process_sync_committee_updates(state)

    # == genesis ===========================================================

    def initialize_beacon_state_from_eth1(self, eth1_block_hash, eth1_timestamp, deposits):
        state = super().initialize_beacon_state_from_eth1(
            eth1_block_hash, eth1_timestamp, deposits
        )
        # pure-altair genesis fills both sync committees (state unchanged
        # between the fields, so compute once)
        committee = self.get_next_sync_committee(state)
        state.current_sync_committee = committee
        state.next_sync_committee = committee
        state.fork = self.Fork(
            previous_version=Version(self.config.ALTAIR_FORK_VERSION),
            current_version=Version(self.config.ALTAIR_FORK_VERSION),
            epoch=self.GENESIS_EPOCH,
        )
        return state

    # == fork upgrade (specs/altair/fork.md) ===============================

    def translate_participation(self, state, pending_attestations) -> None:
        for attestation in pending_attestations:
            data = attestation.data
            inclusion_delay = int(attestation.inclusion_delay)
            participation_flag_indices = self.get_attestation_participation_flag_indices(
                state, data, inclusion_delay
            )
            epoch_participation = state.previous_epoch_participation
            for index in self.get_attesting_indices_from_data(
                state, data, attestation.aggregation_bits
            ):
                for flag_index in participation_flag_indices:
                    epoch_participation[index] = self.add_flag(
                        epoch_participation[index], flag_index
                    )

    def upgrade_from_parent(self, pre):
        """upgrade_to_altair: carry the phase0 state across the fork
        boundary, translating pending attestations into participation flags
        and seeding both sync committees. Field-name-matched containers
        cross-coerce between the per-fork type families."""
        epoch = self.compute_epoch_at_slot(int(pre.slot))
        post = self.BeaconState(
            genesis_time=pre.genesis_time,
            genesis_validators_root=pre.genesis_validators_root,
            slot=pre.slot,
            fork=self.Fork(
                previous_version=pre.fork.current_version,
                current_version=Version(self.config.ALTAIR_FORK_VERSION),
                epoch=epoch,
            ),
            latest_block_header=pre.latest_block_header,
            block_roots=list(pre.block_roots),
            state_roots=list(pre.state_roots),
            historical_roots=list(pre.historical_roots),
            eth1_data=pre.eth1_data,
            eth1_data_votes=list(pre.eth1_data_votes),
            eth1_deposit_index=pre.eth1_deposit_index,
            validators=list(pre.validators),
            balances=list(pre.balances),
            randao_mixes=list(pre.randao_mixes),
            slashings=list(pre.slashings),
            previous_epoch_participation=[0] * len(pre.validators),
            current_epoch_participation=[0] * len(pre.validators),
            justification_bits=list(pre.justification_bits),
            previous_justified_checkpoint=pre.previous_justified_checkpoint,
            current_justified_checkpoint=pre.current_justified_checkpoint,
            finalized_checkpoint=pre.finalized_checkpoint,
            inactivity_scores=[0] * len(pre.validators),
        )
        self.translate_participation(post, pre.previous_epoch_attestations)
        # duplicate committee at the boundary; state unchanged between the
        # two fields, so compute once
        committee = self.get_next_sync_committee(post)
        post.current_sync_committee = committee
        post.next_sync_committee = committee
        return post
