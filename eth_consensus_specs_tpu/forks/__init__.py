"""Per-fork executable spec modules.

The reference compiles markdown into one flat module per fork x preset
(reference: pysetup/generate_specs.py:252-361). Here the same surface is a
CLASS HIERARCHY: each fork subclasses its parent and overrides exactly the
functions/types that fork changes — subclassing IS the fork-composition
operation (the reference's `combine_spec_objects` dict-union,
pysetup/helpers.py:351-380, done by the language). `get_spec()` returns a
cached instance whose bound methods give tests the familiar call shape
`spec.process_attestation(state, att)`.
"""

from __future__ import annotations

from functools import lru_cache

from eth_consensus_specs_tpu.config import FORK_ORDER, load_config, load_preset


def _spec_class(fork: str):
    if fork == "phase0":
        from .phase0 import Phase0Spec

        return Phase0Spec
    if fork == "altair":
        from .altair import AltairSpec

        return AltairSpec
    if fork == "bellatrix":
        from .bellatrix import BellatrixSpec

        return BellatrixSpec
    if fork == "capella":
        from .capella import CapellaSpec

        return CapellaSpec
    if fork == "deneb":
        from .deneb import DenebSpec

        return DenebSpec
    if fork == "electra":
        from .electra import ElectraSpec

        return ElectraSpec
    if fork == "fulu":
        from .fulu import FuluSpec

        return FuluSpec
    if fork == "gloas":
        from .gloas import GloasSpec

        return GloasSpec
    raise ValueError(f"unknown fork {fork!r}")


@lru_cache(maxsize=None)
def get_spec(fork: str = "phase0", preset_name: str = "mainnet", config_name: str | None = None):
    """Cached spec instance for (fork, preset, config)."""
    cls = _spec_class(fork)
    preset = load_preset(preset_name, fork)
    config = load_config(config_name if config_name is not None else preset_name)
    return cls(preset, config, preset_name=preset_name)


@lru_cache(maxsize=None)
def _get_spec_overridden(fork: str, preset_name: str, config_name: str | None, items: tuple):
    cls = _spec_class(fork)
    preset = load_preset(preset_name, fork)
    config = load_config(config_name if config_name is not None else preset_name)
    return cls(preset, config.replace(**dict(items)), preset_name=preset_name)


def get_spec_with_overrides(
    fork: str,
    preset_name: str = "mainnet",
    config_name: str | None = None,
    config_overrides: dict | None = None,
):
    """Spec instance with runtime-config overrides (the reference analogue:
    with_config_overrides rebuilding the Configuration NamedTuple,
    context.py:714-783). Cached per override set."""
    if not config_overrides:
        return get_spec(fork, preset_name, config_name)
    return _get_spec_overridden(
        fork, preset_name, config_name, tuple(sorted(config_overrides.items()))
    )


def available_forks() -> list[str]:
    out = []
    for f in FORK_ORDER:
        try:
            _spec_class(f)
            out.append(f)
        except (ValueError, ImportError):
            break
    return out
