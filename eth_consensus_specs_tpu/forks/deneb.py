"""deneb: blobs (EIP-4844) with KZG commitments, extended attestation
inclusion (EIP-7045), fixed exit domain (EIP-7044), activation churn cap
(EIP-7514), parent-beacon-root in the engine API (EIP-4788).

Behavioral parity targets (reference, by section):
  * state machine:  specs/deneb/beacon-chain.md (blob commitment checks
    :428, EIP-7045 process_attestation :375, EIP-7044 exits :492,
    EIP-7514 registry :522)
  * KZG:            specs/deneb/polynomial-commitments.md — implemented in
    crypto/kzg.py and re-exposed as spec methods here
  * fork choice:    specs/deneb/fork-choice.md (is_data_available gate)
  * p2p types:      specs/deneb/p2p-interface.md (BlobSidecar, inclusion
    proof verification)

The blob-proof batch verification is the framework's canonical batching
seam: N proofs -> one pairing via random linear combination, with all
scalar*point work in the Pippenger MSM (device kernel slot).
"""

from eth_consensus_specs_tpu.crypto import kzg as _kzg
from eth_consensus_specs_tpu.ssz import (
    Bitvector,
    ByteList,
    ByteVector,
    Bytes32,
    Bytes48,
    Container,
    List,
    Vector,
    hash_tree_root,
    uint64,
    uint256,
)
from eth_consensus_specs_tpu.utils import bls

from .altair import ParticipationFlags
from .bellatrix import ExecutionAddress, Hash32, NoopExecutionEngine
from .capella import CapellaSpec, WithdrawalIndex
from .phase0 import (
    BLSPubkey,
    BLSSignature,
    Gwei,
    Root,
    Slot,
    ValidatorIndex,
    Version,
)

KZGCommitment = Bytes48
KZGProof = Bytes48
VersionedHash = Bytes32
BlobIndex = uint64


class DenebExecutionEngine(NoopExecutionEngine):
    """Adds the deneb request-shape checks (versioned hashes, parent root)."""

    def is_valid_block_hash(self, execution_payload, parent_beacon_block_root) -> bool:
        return True

    def is_valid_versioned_hashes(self, new_payload_request) -> bool:
        return True

    def notify_new_payload(self, execution_payload, parent_beacon_block_root) -> bool:
        return True

    def verify_and_notify_new_payload(self, new_payload_request) -> bool:
        execution_payload = new_payload_request.execution_payload
        parent_beacon_block_root = new_payload_request.parent_beacon_block_root
        if b"" in [bytes(tx) for tx in execution_payload.transactions]:
            return False
        if not self.is_valid_block_hash(execution_payload, parent_beacon_block_root):
            return False
        if not self.is_valid_versioned_hashes(new_payload_request):
            return False
        if not self.notify_new_payload(execution_payload, parent_beacon_block_root):
            return False
        return True


class DenebSpec(CapellaSpec):
    fork_name = "deneb"

    VERSIONED_HASH_VERSION_KZG = b"\x01"

    # KZG constants (specs/deneb/polynomial-commitments.md)
    BLS_MODULUS = _kzg.BLS_MODULUS
    BYTES_PER_FIELD_ELEMENT = _kzg.BYTES_PER_FIELD_ELEMENT
    BYTES_PER_BLOB = _kzg.BYTES_PER_BLOB
    BYTES_PER_COMMITMENT = _kzg.BYTES_PER_COMMITMENT
    BYTES_PER_PROOF = _kzg.BYTES_PER_PROOF
    G1_POINT_AT_INFINITY = _kzg.G1_POINT_AT_INFINITY
    KZG_ENDIANNESS = _kzg.KZG_ENDIANNESS
    PRIMITIVE_ROOT_OF_UNITY = _kzg.PRIMITIVE_ROOT_OF_UNITY
    FIAT_SHAMIR_PROTOCOL_DOMAIN = _kzg.FIAT_SHAMIR_PROTOCOL_DOMAIN
    RANDOM_CHALLENGE_KZG_BATCH_DOMAIN = _kzg.RANDOM_CHALLENGE_KZG_BATCH_DOMAIN

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.EXECUTION_ENGINE = DenebExecutionEngine()

    # == type system ======================================================

    def _build_types(self) -> None:
        super()._build_types()
        P = self
        Blob = ByteVector[P.BYTES_PER_FIELD_ELEMENT * P.FIELD_ELEMENTS_PER_BLOB]
        self.Blob = Blob
        self.KZGCommitment = KZGCommitment
        self.KZGProof = KZGProof

        class ExecutionPayload(Container):
            parent_hash: Hash32
            fee_recipient: ExecutionAddress
            state_root: Bytes32
            receipts_root: Bytes32
            logs_bloom: ByteVector[P.BYTES_PER_LOGS_BLOOM]
            prev_randao: Bytes32
            block_number: uint64
            gas_limit: uint64
            gas_used: uint64
            timestamp: uint64
            extra_data: ByteList[P.MAX_EXTRA_DATA_BYTES]
            base_fee_per_gas: uint256
            block_hash: Hash32
            transactions: List[P.Transaction, P.MAX_TRANSACTIONS_PER_PAYLOAD]
            withdrawals: List[P.Withdrawal, P.MAX_WITHDRAWALS_PER_PAYLOAD]
            blob_gas_used: uint64  # [New in Deneb]
            excess_blob_gas: uint64  # [New in Deneb]

        class ExecutionPayloadHeader(Container):
            parent_hash: Hash32
            fee_recipient: ExecutionAddress
            state_root: Bytes32
            receipts_root: Bytes32
            logs_bloom: ByteVector[P.BYTES_PER_LOGS_BLOOM]
            prev_randao: Bytes32
            block_number: uint64
            gas_limit: uint64
            gas_used: uint64
            timestamp: uint64
            extra_data: ByteList[P.MAX_EXTRA_DATA_BYTES]
            base_fee_per_gas: uint256
            block_hash: Hash32
            transactions_root: Root
            withdrawals_root: Root
            blob_gas_used: uint64  # [New in Deneb]
            excess_blob_gas: uint64  # [New in Deneb]

        class BeaconBlockBody(Container):
            randao_reveal: BLSSignature
            eth1_data: P.Eth1Data
            graffiti: Bytes32
            proposer_slashings: List[P.ProposerSlashing, P.MAX_PROPOSER_SLASHINGS]
            attester_slashings: List[P.AttesterSlashing, P.MAX_ATTESTER_SLASHINGS]
            attestations: List[P.Attestation, P.MAX_ATTESTATIONS]
            deposits: List[P.Deposit, P.MAX_DEPOSITS]
            voluntary_exits: List[P.SignedVoluntaryExit, P.MAX_VOLUNTARY_EXITS]
            sync_aggregate: P.SyncAggregate
            execution_payload: ExecutionPayload
            bls_to_execution_changes: List[
                P.SignedBLSToExecutionChange, P.MAX_BLS_TO_EXECUTION_CHANGES
            ]
            blob_kzg_commitments: List[
                KZGCommitment, P.MAX_BLOB_COMMITMENTS_PER_BLOCK
            ]  # [New in Deneb]

        class BeaconBlock(Container):
            slot: Slot
            proposer_index: ValidatorIndex
            parent_root: Root
            state_root: Root
            body: BeaconBlockBody

        class SignedBeaconBlock(Container):
            message: BeaconBlock
            signature: BLSSignature

        class BeaconState(Container):
            genesis_time: uint64
            genesis_validators_root: Root
            slot: Slot
            fork: P.Fork
            latest_block_header: P.BeaconBlockHeader
            block_roots: Vector[Root, P.SLOTS_PER_HISTORICAL_ROOT]
            state_roots: Vector[Root, P.SLOTS_PER_HISTORICAL_ROOT]
            historical_roots: List[Root, P.HISTORICAL_ROOTS_LIMIT]
            eth1_data: P.Eth1Data
            eth1_data_votes: List[P.Eth1Data, P.EPOCHS_PER_ETH1_VOTING_PERIOD * P.SLOTS_PER_EPOCH]
            eth1_deposit_index: uint64
            validators: List[P.Validator, P.VALIDATOR_REGISTRY_LIMIT]
            balances: List[Gwei, P.VALIDATOR_REGISTRY_LIMIT]
            randao_mixes: Vector[Bytes32, P.EPOCHS_PER_HISTORICAL_VECTOR]
            slashings: Vector[Gwei, P.EPOCHS_PER_SLASHINGS_VECTOR]
            previous_epoch_participation: List[ParticipationFlags, P.VALIDATOR_REGISTRY_LIMIT]
            current_epoch_participation: List[ParticipationFlags, P.VALIDATOR_REGISTRY_LIMIT]
            justification_bits: Bitvector[self.JUSTIFICATION_BITS_LENGTH]
            previous_justified_checkpoint: P.Checkpoint
            current_justified_checkpoint: P.Checkpoint
            finalized_checkpoint: P.Checkpoint
            inactivity_scores: List[uint64, P.VALIDATOR_REGISTRY_LIMIT]
            current_sync_committee: P.SyncCommittee
            next_sync_committee: P.SyncCommittee
            latest_execution_payload_header: ExecutionPayloadHeader
            next_withdrawal_index: WithdrawalIndex
            next_withdrawal_validator_index: ValidatorIndex
            historical_summaries: List[P.HistoricalSummary, P.HISTORICAL_ROOTS_LIMIT]

        # p2p containers (specs/deneb/p2p-interface.md)
        class BlobSidecar(Container):
            index: BlobIndex
            blob: Blob
            kzg_commitment: KZGCommitment
            kzg_proof: KZGProof
            signed_block_header: P.SignedBeaconBlockHeader
            kzg_commitment_inclusion_proof: Vector[
                Bytes32, P.KZG_COMMITMENT_INCLUSION_PROOF_DEPTH
            ]

        class BlobIdentifier(Container):
            block_root: Root
            index: BlobIndex

        for name, typ in list(locals().items()):
            if isinstance(typ, type) and issubclass(typ, Container):
                typ.__name__ = name
                setattr(self, name, typ)

    # == request dataclasses ==============================================

    class NewPayloadRequest:
        def __init__(self, execution_payload, versioned_hashes=(), parent_beacon_block_root=b""):
            self.execution_payload = execution_payload
            self.versioned_hashes = versioned_hashes
            self.parent_beacon_block_root = parent_beacon_block_root

    # == KZG surface (delegates to crypto/kzg) =============================

    @staticmethod
    def blob_to_kzg_commitment(blob) -> bytes:
        return KZGCommitment(_kzg.blob_to_kzg_commitment(bytes(blob)))

    @staticmethod
    def compute_kzg_proof(blob, z_bytes):
        proof, y = _kzg.compute_kzg_proof(bytes(blob), bytes(z_bytes))
        return KZGProof(proof), Bytes32(y)

    @staticmethod
    def compute_blob_kzg_proof(blob, commitment_bytes) -> bytes:
        return KZGProof(_kzg.compute_blob_kzg_proof(bytes(blob), bytes(commitment_bytes)))

    @staticmethod
    def verify_kzg_proof(commitment_bytes, z_bytes, y_bytes, proof_bytes) -> bool:
        return _kzg.verify_kzg_proof(
            bytes(commitment_bytes), bytes(z_bytes), bytes(y_bytes), bytes(proof_bytes)
        )

    @staticmethod
    def verify_blob_kzg_proof(blob, commitment_bytes, proof_bytes) -> bool:
        return _kzg.verify_blob_kzg_proof(
            bytes(blob), bytes(commitment_bytes), bytes(proof_bytes)
        )

    @staticmethod
    def verify_blob_kzg_proof_batch(blobs, commitments, proofs) -> bool:
        return _kzg.verify_blob_kzg_proof_batch(
            [bytes(b) for b in blobs],
            [bytes(c) for c in commitments],
            [bytes(p) for p in proofs],
        )

    # == light client (specs/deneb/light-client/sync-protocol.md) ==========

    def get_lc_execution_root(self, header):
        """[Modified in Deneb] capella-era headers must hash the CAPELLA
        header shape (15 fields, depth-4 tree) — re-serializing the stored
        deneb-typed execution into the era's container so the leaf matches
        the execution_branch rooted in the era's body_root."""
        epoch = self.compute_epoch_at_slot(header.beacon.slot)
        if epoch >= self.config.DENEB_FORK_EPOCH:
            return hash_tree_root(header.execution)
        if epoch >= self.config.CAPELLA_FORK_EPOCH:
            from eth_consensus_specs_tpu.forks import get_spec

            capella_type = get_spec("capella", self.preset_name).ExecutionPayloadHeader
            execution_header = capella_type(
                **{
                    name: getattr(header.execution, name)
                    for name in capella_type.fields()
                }
            )
            return hash_tree_root(execution_header)
        return Bytes32()

    def is_valid_light_client_header(self, header) -> bool:
        epoch = self.compute_epoch_at_slot(header.beacon.slot)
        if epoch < self.config.DENEB_FORK_EPOCH:
            # [New in Deneb:EIP4844] blob gas fields must be unset pre-fork
            if header.execution.blob_gas_used != 0 or header.execution.excess_blob_gas != 0:
                return False
        return super().is_valid_light_client_header(header)

    # == misc ==============================================================

    # == blob sidecar construction (specs/deneb/validator.md:170-199,
    # p2p-interface.md verify seam) ========================================

    def compute_signed_block_header(self, signed_block):
        """specs/deneb/p2p-interface.md compute_signed_block_header."""
        block = signed_block.message
        block_header = self.BeaconBlockHeader(
            slot=block.slot,
            proposer_index=block.proposer_index,
            parent_root=block.parent_root,
            state_root=block.state_root,
            body_root=hash_tree_root(block.body),
        )
        return self.SignedBeaconBlockHeader(
            message=block_header, signature=signed_block.signature
        )

    def get_blob_sidecars(self, signed_block, blobs, blob_kzg_proofs):
        """Sidecars for a block's blobs, inclusion proofs included
        (specs/deneb/validator.md:170-188)."""
        from eth_consensus_specs_tpu.ssz.gindex import get_generalized_index
        from eth_consensus_specs_tpu.ssz.merkle import compute_merkle_proof

        block = signed_block.message
        signed_block_header = self.compute_signed_block_header(signed_block)
        return [
            self.BlobSidecar(
                index=index,
                blob=blob,
                kzg_commitment=block.body.blob_kzg_commitments[index],
                kzg_proof=blob_kzg_proofs[index],
                signed_block_header=signed_block_header,
                kzg_commitment_inclusion_proof=compute_merkle_proof(
                    block.body,
                    get_generalized_index(
                        type(block.body), "blob_kzg_commitments", index
                    ),
                ),
            )
            for index, blob in enumerate(blobs)
        ]

    def compute_subnet_for_blob_sidecar(self, blob_index: int) -> int:
        """reference: specs/deneb/validator.md:197-199."""
        return int(blob_index) % int(self.config.BLOB_SIDECAR_SUBNET_COUNT)

    def kzg_commitment_to_versioned_hash(self, kzg_commitment) -> bytes:
        return VersionedHash(
            self.VERSIONED_HASH_VERSION_KZG + self.hash(kzg_commitment)[1:]
        )

    # == accessors =========================================================

    def get_attestation_participation_flag_indices(self, state, data, inclusion_delay: int):
        """EIP-7045: the target flag no longer decays with inclusion delay."""
        if data.target.epoch == self.get_current_epoch(state):
            justified_checkpoint = state.current_justified_checkpoint
        else:
            justified_checkpoint = state.previous_justified_checkpoint
        is_matching_source = data.source == justified_checkpoint
        is_matching_target = (
            is_matching_source and data.target.root == self.get_block_root(state, data.target.epoch)
        )
        is_matching_head = (
            is_matching_target
            and data.beacon_block_root == self.get_block_root_at_slot(state, data.slot)
        )
        assert is_matching_source, "attestation source does not match justified checkpoint"

        participation_flag_indices = []
        if is_matching_source and inclusion_delay <= self.integer_squareroot(self.SLOTS_PER_EPOCH):
            participation_flag_indices.append(self.TIMELY_SOURCE_FLAG_INDEX)
        if is_matching_target:  # [Modified in Deneb:EIP7045]
            participation_flag_indices.append(self.TIMELY_TARGET_FLAG_INDEX)
        if is_matching_head and inclusion_delay == self.MIN_ATTESTATION_INCLUSION_DELAY:
            participation_flag_indices.append(self.TIMELY_HEAD_FLAG_INDEX)
        return participation_flag_indices

    def get_validator_activation_churn_limit(self, state) -> int:
        """EIP-7514: cap the activation queue drain."""
        return min(
            self.config.MAX_PER_EPOCH_ACTIVATION_CHURN_LIMIT,
            self.get_validator_churn_limit(state),
        )

    # == block processing ==================================================

    def process_attestation(self, state, attestation) -> None:
        data = attestation.data
        assert data.target.epoch in (
            self.get_previous_epoch(state),
            self.get_current_epoch(state),
        ), "target epoch out of range"
        assert data.target.epoch == self.compute_epoch_at_slot(data.slot), "target/slot mismatch"
        # [Modified in Deneb:EIP7045] no upper inclusion bound
        assert (
            int(data.slot) + self.MIN_ATTESTATION_INCLUSION_DELAY <= state.slot
        ), "attestation too recent"
        assert data.index < self.get_committee_count_per_slot(state, data.target.epoch)

        committee = self.get_beacon_committee(state, data.slot, data.index)
        assert len(attestation.aggregation_bits) == len(committee), "bitlist length mismatch"

        participation_flag_indices = self.get_attestation_participation_flag_indices(
            state, data, int(state.slot) - int(data.slot)
        )

        assert self.is_valid_indexed_attestation(
            state, self.get_indexed_attestation(state, attestation)
        ), "invalid aggregate signature"

        if data.target.epoch == self.get_current_epoch(state):
            epoch_participation = state.current_epoch_participation
        else:
            epoch_participation = state.previous_epoch_participation

        proposer_reward_numerator = 0
        for index in self.get_attesting_indices(state, attestation):
            for flag_index, weight in enumerate(self.PARTICIPATION_FLAG_WEIGHTS):
                if flag_index in participation_flag_indices and not self.has_flag(
                    epoch_participation[index], flag_index
                ):
                    epoch_participation[index] = self.add_flag(
                        epoch_participation[index], flag_index
                    )
                    proposer_reward_numerator += self.get_base_reward(state, index) * weight

        proposer_reward_denominator = (
            (self.WEIGHT_DENOMINATOR - self.PROPOSER_WEIGHT)
            * self.WEIGHT_DENOMINATOR
            // self.PROPOSER_WEIGHT
        )
        proposer_reward = proposer_reward_numerator // proposer_reward_denominator
        self.increase_balance(state, self.get_beacon_proposer_index(state), proposer_reward)

    def max_blobs_per_block(self) -> int:
        return self.config.MAX_BLOBS_PER_BLOCK

    def process_execution_payload(self, state, body, execution_engine) -> None:
        payload = body.execution_payload
        assert (
            payload.parent_hash == state.latest_execution_payload_header.block_hash
        ), "payload parent mismatch"
        assert payload.prev_randao == self.get_randao_mix(
            state, self.get_current_epoch(state)
        ), "wrong prev_randao"
        assert payload.timestamp == self.compute_timestamp_at_slot(
            state, state.slot
        ), "wrong payload timestamp"
        # [New in Deneb:EIP4844]
        assert len(body.blob_kzg_commitments) <= self.max_blobs_per_block(), "too many blobs"
        versioned_hashes = [
            self.kzg_commitment_to_versioned_hash(commitment)
            for commitment in body.blob_kzg_commitments
        ]
        assert execution_engine.verify_and_notify_new_payload(
            self.NewPayloadRequest(
                execution_payload=payload,
                versioned_hashes=versioned_hashes,
                parent_beacon_block_root=state.latest_block_header.parent_root,
            )
        ), "execution engine rejected payload"
        state.latest_execution_payload_header = self.execution_payload_to_header(payload)

    def execution_payload_to_header(self, payload):
        return self.ExecutionPayloadHeader(
            parent_hash=payload.parent_hash,
            fee_recipient=payload.fee_recipient,
            state_root=payload.state_root,
            receipts_root=payload.receipts_root,
            logs_bloom=payload.logs_bloom,
            prev_randao=payload.prev_randao,
            block_number=payload.block_number,
            gas_limit=payload.gas_limit,
            gas_used=payload.gas_used,
            timestamp=payload.timestamp,
            extra_data=payload.extra_data,
            base_fee_per_gas=payload.base_fee_per_gas,
            block_hash=payload.block_hash,
            transactions_root=hash_tree_root(payload.transactions),
            withdrawals_root=hash_tree_root(payload.withdrawals),
            blob_gas_used=payload.blob_gas_used,
            excess_blob_gas=payload.excess_blob_gas,
        )

    def process_voluntary_exit(self, state, signed_voluntary_exit) -> None:
        """EIP-7044: exits sign over the fixed capella fork version."""
        voluntary_exit = signed_voluntary_exit.message
        validator = state.validators[int(voluntary_exit.validator_index)]
        assert self.is_active_validator(validator, self.get_current_epoch(state)), "not active"
        assert validator.exit_epoch == self.FAR_FUTURE_EPOCH, "already exiting"
        assert self.get_current_epoch(state) >= voluntary_exit.epoch, "exit not yet valid"
        assert (
            self.get_current_epoch(state)
            >= int(validator.activation_epoch) + self.config.SHARD_COMMITTEE_PERIOD
        ), "validator too young to exit"
        domain = self.compute_domain(
            self.DOMAIN_VOLUNTARY_EXIT,
            self.config.CAPELLA_FORK_VERSION,
            state.genesis_validators_root,
        )
        signing_root = self.compute_signing_root(voluntary_exit, domain)
        assert bls.Verify(validator.pubkey, signing_root, signed_voluntary_exit.signature)
        self.initiate_validator_exit(state, voluntary_exit.validator_index)

    # == epoch processing ==================================================

    def process_registry_updates(self, state) -> None:
        """EIP-7514: activations drain at the capped churn limit."""
        current_epoch = self.get_current_epoch(state)
        for index, validator in enumerate(state.validators):
            if self.is_eligible_for_activation_queue(validator):
                validator.activation_eligibility_epoch = current_epoch + 1
            if (
                self.is_active_validator(validator, current_epoch)
                and validator.effective_balance <= self.config.EJECTION_BALANCE
            ):
                self.initiate_validator_exit(state, index)
        activation_queue = sorted(
            [
                index
                for index, validator in enumerate(state.validators)
                if self.is_eligible_for_activation(state, validator)
            ],
            key=lambda index: (int(state.validators[index].activation_eligibility_epoch), index),
        )
        for index in activation_queue[: self.get_validator_activation_churn_limit(state)]:
            state.validators[index].activation_epoch = self.compute_activation_exit_epoch(
                current_epoch
            )

    # == data availability (specs/deneb/fork-choice.md) ====================

    def retrieve_blobs_and_proofs(self, beacon_block_root):
        """Networking-dependent blob retrieval; tests override this method
        (the reference monkeypatches the same stub,
        pysetup/spec_builders/deneb.py + helpers/fork_choice.py:51-108).
        Default: nothing retrievable — blocks carrying commitments fail the
        availability gate until data is supplied."""
        return [], []

    def is_data_available(self, beacon_block_root, blob_kzg_commitments) -> bool:
        blobs, proofs = self.retrieve_blobs_and_proofs(beacon_block_root)
        if len(blobs) != len(blob_kzg_commitments) or len(proofs) != len(
            blob_kzg_commitments
        ):
            # retrieval shortfall is unavailability, not a malformed batch
            return False
        return self.verify_blob_kzg_proof_batch(blobs, blob_kzg_commitments, proofs)

    def _data_availability_check(self, block) -> None:
        # [New in Deneb:EIP4844] (specs/deneb/fork-choice.md:54-63)
        assert self.is_data_available(
            hash_tree_root(block), block.body.blob_kzg_commitments
        ), "blob data not available"

    def verify_blob_sidecar_inclusion_proof(self, blob_sidecar) -> bool:
        # gindex of blob_kzg_commitments[index] inside BeaconBlockBody:
        # body has 12 fields (depth 4); the commitments list adds
        # ceil(log2(MAX_BLOB_COMMITMENTS)) + 1 (length mix-in) levels
        field_index = list(self.BeaconBlockBody.fields()).index("blob_kzg_commitments")
        list_depth = (self.MAX_BLOB_COMMITMENTS_PER_BLOCK - 1).bit_length() + 1
        gindex = (
            ((1 << 4 | field_index) << list_depth)
            | int(blob_sidecar.index)
        )
        depth = self.KZG_COMMITMENT_INCLUSION_PROOF_DEPTH
        return self.is_valid_merkle_branch(
            leaf=hash_tree_root(blob_sidecar.kzg_commitment),
            branch=blob_sidecar.kzg_commitment_inclusion_proof,
            depth=depth,
            index=gindex & ((1 << depth) - 1),
            root=blob_sidecar.signed_block_header.message.body_root,
        )

    # == fork upgrade (specs/deneb/fork.md) ================================

    def upgrade_from_parent(self, pre):
        epoch = self.compute_epoch_at_slot(int(pre.slot))
        pre_header = pre.latest_execution_payload_header
        header = self.ExecutionPayloadHeader(
            parent_hash=pre_header.parent_hash,
            fee_recipient=pre_header.fee_recipient,
            state_root=pre_header.state_root,
            receipts_root=pre_header.receipts_root,
            logs_bloom=pre_header.logs_bloom,
            prev_randao=pre_header.prev_randao,
            block_number=pre_header.block_number,
            gas_limit=pre_header.gas_limit,
            gas_used=pre_header.gas_used,
            timestamp=pre_header.timestamp,
            extra_data=pre_header.extra_data,
            base_fee_per_gas=pre_header.base_fee_per_gas,
            block_hash=pre_header.block_hash,
            transactions_root=pre_header.transactions_root,
            withdrawals_root=pre_header.withdrawals_root,
            # blob_gas fields default to zero
        )
        return self.BeaconState(
            genesis_time=pre.genesis_time,
            genesis_validators_root=pre.genesis_validators_root,
            slot=pre.slot,
            fork=self.Fork(
                previous_version=pre.fork.current_version,
                current_version=Version(self.config.DENEB_FORK_VERSION),
                epoch=epoch,
            ),
            latest_block_header=pre.latest_block_header,
            block_roots=list(pre.block_roots),
            state_roots=list(pre.state_roots),
            historical_roots=list(pre.historical_roots),
            eth1_data=pre.eth1_data,
            eth1_data_votes=list(pre.eth1_data_votes),
            eth1_deposit_index=pre.eth1_deposit_index,
            validators=list(pre.validators),
            balances=list(pre.balances),
            randao_mixes=list(pre.randao_mixes),
            slashings=list(pre.slashings),
            previous_epoch_participation=list(pre.previous_epoch_participation),
            current_epoch_participation=list(pre.current_epoch_participation),
            justification_bits=list(pre.justification_bits),
            previous_justified_checkpoint=pre.previous_justified_checkpoint,
            current_justified_checkpoint=pre.current_justified_checkpoint,
            finalized_checkpoint=pre.finalized_checkpoint,
            inactivity_scores=list(pre.inactivity_scores),
            current_sync_committee=pre.current_sync_committee,
            next_sync_committee=pre.next_sync_committee,
            latest_execution_payload_header=header,
            next_withdrawal_index=pre.next_withdrawal_index,
            next_withdrawal_validator_index=pre.next_withdrawal_validator_index,
            historical_summaries=list(pre.historical_summaries),
        )
