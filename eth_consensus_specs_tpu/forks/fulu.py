"""fulu: PeerDAS (EIP-7594) — cells, data-column sidecars, custody groups,
erasure-coded recovery — plus the blob schedule (EIP-7892) and precomputed
proposer lookahead (EIP-7917).

Behavioral parity targets (reference, by section):
  * state machine:  specs/fulu/beacon-chain.md (blob-schedule payload gate
    :63-115, proposer_lookahead state field :134-175, get_blob_parameters
    :193-200, fork-digest bitmask :209-235, proposer-indices lookahead
    :241-327)
  * DAS core:       specs/fulu/das-core.md (custody groups :101-134,
    compute_matrix/recover_matrix :140-189, DataColumnSidecar :77-94)
  * sampling KZG:   specs/fulu/polynomial-commitments-sampling.md —
    implemented in crypto/das.py, re-exposed as spec methods here
  * fork choice:    specs/fulu/fork-choice.md (column-sampled
    is_data_available :19-34)
  * p2p checks:     specs/fulu/p2p-interface.md (sidecar validity :109-175)
  * validator:      specs/fulu/validator.md (sidecar construction :207-265)
  * fork upgrade:   specs/fulu/fork.md (initialize_proposer_lookahead
    :27-44, upgrade_to_fulu :53-110)

TPU-first notes: the DAS math (field FFTs, FK20 lag-MSMs, batched cell
verification) lives in crypto/das.py in flat-vector form — see that
module's docstring for how it diverges from the reference's recursive
formulation. The per-epoch proposer lookahead turns the hot
`get_beacon_proposer_index` path into a table read, which also removes a
per-slot shuffle dependency from the jitted slot loop.
"""

from dataclasses import dataclass

from eth_consensus_specs_tpu.crypto import das as _das
from eth_consensus_specs_tpu.ssz import (
    ByteVector,
    Bytes32,
    Container,
    List,
    Vector,
    hash_tree_root,
    uint64,
)

from .deneb import KZGCommitment, KZGProof
from .electra import ElectraSpec
from .phase0 import Root, ValidatorIndex, Version

RowIndex = uint64
ColumnIndex = uint64
CellIndex = uint64
CustodyIndex = uint64
CommitmentIndex = uint64


class FuluSpec(ElectraSpec):
    fork_name = "fulu"

    # das-core constants (specs/fulu/das-core.md:35-45)
    UINT256_MAX = 2**256 - 1
    RANDOM_CHALLENGE_KZG_CELL_BATCH_DOMAIN = _das.RANDOM_CHALLENGE_KZG_CELL_BATCH_DOMAIN
    BYTES_PER_CELL = _das.BYTES_PER_CELL

    # == type system ======================================================

    def _build_types(self) -> None:
        super()._build_types()
        P = self

        Cell = ByteVector[P.BYTES_PER_FIELD_ELEMENT * P.FIELD_ELEMENTS_PER_CELL]
        self.Cell = Cell

        class BeaconState(Container):
            genesis_time: uint64
            genesis_validators_root: Root
            slot: P.BeaconState.fields()["slot"]
            fork: P.Fork
            latest_block_header: P.BeaconBlockHeader
            block_roots: P.BeaconState.fields()["block_roots"]
            state_roots: P.BeaconState.fields()["state_roots"]
            historical_roots: P.BeaconState.fields()["historical_roots"]
            eth1_data: P.Eth1Data
            eth1_data_votes: P.BeaconState.fields()["eth1_data_votes"]
            eth1_deposit_index: uint64
            validators: P.BeaconState.fields()["validators"]
            balances: P.BeaconState.fields()["balances"]
            randao_mixes: P.BeaconState.fields()["randao_mixes"]
            slashings: P.BeaconState.fields()["slashings"]
            previous_epoch_participation: P.BeaconState.fields()[
                "previous_epoch_participation"
            ]
            current_epoch_participation: P.BeaconState.fields()[
                "current_epoch_participation"
            ]
            justification_bits: P.BeaconState.fields()["justification_bits"]
            previous_justified_checkpoint: P.Checkpoint
            current_justified_checkpoint: P.Checkpoint
            finalized_checkpoint: P.Checkpoint
            inactivity_scores: P.BeaconState.fields()["inactivity_scores"]
            current_sync_committee: P.SyncCommittee
            next_sync_committee: P.SyncCommittee
            latest_execution_payload_header: P.ExecutionPayloadHeader
            next_withdrawal_index: P.BeaconState.fields()["next_withdrawal_index"]
            next_withdrawal_validator_index: P.BeaconState.fields()[
                "next_withdrawal_validator_index"
            ]
            historical_summaries: P.BeaconState.fields()["historical_summaries"]
            deposit_requests_start_index: uint64
            deposit_balance_to_consume: P.BeaconState.fields()["deposit_balance_to_consume"]
            exit_balance_to_consume: P.BeaconState.fields()["exit_balance_to_consume"]
            earliest_exit_epoch: P.BeaconState.fields()["earliest_exit_epoch"]
            consolidation_balance_to_consume: P.BeaconState.fields()[
                "consolidation_balance_to_consume"
            ]
            earliest_consolidation_epoch: P.BeaconState.fields()[
                "earliest_consolidation_epoch"
            ]
            pending_deposits: P.BeaconState.fields()["pending_deposits"]
            pending_partial_withdrawals: P.BeaconState.fields()[
                "pending_partial_withdrawals"
            ]
            pending_consolidations: P.BeaconState.fields()["pending_consolidations"]
            # [New in Fulu:EIP7917]
            proposer_lookahead: Vector[
                ValidatorIndex, (P.MIN_SEED_LOOKAHEAD + 1) * P.SLOTS_PER_EPOCH
            ]

        # specs/fulu/das-core.md:77-84
        class DataColumnSidecar(Container):
            index: ColumnIndex
            column: List[Cell, P.MAX_BLOB_COMMITMENTS_PER_BLOCK]
            kzg_commitments: List[KZGCommitment, P.MAX_BLOB_COMMITMENTS_PER_BLOCK]
            kzg_proofs: List[KZGProof, P.MAX_BLOB_COMMITMENTS_PER_BLOCK]
            signed_block_header: P.SignedBeaconBlockHeader
            kzg_commitments_inclusion_proof: Vector[
                Bytes32, P.KZG_COMMITMENTS_INCLUSION_PROOF_DEPTH
            ]

        # specs/fulu/das-core.md:89-94
        class MatrixEntry(Container):
            cell: Cell
            kzg_proof: KZGProof
            column_index: ColumnIndex
            row_index: RowIndex

        # specs/fulu/p2p-interface.md (req/resp identifier)
        class DataColumnsByRootIdentifier(Container):
            block_root: Root
            columns: List[ColumnIndex, P.NUMBER_OF_COLUMNS]

        for name, typ in list(locals().items()):
            if isinstance(typ, type) and issubclass(typ, Container):
                typ.__name__ = name
                setattr(self, name, typ)

    # == blob schedule (EIP-7892) =========================================

    @dataclass
    class BlobParameters:
        epoch: int
        max_blobs_per_block: int

    def get_blob_parameters(self, epoch: int) -> "FuluSpec.BlobParameters":
        """specs/fulu/beacon-chain.md:193-200."""
        schedule = getattr(self.config, "BLOB_SCHEDULE", ())
        for entry in sorted(schedule, key=lambda e: int(e["EPOCH"]), reverse=True):
            if epoch >= int(entry["EPOCH"]):
                return self.BlobParameters(int(entry["EPOCH"]), int(entry["MAX_BLOBS_PER_BLOCK"]))
        return self.BlobParameters(
            int(self.config.ELECTRA_FORK_EPOCH), int(self.config.MAX_BLOBS_PER_BLOCK_ELECTRA)
        )

    def max_blobs_per_block(self) -> int:
        """Largest scheduled limit — used only for static sizing; the
        consensus gate is epoch-aware (process_execution_payload)."""
        schedule = getattr(self.config, "BLOB_SCHEDULE", ())
        limits = [int(e["MAX_BLOBS_PER_BLOCK"]) for e in schedule]
        return max([int(self.config.MAX_BLOBS_PER_BLOCK_ELECTRA)] + limits)

    def compute_fork_digest(self, genesis_validators_root, epoch=None):
        """[Modified in Fulu:EIP7892] Blob-parameters-aware digest
        (specs/fulu/beacon-chain.md:209-235). Falls back to the legacy
        (version, root) signature when called pre-fulu-style."""
        if epoch is None or isinstance(genesis_validators_root, (bytes, bytearray)) and len(
            genesis_validators_root
        ) == 4:
            # legacy call shape: (current_version, genesis_validators_root)
            return super().compute_fork_digest(genesis_validators_root, epoch)
        fork_version = self.compute_fork_version(int(epoch))
        base_digest = self.compute_fork_data_root(fork_version, genesis_validators_root)
        blob_parameters = self.get_blob_parameters(int(epoch))
        mask = self.hash(
            self.uint_to_bytes(int(blob_parameters.epoch), 8)
            + self.uint_to_bytes(int(blob_parameters.max_blobs_per_block), 8)
        )
        return bytes(a ^ b for a, b in zip(bytes(base_digest), mask))[:4]

    # == proposer lookahead (EIP-7917) ====================================

    def compute_proposer_indices(self, state, epoch: int, seed: bytes, indices):
        """specs/fulu/beacon-chain.md:241-250."""
        start_slot = self.compute_start_slot_at_epoch(int(epoch))
        seeds = [
            self.hash(seed + self.uint_to_bytes(int(start_slot + i), 8))
            for i in range(self.SLOTS_PER_EPOCH)
        ]
        return [self.compute_proposer_index(state, indices, s) for s in seeds]

    def get_beacon_proposer_indices(self, state, epoch: int):
        """specs/fulu/beacon-chain.md:270-279."""
        indices = self.get_active_validator_indices(state, int(epoch))
        seed = self.get_seed(state, int(epoch), self.DOMAIN_BEACON_PROPOSER)
        return self.compute_proposer_indices(state, int(epoch), seed, indices)

    def get_beacon_proposer_index(self, state) -> int:
        """[Modified in Fulu:EIP7917] table read instead of on-demand
        shuffle (specs/fulu/beacon-chain.md:260-265)."""
        return int(state.proposer_lookahead[int(state.slot) % self.SLOTS_PER_EPOCH])

    def initialize_proposer_lookahead(self, state):
        """specs/fulu/fork.md:27-44."""
        current_epoch = self.get_current_epoch(state)
        lookahead = []
        for i in range(self.MIN_SEED_LOOKAHEAD + 1):
            lookahead.extend(self.get_beacon_proposer_indices(state, current_epoch + i))
        return lookahead

    def process_proposer_lookahead(self, state) -> None:
        """specs/fulu/beacon-chain.md:318-327."""
        last_epoch_start = len(state.proposer_lookahead) - self.SLOTS_PER_EPOCH
        full = list(state.proposer_lookahead)
        full[:last_epoch_start] = full[self.SLOTS_PER_EPOCH :]
        last_epoch_proposers = self.get_beacon_proposer_indices(
            state, self.get_current_epoch(state) + self.MIN_SEED_LOOKAHEAD + 1
        )
        full[last_epoch_start:] = last_epoch_proposers
        state.proposer_lookahead = full

    # == epoch processing ==================================================

    def process_epoch(self, state) -> None:
        """specs/fulu/beacon-chain.md:290-307 — electra ordering plus the
        lookahead shift."""
        super().process_epoch(state)
        # [New in Fulu:EIP7917]
        self.process_proposer_lookahead(state)

    # == block processing ==================================================

    def process_execution_payload(self, state, body, execution_engine) -> None:
        """[Modified in Fulu:EIP7892] blob cap comes from the schedule
        (specs/fulu/beacon-chain.md:63-115)."""
        payload = body.execution_payload
        assert (
            payload.parent_hash == state.latest_execution_payload_header.block_hash
        ), "payload parent mismatch"
        assert payload.prev_randao == self.get_randao_mix(
            state, self.get_current_epoch(state)
        ), "wrong prev_randao"
        assert payload.timestamp == self.compute_timestamp_at_slot(
            state, state.slot
        ), "wrong payload timestamp"
        # [Modified in Fulu:EIP7892]
        assert (
            len(body.blob_kzg_commitments)
            <= self.get_blob_parameters(self.get_current_epoch(state)).max_blobs_per_block
        ), "too many blobs"
        versioned_hashes = [
            self.kzg_commitment_to_versioned_hash(commitment)
            for commitment in body.blob_kzg_commitments
        ]
        assert execution_engine.verify_and_notify_new_payload(
            self.NewPayloadRequest(
                execution_payload=payload,
                versioned_hashes=versioned_hashes,
                parent_beacon_block_root=state.latest_block_header.parent_root,
                execution_requests=body.execution_requests,
            )
        ), "execution engine rejected payload"
        state.latest_execution_payload_header = self.execution_payload_to_header(payload)

    # == DAS KZG surface (delegates to crypto/das) =========================

    @staticmethod
    def compute_cells(blob):
        return _das.compute_cells(bytes(blob))

    @staticmethod
    def compute_cells_and_kzg_proofs(blob):
        return _das.compute_cells_and_kzg_proofs(bytes(blob))

    @staticmethod
    def verify_cell_kzg_proof_batch(commitments_bytes, cell_indices, cells, proofs_bytes):
        return _das.verify_cell_kzg_proof_batch(
            [bytes(c) for c in commitments_bytes],
            [int(i) for i in cell_indices],
            [bytes(c) for c in cells],
            [bytes(p) for p in proofs_bytes],
        )

    @staticmethod
    def recover_cells_and_kzg_proofs(cell_indices, cells):
        return _das.recover_cells_and_kzg_proofs(
            [int(i) for i in cell_indices], [bytes(c) for c in cells]
        )

    @staticmethod
    def cell_to_coset_evals(cell):
        return _das.cell_to_coset_evals(bytes(cell))

    @staticmethod
    def coset_evals_to_cell(evals):
        return _das.coset_evals_to_cell(list(evals))

    @staticmethod
    def coset_for_cell(cell_index: int):
        return _das.coset_for_cell(int(cell_index))

    # == custody (specs/fulu/das-core.md:101-134) ==========================

    def get_custody_groups(self, node_id: int, custody_group_count: int):
        assert custody_group_count <= self.config.NUMBER_OF_CUSTODY_GROUPS
        if custody_group_count == self.config.NUMBER_OF_CUSTODY_GROUPS:
            return list(range(self.config.NUMBER_OF_CUSTODY_GROUPS))

        current_id = int(node_id)
        custody_groups: list[int] = []
        while len(custody_groups) < custody_group_count:
            digest = self.hash(current_id.to_bytes(32, "little"))
            custody_group = self.bytes_to_uint64(digest[0:8]) % self.config.NUMBER_OF_CUSTODY_GROUPS
            if custody_group not in custody_groups:
                custody_groups.append(custody_group)
            if current_id == self.UINT256_MAX:
                current_id = 0
            else:
                current_id += 1
        assert len(custody_groups) == len(set(custody_groups))
        return sorted(custody_groups)

    def get_validators_custody_requirement(self, state, validator_indices) -> int:
        """Nodes with attached validators custody more groups, scaled by
        total attached effective balance (reference:
        specs/fulu/validator.md:124-131)."""
        total_node_balance = sum(
            int(state.validators[int(index)].effective_balance)
            for index in validator_indices
        )
        count = total_node_balance // int(
            self.config.BALANCE_PER_ADDITIONAL_CUSTODY_GROUP
        )
        return min(
            max(count, int(self.config.VALIDATOR_CUSTODY_REQUIREMENT)),
            int(self.config.NUMBER_OF_CUSTODY_GROUPS),
        )

    def compute_columns_for_custody_group(self, custody_group: int):
        assert custody_group < self.config.NUMBER_OF_CUSTODY_GROUPS
        columns_per_group = self.NUMBER_OF_COLUMNS // self.config.NUMBER_OF_CUSTODY_GROUPS
        return [
            self.config.NUMBER_OF_CUSTODY_GROUPS * i + custody_group
            for i in range(columns_per_group)
        ]

    def get_sampling_columns(self, node_id: int, custody_group_count: int):
        """Custody sampling (specs/fulu/das-core.md:220-230): sample
        max(SAMPLES_PER_SLOT, cgc) groups' columns."""
        sampling_size = max(self.config.SAMPLES_PER_SLOT, custody_group_count)
        groups = self.get_custody_groups(node_id, sampling_size)
        out: list[int] = []
        for group in groups:
            out.extend(self.compute_columns_for_custody_group(group))
        return sorted(out)

    # == matrix (specs/fulu/das-core.md:140-189) ===========================

    def compute_matrix(self, blobs):
        matrix = []
        for blob_index, blob in enumerate(blobs):
            cells, proofs = self.compute_cells_and_kzg_proofs(blob)
            for cell_index, (cell, proof) in enumerate(zip(cells, proofs)):
                matrix.append(
                    self.MatrixEntry(
                        cell=cell,
                        kzg_proof=proof,
                        row_index=blob_index,
                        column_index=cell_index,
                    )
                )
        return matrix

    def recover_matrix(self, partial_matrix, blob_count: int):
        matrix = []
        for blob_index in range(int(blob_count)):
            cell_indices = [
                int(e.column_index) for e in partial_matrix if int(e.row_index) == blob_index
            ]
            cells = [bytes(e.cell) for e in partial_matrix if int(e.row_index) == blob_index]
            recovered_cells, recovered_proofs = self.recover_cells_and_kzg_proofs(
                cell_indices, cells
            )
            for cell_index, (cell, proof) in enumerate(zip(recovered_cells, recovered_proofs)):
                matrix.append(
                    self.MatrixEntry(
                        cell=cell,
                        kzg_proof=proof,
                        row_index=blob_index,
                        column_index=cell_index,
                    )
                )
        return matrix

    # == sidecar validity (specs/fulu/p2p-interface.md:109-175) ============

    def verify_data_column_sidecar(self, sidecar) -> bool:
        if sidecar.index >= self.NUMBER_OF_COLUMNS:
            return False
        if len(sidecar.kzg_commitments) == 0:
            return False
        epoch = self.compute_epoch_at_slot(int(sidecar.signed_block_header.message.slot))
        if len(sidecar.kzg_commitments) > self.get_blob_parameters(epoch).max_blobs_per_block:
            return False
        if len(sidecar.column) != len(sidecar.kzg_commitments) or len(sidecar.column) != len(
            sidecar.kzg_proofs
        ):
            return False
        return True

    def verify_data_column_sidecar_kzg_proofs(self, sidecar) -> bool:
        cell_indices = [int(sidecar.index)] * len(sidecar.column)
        return self.verify_cell_kzg_proof_batch(
            commitments_bytes=list(sidecar.kzg_commitments),
            cell_indices=cell_indices,
            cells=list(sidecar.column),
            proofs_bytes=list(sidecar.kzg_proofs),
        )

    def verify_data_column_sidecar_inclusion_proof(self, sidecar) -> bool:
        field_index = list(self.BeaconBlockBody.fields()).index("blob_kzg_commitments")
        return self.is_valid_merkle_branch(
            leaf=hash_tree_root(sidecar.kzg_commitments),
            branch=sidecar.kzg_commitments_inclusion_proof,
            depth=self.KZG_COMMITMENTS_INCLUSION_PROOF_DEPTH,
            index=field_index,
            root=sidecar.signed_block_header.message.body_root,
        )

    def compute_subnet_for_data_column_sidecar(self, column_index: int) -> int:
        return int(column_index) % self.config.DATA_COLUMN_SIDECAR_SUBNET_COUNT

    # == sidecar construction (specs/fulu/validator.md:207-265) ============

    def get_data_column_sidecars(
        self,
        signed_block_header,
        kzg_commitments,
        kzg_commitments_inclusion_proof,
        cells_and_kzg_proofs,
    ):
        assert len(cells_and_kzg_proofs) == len(kzg_commitments)
        sidecars = []
        for column_index in range(self.NUMBER_OF_COLUMNS):
            column_cells, column_proofs = [], []
            for cells, proofs in cells_and_kzg_proofs:
                column_cells.append(cells[column_index])
                column_proofs.append(proofs[column_index])
            sidecars.append(
                self.DataColumnSidecar(
                    index=column_index,
                    column=column_cells,
                    kzg_commitments=list(kzg_commitments),
                    kzg_proofs=column_proofs,
                    signed_block_header=signed_block_header,
                    kzg_commitments_inclusion_proof=kzg_commitments_inclusion_proof,
                )
            )
        return sidecars

    def get_data_column_sidecars_from_block(self, signed_block, cells_and_kzg_proofs):
        from eth_consensus_specs_tpu.ssz.merkle import compute_merkle_proof

        body = signed_block.message.body
        field_index = list(type(body).fields()).index("blob_kzg_commitments")
        fields_depth = (len(type(body).fields()) - 1).bit_length()
        gindex = (1 << fields_depth) | field_index
        return self.get_data_column_sidecars(
            self.compute_signed_block_header(signed_block),
            list(body.blob_kzg_commitments),
            compute_merkle_proof(body, gindex),
            cells_and_kzg_proofs,
        )

    # == data availability (specs/fulu/fork-choice.md:19-34) ===============

    def retrieve_column_sidecars(self, beacon_block_root):
        """Implementation/context dependent; tests register a retriever
        (the reference monkeypatches the same seam)."""
        retriever = getattr(self, "_column_retriever", None)
        if retriever is not None:
            return retriever(beacon_block_root)
        return []

    def is_data_available(self, beacon_block_root, blob_kzg_commitments=None) -> bool:
        """[Modified in Fulu:EIP7594] sample columns, not blobs."""
        column_sidecars = self.retrieve_column_sidecars(beacon_block_root)
        return all(
            self.verify_data_column_sidecar(column_sidecar)
            and self.verify_data_column_sidecar_kzg_proofs(column_sidecar)
            for column_sidecar in column_sidecars
        )

    def _data_availability_check(self, block) -> None:
        # [Modified in Fulu:EIP7594] no commitments argument
        assert self.is_data_available(hash_tree_root(block)), "column data not available"

    # == fork upgrade (specs/fulu/fork.md:53-110) ==========================

    def upgrade_from_parent(self, pre):
        epoch = self.compute_epoch_at_slot(int(pre.slot))
        post = self.BeaconState(
            genesis_time=pre.genesis_time,
            genesis_validators_root=pre.genesis_validators_root,
            slot=pre.slot,
            fork=self.Fork(
                previous_version=pre.fork.current_version,
                current_version=Version(self.config.FULU_FORK_VERSION),
                epoch=epoch,
            ),
            latest_block_header=pre.latest_block_header,
            block_roots=list(pre.block_roots),
            state_roots=list(pre.state_roots),
            historical_roots=list(pre.historical_roots),
            eth1_data=pre.eth1_data,
            eth1_data_votes=list(pre.eth1_data_votes),
            eth1_deposit_index=pre.eth1_deposit_index,
            validators=list(pre.validators),
            balances=list(pre.balances),
            randao_mixes=list(pre.randao_mixes),
            slashings=list(pre.slashings),
            previous_epoch_participation=list(pre.previous_epoch_participation),
            current_epoch_participation=list(pre.current_epoch_participation),
            justification_bits=list(pre.justification_bits),
            previous_justified_checkpoint=pre.previous_justified_checkpoint,
            current_justified_checkpoint=pre.current_justified_checkpoint,
            finalized_checkpoint=pre.finalized_checkpoint,
            inactivity_scores=list(pre.inactivity_scores),
            current_sync_committee=pre.current_sync_committee,
            next_sync_committee=pre.next_sync_committee,
            latest_execution_payload_header=pre.latest_execution_payload_header,
            next_withdrawal_index=pre.next_withdrawal_index,
            next_withdrawal_validator_index=pre.next_withdrawal_validator_index,
            historical_summaries=list(pre.historical_summaries),
            deposit_requests_start_index=pre.deposit_requests_start_index,
            deposit_balance_to_consume=pre.deposit_balance_to_consume,
            exit_balance_to_consume=pre.exit_balance_to_consume,
            earliest_exit_epoch=pre.earliest_exit_epoch,
            consolidation_balance_to_consume=pre.consolidation_balance_to_consume,
            earliest_consolidation_epoch=pre.earliest_consolidation_epoch,
            pending_deposits=list(pre.pending_deposits),
            pending_partial_withdrawals=list(pre.pending_partial_withdrawals),
            pending_consolidations=list(pre.pending_consolidations),
            # [New in Fulu:EIP7917]
            proposer_lookahead=self.initialize_proposer_lookahead(pre),
        )
        return post
