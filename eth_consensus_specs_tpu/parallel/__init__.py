"""Device-mesh parallelism.

The reference is a single-process executable spec with no distributed
backend at all (SURVEY §2.3: no NCCL/MPI/Gloo anywhere); its parallelism is
*latent* — per-validator, per-signature and per-chunk independence. Here
those latent axes become explicit mesh axes:

  * ``dp`` — the validator registry: epoch accounting, shuffling, signature
    batches shard their validator/attestation dimension here.
  * ``sp`` — the chunk/sequence axis: SSZ merkle leaf levels and field-FFT
    (KZG/DAS) vectors shard here.

Collectives ride ICI via XLA (psum / all_gather inserted by the SPMD
partitioner or written explicitly in shard_map kernels); multi-host scaling
is the same code over a DCN-backed mesh through jax.distributed.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

DP_AXIS = "dp"
SP_AXIS = "sp"


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """A 2D (dp, sp) mesh over the first n devices: sp = 2 when the count
    is even, else 1; dp takes the rest (the validator axis is the big one,
    so dp dominates by construction)."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    sp = 1
    if n % 2 == 0 and n >= 2:
        sp = 2
    dp = n // sp
    grid = np.asarray(devices).reshape(dp, sp)
    return Mesh(grid, (DP_AXIS, SP_AXIS))
