"""Sharded per-slot BLOCK processing — the dense block plane
(ops/block_epoch.py) over a device mesh.

Sharding layout: the mutable state plane (balance, participation
columns) shards over the flattened validator axes like every other
registry column (parallel/epoch.py); the per-slot block tensors
(committee indices, aggregation bits, sync bits, deposits) are SMALL —
~128 x committee u32s per slot — and replicate.

The interesting op is the scatter: a committee's validator indices span
every shard, so flag/balance scatters are GLOBAL. This module routes
them through jit + NamedSharding and lets XLA's SPMD partitioner insert
the communication (index-matched scatter lowering; on real meshes this
is an all-to-all-sized exchange proportional to the ATTESTING set, not
the registry). The scalable refinement — bucketing committee indices by
owning shard so each device scatters only its residents, the same trick
sharded embedding lookups use — drops in behind this function's
signature without changing callers.

Bit-exactness vs the unsharded kernel is asserted by
tests/test_parallel.py and the driver's dryrun_multichip."""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from eth_consensus_specs_tpu.ops.block_epoch import (
    BlockEpochParams,
    BlockState,
    process_slot_columnar,
)
from eth_consensus_specs_tpu.parallel import DP_AXIS, SP_AXIS

_VALIDATOR_AXES = (DP_AXIS, SP_AXIS)


def block_state_specs():
    """PartitionSpec pytree for BlockState: validator columns sharded,
    the withdrawal-pointer scalars replicated."""
    vec = P(_VALIDATOR_AXES)
    rep = P()
    return BlockState(
        balance=vec, cur_part=vec, prev_part=vec, next_wd_index=rep, next_wd_validator=rep
    )


def make_sharded_block_slot_fn(
    mesh: Mesh,
    params: BlockEpochParams,
    n: int,
    with_withdrawals: bool = True,
):
    """Jitted one-slot block step with the state plane sharded over the
    mesh and block inputs replicated.  Static per-epoch columns
    (base_reward, effective balances, withdrawal predicates) shard with
    the state."""
    st_spec = block_state_specs()
    vec = NamedSharding(mesh, P(_VALIDATOR_AXES))
    rep = NamedSharding(mesh, P())
    to_sh = lambda tree: jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), tree, is_leaf=lambda x: isinstance(x, P)
    )

    def step(st, slot_blk, base_reward, eff, wd_epoch, has_cred, epoch, part_r, prop_r):
        return process_slot_columnar(
            params,
            n,
            st,
            slot_blk,
            base_reward,
            eff,
            wd_epoch,
            has_cred,
            epoch,
            part_r,
            prop_r,
            with_withdrawals=with_withdrawals,
        )

    return jax.jit(
        step,
        in_shardings=(
            to_sh(st_spec),
            rep,  # the slot's block tensors (small, replicated)
            vec,  # base_reward
            vec,  # effective balances
            vec,  # withdrawable epochs
            vec,  # eth1-credential mask
            rep,
            rep,
            rep,
        ),
        out_shardings=to_sh(st_spec),
    )
