"""Mesh selection for the sharded hot-kernel dispatch: one serve layer, N chips.

The three hot kernels (BLS RLC pairing, multi-tree merkleization, G1 MSM)
accept an optional ``mesh``; this module is where the serve layer decides
WHICH mesh that is. One accessor, :func:`serve_mesh`, snapshots the env
knobs per call (never inside a traced function — jit-purity) and hands
back a cached ``(dp, sp)`` mesh over the chips the operator asked for:

    ETH_SPECS_MESH=0           disable sharded dispatch entirely (every
                               entry point falls back to the bit-identical
                               single-device path)
    ETH_SPECS_SERVE_CHIPS=N    chips the serve mesh spans (0/unset = every
                               local device); ``serve_bench.py --chips``
                               forces the matching virtual device count
    ETH_SPECS_MESH_MIN_ITEMS=K smallest live batch worth a sharded
                               dispatch (below it the single-device bucket
                               path is cheaper than the padding)

Batch axes shard over BOTH mesh axes (``PartitionSpec((dp, sp))``): the
hot kernels' batch dimensions (pairing chunks, trees, MSM items/lanes)
have no preferred axis, so the full device count is the shard count.
The mesh *signature* (``cpu4x2`` and friends) tags serve bucket shapes
and warmup keys — a replica must never replay another mesh's compiled
shapes (serve/buckets.py).
"""

from __future__ import annotations

import os

import jax
from jax.sharding import Mesh

from eth_consensus_specs_tpu import obs

from . import DP_AXIS, SP_AXIS, make_mesh

# the shard axes of every batch-sharded hot kernel: one logical axis over
# the whole device grid
BATCH_AXES = (DP_AXIS, SP_AXIS)

_MESH_CACHE: dict[int, Mesh] = {}


def _clear_cache_after_fork_in_child() -> None:
    # fork-safety: a gen-pool child must rebuild meshes against ITS
    # runtime's device objects, not the parent's
    _MESH_CACHE.clear()


os.register_at_fork(after_in_child=_clear_cache_after_fork_in_child)


def mesh_enabled() -> bool:
    return os.environ.get("ETH_SPECS_MESH", "1") != "0"


def chips_requested() -> int:
    """Operator-requested serve-mesh chip count; 0 = every local device."""
    raw = os.environ.get("ETH_SPECS_SERVE_CHIPS", "")
    try:
        return max(int(raw), 0) if raw else 0
    except ValueError:
        return 0


def min_items() -> int:
    """Smallest live batch a sharded dispatch is worth (crossover knob)."""
    raw = os.environ.get("ETH_SPECS_MESH_MIN_ITEMS", "")
    try:
        return max(int(raw), 1) if raw else 2
    except ValueError:
        return 2


def serve_mesh(chips: int | None = None) -> Mesh | None:
    """The serve layer's dispatch mesh, or None for the single-device
    path. ``chips`` overrides ``ETH_SPECS_SERVE_CHIPS`` (the bench builds
    a chips=1 and a chips=N service in one process); the count is capped
    at the local device count. Env is snapshotted per call — a flip
    mid-flush changes the NEXT dispatch, never a traced one.

    Multi-process runtimes (a replica that joined a pod slice via
    ``multihost.maybe_initialize_for_replica``) get the hybrid host-major
    mesh over EVERY process's devices instead of a local slice: the
    replica's mesh IS its pod slice, and the chips cap does not apply —
    per-replica width is a single-host concept."""
    if not mesh_enabled():
        return None
    if jax.process_count() > 1:
        mesh = _MESH_CACHE.get(-1)
        if mesh is None:
            from . import multihost

            mesh = _MESH_CACHE[-1] = multihost.make_hybrid_mesh()
            obs.gauge("mesh.devices", int(mesh.devices.size))
        return mesh
    n_local = len(jax.local_devices())
    want = chips_requested() if chips is None else max(int(chips), 0)
    n = min(want, n_local) if want else n_local
    if n < 2:
        return None
    mesh = _MESH_CACHE.get(n)
    if mesh is None:
        mesh = make_mesh(n)
        _MESH_CACHE[n] = mesh
        obs.gauge("mesh.devices", n)
        obs.event(
            "mesh.serve_mesh",
            devices=n,
            dp=int(mesh.shape[DP_AXIS]),
            sp=int(mesh.shape[SP_AXIS]),
            signature=mesh_signature(mesh),
        )
    return mesh


def shard_count(mesh: Mesh | None) -> int:
    """Total shards a batch axis splits into (1 for no mesh)."""
    if mesh is None:
        return 1
    return int(mesh.shape[DP_AXIS]) * int(mesh.shape[SP_AXIS])


def mesh_signature(mesh: Mesh | None) -> str:
    """Compact identity of a mesh for bucket/warmup keys: platform plus
    the (dp, sp) grid — ``cpu4x2``, ``tpu8x2``. Single-device dispatch
    has NO signature (bucket keys stay byte-compatible with every run
    before mesh dispatch existed)."""
    if mesh is None:
        return ""
    platform = next(iter(mesh.devices.flat)).platform
    return f"{platform}{int(mesh.shape[DP_AXIS])}x{int(mesh.shape[SP_AXIS])}"


def expected_mesh_shape(chips: int) -> tuple[int, int]:
    """The (dp, sp) grid ``make_mesh`` lays ``chips`` devices into —
    pure arithmetic, usable BEFORE any such mesh exists (the front door
    predicts a replica's grid while building its warm-key list)."""
    sp = 2 if chips % 2 == 0 and chips >= 2 else 1
    return chips // sp, sp


def expected_signature(chips: int, platform: str | None = None) -> str:
    """The mesh signature a replica spawned with ``chips`` devices will
    report, predicted PARENT-SIDE (same host, same platform) so warm-key
    lists can be built before the replica boots. The replica's ready
    profile is ground truth; a mismatch (e.g. a real-hardware host
    capping the count) only costs precompile skips, never a wrong
    compile."""
    if chips < 2 or not mesh_enabled():
        return ""
    if platform is None:
        platform = jax.local_devices()[0].platform
    dp, sp = expected_mesh_shape(chips)
    return f"{platform}{dp}x{sp}"


def pad_to_shards(n: int, shards: int) -> int:
    """Smallest multiple of ``shards`` >= n that keeps every shard
    non-empty — the divisibility floor every batch-sharded kernel pads
    to. Degenerate inputs (n == 0, or fewer items than shards) still pad
    to ONE item per shard: a zero-extent shard axis is an invalid
    shard_map operand shape, so the floor is `shards`, never 0."""
    return shards * max(-(-n // shards), 1)
