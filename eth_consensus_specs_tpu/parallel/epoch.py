"""Sharded epoch accounting: the validator axis over the mesh, explicit SPMD.

The columnar epoch kernel (ops/state_columns.py) is embarrassingly parallel
over validators except for a handful of scalar reductions (total/attesting
balances) and one scatter-add (proposer micro-rewards). This path runs the
SAME kernel body under shard_map, swapping the two reduction primitives for
collective-backed ones:

  * sum        -> local jnp.sum + lax.psum over the mesh axes (ICI all-reduce
                  of one u64 scalar);
  * scatter_add -> each shard scatters its contributions into a dense
                  global-length vector, one psum, then slices its own block
                  (proposer targets are global indices: attester i's earliest
                  includer can live on any shard).

Explicit shard_map (not auto-partitioning with NamedSharding annotations)
is deliberate: the u64 scatter under the SPMD partitioner sends XLA's
algebraic simplifier into a non-terminating rewrite loop on the CPU backend,
and on TPU the explicit form pins exactly the collectives we want — two
psums per epoch, nothing speculative.

Validator columns shard over BOTH mesh axes flattened (dp major, sp minor):
epoch accounting wants every chip, not just the dp slice.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from eth_consensus_specs_tpu.ops.altair_epoch import (
    AltairEpochColumns,
    AltairEpochParams,
    AltairEpochResult,
    altair_epoch_accounting_impl,
)
from eth_consensus_specs_tpu.ops.state_columns import (
    EpochColumns,
    EpochParams,
    EpochResult,
    JustificationState,
    epoch_accounting_impl,
)

from . import DP_AXIS, SP_AXIS

_VALIDATOR_AXES = (DP_AXIS, SP_AXIS)


class MeshReductions:
    """psum-backed reduction primitives for the epoch kernel under shard_map."""

    def __init__(self, mesh: Mesh, axes=_VALIDATOR_AXES):
        self.axes = axes
        self.n_shards = 1
        for a in axes:
            self.n_shards *= mesh.shape[a]
        # dp-major linearized shard id, matching P((dp, sp)) block order
        self.mesh = mesh

    def _shard_id(self):
        sid = lax.axis_index(self.axes[0])
        for a in self.axes[1:]:
            sid = sid * self.mesh.shape[a] + lax.axis_index(a)
        return sid

    def sum(self, x: jnp.ndarray) -> jnp.ndarray:
        return lax.psum(jnp.sum(x), self.axes)

    def scatter_add(self, idx: jnp.ndarray, amounts: jnp.ndarray, local_n: int) -> jnp.ndarray:
        """Cross-shard scatter-add via one dense global-length psum.

        NOTE: this is deliberately an O(n_validators) collective — the one
        reduction in the epoch kernel that is not a 32-byte scalar. At 1M
        validators it all-reduces 8 MB per epoch, which at ICI bandwidth
        (~100 GB/s/link) is ~0.1 ms — far below the epoch kernel's compute
        time, so the simple dense form wins until profiles say otherwise.
        The sparse alternative (ragged all_to_all of (index, amount) pairs
        bucketed by destination shard) trades that bandwidth for dynamic
        shapes XLA handles poorly; revisit only if multichip profiles show
        this psum dominating."""
        global_n = local_n * self.n_shards
        dense = (
            jnp.zeros(global_n, amounts.dtype)
            .at[jnp.clip(idx, 0, global_n - 1)]
            .add(amounts)
        )
        dense = lax.psum(dense, self.axes)
        start = (self._shard_id() * local_n).astype(jnp.int32)
        return lax.dynamic_slice(dense, (start,), (local_n,))


def epoch_specs():
    """(cols, just, result) PartitionSpec pytrees for shard_map."""
    vec = P(_VALIDATOR_AXES)
    rep = P()
    cols = EpochColumns(*([vec] * len(EpochColumns._fields)))
    just = JustificationState(*([rep] * len(JustificationState._fields)))
    result = EpochResult(
        balance=vec,
        effective_balance=vec,
        justification_bits=rep,
        prev_justified_epoch=rep,
        prev_justified_root=rep,
        cur_justified_epoch=rep,
        cur_justified_root=rep,
        finalized_epoch=rep,
        finalized_root=rep,
        rewards=vec,
        penalties=vec,
    )
    return cols, just, result


def sharded_epoch_fn(mesh: Mesh, params: EpochParams):
    """Traceable shard_map fn: (EpochColumns, JustificationState) ->
    EpochResult, validator columns sharded over all chips, scalars
    replicated. Global validator count must divide by the chip count."""
    cols_spec, just_spec, res_spec = epoch_specs()
    red = MeshReductions(mesh)

    def local(cols, just):
        return epoch_accounting_impl(params, cols, just, red)

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(cols_spec, just_spec),
        out_specs=res_spec,
        check_rep=False,
    )


def altair_epoch_specs(with_max_effective_balance: bool = False):
    """(cols, just, result) PartitionSpec pytrees for the altair+ kernel.
    The optional electra MaxEB column shards like the other validator
    vectors when present; None (pre-electra) contributes no leaves."""
    vec = P(_VALIDATOR_AXES)
    rep = P()
    cols = AltairEpochColumns(
        **{f: vec for f in AltairEpochColumns._fields if f != "max_effective_balance"},
        max_effective_balance=vec if with_max_effective_balance else None,
    )
    just = JustificationState(*([rep] * len(JustificationState._fields)))
    result = AltairEpochResult(
        balance=vec,
        effective_balance=vec,
        inactivity_scores=vec,
        justification_bits=rep,
        prev_justified_epoch=rep,
        prev_justified_root=rep,
        cur_justified_epoch=rep,
        cur_justified_root=rep,
        finalized_epoch=rep,
        finalized_root=rep,
    )
    return cols, just, result


def sharded_altair_epoch_fn(
    mesh: Mesh, params: AltairEpochParams, with_max_effective_balance: bool = False
):
    """Altair+ flag-based epoch kernel under shard_map — same collective
    shape as the phase0 path minus the proposer scatter (flags carry no
    inclusion-proposer attribution), so it is pure psum reductions."""
    cols_spec, just_spec, res_spec = altair_epoch_specs(with_max_effective_balance)
    red = MeshReductions(mesh)

    def local(cols, just):
        return altair_epoch_accounting_impl(params, cols, just, red)

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(cols_spec, just_spec),
        out_specs=res_spec,
        check_rep=False,
    )


def make_sharded_epoch_fn(mesh: Mesh, params: EpochParams):
    """Jitted sharded epoch with explicit input/output placements."""
    cols_spec, just_spec, res_spec = epoch_specs()
    to_sh = lambda tree: jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), tree, is_leaf=lambda x: isinstance(x, P)
    )
    return jax.jit(
        sharded_epoch_fn(mesh, params),
        in_shardings=(to_sh(cols_spec), to_sh(just_spec)),
        out_shardings=to_sh(res_spec),
    )
