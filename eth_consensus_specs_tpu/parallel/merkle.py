"""Sharded SSZ tree root: local subtree reduce -> all_gather -> tiny top.

The merkle tree over N chunks splits perfectly across devices: each device
owns a contiguous 2**k-leaf subtree (that's just a range of chunks), reduces
it locally with the fused level loop (ops/merkle.py:tree_root_words), and
one all_gather of the per-device subtree roots (32 bytes each) lets every
device finish the log2(n_devices)-level top redundantly — replicated output,
no further communication. Communication total: one 32B x n_devices
all_gather over ICI per tree, regardless of tree size.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from eth_consensus_specs_tpu.ops.merkle import tree_root_words

from . import SP_AXIS


def tree_root_sharded_fn(mesh: Mesh, depth: int, axis: str = SP_AXIS):
    """Build a traceable fn: uint32[2**depth, 8] (sharded on `axis`) ->
    uint32[8] root (replicated). Requires 2**depth % mesh.shape[axis] == 0
    and mesh.shape[axis] a power of two."""
    n_shards = mesh.shape[axis]
    assert n_shards & (n_shards - 1) == 0, "shard count must be a power of two"
    top_depth = (n_shards - 1).bit_length()
    local_depth = depth - top_depth
    assert local_depth >= 0, "tree shallower than the mesh axis"

    def local(leaves):
        sub_root = tree_root_words(leaves, local_depth)  # [8]
        roots = jax.lax.all_gather(sub_root, axis)  # [n_shards, 8]
        return tree_root_words(roots, top_depth)  # replicated [8]

    return shard_map(
        local,
        mesh=mesh,
        in_specs=P(axis),
        out_specs=P(),
        check_rep=False,
    )


def tree_root_sharded(mesh: Mesh, leaves: jnp.ndarray, depth: int) -> jnp.ndarray:
    """One-shot jitted sharded root (places `leaves` on the mesh)."""
    fn = jax.jit(
        tree_root_sharded_fn(mesh, depth),
        in_shardings=NamedSharding(mesh, P(SP_AXIS)),
        out_shardings=NamedSharding(mesh, P()),
    )
    return fn(leaves)
