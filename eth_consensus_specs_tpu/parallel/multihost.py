"""Multi-host scaling: jax.distributed bootstrap + hybrid ICI/DCN meshes.

The reference has no communication backend at all (SURVEY §2.3 — no
NCCL/MPI/Gloo anywhere; it is a single-process executable spec). Here the
"backend" is XLA collectives, and multi-host is the same SPMD code the
single-host meshes run, over a mesh whose axes are laid out so that the
high-traffic collectives ride ICI (within a host's chips) and only the
low-traffic ones cross DCN (between hosts):

  * ``dp`` (validator axis) spans HOSTS: the epoch kernel's cross-shard
    traffic is two psums per epoch — one u64 scalar and one dense
    O(n_validators) scatter-add (parallel/epoch.py MeshReductions) — a
    few MB/epoch, comfortably inside DCN budgets.
  * ``sp`` (chunk/sequence axis) stays WITHIN a host: the sharded merkle
    tree all-gathers per-device subtree roots every level pair
    (parallel/merkle.py), the latency-sensitive path that wants ICI.

This is the scaling-book recipe: pick the mesh, put bandwidth-hungry
axes on ICI, let pjit/shard_map insert the collectives.

Process bootstrap wraps `jax.distributed.initialize`, which speaks the
same coordinator protocol on TPU pods (host metadata autodetection) and
CPU/GPU clusters (explicit coordinator + process count, e.g. from a job
scheduler's env). Single-process callers get a no-op, so every entry
point in this module is safe to call unconditionally.
"""

from __future__ import annotations

import os

import jax
import numpy as np
from jax.sharding import Mesh

from eth_consensus_specs_tpu import obs

from . import DP_AXIS, SP_AXIS

_initialized = False


def _runtime_client():
    """The live ``jax.distributed`` client (or None) WITHOUT touching the
    local backend: ``jax.process_count()`` would finalize the runtime,
    after which ``jax.distributed.initialize`` refuses to run at all —
    the probe must not destroy what it probes for."""
    from jax._src import distributed as _dist

    return getattr(_dist.global_state, "client", None)


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Join (or skip joining) the multi-host runtime. Returns True when a
    multi-process runtime is live after the call.

    Resolution order: explicit args > JAX_COORDINATOR_ADDRESS /
    JAX_NUM_PROCESSES / JAX_PROCESS_ID env > TPU-pod autodetection
    (jax.distributed.initialize with no args works on TPU pods) > no-op
    single process."""
    global _initialized
    if _initialized or _runtime_client() is not None:
        # joined already (here, or by an external bootstrap)
        _initialized = True
        return jax.process_count() > 1
    with obs.span("multihost.initialize"):
        live = _initialize_distributed(coordinator_address, num_processes, process_id)
    obs.count("multihost.initializations", 1)
    obs.count("multihost.processes", jax.process_count())
    return live


def _initialize_distributed(
    coordinator_address: str | None,
    num_processes: int | None,
    process_id: int | None,
) -> bool:
    global _initialized
    coordinator_address = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if num_processes is None and "JAX_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and "JAX_PROCESS_ID" in os.environ:
        process_id = int(os.environ["JAX_PROCESS_ID"])
    if coordinator_address is None and num_processes is None:
        # no explicit cluster config: on a TPU pod slice, initialize()
        # autodetects; everywhere else stay single-process
        if jax.default_backend() in ("tpu", "axon"):
            try:
                jax.distributed.initialize()
                _initialized = True
            except Exception as exc:
                # autodetection failing on a pod slice is a real operational
                # signal (mis-set env, dead coordinator) — leave a breadcrumb
                # instead of degrading to single-process silently
                obs.count("multihost.init_failures", 1)
                obs.event("multihost.init_failed", error=repr(exc)[:200])
                return False
        return jax.process_count() > 1
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    return jax.process_count() > 1


def maybe_initialize_for_replica() -> bool:
    """The replica-boot seam of the two-tier fleet: with
    ``ETH_SPECS_SERVE_DISTRIBUTED=1`` a spawned replica joins the
    multi-host runtime (coordinator env / TPU-pod autodetection, see
    :func:`initialize_distributed`) BEFORE building its service, so its
    serve mesh becomes a whole pod slice instead of a local-device
    slice. Single-host fleets (the default) skip the bootstrap entirely
    — no env, no-op. Returns True when a multi-process runtime is
    live."""
    if os.environ.get("ETH_SPECS_SERVE_DISTRIBUTED") != "1":
        return False
    return initialize_distributed()


def make_hybrid_mesh(sp_per_host: int | None = None) -> Mesh:
    """A (dp, sp) mesh laid out host-major: sp varies WITHIN each host's
    devices (collective-heavy axis on ICI), dp spans hosts (scalar psums
    cross DCN).

    Single-process fallback degrades to the flat make_mesh layout, so
    tests and the virtual CPU mesh exercise the same entry point."""
    devices = jax.devices()
    n_local = len(jax.local_devices())
    n_hosts = max(jax.process_count(), 1)
    if sp_per_host is None:
        sp_per_host = 2 if n_local % 2 == 0 and n_local >= 2 else 1
    if n_hosts <= 1:
        from . import make_mesh

        obs.count("multihost.meshes_flat", 1)
        return make_mesh()
    # [host, local] grid: host-major ordering keeps each host's devices
    # contiguous along the trailing (sp) axis
    dp_per_host = n_local // sp_per_host
    grid = np.asarray(devices).reshape(n_hosts * dp_per_host, sp_per_host)
    obs.count("multihost.meshes_hybrid", 1)
    obs.event(
        "multihost.mesh",
        dp=n_hosts * dp_per_host,
        sp=sp_per_host,
        hosts=n_hosts,
        devices=len(devices),
    )
    return Mesh(grid, (DP_AXIS, SP_AXIS))


class ShardRemainderError(ValueError):
    """`n_global` does not divide the mesh's shard count — an even
    per-shard split would silently orphan the remainder rows. Pad the
    global axis to :func:`padded_global` (and pass ``pad=True``) or keep
    the axis divisible."""

    def __init__(self, n_global: int, n_shards: int):
        self.n_global = n_global
        self.n_shards = n_shards
        self.remainder = n_global % n_shards
        super().__init__(
            f"n_global={n_global} leaves {self.remainder} rows beyond an even "
            f"{n_shards}-shard split; pad to {padded_global(n_global, n_shards)} "
            "(host_local_slice(..., pad=True) slices the padded domain) or "
            "keep the axis divisible"
        )


def padded_global(n_global: int, n_shards: int) -> int:
    """Smallest multiple of the shard count >= n_global — the padded
    domain ``host_local_slice(..., pad=True)`` slices."""
    return n_shards * -(-n_global // n_shards)


def host_local_slice(mesh: Mesh, n_global: int, pad: bool = False) -> tuple[int, int]:
    """[start, stop) of the validator rows this process owns under a
    dp-sharded array on `mesh` — the addressable block a host feeds or
    reads without cross-host transfers (jax.Array per-shard semantics).

    A `n_global` that does not divide the shard count used to silently
    truncate: every shard got ``n_global // n_shards`` rows and the
    remainder belonged to nobody. Now the remainder is counted
    (``multihost.slice_remainder``) and either raises the typed
    :class:`ShardRemainderError` (default) or, with ``pad=True``, slices
    the :func:`padded_global` domain — callers pad their arrays to it,
    exactly like the kernels pad their batch axes."""
    n_shards = mesh.shape[DP_AXIS] * mesh.shape[SP_AXIS]
    rem = n_global % n_shards
    if rem:
        obs.count("multihost.slice_remainder", rem)
        obs.event(
            "multihost.slice_remainder",
            n_global=int(n_global),
            n_shards=int(n_shards),
            remainder=int(rem),
            padded=bool(pad),
        )
        if not pad:
            raise ShardRemainderError(n_global, n_shards)
    per = padded_global(n_global, n_shards) // n_shards if rem else n_global // n_shards
    local_ids = {
        i for i, d in enumerate(mesh.devices.flat) if d.process_index == jax.process_index()
    }
    if not local_ids:
        # a process can legitimately own no devices of this mesh (e.g. a
        # coordinator-only host, or a mesh built from a device subset):
        # its addressable block is empty, not a min()-over-nothing crash
        obs.event(
            "multihost.no_local_devices",
            process=jax.process_index(),
            mesh_devices=int(mesh.devices.size),
        )
        return 0, 0
    lo, hi = min(local_ids), max(local_ids)
    return lo * per, (hi + 1) * per
