"""Device-resident multi-epoch state advance — the framework API for the
BASELINE.json north star (state_transition epoch work at 1M validators in
device memory, no per-epoch host round-trips).

Round-2 verdict weak #3: the 1M-validator resident loop existed only as
hand-rolled bench code.  This module is that loop as a public, reusable
surface:

* ``ingest(spec, state)`` — ONE extraction of the object state into device
  columns (the columnar epoch's extract, device_put once);
* ``run_epochs(spec, cols, just, n_epochs, with_root=...)`` — N accounting
  epochs chained inside one jit (each epoch consumes the previous epoch's
  balances; optional per-epoch SSZ subtree root of the balance column via
  the fused device tree), state never leaving HBM;
* ``writeback(spec, state, carry)`` — final columns applied back onto the
  object view.

The epoch body is the altair+ fused kernel (ops/altair_epoch.py) — the
same code the spec-level default `process_epoch_columnar` dispatches to —
so resident results match the object path wherever the kernel does
(columnar oracle tests).  Registry updates / queues are spec-level,
per-boundary work and are NOT folded into the resident loop; this API
covers the O(N·epochs) accounting plane the reference spends its epoch
time in (reference hot spots: specs/phase0/beacon-chain.md:1527+,
process_rewards_and_penalties; hash_tree_root per slot :1383-1393).
"""

from __future__ import annotations

from functools import lru_cache
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from eth_consensus_specs_tpu.ops.altair_epoch import (
    AltairEpochColumns,
    AltairEpochParams,
    altair_epoch_accounting_impl,
)
from eth_consensus_specs_tpu.ops.merkle import tree_root_words
from eth_consensus_specs_tpu.ops.state_columns import JustificationState


class ResidentCarry(NamedTuple):
    cols: AltairEpochColumns
    just: JustificationState
    root_acc: jnp.ndarray  # xor-chain of per-epoch balance roots (u32[8])


def ingest(spec, state) -> tuple[AltairEpochColumns, JustificationState]:
    """One host->device extraction of the columnar epoch inputs."""
    cols, just = spec.extract_epoch_columns(state)
    return jax.device_put(cols), jax.device_put(just)


def _balance_leaves(bal: jnp.ndarray, n: int) -> jnp.ndarray:
    """u64 balances -> SSZ chunk words (shared swizzle, ops/state_root)."""
    from eth_consensus_specs_tpu.ops.state_root import packed_u64_leaves

    return packed_u64_leaves(bal, n)


def ingest_full(spec, state):
    """ingest() plus the static full-state tree content for
    with_root="state" (ops/state_root.build_static): per-validator static
    nodes, harvested small-field roots, zero-hash table — one host pass,
    device-resident thereafter."""
    from eth_consensus_specs_tpu.ops.state_root import build_static

    cols, just = ingest(spec, state)
    return cols, just, build_static(spec, state)


def run_epochs(
    spec,
    cols: AltairEpochColumns,
    just: JustificationState,
    n_epochs: int,
    with_root=True,
    static=None,
):
    """Advance `n_epochs` accounting epochs entirely on device.

    Each epoch's balances/scores/justification feed the next. Rooting
    modes (xor-chained into the carry — true sequential dependency, also
    the honest-bench measurement shape):

    * ``with_root=False``   — no rooting;
    * ``with_root=True``    — the balance column's SSZ subtree root
      (round-3 behavior);
    * ``with_root="state"`` — the FULL post-epoch BeaconState root via
      dirty-path rehash (ops/state_root.py): per-validator subtrees
      recomputed from 3 hashes each, big columns re-treed, every other
      field a static chunk. Requires ``static`` from ingest_full().
      Exactness caveat: the root is the object-path hash_tree_root for
      the FIRST epoch (tests/test_state_root_device.py); later chained
      epochs keep the stand-in participation (the resident loop does not
      rotate flags), so their roots are the same tree shape/work but not
      a state any object advance produces — fine for benching, not for
      consensus use beyond epoch 1.

    Returns a ResidentCarry of device arrays."""
    params = AltairEpochParams.from_spec(spec)
    n = int(cols.balance.shape[0])
    if with_root is True or with_root == "balance":
        mode = "balance"
    elif with_root is False or with_root is None or with_root == "none":
        mode = "none"
    elif with_root == "state":
        mode = "state"
    else:
        raise ValueError(f"with_root must be bool, 'balance' or 'state', got {with_root!r}")
    depth = (max(n // 4, 1) - 1).bit_length() if mode == "balance" else 0
    if mode == "balance" and n % 4 != 0:
        raise ValueError("with_root requires a multiple-of-4 validator count")
    if mode == "state" and static is None:
        raise ValueError('with_root="state" requires static from ingest_full()')
    if mode == "state":
        arrays, meta = static
        run = _compiled_runner(params, int(n_epochs), mode, n, depth, meta)
        out_cols, out_just, acc = run(cols, just, jnp.zeros(8, jnp.uint32), arrays)
    else:
        run = _compiled_runner(params, int(n_epochs), mode, n, depth, None)
        out_cols, out_just, acc = run(cols, just, jnp.zeros(8, jnp.uint32))
    return ResidentCarry(cols=out_cols, just=out_just, root_acc=acc)


@lru_cache(maxsize=None)
def _compiled_runner(params, n_epochs: int, mode: str, n: int, depth: int, meta):
    """One compiled executable per (params, epochs, shape) — repeat calls
    reuse it instead of retracing."""

    def _advance(cols, just):
        res = altair_epoch_accounting_impl(params, cols, just)
        cols = cols._replace(
            balance=res.balance,
            effective_balance=res.effective_balance,
            inactivity_scores=res.inactivity_scores,
        )
        just = just._replace(
            current_epoch=just.current_epoch + jnp.uint64(1),
            justification_bits=res.justification_bits,
            prev_justified_epoch=res.prev_justified_epoch,
            prev_justified_root=res.prev_justified_root,
            cur_justified_epoch=res.cur_justified_epoch,
            cur_justified_root=res.cur_justified_root,
            finalized_epoch=res.finalized_epoch,
            finalized_root=res.finalized_root,
        )
        return cols, just

    if mode == "state":

        @jax.jit
        def run_state(cols, just, acc0, arrays):
            from eth_consensus_specs_tpu.ops.state_root import post_epoch_state_root

            def body(_, carry):
                cols, just, acc = carry
                cols, just = _advance(cols, just)
                root = post_epoch_state_root(
                    arrays,
                    meta,
                    cols.balance,
                    cols.effective_balance,
                    cols.inactivity_scores,
                    just,
                )
                return cols, just, acc ^ root

            return lax.fori_loop(0, n_epochs, body, (cols, just, acc0))

        return run_state

    @jax.jit
    def run(cols, just, acc0):
        def body(_, carry):
            cols, just, acc = carry
            cols, just = _advance(cols, just)
            if mode == "balance":
                root = tree_root_words(_balance_leaves(cols.balance, n), depth)
                acc = acc ^ root
            return cols, just, acc

        return lax.fori_loop(0, n_epochs, body, (cols, just, acc0))

    return run


def writeback(spec, state, carry: ResidentCarry) -> None:
    """Apply the resident columns back onto the object state (balances,
    effective balances, inactivity scores, justification scalars)."""
    import numpy as np

    from eth_consensus_specs_tpu.ops.altair_epoch import AltairEpochResult

    res = jax.tree_util.tree_map(np.asarray, carry)
    cols, just = res.cols, res.just
    shim = AltairEpochResult(
        balance=cols.balance,
        effective_balance=cols.effective_balance,
        inactivity_scores=cols.inactivity_scores,
        justification_bits=just.justification_bits,
        prev_justified_epoch=just.prev_justified_epoch,
        prev_justified_root=just.prev_justified_root,
        cur_justified_epoch=just.cur_justified_epoch,
        cur_justified_root=just.cur_justified_root,
        finalized_epoch=just.finalized_epoch,
        finalized_root=just.finalized_root,
    )
    spec._writeback_justification(state, shim)
    spec._writeback_balances(state, shim)
    spec._writeback_extra(state, shim)
