"""Device-resident multi-epoch state advance — the framework API for the
BASELINE.json north star (state_transition epoch work at 1M validators in
device memory, no per-epoch host round-trips).

Round-2 verdict weak #3: the 1M-validator resident loop existed only as
hand-rolled bench code.  This module is that loop as a public, reusable
surface:

* ``ingest(spec, state)`` — ONE extraction of the object state into device
  columns (the columnar epoch's extract, device_put once);
* ``run_epochs(spec, cols, just, n_epochs, with_root=...)`` — N accounting
  epochs chained inside one jit (each epoch consumes the previous epoch's
  balances; optional per-epoch SSZ subtree root of the balance column via
  the fused device tree), state never leaving HBM;
* ``writeback(spec, state, carry)`` — final columns applied back onto the
  object view.

The epoch body is the altair+ fused kernel (ops/altair_epoch.py) — the
same code the spec-level default `process_epoch_columnar` dispatches to —
so resident results match the object path wherever the kernel does
(columnar oracle tests).  Registry updates / queues are spec-level,
per-boundary work and are NOT folded into the resident loop; this API
covers the O(N·epochs) accounting plane the reference spends its epoch
time in (reference hot spots: specs/phase0/beacon-chain.md:1527+,
process_rewards_and_penalties; hash_tree_root per slot :1383-1393).
"""

from __future__ import annotations

import os
from functools import lru_cache
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from eth_consensus_specs_tpu import obs
from eth_consensus_specs_tpu.ops.altair_epoch import (
    AltairEpochColumns,
    AltairEpochParams,
    altair_epoch_accounting_impl,
)
from eth_consensus_specs_tpu.ops.merkle import tree_root_words
from eth_consensus_specs_tpu.ops.state_columns import JustificationState


class ResidentCarry(NamedTuple):
    cols: AltairEpochColumns
    just: JustificationState
    root_acc: jnp.ndarray  # xor-chain of per-epoch balance roots (u32[8])
    # incremental mode only: the updated merkle_inc forest (the input
    # forest's buffers were DONATED to the run — thread this one into
    # the next run_epochs call, never reuse the old object)
    forest: object = None


def _ledger_register(owner: str, name: str, tree) -> None:
    """Book a device pytree's bytes in the HBM residency ledger
    (obs/ledger.py) — host-level accounting only, never raises."""
    try:
        from eth_consensus_specs_tpu.obs import ledger

        nbytes = sum(
            int(getattr(a, "nbytes", 0)) for a in jax.tree_util.tree_leaves(tree)
        )
        if nbytes > 0:
            ledger.register(owner, name, nbytes)
    except Exception:
        pass


def ingest(spec, state) -> tuple[AltairEpochColumns, JustificationState]:
    """One host->device extraction of the columnar epoch inputs."""
    cols, just = spec.extract_epoch_columns(state)
    cols, just = jax.device_put(cols), jax.device_put(just)
    _ledger_register("resident_state", "columns", cols)
    _ledger_register("resident_state", "justification", just)
    return cols, just


def _balance_leaves(bal: jnp.ndarray, n: int) -> jnp.ndarray:
    """u64 balances -> SSZ chunk words (shared swizzle, ops/state_root)."""
    from eth_consensus_specs_tpu.ops.state_root import packed_u64_leaves

    return packed_u64_leaves(bal, n)


def ingest_full(spec, state):
    """ingest() plus the static full-state tree content for
    with_root="state" (ops/state_root.build_static): per-validator static
    nodes, harvested small-field roots, zero-hash table — one host pass,
    device-resident thereafter."""
    from eth_consensus_specs_tpu.ops.state_root import build_static

    cols, just = ingest(spec, state)
    # build_static registers its own resident_state ledger entry
    return cols, just, build_static(spec, state)


def forest_plan_for(static, mesh=None, dirty_cap: int | None = None):
    """The incremental plan run_epochs and build_state_forest_device
    share for one (registry shape, mesh, capacity hint) — ONE derivation
    so a forest built here always matches the runner compiled there."""
    from eth_consensus_specs_tpu.ops.state_root import forest_plan

    return forest_plan(static[1], mesh=mesh, dirty_cap=dirty_cap)


def build_state_forest_device(
    static, cols: AltairEpochColumns, mesh=None, dirty_cap: int | None = None
):
    """One-time device forest ingest for ``with_root="state_inc"``: all
    internal levels of the three big subtrees + the static participation
    list root, built from the CURRENT columns (the pre-epoch state the
    first epoch diffs against). Returns (forest, plan). The forest's
    buffers are donated to the first run_epochs call that consumes them —
    thread ``carry.forest`` forward for chained calls."""
    arrays, meta = static
    plan = forest_plan_for(static, mesh=mesh, dirty_cap=dirty_cap)
    build = _compiled_forest_builder(plan, meta)
    forest = build(
        jax.device_put(arrays),
        cols.balance,
        cols.effective_balance,
        cols.inactivity_scores,
    )
    _ledger_register("merkle_forest", "forest", forest)
    return forest, plan


@lru_cache(maxsize=None)
def _compiled_forest_builder(plan, meta):
    import jax

    from eth_consensus_specs_tpu.ops.state_root import build_state_forest

    @jax.jit
    def build(arrays, balances, effective_balance, inactivity_scores):
        return build_state_forest(
            arrays, meta, plan, balances, effective_balance, inactivity_scores
        )

    return build


def run_epochs(
    spec,
    cols: AltairEpochColumns,
    just: JustificationState,
    n_epochs: int,
    with_root=True,
    static=None,
    forest=None,
    mesh=None,
    dirty_cap: int | None = None,
):
    """Advance `n_epochs` accounting epochs entirely on device.

    Each epoch's balances/scores/justification feed the next. Rooting
    modes (xor-chained into the carry — true sequential dependency, also
    the honest-bench measurement shape):

    * ``with_root=False``   — no rooting;
    * ``with_root=True``    — the balance column's SSZ subtree root
      (round-3 behavior);
    * ``with_root="state"`` — the FULL post-epoch BeaconState root via
      dirty-path rehash (ops/state_root.py): per-validator subtrees
      recomputed from 3 hashes each, big columns re-treed, every other
      field a static chunk. Requires ``static`` from ingest_full().
      Exactness caveat: the root is the object-path hash_tree_root for
      the FIRST epoch (tests/test_state_root_device.py); later chained
      epochs keep the stand-in participation (the resident loop does not
      rotate flags), so their roots are the same tree shape/work but not
      a state any object advance produces — fine for benching, not for
      consensus use beyond epoch 1.
    * ``with_root="state_inc"`` — the SAME full state root, bit for bit,
      through the incremental merkle_inc forest: each epoch diffs the
      columns against the previous epoch's, marks the dirty leaves
      inside the jitted chain, and re-hashes only O(dirty x depth)
      ancestor nodes per tree (dense rebuild past the measured
      crossover). Requires ``static``; ``forest`` from
      build_state_forest_device (built automatically when omitted —
      outside any timing), ``mesh`` shards the forest leaf axes over
      the serve mesh, ``dirty_cap`` overrides the pow2 dirty-capacity
      bucket hint. The input forest's buffers are DONATED; chain from
      ``carry.forest``.

    Returns a ResidentCarry of device arrays."""
    from eth_consensus_specs_tpu.serve import buckets as serve_buckets

    params = AltairEpochParams.from_spec(spec)
    n = int(cols.balance.shape[0])
    if with_root is True or with_root == "balance":
        mode = "balance"
    elif with_root is False or with_root is None or with_root == "none":
        mode = "none"
    elif with_root in ("state", "state_inc"):
        mode = with_root
    else:
        raise ValueError(
            f"with_root must be bool, 'balance', 'state' or 'state_inc', got {with_root!r}"
        )
    depth = (max(n // 4, 1) - 1).bit_length() if mode == "balance" else 0
    if mode == "balance" and n % 4 != 0:
        raise ValueError("with_root requires a multiple-of-4 validator count")
    if mode in ("state", "state_inc") and static is None:
        raise ValueError(f'with_root={mode!r} requires static from ingest_full()')

    col_bytes = 2 * sum(a.nbytes for a in jax.tree_util.tree_leaves(cols))
    if mode == "state_inc":
        from eth_consensus_specs_tpu.ops.state_root import state_root_inc_real_hashes

        arrays, meta = static
        plan = forest_plan_for(static, mesh=mesh, dirty_cap=dirty_cap)
        if forest is None:
            forest, _ = build_state_forest_device(
                static, cols, mesh=mesh, dirty_cap=dirty_cap
            )
        real = state_root_inc_real_hashes(meta, plan)
        run = _compiled_runner(
            params, int(n_epochs), mode, n, depth, meta, plan, mesh
        )
        key = ("resident", mode, n, int(n_epochs), plan.cap_val, plan.cap_bal)
        from eth_consensus_specs_tpu.parallel.mesh_ops import mesh_signature

        if plan.shards > 1:
            key = (*key, mesh_signature(mesh))
        with obs.span(
            "resident.run_epochs",
            work_bytes=int(n_epochs) * (col_bytes + 96 * real),
            n_validators=n,
            epochs=int(n_epochs),
            mode=mode,
            shards=plan.shards,
        ) as sp:
            with serve_buckets.first_dispatch(*key):
                out_cols, out_just, acc, out_forest = run(
                    cols, just, jnp.zeros(8, jnp.uint32), jax.device_put(arrays), forest
                )
            sp.result = acc
        obs.count("state_root.inc_roots", int(n_epochs))
        obs.count("state_root.inc_real_hashes", int(n_epochs) * real)
        # the ledger mirrors the donation: the input forest's buffers were
        # consumed by the run (donate_argnums above), the out_forest is the
        # resident tree going forward — net footprint stays flat, and the
        # hbm.donations counter records that the alias actually happened
        try:
            from eth_consensus_specs_tpu.obs import ledger

            ledger.donate("merkle_forest", "forest")
        except Exception:
            pass
        _ledger_register("merkle_forest", "forest", out_forest)
        return ResidentCarry(
            cols=out_cols, just=out_just, root_acc=acc, forest=out_forest
        )
    if mode == "state":
        from eth_consensus_specs_tpu.ops.state_root import state_root_real_hashes

        arrays, meta = static
        real = state_root_real_hashes(meta)
        run = _compiled_runner(params, int(n_epochs), mode, n, depth, meta, None, None)
        with obs.span(
            "resident.run_epochs",
            work_bytes=int(n_epochs) * (col_bytes + 96 * real),
            n_validators=n,
            epochs=int(n_epochs),
            mode=mode,
        ) as sp:
            with serve_buckets.first_dispatch("resident", mode, n, int(n_epochs)):
                out_cols, out_just, acc = run(cols, just, jnp.zeros(8, jnp.uint32), arrays)
            sp.result = acc
    else:
        run = _compiled_runner(params, int(n_epochs), mode, n, depth, None, None, None)
        with serve_buckets.first_dispatch("resident", mode, n, int(n_epochs)):
            out_cols, out_just, acc = run(cols, just, jnp.zeros(8, jnp.uint32))
    return ResidentCarry(cols=out_cols, just=out_just, root_acc=acc)


@lru_cache(maxsize=None)
def _compiled_runner(params, n_epochs: int, mode: str, n: int, depth: int, meta,
                     plan, mesh):
    """One compiled executable per (params, epochs, shape[, forest plan,
    mesh]) — repeat calls reuse it instead of retracing."""

    def _advance(cols, just):
        res = altair_epoch_accounting_impl(params, cols, just)
        cols = cols._replace(
            balance=res.balance,
            effective_balance=res.effective_balance,
            inactivity_scores=res.inactivity_scores,
        )
        just = just._replace(
            current_epoch=just.current_epoch + jnp.uint64(1),
            justification_bits=res.justification_bits,
            prev_justified_epoch=res.prev_justified_epoch,
            prev_justified_root=res.prev_justified_root,
            cur_justified_epoch=res.cur_justified_epoch,
            cur_justified_root=res.cur_justified_root,
            finalized_epoch=res.finalized_epoch,
            finalized_root=res.finalized_root,
        )
        return cols, just

    if mode == "state_inc":
        from functools import partial

        # the forest is DONATED: epoch chains update the resident tree
        # levels in place instead of doubling the footprint (jaxlint's
        # donation-audit proves the alias on the registered kernels)
        @partial(jax.jit, donate_argnums=(4,))
        def run_state_inc(cols, just, acc0, arrays, forest):
            from eth_consensus_specs_tpu.ops.state_root import (
                post_epoch_state_root_inc,
            )

            def body(_, carry):
                cols, just, acc, forest = carry
                old = (cols.balance, cols.effective_balance, cols.inactivity_scores)
                cols, just = _advance(cols, just)
                forest, root = post_epoch_state_root_inc(
                    arrays,
                    meta,
                    plan,
                    forest,
                    *old,
                    cols.balance,
                    cols.effective_balance,
                    cols.inactivity_scores,
                    just,
                    mesh=mesh,
                )
                return cols, just, acc ^ root, forest

            return lax.fori_loop(0, n_epochs, body, (cols, just, acc0, forest))

        return run_state_inc

    if mode == "state":

        @jax.jit
        def run_state(cols, just, acc0, arrays):
            from eth_consensus_specs_tpu.ops.state_root import post_epoch_state_root

            def body(_, carry):
                cols, just, acc = carry
                cols, just = _advance(cols, just)
                root = post_epoch_state_root(
                    arrays,
                    meta,
                    cols.balance,
                    cols.effective_balance,
                    cols.inactivity_scores,
                    just,
                )
                return cols, just, acc ^ root

            return lax.fori_loop(0, n_epochs, body, (cols, just, acc0))

        return run_state

    @jax.jit
    def run(cols, just, acc0):
        def body(_, carry):
            cols, just, acc = carry
            cols, just = _advance(cols, just)
            if mode == "balance":
                root = tree_root_words(_balance_leaves(cols.balance, n), depth)
                acc = acc ^ root
            return cols, just, acc

        return lax.fori_loop(0, n_epochs, body, (cols, just, acc0))

    return run


def _clear_compiled_after_fork_in_child() -> None:
    # fork-safety: cached executables (incl. mesh state_inc runners and
    # forest builders) reference the parent's device objects — a forked
    # gen-pool child must retrace against ITS runtime, same as every
    # other kernel cache (ops/merkle.py, ops/merkle_inc.py, mesh_ops)
    _compiled_runner.cache_clear()
    _compiled_forest_builder.cache_clear()


os.register_at_fork(after_in_child=_clear_compiled_after_fork_in_child)


def writeback(spec, state, carry: ResidentCarry) -> None:
    """Apply the resident columns back onto the object state (balances,
    effective balances, inactivity scores, justification scalars)."""
    import numpy as np

    from eth_consensus_specs_tpu.ops.altair_epoch import AltairEpochResult

    res = jax.tree_util.tree_map(np.asarray, carry)
    cols, just = res.cols, res.just
    shim = AltairEpochResult(
        balance=cols.balance,
        effective_balance=cols.effective_balance,
        inactivity_scores=cols.inactivity_scores,
        justification_bits=just.justification_bits,
        prev_justified_epoch=just.prev_justified_epoch,
        prev_justified_root=just.prev_justified_root,
        cur_justified_epoch=just.cur_justified_epoch,
        cur_justified_root=just.cur_justified_root,
        finalized_epoch=just.finalized_epoch,
        finalized_root=just.finalized_root,
    )
    spec._writeback_justification(state, shim)
    spec._writeback_balances(state, shim)
    spec._writeback_extra(state, shim)


def run_epochs_checkpointed(
    spec,
    cols: AltairEpochColumns,
    just: JustificationState,
    n_epochs: int,
    *,
    static,
    forest=None,
    mesh=None,
    dirty_cap: int | None = None,
    ckpt_dir: str | None = None,
    ckpt_interval: int = 0,
    epoch0: int = 0,
    incremental: bool = True,
):
    """``run_epochs(with_root="state_inc")`` in interval-sized chunks
    with a durable checkpoint after each chunk — the checkpoint hook of
    the durable-resident-state subsystem (ops/snapshot.py). Each chunk
    threads ``carry.forest`` forward through the donated jit chain; the
    checkpoint itself runs OUTSIDE it (host fetch + verified writes),
    so the resident buffers are never aliased mid-write. Returns
    ``(carry, root_bytes, epoch)`` where root_bytes is the canonical
    combined state root of the FINAL state (the same digest gate a
    restore verifies against) and epoch is ``epoch0 + n_epochs``.

    ``ckpt_interval <= 0`` (or no ``ckpt_dir``) degenerates to one
    uncheckpointed run — same arithmetic, same donation discipline."""
    from eth_consensus_specs_tpu.ops import snapshot

    if forest is None:
        forest, _ = build_state_forest_device(
            static, cols, mesh=mesh, dirty_cap=dirty_cap
        )
    plan = forest_plan_for(static, mesh=mesh, dirty_cap=dirty_cap)
    carry = ResidentCarry(cols=cols, just=just, root_acc=None, forest=forest)
    epoch = int(epoch0)
    remaining = int(n_epochs)
    step = int(ckpt_interval) if (ckpt_dir and ckpt_interval > 0) else remaining
    while remaining > 0:
        chunk = min(step, remaining)
        carry = run_epochs(
            spec,
            carry.cols,
            carry.just,
            chunk,
            with_root="state_inc",
            static=static,
            forest=carry.forest,
            mesh=mesh,
            dirty_cap=dirty_cap,
        )
        epoch += chunk
        remaining -= chunk
        if ckpt_dir:
            snapshot.checkpoint(
                ckpt_dir,
                carry.forest,
                carry.cols,
                carry.just,
                epoch=epoch,
                plan=plan,
                static=static,
                epoch0=int(epoch0),
                incremental=incremental,
            )
    root = snapshot.state_root_bytes(static, plan, carry.forest, carry.just)
    return carry, root, epoch
