"""eth_consensus_specs_tpu — a TPU-native executable-spec framework for the
Ethereum proof-of-stake consensus layer.

Built from scratch against the behavior of the reference executable spec
(eth-consensus-specs); the compute hot spots (SSZ merkleization, BLS12-381,
swap-or-not shuffling, KZG/DAS field FFTs) run on TPU via JAX/XLA, everything
else is first-party Python/C++.

Layout:
  ssz/        SSZ type system: serialization, merkleization, proofs
  ops/        device kernels (JAX/Pallas): sha256, shuffle, bls limb math, fft
  parallel/   mesh + sharding helpers, distributed batch primitives
  utils/      bls backend switch, hash, kzg setup tooling, merkle helpers
  config/     two-tier preset (compile-time sizes) / config (runtime) system
  forks/      per-fork spec modules (phase0, altair, ...) as a class hierarchy
  compiler/   fork-composition + markdown-spec ingestion pipeline
  test_infra/ decorator/fixture engine + dual-mode yield protocol
  gen/        reference-test vector generation (runner tree, snappy dumper)
"""

__version__ = "0.1.0"

# All spec arithmetic is uint64 with overflow-as-invalid semantics
# (reference: specs/phase0/beacon-chain.md:1339-1344); the framework is
# unusable under JAX's default 32-bit promotion, so x64 is a hard
# requirement, enabled here — at the package root, before any backend
# initializes — rather than deep inside a lazily-imported kernel module.
import jax as _jax

_jax.config.update("jax_enable_x64", True)
del _jax
