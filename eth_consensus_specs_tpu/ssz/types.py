"""SSZ type system: typed views with serialization + merkleization.

First-party implementation of SimpleSerialize semantics (reference spec:
ssz/simple-serialize.md:189-433; reference runtime: the external
`remerkleable` package re-exported via
tests/core/pyspec/eth2spec/utils/ssz/ssz_typing.py:3-37).

Design notes (TPU-first, not a remerkleable port):
  * Values are plain Python objects (int/bytes subclasses, element lists),
    not persistent binary trees; merkleization happens level-synchronously
    over numpy chunk matrices so large flat regions batch onto the device
    kernel (ssz/merkle.py + ops/sha256.py).
  * Every type knows how to expose its leaf chunks as a numpy matrix, which
    is the seam the columnar/JAX state mirror (ops/state_columns.py) uses.
  * Root caching: container/list roots are cached and invalidated on
    mutation through the typed API (the reference gets this from
    remerkleable's structural sharing; we get it from explicit dirty bits).
"""

from __future__ import annotations

import io
from typing import Any

import numpy as np

from .hashing import hash_bytes
from .merkle import (
    merkleize_chunks,
    mix_in_length,
    mix_in_selector,
    pack_bytes,
)

OFFSET_BYTE_LENGTH = 4


class SSZException(Exception):
    pass


class DeserializationError(SSZException):
    pass


# ---------------------------------------------------------------------------
# Base view
# ---------------------------------------------------------------------------


class View:
    """Common classmethod surface shared by every SSZ type."""

    @classmethod
    def is_fixed_byte_length(cls) -> bool:
        raise NotImplementedError

    @classmethod
    def is_immutable_subtree(cls) -> bool:
        """True iff instances (and their whole subtree) can never mutate.

        Root caches are only kept on nodes ALL of whose children are
        immutable subtrees: then the node's own typed setters cover every
        possible invalidation path. (The reference gets the same guarantee
        from remerkleable's persistent trees.)
        """
        return False

    @classmethod
    def type_byte_length(cls) -> int:
        raise NotImplementedError(f"{cls.__name__} is not fixed-size")

    @classmethod
    def min_byte_length(cls) -> int:
        return cls.type_byte_length()

    @classmethod
    def max_byte_length(cls) -> int:
        return cls.type_byte_length()

    @classmethod
    def default(cls) -> "View":
        raise NotImplementedError

    @classmethod
    def coerce_view(cls, value: Any) -> "View":
        if isinstance(value, cls):
            return value
        return cls(value)  # type: ignore[call-arg]

    def encode_bytes(self) -> bytes:
        raise NotImplementedError

    @classmethod
    def decode_bytes(cls, data: bytes) -> "View":
        raise NotImplementedError

    def get_hash_tree_root(self) -> bytes:
        raise NotImplementedError

    def copy(self):
        return self  # immutable by default

    def type_of(self):
        return self.__class__


# ---------------------------------------------------------------------------
# Basic types
# ---------------------------------------------------------------------------


class BasicView(View):
    @classmethod
    def is_fixed_byte_length(cls) -> bool:
        return True

    @classmethod
    def is_immutable_subtree(cls) -> bool:
        return True

    def get_hash_tree_root(self) -> bytes:
        data = self.encode_bytes()
        return data + b"\x00" * (32 - len(data))


class boolean(int, BasicView):
    def __new__(cls, value: Any = False):
        v = int(value)
        if v not in (0, 1):
            raise ValueError(f"boolean must be 0 or 1, got {value}")
        return super().__new__(cls, v)

    @classmethod
    def type_byte_length(cls) -> int:
        return 1

    @classmethod
    def default(cls):
        return cls(0)

    def encode_bytes(self) -> bytes:
        return bytes([int(self)])

    @classmethod
    def decode_bytes(cls, data: bytes):
        if len(data) != 1 or data[0] not in (0, 1):
            raise DeserializationError(f"invalid boolean bytes: {data!r}")
        return cls(data[0])

    def __repr__(self):
        return f"boolean({int(self)})"

    def __bool__(self):
        return int(self) == 1


class uint(int, BasicView):
    BITS: int = 0

    def __new__(cls, value: Any = 0):
        if isinstance(value, bytes):
            raise ValueError("cannot coerce bytes to uint; use decode_bytes")
        if isinstance(value, float):
            raise TypeError(f"cannot coerce float to {cls.__name__} (non-integral values are bugs, not data)")
        v = int(value)
        if not 0 <= v < (1 << cls.BITS):
            raise ValueError(f"value {v} out of range for {cls.__name__}")
        return super().__new__(cls, v)

    @classmethod
    def type_byte_length(cls) -> int:
        return cls.BITS // 8

    @classmethod
    def default(cls):
        return cls(0)

    def encode_bytes(self) -> bytes:
        return int(self).to_bytes(self.BITS // 8, "little")

    @classmethod
    def decode_bytes(cls, data: bytes):
        if len(data) != cls.BITS // 8:
            raise DeserializationError(f"{cls.__name__}: expected {cls.BITS // 8} bytes, got {len(data)}")
        return cls(int.from_bytes(data, "little"))

    def __repr__(self):
        return f"{self.__class__.__name__}({int(self)})"

    # Arithmetic deliberately returns plain int (range enforcement happens on
    # assignment into typed fields) — matching the reference's overflow-as-
    # invalid semantics (specs/phase0/beacon-chain.md:1339-1344): an
    # out-of-range result only raises when it lands in the state.


class uint8(uint):
    BITS = 8


class uint16(uint):
    BITS = 16


class uint32(uint):
    BITS = 32


class uint64(uint):
    BITS = 64


class uint128(uint):
    BITS = 128


class uint256(uint):
    BITS = 256


byte = uint8
bit = boolean


# ---------------------------------------------------------------------------
# Parameterized-type machinery
# ---------------------------------------------------------------------------

_type_cache: dict[tuple, type] = {}


def _cached_subclass(key: tuple, builder):
    if key not in _type_cache:
        _type_cache[key] = builder()
    return _type_cache[key]


def _coerce_type(t: Any) -> type:
    if isinstance(t, type) and issubclass(t, View):
        return t
    raise TypeError(f"not an SSZ type: {t!r}")


def _store_coerce(t: type, value: Any) -> "View":
    """Coerce for STORAGE inside a composite: mutable values are copied so
    the stored child never aliases the source (value semantics on store,
    matching remerkleable's backing copies; reads still alias)."""
    v = value if isinstance(value, t) else t.coerce_view(value)
    if not t.is_immutable_subtree():
        v = v.copy()
    return v


# ---------------------------------------------------------------------------
# Byte vectors / byte lists
# ---------------------------------------------------------------------------


class ByteVector(bytes, View):
    LENGTH: int = 0

    def __new__(cls, value: Any = None):
        if cls.LENGTH == 0 and cls is ByteVector:
            raise TypeError("use ByteVector[N]")
        if value is None:
            value = b"\x00" * cls.LENGTH
        if isinstance(value, str):
            value = bytes.fromhex(value[2:] if value.startswith("0x") else value)
        elif isinstance(value, (list, tuple)):
            value = bytes(value)
        elif isinstance(value, (int, bool)):
            # bytes(n) would silently mean n zero bytes — always a bug here
            raise TypeError(f"cannot coerce {type(value).__name__} to {cls.__name__}")
        elif not isinstance(value, (bytes, bytearray, memoryview)):
            # generators/iterables: spec code builds roots like
            # Bytes32(a ^ b for a, b in zip(x, y)) (phase0 `xor`)
            value = bytes(value)
        if len(value) != cls.LENGTH:
            raise ValueError(f"{cls.__name__}: expected {cls.LENGTH} bytes, got {len(value)}")
        return super().__new__(cls, value)

    def __class_getitem__(cls, length: int) -> type:
        return _cached_subclass(
            ("ByteVector", length),
            lambda: type(f"ByteVector[{length}]", (ByteVector,), {"LENGTH": length}),
        )

    @classmethod
    def is_fixed_byte_length(cls) -> bool:
        return True

    @classmethod
    def is_immutable_subtree(cls) -> bool:
        return True

    @classmethod
    def type_byte_length(cls) -> int:
        return cls.LENGTH

    @classmethod
    def default(cls):
        return cls()

    def encode_bytes(self) -> bytes:
        return bytes(self)

    @classmethod
    def decode_bytes(cls, data: bytes):
        try:
            return cls(data)
        except ValueError as e:
            raise DeserializationError(str(e)) from None

    def get_hash_tree_root(self) -> bytes:
        return merkleize_chunks(pack_bytes(bytes(self)))

    def __repr__(self):
        return f"{self.__class__.__name__}(0x{bytes(self).hex()})"


Bytes1 = ByteVector[1]
Bytes4 = ByteVector[4]
Bytes8 = ByteVector[8]
Bytes20 = ByteVector[20]
Bytes31 = ByteVector[31]
Bytes32 = ByteVector[32]
Bytes48 = ByteVector[48]
Bytes96 = ByteVector[96]


class ByteList(bytes, View):
    LIMIT: int = 0

    def __new__(cls, value: Any = b""):
        if isinstance(value, str):
            value = bytes.fromhex(value[2:] if value.startswith("0x") else value)
        elif isinstance(value, (list, tuple)):
            value = bytes(value)
        elif isinstance(value, (int, bool)):
            raise TypeError(f"cannot coerce {type(value).__name__} to {cls.__name__}")
        elif not isinstance(value, (bytes, bytearray, memoryview)):
            value = bytes(value)
        if len(value) > cls.LIMIT:
            raise ValueError(f"{cls.__name__}: {len(value)} bytes exceeds limit {cls.LIMIT}")
        return super().__new__(cls, value)

    def __class_getitem__(cls, limit: int) -> type:
        return _cached_subclass(
            ("ByteList", limit),
            lambda: type(f"ByteList[{limit}]", (ByteList,), {"LIMIT": limit}),
        )

    @classmethod
    def is_fixed_byte_length(cls) -> bool:
        return False

    @classmethod
    def is_immutable_subtree(cls) -> bool:
        return True  # bytes subclass: instances immutable

    @classmethod
    def min_byte_length(cls) -> int:
        return 0

    @classmethod
    def max_byte_length(cls) -> int:
        return cls.LIMIT

    @classmethod
    def default(cls):
        return cls()

    def encode_bytes(self) -> bytes:
        return bytes(self)

    @classmethod
    def decode_bytes(cls, data: bytes):
        try:
            return cls(data)
        except ValueError as e:
            raise DeserializationError(str(e)) from None

    def get_hash_tree_root(self) -> bytes:
        limit_chunks = (self.LIMIT + 31) // 32
        root = merkleize_chunks(pack_bytes(bytes(self)), limit=limit_chunks)
        return mix_in_length(root, len(self))

    def __repr__(self):
        return f"{self.__class__.__name__}(0x{bytes(self).hex()})"


# ---------------------------------------------------------------------------
# Bitfields
# ---------------------------------------------------------------------------


def _bits_from_args(args) -> list[bool]:
    if len(args) == 1 and not isinstance(args[0], (bool, int)):
        args = tuple(args[0])
    return [bool(b) for b in args]


def _bitfield_bytes(bits: list[bool]) -> bytes:
    n = len(bits)
    out = bytearray((n + 7) // 8)
    for i, b in enumerate(bits):
        if b:
            out[i // 8] |= 1 << (i % 8)
    return bytes(out)


class Bitvector(View):
    LENGTH: int = 0

    def __init__(self, *args):
        bits = [False] * self.LENGTH if len(args) == 0 else _bits_from_args(args)
        if len(bits) != self.LENGTH:
            raise ValueError(f"{self.__class__.__name__}: expected {self.LENGTH} bits, got {len(bits)}")
        self._bits = bits

    def __class_getitem__(cls, length: int) -> type:
        if length <= 0:
            raise TypeError("Bitvector length must be > 0")
        return _cached_subclass(
            ("Bitvector", length),
            lambda: type(f"Bitvector[{length}]", (Bitvector,), {"LENGTH": length}),
        )

    @classmethod
    def is_fixed_byte_length(cls) -> bool:
        return True

    @classmethod
    def type_byte_length(cls) -> int:
        return (cls.LENGTH + 7) // 8

    @classmethod
    def default(cls):
        return cls()

    def __len__(self):
        return self.LENGTH

    def __iter__(self):
        return iter(self._bits)

    def __getitem__(self, i):
        return self._bits[i]

    def __setitem__(self, i, v):
        if isinstance(i, slice):
            # spec code shifts justification bits with slice assignment
            # (specs/phase0/beacon-chain.md weigh_justification_and_finalization)
            vals = [bool(b) for b in v]
            if len(range(*i.indices(self.LENGTH))) != len(vals):
                raise ValueError("Bitvector slice assignment must preserve length")
            self._bits[i] = vals
            return
        self._bits[i] = bool(v)

    def __eq__(self, other):
        return isinstance(other, Bitvector) and other.LENGTH == self.LENGTH and other._bits == self._bits

    def __hash__(self):
        return hash((self.LENGTH, tuple(self._bits)))

    def encode_bytes(self) -> bytes:
        return _bitfield_bytes(self._bits)

    @classmethod
    def decode_bytes(cls, data: bytes):
        if len(data) != cls.type_byte_length():
            raise DeserializationError(f"{cls.__name__}: wrong byte length {len(data)}")
        bits = [bool((data[i // 8] >> (i % 8)) & 1) for i in range(cls.LENGTH)]
        # Excess bits beyond LENGTH must be zero
        if cls.LENGTH % 8 != 0 and data[-1] >> (cls.LENGTH % 8):
            raise DeserializationError(f"{cls.__name__}: non-zero padding bits")
        return cls(bits)

    def get_hash_tree_root(self) -> bytes:
        limit_chunks = (self.LENGTH + 255) // 256
        return merkleize_chunks(pack_bytes(self.encode_bytes()), limit=limit_chunks)

    def copy(self):
        return self.__class__(list(self._bits))

    def __repr__(self):
        return f"{self.__class__.__name__}({''.join('1' if b else '0' for b in self._bits)})"


class Bitlist(View):
    LIMIT: int = 0

    def __init__(self, *args):
        bits = _bits_from_args(args)
        if len(bits) > self.LIMIT:
            raise ValueError(f"{self.__class__.__name__}: {len(bits)} bits exceeds limit {self.LIMIT}")
        self._bits = bits

    def __class_getitem__(cls, limit: int) -> type:
        return _cached_subclass(
            ("Bitlist", limit),
            lambda: type(f"Bitlist[{limit}]", (Bitlist,), {"LIMIT": limit}),
        )

    @classmethod
    def is_fixed_byte_length(cls) -> bool:
        return False

    @classmethod
    def min_byte_length(cls) -> int:
        return 1

    @classmethod
    def max_byte_length(cls) -> int:
        return (cls.LIMIT + 7) // 8 + 1

    @classmethod
    def default(cls):
        return cls()

    def __len__(self):
        return len(self._bits)

    def __iter__(self):
        return iter(self._bits)

    def __getitem__(self, i):
        return self._bits[i]

    def __setitem__(self, i, v):
        if isinstance(i, slice):
            vals = [bool(b) for b in v]
            if len(range(*i.indices(len(self._bits)))) != len(vals):
                raise ValueError("Bitlist slice assignment must preserve length")
            self._bits[i] = vals
            return
        self._bits[i] = bool(v)

    def append(self, v):
        if len(self._bits) >= self.LIMIT:
            raise ValueError("Bitlist full")
        self._bits.append(bool(v))

    def to_numpy(self):
        """Dense bool array of the bits (columnar extraction fast path)."""
        import numpy as _np

        return _np.array(self._bits, dtype=bool)

    def __eq__(self, other):
        return isinstance(other, Bitlist) and other.LIMIT == self.LIMIT and other._bits == self._bits

    def __hash__(self):
        return hash((self.LIMIT, tuple(self._bits)))

    def encode_bytes(self) -> bytes:
        # bits + delimiter bit (ssz/simple-serialize.md bitlist encoding)
        bits = self._bits + [True]
        return _bitfield_bytes(bits)

    @classmethod
    def decode_bytes(cls, data: bytes):
        if len(data) == 0:
            raise DeserializationError("Bitlist: empty bytes")
        if data[-1] == 0:
            raise DeserializationError("Bitlist: missing delimiter bit")
        total_bits = (len(data) - 1) * 8 + data[-1].bit_length() - 1
        if total_bits > cls.LIMIT:
            raise DeserializationError(f"Bitlist: {total_bits} bits exceeds limit {cls.LIMIT}")
        bits = [bool((data[i // 8] >> (i % 8)) & 1) for i in range(total_bits)]
        return cls(bits)

    def get_hash_tree_root(self) -> bytes:
        limit_chunks = (self.LIMIT + 255) // 256
        root = merkleize_chunks(pack_bytes(_bitfield_bytes(self._bits)), limit=limit_chunks)
        return mix_in_length(root, len(self._bits))

    def copy(self):
        return self.__class__(list(self._bits))

    def __repr__(self):
        return f"{self.__class__.__name__}({''.join('1' if b else '0' for b in self._bits)})"


# ---------------------------------------------------------------------------
# List / Vector
# ---------------------------------------------------------------------------


def _pack_basic_elements(element_type: type, items: list) -> np.ndarray:
    """Pack a sequence of basic values into 32-byte chunks (fast path)."""
    if issubclass(element_type, uint):
        nbytes = element_type.BITS // 8
        if nbytes <= 8:
            dt = {1: np.uint8, 2: np.uint16, 4: np.uint32, 8: np.uint64}[nbytes]
            arr = np.array([int(v) for v in items], dtype=dt)
            return pack_bytes(arr.tobytes())
        data = b"".join(int(v).to_bytes(nbytes, "little") for v in items)
        return pack_bytes(data)
    if issubclass(element_type, boolean):
        return pack_bytes(bytes(int(v) for v in items))
    raise TypeError(f"not a basic type: {element_type}")


class _Sequence(View):
    """Shared element-sequence behavior for List and Vector."""

    ELEMENT_TYPE: type = View

    def __init__(self, *args):
        if len(args) == 1 and (
            isinstance(args[0], _Sequence)  # a sequence view always means "these elements"
            or not isinstance(args[0], (int, bytes, str, View))
        ):
            try:
                args = tuple(args[0])
            except TypeError:
                pass
        et = self.ELEMENT_TYPE
        self._items = [_store_coerce(et, v) for v in args]
        self._check_init_length()
        self._root_cache: bytes | None = None

    def _check_init_length(self):
        raise NotImplementedError

    def __len__(self):
        return len(self._items)

    def __iter__(self):
        return iter(self._items)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return self._items[i]
        if isinstance(i, int) and not -len(self._items) <= i < len(self._items):
            raise IndexError(f"index {i} out of range for length {len(self._items)}")
        return self._items[int(i)]

    def __setitem__(self, i, v):
        if isinstance(i, slice):
            # length-preserving slice assignment (spec code shifts windows,
            # e.g. fulu process_proposer_lookahead,
            # specs/fulu/beacon-chain.md:318-326)
            idxs = range(*i.indices(len(self._items)))
            vals = list(v)
            if len(vals) != len(idxs):
                raise ValueError(
                    f"slice assignment must preserve length ({len(idxs)} != {len(vals)})"
                )
            # coerce BEFORE mutating: a mid-loop coercion failure must not
            # leave a half-modified sequence with a stale cached root
            coerced = [_store_coerce(self.ELEMENT_TYPE, val) for val in vals]
            for j, val in zip(idxs, coerced):
                self._items[j] = val
            self._root_cache = None
            return
        if not -len(self._items) <= i < len(self._items):
            raise IndexError(f"index {i} out of range for length {len(self._items)}")
        self._items[int(i)] = _store_coerce(self.ELEMENT_TYPE, v)
        self._root_cache = None

    def __eq__(self, other):
        if other.__class__ is self.__class__:
            return other._items == self._items
        if isinstance(other, (list, tuple)):
            # plain-sequence equality is part of the remerkleable-compatible
            # surface: spec code compares lists to `sorted(...)` results
            # (e.g. is_valid_indexed_attestation,
            # specs/phase0/beacon-chain.md:776-792)
            return len(other) == len(self._items) and all(
                a == b for a, b in zip(self._items, other)
            )
        return NotImplemented

    def __hash__(self):
        return hash(tuple(self._items))

    def index(self, v):
        return self._items.index(self.ELEMENT_TYPE.coerce_view(v))

    def count(self, v):
        # list-protocol count (spec: eth1_data_votes.count(body.eth1_data),
        # specs/phase0/beacon-chain.md process_eth1_data)
        return sum(1 for item in self._items if item == v)

    def __contains__(self, v):
        try:
            return self.ELEMENT_TYPE.coerce_view(v) in self._items
        except (ValueError, TypeError):
            return False

    def copy(self):
        new = self.__class__.__new__(self.__class__)
        new._items = [v.copy() for v in self._items]
        new._root_cache = self._root_cache
        return new

    def _invalidate(self):
        self._root_cache = None

    # --- serialization (element sequence rules, ssz/simple-serialize.md) ---

    def encode_bytes(self) -> bytes:
        et = self.ELEMENT_TYPE
        if issubclass(et, uint) and et.BITS <= 64:
            nbytes = et.BITS // 8
            dt = {1: np.uint8, 2: np.uint16, 4: np.uint32, 8: np.uint64}[nbytes]
            return np.array([int(v) for v in self._items], dtype=dt).tobytes()
        if et.is_fixed_byte_length():
            return b"".join(v.encode_bytes() for v in self._items)
        parts = [v.encode_bytes() for v in self._items]
        offset = OFFSET_BYTE_LENGTH * len(parts)
        out = io.BytesIO()
        for p in parts:
            out.write(offset.to_bytes(OFFSET_BYTE_LENGTH, "little"))
            offset += len(p)
        for p in parts:
            out.write(p)
        return out.getvalue()

    @classmethod
    def _decode_elements(cls, data: bytes, max_count: int, exact_count: int | None = None) -> list:
        et = cls.ELEMENT_TYPE
        items: list = []
        if et.is_fixed_byte_length():
            elen = et.type_byte_length()
            if len(data) % elen != 0:
                raise DeserializationError(f"{cls.__name__}: byte length {len(data)} not a multiple of {elen}")
            count = len(data) // elen
            if exact_count is not None and count != exact_count:
                raise DeserializationError(f"{cls.__name__}: expected {exact_count} elements, got {count}")
            if count > max_count:
                raise DeserializationError(f"{cls.__name__}: {count} elements exceeds limit {max_count}")
            for i in range(count):
                items.append(et.decode_bytes(data[i * elen : (i + 1) * elen]))
            return items
        # variable-size elements: offset table
        if len(data) == 0:
            if exact_count not in (None, 0):
                raise DeserializationError(f"{cls.__name__}: expected {exact_count} elements, got 0")
            return items
        if len(data) < OFFSET_BYTE_LENGTH:
            raise DeserializationError(f"{cls.__name__}: truncated offset table")
        first_offset = int.from_bytes(data[:OFFSET_BYTE_LENGTH], "little")
        if first_offset % OFFSET_BYTE_LENGTH != 0 or first_offset == 0:
            raise DeserializationError(f"{cls.__name__}: bad first offset {first_offset}")
        count = first_offset // OFFSET_BYTE_LENGTH
        if first_offset > len(data):
            raise DeserializationError(f"{cls.__name__}: offset table past end of data")
        if exact_count is not None and count != exact_count:
            raise DeserializationError(f"{cls.__name__}: expected {exact_count} elements, got {count}")
        if count > max_count:
            raise DeserializationError(f"{cls.__name__}: {count} elements exceeds limit {max_count}")
        offsets = [int.from_bytes(data[i * 4 : i * 4 + 4], "little") for i in range(count)]
        offsets.append(len(data))
        for i in range(count):
            if offsets[i] > offsets[i + 1] or offsets[i + 1] > len(data):
                raise DeserializationError(f"{cls.__name__}: non-monotonic offsets")
            items.append(et.decode_bytes(data[offsets[i] : offsets[i + 1]]))
        return items

    @classmethod
    def _from_owned_items(cls, items: list):
        """Wrap a list of already-coerced, exclusively-owned elements
        (decode paths) without the copy-on-store pass."""
        new = cls.__new__(cls)
        new._items = items
        new._root_cache = None
        new._check_init_length()
        return new

    def _element_chunks(self) -> np.ndarray:
        et = self.ELEMENT_TYPE
        if issubclass(et, BasicView):
            return _pack_basic_elements(et, self._items)
        roots = [v.get_hash_tree_root() for v in self._items]
        if not roots:
            return np.empty((0, 32), dtype=np.uint8)
        return np.frombuffer(b"".join(roots), dtype=np.uint8).reshape(len(roots), 32)

    @classmethod
    def _chunk_limit(cls, capacity: int) -> int:
        et = cls.ELEMENT_TYPE
        if issubclass(et, BasicView):
            return (capacity * et.type_byte_length() + 31) // 32
        return capacity


class List(_Sequence):
    LIMIT: int = 0

    def __class_getitem__(cls, params) -> type:
        element_type, limit = params
        element_type = _coerce_type(element_type)
        limit = int(limit)
        return _cached_subclass(
            ("List", element_type, limit),
            lambda: type(
                f"List[{element_type.__name__},{limit}]",
                (List,),
                {"ELEMENT_TYPE": element_type, "LIMIT": limit},
            ),
        )

    def _check_init_length(self):
        if len(self._items) > self.LIMIT:
            raise ValueError(f"{self.__class__.__name__}: {len(self._items)} elements exceeds limit {self.LIMIT}")

    @classmethod
    def is_fixed_byte_length(cls) -> bool:
        return False

    @classmethod
    def min_byte_length(cls) -> int:
        return 0

    @classmethod
    def max_byte_length(cls) -> int:
        et = cls.ELEMENT_TYPE
        per = et.max_byte_length() + (0 if et.is_fixed_byte_length() else OFFSET_BYTE_LENGTH)
        return per * cls.LIMIT

    @classmethod
    def default(cls):
        return cls()

    def append(self, v):
        if len(self._items) >= self.LIMIT:
            raise ValueError(f"{self.__class__.__name__}: append past limit {self.LIMIT}")
        self._items.append(_store_coerce(self.ELEMENT_TYPE, v))
        self._root_cache = None

    def pop(self, idx: int = -1):
        if not self._items:
            raise IndexError("pop from empty List")
        self._root_cache = None
        return self._items.pop(idx)

    @classmethod
    def decode_bytes(cls, data: bytes):
        return cls._from_owned_items(cls._decode_elements(data, cls.LIMIT))

    def get_hash_tree_root(self) -> bytes:
        if self._root_cache is not None and self.ELEMENT_TYPE.is_immutable_subtree():
            return self._root_cache
        root = merkleize_chunks(self._element_chunks(), limit=self._chunk_limit(self.LIMIT))
        self._root_cache = mix_in_length(root, len(self._items))
        return self._root_cache

    def __repr__(self):
        return f"{self.__class__.__name__}({list(self._items)!r})"


class Vector(_Sequence):
    LENGTH: int = 0

    def __class_getitem__(cls, params) -> type:
        element_type, length = params
        element_type = _coerce_type(element_type)
        length = int(length)
        if length <= 0:
            raise TypeError("Vector length must be > 0")
        return _cached_subclass(
            ("Vector", element_type, length),
            lambda: type(
                f"Vector[{element_type.__name__},{length}]",
                (Vector,),
                {"ELEMENT_TYPE": element_type, "LENGTH": length},
            ),
        )

    def __init__(self, *args):
        if not args:
            args = tuple(self.ELEMENT_TYPE.default() for _ in range(self.LENGTH))
        super().__init__(*args)

    def _check_init_length(self):
        if len(self._items) != self.LENGTH:
            raise ValueError(f"{self.__class__.__name__}: expected {self.LENGTH} elements, got {len(self._items)}")

    @classmethod
    def is_fixed_byte_length(cls) -> bool:
        return cls.ELEMENT_TYPE.is_fixed_byte_length()

    @classmethod
    def type_byte_length(cls) -> int:
        return cls.ELEMENT_TYPE.type_byte_length() * cls.LENGTH

    @classmethod
    def min_byte_length(cls) -> int:
        et = cls.ELEMENT_TYPE
        if et.is_fixed_byte_length():
            return cls.type_byte_length()
        return (et.min_byte_length() + OFFSET_BYTE_LENGTH) * cls.LENGTH

    @classmethod
    def max_byte_length(cls) -> int:
        et = cls.ELEMENT_TYPE
        if et.is_fixed_byte_length():
            return cls.type_byte_length()
        return (et.max_byte_length() + OFFSET_BYTE_LENGTH) * cls.LENGTH

    @classmethod
    def default(cls):
        return cls()

    @classmethod
    def decode_bytes(cls, data: bytes):
        return cls._from_owned_items(
            cls._decode_elements(data, cls.LENGTH, exact_count=cls.LENGTH)
        )

    def get_hash_tree_root(self) -> bytes:
        if self._root_cache is not None and self.ELEMENT_TYPE.is_immutable_subtree():
            return self._root_cache
        self._root_cache = merkleize_chunks(
            self._element_chunks(), limit=self._chunk_limit(self.LENGTH)
        )
        return self._root_cache

    def __repr__(self):
        return f"{self.__class__.__name__}({list(self._items)!r})"


# ---------------------------------------------------------------------------
# Container
# ---------------------------------------------------------------------------


class Container(View):
    _field_names: tuple[str, ...] = ()
    _field_types: tuple[type, ...] = ()

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        fields: dict[str, type] = {}
        for klass in reversed(cls.__mro__):
            ann = klass.__dict__.get("__annotations__", {})
            for name, t in ann.items():
                if name.startswith("_"):
                    continue
                fields[name] = _coerce_type(t)
        cls._field_names = tuple(fields.keys())
        cls._field_types = tuple(fields.values())
        # root cache is only safe when every child subtree is immutable:
        # then __setattr__ covers all invalidation paths
        cls._cacheable = all(t.is_immutable_subtree() for t in cls._field_types)

    def __init__(self, **kwargs):
        object.__setattr__(self, "_root_cache", None)
        values = {}
        for name, t in zip(self._field_names, self._field_types):
            if name in kwargs:
                values[name] = _store_coerce(t, kwargs.pop(name))
            else:
                values[name] = t.default()
        if kwargs:
            raise TypeError(f"{self.__class__.__name__}: unknown fields {list(kwargs)}")
        object.__setattr__(self, "_values", values)

    @classmethod
    def fields(cls) -> dict[str, type]:
        return dict(zip(cls._field_names, cls._field_types))

    def __getattr__(self, name):
        # only called when normal lookup fails
        values = object.__getattribute__(self, "_values")
        if name in values:
            return values[name]
        raise AttributeError(f"{self.__class__.__name__} has no field {name!r}")

    def __setattr__(self, name, value):
        if name.startswith("_"):
            object.__setattr__(self, name, value)
            return
        try:
            idx = self._field_names.index(name)
        except ValueError:
            raise AttributeError(f"{self.__class__.__name__} has no field {name!r}") from None
        t = self._field_types[idx]
        self._values[name] = _store_coerce(t, value)
        object.__setattr__(self, "_root_cache", None)

    def __eq__(self, other):
        return (
            isinstance(other, Container)
            and other.__class__._field_names == self._field_names
            and other.__class__._field_types == self.__class__._field_types
            and all(other._values[n] == self._values[n] for n in self._field_names)
        )

    def __hash__(self):
        return hash(self.get_hash_tree_root())

    @classmethod
    def is_fixed_byte_length(cls) -> bool:
        return all(t.is_fixed_byte_length() for t in cls._field_types)

    @classmethod
    def type_byte_length(cls) -> int:
        if not cls.is_fixed_byte_length():
            raise NotImplementedError(f"{cls.__name__} is variable-size")
        return sum(t.type_byte_length() for t in cls._field_types)

    @classmethod
    def min_byte_length(cls) -> int:
        total = 0
        for t in cls._field_types:
            if t.is_fixed_byte_length():
                total += t.type_byte_length()
            else:
                total += OFFSET_BYTE_LENGTH + t.min_byte_length()
        return total

    @classmethod
    def max_byte_length(cls) -> int:
        total = 0
        for t in cls._field_types:
            if t.is_fixed_byte_length():
                total += t.type_byte_length()
            else:
                total += OFFSET_BYTE_LENGTH + t.max_byte_length()
        return total

    @classmethod
    def default(cls):
        return cls()

    @classmethod
    def coerce_view(cls, value):
        if isinstance(value, cls):
            return value
        if isinstance(value, Container) and value.__class__._field_names == cls._field_names:
            return cls(**{n: value._values[n] for n in cls._field_names})
        raise ValueError(f"cannot coerce {value!r} to {cls.__name__}")

    def encode_bytes(self) -> bytes:
        fixed_parts: list[bytes | None] = []
        var_parts: list[bytes] = []
        for name, t in zip(self._field_names, self._field_types):
            v = self._values[name]
            if t.is_fixed_byte_length():
                fixed_parts.append(v.encode_bytes())
            else:
                fixed_parts.append(None)
                var_parts.append(v.encode_bytes())
        fixed_len = sum(OFFSET_BYTE_LENGTH if p is None else len(p) for p in fixed_parts)
        out = io.BytesIO()
        offset = fixed_len
        vi = 0
        for p in fixed_parts:
            if p is None:
                out.write(offset.to_bytes(OFFSET_BYTE_LENGTH, "little"))
                offset += len(var_parts[vi])
                vi += 1
            else:
                out.write(p)
        for p in var_parts:
            out.write(p)
        return out.getvalue()

    @classmethod
    def decode_bytes(cls, data: bytes):
        values: dict[str, View] = {}
        pos = 0
        offsets: list[tuple[str, type, int]] = []
        for name, t in zip(cls._field_names, cls._field_types):
            if t.is_fixed_byte_length():
                elen = t.type_byte_length()
                if pos + elen > len(data):
                    raise DeserializationError(f"{cls.__name__}: truncated at field {name}")
                values[name] = t.decode_bytes(data[pos : pos + elen])
                pos += elen
            else:
                if pos + OFFSET_BYTE_LENGTH > len(data):
                    raise DeserializationError(f"{cls.__name__}: truncated offset at field {name}")
                offsets.append((name, t, int.from_bytes(data[pos : pos + 4], "little")))
                pos += OFFSET_BYTE_LENGTH
        if offsets:
            if offsets[0][2] != pos:
                raise DeserializationError(f"{cls.__name__}: first offset {offsets[0][2]} != fixed size {pos}")
            bounds = [o[2] for o in offsets] + [len(data)]
            for (name, t, start), end in zip(offsets, bounds[1:]):
                if start > end or end > len(data):
                    raise DeserializationError(f"{cls.__name__}: bad offsets for field {name}")
                values[name] = t.decode_bytes(data[start:end])
        elif pos != len(data):
            raise DeserializationError(f"{cls.__name__}: {len(data) - pos} trailing bytes")
        new = cls.__new__(cls)
        object.__setattr__(new, "_root_cache", None)
        object.__setattr__(new, "_values", values)
        return new

    def get_hash_tree_root(self) -> bytes:
        if self._root_cache is not None and self._cacheable:
            return self._root_cache
        roots = b"".join(self._values[n].get_hash_tree_root() for n in self._field_names)
        chunks = np.frombuffer(roots, dtype=np.uint8).reshape(len(self._field_names), 32)
        object.__setattr__(self, "_root_cache", merkleize_chunks(chunks))
        return self._root_cache

    def copy(self):
        new = self.__class__.__new__(self.__class__)
        object.__setattr__(new, "_root_cache", self._root_cache)
        object.__setattr__(new, "_values", {n: v.copy() for n, v in self._values.items()})
        return new

    def __repr__(self):
        inner = ", ".join(f"{n}={self._values[n]!r}" for n in self._field_names)
        return f"{self.__class__.__name__}({inner})"


# ---------------------------------------------------------------------------
# Union
# ---------------------------------------------------------------------------


class Union(View):
    OPTIONS: tuple[type | None, ...] = ()

    def __init__(self, selector: int, value: Any = None):
        if not 0 <= selector < len(self.OPTIONS):
            raise ValueError(f"Union selector {selector} out of range")
        t = self.OPTIONS[selector]
        if t is None:
            if value is not None:
                raise ValueError("Union None option takes no value")
            self._value = None
        else:
            self._value = _store_coerce(t, value)
        self._selector = selector

    def __class_getitem__(cls, params) -> type:
        if not isinstance(params, tuple):
            params = (params,)
        opts = tuple(None if p is None else _coerce_type(p) for p in params)
        if len(opts) == 0 or (opts[0] is None and len(opts) == 1):
            raise TypeError("invalid Union options")
        return _cached_subclass(
            ("Union", opts),
            lambda: type(
                f"Union[{','.join('None' if o is None else o.__name__ for o in opts)}]",
                (Union,),
                {"OPTIONS": opts},
            ),
        )

    @property
    def selector(self) -> int:
        return self._selector

    @property
    def value(self):
        return self._value

    @classmethod
    def is_fixed_byte_length(cls) -> bool:
        return False

    @classmethod
    def min_byte_length(cls) -> int:
        return 1

    @classmethod
    def max_byte_length(cls) -> int:
        return 1 + max((o.max_byte_length() if o else 0) for o in cls.OPTIONS)

    @classmethod
    def default(cls):
        t = cls.OPTIONS[0]
        return cls(0, None if t is None else t.default())

    def __eq__(self, other):
        return (
            isinstance(other, Union)
            and other.OPTIONS == self.OPTIONS
            and other._selector == self._selector
            and other._value == self._value
        )

    def __hash__(self):
        return hash((self.OPTIONS, self._selector, self._value))

    def encode_bytes(self) -> bytes:
        body = b"" if self._value is None else self._value.encode_bytes()
        return bytes([self._selector]) + body

    @classmethod
    def decode_bytes(cls, data: bytes):
        if len(data) < 1:
            raise DeserializationError("Union: empty bytes")
        selector = data[0]
        if selector >= len(cls.OPTIONS):
            raise DeserializationError(f"Union: selector {selector} out of range")
        t = cls.OPTIONS[selector]
        if t is None:
            if len(data) != 1:
                raise DeserializationError("Union: None option with body")
            return cls(selector, None)
        return cls(selector, t.decode_bytes(data[1:]))

    def get_hash_tree_root(self) -> bytes:
        body_root = b"\x00" * 32 if self._value is None else self._value.get_hash_tree_root()
        return mix_in_selector(body_root, self._selector)

    def copy(self):
        return self.__class__(self._selector, None if self._value is None else self._value.copy())

    def __repr__(self):
        return f"{self.__class__.__name__}(selector={self._selector}, value={self._value!r})"


# ---------------------------------------------------------------------------
# Module-level API (reference surface: utils/ssz/ssz_impl.py:8-37)
# ---------------------------------------------------------------------------


def serialize(obj: View) -> bytes:
    return obj.encode_bytes()


def deserialize(typ: type, data: bytes) -> View:
    return typ.decode_bytes(data)


def hash_tree_root(obj: View) -> Bytes32:
    if isinstance(obj, View):
        return Bytes32(obj.get_hash_tree_root())
    raise TypeError(f"hash_tree_root: not an SSZ value: {obj!r}")


def uint_to_bytes(n: uint) -> bytes:
    return n.encode_bytes()
