"""Generalized-index algebra and Merkle (multi)proofs.

Behavioral parity target: ssz/merkle-proofs.md — the path→gindex mapping
(:71-195), gindex helpers (:195-241), helper-index computation and
single/multi-item proof verification (:243-380). `compute_merkle_proof`
(the prover side used by the light-client protocol) lives in
ssz/merkle.py; this module is the consumer-side algebra plus the
type-directed gindex derivation over the first-party SSZ type system.

The object→index mapping works on this package's types: `Container`
fields, `List`/`Vector` elements (with length mix-in for lists),
`ByteList`/`ByteVector` byte positions, and `Bitlist`/`Bitvector` bits —
mirroring the reference's chunk-count rules exactly so hardcoded spec
gindices (e.g. the light-client ones) agree.
"""

from __future__ import annotations

from .hashing import hash_bytes
from .types import (
    BasicView,
    Bitlist,
    Bitvector,
    ByteList,
    ByteVector,
    Container,
    List,
    Vector,
    uint64,
)

GeneralizedIndex = int


def get_power_of_two_ceil(x: int) -> int:
    if x <= 1:
        return 1
    return 1 << (x - 1).bit_length()


# == SSZ object -> index (ssz/merkle-proofs.md:71-195) ======================


def item_length(typ) -> int:
    """Bytes per element: basic types their width, compound types a hash."""
    if isinstance(typ, type) and issubclass(typ, BasicView):
        return typ.type_byte_length()
    return 32


def get_elem_type(typ, index_or_variable_name):
    """Element type at an index (`7` for x[7]) or field name (`"foo"`)."""
    if isinstance(typ, type) and issubclass(typ, Container):
        return typ.fields()[index_or_variable_name]
    if isinstance(typ, type) and issubclass(typ, (ByteList, ByteVector)):
        from .types import uint8

        return uint8
    if isinstance(typ, type) and issubclass(typ, (Bitlist, Bitvector)):
        from .types import boolean

        return boolean
    return typ.ELEMENT_TYPE


def chunk_count(typ) -> int:
    """Top-level chunk count of a type (ssz/merkle-proofs.md:121-141)."""
    if isinstance(typ, type) and issubclass(typ, BasicView):
        return 1
    if isinstance(typ, type) and issubclass(typ, Bitvector):
        return (typ.LENGTH + 255) // 256
    if isinstance(typ, type) and issubclass(typ, Bitlist):
        return (typ.LIMIT + 255) // 256
    if isinstance(typ, type) and issubclass(typ, ByteVector):
        return (typ.LENGTH + 31) // 32
    if isinstance(typ, type) and issubclass(typ, ByteList):
        return (typ.LIMIT + 31) // 32
    if isinstance(typ, type) and issubclass(typ, Vector):
        return (typ.LENGTH * item_length(typ.ELEMENT_TYPE) + 31) // 32
    if isinstance(typ, type) and issubclass(typ, List):
        return (typ.LIMIT * item_length(typ.ELEMENT_TYPE) + 31) // 32
    if isinstance(typ, type) and issubclass(typ, Container):
        return len(typ.fields())
    raise TypeError(f"type not supported: {typ}")


def get_item_position(typ, index_or_variable_name) -> tuple[int, int, int]:
    """(chunk index, start byte in chunk, end byte in chunk)."""
    if isinstance(typ, type) and issubclass(typ, Container):
        names = list(typ.fields())
        pos = names.index(index_or_variable_name)
        return pos, 0, item_length(get_elem_type(typ, index_or_variable_name))
    if isinstance(typ, type) and issubclass(
        typ, (List, Vector, ByteList, ByteVector, Bitlist, Bitvector)
    ):
        index = int(index_or_variable_name)
        elem_len = item_length(get_elem_type(typ, index))
        if isinstance(typ, type) and issubclass(typ, (Bitlist, Bitvector)):
            # bit-packed: 256 bits per chunk
            return index // 256, (index % 256) // 8, (index % 256) // 8 + 1
        start = index * elem_len
        return start // 32, start % 32, start % 32 + elem_len
    raise TypeError("only lists/vectors/containers supported")


def _is_list_like(typ) -> bool:
    return isinstance(typ, type) and issubclass(typ, (List, ByteList, Bitlist))


def get_generalized_index(typ, *path) -> GeneralizedIndex:
    """Path (e.g. `(7, "foo", 3)` or `("y", "__len__")`) → gindex
    (ssz/merkle-proofs.md:166-193)."""
    root = 1
    for p in path:
        assert not (isinstance(typ, type) and issubclass(typ, BasicView)), (
            "path descends into a basic type"
        )
        if p == "__len__":
            assert _is_list_like(typ), "__len__ only applies to lists"
            typ = uint64
            root = root * 2 + 1
        else:
            pos, _, _ = get_item_position(typ, p)
            base_index = 2 if _is_list_like(typ) else 1
            root = root * base_index * get_power_of_two_ceil(chunk_count(typ)) + pos
            typ = get_elem_type(typ, p)
    return root


# == gindex helpers (ssz/merkle-proofs.md:195-241) ==========================


def get_generalized_index_length(index: GeneralizedIndex) -> int:
    return int(index).bit_length() - 1


def get_generalized_index_bit(index: GeneralizedIndex, position: int) -> bool:
    return (int(index) & (1 << position)) > 0


def generalized_index_sibling(index: GeneralizedIndex) -> GeneralizedIndex:
    return int(index) ^ 1


def generalized_index_child(index: GeneralizedIndex, right_side: bool) -> GeneralizedIndex:
    return int(index) * 2 + int(bool(right_side))


def generalized_index_parent(index: GeneralizedIndex) -> GeneralizedIndex:
    return int(index) // 2


def get_power_of_two_floor(x: int) -> int:
    if x <= 1:
        return 1
    return 1 << (x.bit_length() - 1)


def concat_generalized_indices(*indices: GeneralizedIndex) -> GeneralizedIndex:
    """Index of the node reached by successively navigating each gindex
    inside the previous one's subtree (ssz/merkle-proofs.md:18-33)."""
    o = 1
    for i in indices:
        i = int(i)
        floor = get_power_of_two_floor(i)
        o = o * floor + (i - floor)
    return o


def get_subtree_index(generalized_index: GeneralizedIndex) -> int:
    return int(generalized_index) % (1 << get_generalized_index_length(generalized_index))


# == multiproof helper indices (ssz/merkle-proofs.md:266-303) ===============


def get_branch_indices(tree_index: GeneralizedIndex) -> list[GeneralizedIndex]:
    o = [generalized_index_sibling(tree_index)]
    while o[-1] > 1:
        o.append(generalized_index_sibling(generalized_index_parent(o[-1])))
    return o[:-1]


def get_path_indices(tree_index: GeneralizedIndex) -> list[GeneralizedIndex]:
    o = [int(tree_index)]
    while o[-1] > 1:
        o.append(generalized_index_parent(o[-1]))
    return o[:-1]


def get_helper_indices(indices) -> list[GeneralizedIndex]:
    all_helper_indices: set[int] = set()
    all_path_indices: set[int] = set()
    for index in indices:
        all_helper_indices |= set(get_branch_indices(index))
        all_path_indices |= set(get_path_indices(index))
    return sorted(all_helper_indices - all_path_indices, reverse=True)


# == proof verification (ssz/merkle-proofs.md:305-380) ======================


def calculate_merkle_root(leaf: bytes, proof, index: GeneralizedIndex) -> bytes:
    assert len(proof) == get_generalized_index_length(index), "proof length mismatch"
    leaf = bytes(leaf)
    for i, h in enumerate(proof):
        if get_generalized_index_bit(index, i):
            leaf = hash_bytes(bytes(h) + leaf)
        else:
            leaf = hash_bytes(leaf + bytes(h))
    return leaf


def verify_merkle_proof(leaf: bytes, proof, index: GeneralizedIndex, root: bytes) -> bool:
    return calculate_merkle_root(leaf, proof, index) == bytes(root)


def calculate_multi_merkle_root(leaves, proof, indices) -> bytes:
    assert len(leaves) == len(indices), "leaves/indices mismatch"
    helper_indices = get_helper_indices(indices)
    assert len(proof) == len(helper_indices), "proof length mismatch"
    objects: dict[int, bytes] = {
        **{int(index): bytes(node) for index, node in zip(indices, leaves)},
        **{int(index): bytes(node) for index, node in zip(helper_indices, proof)},
    }
    keys = sorted(objects.keys(), reverse=True)
    pos = 0
    while pos < len(keys):
        k = keys[pos]
        if k in objects and k ^ 1 in objects and k // 2 not in objects:
            objects[k // 2] = hash_bytes(objects[(k | 1) ^ 1] + objects[k | 1])
            keys.append(k // 2)
        pos += 1
    return objects[1]


def verify_merkle_multiproof(leaves, proof, indices, root: bytes) -> bool:
    return calculate_multi_merkle_root(leaves, proof, indices) == bytes(root)
