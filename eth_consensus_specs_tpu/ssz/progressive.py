"""Progressive SSZ types (EIP-7916 progressive lists/bitlists, EIP-7495
progressive containers).

Behavioral parity target: ssz/simple-serialize.md — `merkleize_progressive`
(:386-395), `mix_in_active_fields` (:396-398), the progressive
hash-tree-root rules (:404-433), and the type definitions (:58-99).

A progressive list has no compile-time limit: its Merkle shape grows as a
chain of 4x-larger binary subtrees, so the root is stable as the value
grows (no pre-committed capacity). Serialization is identical to the
corresponding unlimited list/bitlist.

TPU note: each progressive subtree is a fixed-shape balanced tree
(1, 4, 16, ... leaves), so the device tree kernel (ops/merkle.py) applies
per subtree; the spine is a tiny O(log4 n) host fold.
"""

from __future__ import annotations

import numpy as np

from .hashing import hash_bytes
from .merkle import merkleize_chunks, mix_in_length
from .types import (
    Bitlist,
    ByteList,
    Container,
    List,
    _bitfield_bytes,
    pack_bytes,
)

_UNLIMITED = 2**63  # effectively no limit for decode-count checks


def merkleize_progressive(chunks, num_leaves: int = 1) -> bytes:
    """Recursive progressive merkleization (ssz/simple-serialize.md:386-395):
    hash(progressive-rest, balanced-first-num_leaves)."""
    if isinstance(chunks, np.ndarray):
        n = chunks.shape[0]
    else:
        chunks = list(chunks)
        n = len(chunks)
    if n == 0:
        return b"\x00" * 32
    a = merkleize_progressive(chunks[num_leaves:], num_leaves * 4)
    b = merkleize_chunks(chunks[:num_leaves], limit=num_leaves)
    return hash_bytes(a + b)


def mix_in_active_fields(root: bytes, active_fields) -> bytes:
    """ssz/simple-serialize.md:396-398 — active_fields ≤ 256 bits, packed
    as a bitvector chunk."""
    bits = [bool(b) for b in active_fields]
    assert len(bits) <= 256, "active_fields restricted to 256 bits"
    packed = _bitfield_bytes(bits)
    return hash_bytes(bytes(root) + packed.ljust(32, b"\x00"))


# == ProgressiveList[type] ==================================================


class ProgressiveList(List):
    """Variable-length list without a limit; progressive Merkle shape
    (ssz/simple-serialize.md:76-84)."""

    LIMIT: int = _UNLIMITED

    def __class_getitem__(cls, element_type) -> type:
        from .types import _cached_subclass, _coerce_type

        element_type = _coerce_type(element_type)
        return _cached_subclass(
            ("ProgressiveList", element_type),
            lambda: type(
                f"ProgressiveList[{element_type.__name__}]",
                (ProgressiveList,),
                {"ELEMENT_TYPE": element_type, "LIMIT": _UNLIMITED},
            ),
        )

    def _check_init_length(self):
        pass

    @classmethod
    def max_byte_length(cls) -> int:
        raise TypeError("progressive lists have no maximum byte length")

    def get_hash_tree_root(self) -> bytes:
        if self._root_cache is None:
            root = merkleize_progressive(self._element_chunks())
            self._root_cache = mix_in_length(root, len(self._items))
        return self._root_cache


class ProgressiveByteList(ByteList):
    """`ProgressiveList[byte]` alias shape (ssz/simple-serialize.md:120)."""

    LIMIT: int = _UNLIMITED

    def get_hash_tree_root(self) -> bytes:
        root = merkleize_progressive(pack_bytes(bytes(self)))
        return mix_in_length(root, len(self))


class ProgressiveBitlist(Bitlist):
    """Unlimited bitlist with progressive merkleization
    (ssz/simple-serialize.md:85-92, :417-418)."""

    LIMIT: int = _UNLIMITED

    def get_hash_tree_root(self) -> bytes:
        root = merkleize_progressive(pack_bytes(_bitfield_bytes(self._bits)))
        return mix_in_length(root, len(self._bits))


# == ProgressiveContainer(active_fields) ====================================


def ProgressiveContainer(active_fields):
    """Class factory: a container whose root commits to an active-fields
    bitvector over a progressive field tree (ssz/simple-serialize.md:58-75,
    :154-160, :421-422). Subclass it with field annotations; the number of
    fields must equal the number of set bits."""
    bits = [bool(b) for b in active_fields]
    assert len(bits) > 0, "ProgressiveContainer with no configuration is illegal"
    assert len(bits) <= 256, "active_fields restricted to 256 bits"
    assert bits[-1], "active_fields must not end in 0"

    n_active = sum(bits)

    class _ProgressiveContainerBase(Container):
        ACTIVE_FIELDS = tuple(bits)

        def __init_subclass__(cls, **kwargs):
            super().__init_subclass__(**kwargs)
            fields = cls.fields()
            if fields and len(fields) != n_active:
                raise TypeError(
                    f"{cls.__name__}: {len(fields)} fields != "
                    f"{n_active} active bits in active_fields"
                )

        def get_hash_tree_root(self) -> bytes:
            roots = [
                bytes(self._values[name].get_hash_tree_root())
                for name in type(self).fields()
            ]
            return mix_in_active_fields(merkleize_progressive(roots), self.ACTIVE_FIELDS)

    return _ProgressiveContainerBase
