"""Hash primitives for SSZ merkleization.

Semantics match the reference's ``hash(x) = sha256(x).digest()``
(reference: tests/core/pyspec/eth2spec/utils/hash_function.py:8-9).

Two paths:
  * ``hash_bytes`` — single sha256 on host (hashlib, C speed).
  * ``hash_pairs_batch`` — hash N 64-byte (left||right) pairs at once.
    Dispatches to the device kernel (ops.sha256) above a size threshold,
    otherwise loops hashlib on host. The device path is the TPU hot spot
    for full-state merkleization.
"""

from __future__ import annotations

import hashlib

import numpy as np

# Nodes-per-level threshold above which batched pair hashing is routed to the
# JAX kernel. Tuned on the v5e bench: below this, hashlib's C loop wins.
_DEVICE_THRESHOLD = 2048

_use_device = False


def use_device(enable: bool = True) -> None:
    """Route large batched hashing onto the accelerator (ssz.use_tpu seam)."""
    global _use_device
    _use_device = enable


def device_enabled() -> bool:
    return _use_device


def hash_bytes(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


# Below this the per-call ctypes setup outweighs the native core's SHA-NI
# batch win over hashlib's (also-C) one-shot path.
_NATIVE_THRESHOLD = 8


def _hash_pairs_host(pairs: np.ndarray) -> np.ndarray:
    """pairs: uint8[N, 64] -> uint8[N, 32] via the native C sha core (one
    call per batch, SHA-NI when the host has it) or hashlib."""
    n = pairs.shape[0]
    if n >= _NATIVE_THRESHOLD:
        from eth_consensus_specs_tpu import native

        if native.available():
            out = native.sha256_pairs(np.ascontiguousarray(pairs).tobytes())
            return np.frombuffer(out, dtype=np.uint8).reshape(n, 32)
    out = np.empty((n, 32), dtype=np.uint8)
    sha = hashlib.sha256
    for i in range(n):
        out[i] = np.frombuffer(sha(pairs[i].tobytes()).digest(), dtype=np.uint8)
    return out


def hash_pairs_batch(pairs: np.ndarray) -> np.ndarray:
    """Hash N 64-byte messages. pairs: uint8[N, 64] -> uint8[N, 32]."""
    if _use_device and pairs.shape[0] >= _DEVICE_THRESHOLD:
        from eth_consensus_specs_tpu.ops.sha256 import sha256_64B_batch_np

        return sha256_64B_batch_np(pairs)
    return _hash_pairs_host(pairs)
