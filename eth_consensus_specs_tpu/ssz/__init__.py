"""SSZ: SimpleSerialize type system, serialization and merkleization.

Public surface mirrors the reference's
tests/core/pyspec/eth2spec/utils/ssz/{ssz_typing,ssz_impl}.py so spec modules
and tests read identically, while the implementation is first-party and
batches merkleization for the device hash kernel.
"""

from .hashing import hash_bytes, use_device, device_enabled
from .merkle import (
    ZERO_CHUNK,
    zerohashes,
    merkleize_chunks,
    mix_in_length,
    mix_in_selector,
    get_merkle_proof,
    is_valid_merkle_branch,
    pack_bytes,
)
from .types import (
    View,
    BasicView,
    boolean,
    bit,
    uint,
    uint8,
    uint16,
    uint32,
    uint64,
    uint128,
    uint256,
    byte,
    ByteVector,
    ByteList,
    Bytes1,
    Bytes4,
    Bytes8,
    Bytes20,
    Bytes31,
    Bytes32,
    Bytes48,
    Bytes96,
    Bitvector,
    Bitlist,
    List,
    Vector,
    Container,
    Union,
    SSZException,
    DeserializationError,
    serialize,
    deserialize,
    hash_tree_root,
    uint_to_bytes,
)
from .progressive import (
    ProgressiveBitlist,
    ProgressiveByteList,
    ProgressiveContainer,
    ProgressiveList,
    merkleize_progressive,
    mix_in_active_fields,
)
from .gindex import (
    GeneralizedIndex,
    get_generalized_index,
    concat_generalized_indices,
    get_subtree_index,
    get_helper_indices,
    calculate_merkle_root,
    calculate_multi_merkle_root,
    verify_merkle_proof,
    verify_merkle_multiproof,
)

__all__ = [k for k in dir() if not k.startswith("_")]
