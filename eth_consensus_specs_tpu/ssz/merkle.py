"""Chunk-level merkleization.

Implements the ``merkleize(chunks, limit)`` / ``mix_in_length`` /
``mix_in_selector`` rules of the SSZ spec (reference:
ssz/simple-serialize.md:343-433) and the standalone padded-binary-tree
helpers the reference keeps in utils/merkle_minimal.py:7-91.

The per-level pair hashing is batched (numpy byte matrices) so that large
trees — the validator registry, balances, randao mixes — can be handed to
the device kernel in one call per level instead of one hashlib call per node.
"""

from __future__ import annotations

import numpy as np

from . import hashing
from .hashing import hash_bytes, hash_pairs_batch

ZERO_CHUNK = b"\x00" * 32

# zerohashes[i] = root of an all-zero subtree of depth i
# (reference: utils/merkle_minimal.py:7-9)
MAX_DEPTH = 64
zerohashes: list[bytes] = [ZERO_CHUNK]
for _ in range(MAX_DEPTH - 1):
    zerohashes.append(hash_bytes(zerohashes[-1] + zerohashes[-1]))

_ZEROHASH_NP = [np.frombuffer(z, dtype=np.uint8) for z in zerohashes]


def next_power_of_two(v: int) -> int:
    if v <= 1:
        return 1
    return 1 << (v - 1).bit_length()


def _merkleize_array(chunks: np.ndarray, depth: int) -> bytes:
    """Root of `chunks` (uint8[N,32]) padded with zero-subtrees to 2**depth leaves."""
    n = chunks.shape[0]
    if n == 0:
        return zerohashes[depth]
    if hashing.device_enabled():
        from eth_consensus_specs_tpu.ops.merkle import (
            device_subtree_worthwhile,
            merkleize_subtree_device,
        )

        if device_subtree_worthwhile(n):
            # whole real subtree on device, then fold virtual zero-depth on host
            sub_depth = min(depth, max(n - 1, 0).bit_length())
            root = merkleize_subtree_device(chunks, sub_depth)
            for d in range(sub_depth, depth):
                root = hash_bytes(root + zerohashes[d])
            return root
    level = chunks
    for d in range(depth):
        cnt = level.shape[0]
        if cnt % 2 == 1:
            level = np.concatenate([level, _ZEROHASH_NP[d][None, :]], axis=0)
            cnt += 1
        pairs = level.reshape(cnt // 2, 64)
        level = hash_pairs_batch(pairs)
    return level[0].tobytes()


def merkleize_chunks(chunks: list[bytes] | np.ndarray, limit: int | None = None) -> bytes:
    """Merkleize chunks into a single root.

    `limit` is the chunk limit that fixes the tree depth (lists pad virtually
    to their capacity with zero subtrees); None means pad to the next power
    of two of len(chunks) (vectors/containers).
    Matches reference semantics at ssz/simple-serialize.md:393-414 and
    utils/merkle_minimal.py:47-91.
    """
    if isinstance(chunks, np.ndarray):
        arr = chunks
        count = arr.shape[0]
    else:
        count = len(chunks)
        arr = (
            np.frombuffer(b"".join(chunks), dtype=np.uint8).reshape(count, 32)
            if count
            else np.empty((0, 32), dtype=np.uint8)
        )
    if limit is None:
        limit = count
    if count > limit:
        raise ValueError(f"merkleize: {count} chunks exceeds limit {limit}")
    depth = max(limit - 1, 0).bit_length()  # depth of tree with `limit` leaves
    return _merkleize_array(arr, depth)


def mix_in_length(root: bytes, length: int) -> bytes:
    return hash_bytes(root + length.to_bytes(32, "little"))


def mix_in_selector(root: bytes, selector: int) -> bytes:
    return hash_bytes(root + selector.to_bytes(32, "little"))


def pack_bytes(data: bytes) -> np.ndarray:
    """Right-pad serialized bytes to a whole number of 32-byte chunks."""
    n = len(data)
    padded = n + (-n % 32)
    buf = np.zeros(padded, dtype=np.uint8)
    if n:
        buf[:n] = np.frombuffer(data, dtype=np.uint8)
    return buf.reshape(-1, 32)


def get_merkle_proof(chunks: list[bytes], index: int, limit: int | None = None) -> list[bytes]:
    """Single-leaf Merkle branch (reference: utils/merkle_minimal.py:12-44)."""
    count = len(chunks)
    if limit is None:
        limit = count
    depth = max(limit - 1, 0).bit_length()
    # build all levels
    level_nodes: list[list[bytes]] = [list(chunks)]
    for d in range(depth):
        cur = level_nodes[-1]
        if len(cur) % 2 == 1:
            cur = cur + [zerohashes[d]]
            level_nodes[-1] = cur
        nxt = [hash_bytes(cur[i] + cur[i + 1]) for i in range(0, len(cur), 2)]
        level_nodes.append(nxt)
    proof = []
    idx = index
    for d in range(depth):
        sibling = idx ^ 1
        nodes = level_nodes[d]
        proof.append(nodes[sibling] if sibling < len(nodes) else zerohashes[d])
        idx >>= 1
    return proof


def compute_merkle_proof(value, gindex: int) -> list[bytes]:
    """Merkle proof for the subtree at generalized index `gindex` within an
    SSZ value, bottom-up (the order is_valid_merkle_branch consumes).

    Descends through Containers (field boundaries), Lists (length mix-in +
    data subtree — the deneb blob-sidecar inclusion-proof shape, reference
    test/deneb/unittests/test_single_merkle_proof.py) and Vectors; paths
    into packed basic-element sequences end at the packed chunk.  Covers
    the spec's hardcoded light-client gindices (reference:
    ssz/merkle-proofs.md; pysetup/spec_builders/altair.py:40-45)."""
    from .types import (  # lazy: avoid import cycle
        BasicView,
        Container,
        List as SSZList,
        Vector as SSZVector,
        _pack_basic_elements,
        hash_tree_root,
    )

    path = bin(int(gindex))[3:]  # binary digits after the leading 1
    proof: list[bytes] = []
    while path:
        if isinstance(value, Container):
            fields = list(type(value).fields())
            depth = max(len(fields) - 1, 0).bit_length()
            if len(path) < depth:
                raise ValueError("gindex path ends inside a container's chunk tree")
            field_index = int(path[:depth], 2) if depth else 0
            if field_index >= len(fields):
                raise ValueError(f"gindex selects padding chunk {field_index}")
            chunks = [bytes(hash_tree_root(getattr(value, name))) for name in fields]
            # walking top-down: each new segment is DEEPER than what's
            # accumulated, and bottom-up order puts deeper siblings first
            proof = get_merkle_proof(chunks, field_index, limit=1 << depth) + proof
            value = getattr(value, fields[field_index])
            path = path[depth:]
            continue
        if isinstance(value, (SSZList, SSZVector)):
            typ = type(value)
            elem = typ.ELEMENT_TYPE
            basic = issubclass(elem, BasicView)
            if basic:
                per_chunk = 32 // elem.type_byte_length()
                limit = typ.LIMIT if isinstance(value, SSZList) else typ.LENGTH
                limit_chunks = (limit + per_chunk - 1) // per_chunk
                data = bytes(_pack_basic_elements(elem, list(value)).tobytes())
                chunks = [
                    data[i : i + 32] for i in range(0, len(data), 32)
                ] or [ZERO_CHUNK]
            else:
                limit_chunks = typ.LIMIT if isinstance(value, SSZList) else typ.LENGTH
                chunks = [bytes(hash_tree_root(v)) for v in value] or []
            depth = max(limit_chunks - 1, 0).bit_length()
            is_list = isinstance(value, SSZList)
            need = depth + (1 if is_list else 0)
            if len(path) < need:
                raise ValueError("gindex path ends inside a sequence's chunk tree")
            if is_list:
                if path[0] != "0":
                    raise ValueError("gindex selects the length mix-in, not an element")
                path = path[1:]
            chunk_index = int(path[:depth], 2) if depth else 0
            seg = get_merkle_proof(chunks, chunk_index, limit=limit_chunks)
            if is_list:
                seg = seg + [len(value).to_bytes(32, "little")]
            proof = seg + proof
            path = path[depth:]
            if basic:
                if path:
                    raise ValueError("gindex descends past a packed basic chunk")
                return proof
            if chunk_index >= len(value):
                raise ValueError(f"gindex selects padding element {chunk_index}")
            value = value[chunk_index]
            continue
        raise TypeError(
            f"gindex path descends into unsupported type {type(value).__name__}"
        )
    return proof


def is_valid_merkle_branch(leaf: bytes, branch: list[bytes], depth: int, index: int, root: bytes) -> bool:
    """Verify a Merkle branch (reference: specs/phase0/beacon-chain.md:793-810)."""
    value = leaf
    for i in range(depth):
        if index // (2**i) % 2:
            value = hash_bytes(branch[i] + value)
        else:
            value = hash_bytes(value + branch[i])
    return value == root
