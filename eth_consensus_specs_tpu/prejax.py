"""Pre-jax-init device-count bootstrap — the ONE implementation.

XLA reads ``XLA_FLAGS`` exactly once, at backend init, so anything that
wants N virtual CPU devices must mutate the environment BEFORE the
first device query. Three call sites share this logic and had started
to grow copies:

  * ``scripts/serve_bench.py`` / ``scripts/jaxlint.py`` — pre-parse
    ``--chips`` from argv before importing anything jax-touching
    (they load this file by PATH via ``scripts/prejax.py``, so no
    package import happens before the flags are set);
  * the replica child boot (serve/replica.py) — a spawned replica owns
    a fresh interpreter whose backend has not initialized yet, but it
    INHERITS the parent's ``XLA_FLAGS`` (e.g. the bench parent's 8
    virtual devices), so its per-replica ``mesh_chips`` must
    authoritatively REPLACE the inherited device-count flag, not
    defer to it.

This module must import nothing beyond the stdlib ``os``/``sys``: the
scripts load it before jax exists in the process, and the constraint is
what makes that loading order safe.
"""

from __future__ import annotations

import os
import sys

_DEVICE_COUNT_FLAG = "--xla_force_host_platform_device_count"


def parse_int_flag(name: str, argv: list[str] | None = None, default: int = 0) -> int:
    """Pre-parse ``--<name> N`` / ``--<name>=N`` from argv without
    argparse (which would force importing the full CLI module graph
    before the env mutation)."""
    argv = sys.argv if argv is None else argv
    flag = f"--{name}"
    n = default
    for i, a in enumerate(argv):
        if a == flag and i + 1 < len(argv):
            try:
                n = int(argv[i + 1])
            except ValueError:
                pass
        elif a.startswith(flag + "="):
            try:
                n = int(a.split("=", 1)[1])
            except ValueError:
                pass
    return n


def parse_chips(argv: list[str] | None = None, default: int = 0) -> int:
    return parse_int_flag("chips", argv, default)


def parse_replicas(argv: list[str] | None = None, default: int = 0) -> int:
    return parse_int_flag("replicas", argv, default)


def parse_chips_matrix(argv: list[str] | None = None) -> tuple[int, ...]:
    """Pre-parse ``--chips-matrix 1,8`` — the per-replica chip cycle of
    a heterogeneous fleet (serve_bench's fleet-matrix mode)."""
    argv = sys.argv if argv is None else argv
    raw = ""
    for i, a in enumerate(argv):
        if a == "--chips-matrix" and i + 1 < len(argv):
            raw = argv[i + 1]
        elif a.startswith("--chips-matrix="):
            raw = a.split("=", 1)[1]
    try:
        return tuple(int(x) for x in raw.split(",") if x.strip())
    except ValueError:
        return ()


def chips_xla_flags(n: int, existing: str = "") -> str:
    """``XLA_FLAGS`` with the virtual-device-count flag forced to ``n``:
    any existing count flag is stripped, and ``n > 1`` appends the new
    one (``n <= 1`` means the platform default of one device)."""
    toks = [t for t in existing.split() if not t.startswith(_DEVICE_COUNT_FLAG)]
    if n > 1:
        toks.append(f"{_DEVICE_COUNT_FLAG}={n}")
    return " ".join(toks)


def replica_chips_env(n: int, environ=None) -> dict[str, str]:
    """The env assignments a spawned replica applies FIRST (before its
    backend initializes) so it owns exactly ``n`` virtual CPU devices:
    authoritative — an inherited device-count flag (the bench parent's)
    is replaced, because the replica's mesh slice is per-replica policy,
    not process-wide inheritance. Off-cpu the device count is real
    hardware and the flag is left alone (``mesh_chips`` caps the mesh
    instead)."""
    environ = os.environ if environ is None else environ
    if environ.get("JAX_PLATFORMS", "cpu") != "cpu" or n <= 0:
        return {}
    return {"XLA_FLAGS": chips_xla_flags(n, environ.get("XLA_FLAGS", ""))}


def force_virtual_chips(
    default: int = 0, env_var: str | None = "ETH_SPECS_SERVE_CHIPS"
) -> int:
    """Pre-parse ``--chips N`` from argv (falling back to ``env_var``,
    then ``default``) and force that many virtual CPU devices via
    ``XLA_FLAGS`` — only on the cpu platform, only when the flag is not
    already set (an operator-set flag wins), and only for N > 1.
    Defaults ``JAX_PLATFORMS`` to cpu (real-accelerator hosts override
    it and are left alone). Returns the resolved chip count."""
    n = parse_chips()
    if n <= 0 and env_var:
        try:
            n = int(os.environ.get(env_var, "0") or 0)
        except ValueError:
            n = 0
    if n <= 0:
        n = default
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if (
        n > 1
        and os.environ.get("JAX_PLATFORMS") == "cpu"
        and _DEVICE_COUNT_FLAG.lstrip("-") not in flags
    ):
        os.environ["XLA_FLAGS"] = chips_xla_flags(n, flags)
    return n
