"""Optimistic sync: importing blocks before their execution payloads are
validated.

Behavioral parity target: sync/optimistic.md — constants (:45-49), the
OptimisticStore + helper functions (:83-122), optimistic-candidate rules
(:139-156), and the NOT_VALIDATED→{VALID,INVALIDATED} transition rules
(:160-236, prose in the reference; executable here).

The store only *tracks* validation state; the fork-choice Store stays the
single source of block truth. `mark_valid`/`mark_invalidated` implement
the mandated propagation (validity flows to ancestors, invalidity to
descendants) and `process_invalid_payload_status` applies the engine's
`latestValidHash` semantics table (:215-232).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Set

from eth_consensus_specs_tpu.ssz import hash_tree_root

# sync/optimistic.md:45-49 (MUST be user-configurable)
SAFE_SLOTS_TO_IMPORT_OPTIMISTICALLY = 128

ZERO_ROOT = b"\x00" * 32


@dataclass
class OptimisticStore:
    """sync/optimistic.md:83-90."""

    optimistic_roots: Set[bytes]
    head_block_root: bytes
    blocks: Dict[bytes, object] = field(default_factory=dict)
    block_states: Dict[bytes, object] = field(default_factory=dict)


def get_optimistic_store(anchor_block, anchor_state) -> OptimisticStore:
    """Bootstrap from a fully-verified anchor (cf. the reference test
    helper get_optimistic_store, test/utils/randomized_block_tests.py)."""
    root = bytes(hash_tree_root(anchor_block))
    return OptimisticStore(
        optimistic_roots=set(),
        head_block_root=root,
        blocks={root: anchor_block.copy()},
        block_states={root: anchor_state.copy()},
    )


def is_optimistic(opt_store: OptimisticStore, block) -> bool:
    """sync/optimistic.md:93-94."""
    return bytes(hash_tree_root(block)) in opt_store.optimistic_roots


def latest_verified_ancestor(opt_store: OptimisticStore, block):
    """First non-optimistic ancestor (sync/optimistic.md:98-103). The
    block parameter is assumed never INVALIDATED."""
    while True:
        if not is_optimistic(opt_store, block) or bytes(block.parent_root) == ZERO_ROOT:
            return block
        block = opt_store.blocks[bytes(block.parent_root)]


def is_execution_block(block) -> bool:
    """sync/optimistic.md:107-108."""
    payload = block.body.execution_payload
    return payload != type(payload)()


def is_optimistic_candidate_block(opt_store: OptimisticStore, current_slot: int, block) -> bool:
    """Merge-block poisoning guard (sync/optimistic.md:112-121)."""
    if is_execution_block(opt_store.blocks[bytes(block.parent_root)]):
        return True
    if int(block.slot) + SAFE_SLOTS_TO_IMPORT_OPTIMISTICALLY <= int(current_slot):
        return True
    return False


# == status transitions (sync/optimistic.md:160-236) ========================


def add_optimistic_block(opt_store: OptimisticStore, block, state) -> None:
    """Record a block imported with a NOT_VALIDATED payload status."""
    root = bytes(hash_tree_root(block))
    opt_store.blocks[root] = block.copy()
    opt_store.block_states[root] = state.copy()
    opt_store.optimistic_roots.add(root)


def add_verified_block(opt_store: OptimisticStore, block, state) -> None:
    """Record a block whose payload the engine reported VALID."""
    root = bytes(hash_tree_root(block))
    opt_store.blocks[root] = block.copy()
    opt_store.block_states[root] = state.copy()
    opt_store.optimistic_roots.discard(root)


def mark_valid(opt_store: OptimisticStore, block_root: bytes) -> None:
    """NOT_VALIDATED -> VALID; validity propagates to every ancestor
    (sync/optimistic.md:189-193)."""
    block_root = bytes(block_root)
    assert block_root in opt_store.blocks, "unknown block"
    root = block_root
    while root in opt_store.optimistic_roots:
        opt_store.optimistic_roots.discard(root)
        parent = bytes(opt_store.blocks[root].parent_root)
        if parent not in opt_store.blocks:
            break
        root = parent


def _descendants(opt_store: OptimisticStore, root: bytes) -> Set[bytes]:
    children: Dict[bytes, list] = {}
    for r, b in opt_store.blocks.items():
        children.setdefault(bytes(b.parent_root), []).append(r)
    out: Set[bytes] = set()
    frontier = [root]
    while frontier:
        cur = frontier.pop()
        out.add(cur)
        frontier.extend(children.get(cur, []))
    return out


def mark_invalidated(opt_store: OptimisticStore, block_root: bytes) -> Set[bytes]:
    """NOT_VALIDATED -> INVALIDATED; invalidity propagates to every
    descendant, which are removed from the block tree
    (sync/optimistic.md:195-200, :282-287). Returns the removed roots."""
    block_root = bytes(block_root)
    assert block_root in opt_store.blocks, "unknown block"
    removed = _descendants(opt_store, block_root)
    for root in removed:
        opt_store.optimistic_roots.discard(root)
        opt_store.blocks.pop(root, None)
        opt_store.block_states.pop(root, None)
    return removed


def process_invalid_payload_status(
    opt_store: OptimisticStore, block_root: bytes, latest_valid_hash: Optional[bytes]
) -> Set[bytes]:
    """Apply the engine's INVALID verdict per the latestValidHash table
    (sync/optimistic.md:215-232). Returns the invalidated roots."""
    block_root = bytes(block_root)
    assert block_root in opt_store.blocks, "unknown block"

    # chain from anchor to the offending block
    chain = []
    root = block_root
    while root in opt_store.blocks:
        chain.append(root)
        root = bytes(opt_store.blocks[root].parent_root)
    chain.reverse()

    if latest_valid_hash is None:
        invalid_root = block_root
    elif bytes(latest_valid_hash) == b"\x00" * 32:
        # first execution-enabled block in the chain
        invalid_root = block_root
        for r in chain:
            if is_execution_block(opt_store.blocks[r]):
                invalid_root = r
                break
    else:
        # child of the block carrying latestValidHash; unknown hash -> null
        invalid_root = block_root
        for i, r in enumerate(chain):
            payload = opt_store.blocks[r].body.execution_payload
            if bytes(payload.block_hash) == bytes(latest_valid_hash):
                if i + 1 < len(chain):
                    invalid_root = chain[i + 1]
                break
    return mark_invalidated(opt_store, invalid_root)
