"""Sync-protocol modules (reference: /root/reference/sync/)."""

from .optimistic import (
    SAFE_SLOTS_TO_IMPORT_OPTIMISTICALLY,
    OptimisticStore,
    get_optimistic_store,
    is_execution_block,
    is_optimistic,
    is_optimistic_candidate_block,
    latest_verified_ancestor,
)

__all__ = [k for k in dir() if not k.startswith("_")]
