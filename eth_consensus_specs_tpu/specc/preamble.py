"""Runtime environment for compiled reference-spec modules.

The reference's generated modules import only the L2 runtime layer
(reference: pysetup/spec_builders/phase0.py:20-26 — bls, hash,
hash_tree_root/serialize, SSZ types, copy, uint_to_bytes) plus builder-
injected "sundry functions" (floorlog2/ceillog2, the Noop execution
engine, deneb.py:46-79).  This module assembles the same surface from this
framework's first-party runtime.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, NamedTuple, Optional, Protocol, Sequence, Set, Tuple

from eth_consensus_specs_tpu import ssz
from eth_consensus_specs_tpu.ssz.hashing import hash_bytes
from eth_consensus_specs_tpu.utils import bls


class _SpecBLSProxy:
    """utils.bls with AggregatePKs UNGATED.  The aggregate pubkey lands in
    state bytes (SyncCommittee.aggregate_pubkey), and upstream's published
    vectors (generated with bls on) carry the real elliptic-curve sum —
    state content must not depend on the bls_active test switch, on
    either side of the parity seam (forks/altair.py
    eth_aggregate_pubkeys makes the same choice)."""

    def __getattr__(self, name):
        return getattr(bls, name)

    @staticmethod
    def AggregatePKs(pubkeys):
        from eth_consensus_specs_tpu.crypto import signature as _sig

        return _sig.aggregate_pks([bytes(p) for p in pubkeys])


_SPEC_BLS = _SpecBLSProxy()


def floorlog2(x: int) -> ssz.uint64:
    if x < 1:
        raise ValueError(f"floorlog2 accepts only positive values, x={x}")
    return ssz.uint64(int(x).bit_length() - 1)


def ceillog2(x: int) -> ssz.uint64:
    if x < 1:
        raise ValueError(f"ceillog2 accepts only positive values, x={x}")
    return ssz.uint64((int(x) - 1).bit_length())


def _copy(v):
    return v.copy() if hasattr(v, "copy") else v


def _uint_to_bytes(n) -> bytes:
    """remerkleable arithmetic preserves the uint type; this framework's
    returns plain int (range checks on assignment).  At every reference
    call site the degraded value originated as uint64 (narrower types are
    always constructed explicitly, e.g. uint_to_bytes(uint8(round))), so
    re-typing plain ints as uint64 reproduces the reference encoding."""
    if isinstance(n, ssz.uint):
        return ssz.uint_to_bytes(n)
    return ssz.uint64(n).encode_bytes()


def _get_generalized_index(typ, *path):
    from eth_consensus_specs_tpu.ssz.gindex import get_generalized_index

    return get_generalized_index(typ, *path)


class _NoopExecutionEngine:
    """Behavioral match of the reference's NoopExecutionEngine
    (pysetup/spec_builders/deneb.py:46-79): every verification answers
    True, payload building is unsupported."""

    def notify_new_payload(self, *args, **kwargs) -> bool:
        return True

    def notify_forkchoice_updated(self, *args, **kwargs):
        return None

    def get_payload(self, payload_id):
        raise NotImplementedError("no payload building in the noop engine")

    def is_valid_block_hash(self, *args, **kwargs) -> bool:
        return True

    def is_valid_versioned_hashes(self, *args, **kwargs) -> bool:
        return True

    def verify_and_notify_new_payload(self, new_payload_request) -> bool:
        return True


def build_namespace() -> dict:
    """Base globals for a compiled spec module (types + runtime verbs)."""
    ns: dict[str, Any] = {
        # typing surface used by spec code
        "Any": Any,
        "Dict": Dict,
        "Optional": Optional,
        "Sequence": Sequence,
        "Set": Set,
        "Tuple": Tuple,
        "NamedTuple": NamedTuple,
        "Protocol": Protocol,
        "dataclass": dataclass,
        "field": field,
        # SSZ type system (first-party remerkleable-compatible surface)
        "boolean": ssz.boolean,
        "bit": ssz.bit,
        "uint8": ssz.uint8,
        "uint16": ssz.uint16,
        "uint32": ssz.uint32,
        "uint64": ssz.uint64,
        "uint128": ssz.uint128,
        "uint256": ssz.uint256,
        "byte": ssz.byte,
        "Bytes1": ssz.Bytes1,
        "Bytes4": ssz.Bytes4,
        "Bytes8": ssz.Bytes8,
        "Bytes20": ssz.Bytes20,
        "Bytes31": ssz.Bytes31,
        "Bytes32": ssz.Bytes32,
        "Bytes48": ssz.Bytes48,
        "Bytes96": ssz.Bytes96,
        "ByteList": ssz.ByteList,
        "ByteVector": ssz.ByteVector,
        "Bitlist": ssz.Bitlist,
        "Bitvector": ssz.Bitvector,
        "List": ssz.List,
        "Vector": ssz.Vector,
        "Container": ssz.Container,
        "Union": ssz.Union,
        "ProgressiveList": ssz.ProgressiveList,
        "ProgressiveBitlist": ssz.ProgressiveBitlist,
        "ProgressiveContainer": ssz.ProgressiveContainer,
        "ProgressiveByteList": ssz.ProgressiveByteList,
        # runtime verbs (reference L2 layer)
        "bls": _SPEC_BLS,
        "hash": lambda data: ssz.Bytes32(hash_bytes(bytes(data))),
        "hash_tree_root": ssz.hash_tree_root,
        "get_generalized_index": _get_generalized_index,
        "serialize": ssz.serialize,
        "uint_to_bytes": _uint_to_bytes,
        "copy": _copy,
        "floorlog2": floorlog2,
        "ceillog2": ceillog2,
        # execution engine seam (bellatrix+)
        "EXECUTION_ENGINE": _NoopExecutionEngine(),
        "NoopExecutionEngine": _NoopExecutionEngine,
    }
    return ns
