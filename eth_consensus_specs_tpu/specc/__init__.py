"""specc — the markdown->executable-module spec compiler.

The reference's L1 layer (reference: pysetup/md_to_spec.py:19-59,
pysetup/generate_specs.py:95-135) compiles the fenced Python blocks and
constant tables of ``specs/**/*.md`` into one flat module per fork x
preset.  This package is the same compiler re-designed for this framework:

* line-based fence/table extraction instead of a marko AST walk,
* fork composition by collect-and-override across the fork lineage (the
  reference's ``combine_spec_objects`` dict-union,
  pysetup/helpers.py:351-380),
* class re-definition handled by a single final topological exec, so every
  container binds to the *latest* version of its field types (the
  reference achieves this by re-emitting all classes per module,
  pysetup/helpers.py:310-338),
* preset/config values substituted from this framework's own two-tier
  loaders (config/), exactly where the reference substitutes preset yaml.

The compiled module runs on THIS framework's runtime (ssz/, utils/bls) —
which makes it an independent executable oracle derived from the
reference's normative text.  tests/parity/ replays scenarios through both
a compiled module and the class-based spec (forks/) and asserts
byte-identical post-states: that is the repo's reference-parity evidence.
"""

from .compiler import compile_fork, compiled_forks

__all__ = ["compile_fork", "compiled_forks"]
