"""Markdown spec-document parser.

Extracts the executable payload of a reference spec document — fenced
``python`` blocks and definition tables — with a line scanner (the
reference walks a marko AST instead: pysetup/md_to_spec.py:60-120).

Classification rules:

* fenced block starting ``def name(`` — a spec function; if its first
  parameter is ``self`` it is a protocol method (reference collects these
  into protocol classes, md_to_spec.py "protocols" bucket) and is recorded
  separately,
* fenced block whose last decorator-free line starts ``class name(`` — an
  SSZ container / dataclass / protocol class,
* table row ``| `NAME` | `value` |`` with an ALL_CAPS name — a constant
  (preset/config membership decided later against the framework's own
  loaders),
* table row with a CamelCase name whose value cell is a type expression —
  a custom type alias (``Slot`` -> ``uint64``; reference:
  specs/phase0/beacon-chain.md "Custom types").
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field


@dataclass
class ParsedDoc:
    path: str
    functions: dict[str, str] = field(default_factory=dict)
    protocol_methods: dict[str, str] = field(default_factory=dict)
    classes: dict[str, str] = field(default_factory=dict)
    constants: list[tuple[str, str]] = field(default_factory=list)
    custom_types: list[tuple[str, str]] = field(default_factory=list)
    # unified document-order stream of table definitions:
    # ("const" | "ctype", name, value-expression)
    table_items: list[tuple[str, str, str]] = field(default_factory=list)


_CONST_NAME = re.compile(r"^[A-Z][A-Z0-9_]*$")
_TYPE_NAME = re.compile(r"^[A-Z][A-Za-z0-9]*$")
# a type-alias value cell: identifier, optionally subscripted (uint64,
# Bytes32, ByteList[MAX_BYTES_PER_TRANSACTION], ...)
_TYPE_VALUE = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*(\[.*\])?$")
_DEF_RE = re.compile(r"^def\s+([A-Za-z_][A-Za-z0-9_]*)\s*\(\s*([A-Za-z_][A-Za-z0-9_]*)?")
_CLASS_RE = re.compile(r"^class\s+([A-Za-z_][A-Za-z0-9_]*)\s*[(:]")
_BACKTICK = re.compile(r"`([^`]+)`")


def _classify_block(code: str, doc: ParsedDoc) -> None:
    lines = code.strip().splitlines()
    if not lines:
        return
    first_code = 0
    while first_code < len(lines) and lines[first_code].lstrip().startswith("@"):
        first_code += 1
    if first_code >= len(lines):
        return
    head = lines[first_code]
    m = _CLASS_RE.match(head)
    if m:
        doc.classes[m.group(1)] = code
        return
    m = _DEF_RE.match(head)
    if m:
        name, first_arg = m.group(1), m.group(2)
        if first_arg == "self":
            doc.protocol_methods[name] = code
        else:
            doc.functions[name] = code
        return
    # module-level assignment blocks (rare; e.g. trusted-setup injection
    # markers) — ignored; the preamble provides runtime globals.


def _cells(row: str) -> list[str]:
    parts = row.strip().strip("|").split("|")
    return [p.strip() for p in parts]


def _first_backtick(cell: str) -> str | None:
    m = _BACKTICK.search(cell)
    return m.group(1) if m else None


def parse_doc(path: str, text: str | None = None) -> ParsedDoc:
    """Parse a spec markdown document. When `text` is given, the path is
    used only for labeling — the caller already read (and content-pinned)
    the bytes, and the verified bytes must be the consumed bytes."""
    doc = ParsedDoc(path=path)
    if text is None:
        with open(path, encoding="utf-8") as fh:
            text = fh.read()
    lines = text.splitlines()
    i = 0
    n = len(lines)
    while i < n:
        line = lines[i]
        if line.strip().startswith("```python"):
            j = i + 1
            block: list[str] = []
            while j < n and not lines[j].strip().startswith("```"):
                block.append(lines[j])
                j += 1
            _classify_block("\n".join(block), doc)
            i = j + 1
            continue
        if line.lstrip().startswith("|"):
            cells = _cells(line)
            if len(cells) >= 2:
                name = _first_backtick(cells[0])
                value = _first_backtick(cells[1])
                if name and value and not set(name) <= set("-: "):
                    if _CONST_NAME.match(name):
                        doc.constants.append((name, value))
                        doc.table_items.append(("const", name, value))
                    elif _TYPE_NAME.match(name) and _TYPE_VALUE.match(value):
                        doc.custom_types.append((name, value))
                        doc.table_items.append(("ctype", name, value))
            i += 1
            continue
        i += 1
    return doc
