"""Fork composition + module assembly for compiled reference specs.

Mirrors the reference pipeline (pysetup/generate_specs.py:95-135):

  collect docs of the fork lineage -> merge objects (later fork wins) ->
  substitute preset/config values -> emit one executable namespace.

Differences by design: composition happens on parsed dicts instead of
emitted source; classes are exec'd once, topologically sorted, at the end
(the reference's dependency_order_class_objects fixpoint,
pysetup/helpers.py:310-338), so every container's fields bind to the
final version of their types; functions are exec'd with deferred
annotations so excluded layers (fork-choice stores, validator duties)
never produce import-time NameErrors.
"""

from __future__ import annotations

import ast
import json
import os
import types
from functools import lru_cache

from eth_consensus_specs_tpu.config import load_config, load_preset

from .parser import ParsedDoc, parse_doc
from .preamble import build_namespace

REFERENCE_SPECS = os.environ.get("ETH_SPECS_REFERENCE", "/root/reference")

# Content pins: the oracle exec()s code parsed out of the (untrusted)
# reference tree, so every consumed file is pinned by sha256 in pins.json
# (regenerate with scripts/update_specc_pins.py). A mismatching or
# unpinned file refuses to compile unless ETH_SPECS_ALLOW_UNPINNED=1 —
# the executable oracle must not silently change when the tree does.
_PINS_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)), "pins.json")


@lru_cache(maxsize=None)
def _load_pins() -> dict:
    # pins.json is a committed artifact: failing to read it is
    # indistinguishable from tampering, so fail loudly (no silent {}).
    with open(_PINS_PATH) as fh:
        return json.load(fh)


def _read_pinned(path: str) -> bytes:
    """Read a reference file ONCE, verify its pin, and return the verified
    bytes — the caller must parse these bytes, never reopen the path (no
    check-then-use window for a concurrent writer to exploit)."""
    with open(path, "rb") as fh:
        data = fh.read()
    if os.environ.get("ETH_SPECS_ALLOW_UNPINNED"):
        return data
    import hashlib

    rel = os.path.relpath(path, REFERENCE_SPECS)
    got = hashlib.sha256(data).hexdigest()
    want = _load_pins().get(rel)
    if want is None:
        raise RuntimeError(
            f"specc: {rel} is not in pins.json — refusing to exec unpinned "
            "reference content (set ETH_SPECS_ALLOW_UNPINNED=1 to override, "
            "or run scripts/update_specc_pins.py after auditing)"
        )
    if got != want:
        raise RuntimeError(
            f"specc: {rel} content hash {got[:16]}… != pinned {want[:16]}… — "
            "the reference tree changed under the oracle"
        )
    return data


def _require_absent_unpinned(path: str) -> None:
    """A pinned file that has *disappeared* is as suspicious as a modified
    one — deletion must not silently shrink the compiled oracle."""
    if os.environ.get("ETH_SPECS_ALLOW_UNPINNED"):
        return
    rel = os.path.relpath(path, REFERENCE_SPECS)
    if rel in _load_pins():
        raise RuntimeError(f"specc: pinned reference file {rel} is missing from the tree")

# Fork lineage and the per-fork document sets compiled into the oracle.
# beacon-chain + fork (upgrade) + the crypto documents containers depend
# on, through the full lineage phase0..gloas including the fulu DAS math;
# validator/p2p/light-client stay out of the oracle scope (reference doc
# map: pysetup/md_doc_paths.py:78-96).
CHAIN = ["phase0", "altair", "bellatrix", "capella", "deneb", "electra", "fulu", "gloas"]
DOC_SETS: dict[str, list[str]] = {
    "phase0": ["beacon-chain.md"],
    "altair": ["beacon-chain.md", "bls.md", "fork.md"],
    "bellatrix": ["beacon-chain.md", "fork.md"],
    "capella": ["beacon-chain.md", "fork.md"],
    "deneb": ["polynomial-commitments.md", "beacon-chain.md", "fork.md"],
    "electra": ["beacon-chain.md", "fork.md"],
    "fulu": [
        "polynomial-commitments-sampling.md",
        "das-core.md",
        "beacon-chain.md",
        "fork.md",
    ],
    "gloas": ["beacon-chain.md", "fork.md"],
}

# fork-choice documents, compiled on request (compile_fork(..., fork_choice
# =True)) on top of the beacon-chain lineage — the reference compiles
# fork-choice.md per fork into the same flat module
# (pysetup/md_doc_paths.py:36-77). Not every fork modifies fork choice.
FC_DOCS: dict[str, list[str]] = {
    # validator.md precedes fork-choice.md: the handlers read timing
    # constants defined in the honest-validator doc (ATTESTATION_DUE_BPS,
    # reference specs/phase0/validator.md:113 used by fork-choice.md:482)
    "phase0": ["validator.md", "fork-choice.md"],
    "altair": ["validator.md", "fork-choice.md"],
    "bellatrix": ["validator.md", "fork-choice.md"],
    "capella": ["validator.md", "fork-choice.md"],
    "deneb": ["validator.md", "fork-choice.md"],
    "electra": ["validator.md", "fork-choice.md"],
    "fulu": ["validator.md", "fork-choice.md"],
    "gloas": ["validator.md", "fork-choice.md"],
}

_FUTURE = "from __future__ import annotations\n"

# Definitions the reference keeps in documents outside the oracle doc set
# (p2p-interface tables marked `<!-- predefined -->`), with the exact
# expressions from those tables, as (kind, name, expr) fixpoint items.
_PREDEFINED: dict[str, list[tuple[str, str, str]]] = {
    # NodeID/SubnetID custom types (specs/phase0/p2p-interface.md:235-236)
    "phase0": [
        ("ctype", "NodeID", "uint256"),
        ("ctype", "SubnetID", "uint64"),
    ],
    "fulu": [
        (
            "const",
            "KZG_COMMITMENTS_INCLUSION_PROOF_DEPTH",
            "uint64(floorlog2(get_generalized_index(BeaconBlockBody, 'blob_kzg_commitments')))",
        ),
    ],
}

# Classes the reference's per-fork spec builders inject instead of the
# `<!-- predefined-type -->` table aliases (pysetup/spec_builders/deneb.py
# classes(): BLSFieldElement(bls.Scalar), Polynomial; fulu.py classes():
# PolynomialCoeff, Coset, CosetEvals). Semantically equivalent first-party
# definitions; they override the table alias during the class fixpoint.
_BUILDER_CLASSES: dict[str, list[tuple[str, str]]] = {
    "deneb": [
        ("BLSFieldElement", "class BLSFieldElement(bls.Scalar):\n    pass\n"),
        (
            "Polynomial",
            "class Polynomial(list):\n"
            "    def __init__(self, evals=None):\n"
            "        if evals is None:\n"
            "            evals = [BLSFieldElement(0)] * FIELD_ELEMENTS_PER_BLOB\n"
            "        if len(evals) != FIELD_ELEMENTS_PER_BLOB:\n"
            "            raise ValueError('expected FIELD_ELEMENTS_PER_BLOB evals')\n"
            "        super().__init__(evals)\n",
        ),
    ],
    "fulu": [
        (
            "PolynomialCoeff",
            "class PolynomialCoeff(list):\n"
            "    def __init__(self, coeffs):\n"
            "        if len(coeffs) > FIELD_ELEMENTS_PER_EXT_BLOB:\n"
            "            raise ValueError('expected <= FIELD_ELEMENTS_PER_EXT_BLOB coeffs')\n"
            "        super().__init__(coeffs)\n",
        ),
        (
            "Coset",
            "class Coset(list):\n"
            "    def __init__(self, coeffs=None):\n"
            "        if coeffs is None:\n"
            "            coeffs = [BLSFieldElement(0)] * FIELD_ELEMENTS_PER_CELL\n"
            "        if len(coeffs) != FIELD_ELEMENTS_PER_CELL:\n"
            "            raise ValueError('expected FIELD_ELEMENTS_PER_CELL coeffs')\n"
            "        super().__init__(coeffs)\n",
        ),
        (
            "CosetEvals",
            "class CosetEvals(list):\n"
            "    def __init__(self, evals=None):\n"
            "        if evals is None:\n"
            "            evals = [BLSFieldElement(0)] * FIELD_ELEMENTS_PER_CELL\n"
            "        if len(evals) != FIELD_ELEMENTS_PER_CELL:\n"
            "            raise ValueError('expected FIELD_ELEMENTS_PER_CELL coeffs')\n"
            "        super().__init__(evals)\n",
        ),
    ],
}


def compiled_forks() -> list[str]:
    return list(CHAIN)


def _coerce(default, raw):
    """Coerce a preset/config value onto the type the markdown expression
    evaluates to (the reference substitutes yaml text at build time,
    pysetup/md_to_spec.py preset handling)."""
    if default is None:
        return raw
    cls = type(default)
    try:
        if isinstance(default, bytes):
            if isinstance(raw, bytes):
                return cls(raw)
            if isinstance(raw, str) and raw.startswith("0x"):
                return cls(bytes.fromhex(raw[2:]))
            return cls(raw)
        if isinstance(default, bool):
            return bool(raw)
        if isinstance(default, int):
            return cls(int(raw))
    except Exception:
        return raw
    return raw


def _class_deps(name: str, code: str, universe: set[str]) -> set[str]:
    deps: set[str] = set()
    try:
        tree = ast.parse(code)
    except SyntaxError:
        return deps
    for node in ast.walk(tree):
        if isinstance(node, ast.Name) and node.id in universe and node.id != name:
            deps.add(node.id)
    return deps


def _topo_classes(classes: dict[str, str], order: dict[str, int]) -> list[str]:
    universe = set(classes)
    deps = {n: _class_deps(n, c, universe) for n, c in classes.items()}
    placed: list[str] = []
    done: set[str] = set()
    pending = sorted(classes, key=lambda n: order[n])
    while pending:
        progressed = False
        remaining = []
        for n in pending:
            if deps[n] <= done:
                placed.append(n)
                done.add(n)
                progressed = True
            else:
                remaining.append(n)
        if not progressed:
            # cycle (mutually recursive annotations) — fall back to
            # encounter order for the rest
            placed.extend(remaining)
            break
        pending = remaining
    return placed


def _load_trusted_setup(preset_name: str) -> dict:
    """The reference inlines the ceremony trusted setup into generated
    modules (pysetup/md_to_spec.py:521-563); load the same JSON artifact
    from the mounted reference tree."""
    path = os.path.join(
        REFERENCE_SPECS, "presets", preset_name, "trusted_setups", "trusted_setup_4096.json"
    )
    if not os.path.exists(path):
        _require_absent_unpinned(path)
        return {}
    data = json.loads(_read_pinned(path))
    out = {}
    from eth_consensus_specs_tpu import ssz

    def _pts(key, cls):
        vals = data.get(key)
        if vals is None:
            return None
        return tuple(cls(bytes.fromhex(v[2:] if v.startswith("0x") else v)) for v in vals)

    g1m = _pts("g1_monomial", ssz.Bytes48)
    g1l = _pts("g1_lagrange", ssz.Bytes48)
    g2m = _pts("g2_monomial", ssz.Bytes96)
    if g1m:
        out["KZG_SETUP_G1_MONOMIAL"] = g1m
    if g1l:
        out["KZG_SETUP_G1_LAGRANGE"] = g1l
    if g2m:
        out["KZG_SETUP_G2_MONOMIAL"] = g2m
    return out


class CompileReport:
    """What the compiler skipped — surfaced so parity tests can assert the
    skip list stays small and name-addressed."""

    def __init__(self):
        self.skipped_constants: list[tuple[str, str, str]] = []
        self.skipped_types: list[tuple[str, str, str]] = []
        self.protocol_methods: list[str] = []


@lru_cache(maxsize=None)
def compile_fork(
    fork: str,
    preset_name: str = "minimal",
    config_name: str | None = None,
    fork_choice: bool = False,
) -> types.ModuleType:
    """Compile the reference markdown lineage of `fork` into an executable
    module bound to this framework's runtime. With ``fork_choice=True`` the
    lineage's fork-choice.md documents (Store + handlers) compile into the
    same namespace, mirroring the reference's flat per-fork module."""
    if fork not in CHAIN:
        raise ValueError(f"fork {fork!r} not in compiled lineage {CHAIN}")
    lineage = CHAIN[: CHAIN.index(fork) + 1]

    preset = load_preset(preset_name, fork)
    config = load_config(config_name if config_name is not None else preset_name)
    preset_vals = dict(preset.items()) if hasattr(preset, "items") else dict(vars(preset))
    config_vals = dict(config.items()) if hasattr(config, "items") else dict(vars(config))

    mod = types.ModuleType(f"ref_spec_{fork}_{preset_name}")
    ns = mod.__dict__
    ns.update(build_namespace())
    report = CompileReport()
    ns["__specc_report__"] = report
    ns["fork"] = fork

    # fork upgrade functions address the previous fork's spec as a module
    # (e.g. `deneb.get_current_epoch(pre)` in electra's fork.md) — the
    # reference's generated modules import their ancestors the same way
    for ancestor in lineage[:-1]:
        ns[ancestor] = compile_fork(ancestor, preset_name, config_name)

    docs: list[ParsedDoc] = []
    doc_names: list[list[str]] = [list(DOC_SETS[f]) for f in lineage]
    if fork_choice:
        for i, f in enumerate(lineage):
            doc_names[i] += FC_DOCS[f]
    for f, names in zip(lineage, doc_names):
        base = os.path.join(REFERENCE_SPECS, "specs", f)
        for name in names:
            path = os.path.join(base, name)
            if os.path.exists(path):
                docs.append(parse_doc(path, text=_read_pinned(path).decode("utf-8")))
            else:
                _require_absent_unpinned(path)

    # pass 1: custom types + constants in document order (later forks
    # override by re-evaluating the same name).  Definitions whose value
    # expression references a not-yet-defined name (custom types placed
    # before the preset table that sizes them, e.g. bellatrix's
    # Transaction = ByteList[MAX_BYTES_PER_TRANSACTION]) are deferred and
    # retried to a fixpoint — the reference gets the same effect from its
    # class dependency-ordering fixpoint (pysetup/helpers.py:310-338).
    def _apply_item(kind: str, name: str, expr: str) -> str | None:
        """Returns None on success, else the failure reason."""
        if kind == "ctype":
            try:
                base = eval(expr, ns)  # noqa: S307 - spec text, trusted input set
            except Exception as e:
                return str(e)
            # alias, not subclass: type identity must unify across compiled
            # modules and with the framework's own types (Root IS Bytes32),
            # or cross-fork coercion in upgrade functions would see foreign
            # classes (the reference's aliases are SSZ-identical subclasses
            # within ONE flat module, so it never crosses this boundary)
            ns[name] = base
            return None
        default = None
        try:
            default = eval(expr, ns)  # noqa: S307
        except Exception as e:
            if name not in preset_vals and name not in config_vals:
                return str(e)
        if name in preset_vals:
            ns[name] = _coerce(default, preset_vals[name])
        elif name in config_vals:
            ns[name] = _coerce(default, config_vals[name])
        else:
            ns[name] = default
        return None

    pending: list[tuple[str, str, str]] = []
    for doc in docs:
        for kind, name, expr in doc.table_items:
            if _apply_item(kind, name, expr) is not None:
                pending.append((kind, name, expr))
    # "predefined" constants the reference keeps in documents outside the
    # oracle doc set (p2p-interface tables marked `<!-- predefined -->`);
    # same expressions, evaluated through the fixpoint like any table row
    for f in lineage:
        for kind, name, expr in _PREDEFINED.get(f, ()):
            if _apply_item(kind, name, expr) is not None:
                pending.append((kind, name, expr))
    skip_reasons: dict[tuple[str, str], str] = {}

    def _retry_pending() -> bool:
        """One sweep over deferred table items; True if any landed."""
        nonlocal pending
        progressed = False
        still: list[tuple[str, str, str]] = []
        for kind, name, expr in pending:
            reason = _apply_item(kind, name, expr)
            if reason is None:
                progressed = True
            else:
                skip_reasons[(kind, name)] = reason
                still.append((kind, name, expr))
        pending = still
        return progressed

    while pending and _retry_pending():
        pass

    # config vars with no markdown table definition (BLOB_SCHEDULE lives
    # only in configs/*.yaml; the reference exposes EVERY config key on the
    # module via its config.NAME rewrite, pysetup/helpers.py:94-98)
    for cname, cval in config_vals.items():
        ns.setdefault(cname, cval)

    # trusted setup globals (deneb+ polynomial commitments)
    if "deneb" in lineage:
        ns.update(_load_trusted_setup(preset_name))

    # pass 2: classes — override by name across the lineage, then a
    # topologically-ordered exec. A class may need a constant that itself
    # needs an earlier class (fulu's DataColumnSidecar sizes a Vector by
    # KZG_COMMITMENTS_INCLUSION_PROOF_DEPTH = f(BeaconBlockBody) — the
    # reference's predefined p2p constants), so deferred table items are
    # retried between class sweeps to a joint fixpoint.
    classes: dict[str, str] = {}
    order: dict[str, int] = {}
    counter = 0
    for f in lineage:
        for name, code in _BUILDER_CLASSES.get(f, ()):
            if name not in order:
                order[name] = counter
                counter += 1
            classes[name] = code
    for doc in docs:
        for name, code in doc.classes.items():
            if name not in order:
                order[name] = counter
                counter += 1
            classes[name] = code
    remaining = _topo_classes(classes, order)
    while remaining:
        progressed = False
        deferred: list[str] = []
        for name in remaining:
            try:
                # dont_inherit: this module's own `from __future__ import
                # annotations` must NOT leak into spec class bodies —
                # container fields need eagerly-evaluated type annotations
                exec(compile(classes[name], f"<spec:{name}>", "exec", dont_inherit=True), ns)  # noqa: S102
                progressed = True
            except NameError:
                deferred.append(name)
        if _retry_pending():
            progressed = True
        if not progressed:
            # re-raise the first failure with its real error
            exec(compile(classes[deferred[0]], f"<spec:{deferred[0]}>", "exec", dont_inherit=True), ns)  # noqa: S102
        remaining = deferred
    # tail sweep: constants chained behind other just-landed constants
    while pending and _retry_pending():
        pass
    for kind, name, expr in pending:
        target = report.skipped_types if kind == "ctype" else report.skipped_constants
        target.append(
            (name, expr, skip_reasons.get((kind, name), "unresolved after fixpoint"))
        )

    # pass 3: functions (late-bound globals; deferred annotations)
    functions: dict[str, str] = {}
    for doc in docs:
        functions.update(doc.functions)
        report.protocol_methods.extend(doc.protocol_methods)
    for name, code in functions.items():
        exec(  # noqa: S102
            compile(_FUTURE + code, f"<spec:{name}>", "exec", dont_inherit=True), ns
        )

    # builder overrides: the reference's per-fork spec builders replace a
    # few markdown functions whose in-document bodies are explicitly
    # demonstrative (pysetup/spec_builders/altair.py:47-51 swaps
    # eth_aggregate_pubkeys' "interpret + as point addition" sketch for a
    # real aggregation call)
    if "altair" in lineage:
        _bls = ns["bls"]

        def eth_aggregate_pubkeys(pubkeys):
            return _bls.AggregatePKs(list(pubkeys))

        ns["eth_aggregate_pubkeys"] = eth_aggregate_pubkeys
    if fork_choice and "deneb" in lineage:
        # data-availability retrieval stubs the reference injects per fork
        # builder (pysetup/spec_builders/deneb.py:38-43, fulu.py:46) —
        # tests monkeypatch these exactly as the reference's do
        ns.setdefault("retrieve_blobs_and_proofs", lambda beacon_block_root: ([], []))
    if fork_choice and "fulu" in lineage:
        ns.setdefault("retrieve_column_sidecars", lambda beacon_block_root: [])

    ns["preset"] = preset
    ns["config"] = config
    return mod
