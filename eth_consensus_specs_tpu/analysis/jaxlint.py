"""jaxlint — trace-level static analysis of every registered kernel.

speclint (analysis/lint.py) reads source; the bug classes that actually
cost on accelerators live BELOW the AST, in what the tracer builds:
silent host↔device transfers, missed buffer donation, compile-key
functions that under-discriminate traced signatures (the PR 8
mesh-signature class), collectives whose axis binding only fails on a
real N-chip grid, constants baked into every executable, and dtype
drift that doubles a 32-bit kernel's footprint. jaxlint abstract-evals
every entry of the kernel registry (analysis/kernels.py) with
``jax.make_jaxpr`` — no execution, no XLA compile — and walks the
jaxprs:

``transfer-free``
    No explicit ``device_put`` (a device target or a copying transfer)
    and no host-callback primitive inside a hot traced body. Trace-time
    alias annotations (``devices=[None]``, ALIAS semantics — what
    ``jnp.asarray`` leaves behind) are exempt: they move nothing.
``donation-audit``
    Declared donate argnums are ACTUALLY donated (the pjit eqn's
    ``donated_invars``) and usable (an output aval matches — XLA drops
    unusable donations silently); an undeclared input whose aval equals
    an output aval above ``ETH_SPECS_ANALYSIS_DONATE_MIN_BYTES`` is a
    missed in-place opportunity (the ROADMAP item-2 seam) unless the
    registry entry carries a reviewed waiver.
``recompile-surface``
    The registry's LIVE compile-key functions must be injective over
    the bucket grid: one key mapping to two distinct traced signatures
    means the warmup artifact lies and a "warm" boot cold-compiles (or
    worse, replays an alien mesh's shapes).
``collective-audit``
    Every ``psum``/``all_gather``/``ppermute``/... names only axes the
    enclosing shard_map mesh binds; ANY collective in a single-device
    variant is a finding (it would either fail at runtime or silently
    reduce over a one-element axis).
``constant-bloat``
    No single jaxpr constant above ``ETH_SPECS_ANALYSIS_CONST_MAX_BYTES``
    — big closure constants are re-uploaded per executable and bloat
    every compile cache entry; they belong in traced arguments (the
    fr_fft twiddle design).
``x64-drift``
    Every non-weak aval dtype is in the kernel's declared set —
    f64/i64 creeping into a kernel declared 32/uint32 (a python-int
    ``fori_loop`` bound under the x64 flag, say) silently doubles
    register pressure and memory traffic.

Findings reuse speclint's machinery: line-free fingerprints
(``kernel::rule::detail``), the ratcheting baseline
(``jaxlint_baseline.json``, ships EMPTY, ``write_baseline`` refuses
growth), registry-level ``suppress`` as the reviewed escape hatch, and
the shared CLI front end (analysis/cli.py). ``scripts/jaxlint.py`` /
``make jaxlint`` run it; CI's static-analysis job gates zero
non-baselined findings and asserts transfer-free/collective-audit are
NEVER baselined.
"""

from __future__ import annotations

import math
import os

from . import kernels as kernels_mod
from .lint import Finding

ALL_RULES = (
    "transfer-free",
    "donation-audit",
    "recompile-surface",
    "collective-audit",
    "constant-bloat",
    "x64-drift",
)

# rules whose findings may never be baselined (CI asserts this): a
# transfer or an unbound collective in a hot body is a bug, not debt
HARD_RULES = ("transfer-free", "collective-audit")

_CALLBACK_PRIMS = {
    "pure_callback",
    "io_callback",
    "debug_callback",
    "callback",
    "host_callback",
    "outside_call",
    "infeed",
    "outfeed",
}

_COLLECTIVE_PRIMS = {
    "psum",
    "psum2",  # shard_map's check_rep rewrite renames psum
    "pmin",
    "pmax",
    "pmean",
    "all_gather",
    "all_to_all",
    "ppermute",
    "pgather",
    "psum_scatter",
    "reduce_scatter",
    "axis_index",
}


def const_max_bytes() -> int:
    raw = os.environ.get("ETH_SPECS_ANALYSIS_CONST_MAX_BYTES", "")
    try:
        return int(raw) if raw else 1 << 20
    except ValueError:
        return 1 << 20


def donate_min_bytes() -> int:
    raw = os.environ.get("ETH_SPECS_ANALYSIS_DONATE_MIN_BYTES", "")
    try:
        return int(raw) if raw else 1 << 20
    except ValueError:
        return 1 << 20


# --------------------------------------------------------- jaxpr walking --


def iter_eqns(jaxpr):
    """Every eqn of a (Closed)Jaxpr, recursing through sub-jaxprs in eqn
    params (pjit/shard_map/scan/while/cond bodies)."""
    inner = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in inner.eqns:
        yield eqn
        for val in eqn.params.values():
            vals = val if isinstance(val, (list, tuple)) else [val]
            for sub in vals:
                if hasattr(sub, "eqns") or hasattr(sub, "jaxpr"):
                    yield from iter_eqns(sub)


def iter_consts(jaxpr):
    """(const, nbytes) for this jaxpr and every sub-jaxpr's constvals."""
    import numpy as np

    inner = getattr(jaxpr, "jaxpr", jaxpr)
    for const in getattr(jaxpr, "consts", []) or []:
        arr = np.asarray(const)
        yield const, arr.nbytes
    for eqn in inner.eqns:
        for val in eqn.params.values():
            vals = val if isinstance(val, (list, tuple)) else [val]
            for sub in vals:
                if hasattr(sub, "eqns") or hasattr(sub, "jaxpr"):
                    yield from iter_consts(sub)


def iter_avals(jaxpr):
    """Every aval bound anywhere in the jaxpr (invars, outvars, every
    eqn's vars, recursively)."""
    inner = getattr(jaxpr, "jaxpr", jaxpr)
    for v in list(inner.invars) + list(inner.outvars):
        av = getattr(v, "aval", None)
        if av is not None:
            yield av
    for eqn in iter_eqns(jaxpr):
        for v in list(eqn.invars) + list(eqn.outvars):
            av = getattr(v, "aval", None)
            if av is not None:
                yield av


def _aval_nbytes(av) -> int:
    try:
        return int(math.prod(av.shape)) * av.dtype.itemsize
    except (AttributeError, TypeError):
        return 0


def _axis_names(eqn) -> tuple[str, ...]:
    """Axis names a collective eqn reduces/gathers over."""
    for param in ("axes", "axis_name", "axis"):
        val = eqn.params.get(param)
        if val is None:
            continue
        if isinstance(val, (list, tuple)):
            return tuple(str(a) for a in val if isinstance(a, str))
        if isinstance(val, str):
            return (str(val),)
    return ()


def trace_variant(variant):
    """Abstract-eval one registry variant into a ClosedJaxpr (no
    execution, no compile)."""
    import jax

    return jax.make_jaxpr(variant.fn, static_argnums=variant.static_argnums)(
        *variant.args
    )


# ------------------------------------------------------------------ rules --


def _f(spec, rule: str, detail: str, message: str) -> Finding:
    # path = kernel name: the fingerprint becomes kernel::rule::detail
    # (line-free, like speclint's path::rule::symbol)
    return Finding(rule, spec.name, 0, detail, message)


def rule_transfer_free(spec, variant, closed) -> list[Finding]:
    findings = []
    for eqn in iter_eqns(closed):
        name = eqn.primitive.name
        if name == "device_put":
            devices = eqn.params.get("devices", ())
            semantics = eqn.params.get("copy_semantics", ())
            explicit = any(d is not None for d in devices)
            copies = any("ALIAS" not in str(s).upper() for s in semantics)
            if explicit or copies:
                findings.append(
                    _f(
                        spec,
                        "transfer-free",
                        f"{variant.label}:device_put",
                        f"{spec.name}/{variant.label}: explicit device_put "
                        f"inside the traced body (devices={devices}, "
                        f"copy_semantics={semantics}) — a host<->device "
                        "transfer on the hot path, invisible to the span's "
                        "roofline accounting",
                    )
                )
        elif name in _CALLBACK_PRIMS:
            findings.append(
                _f(
                    spec,
                    "transfer-free",
                    f"{variant.label}:{name}",
                    f"{spec.name}/{variant.label}: host-callback primitive "
                    f"{name} inside the traced body — every dispatch "
                    "round-trips the host, serializing the accelerator",
                )
            )
    return findings


def rule_donation_audit(spec, variant, closed) -> list[Finding]:
    """Donation contract on the SINGLE-device variant (mesh variants
    shard the same buffers; donation is declared once, at the jit)."""
    if variant.mesh is not None:
        return []
    findings = []
    inner = closed.jaxpr
    in_avals = [getattr(v, "aval", None) for v in inner.invars]
    out_avals = [getattr(v, "aval", None) for v in inner.outvars]

    # what the traced callable ACTUALLY donates: the top-level pjit eqn
    donated = [False] * len(in_avals)
    for eqn in inner.eqns:
        if eqn.primitive.name == "pjit" and "donated_invars" in eqn.params:
            flags = eqn.params["donated_invars"]
            # map pjit operands back to top-level invars
            positions = {id(v): i for i, v in enumerate(inner.invars)}
            for opv, flag in zip(eqn.invars, flags):
                i = positions.get(id(opv))
                if i is not None and flag:
                    donated[i] = True

    def key(av):
        return (tuple(av.shape), str(av.dtype)) if av is not None else None

    out_keys: dict = {}
    for av in out_avals:
        k = key(av)
        if k is not None:
            out_keys[k] = out_keys.get(k, 0) + 1

    for argnum in spec.donate:
        if argnum >= len(in_avals):
            findings.append(
                _f(
                    spec,
                    "donation-audit",
                    f"declared:arg{argnum}:missing",
                    f"{spec.name}: registry declares donate argnum {argnum} "
                    f"but the traced callable has only {len(in_avals)} flat "
                    "inputs",
                )
            )
            continue
        if not donated[argnum]:
            findings.append(
                _f(
                    spec,
                    "donation-audit",
                    f"declared:arg{argnum}:not-donated",
                    f"{spec.name}: registry declares argnum {argnum} donated "
                    "but the jit does not mark it (donated_invars) — the "
                    "declaration documents an alias the compiler never makes",
                )
            )
        elif out_keys.get(key(in_avals[argnum]), 0) <= 0:
            findings.append(
                _f(
                    spec,
                    "donation-audit",
                    f"declared:arg{argnum}:unusable",
                    f"{spec.name}: donated argnum {argnum} "
                    f"(aval {key(in_avals[argnum])}) matches no output aval — "
                    "XLA silently drops unusable donations; the buffer is "
                    "freed, not reused",
                )
            )
        else:
            out_keys[key(in_avals[argnum])] -= 1

    # missed opportunities: undeclared inputs whose aval equals a
    # remaining output aval, above the byte threshold
    if spec.donation_waiver is None:
        floor = donate_min_bytes()
        budget = dict(out_keys)
        for i, av in enumerate(in_avals):
            if av is None or donated[i] or i in spec.donate:
                continue
            k = key(av)
            if budget.get(k, 0) > 0 and _aval_nbytes(av) >= floor:
                budget[k] -= 1
                findings.append(
                    _f(
                        spec,
                        "donation-audit",
                        f"opportunity:arg{i}",
                        f"{spec.name}: input {i} (aval {k}, "
                        f"{_aval_nbytes(av)} B) matches an output aval and is "
                        "not donated — declare donate_argnums (in-place "
                        "update, halves the resident footprint) or a "
                        "donation_waiver in the kernel registry",
                    )
                )
    return findings


def rule_collective_audit(spec, variant, closed) -> list[Finding]:
    findings = []
    bound: set[str] = set()
    if variant.mesh is not None:
        bound = {str(a) for a in variant.mesh.axis_names}
    for eqn in iter_eqns(closed):
        name = eqn.primitive.name
        if name == "shard_map":
            eqn_mesh = eqn.params.get("mesh")
            if eqn_mesh is not None and variant.mesh is not None:
                eqn_axes = {str(a) for a in getattr(eqn_mesh, "axis_names", ())}
                if eqn_axes - bound:
                    findings.append(
                        _f(
                            spec,
                            "collective-audit",
                            f"{variant.label}:alien-mesh",
                            f"{spec.name}/{variant.label}: shard_map binds "
                            f"axes {sorted(eqn_axes)} but the registry's mesh "
                            f"only has {sorted(bound)} — the variant is "
                            "sharded over a mesh the serve layer never built",
                        )
                    )
            continue
        if name not in _COLLECTIVE_PRIMS:
            continue
        name = "psum" if name == "psum2" else name  # canonical fingerprint
        axes = _axis_names(eqn)
        if variant.mesh is None:
            findings.append(
                _f(
                    spec,
                    "collective-audit",
                    f"{variant.label}:{name}",
                    f"{spec.name}/{variant.label}: collective {name} (axes "
                    f"{axes or '?'}) in the SINGLE-device variant — it either "
                    "fails at dispatch or silently reduces a one-element "
                    "axis; the single-device path must stay collective-free",
                )
            )
        else:
            unbound = [a for a in axes if a not in bound]
            if unbound:
                findings.append(
                    _f(
                        spec,
                        "collective-audit",
                        f"{variant.label}:{name}:{'+'.join(unbound)}",
                        f"{spec.name}/{variant.label}: collective {name} "
                        f"names axes {unbound} that the enclosing shard_map "
                        f"mesh ({sorted(bound)}) does not bind — this only "
                        "explodes on a real multi-chip grid (the mesh-smoke "
                        "class of bug)",
                    )
                )
    return findings


def rule_constant_bloat(spec, variant, closed, limit: int | None = None) -> list[Finding]:
    import numpy as np

    limit = const_max_bytes() if limit is None else limit
    findings = []
    for const, nbytes in iter_consts(closed):
        if nbytes > limit:
            arr = np.asarray(const)
            findings.append(
                _f(
                    spec,
                    "constant-bloat",
                    f"{variant.label}:const{arr.shape}",
                    f"{spec.name}/{variant.label}: {nbytes} B constant "
                    f"(shape {arr.shape}, {arr.dtype}) baked into the jaxpr "
                    f"(limit {limit} B) — closure constants ride every "
                    "executable and bloat each compile-cache entry; pass it "
                    "as a traced argument (the fr_fft twiddle pattern)",
                )
            )
    return findings


def rule_x64_drift(spec, variant, closed) -> list[Finding]:
    findings = []
    seen: set[str] = set()
    for av in iter_avals(closed):
        dt = getattr(av, "dtype", None)
        if dt is None:
            continue
        name = str(dt)
        if name in spec.dtypes or name in seen:
            continue
        # 0-d weak-typed INTEGER scalars are literal-derived trace
        # constants (python ints riding a mask or a shift) — not real
        # buffers. Float weaks get no exemption: a python float leaking
        # into a u32 kernel is a weak f64 (f32 under jax's default-dtype
        # demotion is still drift in an integer kernel), exactly the
        # class the rule exists for
        if (
            getattr(av, "ndim", None) == 0
            and getattr(av, "weak_type", False)
            and getattr(dt, "kind", None) in ("i", "u")
        ):
            continue
        seen.add(name)
        findings.append(
            _f(
                spec,
                "x64-drift",
                f"{variant.label}:{name}",
                f"{spec.name}/{variant.label}: {name} aval (shape "
                f"{tuple(getattr(av, 'shape', ()))}) outside the declared "
                f"dtype set {sorted(spec.dtypes)} — 64-bit drift in a "
                "32-bit kernel doubles register pressure and HBM traffic "
                "(python-int loop bounds under the x64 flag are the usual "
                "culprit)",
            )
        )
    return findings


def rule_recompile_surface(spec, mesh, grid=None) -> list[Finding]:
    """Injectivity of the LIVE compile-key function over the bucket
    grid: one serve/warmup key must map to exactly one traced
    signature. ``grid`` lets analyze() evaluate the key grid once."""
    if spec.key_grid is None:
        return []
    findings = []
    by_key: dict[tuple, set] = {}
    by_sig: dict[tuple, set] = {}
    for key, sig in spec.key_grid(mesh) if grid is None else grid:
        by_key.setdefault(tuple(key), set()).add(tuple(sig))
        by_sig.setdefault(tuple(sig), set()).add(tuple(key))
    for key, sigs in sorted(by_key.items()):
        if len(sigs) > 1:
            findings.append(
                _f(
                    spec,
                    "recompile-surface",
                    f"collision:{':'.join(map(str, key))}",
                    f"{spec.name}: serve key {key} maps to "
                    f"{len(sigs)} DISTINCT traced signatures "
                    f"({sorted(map(str, sigs))[:2]}...) — the warmup artifact "
                    "replays one compile where the dispatch pays several "
                    "(the PR 8 mesh-signature bug class, generalized)",
                )
            )
    for sig, keys in sorted(by_sig.items()):
        if len(keys) > 1:
            # the fingerprint embeds the colliding KEYS (not their
            # count): two unrelated aliasing groups must stay distinct
            # findings, and a baselined one must not mask a future one
            aliased = "+".join(
                ":".join(map(str, k)) for k in sorted(keys)
            )
            findings.append(
                _f(
                    spec,
                    "recompile-surface",
                    f"aliased:{aliased}",
                    f"{spec.name}: {len(keys)} distinct serve keys "
                    f"({sorted(map(str, keys))[:3]}) share ONE traced "
                    "signature — warmup replays compile the same executable "
                    "repeatedly and the compile accounting overcounts",
                )
            )
    return findings


# ------------------------------------------------------------------ engine --


def analyze(
    mesh=None,
    rules: set[str] | None = None,
    registry: tuple | None = None,
    only: set[str] | None = None,
) -> tuple[list[Finding], dict]:
    """Run the selected trace-level rules over the kernel registry.
    Returns (findings, stats). ``mesh=None`` analyzes single-device
    variants only (mesh variants need >= 2 devices); ``only`` narrows to
    a kernel-name subset (the cheap tier-1 test lane uses it)."""
    rules = set(rules) if rules is not None else set(ALL_RULES)
    registry = kernels_mod.REGISTRY if registry is None else registry
    findings: list[Finding] = []
    stats = {"kernels": 0, "variants": 0, "mesh_variants": 0, "keys": 0}
    for spec in registry:
        if only is not None and spec.name not in only:
            continue
        stats["kernels"] += 1
        for variant in spec.build_variants(mesh):
            stats["variants"] += 1
            if variant.mesh is not None:
                stats["mesh_variants"] += 1
            closed = trace_variant(variant)
            if "transfer-free" in rules:
                findings.extend(rule_transfer_free(spec, variant, closed))
            if "donation-audit" in rules:
                findings.extend(rule_donation_audit(spec, variant, closed))
            if "collective-audit" in rules:
                findings.extend(rule_collective_audit(spec, variant, closed))
            if "constant-bloat" in rules:
                findings.extend(rule_constant_bloat(spec, variant, closed))
            if "x64-drift" in rules:
                findings.extend(rule_x64_drift(spec, variant, closed))
        if "recompile-surface" in rules and spec.key_grid is not None:
            grid = spec.key_grid(mesh)
            stats["keys"] += len(grid)
            findings.extend(rule_recompile_surface(spec, mesh, grid))
        if spec.suppress:
            findings = [
                f
                for f in findings
                if not (f.path == spec.name and f.rule in spec.suppress)
            ]
    # one finding per fingerprint: several variants repeating the same
    # defect (e.g. both sha tiles) collapse, like speclint's line-free
    # fingerprints
    seen: set[str] = set()
    unique = []
    for f in sorted(findings, key=lambda f: (f.path, f.rule, f.symbol)):
        if f.fingerprint in seen:
            continue
        seen.add(f.fingerprint)
        unique.append(f)
    return unique, stats
