"""Runtime lock-order watchdog — the live counterpart of the static
``lock-order`` lint rule.

Static analysis sees every *possible* nesting; it cannot see orders
that only materialize through callbacks, thread hand-offs, or dynamic
dispatch. This module watches the orders that actually happen:

  * ``wrap(lock, "serve.admission.AdmissionController._lock")`` returns
    the raw lock unchanged unless ``ETH_SPECS_ANALYSIS_LOCKWATCH=1`` —
    the disabled hot path costs nothing, not even an attribute hop;
  * when enabled, the returned :class:`WatchedLock` records a
    per-thread held stack and, on every acquisition of B while holding
    A, the edge ``A -> B``. The FIRST time the reverse edge of an
    already-seen edge appears — from any thread — it is an
    **inversion**: ``lockwatch.inversions`` is bumped and a
    ``lockwatch.inversion`` event carries both edges' thread names and
    call sites. Two threads running those orders concurrently is the
    textbook ABBA deadlock; seeing both orders live, even sequentially,
    means the schedule exists;
  * lock names deliberately share the static rule's identity namespace
    (``<module>.<NAME>`` / ``<module>.<Class>.<attr>``), so
    :func:`edges` can be diffed directly against
    ``analysis.lint.build_lock_graph`` — tier-1 and serve_bench assert
    the union stays acyclic (runtime confirms the static order, static
    explains the runtime one).

The obs registry / flight / histogram locks are NOT wrapped: they are
terminal by design (they never acquire another lock while held — the
static rule proves it), and the watch tap itself reports through them,
so wrapping them would recurse. Everything above that floor — fault,
serve, ops caches — wraps its locks through :func:`wrap`.

Condition variables wrap their *inner* lock:
``threading.Condition(wrap(threading.RLock(), name))`` — ``wait()``
releases through the wrapper (the full ``_release_save`` protocol), so
the held stack stays truthful across a wait.
"""

from __future__ import annotations

import os
import threading

_ENV = "ETH_SPECS_ANALYSIS_LOCKWATCH"

_WATCH_LOCK = threading.Lock()  # guards the edge/inversion tables only
_EDGES: dict[tuple[str, str], int] = {}
_EDGE_SITES: dict[tuple[str, str], str] = {}
_INVERSIONS: list[dict] = []
_ACQUISITIONS = 0
_TLS = threading.local()


def _reinit_after_fork_in_child() -> None:
    # same contract as every other module lock in this repo (the
    # fork-safety rule's own discipline applies here first)
    global _WATCH_LOCK, _TLS
    _WATCH_LOCK = threading.Lock()
    _TLS = threading.local()


os.register_at_fork(after_in_child=_reinit_after_fork_in_child)


def enabled() -> bool:
    return os.environ.get(_ENV, "0") not in ("0", "false", "")


def _held() -> list[str]:
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    return stack


def _note_acquired(name: str) -> None:
    global _ACQUISITIONS
    stack = _held()
    inversion = None
    with _WATCH_LOCK:
        _ACQUISITIONS += 1
        # a reentrant RLock acquire anywhere in the held stack is not an
        # edge: it cannot block (the lock is already owned), so it can
        # never participate in a deadlock schedule
        if stack and name not in stack:
            edge = (stack[-1], name)
            _EDGES[edge] = _EDGES.get(edge, 0) + 1
            if edge not in _EDGE_SITES:
                _EDGE_SITES[edge] = threading.current_thread().name
            rev = (name, stack[-1])
            if rev in _EDGES and _EDGES[edge] == 1:
                inversion = {
                    "edge": f"{edge[0]} -> {edge[1]}",
                    "reverse": f"{rev[0]} -> {rev[1]}",
                    "thread": threading.current_thread().name,
                    "reverse_thread": _EDGE_SITES.get(rev, "?"),
                }
                _INVERSIONS.append(inversion)
    stack.append(name)
    if inversion is not None:
        # report OUTSIDE the watch lock: the obs registry lock is a leaf
        # lock and must never nest under ours
        from eth_consensus_specs_tpu import obs

        obs.count("lockwatch.inversions", 1)
        obs.event("lockwatch.inversion", **inversion)


def _note_released(name: str) -> None:
    stack = _held()
    # remove the LAST occurrence: Condition.wait releases out of LIFO
    # order relative to locks taken after the condition was entered
    for i in range(len(stack) - 1, -1, -1):
        if stack[i] == name:
            del stack[i]
            break


class WatchedLock:
    """Order-tracking proxy over a ``threading.Lock``/``RLock``. Exposes
    the subset of the lock API this codebase (and ``Condition``) uses."""

    __slots__ = ("_lock", "name")

    def __init__(self, lock, name: str):
        self._lock = lock
        self.name = name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._lock.acquire(blocking, timeout)
        if got:
            _note_acquired(self.name)
        return got

    def release(self) -> None:
        self._lock.release()
        _note_released(self.name)

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> "WatchedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    # Condition-variable protocol: threading.Condition prefers these
    # over plain acquire/release when present, and an RLock inner needs
    # them to release EVERY recursion level across a wait(). The held
    # stack drops all levels of this name on save and restores them on
    # reacquire, so orders observed across a wait stay truthful.

    def _release_save(self):
        inner = getattr(self._lock, "_release_save", None)
        if inner is not None:
            state = inner()
        else:
            self._lock.release()
            state = None
        stack = _held()
        levels = stack.count(self.name)
        for _ in range(levels):
            _note_released(self.name)
        return (state, levels)

    def _acquire_restore(self, saved) -> None:
        state, levels = saved
        inner = getattr(self._lock, "_acquire_restore", None)
        if inner is not None and state is not None:
            inner(state)
        else:
            self._lock.acquire()
        if levels:
            _note_acquired(self.name)  # the reacquire can form new edges
            _held().extend([self.name] * (levels - 1))

    def _is_owned(self) -> bool:
        inner = getattr(self._lock, "_is_owned", None)
        if inner is not None:
            return inner()
        if self._lock.acquire(False):
            self._lock.release()
            return False
        return True


def wrap(lock, name: str):
    """Instrument `lock` under its static-graph identity; a no-op
    passthrough (returns `lock` itself) unless the watchdog env knob is
    on — creation sites call this unconditionally."""
    if not enabled():
        return lock
    return WatchedLock(lock, name)


# --------------------------------------------------------------- reporting --


def edges() -> dict[tuple[str, str], int]:
    with _WATCH_LOCK:
        return dict(_EDGES)


def inversions() -> list[dict]:
    with _WATCH_LOCK:
        return list(_INVERSIONS)


def acquisitions() -> int:
    with _WATCH_LOCK:
        return _ACQUISITIONS


def reset() -> None:
    global _ACQUISITIONS
    with _WATCH_LOCK:
        _EDGES.clear()
        _EDGE_SITES.clear()
        _INVERSIONS.clear()
        _ACQUISITIONS = 0
    _TLS.stack = []


def publish() -> None:
    """Fold the watch totals into the obs registry (gauges — lazy, so
    the per-acquisition hot path never pays an obs call): run epilogues
    (serve_bench, the pytest obs plugin) call this once, making the
    acquisition/edge counts visible in snapshots and expositions next
    to the live ``lockwatch.inversions`` counter."""
    if not enabled():
        return
    from eth_consensus_specs_tpu import obs

    with _WATCH_LOCK:
        acq, nedges = _ACQUISITIONS, len(_EDGES)
    obs.gauge("lockwatch.acquisitions", acq)
    obs.gauge("lockwatch.edges", nedges)


def report() -> dict:
    """Snapshot for gates and the serve_bench report: edge list, counts,
    inversion details."""
    with _WATCH_LOCK:
        return {
            "enabled": enabled(),
            "acquisitions": _ACQUISITIONS,
            "edges": {f"{a} -> {b}": n for (a, b), n in sorted(_EDGES.items())},
            "inversions": list(_INVERSIONS),
        }


def check_against_static(static_edges) -> dict:
    """Cross-check: the union of the static graph and the live edges
    must stay acyclic — a live edge whose reverse is statically
    derivable (or vice versa) is a deadlock schedule the other analysis
    alone could not prove. Returns {"ok": bool, "cycles": [...]}."""
    from . import lint

    union: dict[tuple[str, str], list] = {}
    for (a, b), locs in dict(static_edges).items():
        union[(a, b)] = list(locs) if isinstance(locs, list) else [locs]
    for (a, b), n in edges().items():
        union.setdefault((a, b), []).append(("runtime", n))
    cycles = lint.find_cycles(union)
    return {"ok": not cycles, "cycles": cycles}
