"""speclint — AST-based static analysis encoding this repo's invariants.

The engine is deliberately small: parse every ``.py`` under the package
once into :class:`ModuleInfo`, run each :class:`Rule` (per-module checks
plus whole-project graph checks), filter inline suppressions, then diff
against the ratcheting baseline. Rules encode bugs this codebase has
actually shipped and fixed by hand in review — see docs/analysis.md for
the rule-by-rule history:

``fork-safety``
    Every module-level ``threading.Lock/RLock/Condition`` must be
    re-initialized by an ``os.register_at_fork(after_in_child=...)``
    hook (the PR 6 class: gen-pool forks inheriting locks held by
    front-door supervisor threads), and nothing may start a thread at
    import time.
``blocking-under-lock``
    No ``time.sleep``, socket ``recv``/``accept``/``connect``,
    ``subprocess`` calls, timeout-less ``Future.result()`` or
    queue ``get()`` inside a ``with <lock>:`` body (the PR 3/PR 4
    class: slow or unbounded work serialized under a hot lock).
``lock-order``
    The static lock-acquisition graph — nested ``with`` statements
    plus intra-package call edges — must be acyclic; any cycle is a
    potential deadlock. ``analysis.lockwatch`` is the runtime
    counterpart cross-checking this graph against live acquisitions.
``jit-purity``
    Functions reachable from ``jax.jit``/``vmap`` wrap sites must not
    read ``os.environ``, call ``time.*``/stdlib ``random``, take
    locks, or bump obs counters — the value would be silently baked
    into the compiled program at trace time (the ``_use_device()``
    snapshot-once lesson from PR 3, generalized).
``obs-discipline``
    Device-timed spans (the body assigns ``sp.result``) must declare
    ``work_bytes`` (no roofline verdict otherwise — the 878 Ghash/s
    lesson), and every counter/gauge/histogram/span name must match
    the Prometheus-safe grammar and be declared in ``obs/catalog.py``.
``env-registry``
    Every ``ETH_SPECS_*`` environment read must be declared once in
    ``envreg.py`` (default + docs anchor); declared vars nothing reads
    are stale. docs/env-reference.md is generated from the registry.
``fault-site-registry``
    Every ``fault.check(site)`` / ``fault.corrupt(site)`` literal must
    be declared in ``fault/sites.py``, and every declared site must be
    referenced by a chaos test or the docs failure matrix.

Suppression: a trailing or preceding-line comment
``# speclint: disable=<rule>[,<rule>...]`` silences a finding at that
line — reviewed escape hatches, visible in the diff. Baseline:
``speclint_baseline.json`` maps finding fingerprints (path::rule::symbol,
line-number free so they survive unrelated edits) to counts; the CLI
fails on any non-baselined finding and refuses a baseline update that
grows a rule's count (the ratchet — findings may only be fixed, never
accumulated).
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field

PACKAGE = "eth_consensus_specs_tpu"

_SUPPRESS_RE = re.compile(r"#\s*speclint:\s*disable=([\w,\-]+)")
_LOCK_CTORS = {"Lock", "RLock", "Condition"}
_LOCKISH_NAME_RE = re.compile(r"(?i)(?:^|_)(lock|cond|mutex)s?$|_lock$|_cond$")
_METRIC_GRAMMAR_RE = re.compile(r"^[a-z][a-z0-9_]*(?:\.[a-z0-9_*]+)*$")

ALL_RULES = (
    "fork-safety",
    "blocking-under-lock",
    "lock-order",
    "jit-purity",
    "obs-discipline",
    "env-registry",
    "fault-site-registry",
)


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    symbol: str  # stable anchor: lock/env/site/function name
    message: str

    @property
    def fingerprint(self) -> str:
        # line-number free on purpose: unrelated edits above a finding
        # must not churn the baseline
        return f"{self.path}::{self.rule}::{self.symbol}"

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "symbol": self.symbol,
            "message": self.message,
            "fingerprint": self.fingerprint,
        }


@dataclass
class ModuleInfo:
    """One parsed module plus everything the rules need resolved."""

    path: str  # absolute
    relpath: str  # repo-relative
    modname: str  # dotted, package-relative ("serve.admission")
    tree: ast.Module
    suppressions: dict[int, set[str]] = field(default_factory=dict)
    # local name -> package-relative dotted module it refers to
    import_map: dict[str, str] = field(default_factory=dict)
    # module-level constants: NAME -> str value (for site-name resolution)
    str_consts: dict[str, str] = field(default_factory=dict)
    # module-level lock names -> lineno
    module_locks: dict[str, int] = field(default_factory=dict)
    # (class, attr) -> lineno for self.<attr> = threading.Lock() in methods
    class_locks: dict[tuple[str, str], int] = field(default_factory=dict)


# ------------------------------------------------------------ module parse --


def _parse_suppressions(source: str) -> dict[int, set[str]]:
    out: dict[int, set[str]] = {}
    for lineno, line in enumerate(source.splitlines(), 1):
        m = _SUPPRESS_RE.search(line)
        if m:
            out[lineno] = {r.strip() for r in m.group(1).split(",") if r.strip()}
    return out


def _is_lock_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    if isinstance(fn, ast.Attribute) and fn.attr in _LOCK_CTORS:
        return isinstance(fn.value, ast.Name) and fn.value.id == "threading"
    if isinstance(fn, ast.Name) and fn.id in _LOCK_CTORS:
        return True
    # analysis.lockwatch.wrap(threading.Lock(), "name") — still a lock
    if isinstance(fn, ast.Attribute) and fn.attr == "wrap" and node.args:
        return _is_lock_ctor(node.args[0])
    if isinstance(fn, ast.Name) and fn.id == "wrap" and node.args:
        return _is_lock_ctor(node.args[0])
    return False


def _build_import_map(tree: ast.Module, modname: str) -> dict[str, str]:
    """local name -> package-relative dotted module, for intra-package
    call-edge resolution."""
    out: dict[str, str] = {}
    pkg_parts = modname.split(".")[:-1]  # containing package of this module
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                name = alias.name
                if name.startswith(PACKAGE + ".") or name == PACKAGE:
                    rel = name[len(PACKAGE) + 1 :] if name != PACKAGE else ""
                    out[alias.asname or name.split(".")[-1]] = rel
        elif isinstance(node, ast.ImportFrom):
            if node.level:  # relative import
                base = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                prefix = ".".join(base + ([node.module] if node.module else []))
            elif node.module and (
                node.module == PACKAGE or node.module.startswith(PACKAGE + ".")
            ):
                prefix = node.module[len(PACKAGE) + 1 :] if node.module != PACKAGE else ""
            else:
                continue
            for alias in node.names:
                target = f"{prefix}.{alias.name}" if prefix else alias.name
                out[alias.asname or alias.name] = target
    return out


def load_module(path: str, repo_root: str, package_root: str) -> ModuleInfo | None:
    try:
        with open(path, encoding="utf-8") as fh:
            source = fh.read()
        tree = ast.parse(source, filename=path)
    except (OSError, SyntaxError):
        return None
    relpath = os.path.relpath(path, repo_root).replace(os.sep, "/")
    rel_to_pkg = os.path.relpath(path, package_root).replace(os.sep, "/")
    modname = rel_to_pkg[:-3].replace("/", ".")
    if modname.endswith(".__init__"):
        modname = modname[: -len(".__init__")]
    mi = ModuleInfo(
        path=path,
        relpath=relpath,
        modname=modname,
        tree=tree,
        suppressions=_parse_suppressions(source),
    )
    mi.import_map = _build_import_map(tree, modname)
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt, val = node.targets[0], node.value
            if isinstance(tgt, ast.Name):
                if _is_lock_ctor(val):
                    mi.module_locks[tgt.id] = node.lineno
                elif isinstance(val, ast.Constant) and isinstance(val.value, str):
                    mi.str_consts[tgt.id] = val.value
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
            if node.value is not None and _is_lock_ctor(node.value):
                mi.module_locks[node.target.id] = node.lineno
    for cls in [n for n in tree.body if isinstance(n, ast.ClassDef)]:
        for sub in ast.walk(cls):
            if (
                isinstance(sub, ast.Assign)
                and len(sub.targets) == 1
                and isinstance(sub.targets[0], ast.Attribute)
                and isinstance(sub.targets[0].value, ast.Name)
                and sub.targets[0].value.id == "self"
                and _is_lock_ctor(sub.value)
            ):
                mi.class_locks[(cls.name, sub.targets[0].attr)] = sub.lineno
    return mi


# -------------------------------------------------------- lock identities --


def _lock_identity(mi: ModuleInfo, expr: ast.AST, cls: str | None) -> str | None:
    """Resolve a with-item expression to a stable lock identity, or None
    when it is not recognizably a lock. Identities match what
    analysis.lockwatch wraps use, so the static and runtime graphs share
    a namespace."""
    if isinstance(expr, ast.Name):
        if expr.id in mi.module_locks:
            return f"{mi.modname}.{expr.id}"
        if _LOCKISH_NAME_RE.search(expr.id):
            return f"{mi.modname}.{expr.id}"
        return None
    if (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id == "self"
        and cls is not None
    ):
        if (cls, expr.attr) in mi.class_locks or _LOCKISH_NAME_RE.search(expr.attr):
            return f"{mi.modname}.{cls}.{expr.attr}"
    # ALIAS._LOCK — a module-level lock referenced through an import
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
        target_mod = mi.import_map.get(expr.value.id)
        if target_mod is not None and _LOCKISH_NAME_RE.search(expr.attr):
            return f"{target_mod}.{expr.attr}"
    return None


def _lockish(mi: ModuleInfo, expr: ast.AST, cls: str | None) -> bool:
    return _lock_identity(mi, expr, cls) is not None


# ------------------------------------------------------------- call graph --


@dataclass
class FuncInfo:
    qualname: str  # "serve.service.VerifyService._submit"
    modname: str
    node: ast.AST
    acquires: set[str] = field(default_factory=set)  # lock identities
    calls: set[str] = field(default_factory=set)  # resolved callee qualnames
    # (held lock identity, callee qualname, lineno)
    held_calls: list[tuple[str, str, int]] = field(default_factory=list)
    # (held lock identity, acquired lock identity, lineno)
    held_acquires: list[tuple[str, str, int]] = field(default_factory=list)
    # (held lock identity, lineno, blocking-call description)
    blocking: list[tuple[str, int, str]] = field(default_factory=list)


def _resolve_call(mi: ModuleInfo, node: ast.Call, cls: str | None) -> str | None:
    fn = node.func
    if isinstance(fn, ast.Name):
        return f"{mi.modname}.{fn.id}"  # same-module function (validated later)
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
        base = fn.value.id
        if base == "self" and cls is not None:
            return f"{mi.modname}.{cls}.{fn.attr}"
        target_mod = mi.import_map.get(base)
        if target_mod is not None:
            return f"{target_mod}.{fn.attr}"
    return None


class _FuncWalker(ast.NodeVisitor):
    """Walk one function body tracking the held-lock stack through
    nested ``with`` statements, collecting acquisitions, call edges, and
    blocking-call sites."""

    def __init__(self, mi: ModuleInfo, cls: str | None, fi: FuncInfo):
        self.mi = mi
        self.cls = cls
        self.fi = fi
        self.held: list[str] = []

    def visit_With(self, node: ast.With) -> None:  # noqa: N802 — ast API
        pushed = 0
        for item in node.items:
            expr = item.context_expr
            ident = _lock_identity(self.mi, expr, self.cls)
            if ident is not None:
                self.fi.acquires.add(ident)
                if self.held:
                    self.fi.held_acquires.append((self.held[-1], ident, node.lineno))
                self.held.append(ident)
                pushed += 1
        for stmt in node.body:
            self.visit(stmt)
        for _ in range(pushed):
            self.held.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:  # noqa: N802
        pass  # nested defs are separate functions; don't inherit the held stack

    visit_AsyncFunctionDef = visit_FunctionDef

    def _held_lock_exprs(self) -> set[str]:
        return set(self.held)

    def visit_Call(self, node: ast.Call) -> None:  # noqa: N802
        callee = _resolve_call(self.mi, node, self.cls)
        if callee is not None:
            self.fi.calls.add(callee)
            if self.held:
                self.fi.held_calls.append((self.held[-1], callee, node.lineno))
        if self.held:
            what = _blocking_call(self.mi, node, self.cls, self._held_lock_exprs())
            if what is not None:
                self.fi.blocking.append((self.held[-1], node.lineno, what))
        self.generic_visit(node)


def _blocking_call(
    mi: ModuleInfo, node: ast.Call, cls: str | None, held: set[str]
) -> str | None:
    """Classify a call as blocking-under-lock, or None. ``held`` carries
    the identities of currently held locks so the Condition idiom
    (``self._cond.wait()`` inside ``with self._cond``) is exempt."""
    fn = node.func
    kwnames = {kw.arg for kw in node.keywords}
    if isinstance(fn, ast.Attribute):
        base = fn.value
        if isinstance(base, ast.Name) and base.id == "time" and fn.attr == "sleep":
            return "time.sleep"
        if fn.attr in ("recv", "recv_into", "accept", "connect", "sendall", "makefile"):
            return f"socket .{fn.attr}()"
        if isinstance(base, ast.Name) and base.id in ("subprocess",):
            return f"subprocess.{fn.attr}"
        if isinstance(base, ast.Name) and base.id == "os" and fn.attr == "system":
            return "os.system"
        if fn.attr == "result" and not node.args and "timeout" not in kwnames:
            return "Future.result() without timeout"
        if fn.attr in ("wait", "acquire", "join", "get"):
            # exempt waiting on a lock/condition we already hold (the
            # Condition wait idiom releases it while waiting)
            ident = _lock_identity(mi, base, cls)
            if ident is not None and ident in held:
                return None
            has_timeout = (
                "timeout" in kwnames
                or any(not isinstance(a, ast.Constant) or a.value is not None
                       for a in node.args)
            )
            if fn.attr == "get" and not has_timeout:
                last = base.attr if isinstance(base, ast.Attribute) else (
                    base.id if isinstance(base, ast.Name) else ""
                )
                if re.search(r"(?i)(^|_)q(ueue)?$", last):
                    return "queue get() without timeout"
            if fn.attr == "join" and not node.args and "timeout" not in kwnames:
                last = base.attr if isinstance(base, ast.Attribute) else (
                    base.id if isinstance(base, ast.Name) else ""
                )
                if re.search(r"(?i)(thread|proc|worker)", last):
                    return "thread join() without timeout"
    elif isinstance(fn, ast.Name):
        if fn.id == "sleep":
            return "sleep"
    return None


def _iter_functions(mi: ModuleInfo):
    """Yield (cls_or_None, FunctionDef) for every function in the module,
    including methods (one level of class nesting, which is all this
    codebase uses)."""
    for node in mi.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield None, node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield node.name, sub


def build_function_table(modules: list[ModuleInfo]) -> dict[str, FuncInfo]:
    table: dict[str, FuncInfo] = {}
    for mi in modules:
        for cls, fn in _iter_functions(mi):
            qual = f"{mi.modname}.{cls}.{fn.name}" if cls else f"{mi.modname}.{fn.name}"
            fi = FuncInfo(qualname=qual, modname=mi.modname, node=fn)
            w = _FuncWalker(mi, cls, fi)
            for stmt in fn.body:
                w.visit(stmt)
            table[qual] = fi
    # keep only call edges that resolve to a known function
    for fi in table.values():
        fi.calls = {c for c in fi.calls if c in table}
        fi.held_calls = [(h, c, ln) for h, c, ln in fi.held_calls if c in table]
    return table


def may_acquire_fixpoint(table: dict[str, FuncInfo]) -> dict[str, set[str]]:
    """Transitive lock-acquisition sets over intra-package call edges."""
    may: dict[str, set[str]] = {q: set(fi.acquires) for q, fi in table.items()}
    changed = True
    while changed:
        changed = False
        for q, fi in table.items():
            for callee in fi.calls:
                extra = may.get(callee, set()) - may[q]
                if extra:
                    may[q] |= extra
                    changed = True
    return may


def build_lock_graph(
    modules: list[ModuleInfo], table: dict[str, FuncInfo] | None = None
) -> dict:
    """The static lock-order graph: direct nested-with edges plus edges
    through intra-package calls made while a lock is held. Returns
    {"edges": {(a, b): [(relpath, lineno), ...]}, "locks": set[str]}.
    ``analysis.lockwatch`` cross-checks its live edges against this."""
    if table is None:
        table = build_function_table(modules)
    may = may_acquire_fixpoint(table)
    by_mod = {mi.modname: mi for mi in modules}
    edges: dict[tuple[str, str], list[tuple[str, int]]] = {}

    def add(a: str, b: str, modname: str, lineno: int) -> None:
        if a == b:
            return
        relpath = by_mod[modname].relpath if modname in by_mod else modname
        edges.setdefault((a, b), []).append((relpath, lineno))

    for fi in table.values():
        for a, b, ln in fi.held_acquires:
            add(a, b, fi.modname, ln)
        for a, callee, ln in fi.held_calls:
            for b in may.get(callee, ()):
                add(a, b, fi.modname, ln)
    locks = {lk for pair in edges for lk in pair}
    for mi in modules:
        for name in mi.module_locks:
            locks.add(f"{mi.modname}.{name}")
        for (cls, attr) in mi.class_locks:
            locks.add(f"{mi.modname}.{cls}.{attr}")
    return {"edges": edges, "locks": locks}


def find_cycles(edges: dict[tuple[str, str], list]) -> list[list[str]]:
    """Every elementary cycle's node set (via strongly connected
    components — one finding per SCC keeps the report stable)."""
    graph: dict[str, set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    onstack: set[str] = set()
    stack: list[str] = []
    counter = [0]
    sccs: list[list[str]] = []

    def strongconnect(v: str) -> None:  # iterative Tarjan
        work = [(v, iter(sorted(graph[v])))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        onstack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    onstack.add(w)
                    work.append((w, iter(sorted(graph[w]))))
                    advanced = True
                    break
                if w in onstack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    onstack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1 or node in graph.get(node, ()):
                    sccs.append(sorted(comp))

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    return sccs


# ------------------------------------------------------------------ rules --


def rule_fork_safety(mi: ModuleInfo) -> list[Finding]:
    findings: list[Finding] = []
    has_at_fork = False
    for node in ast.walk(mi.tree):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr == "register_at_fork":
                has_at_fork = True
    # names re-assigned under a `global` declaration inside any function
    # (the re-init hook pattern: fault/spec.py:81, obs/flight.py:79)
    reinit: set[str] = set()
    for _, fn in _iter_functions(mi):
        globals_declared: set[str] = set()
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Global):
                globals_declared.update(sub.names)
            elif isinstance(sub, ast.Assign):
                for tgt in sub.targets:
                    if isinstance(tgt, ast.Name) and tgt.id in globals_declared:
                        reinit.add(tgt.id)
    for name, lineno in sorted(mi.module_locks.items()):
        if name not in reinit or not has_at_fork:
            why = (
                "no os.register_at_fork hook in this module"
                if not has_at_fork
                else "no at-fork re-init function reassigns it (global + assign)"
            )
            findings.append(
                Finding(
                    "fork-safety",
                    mi.relpath,
                    lineno,
                    name,
                    f"module-level lock {name} is not re-initialized after fork: "
                    f"{why}; a forked child inherits it possibly held by a "
                    "thread that does not exist there (see fault/spec.py:81)",
                )
            )
    # thread creation at import time: Thread(...).start() in module body
    for node in mi.tree.body:
        for sub in ast.walk(node) if not isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ) else ():
            if (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "start"
                and isinstance(sub.func.value, ast.Call)
            ):
                inner = sub.func.value.func
                name = inner.attr if isinstance(inner, ast.Attribute) else (
                    inner.id if isinstance(inner, ast.Name) else ""
                )
                if name == "Thread":
                    findings.append(
                        Finding(
                            "fork-safety",
                            mi.relpath,
                            sub.lineno,
                            "import-time-thread",
                            "thread started at import time: importing this "
                            "module in a fork-then-import process leaks a "
                            "thread every consumer pays for",
                        )
                    )
    return findings


def rule_blocking_under_lock(
    modules: list[ModuleInfo], table: dict[str, FuncInfo]
) -> list[Finding]:
    by_mod = {mi.modname: mi for mi in modules}
    findings: list[Finding] = []
    for fi in table.values():
        mi = by_mod[fi.modname]
        qual = fi.qualname[len(fi.modname) + 1 :]
        for held, lineno, what in fi.blocking:
            findings.append(
                Finding(
                    "blocking-under-lock",
                    mi.relpath,
                    lineno,
                    f"{qual}:{what}",
                    f"{what} inside `with {held}:` — every other thread "
                    "contending this lock stalls for the call's full "
                    "duration (the PR 3 _H2G2 / PR 4 reservoir class)",
                )
            )
    return findings


def rule_lock_order(
    modules: list[ModuleInfo], table: dict[str, FuncInfo] | None = None
) -> list[Finding]:
    graph = build_lock_graph(modules, table)
    findings: list[Finding] = []
    for comp in find_cycles(graph["edges"]):
        sites: list[str] = []
        first_loc: tuple[str, int] | None = None
        for (a, b), locs in sorted(graph["edges"].items()):
            if a in comp and b in comp:
                sites.append(f"{a}->{b} at {locs[0][0]}:{locs[0][1]}")
                if first_loc is None:
                    first_loc = locs[0]
        path, line = first_loc if first_loc else ("?", 0)
        findings.append(
            Finding(
                "lock-order",
                path,
                line,
                "+".join(comp),
                "potential deadlock: lock-acquisition cycle "
                + " | ".join(sites),
            )
        )
    return findings


_JIT_WRAPPERS = {"jit", "vmap", "pmap", "shard_map"}


def _is_jit_expr(node: ast.AST) -> bool:
    if isinstance(node, ast.Attribute):
        return node.attr in _JIT_WRAPPERS
    if isinstance(node, ast.Name):
        return node.id in _JIT_WRAPPERS
    if isinstance(node, ast.Call):
        # partial(jax.jit, ...) / functools.partial(jax.jit, ...)
        fn = node.func
        is_partial = (isinstance(fn, ast.Name) and fn.id == "partial") or (
            isinstance(fn, ast.Attribute) and fn.attr == "partial"
        )
        if is_partial and node.args:
            return _is_jit_expr(node.args[0])
        return _is_jit_expr(fn)
    return False


def _jit_root_names(mi: ModuleInfo) -> dict[str, int]:
    """Function names in this module wrapped by jax.jit/vmap — via
    decorator, ``jax.jit(f)`` call, or ``partial(jax.jit, ...)(f)``."""
    roots: dict[str, int] = {}
    for node in mi.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _is_jit_expr(dec):
                    roots[node.name] = node.lineno
    for node in ast.walk(mi.tree):
        if isinstance(node, ast.Call) and _is_jit_expr(node.func):
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    roots.setdefault(arg.id, node.lineno)
    return roots


def _nested_defs(mi: ModuleInfo) -> dict[str, ast.AST]:
    """FunctionDefs NOT at module/class level (the shard_map-closure
    factories' `local` pattern), by name — reachable only through the
    wrap sites, so outside the module-level root scan."""
    top: set[int] = set()
    for node in mi.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            top.add(id(node))
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    top.add(id(sub))
    out: dict[str, ast.AST] = {}
    for node in ast.walk(mi.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if id(node) not in top:
                out[node.name] = node
    return out


def _shard_map_closures(
    mi: ModuleInfo, table: dict[str, FuncInfo]
) -> tuple[list[Finding], set[str]]:
    """Traced bodies reachable ONLY through a wrap site (PR 8's
    shard_map idiom): lambdas passed to jit/vmap/shard_map, and nested
    function defs referenced by name. Returns the purity findings inside
    those bodies plus the module-level functions they call — extra
    reachability roots for :func:`rule_jit_purity`. Bare-name calls
    resolve through the import map first (``from ops.x import f`` then
    ``shard_map(lambda v: f(v), ...)`` roots ``ops.x.f``)."""
    nested = _nested_defs(mi)
    roots: set[str] = set()
    findings: list[Finding] = []
    visited: set[int] = set()

    def visit(node: ast.AST, label: str) -> None:
        if id(node) in visited:
            return
        visited.add(id(node))
        for lineno, what in _purity_violations(mi, node, None):
            findings.append(
                Finding(
                    "jit-purity",
                    mi.relpath,
                    lineno,
                    f"{label}:{what.split()[0]}",
                    f"{mi.modname}.{label} is traced through a "
                    f"jit/vmap/shard_map wrap site and {what}: the value is "
                    "read ONCE at trace time and baked into every later "
                    "execution of the compiled program",
                )
            )
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            fn = sub.func
            if isinstance(fn, ast.Name):
                target = mi.import_map.get(fn.id)
                qual = target if target is not None else f"{mi.modname}.{fn.id}"
                if qual in table:
                    roots.add(qual)
                elif fn.id in nested:
                    visit(nested[fn.id], fn.id)
            elif isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
                target_mod = mi.import_map.get(fn.value.id)
                if target_mod is not None and f"{target_mod}.{fn.attr}" in table:
                    roots.add(f"{target_mod}.{fn.attr}")

    for node in ast.walk(mi.tree):
        if not (isinstance(node, ast.Call) and _is_jit_expr(node.func)):
            continue
        for arg in node.args:
            if isinstance(arg, ast.Lambda):
                visit(arg, "<lambda>")
            elif (
                isinstance(arg, ast.Name)
                and arg.id in nested
                and f"{mi.modname}.{arg.id}" not in table
            ):
                visit(nested[arg.id], arg.id)
    return findings, roots


def _purity_violations(mi: ModuleInfo, fn: ast.AST, cls: str | None) -> list[tuple[int, str]]:
    out: list[tuple[int, str]] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Attribute) and node.attr == "environ":
            if isinstance(node.value, ast.Name) and node.value.id == "os":
                out.append((node.lineno, "reads os.environ"))
        elif isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
                base, attr = f.value.id, f.attr
                if base == "os" and attr == "getenv":
                    out.append((node.lineno, "reads os.environ (os.getenv)"))
                elif base == "time" and attr in (
                    "time", "monotonic", "perf_counter", "sleep", "time_ns",
                ):
                    out.append((node.lineno, f"calls time.{attr}"))
                elif base == "random" and "random" not in mi.import_map:
                    out.append((node.lineno, f"calls stdlib random.{attr}"))
                elif base == "obs" and attr in (
                    "count", "event", "gauge", "observe", "span", "bytes_moved",
                ):
                    out.append((node.lineno, f"touches obs.{attr}"))
        elif isinstance(node, ast.With):
            for item in node.items:
                if _lockish(mi, item.context_expr, cls):
                    out.append((node.lineno, "acquires a lock"))
    return out


def rule_jit_purity(
    modules: list[ModuleInfo], table: dict[str, FuncInfo] | None = None
) -> list[Finding]:
    if table is None:
        table = build_function_table(modules)
    roots: dict[str, int] = {}
    closure_findings: list[Finding] = []
    for mi in modules:
        for name, lineno in _jit_root_names(mi).items():
            qual = f"{mi.modname}.{name}"
            if qual in table:
                roots[qual] = lineno
        # shard_map/jit wrap sites whose traced body is a lambda or a
        # nested def (the PR 8 sharded-kernel factories): the body is
        # purity-checked directly and the module-level functions it
        # calls join the root set
        extra_findings, extra_roots = _shard_map_closures(mi, table)
        closure_findings.extend(extra_findings)
        for qual in extra_roots:
            roots.setdefault(qual, 0)
    # reachability over intra-package call edges
    reachable: set[str] = set()
    frontier = list(roots)
    while frontier:
        q = frontier.pop()
        if q in reachable:
            continue
        reachable.add(q)
        frontier.extend(table[q].calls - reachable)
    by_mod = {mi.modname: mi for mi in modules}
    findings: list[Finding] = list(closure_findings)
    for qual in sorted(reachable):
        fi = table[qual]
        mi = by_mod[fi.modname]
        cls = qual.rsplit(".", 2)[-2] if qual.count(".") >= 2 and qual.rsplit(
            ".", 2
        )[-2][0:1].isupper() else None
        for lineno, what in _purity_violations(mi, fi.node, cls):
            findings.append(
                Finding(
                    "jit-purity",
                    mi.relpath,
                    lineno,
                    f"{qual.rsplit('.', 1)[-1]}:{what.split()[0]}",
                    f"{qual} is reachable from a jax.jit/vmap wrap site and "
                    f"{what}: the value is read ONCE at trace time and baked "
                    "into every later execution of the compiled program",
                )
            )
    return findings


_METRIC_METHODS = {"count", "gauge", "observe", "span", "bytes_moved"}
_METRIC_KIND = {
    "count": "counter",
    "gauge": "gauge",
    "observe": "histogram",
    "span": "span",
    "bytes_moved": "counter",
}


def _literal_name(node: ast.AST) -> str | None:
    """A str constant, f-string (placeholders -> '*'), or conditional of
    constants; None when dynamic beyond that."""
    names = _literal_names(node)
    return names[0] if names else None


def _literal_names(node: ast.AST) -> list[str]:
    """Every name a metric/site argument can statically evaluate to —
    a conditional expression contributes BOTH branches (the router's
    ``"...affinity" if k == 0 else "...fallback"`` idiom)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            parts.append(v.value if isinstance(v, ast.Constant) else "*")
        return ["".join(parts)]
    if isinstance(node, ast.IfExp):
        return _literal_names(node.body) + _literal_names(node.orelse)
    return []


# helpers that EMIT a derived metric family: calling them is emitting.
# observe_compile_ms(op, ...) / first_dispatch(op, *dims) record into the
# serve.compile_ms.<op> histograms (serve/buckets.py) — before this scan
# those call sites were invisible to the catalog check (a PR 5 gap: the
# metric literal lives in the helper, the FAMILY key at the call site)
_DERIVED_EMITTERS = {
    "observe_compile_ms": ("histogram", "serve.compile_ms.{}"),
    "first_dispatch": ("histogram", "serve.compile_ms.{}"),
}


def rule_obs_discipline(mi: ModuleInfo, catalog) -> list[Finding]:
    if mi.modname in ("obs.catalog",):
        return []
    findings: list[Finding] = []
    emitting_bases = {"obs", "reg", "registry"}
    for node in ast.walk(mi.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        attr = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None
        )
        if attr in _DERIVED_EMITTERS and mi.modname != "serve.buckets":
            # serve.buckets itself is the helper's home: its internal
            # obs.observe(...) literals are scanned by the branch below
            kind, template = _DERIVED_EMITTERS[attr]
            for op in _literal_names(node.args[0]) if node.args else []:
                name = template.format(op)
                if not _METRIC_GRAMMAR_RE.match(name):
                    findings.append(
                        Finding(
                            "obs-discipline",
                            mi.relpath,
                            node.lineno,
                            f"grammar:{name}",
                            f"derived metric name {name!r} (via {attr}) "
                            "violates the grammar "
                            "[a-z][a-z0-9_]*(.[a-z0-9_]+)* — it would "
                            "collapse lossily in the Prometheus exposition",
                        )
                    )
                elif catalog is not None and not catalog.declared(kind, name):
                    findings.append(
                        Finding(
                            "obs-discipline",
                            mi.relpath,
                            node.lineno,
                            f"undeclared:{name}",
                            f"{kind} {name!r} (emitted through {attr}) is not "
                            "declared in obs/catalog.py — compile-timing "
                            "families added at dispatch sites must be "
                            "visible to exposition consumers too",
                        )
                    )
            continue
        if not (
            isinstance(fn, ast.Attribute)
            and fn.attr in _METRIC_METHODS
            and isinstance(fn.value, ast.Name)
            and fn.value.id in emitting_bases
        ):
            continue
        if not node.args:
            continue
        kind = _METRIC_KIND[fn.attr]
        # a conditional name contributes every branch; fully dynamic
        # names (bare variables) are the delta/merge plumbing — skipped
        for name in _literal_names(node.args[0]):
            if fn.attr == "bytes_moved":
                name = f"{name}.bytes_moved"
            if not _METRIC_GRAMMAR_RE.match(name):
                findings.append(
                    Finding(
                        "obs-discipline",
                        mi.relpath,
                        node.lineno,
                        f"grammar:{name}",
                        f"metric name {name!r} violates the grammar "
                        "[a-z][a-z0-9_]*(.[a-z0-9_]+)* — it would collapse "
                        "lossily in the Prometheus exposition",
                    )
                )
            elif catalog is not None and not catalog.declared(kind, name):
                findings.append(
                    Finding(
                        "obs-discipline",
                        mi.relpath,
                        node.lineno,
                        f"undeclared:{name}",
                        f"{kind} {name!r} is not declared in obs/catalog.py — "
                        "exposition consumers (dashboards, SLOs, "
                        "validate_text) can't see undeclared drift",
                    )
                )
    # device-timed spans must declare work_bytes: `with obs.span(...) as
    # sp:` whose body assigns sp.result gets a roofline verdict ONLY when
    # the span call passed work_bytes
    for node in ast.walk(mi.tree):
        if not isinstance(node, ast.With):
            continue
        for item in node.items:
            call = item.context_expr
            if not (
                isinstance(call, ast.Call)
                and isinstance(call.func, ast.Attribute)
                and call.func.attr == "span"
                and isinstance(call.func.value, ast.Name)
                and call.func.value.id in emitting_bases
                and isinstance(item.optional_vars, ast.Name)
            ):
                continue
            sp = item.optional_vars.id
            assigns_result = any(
                isinstance(sub, ast.Assign)
                and any(
                    isinstance(t, ast.Attribute)
                    and t.attr == "result"
                    and isinstance(t.value, ast.Name)
                    and t.value.id == sp
                    for t in sub.targets
                )
                for stmt in node.body
                for sub in ast.walk(stmt)
            )
            has_work_bytes = any(kw.arg == "work_bytes" for kw in call.keywords)
            name = _literal_name(call.args[0]) if call.args else "?"
            if assigns_result and not has_work_bytes:
                findings.append(
                    Finding(
                        "obs-discipline",
                        mi.relpath,
                        node.lineno,
                        f"no-work-bytes:{name}",
                        f"span {name!r} blocks on a device result "
                        f"({sp}.result) but declares no work_bytes — no "
                        "roofline verdict, the exact blind spot that let "
                        "878 Ghash/s ship",
                    )
                )
    return findings


def rule_env_registry(mi: ModuleInfo, declared_env: set[str]) -> list[Finding]:
    if mi.modname in ("envreg",):
        return []
    findings: list[Finding] = []
    for node in ast.walk(mi.tree):
        var = None
        if isinstance(node, ast.Call):
            fn = node.func
            if (
                isinstance(fn, ast.Attribute)
                and fn.attr == "get"
                and isinstance(fn.value, ast.Attribute)
                and fn.value.attr == "environ"
            ) or (
                isinstance(fn, ast.Attribute)
                and fn.attr == "getenv"
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "os"
            ):
                if node.args and isinstance(node.args[0], ast.Constant):
                    var = node.args[0].value
        elif isinstance(node, ast.Subscript):
            if (
                isinstance(node.value, ast.Attribute)
                and node.value.attr == "environ"
                and isinstance(node.slice, ast.Constant)
            ):
                var = node.slice.value
        if (
            isinstance(var, str)
            and var.startswith("ETH_SPECS_")
            and var not in declared_env
        ):
            findings.append(
                Finding(
                    "env-registry",
                    mi.relpath,
                    node.lineno,
                    var,
                    f"{var} is read here but not declared in envreg.py — "
                    "undeclared knobs never reach docs/env-reference.md and "
                    "rot out of the operator's view",
                )
            )
    return findings


def rule_fault_site_registry(
    mi: ModuleInfo, declared_sites: set[str]
) -> list[Finding]:
    if mi.modname in ("fault.sites", "fault.spec"):
        return []
    findings: list[Finding] = []
    for node in ast.walk(mi.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        is_fault_call = (
            isinstance(fn, ast.Attribute)
            and fn.attr in ("check", "corrupt")
            and isinstance(fn.value, ast.Name)
            and fn.value.id == "fault"
        )
        site_arg = None
        if is_fault_call and node.args:
            site_arg = node.args[0]
        else:
            for kw in node.keywords:
                if kw.arg == "site":
                    site_arg = kw.value
        if site_arg is None:
            continue
        sites: list[str] = _literal_names(site_arg)
        if isinstance(site_arg, ast.Name):
            const = mi.str_consts.get(site_arg.id)
            if const is not None:
                sites = [const]
        for site in sites:
            if "*" in site:
                continue
            if site not in declared_sites:
                findings.append(
                    Finding(
                        "fault-site-registry",
                        mi.relpath,
                        node.lineno,
                        site,
                        f"fault site {site!r} is not declared in "
                        "fault/sites.py — undeclared sites are invisible to "
                        "the chaos grammar docs and nothing proves a test "
                        "ever injects them",
                    )
                )
    return findings


def check_site_references(repo_root: str, sites: dict) -> list[Finding]:
    """Project-level completeness: every declared fault site must appear
    in a chaos test (tests/) or the docs failure matrix (docs/)."""
    corpus: list[str] = []
    for base, exts in (("tests", (".py",)), ("docs", (".md",)), ("scripts", (".py",))):
        root = os.path.join(repo_root, base)
        for dirpath, _, files in os.walk(root):
            for f in files:
                if f.endswith(exts):
                    try:
                        with open(os.path.join(dirpath, f), encoding="utf-8") as fh:
                            corpus.append(fh.read())
                    except OSError:
                        pass
    blob = "\n".join(corpus)
    findings = []
    for site in sorted(sites):
        if site not in blob:
            findings.append(
                Finding(
                    "fault-site-registry",
                    f"{PACKAGE}/fault/sites.py",
                    1,
                    f"unreferenced:{site}",
                    f"declared fault site {site!r} is referenced by no chaos "
                    "test and no docs failure-matrix entry — an injection "
                    "point nothing exercises is a dead invariant",
                )
            )
    return findings


def check_env_stale(modules: list[ModuleInfo], declared_env: set[str],
                    repo_root: str) -> list[Finding]:
    """Declared env vars nothing reads anywhere in the repo are stale."""
    read: set[str] = set()
    scan_roots = [os.path.join(repo_root, d) for d in (PACKAGE, "scripts", "tests")]
    scan_roots.append(os.path.join(repo_root, "bench.py"))
    pat = re.compile(r"ETH_SPECS_[A-Z0-9_]+")
    for root in scan_roots:
        paths = []
        if os.path.isfile(root):
            paths = [root]
        else:
            for dirpath, _, files in os.walk(root):
                paths.extend(
                    os.path.join(dirpath, f) for f in files if f.endswith(".py")
                )
        for p in paths:
            if p.endswith("envreg.py"):
                # the registry's own declaration strings must not count
                # as reads — they would satisfy the stale check for
                # every declared var, making it unable to ever fire
                continue
            try:
                with open(p, encoding="utf-8") as fh:
                    read.update(pat.findall(fh.read()))
            except OSError:
                pass
    return [
        Finding(
            "env-registry",
            f"{PACKAGE}/envreg.py",
            1,
            f"stale:{var}",
            f"{var} is declared in envreg.py but nothing in the repo reads "
            "it — stale declarations teach operators knobs that do nothing",
        )
        for var in sorted(declared_env - read)
    ]


# ------------------------------------------------------------------ engine --


def _suppressed(finding: Finding, mi: ModuleInfo | None) -> bool:
    if mi is None:
        return False
    for line in (finding.line, finding.line - 1):
        rules = mi.suppressions.get(line)
        if rules and (finding.rule in rules or "all" in rules):
            return True
    return False


def collect_modules(repo_root: str, paths: list[str] | None = None) -> list[ModuleInfo]:
    package_root = os.path.join(repo_root, PACKAGE)
    roots = paths or [package_root]
    out: list[ModuleInfo] = []
    for root in roots:
        if os.path.isfile(root):
            mi = load_module(root, repo_root, package_root)
            if mi:
                out.append(mi)
            continue
        for dirpath, dirnames, files in os.walk(root):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for f in sorted(files):
                if f.endswith(".py"):
                    mi = load_module(os.path.join(dirpath, f), repo_root, package_root)
                    if mi:
                        out.append(mi)
    return out


def run(
    repo_root: str,
    paths: list[str] | None = None,
    rules: set[str] | None = None,
    catalog=None,
    declared_env: set[str] | None = None,
    declared_sites: dict | None = None,
    project_checks: bool = True,
) -> list[Finding]:
    """Run the selected rules; returns unsuppressed findings sorted by
    (path, line). The registry arguments default to the live project
    catalogs; tests pass their own to lint fixtures hermetically."""
    rules = set(rules) if rules is not None else set(ALL_RULES)
    modules = collect_modules(repo_root, paths)
    by_path = {mi.relpath: mi for mi in modules}

    if catalog is None and ("obs-discipline" in rules):
        from eth_consensus_specs_tpu.obs import catalog as catalog_mod

        catalog = catalog_mod
    if declared_env is None and "env-registry" in rules:
        from eth_consensus_specs_tpu import envreg

        declared_env = {v.name for v in envreg.ENV_VARS}
    if declared_sites is None and "fault-site-registry" in rules:
        from eth_consensus_specs_tpu.fault import sites as sites_mod

        declared_sites = dict(sites_mod.SITES)

    # one function-table build (the expensive held-stack walk) feeds the
    # three rules that need call/lock structure
    table: dict[str, FuncInfo] | None = None
    if rules & {"blocking-under-lock", "lock-order", "jit-purity"}:
        table = build_function_table(modules)

    findings: list[Finding] = []
    for mi in modules:
        if "fork-safety" in rules:
            findings.extend(rule_fork_safety(mi))
        if "obs-discipline" in rules:
            findings.extend(rule_obs_discipline(mi, catalog))
        if "env-registry" in rules:
            findings.extend(rule_env_registry(mi, declared_env or set()))
        if "fault-site-registry" in rules:
            findings.extend(rule_fault_site_registry(mi, set(declared_sites or ())))
    if "blocking-under-lock" in rules:
        findings.extend(rule_blocking_under_lock(modules, table))
    if "lock-order" in rules:
        findings.extend(rule_lock_order(modules, table))
    if "jit-purity" in rules:
        findings.extend(rule_jit_purity(modules, table))
    if project_checks:
        if "fault-site-registry" in rules and declared_sites:
            findings.extend(check_site_references(repo_root, declared_sites))
        if "env-registry" in rules and declared_env:
            findings.extend(check_env_stale(modules, declared_env, repo_root))

    findings = [f for f in findings if not _suppressed(f, by_path.get(f.path))]
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule, f.symbol))


# ---------------------------------------------------------------- baseline --


def load_baseline(path: str) -> dict[str, int]:
    try:
        with open(path, encoding="utf-8") as fh:
            data = json.load(fh)
        return {str(k): int(v) for k, v in data.get("findings", {}).items()}
    except (OSError, ValueError):
        return {}


def baseline_diff(findings: list[Finding], baseline: dict[str, int]) -> dict:
    """Split findings into baselined and new; report stale baseline
    entries (fixed findings whose fingerprint should be ratcheted out)."""
    counts: dict[str, int] = {}
    for f in findings:
        counts[f.fingerprint] = counts.get(f.fingerprint, 0) + 1
    new: list[Finding] = []
    budget = dict(baseline)
    for f in findings:
        if budget.get(f.fingerprint, 0) > 0:
            budget[f.fingerprint] -= 1
        else:
            new.append(f)
    stale = sorted(fp for fp, n in baseline.items() if counts.get(fp, 0) < n)
    return {"new": new, "stale": stale, "counts": counts}


def write_baseline(path: str, findings: list[Finding], *, force: bool = False) -> dict:
    """Ratcheting write: per rule, the new count may only DECREASE
    relative to the existing baseline (force overrides, for bootstrap).
    Raises ValueError on a would-grow rule."""
    old = load_baseline(path)
    old_by_rule: dict[str, int] = {}
    for fp, n in old.items():
        rule = fp.split("::")[1] if fp.count("::") >= 2 else "?"
        old_by_rule[rule] = old_by_rule.get(rule, 0) + n
    new_by_rule: dict[str, int] = {}
    for f in findings:
        new_by_rule[f.rule] = new_by_rule.get(f.rule, 0) + 1
    if not force and os.path.exists(path):
        grew = {
            r: (old_by_rule.get(r, 0), n)
            for r, n in new_by_rule.items()
            if n > old_by_rule.get(r, 0)
        }
        if grew:
            raise ValueError(
                "baseline ratchet: these rules would GROW, fix the findings "
                f"instead of baselining them: {grew}"
            )
    counts: dict[str, int] = {}
    for f in findings:
        counts[f.fingerprint] = counts.get(f.fingerprint, 0) + 1
    payload = {"version": 1, "findings": dict(sorted(counts.items()))}
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)
    return payload
