"""One argparse front end for the two static-analysis CLIs.

``scripts/speclint.py`` (AST-level) and ``scripts/jaxlint.py``
(trace-level) share the same contract — findings diffed against a
ratcheting baseline, ``--json`` machine reports for CI, a
``--write-baseline`` that refuses growth — and before this module each
tool carried its own copy of the flag set and the exit-code protocol.
Two copies drift: a flag renamed in one tool silently breaks the CI
invocation of the other. So the front end lives HERE once:

  * :func:`add_common_args` installs ``--json`` / ``--rules`` /
    ``--baseline`` / ``--write-baseline`` (``--update-baseline`` kept as
    a compatibility alias) / ``--force`` on any parser;
  * :func:`finish` runs the whole post-findings flow — baseline write
    (ratchet errors -> exit 1), diff, human printout, JSON report — and
    returns the shared exit code: 0 clean, 1 usage/ratchet error,
    2 non-baselined findings.

The report dict layout is identical for both tools (``findings``,
``counts_by_rule``, ``total``, ``baselined``, ``new``,
``stale_baseline_entries`` + tool-specific ``extra``), so CI jobs and
dashboards parse one schema.
"""

from __future__ import annotations

import argparse
import json

from . import lint


def add_common_args(
    ap: argparse.ArgumentParser, *, default_baseline: str, all_rules: tuple[str, ...]
) -> None:
    """The shared flag set. ``default_baseline`` is each tool's ratchet
    file (speclint_baseline.json / jaxlint_baseline.json)."""
    ap.add_argument("--json", dest="json_out", help="write a JSON report here")
    ap.add_argument(
        "--rules",
        help=f"comma-separated rule subset (default: all of {', '.join(all_rules)})",
    )
    ap.add_argument(
        "--baseline",
        default=default_baseline,
        help=f"baseline path (default: {default_baseline})",
    )
    ap.add_argument(
        "--write-baseline",
        "--update-baseline",  # compatibility alias (pre-extraction speclint)
        dest="write_baseline",
        action="store_true",
        help="rewrite the baseline from current findings (ratchet: a rule's "
        "count may only decrease; --force overrides for bootstrap)",
    )
    ap.add_argument("--force", action="store_true", help="override the ratchet")


def parse_rules(args, all_rules: tuple[str, ...]) -> set[str] | None:
    """``--rules`` -> validated set (None = all). Raises SystemExit-free:
    returns None and prints on unknown rules so callers can exit 1."""
    if not args.rules:
        return None
    rules = {r.strip() for r in args.rules.split(",") if r.strip()}
    unknown = rules - set(all_rules)
    if unknown:
        raise ValueError(f"unknown rules: {sorted(unknown)} (have {all_rules})")
    return rules


def finish(
    args,
    findings: list[lint.Finding],
    *,
    tool: str,
    extra: dict | None = None,
) -> int:
    """Shared post-findings flow: baseline write OR diff + report.
    Exit codes: 0 clean, 1 ratchet refusal, 2 non-baselined findings."""
    if args.write_baseline:
        try:
            payload = lint.write_baseline(args.baseline, findings, force=args.force)
        except ValueError as exc:
            print(f"REFUSED: {exc}")
            return 1
        print(f"baseline updated: {len(payload['findings'])} fingerprints")
        return 0

    baseline = lint.load_baseline(args.baseline)
    diff = lint.baseline_diff(findings, baseline)
    by_rule: dict[str, int] = {}
    for f in findings:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1

    report = {
        "tool": tool,
        "findings": [f.to_dict() for f in findings],
        "counts_by_rule": dict(sorted(by_rule.items())),
        "total": len(findings),
        "baselined": len(findings) - len(diff["new"]),
        "new": [f.to_dict() for f in diff["new"]],
        "stale_baseline_entries": diff["stale"],
    }
    if extra:
        report["extra"] = extra
    if args.json_out:
        with open(args.json_out, "w", encoding="utf-8") as fh:
            json.dump(report, fh, indent=1, sort_keys=True)
            fh.write("\n")

    for f in diff["new"]:
        print(f"{f.path}:{f.line}: [{f.rule}] {f.message}")
    if diff["stale"]:
        print(
            f"note: {len(diff['stale'])} stale baseline entr"
            f"{'y' if len(diff['stale']) == 1 else 'ies'} (fixed findings) — "
            "run --write-baseline to ratchet them out"
        )
    summary = ", ".join(f"{r}={n}" for r, n in sorted(by_rule.items())) or "clean"
    print(
        f"{tool}: {len(findings)} finding(s) ({summary}); "
        f"{len(diff['new'])} non-baselined"
    )
    return 2 if diff["new"] else 0
